"""Headline benchmark: Llama training-step MFU on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
The reference publishes no training-throughput numbers (BASELINE.md — its perf
story defers to torch/NCCL); the driver-defined north star is >=45% MFU, so
``vs_baseline`` is value / 0.45.

On a real TPU this trains a ~450M-param Llama (bf16 compute, fp32 master
params + adam moments, remat) at seq 2048. On CPU (no TPU attached) it runs a
tiny config just to prove the path end-to-end.
"""

from __future__ import annotations

import json
import signal
import sys
import time

import jax
import numpy as np
import jax.numpy as jnp

# Partial results accumulate here; a timeout kill (SIGTERM) still emits one
# valid JSON line with whatever finished instead of losing the whole run
# (the 8B big-model phase makes the full bench ~20+ min).
_RESULT: dict = {}


def _emit_partial(signum, frame):  # pragma: no cover - signal path
    # One-shot: disarm first so a signal racing the normal final print can
    # never produce a second JSON line (the output contract is ONE line).
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    try:
        if _RESULT:
            _RESULT.setdefault("partial", True)
            print(json.dumps(_RESULT), flush=True)
    finally:
        # sys.exit in finally: even a BrokenPipeError from the print must
        # not fall back into the interrupted frame's `except Exception`
        # (which would swallow the shutdown and keep the bench running).
        sys.exit(1)


signal.signal(signal.SIGTERM, _emit_partial)

# bf16 peak FLOPs per chip by device kind (dense matmul).
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _phase_snapshot(phase: str) -> None:
    """Drop a per-phase registry snapshot under ATX_METRICS_DIR (no-op when
    unset): `<dir>/<phase>/metrics_0.json`, the same exchange format the
    fleet /metrics endpoint merges — post-hoc phase attribution without
    parsing the JSON line (docs/observability.md)."""
    import os

    root = os.environ.get("ATX_METRICS_DIR", "")
    if not root:
        return
    try:
        from accelerate_tpu import telemetry

        telemetry.write_snapshot(os.path.join(root, phase), process_index=0)
    except Exception:
        pass  # telemetry must never sink a bench run


def _peak_flops(device: jax.Device) -> float | None:
    kind = getattr(device, "device_kind", "")
    for name, flops in _PEAK_FLOPS.items():
        if kind.startswith(name) or name.startswith(kind):
            return flops
    return None


def main() -> None:
    import optax

    import accelerate_tpu as atx
    from accelerate_tpu.models import llama

    device = jax.devices()[0]
    on_tpu = device.platform == "tpu" or "TPU" in getattr(device, "device_kind", "")
    if on_tpu:
        # head_dim 128 (not 64): the MXU contracts 128 lanes per pass, so
        # h=64 attention dots run at half utilization — measured 37 vs 65
        # TF/s on v5e for the same FLOPs. Param count is unchanged.
        config = llama.LlamaConfig(
            vocab_size=32000,
            d_model=1024,
            n_layers=24,
            num_heads=8,
            num_kv_heads=4,
            head_dim=128,
            d_ff=4096,
            max_seq_len=2048,
            remat=True,
            # Measured on v5e: attn_and_outputs 448 ms/step vs block_outputs
            # 458 ms (saving the attention outputs skips the most expensive
            # recompute); "dots"/no-remat exceed HBM at this size.
            remat_policy="attn_and_outputs",
            attention_impl="flash",
        )
        batch_size, seq = 8, 2048
        steps, warmup = 10, 3
    else:
        config = llama.LlamaConfig.tiny(remat=True)
        batch_size, seq = 4, 64
        steps, warmup = 3, 1

    acc = atx.Accelerator(mixed_precision="bf16", seed=0, max_grad_norm=1.0)
    state = acc.create_train_state(lambda r: llama.init(r, config), optax.adamw(3e-4))
    step = acc.make_train_step(lambda p, b, r: llama.loss_fn(p, b, config, r))
    batch = {
        "input_ids": jax.random.randint(
            jax.random.PRNGKey(1), (batch_size, seq), 0, config.vocab_size, jnp.int32
        )
    }
    batch = jax.device_put(batch)

    state, metrics, dt, fetch_latency = _timed_steps(step, state, batch, steps, warmup)

    tokens_per_step = batch_size * (seq - 1)  # loss_fn shifts by one
    tokens_per_sec = tokens_per_step * steps / dt
    n_params = config.param_count()
    # Training FLOPs/token: 6N for matmuls + causal attention term (fwd+bwd).
    attn_flops = 6.0 * config.n_layers * config.d_model * seq  # 12*L*D*S/2 (causal)
    flops_per_token = 6.0 * n_params + attn_flops
    model_flops_per_sec = tokens_per_sec * flops_per_token
    peak = _peak_flops(device)
    mfu = model_flops_per_sec / peak if peak else 0.0

    # Free the Llama state/opt buffers before the BERT measurement — both
    # would not fit HBM together.
    final_loss = round(float(metrics["loss"]), 4)
    _RESULT.update(
        {
            "metric": "llama_train_mfu",
            "value": round(mfu, 4),
            "unit": "MFU",
            "vs_baseline": round(mfu / 0.45, 4) if peak else 0.0,
            "tokens_per_sec": round(tokens_per_sec, 1),
            "step_time_ms": round(1000 * dt / steps, 2),
            "params": n_params,
            "device": getattr(device, "device_kind", str(device)),
            "loss": final_loss,
        }
    )
    # Runtime-telemetry view of the same loop (ATX_METRICS, default on):
    # dispatch-gap exposes a host-bound loop the external wall clock can't
    # see, and train_mfu cross-checks the hand-computed MFU above from
    # XLA's own cost analysis of the compiled step.
    stats = getattr(step, "step_stats", None)
    if stats is not None:
        latest = stats.latest()
        _RESULT["train_dispatch_gap_ms"] = round(latest["train_dispatch_gap_ms"], 2)
        _RESULT["train_mfu"] = round(latest["train_mfu"], 4)
        _RESULT["train_compiles"] = int(latest["train_compiles"])
    try:
        # Static twin of the measured series (docs/performance.md, "perf
        # campaign"): the ATX601 roofline over the SAME compiled step, so
        # `--compare` can tell "the program got worse" (bound moved) from
        # "the run got slower" (bound unchanged, measured MFU dropped).
        _RESULT.update(_static_perf_series(step, state, batch, config))
    except Exception as e:
        _RESULT["static_perf_error"] = f"{type(e).__name__}: {e}"[:200]
    _phase_snapshot("train")
    state, batch, metrics = acc.free_memory(state, batch, metrics)
    try:
        _RESULT.update(_bench_bert(on_tpu, fetch_latency))
    except Exception as e:  # never lose the headline MFU number
        _RESULT["bert_error"] = f"{type(e).__name__}: {e}"[:200]
    _phase_snapshot("bert")
    try:
        # Runs on CPU too (tiny buffer): the engine-vs-blocking comparison
        # is the before/after for the whole transfer-bound family
        # (bigmodel_8b_load_s, hostoffload_adamw_mfu, overram decode).
        _RESULT.update(_bench_transfer(on_tpu))
    except Exception as e:
        _RESULT["transfer_error"] = f"{type(e).__name__}: {e}"[:200]
    _phase_snapshot("transfer")
    if on_tpu:
        extra_benches = [
            ("longctx", _bench_long_context),
            ("generate", lambda: _bench_generate(config)),
            ("serve", lambda: _bench_serve(config)),
            ("specdecode", lambda: _bench_specdecode(config)),
            ("int8kv", lambda: _bench_int8_kv(config)),
            ("kernels", lambda: _bench_kernels(config)),
            ("int8mm", _bench_int8_matmul),
            ("fp8", _bench_fp8),
            ("llama2b", lambda: _bench_llama2b(fetch_latency)),
            ("hostoffload", lambda: _bench_hostoffload_adamw(fetch_latency)),
            ("vit", lambda: _bench_vit(fetch_latency)),
            ("bigmodel", _bench_bigmodel),
            ("overram", _bench_overram),
        ]
        for name, fn in extra_benches:
            try:
                _RESULT.update(fn())
            except Exception as e:  # keep the headline fields no matter what
                _RESULT[f"{name}_error"] = f"{type(e).__name__}: {e}"[:200]
            _phase_snapshot(name)

    signal.signal(signal.SIGTERM, signal.SIG_DFL)  # past the point of partials
    print(json.dumps(_RESULT))


def _static_perf_series(step, state, batch, config) -> dict:
    """ATX601/ATX70x statically-derived series next to the measured ones:
    lower + compile the already-built train step (no extra steps run),
    bound it against the local chip's roofline spec, sweep the scheduled
    HLO for the peak-HBM timeline, and solve the serving capacity planner
    for this config on this chip. Emitted per run so `bench.py --compare`
    ratchets them alongside the measured MFU."""
    from accelerate_tpu.analysis import capacity, memory, roofline
    from accelerate_tpu.models import llama

    text = step.lower(state, batch).compile().as_text()
    spec = roofline.chip_spec_for()
    res = roofline.analyze_hlo(text, spec)
    exposed = roofline.find_exposed_collectives(text, spec)
    out = {
        "train_static_mfu_bound": round(res.static_mfu_bound, 4),
        "train_exposed_comms_mib": round(sum(e.bytes for e in exposed) / 2**20, 3),
        "train_padding_waste_frac": round(res.padding_waste_fraction, 4),
    }
    try:
        timeline = memory.build_timeline(text)
        out["train_peak_hbm_mib"] = round(timeline.peak_bytes / 2**20, 1)
    except Exception:
        pass  # the roofline series above still land
    try:
        # Serving twin: one abstract KV slot of this config + the bf16
        # weights it would serve with — the planner needs only byte counts.
        slot_kv = jax.eval_shape(lambda: llama.init_cache(config, 1, config.max_seq_len))
        weights = 2 * config.param_count()  # bf16 serving weights
        plan = capacity.plan_capacity(
            chip=spec,
            weights_bytes=weights,
            kv_bytes_per_slot=capacity.tree_bytes(slot_kv),
            n_slots=1,
            max_len=config.max_seq_len,
        )
        out["serve_static_max_slots"] = int(plan.max_slots)
    except Exception:
        pass
    return out


def _timed_steps(step, state, batch, steps: int, warmup: int, fetch_latency: float | None = None):
    """Warm up, then time `steps` train steps.

    A device->host scalar fetch is the only reliable barrier on every
    platform (block_until_ready is a no-op through the axon PJRT tunnel);
    its round trip is measured once and subtracted from the timed loop.
    Returns (state, metrics, dt_seconds, fetch_latency).
    """
    for _ in range(warmup):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    if fetch_latency is None:
        t0 = time.perf_counter()
        float(metrics["loss"])
        fetch_latency = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    dt = max(time.perf_counter() - t0 - fetch_latency, 1e-9)
    return state, metrics, dt, fetch_latency


def _bench_transfer(on_tpu: bool) -> dict:
    """H2D roofline, blocking vs the async chunked engine
    (`parallel/transfer.py`): the same host buffer moved once as a single
    whole-leaf `jax.device_put` (the pre-engine code path — BENCH_r05
    measured it at 23.9 MiB/s through the v5e tunnel against a 2655.9
    MiB/s disk) and once through `TransferEngine.put` (chunks issued
    concurrently from the worker pool). `transfer_mib_s` over
    `transfer_blocking_mib_s` is the dispatch-serialization win every
    transfer-bound path (8B load, over-RAM decode, disk-offloaded AdamW)
    inherits. Meaningful on a real link: on a local CPU "device" blocking
    device_put is already memcpy speed, so the CPU run is a smoke check of
    the code path, not a win."""
    from accelerate_tpu.parallel.transfer import TransferEngine

    n_mib = 256 if on_tpu else 8
    x = np.empty((n_mib, 1 << 20), np.int8)
    x[:] = np.arange(n_mib, dtype=np.int8)[:, None]

    def barrier(d) -> None:
        float(jnp.sum(d[0, :8].astype(jnp.float32)))  # scalar fetch = barrier

    # Warm both paths (compile the engine's fold, open the link). On CPU
    # the probe is small, so force a sub-probe chunk size — the point is to
    # exercise the chunked multi-stream path, not the single-shot fallback.
    barrier(jax.device_put(x[:1]))
    engine = TransferEngine() if on_tpu else TransferEngine(chunk_bytes=1 << 20)
    barrier(engine.put(x[:2]).result())

    def timed(fn) -> float:
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            barrier(fn())
            best = min(best, time.perf_counter() - t0)
        return best

    dt_block = timed(lambda: jax.device_put(x))
    dt_engine = timed(lambda: engine.put(x).result())
    return {
        "transfer_mib_s": round(n_mib / dt_engine, 1),
        "transfer_blocking_mib_s": round(n_mib / dt_block, 1),
        "transfer_speedup": round(dt_block / dt_engine, 3),
        "transfer_chunk_mib": engine.chunk_bytes >> 20,
        "transfer_workers": engine.workers,
    }


def _bench_long_context() -> dict:
    """Flash-attention fwd+bwd throughput at 32k context (the blocked-KV
    kernel path; the resident-KV path cannot compile at this length)."""
    from accelerate_tpu.ops.flash_attention import flash_attention

    B, S, H, K, h = 1, 32768, 8, 4, 128
    k0 = jax.random.PRNGKey(9)
    q = jax.random.normal(k0, (B, S, H, h), jnp.bfloat16)
    k = jax.random.normal(jax.random.fold_in(k0, 1), (B, S, K, h), jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(k0, 2), (B, S, K, h), jnp.bfloat16)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    g = step(q, k, v)
    float(jnp.sum(g[0].astype(jnp.float32)))  # barrier
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        g = step(q, k, v)
    float(jnp.sum(g[0].astype(jnp.float32)))
    dt = (time.perf_counter() - t0) / reps
    # fwd 4*B*H*S^2*h/2 (causal) + bwd 2.5x fwd
    flops = 3.5 * 4 * B * H * S * S * h / 2
    return {
        "longctx_seq": S,
        "longctx_step_ms": round(dt * 1000, 1),
        "longctx_tflops": round(flops / dt / 1e12, 1),
    }


def _bench_fp8() -> dict:
    """fp8-vs-bf16 matmul microbench (VERDICT r2 #9): measures whether THIS
    chip's MXU gives fp8 a real speedup, or only upcasts (v5e). The config
    Q&A points users at this field before they pick fp8."""
    from accelerate_tpu.ops import fp8 as _fp8

    N = 4096
    k0 = jax.random.PRNGKey(11)
    x = jax.random.normal(k0, (N, N), jnp.bfloat16)
    w = jax.random.normal(jax.random.fold_in(k0, 1), (N, N), jnp.bfloat16)

    def bf16_mm(x, w):
        return _fp8.matmul_einsum("ij,jk->ik", x, w)

    def fp8_mm(x, w):
        with _fp8.fp8_matmuls(True):
            return _fp8.matmul_einsum("ij,jk->ik", x, w)

    def timed(jitted) -> float:
        out = jitted(x, w)
        float(jnp.sum(out.astype(jnp.float32)))  # warm + barrier
        reps = 20
        t0 = time.perf_counter()
        for _ in range(reps):
            out = jitted(x, w)
        float(jnp.sum(out.astype(jnp.float32)))
        return (time.perf_counter() - t0) / reps

    bf16_jit, fp8_jit = jax.jit(bf16_mm), jax.jit(fp8_mm)
    dt_bf16 = min(timed(bf16_jit) for _ in range(2))
    dt_fp8 = min(timed(fp8_jit) for _ in range(2))
    flops = 2.0 * N * N * N
    # Feed the launcher's lose-lose gate (launch refuses fp8 on device kinds
    # with measured speedup <= 1 unless --force_fp8).
    try:
        from accelerate_tpu.utils import fp8_telemetry

        fp8_telemetry.record(jax.devices()[0].device_kind, dt_bf16 / dt_fp8)
    except Exception:
        pass
    return {
        "bf16_matmul_tflops": round(flops / dt_bf16 / 1e12, 1),
        "fp8_matmul_tflops": round(flops / dt_fp8 / 1e12, 1),
        # > 1.0 means fp8 actually pays on this chip.
        "fp8_matmul_speedup": round(dt_bf16 / dt_fp8, 3),
    }


def _bench_int8_matmul() -> dict:
    """int8×int8→int32 vs bf16 MXU rate (VERDICT r4 #3, `ops/int8.py`).

    The v5e's int8 MXU runs ~2× the bf16 rate; this is the lever fp8
    cannot pull on this chip (fp8_matmul_speedup 0.513 in BENCH_r03).
    Times a jitted fori_loop at two iteration counts and divides the
    MARGINAL times, so the tunnel's fixed per-execution latency cancels
    (measured ~100 ms — larger than 16 matmuls at peak)."""
    N, NB = 4096, 4
    kx, kw = jax.random.split(jax.random.PRNGKey(13))
    x8 = jax.random.randint(kx, (N, N), -127, 127, jnp.int8)
    w8s = jax.random.randint(kw, (NB, N, N), -127, 127, jnp.int8)
    xb = jax.random.normal(kx, (N, N), jnp.bfloat16)
    wbs = jax.random.normal(kw, (NB, N, N), jnp.bfloat16)

    def make(dtype_out, iters):
        @jax.jit
        def loop(a, bs):
            def body(i, acc):
                # Loop-variant operand: the dot cannot be hoisted.
                bb = jax.lax.dynamic_index_in_dim(bs, i % NB, 0, keepdims=False)
                return acc + jax.lax.dot(a, bb, preferred_element_type=dtype_out)
            return jnp.sum(
                jax.lax.fori_loop(0, iters, body, jnp.zeros((N, N), dtype_out))
            )
        return loop

    def run(fn, a, b, reps=3):
        float(fn(a, b))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(fn(a, b))  # scalar fetch = the only reliable barrier here
            best = min(best, time.perf_counter() - t0)
        return best

    small, big = 16, 96
    marginal = {}
    for name, xv, wv, dt_ in (("bf16", xb, wbs, jnp.float32), ("int8", x8, w8s, jnp.int32)):
        t_small = run(make(dt_, small), xv, wv)
        t_big = run(make(dt_, big), xv, wv)
        marginal[name] = max(t_big - t_small, 1e-9) / (big - small)
    flops = 2.0 * N * N * N
    return {
        "int8_matmul_tops": round(flops / marginal["int8"] / 1e12, 1),
        "int8_mxu_bf16_tflops": round(flops / marginal["bf16"] / 1e12, 1),
        # > 1.0 means the int8 MXU path pays on this chip (v5e: ~1.9).
        "int8_matmul_speedup": round(marginal["bf16"] / marginal["int8"], 3),
    }


def _bench_generate(config) -> dict:
    """KV-cache decode throughput on the headline model (the
    big-model-inference `generate()` config BASELINE.md tracks): bf16
    params, batch 8, prefill 128, steady-state decode tokens/sec.

    Timed as the DIFFERENCE between a long and a short generation, which
    cancels the prefill forward and the device->host fetch round trip from
    the measurement (the same concern `_timed_steps` handles; only the extra
    decode steps remain)."""
    import dataclasses

    from accelerate_tpu.generation import GenerationConfig
    from accelerate_tpu.models import llama

    gen_config = dataclasses.replace(
        config,
        remat=False,
        attention_impl="dot",  # decode T=1 steps; flash needs block-sized S
    )
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16),
        llama.init(jax.random.PRNGKey(3), gen_config),
    )
    B, prompt_len = 8, 128
    short, long = 16, 80
    prompt = jax.random.randint(
        jax.random.PRNGKey(4), (B, prompt_len), 0, gen_config.vocab_size, jnp.int32
    )
    gcfg_short = GenerationConfig(max_new_tokens=short)
    gcfg_long = GenerationConfig(max_new_tokens=long)

    def run(gcfg) -> float:
        t0 = time.perf_counter()
        out = llama.generate(params, prompt, gen_config, generation_config=gcfg)
        int(out[0, -1])  # fetch barrier (block_until_ready is a no-op via axon)
        return time.perf_counter() - t0

    run(gcfg_short), run(gcfg_long)  # compile both loop lengths
    dt_short = min(run(gcfg_short) for _ in range(2))
    dt_long = min(run(gcfg_long) for _ in range(2))
    decode_dt = max(dt_long - dt_short, 1e-9)
    n_tokens = long - short
    return {
        "decode_tokens_per_sec": round(B * n_tokens / decode_dt, 1),
        "decode_ms_per_token": round(1000 * decode_dt / n_tokens, 3),
    }


def _bench_int8_kv(config) -> dict:
    """int8 KV cache at long context (beyond-reference: per-token-scale
    quantized cache, `models/llama.py:init_cache`): at 16k context the
    bf16 cache (~1.6 GiB) outweighs the 443M model's weights ~2:1, so
    halving cache bytes moves the B=1 decode roofline directly. Prefill
    runs in 2k chunks (the dot-attention score block stays bounded), then
    a timed single-token decode loop."""
    import dataclasses

    from accelerate_tpu.models import llama

    S_ctx, chunk, decode_n = 16384, 2048, 48
    gen_config = dataclasses.replace(
        config, remat=False, attention_impl="dot", max_seq_len=S_ctx + 128
    )
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16), llama.init(jax.random.PRNGKey(3), gen_config)
    )
    prompt = jax.random.randint(
        jax.random.PRNGKey(6), (1, S_ctx), 0, gen_config.vocab_size, jnp.int32
    )

    # One jitted callable serves prefill chunks and 1-token decode: jit
    # specializes per input shape anyway.
    step_fn = jax.jit(
        lambda p, t, c: llama.forward_with_cache(p, t, c, gen_config),
        donate_argnums=(2,),
    )
    prefill = decode = step_fn

    out = {}
    rates = {}
    for label, dt in (("bf16", jnp.bfloat16), ("int8", jnp.int8)):
        cache = llama.init_cache(gen_config, 1, S_ctx + 128, dtype=dt)
        for i in range(S_ctx // chunk):
            logits, cache = prefill(params, prompt[:, i * chunk:(i + 1) * chunk], cache)
        tok = jnp.argmax(logits[:, -1:, :], axis=-1).astype(jnp.int32)
        for _ in range(4):  # compile + warm
            logits, cache = decode(params, tok, cache)
        int(jnp.argmax(logits[0, -1]))  # sync
        t0 = time.perf_counter()
        for _ in range(decode_n):
            logits, cache = decode(params, tok, cache)
        int(jnp.argmax(logits[0, -1]))  # fetch barrier
        dt_total = time.perf_counter() - t0
        rates[label] = decode_n / dt_total
        out[f"kv16k_decode_{label}_tokens_per_sec"] = round(rates[label], 1)
    out["kv16k_int8_speedup"] = round(rates["int8"] / rates["bf16"], 3)

    # Same int8 cache, flash-decode kernel pinned OFF, fresh function object
    # (fresh jit cache): isolates the kernel's contribution at 16k context.
    # The loop above runs under the default knobs (kernel on where TPU +
    # pallas), so rates["int8"] / off_rate is the on/off delta.
    from accelerate_tpu.native.pallas import force_kernels

    with force_kernels("off"):
        decode_off = jax.jit(
            lambda p, t, c: llama.forward_with_cache(p, t, c, gen_config),
            donate_argnums=(2,),
        )
        for _ in range(4):  # compile + warm
            logits, cache = decode_off(params, tok, cache)
        int(jnp.argmax(logits[0, -1]))
        t0 = time.perf_counter()
        for _ in range(decode_n):
            logits, cache = decode_off(params, tok, cache)
        int(jnp.argmax(logits[0, -1]))
        off_rate = decode_n / (time.perf_counter() - t0)
    out["kv16k_decode_int8_off_tokens_per_sec"] = round(off_rate, 1)
    out["kv16k_decode_kernel_speedup"] = round(rates["int8"] / off_rate, 3)
    return out


def _bench_kernels(config) -> dict:
    """Pallas kernel tier on/off deltas (`native/pallas/`): each hot path
    timed under ``force_kernels("on")`` vs ``"off"`` with fresh function
    objects per mode (the mode is read at trace time, so each gets its own
    jit cache). On CPU "on" resolves to the fallback and the ratios sit at
    ~1.0; on TPU these are the tier's headline numbers."""
    import dataclasses

    from accelerate_tpu.models import llama
    from accelerate_tpu.native.pallas import force_kernels
    from accelerate_tpu.ops import fp8 as _fp8
    from accelerate_tpu.parallel import host_offload

    out = {}

    # --- flash-decode attention: B=8 steady-state decode, on vs off.
    gen_config = dataclasses.replace(config, remat=False, attention_impl="dot")
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16),
        llama.init(jax.random.PRNGKey(3), gen_config),
    )
    B, prompt_len, decode_n = 8, 256, 48
    prompt = jax.random.randint(
        jax.random.PRNGKey(4), (B, prompt_len), 0, gen_config.vocab_size, jnp.int32
    )

    def run_decode(mode: str) -> float:
        with force_kernels(mode):
            step = jax.jit(
                lambda p, t, c: llama.forward_with_cache(p, t, c, gen_config),
                donate_argnums=(2,),
            )
            cache = llama.init_cache(gen_config, B, prompt_len + decode_n + 8)
            logits, cache = step(params, prompt, cache)
            tok = jnp.argmax(logits[:, -1:, :], axis=-1).astype(jnp.int32)
            for _ in range(4):
                logits, cache = step(params, tok, cache)
            int(jnp.argmax(logits[0, -1]))  # sync
            t0 = time.perf_counter()
            for _ in range(decode_n):
                logits, cache = step(params, tok, cache)
            int(jnp.argmax(logits[0, -1]))  # fetch barrier
            return decode_n * B / (time.perf_counter() - t0)

    tps_on = run_decode("on")
    tps_off = run_decode("off")
    out["decode_kernel_tokens_per_sec"] = round(tps_on, 1)
    out["decode_kernel_off_tokens_per_sec"] = round(tps_off, 1)
    out["decode_kernel_speedup"] = round(tps_on / tps_off, 3)

    # --- fp8 contraction kernel: the 1.004 fp8_matmul_speedup target.
    N = 4096
    k0 = jax.random.PRNGKey(11)
    x = jax.random.normal(k0, (N, N), jnp.bfloat16)
    w = jax.random.normal(jax.random.fold_in(k0, 1), (N, N), jnp.bfloat16)

    def run_fp8(mode: str) -> float:
        with force_kernels(mode):

            def mm(x, w):
                with _fp8.fp8_matmuls(True):
                    return _fp8.matmul_einsum("ij,jk->ik", x, w)

            jitted = jax.jit(mm)
            o = jitted(x, w)
            float(jnp.sum(o.astype(jnp.float32)))  # warm + barrier
            reps = 20
            t0 = time.perf_counter()
            for _ in range(reps):
                o = jitted(x, w)
            float(jnp.sum(o.astype(jnp.float32)))
            return (time.perf_counter() - t0) / reps

    dt_off = min(run_fp8("off") for _ in range(2))
    dt_on = min(run_fp8("on") for _ in range(2))
    out["fp8_kernel_matmul_speedup"] = round(dt_off / dt_on, 3)

    # --- fused AdamW: one big leaf's worth of update, on vs off.
    n = 8 * 1024 * 1024
    keys = jax.random.split(jax.random.PRNGKey(17), 4)
    g, mu, nu, p = (
        jax.random.normal(k, (n,), jnp.float32) * s
        for k, s in zip(keys, (1e-3, 1e-3, 1e-6, 1.0))
    )
    nu = jnp.abs(nu)

    def run_adamw(mode: str) -> float:
        with force_kernels(mode):
            step = jax.jit(
                lambda g, mu, nu, p: host_offload._adamw_slice(
                    g, mu, nu, p, jnp.ones(()), 1e-4, 0.9, 0.999, 1e-8, 1e-4
                )
            )
            u, m2, n2 = step(g, mu, nu, p)
            float(jnp.sum(u))  # warm + barrier
            reps = 20
            t0 = time.perf_counter()
            for _ in range(reps):
                u, m2, n2 = step(g, mu, nu, p)
            float(jnp.sum(u))
            return (time.perf_counter() - t0) / reps

    ms_on = min(run_adamw("on") for _ in range(2)) * 1000
    ms_off = min(run_adamw("off") for _ in range(2)) * 1000
    out["fused_adamw_step_ms"] = round(ms_on, 3)
    out["fused_adamw_off_step_ms"] = round(ms_off, 3)
    out["fused_adamw_speedup"] = round(ms_off / max(ms_on, 1e-9), 3)
    return out


def _bench_serve(config) -> dict:
    """Continuous-batching serving engine (`serving.Engine`,
    docs/serving.md) on the headline decode model: a trace of 48
    mixed-length requests (prompts 32/64/128, budgets 24/48) served through
    the slot pool, vs the same request set run SEQUENTIALLY through batch-1
    `generate()` — the fixed-batch workflow the engine replaces. The
    ISSUE-3 acceptance bar is `serve_vs_b1_speedup >= 3`. Then a second
    pass replays Poisson arrivals at ~70% of the measured capacity on the
    wall clock for honest p50/p99 request + TTFT latency. Finally a
    shared-prefix trace (two 128-token system prompts) is served with the
    prefix cache on vs off: `serve_prefix_hit_rate`/`serve_prefill_saved`
    quantify the radix-tree KV reuse and the TTFT p50 pair shows the
    time-to-first-token win (ISSUE-6). A last router phase replays the
    same traces through the multi-replica front-end (`serving.Router`) at
    replicas=2 vs 1 (`serve_router_scaling_efficiency`, TTFT p99) and
    with prefix-affinity routing on vs off
    (`serve_router_affinity_hit_delta`) — judge the scaling on TPU
    (ISSUE-8)."""
    import dataclasses

    from accelerate_tpu import serving
    from accelerate_tpu.generation import GenerationConfig, Generator
    from accelerate_tpu.models import llama

    gen_config = dataclasses.replace(
        config, remat=False, attention_impl="dot", max_seq_len=512
    )
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16),
        llama.init(jax.random.PRNGKey(3), gen_config),
    )
    apply_fn = lambda p, t, c: llama.forward_with_cache(p, t, c, gen_config)
    init_cache_fn = lambda b, m: llama.init_cache(gen_config, b, m)

    # Request mix from a small set of (prompt, budget) pairs so the b1
    # BASELINE compiles a bounded number of (shape, cache) specializations;
    # the engine itself needs no such care (that is the point: one decode
    # compile + one prefill compile per bucket, whatever the mix).
    prompt_lens, budgets, buckets = (32, 64, 128), (24, 48), (32, 64, 128)
    n_requests = 48
    rng = np.random.RandomState(7)
    arrivals = np.cumsum(rng.exponential(1.0, n_requests))  # rescaled later
    trace = [
        serving.Request(
            prompt=rng.randint(0, gen_config.vocab_size, (int(rng.choice(prompt_lens)),)).astype(np.int32),
            max_new_tokens=int(rng.choice(budgets)),
            rid=i,
            seed=i,
            arrival=float(arrivals[i]),
        )
        for i in range(n_requests)
    ]

    def fresh_engine(prefix_cache: bool = False, max_len: int | None = None):
        return serving.Engine(
            apply_fn,
            init_cache_fn,
            params,
            GenerationConfig(),
            buckets=buckets,
            max_len=max_len or (max(prompt_lens) + max(budgets)),
            decode_block=8,
            prefix_cache=prefix_cache,
            prefix_cache_rows=8 if prefix_cache else None,
        )

    engine = fresh_engine()
    # Warm every compile the trace will hit: one request per bucket.
    engine.serve(
        serving.Request(
            prompt=rng.randint(0, gen_config.vocab_size, (S,)).astype(np.int32),
            max_new_tokens=2,
            rid=1000 + S,
        )
        for S in prompt_lens
    )
    t0 = time.perf_counter()
    completions = engine.serve(trace)
    serve_wall = max(time.perf_counter() - t0, 1e-9)
    total_new = sum(c.n_new for c in completions)
    serve_tps = total_new / serve_wall

    # Sequential b1 baseline over a 12-request subset covering every
    # (prompt, budget) pair; first pass compiles, second is timed.
    subset = trace[:12]
    gens: dict[int, Generator] = {}
    for timed in (False, True):
        t0 = time.perf_counter()
        for r in subset:
            g = gens.setdefault(
                r.max_new_tokens, Generator(
                    apply_fn, init_cache_fn,
                    GenerationConfig(max_new_tokens=r.max_new_tokens),
                )
            )
            out = g(params, jnp.asarray(r.prompt[None]))
            int(out[0, -1])  # fetch barrier
        if timed:
            b1_wall = max(time.perf_counter() - t0, 1e-9)
    b1_tps = sum(r.max_new_tokens for r in subset) / b1_wall

    # Latency pass: Poisson arrivals at ~70% of measured capacity, wall
    # clock honoured, so p50/p99 include real queueing.
    rate = 0.7 * n_requests / serve_wall
    lat_engine = fresh_engine()
    lat_trace = [
        dataclasses.replace(r, arrival=float(a / arrivals[-1] * n_requests / rate))
        for r, a in zip(trace, arrivals)
    ]
    lat = lat_engine.serve(lat_trace, realtime=True)
    lat_ms = sorted(1e3 * (c.finished_at - c.submitted_at) for c in lat)
    ttft_ms = sorted(1e3 * (c.first_token_at - c.submitted_at) for c in lat)
    pick = lambda xs, q: xs[min(len(xs) - 1, int(q * len(xs)))]

    # Prefix-cache phase: 32 requests behind two 128-token system prompts
    # with short unique tails, replayed (as-fast-as-possible) through a
    # cache-on and a cache-off engine. TTFT here is the queue+prefill time
    # per request; with ~94% of each prompt's prefill skipped on a hit the
    # cache-on engine should cut it well below the cache-off run.
    prefix_trace = serving.shared_prefix_trace(
        32,
        1e9,  # all requests queued up-front: measures prefill work, not arrivals
        vocab_size=gen_config.vocab_size,
        n_prefixes=2,
        prefix_len=128,
        tail_lens=(8, 32),
        new_tokens=(8, 24),
        seed=11,
    )
    prefix_max_len = 128 + 32 + 24
    prefix_results = {}
    for label, on in (("prefix", True), ("nocache", False)):
        eng = fresh_engine(prefix_cache=on, max_len=prefix_max_len)
        # Warm compiles (prefill buckets + decode) outside the timed pass.
        eng.serve(
            serving.Request(
                prompt=rng.randint(0, gen_config.vocab_size, (S,)).astype(np.int32),
                max_new_tokens=2,
                rid=2000 + S,
            )
            for S in buckets
        )
        done = eng.serve(prefix_trace)
        tt = sorted(1e3 * (c.first_token_at - c.submitted_at) for c in done)
        prefix_results[label] = (eng, pick(tt, 0.50), pick(tt, 0.99))
    prefix_eng = prefix_results["prefix"][0]
    pm = prefix_eng.prefix_metrics()

    # Router phase (ISSUE-8): the same Poisson trace through the
    # multi-replica front-end at replicas=1 vs replicas=2 for aggregate
    # tokens/sec + TTFT p99 scaling (each replica engine is warmed
    # separately; on a shared-CPU host the two replica loops contend for
    # the same cores, so judge `serve_router_scaling_efficiency` on TPU —
    # this lane smoke-checks the path). Then the shared-prefix trace with
    # prefix-affinity routing on vs off: the fleet hit-rate delta is what
    # cache-aware placement buys over pure least-loaded.
    def warm_router_engines(n: int, **kw) -> list:
        engines = []
        for _ in range(n):
            e = fresh_engine(**kw)
            e.serve(
                serving.Request(
                    prompt=rng.randint(
                        0, gen_config.vocab_size, (S,)
                    ).astype(np.int32),
                    max_new_tokens=2,
                    rid=3000 + S,
                )
                for S in buckets
            )
            engines.append(e)
        return engines

    router_tps, router_ttft_p99 = {}, {}
    for n_rep in (1, 2):
        with serving.Router(warm_router_engines(n_rep)) as router:
            t0 = time.perf_counter()
            done = router.serve([dataclasses.replace(r) for r in trace])
            wall = max(time.perf_counter() - t0, 1e-9)
        router_tps[n_rep] = sum(c.n_new for c in done) / wall
        tt = sorted(1e3 * (c.first_token_at - c.submitted_at) for c in done)
        router_ttft_p99[n_rep] = pick(tt, 0.99)

    affinity_hit_rate = {}
    for label, policy in (("affinity", "prefix"), ("noaffinity", "least-loaded")):
        engines = warm_router_engines(
            2, prefix_cache=True, max_len=prefix_max_len
        )
        with serving.Router(engines, affinity=policy) as router:
            router.serve([dataclasses.replace(r) for r in prefix_trace])
        hits = sum(e.stats["prefix_hits"] for e in engines)
        lookups = sum(e.prefix_cache.stats["lookups"] for e in engines)
        affinity_hit_rate[label] = hits / max(lookups, 1)

    return {
        "serve_requests": n_requests,
        "serve_tokens_per_sec": round(serve_tps, 1),
        "serve_b1_tokens_per_sec": round(b1_tps, 1),
        "serve_vs_b1_speedup": round(serve_tps / b1_tps, 2),
        "serve_p50_ms": round(pick(lat_ms, 0.50), 1),
        "serve_p99_ms": round(pick(lat_ms, 0.99), 1),
        "serve_ttft_p50_ms": round(pick(ttft_ms, 0.50), 1),
        "serve_ttft_p99_ms": round(pick(ttft_ms, 0.99), 1),
        "serve_slots": engine.n_slots,
        "serve_occupancy": round(
            engine.stats["decode_slot_steps"]
            / max(engine.stats["decode_steps"] * engine.n_slots, 1),
            3,
        ),
        "serve_prefill_compiles": engine._prefill._cache_size(),
        "serve_decode_compiles": engine._decode._cache_size(),
        "serve_prefix_hit_rate": round(pm["prefix_hit_rate"], 3),
        "serve_prefill_tokens_saved": pm["prefill_tokens_saved"],
        "serve_prefill_saved_frac": round(pm["prefill_saved_frac"], 3),
        "serve_prefix_copy_compiles": pm["prefix_copy_compiles"],
        "serve_prefix_ttft_p50_ms": round(prefix_results["prefix"][1], 1),
        "serve_nocache_ttft_p50_ms": round(prefix_results["nocache"][1], 1),
        "serve_prefix_ttft_speedup": round(
            prefix_results["nocache"][1] / max(prefix_results["prefix"][1], 1e-9), 2
        ),
        "serve_router_r1_tokens_per_sec": round(router_tps[1], 1),
        "serve_router_r2_tokens_per_sec": round(router_tps[2], 1),
        "serve_router_scaling_efficiency": round(
            router_tps[2] / max(2 * router_tps[1], 1e-9), 3
        ),
        "serve_router_r1_ttft_p99_ms": round(router_ttft_p99[1], 1),
        "serve_router_r2_ttft_p99_ms": round(router_ttft_p99[2], 1),
        "serve_router_affinity_hit_rate": round(affinity_hit_rate["affinity"], 3),
        "serve_router_noaffinity_hit_rate": round(
            affinity_hit_rate["noaffinity"], 3
        ),
        "serve_router_affinity_hit_delta": round(
            affinity_hit_rate["affinity"] - affinity_hit_rate["noaffinity"], 3
        ),
    }


def _train_affine_lm(params, cfg, steps, *, task_vocab=256, lr=1e-3, seed=0):
    """Briefly train an LM on a fixed affine next-token chain
    (x_{t+1} = (3x_t + 7) mod task_vocab): a memorizable synthetic task
    both the spec-decode target and its small draft learn in O(100) tiny
    steps, so their argmax streams CORRELATE — the fix for the meaningless
    `specdecode_accept_rate 0.0` that random weights produced (VERDICT r5
    #2: a layer-prefix of random weights shares no distribution with its
    target; the accept MATH was verified aligned, see
    tests/test_speculative.py::TestAcceptRateRegression)."""
    import optax

    from accelerate_tpu.models import llama

    tx = optax.adamw(lr)
    opt = tx.init(params)

    @jax.jit
    def train_step(params, opt, batch):
        loss, g = jax.value_and_grad(
            lambda p: llama.loss_fn(p, {"input_ids": batch}, cfg)
        )(params)
        upd, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    rng = np.random.RandomState(seed)
    for _ in range(steps):
        params, opt, loss = train_step(
            params, opt, jnp.asarray(_affine_chain(rng, 16, 64, task_vocab))
        )
    return params, float(loss)


def _affine_chain(rng, B, S, task_vocab=256):
    x = rng.randint(0, task_vocab, (B, 1))
    xs = [x]
    for _ in range(S - 1):
        xs.append((3 * xs[-1] + 7) % task_vocab)
    return np.concatenate(xs, axis=1).astype(np.int32)


def _bench_specdecode(config) -> dict:
    """Speculative decoding at B=1 (the latency regime the reference's
    big-model tables report, `benchmarks/big_model_inference/README.md`):
    target = the headline decode model, draft = a separately-initialized
    2-layer model. Both are briefly trained on the same synthetic affine
    chain (`_train_affine_lm`) so their greedy streams CORRELATE and the
    accept rate measures the mechanism rather than the entropy of random
    weights — BENCH_r05's `specdecode_accept_rate 0.0` was the latter
    (VERDICT r5 #2); the accept comparison itself was verified aligned
    (tests/test_speculative.py::TestAcceptRateRegression). Greedy, so the
    output is bit-identical to vanilla decoding by construction.

    Also reports the self-draft run (accept == 1 by construction) as the
    mechanism ceiling."""
    import dataclasses
    import os

    from accelerate_tpu.generation import GenerationConfig, Generator
    from accelerate_tpu.models import llama
    from accelerate_tpu.speculative import SpeculativeGenerator

    tcfg = dataclasses.replace(config, remat=False, attention_impl="dot")
    dcfg = dataclasses.replace(tcfg, n_layers=2)
    train_steps = int(os.environ.get("ATX_BENCH_SPEC_TRAIN_STEPS", "150"))
    t0 = time.perf_counter()
    tparams_f32, t_loss = _train_affine_lm(
        llama.init(jax.random.PRNGKey(3), tcfg), tcfg, train_steps
    )
    dparams_f32, d_loss = _train_affine_lm(
        llama.init(jax.random.PRNGKey(5), dcfg), dcfg, train_steps
    )
    train_s = time.perf_counter() - t0
    params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), tparams_f32)
    draft_params = jax.tree.map(lambda x: x.astype(jnp.bfloat16), dparams_f32)
    del tparams_f32, dparams_f32
    prompt = jnp.asarray(_affine_chain(np.random.RandomState(4), 1, 128))
    short, long = 16, 80
    n_tokens = long - short

    def t_pair(cfg):
        return (
            lambda p, t, c: llama.forward_with_cache(p, t, c, cfg),
            lambda b, m: llama.init_cache(cfg, b, m),
        )

    ta, tc = t_pair(tcfg)
    da, dc = t_pair(dcfg)

    def run(gen, *args) -> float:
        t0 = time.perf_counter()
        out = gen(*args, prompt)
        int(out[0, -1])
        return time.perf_counter() - t0

    out = {
        "specdecode_train_s": round(train_s, 1),
        "specdecode_task_loss": round(t_loss, 4),
        "specdecode_draft_task_loss": round(d_loss, 4),
    }
    # Vanilla B=1 decode as the speedup denominator (the B=8 headline
    # number amortizes per-step overhead differently).
    van_s = Generator(ta, tc, GenerationConfig(max_new_tokens=short))
    van_l = Generator(ta, tc, GenerationConfig(max_new_tokens=long))
    run(van_s, params), run(van_l, params)  # compile
    base_dt = max(
        min(run(van_l, params) for _ in range(2))
        - min(run(van_s, params) for _ in range(2)),
        1e-9,
    )
    out["decode_b1_tokens_per_sec"] = round(n_tokens / base_dt, 1)
    for label, dp in (("specdecode", draft_params), ("specdecode_selfdraft", None)):
        d_apply, d_cache, d_params = (da, dc, dp) if dp is not None else (ta, tc, params)
        spec = SpeculativeGenerator(
            ta, tc, d_apply, d_cache, GenerationConfig(max_new_tokens=long), draft_tokens=4
        )

        cache_cap = prompt.shape[1] + long + 2 * (4 + 1)

        def srun(n) -> float:
            t0 = time.perf_counter()
            o = spec(params, d_params, prompt, max_new_tokens=n, cache_len=cache_cap)
            int(o[0, -1])
            return time.perf_counter() - t0

        srun(short), srun(long)  # compile prefill + spec_step once
        dt = max(
            min(srun(long) for _ in range(2)) - min(srun(short) for _ in range(2)),
            1e-9,
        )
        out[f"{label}_tokens_per_sec"] = round(n_tokens / dt, 1)
        out[f"{label}_speedup"] = round(base_dt / dt, 3)
        if dp is not None:
            out["specdecode_accept_rate"] = round(spec.last_accept_rate, 3)

    # Batched self-draft (acceptance 1 by construction): with PER-ROW cache
    # commits each row advances independently, so B=4 throughput must scale
    # ~4x over the B=1 self-draft number (VERDICT r4 #4's "done" bar) —
    # under the old min-commit scheme one slow row throttled the batch.
    B4 = 4
    prompt4 = jnp.tile(prompt, (B4, 1))
    spec4 = SpeculativeGenerator(
        ta, tc, ta, tc, GenerationConfig(max_new_tokens=long), draft_tokens=4
    )
    cache_cap = prompt.shape[1] + long + 2 * (4 + 1)

    def b4run(n) -> float:
        t0 = time.perf_counter()
        o = spec4(params, params, prompt4, max_new_tokens=n, cache_len=cache_cap)
        int(o[0, -1])
        return time.perf_counter() - t0

    b4run(short), b4run(long)
    dt4 = max(
        min(b4run(long) for _ in range(2)) - min(b4run(short) for _ in range(2)),
        1e-9,
    )
    out["specdecode_b4_selfdraft_tokens_per_sec"] = round(B4 * n_tokens / dt4, 1)
    return out


def _bench_llama2b(fetch_latency: float) -> dict:
    """Largest *trainable* llama on one chip (VERDICT r2 #3a): 1.64B params,
    seq 4096, flash + remat. bf16 weights + adafactor are how 2B-class
    models train on a 16 GiB chip (fp32 master + adam moments alone would
    need 20+ GiB); measured on v5e: L=24/attn_and_outputs/batch 2 is the
    MFU-optimal fit (L=26 or batch 4 exceed HBM, block_outputs loses ~8
    MFU points to recompute). Evidence the headline MFU survives 8B-class
    arithmetic intensity."""
    import optax

    import accelerate_tpu as atx
    from accelerate_tpu.models import llama
    from accelerate_tpu.state import AcceleratorState

    AcceleratorState._reset_state()
    config = llama.LlamaConfig(
        vocab_size=32000,
        d_model=2048,
        n_layers=24,
        num_heads=16,
        num_kv_heads=8,
        head_dim=128,
        d_ff=8192,
        max_seq_len=4096,
        remat=True,
        remat_policy="attn_and_outputs",
        attention_impl="flash",
        loss_chunk_size=512,
    )
    batch_size, seq, steps, warmup = 2, 4096, 8, 2
    acc = atx.Accelerator(mixed_precision="bf16", seed=0, max_grad_norm=1.0)
    state = acc.create_train_state(
        lambda r: llama.init(r, config, dtype=jnp.bfloat16), optax.adafactor(3e-4)
    )
    step = acc.make_train_step(lambda p, b, r: llama.loss_fn(p, b, config, r))
    batch = jax.device_put(
        {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(21), (batch_size, seq), 0, config.vocab_size, jnp.int32
            )
        }
    )
    state, metrics, dt, _ = _timed_steps(step, state, batch, steps, warmup, fetch_latency)
    tokens_per_sec = batch_size * (seq - 1) * steps / dt
    flops_per_token = 6.0 * config.param_count() + 6.0 * config.n_layers * config.d_model * seq
    peak = _peak_flops(jax.devices()[0])
    state, batch, metrics = acc.free_memory(state, batch, metrics)
    return {
        "llama2b_params": config.param_count(),
        "llama2b_mfu": round(tokens_per_sec * flops_per_token / peak, 4) if peak else 0.0,
        "llama2b_tokens_per_sec": round(tokens_per_sec, 1),
    }


def _bench_hostoffload_adamw(fetch_latency: float) -> dict:
    """VERDICT r3 #2: adam-class fine-tuning past HBM via host-resident
    optimizer state (parallel/host_offload.py). Same 1.64B model as the
    llama2b phase but with adamw — whose fp32 moments (13 GiB) plus bf16
    weights would not leave room for seq-4096 activations in 16 GiB HBM;
    the moments live in pinned host RAM and stream through the update
    inside the compiled step."""
    import optax

    import accelerate_tpu as atx
    from accelerate_tpu.models import llama
    from accelerate_tpu.parallel import host_offload
    from accelerate_tpu.state import AcceleratorState
    from accelerate_tpu.utils.dataclasses import FsdpPlugin

    AcceleratorState._reset_state()
    config = llama.LlamaConfig(
        vocab_size=32000,
        d_model=2048,
        n_layers=24,
        num_heads=16,
        num_kv_heads=8,
        head_dim=128,
        d_ff=8192,
        max_seq_len=4096,
        remat=True,
        remat_policy="attn_and_outputs",
        attention_impl="flash",
        loss_chunk_size=512,
    )
    # batch 1 (vs llama2b's 2): the fp32 backward cotangents of the three
    # big MLP matmuls (4.5 GiB) + the moment working set leave ~batch-1
    # headroom on 16 GiB; batch 2 compiles 0.8 GiB over.
    batch_size, seq, steps, warmup = 1, 4096, 6, 2
    acc = atx.Accelerator(
        mixed_precision="bf16",
        seed=0,
        max_grad_norm=1.0,
        strategy=FsdpPlugin(offload_optimizer=True),
    )
    state = acc.create_train_state(
        lambda r: llama.init(r, config, dtype=jnp.bfloat16),
        # fp32 moments: the adam configuration whose state genuinely cannot
        # share HBM with the activations at this scale (13 GiB of moments).
        atx.host_offloaded_adamw(1e-4, mu_dtype=jnp.float32),
    )
    offloaded = host_offload.HOST_MEMORY_KIND in {
        l.sharding.memory_kind
        for l in jax.tree.leaves(state.opt_state)
        if isinstance(l, jax.Array)
    }
    step = acc.make_train_step(lambda p, b, r: llama.loss_fn(p, b, config, r))
    batch = jax.device_put(
        {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(23), (batch_size, seq), 0, config.vocab_size, jnp.int32
            )
        }
    )
    state, metrics, dt, _ = _timed_steps(step, state, batch, steps, warmup, fetch_latency)
    tokens_per_sec = batch_size * (seq - 1) * steps / dt
    flops_per_token = 6.0 * config.param_count() + 6.0 * config.n_layers * config.d_model * seq
    peak = _peak_flops(jax.devices()[0])
    state, batch, metrics = acc.free_memory(state, batch, metrics)
    return {
        "hostoffload_adamw_params": config.param_count(),
        "hostoffload_adamw_active": offloaded,
        "hostoffload_adamw_mfu": round(tokens_per_sec * flops_per_token / peak, 4) if peak else 0.0,
        "hostoffload_adamw_tokens_per_sec": round(tokens_per_sec, 1),
    }


def _bench_vit(fetch_latency: float) -> dict:
    """ViT-base data-parallel training samples/sec — the cv_example config
    BASELINE.md tracks (VERDICT r2 #3b)."""
    import optax

    import accelerate_tpu as atx
    from accelerate_tpu.models import vit
    from accelerate_tpu.state import AcceleratorState

    AcceleratorState._reset_state()
    # remat + batch 64: vit-base at batch 128 without remat needs ~25 GiB
    # of activations (fp32 adam moments are small; the 197-token streams
    # are not) — v5e has 16.
    config = vit.ViTConfig.vit_base(remat=True)
    batch_size, steps, warmup = 64, 10, 3
    acc = atx.Accelerator(mixed_precision="bf16", seed=0, max_grad_norm=1.0)
    state = acc.create_train_state(
        lambda r: vit.init(r, config), optax.adamw(3e-4)
    )

    def loss_fn(p, b, r):
        logits = vit.forward(p, b["pixels"], config)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, b["label"][:, None], axis=1))

    step = acc.make_train_step(loss_fn)
    k = jax.random.PRNGKey(31)
    batch = jax.device_put(
        {
            "pixels": jax.random.normal(
                k, (batch_size, config.image_size, config.image_size, 3), jnp.bfloat16
            ),
            "label": jax.random.randint(
                jax.random.fold_in(k, 1), (batch_size,), 0, config.num_classes, jnp.int32
            ),
        }
    )
    state, metrics, dt, _ = _timed_steps(step, state, batch, steps, warmup, fetch_latency)
    state, batch, metrics = acc.free_memory(state, batch, metrics)
    return {"vit_samples_per_sec": round(batch_size * steps / dt, 1)}


# ------------------------------------------------------------- 8B big model
# Llama-3.1-8B-shaped (rope_scaling included — the exact config published
# repos carry; exercises the scaled-frequency ingestion path at bench scale).
_LLAMA3_8B_HF_CONFIG = {
    "model_type": "llama",
    "vocab_size": 128256,
    "hidden_size": 4096,
    "intermediate_size": 14336,
    "num_hidden_layers": 32,
    "num_attention_heads": 32,
    "num_key_value_heads": 8,
    "max_position_embeddings": 8192,
    "rope_theta": 500000.0,
    "rope_scaling": {
        "rope_type": "llama3",
        "factor": 8.0,
        "low_freq_factor": 1.0,
        "high_freq_factor": 4.0,
        "original_max_position_embeddings": 8192,
    },
    "rms_norm_eps": 1e-5,
    "tie_word_embeddings": False,
}


def _synth_llama8b_repo(repo: str, cfg: dict | None = None) -> None:
    """Write a Llama-3-8B-shaped HF repo (config.json + sharded fp16
    safetensors, real HF tensor names, ~16 GiB). Values are a tiled random
    block — load/quantize/decode timing is entropy-agnostic, and full-size
    RNG would dominate the one-time synthesis cost."""
    import json as _json
    import os

    import numpy as np
    from safetensors.numpy import save_file

    cfg = cfg or _LLAMA3_8B_HF_CONFIG
    os.makedirs(repo, exist_ok=True)
    with open(os.path.join(repo, "config.json"), "w") as f:
        _json.dump(cfg, f)

    rng = np.random.RandomState(0)
    block = (rng.standard_normal(1 << 20) * 0.02).astype(np.float16)

    def rnd(*shape) -> np.ndarray:
        n = int(np.prod(shape))
        reps = -(-n // block.size)
        return np.tile(block, reps)[:n].reshape(shape)

    d, ff = cfg["hidden_size"], cfg["intermediate_size"]
    head_dim = d // cfg["num_attention_heads"]
    kv = cfg["num_key_value_heads"] * head_dim
    weight_map: dict[str, str] = {}

    def dump(fname: str, tensors: dict) -> None:
        save_file(tensors, os.path.join(repo, fname))
        for k in tensors:
            weight_map[k] = fname

    dump(
        "model-embed.safetensors",
        {
            "model.embed_tokens.weight": rnd(cfg["vocab_size"], d),
            "lm_head.weight": rnd(cfg["vocab_size"], d),
            "model.norm.weight": np.ones((d,), np.float16),
        },
    )
    group = 4  # layers per shard file
    for start in range(0, cfg["num_hidden_layers"], group):
        tensors = {}
        for i in range(start, min(start + group, cfg["num_hidden_layers"])):
            L = f"model.layers.{i}."
            tensors[L + "input_layernorm.weight"] = np.ones((d,), np.float16)
            tensors[L + "post_attention_layernorm.weight"] = np.ones((d,), np.float16)
            tensors[L + "self_attn.q_proj.weight"] = rnd(d, d)
            tensors[L + "self_attn.k_proj.weight"] = rnd(kv, d)
            tensors[L + "self_attn.v_proj.weight"] = rnd(kv, d)
            tensors[L + "self_attn.o_proj.weight"] = rnd(d, d)
            tensors[L + "mlp.gate_proj.weight"] = rnd(ff, d)
            tensors[L + "mlp.up_proj.weight"] = rnd(ff, d)
            tensors[L + "mlp.down_proj.weight"] = rnd(d, ff)
        dump(f"model-layers-{start:02d}.safetensors", tensors)
    with open(os.path.join(repo, "model.safetensors.index.json"), "w") as f:
        _json.dump({"metadata": {"total_size": 0}, "weight_map": weight_map}, f)
    with open(os.path.join(repo, ".complete"), "w") as f:
        f.write("ok")


def _bench_bigmodel() -> dict:
    """The flagship big-model path EXECUTED at 8B scale (VERDICT r2 #1):
    stream a 16 GiB HF-named repo from disk, int8-quantize on the way in
    (only packed weights touch HBM), run batched `generate()` on the one
    chip. Reports wall-clock load+quantize seconds and steady-state decode
    tokens/sec — the numbers the reference publishes for its
    big-model-inference path (`benchmarks/big_model_inference`)."""
    import dataclasses
    import os

    import accelerate_tpu as atx
    from accelerate_tpu.generation import GenerationConfig
    from accelerate_tpu.models import llama
    from accelerate_tpu.state import AcceleratorState

    # The synthetic repo is ~16 GiB on disk and reused across runs. Point
    # ATX_BENCH_CACHE at a disk-backed path if /tmp is tmpfs (RAM-backed).
    cache = os.environ.get("ATX_BENCH_CACHE", "/tmp/atx_bench_cache")
    repo = os.path.join(cache, "llama3_8b_synth")
    if not os.path.exists(os.path.join(repo, ".complete")):
        t0 = time.perf_counter()
        _synth_llama8b_repo(repo)
        synth_s = time.perf_counter() - t0
    else:
        synth_s = 0.0
        # The weights are config-agnostic tiled noise; refresh config.json so
        # a repo cached by an older bench picks up config changes (e.g. the
        # llama-3.1 rope_scaling block) without a 16 GiB re-synthesis.
        with open(os.path.join(repo, "config.json"), "w") as f:
            json.dump(_LLAMA3_8B_HF_CONFIG, f)

    # Raw-read roofline: sequential read of one weight shard, so the load
    # time has an IO baseline to be judged against (VERDICT r3 #5).
    shard_file = next(
        os.path.join(repo, n) for n in sorted(os.listdir(repo))
        if n.endswith(".safetensors")
    )
    t0 = time.perf_counter()
    read_bytes = 0
    with open(shard_file, "rb", buffering=0) as f:
        while chunk := f.read(1 << 24):
            read_bytes += len(chunk)
    io_mib_s = read_bytes / (time.perf_counter() - t0) / 2**20

    # Host->device link roofline: the load time must be judged against what
    # the link can move. BENCH_r05's `device_put_mib_s: 23.9` was a
    # cold-path artifact: a 1 MiB warm-up does not open the full-size
    # transfer path, so the single timed 64 MiB put paid first-touch
    # allocation and link setup. Measure steady state instead — full-size
    # warm put, then best-of-3 — and report the chunked TransferEngine
    # (parallel/transfer.py, PR 1) over the same buffer alongside it, since
    # that is the path load_pretrained actually rides.
    from accelerate_tpu.parallel.transfer import TransferEngine

    probe = np.empty(64 * 2**20, np.int8)

    def _put_mib_s(fn) -> float:
        fn().block_until_ready()  # full-size warm: opens the real path
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            fn().block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return 64 / best

    tunnel_put_mib_s = _put_mib_s(lambda: jax.device_put(probe))
    transfer_engine = TransferEngine()
    engine_put_mib_s = _put_mib_s(lambda: transfer_engine.put(probe).result())
    del probe

    AcceleratorState._reset_state()
    t0 = time.perf_counter()
    loaded = atx.load_pretrained(
        repo,
        mesh=atx.build_mesh(atx.MeshConfig()),
        dtype=jnp.bfloat16,
        quantize_bits=8,
    )
    load_s = time.perf_counter() - t0

    gen_config = dataclasses.replace(
        loaded.config, remat=False, attention_impl="dot", max_seq_len=512
    )
    B, prompt_len = 8, 128
    short, long = 8, 40
    prompt = jax.random.randint(
        jax.random.PRNGKey(5), (B, prompt_len), 0, gen_config.vocab_size, jnp.int32
    )

    def run(n_new: int) -> float:
        t0 = time.perf_counter()
        out = llama.generate(
            loaded.params,
            prompt,
            gen_config,
            generation_config=GenerationConfig(max_new_tokens=n_new),
        )
        int(out[0, -1])  # fetch barrier
        return time.perf_counter() - t0

    run(short), run(long)  # compile both loop lengths
    dt_short = min(run(short) for _ in range(2))
    dt_long = min(run(long) for _ in range(2))
    decode_dt = max(dt_long - dt_short, 1e-9)
    n_tokens = long - short
    out = {
        "bigmodel_8b_params": loaded.config.param_count(),
        "bigmodel_8b_bits": 8,
        "bigmodel_8b_load_s": round(load_s, 1),
        "bigmodel_8b_synth_s": round(synth_s, 1),
        "io_read_mib_s": round(io_mib_s, 1),
        "device_put_mib_s": round(tunnel_put_mib_s, 1),
        "device_put_engine_mib_s": round(engine_put_mib_s, 1),
        "bigmodel_8b_decode_tokens_per_sec": round(B * n_tokens / decode_dt, 1),
        "bigmodel_8b_decode_ms_per_token": round(1000 * decode_dt / n_tokens, 2),
    }
    try:
        out.update(_bench_bigmodel_int8_prefill(loaded, gen_config, prompt))
    except Exception as e:
        out["bigmodel_prefill_error"] = f"{type(e).__name__}: {e}"[:200]
    try:
        out.update(_bench_bigmodel_specdecode(loaded, gen_config, prompt[:1]))
    except Exception as e:  # never lose the headline load/decode numbers
        out["bigmodel_spec_error"] = f"{type(e).__name__}: {e}"[:200]
    return out


def _bench_bigmodel_int8_prefill(loaded, gen_config, prompt) -> dict:
    """8B prefill on the already-int8-quantized weights: dequantize-first
    (weight-only) vs the int8 MXU path (`ops/int8.py`, VERDICT r4 #3).
    Prefill at B=8, S=128 is compute-bound — exactly where dequantizing to
    bf16 before the matmul leaves the ~2× int8 MXU rate unused."""
    from accelerate_tpu.models import llama
    from accelerate_tpu.ops.int8 import with_int8_compute

    B, S = prompt.shape
    cache0 = llama.init_cache(gen_config, B, S + 8)

    def fwd(p, t, c):
        return llama.forward_with_cache(p, t, c, gen_config)

    f_deq = jax.jit(fwd)
    # with_int8_compute gives the int8 variant its own function object (and
    # thus its own jit cache entry) AND guarantees every trace happens with
    # the mode on — jax.jit(fwd) twice would silently share one jaxpr.
    f_i8 = jax.jit(with_int8_compute(fwd))
    logits, _ = f_deq(loaded.params, prompt, cache0)
    logits_i8, _ = f_i8(loaded.params, prompt, cache0)

    def timed(f, k=5, reps=3) -> float:
        # k pipelined prefills per scalar fetch amortize the tunnel RTT.
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(k):
                lg, _ = f(loaded.params, prompt, cache0)
            float(lg[0, -1, 0])
            best = min(best, time.perf_counter() - t0)
        return best / k

    dt_deq = timed(f_deq)
    dt_i8 = timed(f_i8)
    # Logit drift bound: only activation rounding separates the paths.
    a = jnp.asarray(logits[:, -1, :], jnp.float32)
    b = jnp.asarray(logits_i8[:, -1, :], jnp.float32)
    drift = float(
        jnp.sqrt(jnp.mean((a - b) ** 2))
        / jnp.maximum(jnp.sqrt(jnp.mean(a**2)), 1e-9)
    )
    if drift == 0.0:
        # Identical logits mean the int8 trace silently aliased the bf16
        # one (the jit-cache pitfall) — refuse to report a fake comparison.
        raise RuntimeError("int8 prefill produced bit-identical logits")
    return {
        "prefill_8b_tokens_per_sec": round(B * S / dt_i8, 1),
        "prefill_8b_bf16_tokens_per_sec": round(B * S / dt_deq, 1),
        "prefill_8b_int8_speedup": round(dt_deq / dt_i8, 3),
        "prefill_8b_int8_logit_drift": round(drift, 6),
    }


def _bench_bigmodel_specdecode(loaded, gen_config, prompt) -> dict:
    """Speculative decoding where it actually pays: 8B int8 single-row
    decode is HBM-bandwidth-bound (every token streams all packed
    weights), so a K+1-token verify costs barely more than one decode step.
    Draft = the model's own first-2-layers prefix (zero extra load, shares
    embed/norms/head — quantized leaves slice along the stacked layer axis
    like any other). Greedy, so the stream equals vanilla decoding exactly;
    with the synthetic repo's random weights the accept rate is a FLOOR —
    report the self-consistency ceiling via implied tokens/iteration."""
    import dataclasses

    from accelerate_tpu.generation import GenerationConfig
    from accelerate_tpu.models import llama
    from accelerate_tpu.speculative import SpeculativeGenerator

    K = 4
    short, long = 8, 40
    n_tokens = long - short
    dcfg = dataclasses.replace(gen_config, n_layers=2)
    draft_params = dict(
        loaded.params,
        blocks=jax.tree.map(lambda x: x[:2], loaded.params["blocks"]),
    )

    def pair(cfg):
        return (
            lambda p, t, c: llama.forward_with_cache(p, t, c, cfg),
            lambda b, m: llama.init_cache(cfg, b, m),
        )

    ta, tc = pair(gen_config)
    da, dc = pair(dcfg)

    def vrun(n):
        # llama.generate caches its Generator per (config, gen_config), so
        # the short/long specializations compile once each.
        t0 = time.perf_counter()
        o = llama.generate(
            loaded.params, prompt, gen_config,
            generation_config=GenerationConfig(max_new_tokens=n),
        )
        int(o[0, -1])
        return time.perf_counter() - t0

    spec = SpeculativeGenerator(
        ta, tc, da, dc, GenerationConfig(max_new_tokens=long), draft_tokens=K
    )

    # Pin one cache capacity so short/long share one compiled graph set.
    spec_cache = prompt.shape[1] + long + 2 * (K + 1)

    def srun(n):
        t0 = time.perf_counter()
        o = spec(
            loaded.params, draft_params, prompt, max_new_tokens=n,
            cache_len=spec_cache,
        )
        int(o[0, -1])
        return time.perf_counter() - t0

    # Warm EVERY measured specialization (vanilla caches size on
    # prompt+max_new_tokens, so short and long are distinct compiles).
    vrun(short), vrun(long), srun(short), srun(long)
    base_dt = max(
        min(vrun(long) for _ in range(2)) - min(vrun(short) for _ in range(2)), 1e-9
    )
    spec_dt = max(
        min(srun(long) for _ in range(2)) - min(srun(short) for _ in range(2)), 1e-9
    )
    accept = spec.last_accept_rate
    out = {
        "bigmodel_8b_b1_decode_tokens_per_sec": round(n_tokens / base_dt, 1),
        "bigmodel_8b_specdecode_tokens_per_sec": round(n_tokens / spec_dt, 1),
        "bigmodel_8b_specdecode_speedup": round(base_dt / spec_dt, 3),
        "bigmodel_8b_specdecode_accept_rate": round(accept, 3),
    }
    # Mechanism ceiling: tokens/iteration scales 1 -> K+1 with acceptance,
    # iteration time is acceptance-independent (same draft scan + verify).
    # With random synthetic weights accept ~= 0, so the measured rate IS
    # ~the iteration rate; the ceiling says what a trained draft buys.
    iters_per_sec = (n_tokens / spec_dt) / (1 + K * accept)
    out["bigmodel_8b_specdecode_ceiling_tokens_per_sec"] = round(
        (K + 1) * iters_per_sec, 1
    )
    return out


def _bench_overram() -> dict:
    """Disk-offloaded decode (VERDICT r3 #4): block weights live on DISK as
    memmaps (never resident in host RAM), streamed layer-by-layer per
    generated token — the reference's disk_offload / OPT-30B configuration
    (`big_modeling.py:260`). Decode rate = link-bandwidth / streamed-bytes;
    through the axon tunnel H2D is ~20 MiB/s (measured; a real PCIe host
    does 10+ GiB/s), so the phase streams a layer-sliced view of the 8B
    repo (same tensors, same loader path, ATX_BENCH_OVERRAM_LAYERS of the
    32 layers) to keep the phase inside the driver budget, and reports the
    measured stream bandwidth so the number scales to real hosts."""
    import dataclasses
    import os

    import accelerate_tpu as atx
    from accelerate_tpu.models import llama
    from accelerate_tpu.state import AcceleratorState

    cache = os.environ.get("ATX_BENCH_CACHE", "/tmp/atx_bench_cache")
    repo = os.path.join(cache, "llama3_8b_synth")
    if not os.path.exists(os.path.join(repo, ".complete")):
        return {"overram_error": "synth repo missing (bigmodel phase runs first)"}
    n_layers = int(os.environ.get("ATX_BENCH_OVERRAM_LAYERS", "3"))
    # A view repo: the 8B safetensors linked in place, config clamped to the
    # first n_layers (the loader reads only the tensors the shapes need).
    view = os.path.join(cache, f"overram_view_l{n_layers}")
    os.makedirs(view, exist_ok=True)
    cfg = dict(_LLAMA3_8B_HF_CONFIG)
    cfg["num_hidden_layers"] = n_layers
    with open(os.path.join(view, "config.json"), "w") as f:
        json.dump(cfg, f)
    for name in os.listdir(repo):
        if name.endswith(".safetensors") or name.endswith(".index.json"):
            dst = os.path.join(view, name)
            if not os.path.exists(dst):
                os.symlink(os.path.join(repo, name), dst)

    AcceleratorState._reset_state()
    t0 = time.perf_counter()
    loaded = atx.load_pretrained(
        view,
        mesh=atx.build_mesh(atx.MeshConfig()),
        dtype=jnp.bfloat16,
        # Budget just above the resident set (embed+lm_head bf16 = 2.1 GiB)
        # so every block is forced onto disk.
        hbm_budget=int(2.4 * 2**30),
        no_offload_patterns=("embed", "lm_head", "final_norm"),
        offload_dir=os.path.join(view, "offload"),
    )
    load_s = time.perf_counter() - t0
    n_memmap = sum(
        isinstance(l, np.memmap) for l in jax.tree.leaves(loaded.params)
    )
    if n_memmap == 0:
        return {"overram_error": "plan offloaded nothing to disk"}
    streamed_bytes = sum(
        l.nbytes for l in jax.tree.leaves(loaded.params) if isinstance(l, np.memmap)
    )

    gen_config = dataclasses.replace(
        loaded.config, remat=False, attention_impl="dot", max_seq_len=64
    )
    prompt = jax.random.randint(
        jax.random.PRNGKey(6), (1, 16), 0, gen_config.vocab_size, jnp.int32
    )
    n_new = int(os.environ.get("ATX_BENCH_OVERRAM_TOKENS", "2"))
    t0 = time.perf_counter()
    out = llama.generate_offloaded(
        loaded.params, prompt, gen_config, max_new_tokens=n_new
    )
    int(out[0, -1])
    dt = time.perf_counter() - t0
    # generate_offloaded runs 1 prefill + (n_new - 1) decode forwards.
    per_pass = dt / n_new
    return {
        "bigmodel_overram_disk_leaves": n_memmap,
        "bigmodel_overram_layers": n_layers,
        "bigmodel_overram_streamed_gib_per_token": round(streamed_bytes / 2**30, 2),
        "bigmodel_overram_stream_mib_s": round(streamed_bytes / per_pass / 2**20, 1),
        "bigmodel_overram_load_s": round(load_s, 1),
        "bigmodel_overram_decode_tokens_per_sec": round(n_new / dt, 4),
    }


def _bench_bert(on_tpu: bool, fetch_latency: float) -> dict:
    """BERT-base training throughput — the `nlp_example` config BASELINE.md
    tracks (samples/sec/chip, bf16, seq 128). Returned as extra fields on the
    bench's single JSON line."""
    import optax

    import accelerate_tpu as atx
    from accelerate_tpu.models import bert
    from accelerate_tpu.state import AcceleratorState

    AcceleratorState._reset_state()
    if on_tpu:
        config = bert.BertConfig.bert_base()
        batch_size, seq, steps, warmup = 128, 128, 10, 3
    else:
        config = bert.BertConfig.tiny()
        batch_size, seq, steps, warmup = 8, 32, 3, 1

    acc = atx.Accelerator(mixed_precision="bf16", seed=0, max_grad_norm=1.0)
    state = acc.create_train_state(lambda r: bert.init(r, config), optax.adamw(3e-5))
    step = acc.make_train_step(lambda p, b, r: bert.loss_fn(p, b, config, r))
    rng = jax.random.PRNGKey(2)
    batch = {
        "input_ids": jax.random.randint(rng, (batch_size, seq), 3, config.vocab_size, jnp.int32),
        "attention_mask": jnp.ones((batch_size, seq), jnp.int32),
        "token_type_ids": jnp.zeros((batch_size, seq), jnp.int32),
        "labels": jax.random.randint(rng, (batch_size,), 0, config.num_labels, jnp.int32),
    }
    batch = jax.device_put(batch)
    state, metrics, dt, _ = _timed_steps(step, state, batch, steps, warmup, fetch_latency)
    stats = {
        "bert_samples_per_sec": round(batch_size * steps / dt, 1),
        "bert_step_time_ms": round(1000 * dt / steps, 2),
        "bert_params": config.param_count(),
    }
    # Free BERT buffers so the long-context bench that follows has full HBM.
    state, batch, metrics = acc.free_memory(state, batch, metrics)
    return stats


# ------------------------------------------------ regression compare gate
# `python bench.py --compare OLD.json NEW.json [--threshold 0.05]
#  [--series name,name,...]` diffs two bench result lines (the BENCH_r0x
# lineage) and exits non-zero on a regression beyond the threshold — the
# trajectory gate future perf PRs run in CI (`make smoke-trace`).

# Metric direction by suffix. Checked in order: a name matching a
# higher-better suffix is higher-better even when a lower-better suffix
# also matches (e.g. *_mib_s ends with both "_mib_s" and "_s").
_HIGHER_BETTER = (
    "_mfu", "_tokens_per_sec", "_samples_per_sec", "_per_sec", "_tflops",
    "_mib_s", "_gib_s", "_speedup", "_hit_rate", "_flops", "_mfu_bound",
    "_max_slots",
)
_LOWER_BETTER = (
    "_ms", "_s", "_secs", "_compiles", "_gib_per_token", "_comms_mib",
    "_waste_frac", "_peak_hbm_mib",
)


def _direction(name: str) -> int:
    """+1 higher-better, -1 lower-better, 0 not a perf series."""
    for suf in _HIGHER_BETTER:
        if name.endswith(suf):
            return 1
    for suf in _LOWER_BETTER:
        if name.endswith(suf):
            return -1
    return 0


def compare_results(
    old_path: str,
    new_path: str,
    *,
    threshold: float = 0.05,
    series: list[str] | None = None,
) -> tuple[list[str], int]:
    """Diff two bench JSON result files. Returns (regression messages,
    number of series compared). A series regresses when it moves against
    its direction by more than ``threshold`` (relative). ``series``
    restricts the comparison to named keys (and makes a named key MISSING
    from the new result a regression too — a silently dropped series must
    not pass the gate)."""
    with open(old_path) as f:
        old = json.load(f)
    with open(new_path) as f:
        new = json.load(f)
    regressions: list[str] = []
    compared = 0
    names = series if series is not None else sorted(set(old) & set(new))
    for name in names:
        if series is not None and (name not in old or name not in new):
            missing = "new" if name not in new else "old"
            regressions.append(f"{name}: named series missing from {missing} result")
            continue
        ov, nv = old.get(name), new.get(name)
        if (
            isinstance(ov, bool) or isinstance(nv, bool)
            or not isinstance(ov, (int, float))
            or not isinstance(nv, (int, float))
        ):
            continue
        sign = _direction(name)
        if sign == 0 and series is None:
            continue  # unnamed non-perf keys (counts, params) are ignored
        compared += 1
        if not ov:
            continue  # no baseline magnitude to compare against
        rel = (nv - ov) / abs(ov)
        if sign >= 0 and rel < -threshold:
            regressions.append(
                f"{name}: {ov} -> {nv} ({rel:+.1%}, higher is better, "
                f"threshold {threshold:.0%})"
            )
        elif sign < 0 and rel > threshold:
            regressions.append(
                f"{name}: {ov} -> {nv} ({rel:+.1%}, lower is better, "
                f"threshold {threshold:.0%})"
            )
    return regressions, compared


def _compare_main(argv: list[str]) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="bench.py --compare",
        description="Regression-gate two bench result JSON files",
    )
    p.add_argument("old")
    p.add_argument("new")
    p.add_argument(
        "--threshold", type=float, default=0.05,
        help="relative regression tolerance (default 0.05 = 5%%)",
    )
    p.add_argument(
        "--series", default=None,
        help="comma-separated series names to gate on (default: every "
        "shared key with a recognized perf suffix); a named series "
        "missing from either side is itself a regression",
    )
    args = p.parse_args(argv)
    series = (
        [s.strip() for s in args.series.split(",") if s.strip()]
        if args.series else None
    )
    regressions, compared = compare_results(
        args.old, args.new, threshold=args.threshold, series=series
    )
    for msg in regressions:
        print(f"REGRESSION {msg}")
    print(
        json.dumps(
            {
                "compared": compared,
                "regressions": len(regressions),
                "threshold": args.threshold,
                "ok": not regressions,
            }
        )
    )
    return 1 if regressions else 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--compare":
        sys.exit(_compare_main(sys.argv[2:]))
    main()

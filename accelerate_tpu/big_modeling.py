"""Big-model inference: shape-only init, HBM-budget planning, streamed
sharded loading, and host-RAM offload for over-HBM models.

TPU-native redesign of the reference big-modeling stack:

- `init_empty_weights` (reference `big_modeling.py:58`): torch meta device ->
  `jax.eval_shape`. Nothing is allocated; the result is a pytree of
  ShapeDtypeStructs that the planner and loaders consume.
- `infer_sharding_plan` (reference `utils/modeling.py:1281`
  `infer_auto_device_map` + `:923` `get_balanced_memory`): the reference
  greedily assigns whole layers to devices ("device map"); on TPU the analog
  is a PartitionSpec per leaf over the mesh — GSPMD shards every layer across
  all chips instead of pinning layers to single chips, which is both the
  faster and the simpler layout. The planner starts from the family's TP/FSDP
  rules, measures per-device bytes against the HBM budget, widens sharding if
  needed, and spills the largest leaves to host RAM last (the
  `cpu_offload` analog, reference `big_modeling.py:170`).
- `load_checkpoint_and_dispatch` (reference `big_modeling.py:511`,
  `utils/modeling.py:1787`): streams a checkpoint leaf-by-leaf straight into
  sharded device buffers — each device fetches exactly its slice via
  `jax.make_array_from_callback`, so no host ever materializes the full
  model. Reads this framework's sharded format, consolidated `.npz`, and
  HF-style safetensors (single file or `*.index.json` shards).
- `offload_blocks` / `streamed_scan` (reference `hooks.py:226`
  `AlignDevicesHook`, `utils/offload.py:127`): for scan-over-layers models
  whose stacked blocks exceed HBM, block params stay in host RAM and stream
  one layer ahead of compute (double buffering) — the forward-hook
  weight-staging pattern without monkey-patching forward.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .parallel.sharding import (
    Rules,
    _path_str,
    _sanitize_spec,
    _shard_largest_dim,
)

__all__ = [
    "init_empty_weights",
    "compute_leaf_sizes",
    "ShardingPlan",
    "infer_sharding_plan",
    "load_checkpoint_and_dispatch",
    "offload_blocks",
    "streamed_scan",
]


def init_empty_weights(init_fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
    """Shape-only "materialization" of a model (reference `init_empty_weights`,
    `big_modeling.py:58`): returns the params pytree as ShapeDtypeStructs
    without allocating anything, on host or device."""
    return jax.eval_shape(init_fn, *args, **kwargs)


def _leaf_bytes(leaf: Any, dtype: Any | None = None) -> int:
    shape = tuple(getattr(leaf, "shape", ()))
    dt = np.dtype(dtype) if dtype is not None else np.dtype(leaf.dtype)
    return int(np.prod(shape)) * dt.itemsize if shape else dt.itemsize


def compute_leaf_sizes(shapes: Any, dtype: Any | None = None) -> dict[str, int]:
    """Per-leaf byte sizes (reference `compute_module_sizes`,
    `utils/modeling.py:656`). ``dtype`` overrides each leaf's dtype (e.g.
    planning a bf16 deployment of fp32-initialized weights)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(shapes)
    return {_path_str(path): _leaf_bytes(leaf, dtype) for path, leaf in flat}


@dataclass
class ShardingPlan:
    """The TPU "device map": a PartitionSpec per leaf + host-offload set.

    ``specs`` is a pytree matching the params; ``offload`` holds the leaf
    paths that stay in host RAM; ``fits`` says whether the on-device portion
    fits the per-device budget; ``per_device_bytes`` is the planned resident
    HBM per chip (offloaded leaves count only via ``streaming_bytes`` — the
    largest single offloaded leaf that must be staged during execution).
    """

    specs: Any
    mesh: Mesh
    offload: set[str] = field(default_factory=set)
    per_device_bytes: int = 0
    streaming_bytes: int = 0
    budget_bytes: int | None = None
    total_bytes: int = 0
    fits: bool = True

    def summary(self) -> str:
        gib = 1 << 30
        lines = [
            f"total params: {self.total_bytes / gib:.2f} GiB",
            f"per-device resident: {self.per_device_bytes / gib:.2f} GiB"
            + (f" (budget {self.budget_bytes / gib:.2f} GiB)" if self.budget_bytes else ""),
            f"fits: {self.fits}",
        ]
        if self.offload:
            lines.append(
                f"host-offloaded leaves: {len(self.offload)} "
                f"(streaming working set {self.streaming_bytes / gib:.2f} GiB)"
            )
        return "\n".join(lines)


def infer_sharding_plan(
    shapes: Any,
    mesh: Mesh,
    *,
    hbm_budget: int | None = None,
    rules: Rules = (),
    dtype: Any | None = None,
    no_offload_patterns: Sequence[str] = (),
    min_weight_size: int = 2**11,
) -> ShardingPlan:
    """Plan shardings for a shape-only model against a per-chip HBM budget
    (reference `infer_auto_device_map`, `utils/modeling.py:1281`).

    Strategy (greedy, three passes — mirrors the reference's
    biggest-first greedy assignment but over PartitionSpecs):

    1. apply the family ``rules`` (TP plan) where they match;
    2. if per-device bytes exceed the budget, shard every still-replicated
       leaf's largest divisible dim across the whole mesh (FSDP-widen),
       biggest leaves first, until it fits;
    3. still over budget: move the biggest leaves to host RAM (``offload``),
       excluding ``no_offload_patterns`` (e.g. embeddings read every step).

    ``fits=False`` on the returned plan means even full offload of eligible
    leaves cannot fit the resident set — the caller needs a bigger mesh.
    """
    n_devices = int(np.prod(list(mesh.shape.values()))) or 1
    all_axes = tuple(mesh.shape.keys())
    flat, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    sizes = compute_leaf_sizes(shapes, dtype)
    total = sum(sizes.values())

    specs: dict[str, PartitionSpec] = {}
    for path, leaf in flat:
        key = _path_str(path)
        shape = tuple(leaf.shape)
        spec = PartitionSpec()
        for pattern, rule_spec in rules:
            if re.search(pattern, key):
                spec = _sanitize_spec(rule_spec, shape, mesh, path=key)
                break
        specs[key] = spec

    def shard_factor(key: str, leaf: Any) -> int:
        """How many ways the planned spec divides this leaf."""
        factor = 1
        for entry in specs[key]:
            if entry is None:
                continue
            axes = (entry,) if isinstance(entry, str) else tuple(entry)
            factor *= int(np.prod([mesh.shape[a] for a in axes]))
        return factor

    def resident_per_device() -> int:
        return sum(
            sizes[_path_str(p)] // shard_factor(_path_str(p), l)
            for p, l in flat
            if _path_str(p) not in offload
        )

    offload: set[str] = set()

    # Pass 2: FSDP-widen replicated/under-sharded leaves, biggest first.
    if hbm_budget is not None and resident_per_device() > hbm_budget:
        order = sorted(flat, key=lambda pl: -sizes[_path_str(pl[0])])
        for path, leaf in order:
            key = _path_str(path)
            if shard_factor(key, leaf) >= n_devices:
                continue
            widened = _shard_largest_dim(
                tuple(leaf.shape), all_axes, mesh, min_weight_size
            )
            if widened != PartitionSpec():
                specs[key] = widened
            if resident_per_device() <= hbm_budget:
                break

    # Pass 3: host-offload the biggest leaves that remain.
    if hbm_budget is not None and resident_per_device() > hbm_budget:
        order = sorted(flat, key=lambda pl: -sizes[_path_str(pl[0])])
        for path, leaf in order:
            key = _path_str(path)
            if any(re.search(pat, key) for pat in no_offload_patterns):
                continue
            offload.add(key)
            if resident_per_device() <= hbm_budget:
                break

    resident = resident_per_device()
    streaming = max(
        (sizes[k] // shard_factor(k, None) for k in offload), default=0
    )
    spec_leaves = [specs[_path_str(p)] for p, _ in flat]
    return ShardingPlan(
        specs=jax.tree_util.tree_unflatten(treedef, spec_leaves),
        mesh=mesh,
        offload=offload,
        per_device_bytes=resident,
        streaming_bytes=streaming,
        budget_bytes=hbm_budget,
        total_bytes=total,
        fits=hbm_budget is None or resident <= hbm_budget,
    )


# ----------------------------------------------------------- checkpoint readers
class _NpzSource:
    """Consolidated `.npz` checkpoint (the `consolidate_checkpoint` output)."""

    def __init__(self, path: str) -> None:
        self._npz = np.load(path)
        self._last: tuple[str, np.ndarray] | None = None

    def keys(self) -> Iterable[str]:
        return self._npz.files

    def read_slice(self, key: str, idx: tuple[slice, ...]) -> np.ndarray:
        # NpzFile re-reads + decompresses the zip member on every access, and
        # an N-device mesh requests N slices of each leaf — cache the
        # last-decoded array (leaves are read leaf-at-a-time, so one entry
        # suffices without pinning the whole checkpoint in RAM).
        if self._last is None or self._last[0] != key:
            self._last = (key, self._npz[key])
        return self._last[1][idx]

    def close(self) -> None:
        self._last = None
        self._npz.close()


class _ShardedSource:
    """This framework's sharded checkpoint directory (index_*.json)."""

    def __init__(self, directory: str) -> None:
        from .checkpointing import _ShardReader

        self._reader = _ShardReader(directory)

    def keys(self) -> Iterable[str]:
        return self._reader.index.keys()

    def read_slice(self, key: str, idx: tuple[slice, ...]) -> np.ndarray:
        info = self._reader.leaf_info(key)
        return self._reader.read_slice(
            key, idx, tuple(info["shape"]), np.dtype(info["dtype"])
        )

    def close(self) -> None:
        self._reader.close()


class _SafetensorsSource:
    """HF-style safetensors: one `.safetensors` file or a sharded repo dir
    with `*.index.json` (reference `load_state_dict`, `utils/modeling.py:1615`
    — lazy per-tensor reads, never the whole file)."""

    def __init__(self, path: str) -> None:
        from safetensors import safe_open

        self._safe_open = safe_open
        self._files: dict[str, Any] = {}
        self._key_to_file: dict[str, str] = {}
        if os.path.isdir(path):
            index = None
            for name in os.listdir(path):
                if name.endswith(".index.json"):
                    index = os.path.join(path, name)
                    break
            if index is not None:
                with open(index) as f:
                    weight_map = json.load(f)["weight_map"]
                for key, fname in weight_map.items():
                    self._key_to_file[key] = os.path.join(path, fname)
            else:
                for name in sorted(os.listdir(path)):
                    if name.endswith(".safetensors"):
                        self._scan_file(os.path.join(path, name))
        else:
            self._scan_file(path)

    def _scan_file(self, path: str) -> None:
        with self._safe_open(path, framework="numpy") as f:
            for key in f.keys():
                self._key_to_file[key] = path

    def _open(self, path: str) -> Any:
        if path not in self._files:
            self._files[path] = self._safe_open(path, framework="numpy").__enter__()
        return self._files[path]

    def keys(self) -> Iterable[str]:
        return self._key_to_file.keys()

    def read_slice(self, key: str, idx: tuple[slice, ...]) -> np.ndarray:
        f = self._open(self._key_to_file[key])
        return f.get_slice(key)[idx]

    def close(self) -> None:
        for f in self._files.values():
            f.__exit__(None, None, None)
        self._files.clear()


def _open_source(path: str):
    if os.path.isfile(path) and path.endswith(".npz"):
        return _NpzSource(path)
    if os.path.isfile(path) and path.endswith(".safetensors"):
        return _SafetensorsSource(path)
    if os.path.isdir(path):
        names = os.listdir(path)
        if any(re.match(r"^index_\d+\.json$", n) for n in names):
            return _ShardedSource(path)
        if any(n.endswith(".safetensors") or n.endswith(".index.json") for n in names):
            return _SafetensorsSource(path)
    raise ValueError(f"Unrecognized checkpoint layout at {path}")


def load_checkpoint_and_dispatch(
    shapes: Any,
    checkpoint_path: str,
    plan: ShardingPlan,
    *,
    key_map: Callable[[str], str] | None = None,
    dtype: Any | None = None,
    offload_dir: str | None = None,
) -> Any:
    """Stream a checkpoint into sharded device buffers per ``plan``
    (reference `load_checkpoint_and_dispatch`, `big_modeling.py:511`).

    Each on-device leaf is built with `jax.make_array_from_callback`: every
    device pulls exactly its planned slice from the source — works for
    checkpoints far larger than any single host's RAM. Leaves in
    ``plan.offload`` are returned as host numpy arrays (stream them through
    `streamed_scan` at execution time).

    ``key_map`` translates this model's leaf paths to source tensor names
    (e.g. HF checkpoint naming); ``dtype`` casts on the fly (bf16 deploys of
    fp32 checkpoints).
    """
    source = _open_source(checkpoint_path)

    def make_fetch(key: str, leaf: Any) -> Callable[[tuple], np.ndarray]:
        src_key = key_map(key) if key_map else key
        return lambda idx, _k=src_key: np.asarray(source.read_slice(_k, tuple(idx)))

    try:
        return dispatch_leaves(
            shapes, plan, make_fetch, dtype=dtype, offload_dir=offload_dir,
            source_id=source_fingerprint(checkpoint_path) if offload_dir else "",
        )
    finally:
        source.close()


def source_fingerprint(checkpoint_path: str) -> str:
    """Identity of a checkpoint directory for the disk-offload cache: the
    resolved path plus each weight file's (name, size, mtime). Two
    same-architecture checkpoints (base model vs finetune) must never share
    cached .bin dumps."""
    path = os.path.realpath(os.fspath(checkpoint_path))
    parts = [path]
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            if name.endswith((".safetensors", ".npz", ".bin")):
                st = os.stat(os.path.join(path, name))
                parts.append(f"{name}:{st.st_size}:{st.st_mtime_ns}")
    elif os.path.exists(path):
        st = os.stat(path)
        parts.append(f"{st.st_size}:{st.st_mtime_ns}")
    return "|".join(parts)


def _disk_offload_leaf(
    directory: str,
    key: str,
    shape: tuple,
    dtype: np.dtype,
    fetch: Callable[[tuple], np.ndarray],
    chunk_bytes: int = 1 << 28,
    fingerprint: str = "",
) -> np.ndarray:
    """Write one offloaded leaf to ``<directory>/<key>.bin`` (chunked along
    dim 0, so host RAM holds at most ``chunk_bytes`` of it) and return a
    read-mode memmap — the reference ``offload_weight`` / offload_dir
    layout (`utils/offload.py:34,127`: per-tensor .dat + index.json), numpy
    flavored. A leaf whose index entry already matches is reused, so
    repeated loads of the same repo skip the dump."""
    os.makedirs(directory, exist_ok=True)
    fname = key.replace("/", ".") + ".bin"
    path = os.path.join(directory, fname)
    index_path = os.path.join(directory, "index.json")
    index: dict = {}
    if os.path.exists(index_path):
        try:
            with open(index_path) as f:
                index = json.load(f)
        except ValueError:
            index = {}
    entry = {"shape": list(shape), "dtype": str(dtype), "source": fingerprint}
    if index.get(key) != entry or not os.path.exists(path):
        tmp = path + ".tmp"
        mm = np.memmap(tmp, mode="w+", dtype=dtype, shape=shape)
        row_bytes = int(np.prod(shape[1:], dtype=np.int64)) * dtype.itemsize
        rows = max(1, chunk_bytes // max(1, row_bytes))
        for start in range(0, shape[0], rows):
            stop = min(shape[0], start + rows)
            idx = (slice(start, stop),) + tuple(slice(0, d) for d in shape[1:])
            mm[start:stop] = np.asarray(fetch(idx), dtype=dtype)
        mm.flush()
        del mm
        os.replace(tmp, path)
        index[key] = entry
        with open(index_path, "w") as f:
            json.dump(index, f)
    return np.memmap(path, mode="r", dtype=dtype, shape=shape)


def dispatch_leaves(
    shapes: Any,
    plan: ShardingPlan,
    make_fetch: Callable[[str, Any], Callable[[tuple], np.ndarray]],
    *,
    dtype: Any | None = None,
    leaf_override: Callable[[str, Any, Callable], Any] | None = None,
    offload_dir: str | None = None,
    source_id: str = "",
) -> Any:
    """Shared streaming-dispatch core: for each leaf of ``shapes``,
    ``make_fetch(plan_key, leaf)`` returns a host-side callback mapping a
    slice index to the leaf's content; sharded leaves are built with
    `jax.make_array_from_callback` (each device pulls exactly its planned
    slice), ``plan.offload`` leaves come back as full host numpy arrays.
    Both `load_checkpoint_and_dispatch` and the HF-named streaming loader
    (`models/hf.py`) ride this loop.

    ``leaf_override(plan_key, leaf, fetch)`` may return either a finished
    replacement leaf, or a ``(host_fn, place_fn)`` pair — the host stage
    runs on the pipeline's IO worker, the place stage on the shared
    transfer engine's worker pool — or None to take the normal path.

    The loop is a pipeline: while the transfer engine pushes leaf i's
    bytes to the device(s) (chunked, multiple concurrent streams —
    `parallel/transfer.py`), a worker thread is already reading and
    transforming leaf i+1 (and i+2). Loads through a slow device link are
    then bounded by max(read+pack, transfer) instead of their sum, and the
    transfer term itself is no longer serialized behind one Python-level
    ``device_put`` call per leaf (BENCH_r05 measured that serialization at
    23.9 MiB/s against a 2655.9 MiB/s disk). One IO worker, because the
    checkpoint source's lazy file handles are not thread-safe; the read
    order also stays sequential, which is what spinning-disk and network
    filesystems want."""
    from concurrent.futures import Future, ThreadPoolExecutor

    from .parallel.transfer import get_transfer_engine

    engine = get_transfer_engine()

    def _done(value: Any) -> Future:
        f: Future = Future()
        f.set_result(value)
        return f

    mesh = plan.mesh
    flat, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    spec_leaves = jax.tree.leaves(
        plan.specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
    )

    def _norm(idx: tuple, shape: tuple) -> tuple:
        return tuple(
            (s.start or 0, shape[d] if s.stop is None else s.stop)
            for d, s in enumerate(idx)
        )

    def make_stages(path, leaf, spec):
        """-> (host_fn, place_fn): host_fn runs on the IO worker and returns
        the staged host-side payload; place_fn consumes it and returns a
        FUTURE of the finished leaf (device traffic rides the shared
        transfer engine — chunked multi-stream H2D for fully-owned leaves,
        pooled make_array for multi-host sharded ones)."""
        key = _path_str(path)
        shape = tuple(leaf.shape)
        target_dtype = np.dtype(dtype) if dtype is not None else np.dtype(leaf.dtype)
        fetch = make_fetch(key, leaf)
        if leaf_override is not None:
            replaced = leaf_override(key, leaf, fetch)
            if replaced is not None:
                if isinstance(replaced, tuple) and callable(replaced[0]):
                    h, p = replaced
                    return h, (lambda staged, _p=p: engine.submit(_p, staged))
                return (lambda _r=replaced: _r), _done
        if key in plan.offload:
            if offload_dir is not None:
                # Disk offload: the leaf never fully materializes in host
                # RAM — streamed to disk in chunks, returned as a memmap
                # whose per-layer slices `streamed_scan` reads on demand
                # (reference disk_offload, `big_modeling.py:260`).
                return (
                    lambda: _disk_offload_leaf(
                        offload_dir, key, shape, target_dtype, fetch,
                        fingerprint=source_id,
                    ),
                    _done,
                )
            return (
                lambda: np.asarray(
                    fetch(tuple(slice(0, d) for d in shape)), dtype=target_dtype
                ),
                _done,
            )
        sharding = NamedSharding(mesh, spec)
        full_idx = tuple((0, d) for d in shape)

        def host_fn():
            # Prefetch exactly this process's addressable shard slices
            # (deduped across replicas) so multi-host behavior is unchanged:
            # no host ever reads bytes it doesn't own.
            staged: dict[tuple, np.ndarray] = {}
            for dev, idx in sharding.devices_indices_map(shape).items():
                if dev.process_index != jax.process_index():
                    continue
                nidx = _norm(idx, shape)
                if nidx not in staged:
                    staged[nidx] = np.asarray(fetch(idx), dtype=target_dtype)
            return staged

        def place_fn(staged):
            if set(staged.keys()) == {full_idx}:
                # This process stages the whole leaf (single chip, or a
                # replicated/one-slice layout): the chunked engine path
                # replaces the single serialized device_put call.
                return engine.put(staged[full_idx], sharding=sharding)
            return engine.submit(
                lambda: jax.make_array_from_callback(
                    shape, sharding, lambda idx: staged[_norm(idx, shape)]
                )
            )

        return host_fn, place_fn

    stages = [
        make_stages(path, leaf, spec)
        for (path, leaf), spec in zip(flat, spec_leaves)
    ]
    # Pipeline: one IO worker reads+packs ahead (sequential, the source's
    # lazy handles are not thread-safe and disks want sequential reads);
    # placement goes through the shared transfer engine, whose worker pool
    # keeps several chunk streams in flight per leaf (the remote-tunnel
    # link serializes per call at ~50 MiB/s but aggregates with concurrent
    # streams — measured on the v5e tunnel). The window keeps at most
    # `depth` staged payloads + `window` un-finished placements alive so
    # host RAM stays bounded.
    depth = max(2, engine.prefetch_depth)
    window = depth + 1
    out: list = []
    with ThreadPoolExecutor(max_workers=1) as io_ex:
        host_futures = [io_ex.submit(h) for h, _p in stages[:depth]]
        place_futures: list = []
        for i, (_h, place) in enumerate(stages):
            if i + depth < len(stages):
                host_futures.append(io_ex.submit(stages[i + depth][0]))
            place_futures.append(place(host_futures[i].result()))
            host_futures[i] = None  # release the staged payload reference
            if i >= window:
                place_futures[i - window].result()  # backpressure
        out = [f.result() for f in place_futures]
    return jax.tree_util.tree_unflatten(treedef, out)


# ------------------------------------------------------------- layer streaming
def offload_blocks(blocks: Any) -> Any:
    """Move a stacked block pytree (leading layer axis on every leaf) to host
    RAM (reference `cpu_offload`, `big_modeling.py:170`). All leaves drain
    concurrently through the transfer engine's D2H path."""
    from .parallel.transfer import get_transfer_engine

    return get_transfer_engine().get_tree(blocks).result()


def streamed_scan(
    body: Callable[[Any, Any], Any],
    carry: Any,
    host_blocks: Any,
    *,
    sharding: Any | None = None,
    dtype: Any | None = None,
    engine: Any | None = None,
    prefetch_depth: int | None = None,
) -> Any:
    """Run ``carry = body(carry, block_i)`` over layer-stacked host-resident
    blocks, streaming layers ahead of compute (the `AlignDevicesHook`
    pre-forward staging pattern, reference `hooks.py:329`, without forward
    monkey-patching).

    Staging rides the shared transfer engine (`parallel/transfer.py`):
    while layer *i* computes, layers *i+1..i+depth* are already in flight
    — chunked ``device_put`` issued from the engine's worker pool, with
    ``prefetch_depth`` (default ``ATX_TRANSFER_PREFETCH``, >= 2)
    double-buffered device slots. Memmap-backed leaves (disk offload) have
    their disk reads staged chunk-by-chunk through the same path, so the
    read, the cast, and the H2D copy of layer *i+1* all overlap layer
    *i*'s compute.

    ``host_blocks`` leaves are numpy arrays (or memmaps) with a leading
    layer axis. ``sharding`` optionally places staged layers (a pytree of
    NamedShardings matching one layer, or a single sharding applied to
    every leaf).
    """
    from .parallel.transfer import get_transfer_engine

    eng = engine if engine is not None else get_transfer_engine()
    n_layers = jax.tree.leaves(host_blocks)[0].shape[0]

    def stage(i: int) -> Any:
        layer = jax.tree.map(lambda x: x[i], host_blocks)
        return eng.put_tree(layer, shardings=sharding, dtype=dtype)

    for block in eng.prefetch(n_layers, stage, depth=prefetch_depth):
        carry = body(carry, block)
    return carry

"""Span tracer: wall-clock host spans as Chrome-trace JSONL + XPlane bridge.

``span("name")`` times a host-side block. When a span log is open
(:func:`start_trace_log`, or ``ATX_TRACE_DIR`` at first use) each span is
appended to ``spans_<proc>.jsonl`` as one Chrome-trace complete event
(``"ph": "X"``, microsecond ``ts``/``dur``) per line — load with
:func:`chrome_trace` (wraps the lines into the JSON array Perfetto /
chrome://tracing expect). Nesting is tracked with a ``contextvars`` stack so
events carry their parent span and spans in worker threads don't corrupt
each other.

When a `utils/profiler.py` XPlane capture is active, every span also enters
a ``jax.profiler.TraceAnnotation`` so the same names line up against the
device timeline in TensorBoard; ``step_span`` uses ``StepTraceAnnotation``
so step-time views group ops by step number.

Hot-path safety: with no span log open and no profiler trace running,
``span()`` yields immediately — one contextvar read, no timestamps, no I/O.
"""

from __future__ import annotations

import atexit
import contextlib
import contextvars
import json
import os
import threading
import time
from typing import Any, Iterator

from ..utils import profiler as _profiler

__all__ = [
    "span",
    "step_span",
    "start_trace_log",
    "stop_trace_log",
    "trace_log_path",
    "spans_enabled",
    "chrome_trace",
]

_SPAN_STACK: contextvars.ContextVar[tuple[str, ...]] = contextvars.ContextVar(
    "atx_span_stack", default=()
)

_writer_lock = threading.Lock()
_writer: "_JsonlWriter | None" = None
_env_checked = False


class _JsonlWriter:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a", buffering=1)
        self._lock = threading.Lock()

    def write(self, event: dict[str, Any]) -> None:
        line = json.dumps(event, separators=(",", ":"), default=str)
        with self._lock:
            if not self._f.closed:
                self._f.write(line + "\n")

    def close(self) -> None:
        # Flush + fsync before closing: the atexit/SystemExit path (exit-75
        # preemption) must leave every event durably on disk, not in a
        # page-cache line a subsequent kill can truncate.
        with self._lock:
            if self._f.closed:
                return
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except OSError:
                pass
            self._f.close()


def _process_index() -> int:
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


_atexit_registered = False


def _close_writer_at_exit() -> None:
    # Runs on interpreter shutdown, including ``SystemExit`` paths (exit-75
    # preemption, a drain's sys.exit) and uncaught exceptions — the cases
    # that used to truncate the last events. ``os._exit`` paths (kill-137,
    # the watchdog's default abort) bypass atexit by design; the watchdog
    # dumps its postmortem bundle explicitly before aborting instead.
    writer = _writer
    if writer is not None:
        writer.close()


def start_trace_log(path: str | None = None) -> str:
    """Open the span JSONL log. Default path:
    ``$ATX_TRACE_DIR/spans_<proc>.jsonl``."""
    global _writer, _env_checked, _atexit_registered
    with _writer_lock:
        if _writer is not None:
            return _writer.path
        if path is None:
            base = os.environ.get("ATX_TRACE_DIR", "atx_trace")
            path = os.path.join(base, f"spans_{_process_index()}.jsonl")
        _writer = _JsonlWriter(path)
        _env_checked = True
        if not _atexit_registered:
            atexit.register(_close_writer_at_exit)
            _atexit_registered = True
        return path


def stop_trace_log() -> None:
    global _writer, _env_checked
    with _writer_lock:
        if _writer is not None:
            _writer.close()
            _writer = None
        _env_checked = True


def trace_log_path() -> str | None:
    writer = _writer
    return writer.path if writer is not None else None


def _maybe_open_from_env() -> "_JsonlWriter | None":
    # ATX_TRACE_DIR opt-in checked once, on the first span after import.
    global _env_checked
    if _env_checked:
        return _writer
    with _writer_lock:
        _env_checked = True
    if os.environ.get("ATX_TRACE_DIR"):
        start_trace_log()
    return _writer


def spans_enabled() -> bool:
    """True when spans do real work (log open or XPlane capture running)."""
    writer = _writer if _env_checked else _maybe_open_from_env()
    return writer is not None or _profiler.trace_active()


@contextlib.contextmanager
def span(name: str, **attrs: Any) -> Iterator[None]:
    """Time a host-side block; near-zero cost while tracing is off."""
    writer = _writer if _env_checked else _maybe_open_from_env()
    xplane = _profiler.trace_active()
    if writer is None and not xplane:
        yield
        return
    stack = _SPAN_STACK.get()
    token = _SPAN_STACK.set(stack + (name,))
    cm = _profiler.annotate(name) if xplane else contextlib.nullcontext()
    start = time.perf_counter()
    wall_us = time.time() * 1e6
    try:
        with cm:
            yield
    finally:
        dur_us = (time.perf_counter() - start) * 1e6
        _SPAN_STACK.reset(token)
        if writer is not None:
            event: dict[str, Any] = {
                "name": name,
                "ph": "X",
                "ts": wall_us,
                "dur": dur_us,
                "pid": _process_index(),
                "tid": threading.get_ident() & 0xFFFFFFFF,
            }
            args = dict(attrs)
            if stack:
                args["parent"] = stack[-1]
            if args:
                event["args"] = args
            writer.write(event)


@contextlib.contextmanager
def step_span(step: int, name: str = "train") -> Iterator[None]:
    """Span for one training step, bridged to ``StepTraceAnnotation`` when an
    XPlane capture is running so TensorBoard numbers the steps."""
    with _profiler.maybe_step_annotation(step, name=name):
        with span(f"{name}_step", step=int(step)):
            yield


def current_span() -> str | None:
    stack = _SPAN_STACK.get()
    return stack[-1] if stack else None


def mirror_flight_event(
    entry: dict[str, Any], t0_perf: float, t0_wall: float
) -> None:
    """Write a flight-recorder span record (`telemetry/flight.py`) into the
    Chrome-trace JSONL log when one is open, mapping its monotonic
    perf_counter times onto the wall clock via the recorder's anchors, so a
    live ``ATX_TRACE_DIR`` carries the request-scoped spans alongside the
    block spans and `atx trace` can read either surface."""
    writer = _writer if _env_checked else _maybe_open_from_env()
    if writer is None:
        return
    args: dict[str, Any] = {"rid": entry.get("rid", -1)}
    args.update(entry.get("attrs", ()))
    writer.write(
        {
            "name": entry["name"],
            "ph": "X",
            "ts": (t0_wall + (entry["t0"] - t0_perf)) * 1e6,
            "dur": max(0.0, entry["t1"] - entry["t0"]) * 1e6,
            "pid": _process_index(),
            "tid": threading.get_ident() & 0xFFFFFFFF,
            "args": args,
        }
    )


def chrome_trace(jsonl_path: str) -> dict[str, Any]:
    """Load a span JSONL file as a Chrome-trace/Perfetto ``traceEvents``
    object (``json.dump`` the result to get a loadable ``.json`` trace)."""
    events = []
    with open(jsonl_path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return {"traceEvents": events, "displayTimeUnit": "ms"}

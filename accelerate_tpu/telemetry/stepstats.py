"""Step-time breakdown for the training loop.

JAX dispatch is asynchronous: a jitted step call returns as soon as the work
is enqueued, so host-side wall clocks around the call measure the *dispatch
gap* (host Python + enqueue cost), not device compute. :class:`StepStats`
splits the two from host timestamps alone:

- ``train_step_ms``: EMA of the interval between consecutive step entries —
  the true sustained step time once the pipeline is saturated (the device
  backpressures dispatch through the stream).
- ``train_dispatch_gap_ms``: EMA of the jitted-call wall time — host time
  the step spends NOT overlapping device work. When this approaches
  ``train_step_ms`` the loop is host-bound.
- ``train_device_ms``: on sampled steps only (``ATX_METRICS_SAMPLE_EVERY``,
  default 0 = never), a ``block_until_ready`` on the step outputs measures
  dispatch-begin -> outputs-ready — an upper bound on device compute
  including queued prior work. With sampling off there are ZERO device
  syncs: every other field is pure ``time.perf_counter`` + shape math.
- ``train_tokens_per_sec`` / ``train_mfu``: EMA'd throughput from the batch
  leaf shapes and achieved model-FLOPs utilisation via
  `utils/profiler.estimate_step_flops` (XLA's own cost analysis of the
  compiled step) against the chip's peak — the ROADMAP's "where does the
  step wall clock go" axis.
- ``train_compiles``: jit cache-size deltas — recompiles on the hot path
  (the runtime twin of the ATX302 shape-drift lint).

Blocking on already-computed outputs never changes their values, so losses
are bit-identical with stats on or off; instrumentation never touches rng,
step math, or dispatch order.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..utils.environment import get_int_from_env
from .registry import REGISTRY, Registry

__all__ = ["StepStats", "peak_device_flops", "tokens_in_batch"]

# Per-chip bf16 peak FLOP/s by device_kind substring (public TPU specs).
_PEAK_FLOPS = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# Indirection so tests can count sync calls (zero-sync assertion).
_block_until_ready = jax.block_until_ready


def peak_device_flops(device: Any | None = None) -> float | None:
    """Peak bf16 FLOP/s of one chip, or None off-TPU (MFU reads 0 there)."""
    if device is None:
        try:
            device = jax.devices()[0]
        except Exception:
            return None
    kind = str(getattr(device, "device_kind", "")).lower()
    for key, peak in _PEAK_FLOPS:
        if key in kind:
            return peak
    return None


def tokens_in_batch(batch: Any) -> int:
    """Tokens per step from leaf *shapes* only (no device reads): the widest
    integer leaf's batch*seq product, falling back to the widest leaf."""
    best = 0
    fallback = 0
    for leaf in jax.tree_util.tree_leaves(batch):
        shape = getattr(leaf, "shape", None)
        if not shape:
            continue
        n = int(shape[0]) * (int(shape[1]) if len(shape) > 1 else 1)
        fallback = max(fallback, n)
        dtype = getattr(leaf, "dtype", None)
        try:
            if dtype is not None and jnp.issubdtype(dtype, jnp.integer):
                best = max(best, n)
        except TypeError:
            continue
    return best or fallback


class StepStats:
    """Per-train-step telemetry publisher. One instance per built train step
    (`Accelerator.make_train_step`); gauges land on the shared registry so
    the `/metrics` endpoint, tracker glue, and bench read the same fields."""

    def __init__(
        self,
        *,
        registry: Registry | None = None,
        sample_every: int | None = None,
        ema_alpha: float | None = None,
        flops_fn: Callable[[], float | None] | None = None,
        peak_flops_total: float | None = None,
    ):
        reg = registry if registry is not None else REGISTRY
        if sample_every is None:
            sample_every = get_int_from_env(("ATX_METRICS_SAMPLE_EVERY",), 0)
        self.sample_every = max(0, int(sample_every))
        if ema_alpha is None:
            ema_alpha = float(os.environ.get("ATX_METRICS_EMA", "0.2"))
        self.ema_alpha = min(1.0, max(0.0, ema_alpha))
        self._flops_fn = flops_fn
        self._flops_per_step: float | None = None
        self._flops_resolved = flops_fn is None
        self.peak_flops_total = peak_flops_total

        self._g_step = reg.gauge(
            "train_step_ms", "EMA interval between step entries (ms)")
        self._g_gap = reg.gauge(
            "train_dispatch_gap_ms", "EMA wall time of the jitted dispatch (ms)")
        self._g_device = reg.gauge(
            "train_device_ms",
            "Sampled dispatch-begin to outputs-ready wall (ms)")
        self._g_tps = reg.gauge(
            "train_tokens_per_sec", "EMA training throughput", aggregate="sum")
        self._g_mfu = reg.gauge(
            "train_mfu", "Achieved model-FLOPs utilisation (0 when peak unknown)")
        self._c_steps = reg.counter("train_steps", "Steps dispatched")
        self._c_compiles = reg.counter(
            "train_compiles", "Jit cache growth events (ATX302 runtime twin)")

        self._emas: dict[str, float] = {}
        self._t_entry: float | None = None
        self._last_entry: float | None = None
        self._last_interval_s: float | None = None
        self._last_cache_size = 0
        self._steps = 0
        self._compiles = 0
        self._sampled_device_ms: float | None = None

    # -- hot-path hooks ----------------------------------------------------

    def on_entry(self, tokens_per_step: int | None = None) -> None:
        """Call at step entry, before dispatch. Host clocks only."""
        now = time.perf_counter()
        if self._last_entry is not None:
            interval_s = now - self._last_entry
            if interval_s > 0:
                self._last_interval_s = interval_s
                step_ms = self._ema("step_ms", interval_s * 1e3)
                self._g_step.set(step_ms)
                if tokens_per_step:
                    tps = self._ema("tps", tokens_per_step / interval_s)
                    self._g_tps.set(tps)
                self._update_mfu(interval_s)
        self._last_entry = now
        self._t_entry = now

    def on_dispatched(self, outputs: Any = None, cache_size: int | None = None) -> None:
        """Call right after the jitted step returns (work enqueued)."""
        now = time.perf_counter()
        self._steps += 1
        self._c_steps.inc()
        if self._t_entry is not None:
            gap_ms = self._ema("gap_ms", (now - self._t_entry) * 1e3)
            self._g_gap.set(gap_ms)
        if cache_size is not None and cache_size > self._last_cache_size:
            self._compiles += cache_size - self._last_cache_size
            self._c_compiles.inc(cache_size - self._last_cache_size)
            self._last_cache_size = cache_size
        if (
            self.sample_every
            and outputs is not None
            and self._steps % self.sample_every == 0
        ):
            _block_until_ready(outputs)
            device_ms = (time.perf_counter() - (self._t_entry or now)) * 1e3
            self._sampled_device_ms = self._ema("device_ms", device_ms)
            self._g_device.set(self._sampled_device_ms)

    # -- internals ---------------------------------------------------------

    def _ema(self, key: str, value: float) -> float:
        prev = self._emas.get(key)
        out = value if prev is None else prev + self.ema_alpha * (value - prev)
        self._emas[key] = out
        return out

    def _update_mfu(self, interval_s: float) -> None:
        if not self.peak_flops_total:
            # Unknown chip peak (e.g. CPU runs): report 0 and never call
            # flops_fn — resolving it may cost an AOT compile.
            self._g_mfu.set(0.0)
            self._emas.setdefault("mfu", 0.0)
            return
        if not self._flops_resolved:
            self._flops_resolved = True
            try:
                self._flops_per_step = self._flops_fn()  # type: ignore[misc]
            except Exception:
                self._flops_per_step = None
        if self._flops_per_step:
            mfu = self._flops_per_step / (interval_s * self.peak_flops_total)
            self._g_mfu.set(self._ema("mfu", mfu))
        else:
            self._g_mfu.set(0.0)
            self._emas.setdefault("mfu", 0.0)

    # -- read side ---------------------------------------------------------

    @property
    def steps(self) -> int:
        return self._steps

    @property
    def compiles(self) -> int:
        """Compiles seen by THIS train step (the registry counter is the
        process-wide total across all built steps)."""
        return self._compiles

    def latest(self) -> dict[str, float]:
        """Flat float dict for the tracker glue (`Accelerator.log`) and
        bench lines — same field names as the registry gauges."""
        out = {
            "train_step_ms": self._emas.get("step_ms", 0.0),
            "train_dispatch_gap_ms": self._emas.get("gap_ms", 0.0),
            "train_tokens_per_sec": self._emas.get("tps", 0.0),
            "train_mfu": self._emas.get("mfu", 0.0),
            "train_compiles": float(self._compiles),
        }
        if self._sampled_device_ms is not None:
            out["train_device_ms"] = self._sampled_device_ms
        return out

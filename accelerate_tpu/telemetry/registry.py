"""Metrics registry: counters, gauges, and fixed-bucket histograms.

Dependency-free (stdlib only, no jax import) and hot-path-safe: an update is
a dict lookup plus a float add under a per-metric lock — no device access,
no collectives, no allocation after the first observation of a label set.

Cross-host aggregation follows the shared-surface pattern from the elastic
controller (docs/fault_tolerance.md): each process periodically writes an
atomic JSON snapshot (``metrics_<proc>.json``) into a shared directory and
process 0 merges them on read — counters and histogram buckets sum, gauges
reduce per-metric (``max`` by default). No collectives anywhere; the merge
is plain file I/O, so it is safe to run from the host loop of a pod
(pinned by ``atx lint telemetry --multihost 2``).

Prometheus text exposition (rendered by :meth:`Registry.render_prometheus`,
served by `telemetry.export.MetricsServer`) follows the 0.0.4 format:
``# HELP`` / ``# TYPE`` headers, histogram ``_bucket{le=...}`` series with a
cumulative ``+Inf`` bucket plus ``_sum`` / ``_count``.
"""

from __future__ import annotations

import bisect
import json
import os
import re
import threading
import time
from typing import Any, Iterable, Iterator, Mapping, Sequence

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "DEFAULT_MS_BUCKETS",
    "DEFAULT_BYTES_BUCKETS",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "render_prometheus",
    "write_snapshot",
    "read_snapshots",
    "merge_snapshots",
    "aggregate_snapshots",
    "render_snapshot_prometheus",
]

# Latency buckets (milliseconds): sub-ms dispatch gaps up to 30 s tails.
DEFAULT_MS_BUCKETS: tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0,
)

# Transfer-size buckets (bytes): 1 KiB chunks up to multi-GiB checkpoints.
DEFAULT_BYTES_BUCKETS: tuple[float, ...] = (
    1024.0, 65536.0, 1048576.0, 16777216.0, 67108864.0,
    268435456.0, 1073741824.0, 4294967296.0,
)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize_name(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class MetricError(ValueError):
    """Registration/usage conflict: kind, label names, or bucket mismatch."""


class _Metric:
    """Base: one named metric holding a family of labelled series."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: Sequence[str] = ()):
        self.name = _sanitize_name(name)
        self.help = help
        self.label_names: tuple[str, ...] = tuple(labels)
        self._series: dict[tuple[str, ...], Any] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Mapping[str, Any]) -> tuple[str, ...]:
        if len(labels) != len(self.label_names) or any(
            n not in labels for n in self.label_names
        ):
            raise MetricError(
                f"metric {self.name!r} takes labels {self.label_names}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(str(labels[n]) for n in self.label_names)

    def series(self) -> list[tuple[dict[str, str], Any]]:
        with self._lock:
            return [
                (dict(zip(self.label_names, key)), self._copy_state(state))
                for key, state in sorted(self._series.items())
            ]

    def _copy_state(self, state: Any) -> Any:
        return state

    def clear(self) -> None:
        with self._lock:
            self._series.clear()


class Counter(_Metric):
    """Monotonic count. ``inc`` on the hot path; ``set_value`` exists only so
    registry-backed stats views (serving engine/router dicts) can mirror
    absolute assignments — it is not part of the exposition contract."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def set_value(self, value: float, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def value(self, **labels: Any) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))


class Gauge(_Metric):
    """Point-in-time value. ``aggregate`` names the cross-process reduction
    used by :func:`merge_snapshots`: ``max`` (default), ``min``, ``sum``,
    or ``mean``."""

    kind = "gauge"

    def __init__(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        aggregate: str = "max",
    ):
        super().__init__(name, help, labels)
        if aggregate not in ("max", "min", "sum", "mean"):
            raise MetricError(f"unknown gauge aggregate {aggregate!r}")
        self.aggregate = aggregate

    def set(self, value: float, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: Any) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._series.get(key, 0.0))


class Histogram(_Metric):
    """Fixed-bucket histogram. State per series: per-bucket counts (last
    entry is the implicit ``+Inf`` overflow), running sum, and count.
    Quantiles are estimated by linear interpolation inside the bucket that
    holds the target rank — the same math a PromQL ``histogram_quantile``
    would do on the exported series."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
    ):
        super().__init__(name, help, labels)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise MetricError(f"histogram {self.name!r} needs >= 1 bucket bound")
        self.buckets: tuple[float, ...] = bounds

    def _new_state(self) -> list[Any]:
        return [[0] * (len(self.buckets) + 1), 0.0, 0]

    def _copy_state(self, state: list[Any]) -> list[Any]:
        return [list(state[0]), state[1], state[2]]

    def observe(self, value: float, **labels: Any) -> None:
        key = self._key(labels)
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                state = self._series[key] = self._new_state()
            state[0][idx] += 1
            state[1] += value
            state[2] += 1

    def count(self, **labels: Any) -> int:
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            return 0 if state is None else int(state[2])

    def sum(self, **labels: Any) -> float:
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            return 0.0 if state is None else float(state[1])

    def quantile(self, q: float, **labels: Any) -> float | None:
        """Estimated q-quantile (q in [0, 1]); None when the series is empty."""
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            if state is None or state[2] == 0:
                return None
            counts, _, total = list(state[0]), state[1], state[2]
        return _bucket_quantile(self.buckets, counts, total, q)

    def mean(self, **labels: Any) -> float | None:
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            if state is None or state[2] == 0:
                return None
            return float(state[1]) / float(state[2])


def _bucket_quantile(
    bounds: Sequence[float], counts: Sequence[int], total: int, q: float
) -> float:
    rank = max(0.0, min(1.0, q)) * total
    cum = 0.0
    lo = 0.0
    for i, ub in enumerate(bounds):
        c = counts[i]
        if c and cum + c >= rank:
            frac = (rank - cum) / c
            return lo + (ub - lo) * frac
        cum += c
        lo = ub
    # Rank fell in the +Inf overflow bucket: clamp to the top finite bound.
    return float(bounds[-1])


class Registry:
    """Named collection of metrics. ``counter``/``gauge``/``histogram`` are
    get-or-create and raise :class:`MetricError` on a kind/label/bucket
    conflict so two call sites cannot silently fork one name."""

    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls: type, name: str, kwargs: dict) -> Any:
        name = _sanitize_name(name)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls:
                    raise MetricError(
                        f"metric {name!r} already registered as {existing.kind}"
                    )
                if tuple(kwargs.get("labels", ())) != existing.label_names:
                    raise MetricError(
                        f"metric {name!r} label mismatch: "
                        f"{existing.label_names} vs {tuple(kwargs.get('labels', ()))}"
                    )
                if cls is Histogram:
                    want = tuple(sorted(float(b) for b in kwargs.get(
                        "buckets", DEFAULT_MS_BUCKETS)))
                    if want != existing.buckets:
                        raise MetricError(f"metric {name!r} bucket mismatch")
                return existing
            metric = cls(name, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, {"help": help, "labels": labels})

    def gauge(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        aggregate: str = "max",
    ) -> Gauge:
        return self._get_or_create(
            Gauge, name, {"help": help, "labels": labels, "aggregate": aggregate}
        )

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, {"help": help, "labels": labels, "buckets": buckets}
        )

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(_sanitize_name(name))

    def metrics(self) -> list[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def clear(self) -> None:
        """Drop every metric (test isolation; never called at runtime)."""
        with self._lock:
            self._metrics.clear()

    # -- snapshots ---------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """JSON-serialisable dump of every series (the `telemetry.snapshot()`
        API and the cross-process exchange format)."""
        out: list[dict[str, Any]] = []
        for metric in self.metrics():
            entry: dict[str, Any] = {
                "name": metric.name,
                "kind": metric.kind,
                "help": metric.help,
                "label_names": list(metric.label_names),
                "series": [],
            }
            if isinstance(metric, Gauge):
                entry["aggregate"] = metric.aggregate
            if isinstance(metric, Histogram):
                entry["buckets"] = list(metric.buckets)
                for labels, state in metric.series():
                    entry["series"].append(
                        {
                            "labels": labels,
                            "bucket_counts": list(state[0]),
                            "sum": state[1],
                            "count": state[2],
                        }
                    )
            else:
                for labels, value in metric.series():
                    entry["series"].append({"labels": labels, "value": value})
            out.append(entry)
        return {"version": 1, "time_unix": time.time(), "metrics": out}

    def scalars(self, prefix: str = "") -> dict[str, float]:
        """Flat name -> value view of counters/gauges (labelled series sum),
        for bench lines and tracker glue."""
        flat: dict[str, float] = {}
        for metric in self.metrics():
            if not metric.name.startswith(prefix):
                continue
            if isinstance(metric, Histogram):
                continue
            total = 0.0
            seen = False
            for _, value in metric.series():
                total += float(value)
                seen = True
            if seen:
                flat[metric.name] = total
        return flat

    def render_prometheus(self) -> str:
        return render_snapshot_prometheus(self.snapshot())


# -- Prometheus text rendering (works on live registries and merged snapshots)


def _format_value(value: float) -> str:
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _render_labels(labels: Mapping[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_snapshot_prometheus(snap: Mapping[str, Any]) -> str:
    """Render a snapshot dict (live or merged) as Prometheus text 0.0.4."""
    lines: list[str] = []
    for entry in snap.get("metrics", []):
        name = _sanitize_name(entry["name"])
        kind = entry["kind"]
        if entry.get("help"):
            help_text = str(entry["help"]).replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == "histogram":
            bounds = entry.get("buckets", [])
            for series in entry["series"]:
                labels = series["labels"]
                cum = 0
                for bound, c in zip(bounds, series["bucket_counts"]):
                    cum += c
                    extra = 'le="%s"' % _format_value(float(bound))
                    lines.append(f"{name}_bucket{_render_labels(labels, extra)} {cum}")
                cum += series["bucket_counts"][len(bounds)]
                inf = 'le="+Inf"'
                lines.append(f"{name}_bucket{_render_labels(labels, inf)} {cum}")
                lines.append(
                    f"{name}_sum{_render_labels(labels)} "
                    f"{_format_value(series['sum'])}"
                )
                lines.append(f"{name}_count{_render_labels(labels)} {series['count']}")
        else:
            for series in entry["series"]:
                lines.append(
                    f"{name}{_render_labels(series['labels'])} "
                    f"{_format_value(series['value'])}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


# -- cross-process snapshot exchange (shared-surface pattern, no collectives)


def write_snapshot(
    directory: str,
    *,
    registry: "Registry | None" = None,
    process_index: int = 0,
) -> str:
    """Atomically write this process's snapshot as ``metrics_<proc>.json``."""
    reg = registry if registry is not None else REGISTRY
    os.makedirs(directory, exist_ok=True)
    snap = reg.snapshot()
    snap["process_index"] = int(process_index)
    path = os.path.join(directory, f"metrics_{int(process_index)}.json")
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(snap, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_snapshots(directory: str) -> list[dict[str, Any]]:
    snaps: list[dict[str, Any]] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return snaps
    for fname in names:
        if not (fname.startswith("metrics_") and fname.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, fname)) as f:
                snaps.append(json.load(f))
        except (OSError, ValueError):
            continue  # torn write loses one interval, never the merge
    return snaps


def merge_snapshots(snaps: Iterable[Mapping[str, Any]]) -> dict[str, Any]:
    """Proc-0 merge: counters and histogram buckets sum across processes;
    gauges reduce per their declared aggregate (max/min/sum/mean)."""
    merged: dict[str, dict[str, Any]] = {}
    gauge_samples: dict[tuple[str, tuple], list[float]] = {}
    n_procs = 0
    for snap in snaps:
        n_procs += 1
        for entry in snap.get("metrics", []):
            name = entry["name"]
            slot = merged.setdefault(
                name,
                {
                    "name": name,
                    "kind": entry["kind"],
                    "help": entry.get("help", ""),
                    "label_names": list(entry.get("label_names", [])),
                    "series": {},
                },
            )
            if entry["kind"] == "gauge":
                slot["aggregate"] = entry.get("aggregate", "max")
            if entry["kind"] == "histogram":
                slot.setdefault("buckets", list(entry.get("buckets", [])))
            for series in entry.get("series", []):
                key = tuple(sorted(series["labels"].items()))
                if entry["kind"] == "histogram":
                    state = slot["series"].get(key)
                    if state is None:
                        slot["series"][key] = {
                            "labels": dict(series["labels"]),
                            "bucket_counts": list(series["bucket_counts"]),
                            "sum": series["sum"],
                            "count": series["count"],
                        }
                    else:
                        state["bucket_counts"] = [
                            a + b
                            for a, b in zip(
                                state["bucket_counts"], series["bucket_counts"]
                            )
                        ]
                        state["sum"] += series["sum"]
                        state["count"] += series["count"]
                elif entry["kind"] == "gauge":
                    gauge_samples.setdefault((name, key), []).append(
                        float(series["value"])
                    )
                    slot["series"][key] = {"labels": dict(series["labels"])}
                else:
                    state = slot["series"].get(key)
                    if state is None:
                        slot["series"][key] = {
                            "labels": dict(series["labels"]),
                            "value": float(series["value"]),
                        }
                    else:
                        state["value"] += float(series["value"])
    for (name, key), values in gauge_samples.items():
        agg = merged[name].get("aggregate", "max")
        if agg == "max":
            value = max(values)
        elif agg == "min":
            value = min(values)
        elif agg == "sum":
            value = sum(values)
        else:
            value = sum(values) / len(values)
        merged[name]["series"][key]["value"] = value
    out_metrics = []
    for name in sorted(merged):
        entry = merged[name]
        entry["series"] = [entry["series"][k] for k in sorted(entry["series"])]
        out_metrics.append(entry)
    return {"version": 1, "processes": n_procs, "metrics": out_metrics}


def aggregate_snapshots(directory: str) -> dict[str, Any]:
    """Read + merge every per-process snapshot under ``directory``."""
    return merge_snapshots(read_snapshots(directory))


# -- module-level default registry ----------------------------------------

REGISTRY = Registry()


def counter(name: str, help: str = "", labels: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help, labels)


def gauge(
    name: str, help: str = "", labels: Sequence[str] = (), aggregate: str = "max"
) -> Gauge:
    return REGISTRY.gauge(name, help, labels, aggregate)


def histogram(
    name: str,
    help: str = "",
    labels: Sequence[str] = (),
    buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
) -> Histogram:
    return REGISTRY.histogram(name, help, labels, buckets)


def snapshot() -> dict[str, Any]:
    return REGISTRY.snapshot()


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()

"""Unified runtime telemetry (docs/observability.md).

Dependency-free, hot-path-safe metrics + tracing for training and serving:

- `telemetry.registry` — counters / gauges / fixed-bucket histograms with
  label sets, Prometheus text rendering, and cross-host aggregation via
  per-process JSON snapshots merged by proc 0 (no collectives).
- `telemetry.spans` — wall-clock host spans as Chrome-trace JSONL, bridged
  into XPlane via ``jax.profiler.TraceAnnotation`` when a
  `utils/profiler.profile()` capture is running.
- `telemetry.stepstats` — per-step dispatch-gap vs device-compute split,
  EMA tokens/sec + achieved MFU, and a recompile counter, wired into the
  `Accelerator` step helper behind ``ATX_METRICS`` (default on; zero device
  syncs unless ``ATX_METRICS_SAMPLE_EVERY`` turns the sampler on).
- `telemetry.export` — stdlib-only Prometheus ``/metrics`` HTTP endpoint
  (`atx serve --metrics-port`).
- `telemetry.views.StatsView` — the registry-backed dict view behind the
  serving engine/router/prefix-cache ``stats`` so the old snapshot shapes
  and the endpoint read one source of truth.

- `telemetry.flight` — the request-scoped tracing layer: a bounded
  per-process ring of span records (the black-box *flight recorder*) that
  the serving path tags with request ids behind ``ATX_TRACE_REQUESTS=1``,
  plus `dump_postmortem`, which abnormal-exit hooks (watchdog 114, exit-75,
  quarantine, chaos violations, the non-finite guard) use to drop a
  last-N-spans + metrics + thread-stacks bundle into ``ATX_POSTMORTEM_DIR``
  (rendered by ``atx trace``).

Knobs: ``ATX_METRICS`` (default 1), ``ATX_METRICS_SAMPLE_EVERY`` (default 0),
``ATX_METRICS_LOG_EVERY`` (default 0), ``ATX_METRICS_DIR`` (shared snapshot
dir), ``ATX_METRICS_EMA`` (default 0.2), ``ATX_TRACE_DIR`` (span JSONL),
``ATX_TRACE_REQUESTS`` (default 0), ``ATX_FLIGHT_RECORDER_SPANS`` (default
4096), ``ATX_POSTMORTEM_DIR`` (unset = no bundles).
"""

from __future__ import annotations

from ..utils.environment import parse_flag_from_env
from . import export, flight, registry, spans, stepstats, views
from .export import MetricsServer
from .flight import (
    FlightRecorder,
    dump_postmortem,
    read_bundle,
    record_span,
    trace_requests_enabled,
)
from .registry import (
    DEFAULT_BYTES_BUCKETS,
    DEFAULT_MS_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricError,
    Registry,
    aggregate_snapshots,
    counter,
    gauge,
    histogram,
    merge_snapshots,
    read_snapshots,
    render_prometheus,
    render_snapshot_prometheus,
    snapshot,
    write_snapshot,
)
from .spans import chrome_trace, span, spans_enabled, start_trace_log, step_span, stop_trace_log
from .stepstats import StepStats, peak_device_flops, tokens_in_batch
from .views import StatsView

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricError",
    "MetricsServer",
    "Registry",
    "REGISTRY",
    "StatsView",
    "StepStats",
    "DEFAULT_BYTES_BUCKETS",
    "DEFAULT_MS_BUCKETS",
    "FlightRecorder",
    "aggregate_snapshots",
    "chrome_trace",
    "counter",
    "dump_postmortem",
    "gauge",
    "histogram",
    "merge_snapshots",
    "metrics_enabled",
    "peak_device_flops",
    "read_bundle",
    "read_snapshots",
    "record_span",
    "trace_requests_enabled",
    "render_prometheus",
    "render_snapshot_prometheus",
    "snapshot",
    "span",
    "spans_enabled",
    "start_trace_log",
    "step_span",
    "stop_trace_log",
    "tokens_in_batch",
    "write_snapshot",
    "export",
    "flight",
    "registry",
    "spans",
    "stepstats",
    "views",
]


def metrics_enabled() -> bool:
    """The ``ATX_METRICS`` master switch (default ON). Gates the training
    step-stats hooks and span emission; registry counters themselves always
    work — they ARE the serving stats."""
    return parse_flag_from_env("ATX_METRICS", True)

"""Prometheus `/metrics` HTTP endpoint (stdlib ``http.server`` only).

One daemon thread serves three routes off the shared registry:

- ``GET /metrics`` — Prometheus text exposition 0.0.4. When constructed
  with ``snapshot_dir`` (a shared metrics directory, see
  `registry.write_snapshot`), ``/metrics?fleet=1`` serves the proc-0 merge
  of every per-process snapshot instead of the local registry — the fleet
  view for multi-host runs.
- ``GET /metrics.json`` — the raw `telemetry.snapshot()` dict.
- ``GET /healthz`` — liveness probe.

Lifecycle: ``close()`` shuts the listener down and joins the thread;
`atx serve --metrics-port` keeps the endpoint up until the router finishes
draining so a scraper sees the final counters (docs/observability.md).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
from urllib.parse import urlparse, parse_qs

from .registry import (
    REGISTRY,
    Registry,
    aggregate_snapshots,
    render_snapshot_prometheus,
)

__all__ = ["MetricsServer", "PROMETHEUS_CONTENT_TYPE"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Background `/metrics` endpoint over a registry.

    ``port=0`` binds an ephemeral port (``.port`` reports the real one —
    the tests and the smoke lane use this to avoid collisions).
    """

    def __init__(
        self,
        port: int = 0,
        *,
        host: str = "0.0.0.0",
        registry: Registry | None = None,
        snapshot_dir: str | None = None,
    ):
        self.registry = registry if registry is not None else REGISTRY
        self.snapshot_dir = snapshot_dir
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: Any) -> None:
                pass  # scrapes must not spam the serving logs

            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                parsed = urlparse(self.path)
                if parsed.path == "/metrics":
                    query = parse_qs(parsed.query)
                    fleet = query.get("fleet", ["0"])[0] not in ("0", "", "false")
                    body = server.render(fleet=fleet).encode()
                    self._reply(200, PROMETHEUS_CONTENT_TYPE, body)
                elif parsed.path == "/metrics.json":
                    body = json.dumps(server.registry.snapshot()).encode()
                    self._reply(200, "application/json", body)
                elif parsed.path == "/healthz":
                    self._reply(200, "text/plain", b"ok\n")
                else:
                    self._reply(404, "text/plain", b"not found\n")

            def _reply(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="atx-metrics",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        if host == "0.0.0.0":
            host = "127.0.0.1"
        return f"http://{host}:{self.port}/metrics"

    def render(self, *, fleet: bool = False) -> str:
        if fleet and self.snapshot_dir:
            merged = aggregate_snapshots(self.snapshot_dir)
            if merged.get("metrics"):
                return render_snapshot_prometheus(merged)
        return self.registry.render_prometheus()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

"""Dict-shaped views over registry metrics.

The serving engine, router, and prefix cache historically kept plain
``self.stats`` dicts; their snapshot methods (`Router.metrics()`,
`engine.prefix_metrics()`, the `atx serve` JSON line) are load-bearing for
bench compatibility. :class:`StatsView` keeps that dict shape — ``stats["x"] += 1``,
``dict(stats)``, key iteration — while storing every value in the registry,
so the `/metrics` endpoint and the old JSON summaries read the SAME numbers
(one source of truth, no second bookkeeping path).

Each view gets an instance label (e.g. ``engine="3"``): two routers in one
process never share a series, so per-instance snapshots stay exact while a
Prometheus ``sum by (__name__)`` still gives the fleet total.
"""

from __future__ import annotations

import itertools
import threading
from collections.abc import MutableMapping
from typing import Any, Iterator, Mapping, Sequence

from .registry import Counter, Gauge, REGISTRY, Registry

__all__ = ["StatsView"]

_instance_ids = itertools.count()
_id_lock = threading.Lock()


def _next_instance() -> str:
    with _id_lock:
        return str(next(_instance_ids))


class StatsView(MutableMapping):
    """Fixed-key mutable mapping backed by labelled registry metrics.

    ``keys`` become counters named ``{prefix}_{key}`` (keys listed in
    ``gauges`` become gauges — e.g. high-water marks that are assigned, not
    accumulated). The key set is fixed at construction: assigning an unknown
    key raises, so a typo cannot silently mint a new metric.
    """

    def __init__(
        self,
        prefix: str,
        keys: Sequence[str],
        *,
        label: str = "instance",
        instance: str | None = None,
        gauges: Sequence[str] = (),
        registry: Registry | None = None,
    ):
        reg = registry if registry is not None else REGISTRY
        self._label = label
        self._instance = _next_instance() if instance is None else str(instance)
        self._labels = {label: self._instance}
        self._metrics: dict[str, Counter | Gauge] = {}
        gauge_keys = set(gauges)
        for key in keys:
            name = f"{prefix}_{key}"
            if key in gauge_keys:
                metric: Counter | Gauge = reg.gauge(name, labels=(label,))
                metric.set(0.0, **self._labels)
            else:
                metric = reg.counter(name, labels=(label,))
                metric.set_value(0.0, **self._labels)
            self._metrics[key] = metric

    @property
    def instance(self) -> str:
        return self._instance

    @property
    def labels(self) -> dict[str, str]:
        return dict(self._labels)

    def __getitem__(self, key: str) -> int | float:
        value = self._metrics[key].value(**self._labels)
        return int(value) if float(value).is_integer() else value

    def __setitem__(self, key: str, value: Any) -> None:
        metric = self._metrics[key]  # unknown key -> KeyError, by design
        if isinstance(metric, Gauge):
            metric.set(float(value), **self._labels)
        else:
            metric.set_value(float(value), **self._labels)

    def __delitem__(self, key: str) -> None:
        raise TypeError("StatsView has a fixed key set")

    def __iter__(self) -> Iterator[str]:
        return iter(self._metrics)

    def __len__(self) -> int:
        return len(self._metrics)

    def __contains__(self, key: object) -> bool:
        return key in self._metrics

    def __repr__(self) -> str:
        return f"StatsView({dict(self)!r})"

    def update_from(self, other: Mapping[str, Any]) -> None:
        for key, value in other.items():
            self[key] = value

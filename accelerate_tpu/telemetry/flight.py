"""Black-box flight recorder: request-scoped spans + postmortem bundles.

PR 13's metrics registry answers "how is the fleet doing on average"; this
module answers the two questions aggregates cannot: *where did THIS request
spend its time* (Dapper-style request-scoped tracing — every `Request`'s
`rid` tags spans that flow router -> engine -> prefix-cache -> decode) and
*what was the process doing just before it died* (the flight recorder, an
aircraft-style black box: a bounded per-process ring buffer of the last N
span records that an abnormal-exit hook dumps as a postmortem bundle).

Hot-path contract (the serving engine's decode loop is the hardest case):

- recording is OFF unless ``ATX_TRACE_REQUESTS=1`` — the engine/router
  cache the flag at construction, so the disabled cost in the decode inner
  loop is zero;
- a record is one small dict appended into a preallocated ring under a
  lock — no device access, no syncs, no allocation beyond the span record
  itself (the same budget `telemetry/registry.py` promises);
- decode iterations are never recorded individually: residency is
  accumulated per slot (two float adds per resident slot per block) and
  emitted as ONE span at completion.

Postmortem bundles (``ATX_POSTMORTEM_DIR``): on watchdog 114, exit-75
preemption/drain, replica quarantine, a chaos violation, or the non-finite
guard tripping, `dump_postmortem` writes one JSON file with the last-N
spans, a metrics-registry snapshot, every Python thread's stack, the tail
of the multihost collective log (when a host-trace replay is active), and
the currently-armed fault points. Every collector is individually guarded:
a dying process must never die harder because its black box hiccupped.
`atx trace` (commands/trace.py) renders bundles and live trace dirs as
per-request waterfalls. See docs/observability.md.
"""

from __future__ import annotations

import io
import json
import os
import re
import sys
import threading
import time
import traceback
from typing import Any

__all__ = [
    "BUNDLE_VERSION",
    "FlightRecorder",
    "dump_postmortem",
    "postmortem_dir",
    "read_bundle",
    "record_span",
    "recorder",
    "reset_recorder",
    "trace_requests_enabled",
]

BUNDLE_VERSION = 1
DEFAULT_CAPACITY = 4096
# Collective-log tail length kept in a bundle (full logs can be huge).
_COLLECTIVE_TAIL = 50


def _process_index() -> int:
    from .spans import _process_index as spans_process_index

    return spans_process_index()


def trace_requests_enabled() -> bool:
    """Is request-scoped tracing on? Read from the environment every call
    (cheap: one dict lookup); the engine/router snapshot it at construction
    so the decode inner loop never even pays the lookup."""
    return os.environ.get("ATX_TRACE_REQUESTS", "").lower() in ("1", "true", "yes")


class FlightRecorder:
    """Bounded ring of span records. ``capacity`` defaults to
    ``ATX_FLIGHT_RECORDER_SPANS`` (4096). The buffer is preallocated; a
    `record` is one slot assignment + counter bump under the lock, so
    steady-state recording allocates nothing beyond the caller's record."""

    def __init__(self, capacity: int | None = None) -> None:
        if capacity is None:
            try:
                capacity = int(
                    os.environ.get("ATX_FLIGHT_RECORDER_SPANS", DEFAULT_CAPACITY)
                )
            except ValueError:
                capacity = DEFAULT_CAPACITY
        self.capacity = max(1, int(capacity))
        self._buf: list[Any] = [None] * self.capacity
        self._n = 0  # total records ever (wraparound keeps counting)
        self._lock = threading.Lock()
        # Anchors mapping perf_counter span times back to wall clock for
        # renderers (span records carry monotonic times only).
        self.t0_perf = time.perf_counter()
        self.t0_wall = time.time()

    @property
    def total(self) -> int:
        return self._n

    def record(self, entry: dict[str, Any]) -> None:
        with self._lock:
            self._buf[self._n % self.capacity] = entry
            self._n += 1

    def last(self, n: int | None = None) -> list[dict[str, Any]]:
        """The most recent ``n`` records (all retained when None), oldest
        first — the dump order of a postmortem bundle."""
        with self._lock:
            count = min(self._n, self.capacity)
            if n is not None:
                count = min(count, max(0, int(n)))
            start = self._n - count
            return [self._buf[i % self.capacity] for i in range(start, self._n)]

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._n = 0


_RECORDER: FlightRecorder | None = None
_RECORDER_LOCK = threading.Lock()


def recorder() -> FlightRecorder:
    """The per-process flight recorder (created on first use so the env
    capacity knob is read at arming time, not import time)."""
    global _RECORDER
    rec = _RECORDER
    if rec is None:
        with _RECORDER_LOCK:
            rec = _RECORDER
            if rec is None:
                rec = _RECORDER = FlightRecorder()
    return rec


def reset_recorder(capacity: int | None = None) -> FlightRecorder:
    """Replace the process recorder (test isolation; never called at
    runtime)."""
    global _RECORDER
    with _RECORDER_LOCK:
        _RECORDER = FlightRecorder(capacity)
    return _RECORDER


def record_span(
    name: str,
    *,
    rid: int = -1,
    t0: float | None = None,
    t1: float | None = None,
    **attrs: Any,
) -> None:
    """Record one span into the flight recorder (and mirror it into the
    Chrome-trace JSONL writer when `start_trace_log` armed one, so a live
    ``ATX_TRACE_DIR`` carries the request spans too).

    ``t0``/``t1`` are ``time.perf_counter()`` values; both default to "now"
    (an instant marker). ``attrs`` must be JSON-friendly scalars — cast
    numpy ints at the call site."""
    rec = recorder()
    now = time.perf_counter()
    if t1 is None:
        t1 = now
    if t0 is None:
        t0 = t1
    entry: dict[str, Any] = {"name": name, "rid": int(rid), "t0": t0, "t1": t1}
    if attrs:
        entry["attrs"] = attrs
    rec.record(entry)
    from . import spans as _spans

    _spans.mirror_flight_event(entry, rec.t0_perf, rec.t0_wall)


# ------------------------------------------------------- postmortem bundles


def postmortem_dir() -> str:
    return os.environ.get("ATX_POSTMORTEM_DIR", "")


def _thread_stacks() -> str:
    """Every Python thread's stack, formatted. Local (sys._current_frames)
    rather than borrowing resilience.watchdog.dump_all_stacks: the bundle
    writer must work even when the resilience package cannot import in a
    dying process."""
    buf = io.StringIO()
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in frames.items():
        buf.write(f"--- thread {names.get(ident, '?')} ({ident}) ---\n")
        buf.write("".join(traceback.format_stack(frame)))
    return buf.getvalue()


_DUMP_LOCK = threading.Lock()
_DUMP_SEQ = 0


def dump_postmortem(
    reason: str,
    directory: str | None = None,
    *,
    extra: Any = None,
) -> str | None:
    """Write a postmortem bundle and return its path (None when no
    directory is configured or the write failed — the caller is mid-crash
    and must not care). Each collector is independently fenced so one
    broken subsystem cannot cost the rest of the bundle."""
    directory = directory if directory is not None else postmortem_dir()
    if not directory:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
    except OSError:
        return None
    bundle: dict[str, Any] = {
        "version": BUNDLE_VERSION,
        "reason": str(reason),
        "time_unix": time.time(),
        "pid": os.getpid(),
        "process_index": _process_index(),
    }
    rec = _RECORDER
    if rec is not None:
        bundle["spans"] = rec.last()
        bundle["spans_total"] = rec.total
        bundle["t0_perf"] = rec.t0_perf
        bundle["t0_wall"] = rec.t0_wall
    else:
        bundle["spans"] = []
        bundle["spans_total"] = 0
    try:
        from . import registry as _registry

        bundle["metrics"] = _registry.snapshot()
    except Exception as e:
        bundle["metrics_error"] = repr(e)
    try:
        bundle["thread_stacks"] = _thread_stacks()
    except Exception as e:
        bundle["thread_stacks_error"] = repr(e)
    try:
        from ..analysis import host_trace

        hrec = host_trace._ACTIVE_RECORDER
        if hrec is not None:
            bundle["collective_log"] = [
                e.describe() for e in hrec.collective_events[-_COLLECTIVE_TAIL:]
            ]
    except Exception as e:
        bundle["collective_log_error"] = repr(e)
    try:
        from ..test_utils import faults

        bundle["fault_points"] = {
            "seen": sorted(str(p) for p in faults.active_points()),
            "env": {
                k: v for k, v in os.environ.items() if k.startswith("ATX_FAULT_")
            },
        }
    except Exception as e:
        bundle["fault_points_error"] = repr(e)
    if extra is not None:
        bundle["extra"] = extra
    global _DUMP_SEQ
    with _DUMP_LOCK:
        _DUMP_SEQ += 1
        seq = _DUMP_SEQ
    slug = re.sub(r"[^A-Za-z0-9_.-]+", "_", str(reason))[:64] or "bundle"
    path = os.path.join(directory, f"postmortem_{slug}_{os.getpid()}_{seq}.json")
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        return None
    return path


def read_bundle(path: str) -> dict[str, Any]:
    """Load + schema-check a postmortem bundle (the `atx trace` reader)."""
    with open(path) as f:
        bundle = json.load(f)
    if not isinstance(bundle, dict) or "spans" not in bundle:
        raise ValueError(f"{path} is not a postmortem bundle (no 'spans')")
    return bundle

"""Notebook / debug launchers.

Analog of the reference `launchers.py:40-301` (`notebook_launcher`,
`debug_launcher`). The TPU-native story is simpler than the reference's
xmp.spawn / torch.multiprocessing fork dance:

- On a TPU host, ONE process drives all local chips through SPMD — a
  notebook cell calls the training function directly; no spawning at all
  (the reference needs 8 processes per v3-8, `launchers.py:132-160`).
- Multi-process is only needed for CPU-simulation debugging of distributed
  code paths (`debug_launcher`) — children are forked with the same
  ``ATX_*`` env contract the CLI launcher uses, rendezvous over localhost.

The reference's "CUDA must not be initialized before forking" guard
(`launchers.py:169-177`) maps to "JAX backends must not be initialized":
a forked child inheriting live PJRT client state would hang or crash, so
`debug_launcher` refuses in that case with the same remedy (launch from a
fresh process / move jax work after the launcher call).
"""

from __future__ import annotations

import multiprocessing
import os
import sys
import time
from typing import Any, Callable, Sequence

from .utils.environment import patch_environment


def _jax_backends_initialized() -> bool:
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:  # pragma: no cover - private API move
        return False


def _child_entry(
    function: Callable, args: tuple, env: dict[str, str], index: int
) -> None:
    os.environ.update(env)
    os.environ["ATX_PROCESS_ID"] = str(index)
    function(*args)
    # Exit barrier: rank 0 hosts the coordination service — if it exits
    # while peers are still mid-run, their next RPC fails with a gRPC
    # "Socket closed" and a successful job reports as crashed.
    try:
        if "jax" in sys.modules:
            from jax._src import distributed

            if distributed.global_state.client is not None:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices("atx_launcher_exit")
    except Exception:  # pragma: no cover - best effort on teardown
        pass


def notebook_launcher(
    function: Callable,
    args: tuple = (),
    num_processes: int | None = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    host_devices: int | None = None,
) -> Any:
    """Launch training from a notebook (reference `notebook_launcher`,
    `launchers.py:40`).

    With ``num_processes`` unset or 1 (the TPU case: one process drives all
    chips via SPMD) the function is simply called in-process with the env
    contract applied. ``num_processes > 1`` forks CPU-simulation workers —
    the debugging path; see `debug_launcher`.
    """
    if num_processes is None or num_processes <= 1:
        with patch_environment(ATX_MIXED_PRECISION=mixed_precision):
            return function(*args)
    return _fork_workers(
        function,
        args,
        num_processes=num_processes,
        mixed_precision=mixed_precision,
        use_port=use_port,
        host_devices=host_devices or 1,
    )


def debug_launcher(function: Callable, args: tuple = (), num_processes: int = 2) -> None:
    """Run ``function`` under ``num_processes`` CPU processes to debug
    distributed code paths without hardware (reference `debug_launcher`,
    `launchers.py:268`)."""
    _fork_workers(function, args, num_processes=num_processes, mixed_precision="no")


def _fork_workers(
    function: Callable,
    args: tuple,
    *,
    num_processes: int,
    mixed_precision: str = "no",
    use_port: str = "29500",
    host_devices: int = 1,
) -> None:
    if _jax_backends_initialized():
        raise RuntimeError(
            "JAX backends are already initialized in this process; forked "
            "workers would inherit live PJRT state and deadlock. Restart the "
            "notebook kernel (or move all jax calls after the launcher), "
            "then call the launcher first."
        )
    env = {
        "ATX_NUM_PROCESSES": str(num_processes),
        "ATX_COORDINATOR_ADDRESS": f"127.0.0.1:{use_port}",
        "ATX_MIXED_PRECISION": mixed_precision,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={host_devices}"
        ).strip(),
    }
    ctx = multiprocessing.get_context("fork")
    procs = [
        ctx.Process(target=_child_entry, args=(function, args, env, i))
        for i in range(num_processes)
    ]
    for p in procs:
        p.start()
    # Poll rather than join sequentially: if one worker dies before the
    # rendezvous completes, the survivors block on the coordinator forever —
    # tear the job down like the CLI launcher does (commands/launch.py).
    failed: list[tuple[int, int]] = []
    tearing_down = False
    try:
        live = list(enumerate(procs))
        while live:
            for i, p in list(live):
                if p.is_alive():
                    continue
                live.remove((i, p))
                if p.exitcode != 0 and not tearing_down:
                    # Report only the original failure; survivors we
                    # SIGTERM below would otherwise show up as phantom
                    # "exited -15" failures.
                    failed.append((i, p.exitcode))
                    tearing_down = True
                    for _, q in live:
                        q.terminate()
            if live:
                time.sleep(0.1)
    finally:
        for p in procs:
            if p.is_alive():
                p.kill()
    if failed:
        raise RuntimeError(
            "Launched workers failed: "
            + ", ".join(f"process {i} exited {code}" for i, code in failed)
        )

"""Automatic prefix caching: radix-tree KV reuse across requests.

Real serving traffic is dominated by shared prompt prefixes — system
prompts, few-shot templates, multi-turn history — and the continuous-
batching engine re-prefilled every one of them from scratch. This module
is the RadixAttention idea (SGLang) reduced to the engine's slot-paged,
static-shape world:

- a HOST-side radix tree keyed on token ids records which prefixes have
  committed KV retained on device;
- the device storage is a dedicated **prefix pool**: a second family cache
  whose rows mirror the engine's slot rows (same (L, rows, max_len, ...)
  leaf layout), sized by a byte budget (``ATX_SERVE_PREFIX_CACHE_MIB``);
- every row-bearing tree node owns ONE pool row holding committed KV for
  positions ``[0, node.end)`` of its full root path. Rows are
  self-contained (a node never needs its ancestors' rows), so any
  unreferenced node can be LRU-evicted without touching its subtree —
  the price is that two cached prefixes sharing 64 tokens store those 64
  positions twice, which costs nothing here because the pool allocates
  whole fixed-length rows either way;
- cached lengths are **chunk-aligned**: only lengths expressible as sums
  of the engine's prefill bucket lengths are stored or matched, so every
  hit/promotion copies as a bounded set of bucket-sized
  `models/layers.py:cache_slot_copy` chunks — at most one compile per
  bucket per direction, never one per request;
- nodes are **ref-counted**: `match` pins its source node until the engine
  has dispatched the hit copy (`release`), and eviction skips pinned
  nodes, so a row is never recycled while an admitted-but-not-yet-copied
  slot still references it.

The tree itself never touches jax — it hands the engine ``(row, length)``
and the engine issues the jitted copies. That keeps this module unit-
testable in microseconds and the device interaction auditable in one
place (`engine._prefill_step` / `engine._promote`).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Sequence

import numpy as np

from ..telemetry import flight as _flight

__all__ = ["PrefixCache", "CacheNode"]


def _common_prefix(a: np.ndarray, b: np.ndarray) -> int:
    neq = a != b
    return int(neq.argmax()) if neq.any() else len(a)


class CacheNode:
    """One radix-tree node. ``edge`` is the token span from the parent and
    ``end`` the cumulative token depth. Row-bearing nodes (``row is not
    None``) own one pool row whose positions [0, end) hold committed KV for
    the full path from the root; structural nodes created by edge splits
    carry no row and are pruned once childless. ``refs`` pins the node's
    row against eviction while an admitted slot still plans to copy from
    it."""

    __slots__ = ("edge", "end", "children", "row", "refs", "last_use", "parent")

    def __init__(self, edge: np.ndarray, end: int, parent: "CacheNode | None"):
        self.edge = edge
        self.end = end
        self.children: dict[int, CacheNode] = {}
        self.row: int | None = None
        self.refs = 0
        self.last_use = 0
        self.parent = parent


class PrefixCache:
    """Host-side index over a fixed pool of ``rows`` device KV rows.

    ``buckets`` are the engine's prefill bucket lengths; ``max_len`` the
    per-row capacity. The cache only ever stores/matches lengths
    decomposable into bucket-sized chunks (``aligned``/``chunks``), which
    is what bounds the copy kernel's compile count."""

    def __init__(self, rows: int, buckets: Sequence[int], max_len: int) -> None:
        if rows < 1:
            raise ValueError(f"prefix cache needs >= 1 row, got {rows}")
        self.n_rows = rows
        self.buckets = tuple(sorted(set(buckets)))
        self.max_len = max_len
        self._free: deque[int] = deque(range(rows))
        self._root = CacheNode(np.empty((0,), np.int32), 0, None)
        self._entries: set[CacheNode] = set()  # row-bearing nodes
        self._clock = 0
        # Registry-backed dict view (docs/observability.md): the same
        # counters feed `engine.prefix_metrics()` and the `/metrics`
        # endpoint's serve_prefix_cache_* series.
        from .. import telemetry as _telemetry

        self.stats = _telemetry.StatsView(
            "serve_prefix_cache",
            (
                "lookups",
                "hits",
                "tokens_matched",
                "insertions",
                "dedup_skips",
                "evictions",
                "insert_denied",  # no free row and every row pinned
            ),
            label="cache",
        )
        # Request-scoped tracing flag, snapshotted once like the engine's
        # (docs/observability.md): the lookup path never re-reads the env.
        self._trace = _flight.trace_requests_enabled()
        # Reachability DP over [0, max_len]: _chunkable[n] is the LARGEST
        # bucket completing a decomposition of n into bucket lengths (0 =
        # not decomposable). Handles bucket sets that aren't multiples of
        # each other (e.g. (5, 7): 12 = 5 + 7) where greedy would fail.
        chunkable = np.zeros(max_len + 1, np.int64)
        chunkable[0] = -1
        for n in range(1, max_len + 1):
            for b in self.buckets:
                if b <= n and chunkable[n - b]:
                    chunkable[n] = b
        self._chunkable = chunkable

    # ---------------------------------------------------------- alignment
    def aligned(self, n: int) -> int:
        """Largest chunk-decomposable length <= n (0 if none)."""
        n = min(int(n), self.max_len)
        while n > 0 and not self._chunkable[n]:
            n -= 1
        return n

    def chunks(self, n: int) -> list[int]:
        """Decompose an `aligned` length into bucket-sized copy chunks."""
        out: list[int] = []
        n = int(n)
        while n > 0:
            b = int(self._chunkable[n])
            if b <= 0:
                raise ValueError(f"length {n} is not chunk-aligned for buckets {self.buckets}")
            out.append(b)
            n -= b
        return out

    # ------------------------------------------------------------- lookup
    @property
    def used_rows(self) -> int:
        return self.n_rows - len(self._free)

    def _touch(self, node: CacheNode) -> None:
        self._clock += 1
        node.last_use = self._clock

    def _any_row_below(self, node: CacheNode) -> CacheNode | None:
        if node.row is not None:
            return node
        for child in node.children.values():
            found = self._any_row_below(child)
            if found is not None:
                return found
        return None

    def match(
        self, tokens: np.ndarray, *, limit: int | None = None, rid: int = -1
    ) -> tuple[CacheNode | None, int]:
        """Longest usable cached prefix of ``tokens``.

        Returns ``(node, length)``: ``node``'s row holds committed KV for
        at least positions [0, length) of ``tokens`` (its path may extend
        beyond the match — the extra positions are simply not copied), and
        ``length`` is chunk-aligned and <= ``limit`` (the engine passes
        ``len(prompt) - 1`` so at least one prompt token is always left to
        prefill — something has to produce the first sampling logits).
        The node is PINNED against eviction until `release`.
        A miss returns ``(None, 0)``. ``rid`` tags the request-scoped
        trace span when ``ATX_TRACE_REQUESTS=1``."""
        self.stats["lookups"] += 1
        t_match0 = time.perf_counter() if self._trace else 0.0
        tokens = np.asarray(tokens)
        node, depth = self._root, 0
        path: list[CacheNode] = []
        frontier: CacheNode | None = None  # child matched partway into its edge
        while depth < len(tokens):
            child = node.children.get(int(tokens[depth]))
            if child is None:
                break
            n = min(len(child.edge), len(tokens) - depth)
            common = _common_prefix(child.edge[:n], tokens[depth : depth + n])
            depth += common
            if common < len(child.edge):
                if common > 0:
                    frontier = child
                break
            node = child
            path.append(child)
        limit = len(tokens) if limit is None else min(int(limit), len(tokens))
        matched = self.aligned(min(depth, limit))
        if matched <= 0:
            if self._trace:
                _flight.record_span(
                    "prefix_match", rid=rid, t0=t_match0, hit=False, matched=0
                )
            return None, 0
        # A source row must cover [0, matched) of a path agreeing with
        # ``tokens`` for >= matched tokens: fully-matched path nodes with
        # end >= matched qualify, as does ANY row in the subtree hanging
        # off the deepest matched point (everything there shares the first
        # ``depth`` >= matched tokens).
        src: CacheNode | None = None
        for cand in reversed(path):
            if cand.row is not None and cand.end >= matched:
                src = cand
                break
        if src is None:
            src = self._any_row_below(frontier if frontier is not None else node)
        if src is None:
            if self._trace:
                _flight.record_span(
                    "prefix_match", rid=rid, t0=t_match0, hit=False, matched=0
                )
            return None, 0
        src.refs += 1
        self._touch(src)
        self.stats["hits"] += 1
        self.stats["tokens_matched"] += matched
        if self._trace:
            _flight.record_span(
                "prefix_match", rid=rid, t0=t_match0, hit=True, matched=matched
            )
        return src, matched

    def release(self, node: CacheNode) -> None:
        """Unpin a node returned by `match` (after the copy is dispatched)."""
        if node.refs <= 0:
            raise RuntimeError("release() without a matching match() pin")
        node.refs -= 1

    def hot_entries(self, k: int) -> list[np.ndarray]:
        """Token paths of the ``k`` most-recently-used cached prefixes —
        HOST-side token ids only, newest first. Each path is the full
        root-to-node token sequence truncated to the node's committed
        ``end`` (chunk-aligned by construction). This is the migration
        surface the Router uses on quarantine: the dying replica's hottest
        prefixes are re-seeded into survivors by re-PREFILLING these
        tokens there — KV bytes never cross devices."""
        if k <= 0:
            return []
        out: list[np.ndarray] = []
        for node in sorted(self._entries, key=lambda n: -n.last_use)[:k]:
            parts: list[np.ndarray] = []
            cur: CacheNode | None = node
            while cur is not None and len(cur.edge):
                parts.append(cur.edge)
                cur = cur.parent
            path = np.concatenate(list(reversed(parts))) if parts else np.empty((0,), np.int32)
            out.append(np.asarray(path[: node.end], np.int32))
        return out

    # ------------------------------------------------------------- insert
    def insert(self, tokens: np.ndarray) -> int | None:
        """Register ``tokens`` (an `aligned`-length committed prefix) and
        return the pool row the caller must now COPY the KV into, or None
        when nothing needs doing (prefix already cached) or nothing can be
        done (every row pinned by in-flight slots — the caller just skips
        promotion; correctness never depends on an insert landing).

        May LRU-evict an unpinned entry to free a row. The returned row's
        KV is garbage until the caller's copy lands; that is safe because
        the engine dispatches the copy before returning to the scheduler,
        so no later match can read the row earlier in device order."""
        tokens = np.asarray(tokens, np.int32)
        L = len(tokens)
        if L <= 0 or not self._chunkable[min(L, self.max_len)] or L > self.max_len:
            raise ValueError(f"insert length {L} is not chunk-aligned (buckets {self.buckets})")
        node, depth = self._root, 0
        child: CacheNode | None = None
        common = 0
        while depth < L:
            child = node.children.get(int(tokens[depth]))
            if child is None:
                break
            n = min(len(child.edge), L - depth)
            common = _common_prefix(child.edge[:n], tokens[depth : depth + n])
            depth += common
            if common < len(child.edge):
                break
            node = child
            child = None
            common = 0
        if depth == L and child is None and node.row is not None:
            self._touch(node)  # exact duplicate — refresh recency only
            self.stats["dedup_skips"] += 1
            return None
        row = self._take_row()
        if row is None:
            self.stats["insert_denied"] += 1
            return None
        if depth == L and child is None:
            target = node  # structural node at exactly L: adopt a row
        elif child is None:
            target = CacheNode(tokens[depth:].copy(), L, node)
            node.children[int(tokens[depth])] = target
        else:
            # Matched partway into ``child``'s edge: split it at ``common``.
            mid = CacheNode(child.edge[:common], child.end - len(child.edge) + common, node)
            node.children[int(mid.edge[0])] = mid
            child.edge = child.edge[common:]
            child.parent = mid
            mid.children[int(child.edge[0])] = child
            if mid.end == L:
                target = mid
            else:
                target = CacheNode(tokens[depth:].copy(), L, mid)
                mid.children[int(tokens[depth])] = target
        target.row = row
        self._entries.add(target)
        self._touch(target)
        self.stats["insertions"] += 1
        return row

    def _take_row(self) -> int | None:
        if self._free:
            return self._free.popleft()
        victims = [n for n in self._entries if n.refs == 0]
        if not victims:
            return None
        self._evict(min(victims, key=lambda n: n.last_use))
        return self._free.popleft()

    def _evict(self, node: CacheNode) -> None:
        """Free one row (LRU caller picks the node). The subtree keeps
        working — every descendant's row is self-contained — and childless
        structural leftovers are pruned up the path."""
        self._free.append(node.row)
        node.row = None
        self._entries.discard(node)
        self.stats["evictions"] += 1
        while (
            node.parent is not None
            and node.row is None
            and not node.children
            and node.refs == 0
        ):
            parent = node.parent
            del parent.children[int(node.edge[0])]
            node.parent = None
            node = parent

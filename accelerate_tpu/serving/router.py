"""Multi-replica serving front-end: routing, admission, drain, failover.

One `Engine` saturates one device group; this module is the fleet layer
above it, following the front-end/engine split of production LLM servers
(Orca's request-level scheduler over execution engines; SGLang's
cache-aware routing, which the PR-6 radix prefix cache was built to
exploit). A `Router` owns a bounded admission queue and fans requests out
to N engine **replicas** — locally each replica is an `Engine` over its
own device subset; on a pod the same abstraction covers
one-engine-per-host. Five mechanics:

- **Prefix-affinity + least-loaded routing** — a host-side
  `AffinityIndex` over recently dispatched prompts steers a request
  sharing a cached prefix to the replica that owns that prefix KV
  (maximizing per-replica prefix-cache hit rate), falling back to the
  least-loaded replica. ``affinity_min_tokens`` (default: the smallest
  prefill bucket — shorter matches can't be cache-aligned anyway) and
  ``affinity_max_imbalance`` (how many extra in-flight requests affinity
  may pile onto one replica before balance wins) set the trade-off;
  ``affinity="least-loaded"`` disables steering entirely.
- **Admission control & backpressure** — the queue of
  accepted-but-undispatched requests is bounded (``queue_depth``, env
  ``ATX_SERVE_QUEUE_DEPTH``, default 4x total fleet slots); a full queue
  raises `QueueFullError` (a reject the caller SEES, counted in
  ``stats["rejects"]``). Per-request deadlines (`Request.timeout`
  seconds) cancel mid-queue or mid-decode with
  ``finish_reason="cancelled"``; `Router.cancel` does the same on demand.
- **Graceful drain** — every `poll` reads
  ``resilience.preemption_requested()`` (SIGTERM / the GCE maintenance
  poller); when set, the router stops admitting (`RouterDraining`),
  finishes everything already accepted, and the caller exits with
  ``resilience.PREEMPTION_EXIT_CODE`` (75) so an elastic launcher resumes
  it (`atx serve --replicas` does exactly this).
- **Replica failover** — a replica whose thread raises (including
  `test_utils.faults` injection at the ``router.replica<i>.step`` crash
  points) or wedges (per-replica `resilience.Watchdog` on step-entry
  heartbeats; ``watchdog_secs`` / ``ATX_SERVE_REPLICA_WATCHDOG_SECS``) is
  **quarantined**: its in-flight requests are re-dispatched to healthy
  replicas (up to ``max_retries`` attempts, then
  ``finish_reason="failed"``). Greedy outputs stay bit-identical to a
  solo `Engine` regardless of routing, retries, or replica death: tokens
  are a pure function of (prompt, seed, config, params), so a retry is a
  replay — and per-ticket stream dedup delivers each token's callback
  exactly once even when an attempt died mid-decode.
- **Aggregate observability** — `Router.metrics()` snapshots fleet
  counters (queue depth/peak, rejects, retries, cancels, drains,
  TTFT/e2e p50/p99) plus per-replica occupancy, prefix hit rate, and
  quarantine state; `atx serve` merges it into its one-line JSON.

Execution modes:

- ``threads=True`` (default): each replica engine runs on its OWN
  dedicated thread (the one-thread-per-engine ownership rule in
  `engine.py`), pumping submissions/cancellations from a per-replica
  inbox; the caller's thread runs only router logic (`poll`/`serve`).
- ``threads=False``: replicas are pumped inline on the caller's thread,
  round-robin, one step per replica per `poll` — fully deterministic, no
  thread scheduling in the dispatch order. This is the mode the `atx
  lint router_drain` scenario replays through `analysis.lint_host_loop`
  and the mode bit-identity tests use; wedge detection (a stuck step
  would stall the caller itself) needs ``threads=True``.

Replicas must be identically configured (same ``buckets`` / ``max_len``
/ generation config): admission validates against replica 0 and failover
replays on any healthy replica, so a request must fit all of them.
See docs/serving.md ("Multi-replica routing & drain").
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable, Sequence

import numpy as np

# Package-attribute access (not by-value import): `analysis.host_trace`
# patches `resilience.preemption_requested` and `resilience.fault_point`
# on the package during lint replay, so the router must read them through
# the package or the router_drain scenario would dodge the simulation.
from .. import resilience
from .. import telemetry as _telemetry
from ..utils.environment import get_int_from_env
from .engine import Completion, Engine, Request

__all__ = [
    "Router",
    "AffinityIndex",
    "QueueFullError",
    "RouterDraining",
    "NoHealthyReplicaError",
]


class QueueFullError(RuntimeError):
    """Admission queue at ``queue_depth``: the request was REJECTED (never
    queued). Callers retry with backoff or shed load — the visible
    backpressure signal (`stats["rejects"]` counts these)."""


class RouterDraining(RuntimeError):
    """The router is draining (preemption or `Router.drain`): no new
    admissions; everything already accepted still completes."""


class NoHealthyReplicaError(RuntimeError):
    """Every replica is quarantined while requests are still outstanding —
    the fleet cannot make progress."""


class AffinityIndex:
    """Host-side index of recently dispatched prompts per replica.

    The router can't see inside each replica's device-resident prefix
    cache, so it keeps its own LRU record of (prompt, replica) pairs at
    dispatch time and scores candidates by longest shared prefix — the
    same signal the per-engine radix tree keys on, approximated at the
    fleet level. Bounded at ``cap`` entries (drop-oldest) so lookup cost
    stays a few hundred short vector compares per admission."""

    def __init__(self, cap: int = 512) -> None:
        self.cap = cap
        self._entries: deque[tuple[np.ndarray, int]] = deque()

    def insert(self, prompt: np.ndarray, replica: int) -> None:
        self._entries.append((np.asarray(prompt, np.int32), int(replica)))
        while len(self._entries) > self.cap:
            self._entries.popleft()

    def remove_replica(self, replica: int) -> None:
        """Forget a quarantined replica — its cached KV is unreachable, so
        steering traffic at it would be pure imbalance."""
        self._entries = deque((p, r) for p, r in self._entries if r != replica)

    def best(self, prompt: np.ndarray) -> dict[int, int]:
        """Longest shared-prefix length per replica for ``prompt``."""
        prompt = np.asarray(prompt, np.int32)
        best: dict[int, int] = {}
        for toks, r in self._entries:
            n = min(len(toks), len(prompt))
            if n <= best.get(r, 0):
                continue  # can't beat this replica's current best
            neq = np.nonzero(toks[:n] != prompt[:n])[0]
            m = int(neq[0]) if len(neq) else n
            if m > best.get(r, 0):
                best[r] = m
        return best


class _Ticket:
    """Router-side bookkeeping for one accepted request."""

    __slots__ = (
        "req", "user_stream", "submitted_at", "deadline", "replica",
        "attempts", "generation", "streamed", "cancel_sent", "done",
    )

    def __init__(self, req: Request) -> None:
        self.req = req
        self.user_stream = req.stream
        self.submitted_at = time.perf_counter()
        self.deadline = (
            self.submitted_at + req.timeout if req.timeout is not None else None
        )
        self.replica: int | None = None
        self.attempts = 0
        # Bumped at every (re)dispatch and at resolution: a stream callback
        # from a superseded attempt (a quarantined replica's thread still
        # unwinding) sees a stale generation and drops itself.
        self.generation = 0
        self.streamed = 0  # tokens delivered to the user stream so far
        self.cancel_sent = False
        self.done = False


class _Replica:
    """One engine + (in threads mode) its dedicated driver thread.

    The engine is single-threaded by contract; ALL interaction crosses a
    locked inbox of ``("submit", Request)`` / ``("cancel", rid)`` /
    ``("stop",)`` messages, applied between `step` calls by `pump` — which
    is the same code path the thread loop and the inline mode run, so the
    two modes differ only in who calls it."""

    def __init__(
        self,
        id: int,
        engine: Engine,
        router: "Router",
        *,
        watchdog_secs: float | None = None,
    ) -> None:
        self.id = id
        self.engine = engine
        self.router = router
        self.inbox: deque = deque()
        self.inbox_lock = threading.Lock()
        self.wake = threading.Event()
        self.thread: threading.Thread | None = None
        self.dead = False  # router-side quarantine flag (router thread only)
        self.error: str | None = None
        self.wedged = threading.Event()
        self.inflight: set[int] = set()  # rids dispatched here (router thread)
        self.dispatched = 0
        self.completed = 0
        self._stopping = False
        self.watchdog: resilience.Watchdog | None = None
        if watchdog_secs:
            # The abort seam turns the watchdog's process-kill into a
            # per-replica quarantine: the fleet survives one wedged engine.
            self.watchdog = resilience.Watchdog(
                watchdog_secs,
                first_deadline_secs=watchdog_secs * 10.0,  # compile headroom
                abort=self._wedge,
            )

    def _wedge(self) -> None:
        self.wedged.set()
        self.router._results.put((
            "down", self.id,
            f"wedged: step exceeded its {self.watchdog.deadline:.1f}s "
            "deadline (ATX_SERVE_REPLICA_WATCHDOG_SECS)",
        ))

    def send(self, msg: tuple) -> None:
        with self.inbox_lock:
            self.inbox.append(msg)
        self.wake.set()

    def pump(self) -> list[Completion]:
        """Apply queued messages, then run at most one engine step. Runs on
        the replica thread (threads mode) or the caller (inline mode)."""
        out: list[Completion] = []
        with self.inbox_lock:
            msgs = list(self.inbox)
            self.inbox.clear()
        for msg in msgs:
            if msg[0] == "submit":
                self.engine.submit_request(msg[1])
            elif msg[0] == "cancel":
                c = self.engine.cancel(msg[1])
                if c is not None:
                    out.append(c)
            elif msg[0] == "stop":
                self._stopping = True
        if self.engine.busy:
            if self.watchdog is not None:
                self.watchdog.arm()
            resilience.fault_point(f"router.replica{self.id}.step")
            out.extend(self.engine.step())
            if self.watchdog is not None:
                self.watchdog.disarm()
        return out

    def start(self) -> None:
        self.thread = threading.Thread(
            target=self._run, name=f"atx-replica{self.id}", daemon=True
        )
        self.thread.start()

    def _run(self) -> None:
        try:
            while True:
                for c in self.pump():
                    self.router._results.put(("done", self.id, c))
                if self._stopping and not self.engine.busy and not self.inbox:
                    return
                if not self.engine.busy and not self.inbox:
                    self.wake.wait(0.002)
                    self.wake.clear()
        except BaseException as e:  # any replica death is a quarantine event
            self.router._results.put(
                ("down", self.id, f"{type(e).__name__}: {e}")
            )
        finally:
            if self.watchdog is not None:
                self.watchdog.stop()


def _pct(xs: list[float], q: float) -> float | None:
    if not xs:
        return None
    s = sorted(xs)
    return round(s[min(len(s) - 1, int(q * len(s)))], 2)


def _hq(hist: Any, q: float, labels: dict) -> float | None:
    """Histogram-estimated percentile, rounded like the old exact `_pct`
    (None until data) so `metrics()` keeps its field contract."""
    value = hist.quantile(q, **labels)
    return None if value is None else round(value, 2)


class Router:
    """Bounded-admission front-end over N `Engine` replicas (module
    docstring has the full design). Typical use::

        with Router([engine_a, engine_b]) as router:
            completions = router.serve(trace, realtime=True)

    or incrementally: `submit`/`submit_request` -> `poll` (one tick) ->
    `pop_completions`, with `join` to run everything outstanding down.
    All Router methods must be called from ONE thread (the replicas have
    their own); completions come back in finish order with
    ``submitted_at`` rewritten to router admission time, so TTFT/e2e
    latencies include queueing delay."""

    def __init__(
        self,
        engines: Sequence[Engine],
        *,
        queue_depth: int | None = None,
        affinity: str = "prefix",
        affinity_min_tokens: int | None = None,
        affinity_max_imbalance: int | None = None,
        max_retries: int = 2,
        watchdog_secs: float | None = None,
        threads: bool = True,
    ) -> None:
        engines = list(engines)
        if not engines:
            raise ValueError("Router needs at least one engine replica")
        ref = engines[0]
        for i, e in enumerate(engines[1:], start=1):
            if e.buckets != ref.buckets or e.max_len != ref.max_len:
                raise ValueError(
                    "replicas must be identically configured (admission "
                    "validates against replica 0 and failover replays on any "
                    f"healthy replica): replica {i} has buckets={e.buckets} "
                    f"max_len={e.max_len}, replica 0 has buckets="
                    f"{ref.buckets} max_len={ref.max_len}"
                )
        self._ref = ref
        self.threads = threads
        if queue_depth is None:
            queue_depth = get_int_from_env(
                ("ATX_SERVE_QUEUE_DEPTH",), 4 * sum(e.n_slots for e in engines)
            )
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self.queue_depth = queue_depth
        if affinity not in ("prefix", "least-loaded"):
            raise ValueError(
                f"affinity must be 'prefix' or 'least-loaded', got {affinity!r}"
            )
        self.affinity = affinity
        self.affinity_min_tokens = (
            affinity_min_tokens
            if affinity_min_tokens is not None
            else ref.buckets[0]
        )
        self.affinity_max_imbalance = (
            affinity_max_imbalance
            if affinity_max_imbalance is not None
            else max(1, ref.n_slots - 1)
        )
        self.max_retries = max_retries
        if watchdog_secs is None:
            raw = os.environ.get("ATX_SERVE_REPLICA_WATCHDOG_SECS", "")
            try:
                watchdog_secs = float(raw) if raw else None
            except ValueError:
                watchdog_secs = None
        if watchdog_secs is not None and watchdog_secs <= 0:
            watchdog_secs = None
        self.replicas = [
            # Inline mode gets no watchdog: a wedged step stalls the caller
            # itself, so there is nobody left to act on the firing.
            _Replica(i, e, self, watchdog_secs=watchdog_secs if threads else None)
            for i, e in enumerate(engines)
        ]
        self._affinity = AffinityIndex()
        self._results: queue.Queue = queue.Queue()
        self._pending: deque[_Ticket] = deque()  # accepted, not yet dispatched
        self._tickets: dict[int, _Ticket] = {}
        self._completions: list[Completion] = []
        self._next_rid = 0
        self._outstanding = 0
        self._draining = False
        self.drain_reason: str | None = None
        # Latency recording + counters live on the telemetry registry
        # (docs/observability.md): fixed-bucket histograms replace the old
        # unbounded p50/p99 lists, and `metrics()` reads its percentiles
        # from the same series the `/metrics` endpoint exports.
        self._tel_labels = {"router": _telemetry.views._next_instance()}
        _labels = ("router",)
        self._h_ttft = _telemetry.histogram(
            "router_ttft_ms", "admission -> first token", labels=_labels
        )
        self._h_e2e = _telemetry.histogram(
            "router_e2e_ms", "admission -> completion", labels=_labels
        )
        self._h_queue_wait = _telemetry.histogram(
            "router_queue_wait_ms", "admission -> replica dispatch",
            labels=_labels,
        )
        self._g_queue = _telemetry.gauge(
            "router_queue_depth", "pending admissions", labels=_labels
        )
        self.stats = _telemetry.StatsView(
            "router",
            (
                "submitted",
                "rejects",
                "drain_rejected",
                "dispatched",
                "completed",
                "retries",
                "cancelled",
                "failed",
                "replicas_lost",
                "queue_peak",
            ),
            label="router",
            instance=self._tel_labels["router"],
            gauges=("queue_peak",),
        )
        if threads:
            for r in self.replicas:
                r.start()

    # ------------------------------------------------------------- submit
    def submit(
        self,
        prompt: Any,
        max_new_tokens: int | None = None,
        *,
        seed: int = 0,
        stream: Callable[[int, int, str | None], None] | None = None,
        arrival: float | None = None,
        stop_sequences: Sequence[Sequence[int]] | None = None,
        timeout: float | None = None,
    ) -> int:
        """Admit one request; returns its fleet-global request id. Raises
        `QueueFullError` when the admission queue is at ``queue_depth``
        and `RouterDraining` once drain has started. ``timeout`` is the
        request's deadline in seconds from now."""
        return self.submit_request(
            Request(
                prompt=np.asarray(prompt, np.int32).reshape(-1),
                max_new_tokens=max_new_tokens,
                seed=seed,
                arrival=arrival,
                stream=stream,
                stop_sequences=stop_sequences,
                timeout=timeout,
            )
        )

    def submit_request(self, req: Request) -> int:
        if self._draining:
            self.stats["drain_rejected"] += 1
            raise RouterDraining(
                f"router is draining ({self.drain_reason}): "
                "not admitting new requests"
            )
        if len(self._pending) >= self.queue_depth:
            self.stats["rejects"] += 1
            raise QueueFullError(
                f"admission queue full ({len(self._pending)}/"
                f"{self.queue_depth} pending; ATX_SERVE_QUEUE_DEPTH raises "
                "the bound) — retry with backoff"
            )
        # Validate at the front door (engine capacity, bucket-padded plan
        # fit) so a bad request raises HERE, not inside a replica thread.
        self._ref.validate_request(req)
        if req.rid < 0:
            req.rid = self._next_rid
        self._next_rid = max(self._next_rid, req.rid) + 1
        t = _Ticket(req)
        self._tickets[req.rid] = t
        self._pending.append(t)
        self._outstanding += 1
        self.stats["submitted"] += 1
        self.stats["queue_peak"] = max(
            self.stats["queue_peak"], len(self._pending)
        )
        self._g_queue.set(len(self._pending), **self._tel_labels)
        return req.rid

    # ------------------------------------------------------------- cancel
    def cancel(self, rid: int) -> bool:
        """Cancel an accepted request (queued or dispatched). The
        ``finish_reason="cancelled"`` completion surfaces through the
        normal `poll`/`join` path; returns False for unknown/finished
        rids."""
        t = self._tickets.get(rid)
        if t is None or t.done:
            return False
        self._cancel_ticket(t)
        return True

    def _cancel_ticket(self, t: _Ticket) -> None:
        if t.replica is None:
            self._pending.remove(t)
            self._resolve(t, self._local_cancel_completion(t))
        elif not t.cancel_sent:
            t.cancel_sent = True
            self.replicas[t.replica].send(("cancel", t.req.rid))

    def _local_cancel_completion(self, t: _Ticket) -> Completion:
        return self._ref._cancelled_completion(
            t.req,
            np.full(
                (t.req.max_new_tokens,), self._ref.config.pad_token_id, np.int32
            ),
            0,
            0.0,
        )

    # -------------------------------------------------------------- drain
    def drain(self, reason: str = "manual") -> None:
        """Flip to drain mode: stop admitting (`RouterDraining`), let
        everything already accepted finish. `poll` calls this with
        ``reason="preemption"`` when `resilience.preemption_requested()`
        goes high; `atx serve` then exits 75 after `join` so the elastic
        launcher resumes the process."""
        if not self._draining:
            self._draining = True
            self.drain_reason = reason

    @property
    def draining(self) -> bool:
        return self._draining

    # --------------------------------------------------------------- tick
    def poll(self, timeout: float = 0.0) -> None:
        """One router tick: poll the preemption flag, quarantine dead
        replicas, expire deadlines, dispatch what fits, ingest results
        (blocking up to ``timeout`` seconds for the first one in threads
        mode)."""
        if not self._draining and resilience.preemption_requested():
            self.drain("preemption")
        if self.threads:
            self._check_threads()
        self._check_deadlines()
        self._dispatch()
        if self.threads:
            self._pump_results(timeout)
        else:
            worked = self._pump_inline()
            if not worked and timeout > 0:
                time.sleep(timeout)
        # Quarantine/ingest may have freed slots or requeued orphans.
        self._dispatch()

    def _check_threads(self) -> None:
        for r in self.replicas:
            if (
                not r.dead
                and not r._stopping
                and r.thread is not None
                and not r.thread.is_alive()
            ):
                self._quarantine(r.id, r.error or "replica thread exited")

    def _check_deadlines(self) -> None:
        now = time.perf_counter()
        for t in list(self._pending):
            if t.deadline is not None and now >= t.deadline:
                self._pending.remove(t)
                self._resolve(t, self._local_cancel_completion(t))
        for r in self.replicas:
            if r.dead:
                continue
            for rid in list(r.inflight):
                t = self._tickets.get(rid)
                if (
                    t is not None
                    and not t.done
                    and not t.cancel_sent
                    and t.deadline is not None
                    and now >= t.deadline
                ):
                    t.cancel_sent = True
                    r.send(("cancel", rid))

    def _dispatch(self) -> None:
        # Strict FIFO: only the head dispatches (no slot, no overtaking).
        while self._pending:
            r = self._pick_replica(self._pending[0].req)
            if r is None:
                return
            self._dispatch_to(self._pending.popleft(), r)

    def _pick_replica(self, req: Request) -> _Replica | None:
        cands = [
            r
            for r in self.replicas
            if not r.dead and len(r.inflight) < r.engine.n_slots
        ]
        if not cands:
            return None
        least = min(cands, key=lambda r: (len(r.inflight), r.id))
        if self.affinity == "prefix":
            matches = self._affinity.best(req.prompt)
            best, best_m = None, 0
            for r in cands:
                m = matches.get(r.id, 0)
                if m >= self.affinity_min_tokens and m > best_m:
                    best, best_m = r, m
            if (
                best is not None
                and len(best.inflight) - len(least.inflight)
                <= self.affinity_max_imbalance
            ):
                return best
        return least

    def _dispatch_to(self, t: _Ticket, r: _Replica) -> None:
        t.replica = r.id
        t.attempts += 1
        t.generation += 1
        t.cancel_sent = False
        t.req.stream = self._make_stream(t)
        r.inflight.add(t.req.rid)
        r.dispatched += 1
        self.stats["dispatched"] += 1
        self._h_queue_wait.observe(
            (time.perf_counter() - t.submitted_at) * 1e3, **self._tel_labels
        )
        self._g_queue.set(len(self._pending), **self._tel_labels)
        if self.affinity == "prefix":
            # Record at dispatch (not completion) so a burst of same-prefix
            # requests steers together from the second one on.
            self._affinity.insert(t.req.prompt, r.id)
        r.send(("submit", t.req))

    def _make_stream(
        self, t: _Ticket
    ) -> Callable[[int, int, str | None], None]:
        """Exactly-once stream delivery across retries: greedy determinism
        means a retried attempt replays the identical token sequence, so
        the wrapper skips the ``t.streamed`` tokens the dead attempt
        already delivered and drops callbacks from superseded attempts
        (generation mismatch) entirely."""
        gen = t.generation
        count = 0

        def stream(rid: int, tok: int, text: str | None) -> None:
            nonlocal count
            count += 1
            if t.generation != gen:
                return  # superseded attempt still unwinding
            if count > t.streamed:
                t.streamed = count
                if t.user_stream is not None:
                    t.user_stream(rid, tok, text)

        return stream

    def _pump_results(self, timeout: float) -> None:
        block = timeout
        while True:
            try:
                kind, rid, payload = (
                    self._results.get(timeout=block)
                    if block > 0
                    else self._results.get_nowait()
                )
            except queue.Empty:
                return
            block = 0.0
            if kind == "done":
                self._ingest(rid, payload)
            else:
                self._quarantine(rid, payload)

    def _pump_inline(self) -> bool:
        worked = False
        for r in self.replicas:  # fixed order: deterministic replay
            if r.dead:
                continue
            try:
                completions = r.pump()
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                self._quarantine(r.id, f"{type(e).__name__}: {e}")
                worked = True
                continue
            for c in completions:
                self._ingest(r.id, c)
            worked = worked or bool(completions) or r.engine.busy
        return worked

    def _ingest(self, replica_id: int, c: Completion) -> None:
        t = self._tickets.get(c.rid)
        if t is None or t.done or t.replica != replica_id:
            return  # stale: resolved elsewhere or reassigned after quarantine
        self.replicas[replica_id].completed += 1
        self._resolve(t, c)

    def _resolve(self, t: _Ticket, c: Completion) -> None:
        t.done = True
        t.generation += 1  # silence any attempt still unwinding
        if t.replica is not None:
            self.replicas[t.replica].inflight.discard(t.req.rid)
            t.replica = None
        # Router admission time, so latency includes queueing delay.
        c.submitted_at = t.submitted_at
        if c.finish_reason == "cancelled":
            self.stats["cancelled"] += 1
        if c.finish_reason not in ("cancelled", "failed"):
            if c.first_token_at:
                self._h_ttft.observe(
                    (c.first_token_at - t.submitted_at) * 1000.0,
                    **self._tel_labels,
                )
            self._h_e2e.observe(
                (c.finished_at - t.submitted_at) * 1000.0, **self._tel_labels
            )
        self.stats["completed"] += 1
        self._outstanding -= 1
        self._completions.append(c)

    def _quarantine(self, replica_id: int, reason: str) -> None:
        r = self.replicas[replica_id]
        if r.dead:
            return
        r.dead = True
        r.error = reason
        self.stats["replicas_lost"] += 1
        self._affinity.remove_replica(replica_id)
        orphans = [
            self._tickets[rid]
            for rid in sorted(r.inflight)
            if rid in self._tickets
        ]
        r.inflight.clear()
        # Retries jump the queue (appendleft, original order preserved):
        # they already waited once, and FIFO age order stays intact.
        for t in reversed(orphans):
            if t.done:
                continue
            t.replica = None
            t.generation += 1
            if t.attempts > self.max_retries:
                self.stats["failed"] += 1
                fc = self._local_cancel_completion(t)
                fc.finish_reason = "failed"
                self._resolve(t, fc)
                continue
            self.stats["retries"] += 1
            self._pending.appendleft(t)

    # ---------------------------------------------------------- lifecycle
    def pop_completions(self) -> list[Completion]:
        out, self._completions = self._completions, []
        return out

    def join(self, timeout: float | None = None) -> list[Completion]:
        """Run until every accepted request resolves; returns completions
        gathered since the last pop, in finish order. Raises
        `NoHealthyReplicaError` when the whole fleet is quarantined with
        work outstanding, `TimeoutError` past ``timeout`` seconds."""
        t0 = time.perf_counter()
        while self._outstanding > 0:
            if all(r.dead for r in self.replicas):
                errors = "; ".join(
                    f"replica {r.id}: {r.error}" for r in self.replicas
                )
                raise NoHealthyReplicaError(
                    f"{self._outstanding} request(s) outstanding with every "
                    f"replica quarantined ({errors})"
                )
            if timeout is not None and time.perf_counter() - t0 > timeout:
                raise TimeoutError(
                    f"router join timed out after {timeout}s with "
                    f"{self._outstanding} request(s) outstanding"
                )
            self.poll(0.002 if self.threads else 0.0)
        return self.pop_completions()

    def serve(
        self, requests: Iterable[Request], *, realtime: bool = False
    ) -> list[Completion]:
        """Drive a whole trace through the fleet (the `Engine.serve`
        contract at router level). ``realtime=True`` honours arrival
        offsets and REJECTS on a full queue (the latency-measuring mode);
        otherwise submission blocks on backpressure so every request is
        eventually admitted. Drain (preemption or `drain()`) stops
        admissions mid-trace — unsubmitted requests are counted in
        ``stats["drain_rejected"]`` — then everything accepted runs to
        completion, preserving the exit-75 resume contract."""
        reqs = sorted(requests, key=lambda r: (r.arrival or 0.0))
        t0 = time.perf_counter()
        i = 0
        while i < len(reqs):
            if self._draining:
                self.stats["drain_rejected"] += len(reqs) - i
                break
            if realtime and (reqs[i].arrival or 0.0) > time.perf_counter() - t0:
                self.poll(0.002)
                continue
            if not realtime and len(self._pending) >= self.queue_depth:
                self.poll(0.002)  # backpressure: wait for queue space
                continue
            try:
                self.submit_request(reqs[i])
            except QueueFullError:
                pass  # realtime: visible reject, request is shed
            except RouterDraining:
                continue  # top of loop accounts the rest as drain_rejected
            i += 1
        return self.join()

    def close(self) -> None:
        """Stop replica threads and watchdogs. Wedged threads (blocked
        inside a stuck step) are daemons and are left behind."""
        if self.threads:
            for r in self.replicas:
                if r.thread is not None:
                    r.send(("stop",))
            for r in self.replicas:
                if r.thread is not None and not r.wedged.is_set():
                    r.thread.join(timeout=5.0)
        for r in self.replicas:
            if r.watchdog is not None:
                r.watchdog.stop()

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # ------------------------------------------------------------ metrics
    def metrics(self) -> dict:
        """Point-in-time fleet snapshot: router counters, latency
        percentiles (ms, None until data), and one dict per replica —
        the payload `atx serve` flattens into its JSON line."""
        per = []
        for r in self.replicas:
            es = r.engine.stats
            pm = r.engine.prefix_metrics()
            per.append(
                {
                    "replica": r.id,
                    "dispatched": r.dispatched,
                    "completed": r.completed,
                    "inflight": len(r.inflight),
                    "occupancy": round(
                        es["decode_slot_steps"]
                        / max(es["decode_steps"] * r.engine.n_slots, 1),
                        3,
                    ),
                    "prefix_hit_rate": pm.get("prefix_hit_rate", 0.0),
                    "quarantined": int(r.dead),
                    "wedged": int(r.wedged.is_set()),
                    "error": r.error,
                }
            )
        m: dict = dict(self.stats)
        m.update(
            replicas=len(self.replicas),
            replicas_alive=sum(1 for r in self.replicas if not r.dead),
            queue_depth=len(self._pending),
            queue_capacity=self.queue_depth,
            draining=int(self._draining),
            drain_reason=self.drain_reason,
            ttft_p50_ms=_hq(self._h_ttft, 0.50, self._tel_labels),
            ttft_p99_ms=_hq(self._h_ttft, 0.99, self._tel_labels),
            e2e_p50_ms=_hq(self._h_e2e, 0.50, self._tel_labels),
            e2e_p99_ms=_hq(self._h_e2e, 0.99, self._tel_labels),
            per_replica=per,
        )
        return m

"""Multi-replica serving front-end: routing, admission, drain, failover.

One `Engine` saturates one device group; this module is the fleet layer
above it, following the front-end/engine split of production LLM servers
(Orca's request-level scheduler over execution engines; SGLang's
cache-aware routing, which the PR-6 radix prefix cache was built to
exploit). A `Router` owns a bounded admission queue and fans requests out
to N engine **replicas** — locally each replica is an `Engine` over its
own device subset; on a pod the same abstraction covers
one-engine-per-host. Five mechanics:

- **Prefix-affinity + least-loaded routing** — a host-side
  `AffinityIndex` over recently dispatched prompts steers a request
  sharing a cached prefix to the replica that owns that prefix KV
  (maximizing per-replica prefix-cache hit rate), falling back to the
  least-loaded replica. ``affinity_min_tokens`` (default: the smallest
  prefill bucket — shorter matches can't be cache-aligned anyway) and
  ``affinity_max_imbalance`` (how many extra in-flight requests affinity
  may pile onto one replica before balance wins) set the trade-off;
  ``affinity="least-loaded"`` disables steering entirely.
- **Admission scheduling & backpressure** — the queue of
  accepted-but-undispatched requests is bounded (``queue_depth``, env
  ``ATX_SERVE_QUEUE_DEPTH``, default 4x total fleet slots) and, by
  default (``scheduling="edf"``), dispatched earliest-deadline-first
  within priority classes (`Request.priority`, lower = more important;
  requests without deadlines order after deadlined peers, FIFO within a
  class — so a homogeneous trace reproduces the old FIFO order exactly).
  Under overload a full queue *sheds*: an arriving request of a strictly
  more important class evicts the newest queued request of the least
  important class (``finish_reason="shed"``, `router_shed_total{class}`)
  instead of being rejected; arrivals that don't outrank anyone still get
  `QueueFullError`. Requests whose deadline is already infeasible given
  the observed service time and the work ahead of them are rejected at
  the front door (`DeadlineInfeasibleError`,
  `router_deadline_infeasible_total`) once the e2e histogram has data.
  Per-request deadlines (`Request.timeout` seconds) still cancel
  mid-queue or mid-decode with ``finish_reason="cancelled"``;
  `Router.cancel` does the same on demand. ``scheduling="fifo"`` restores
  strict arrival order with reject-only overload behaviour.
- **Graceful drain** — every `poll` reads
  ``resilience.preemption_requested()`` (SIGTERM / the GCE maintenance
  poller); when set, the router stops admitting (`RouterDraining`),
  finishes everything already accepted, and the caller exits with
  ``resilience.PREEMPTION_EXIT_CODE`` (75) so an elastic launcher resumes
  it (`atx serve --replicas` does exactly this).
- **Replica failover, probation & re-admission** — a replica whose
  thread raises (including `test_utils.faults` injection at the
  ``router.replica<i>.step`` crash points) or wedges (per-replica
  `resilience.Watchdog` on step-entry heartbeats; ``watchdog_secs`` /
  ``ATX_SERVE_REPLICA_WATCHDOG_SECS``) is **quarantined**: its in-flight
  requests are re-dispatched to healthy replicas (up to ``max_retries``
  attempts, then ``finish_reason="failed"``), metered by a fleet-wide
  **retry budget** (token bucket: ``ATX_SERVE_RETRY_BUDGET`` capacity,
  ``ATX_SERVE_RETRY_REFILL_PER_SEC`` refill) so a sick fleet degrades to
  visible ``failed`` completions instead of a retry storm. With
  ``readmit_secs`` / ``ATX_SERVE_READMIT_SECS`` set, quarantine is not
  forever: after a capped-exponential + jittered backoff the replica is
  **probed** — a canary request recorded from real traffic is replayed
  directly on the idle quarantined engine and must reproduce the healthy
  fleet's tokens bit-for-bit (greedy determinism makes this exact) — and
  on success re-admitted under **probation** (dispatch capped to one
  in-flight request until ``ATX_SERVE_PROBATION_COMPLETIONS`` clean
  completions). A probe failure (or a wedged engine) rebuilds the
  replica from ``engine_factory`` (fresh engine, same weights) when one
  is provided. On quarantine the dead replica's hottest committed
  prefix-cache entries (HOST-side token ids) are **migrated**: re-seeded
  into a surviving replica by internal warm-up prefills (KV is
  re-prefilled, never copied cross-device) and the `AffinityIndex`
  retargeted so the family's future traffic steers at the warm survivor.
  Greedy outputs stay bit-identical to a solo `Engine` regardless of
  routing, retries, replica death, or re-admission: tokens are a pure
  function of (prompt, seed, config, params), so a retry is a replay —
  and per-ticket stream dedup delivers each token's callback exactly
  once even when an attempt died mid-decode.
- **Aggregate observability** — `Router.metrics()` snapshots fleet
  counters (queue depth/peak, rejects, retries, cancels, drains,
  TTFT/e2e p50/p99) plus per-replica occupancy, prefix hit rate, and
  quarantine state; `atx serve` merges it into its one-line JSON.

Execution modes:

- ``threads=True`` (default): each replica engine runs on its OWN
  dedicated thread (the one-thread-per-engine ownership rule in
  `engine.py`), pumping submissions/cancellations from a per-replica
  inbox; the caller's thread runs only router logic (`poll`/`serve`).
- ``threads=False``: replicas are pumped inline on the caller's thread,
  round-robin, one step per replica per `poll` — fully deterministic, no
  thread scheduling in the dispatch order. This is the mode the `atx
  lint router_drain` scenario replays through `analysis.lint_host_loop`
  and the mode bit-identity tests use; wedge detection (a stuck step
  would stall the caller itself) needs ``threads=True``.

Replicas must be identically configured (same ``buckets`` / ``max_len``
/ generation config): admission validates against replica 0 and failover
replays on any healthy replica, so a request must fit all of them.
See docs/serving.md ("Multi-replica routing & drain").
"""

from __future__ import annotations

import os
import queue
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Iterable, Sequence

import numpy as np

# Package-attribute access (not by-value import): `analysis.host_trace`
# patches `resilience.preemption_requested` and `resilience.fault_point`
# on the package during lint replay, so the router must read them through
# the package or the router_drain scenario would dodge the simulation.
from .. import resilience
from .. import telemetry as _telemetry
from ..telemetry import flight as _flight
from ..utils.environment import get_int_from_env
from .engine import Completion, Engine, Request

__all__ = [
    "Router",
    "AffinityIndex",
    "QueueFullError",
    "RouterDraining",
    "DeadlineInfeasibleError",
    "NoHealthyReplicaError",
]

# Internal warm-up requests (prefix-cache migration) ride the normal
# dispatch path at a priority no user class should ever use: they fill
# idle capacity, never displace traffic, and are first to be shed.
_INTERNAL_PRIORITY = 1_000_000


class QueueFullError(RuntimeError):
    """Admission queue at ``queue_depth``: the request was REJECTED (never
    queued). Callers retry with backoff or shed load — the visible
    backpressure signal (`stats["rejects"]` counts these)."""


class DeadlineInfeasibleError(QueueFullError):
    """The request's deadline cannot be met given the observed service
    time and the queue ahead of it — rejected at admission so the caller
    can fail over instead of burning fleet time on a doomed request.
    Subclasses `QueueFullError` so overload-aware callers (retry with
    backoff / shed) handle both the same way."""


class RouterDraining(RuntimeError):
    """The router is draining (preemption or `Router.drain`): no new
    admissions; everything already accepted still completes."""


class NoHealthyReplicaError(RuntimeError):
    """Every replica is quarantined while requests are still outstanding —
    the fleet cannot make progress."""


class AffinityIndex:
    """Host-side index of recently dispatched prompts per replica.

    The router can't see inside each replica's device-resident prefix
    cache, so it keeps its own LRU record of (prompt, replica) pairs at
    dispatch time and scores candidates by longest shared prefix — the
    same signal the per-engine radix tree keys on, approximated at the
    fleet level. Bounded at ``cap`` entries (drop-oldest) so lookup cost
    stays a few hundred short vector compares per admission."""

    def __init__(self, cap: int = 512) -> None:
        self.cap = cap
        self._entries: deque[tuple[np.ndarray, int]] = deque()

    def insert(self, prompt: np.ndarray, replica: int) -> None:
        self._entries.append((np.asarray(prompt, np.int32), int(replica)))
        while len(self._entries) > self.cap:
            self._entries.popleft()

    def remove_replica(self, replica: int) -> None:
        """Forget a quarantined replica — its cached KV is unreachable, so
        steering traffic at it would be pure imbalance."""
        self._entries = deque((p, r) for p, r in self._entries if r != replica)

    def retarget(self, replica: int, target: int) -> int:
        """Re-point a quarantined replica's entries at ``target`` — the
        survivor its hot prefixes were migrated to — so the prefix
        families keep steering at warm KV instead of being forgotten.
        Returns how many entries moved."""
        moved = 0
        for i, (p, r) in enumerate(self._entries):
            if r == replica:
                self._entries[i] = (p, int(target))
                moved += 1
        return moved

    def best(self, prompt: np.ndarray) -> dict[int, int]:
        """Longest shared-prefix length per replica for ``prompt``."""
        prompt = np.asarray(prompt, np.int32)
        best: dict[int, int] = {}
        for toks, r in self._entries:
            n = min(len(toks), len(prompt))
            if n <= best.get(r, 0):
                continue  # can't beat this replica's current best
            neq = np.nonzero(toks[:n] != prompt[:n])[0]
            m = int(neq[0]) if len(neq) else n
            if m > best.get(r, 0):
                best[r] = m
        return best


class _Ticket:
    """Router-side bookkeeping for one accepted request."""

    __slots__ = (
        "req", "user_stream", "submitted_at", "deadline", "replica",
        "attempts", "generation", "streamed", "cancel_sent", "done",
        "seq", "internal",
    )

    def __init__(self, req: Request, seq: int = 0) -> None:
        self.req = req
        self.user_stream = req.stream
        self.submitted_at = time.perf_counter()
        self.deadline = (
            self.submitted_at + req.timeout if req.timeout is not None else None
        )
        self.replica: int | None = None
        self.attempts = 0
        # Bumped at every (re)dispatch and at resolution: a stream callback
        # from a superseded attempt (a quarantined replica's thread still
        # unwinding) sees a stale generation and drops itself.
        self.generation = 0
        self.streamed = 0  # tokens delivered to the user stream so far
        self.cancel_sent = False
        self.done = False
        # Admission sequence number: the EDF tiebreak (FIFO within a
        # class) — retries keep their original seq so age order survives
        # a re-dispatch, exactly like the old appendleft requeue.
        self.seq = seq
        # Internal tickets (prefix-cache migration warm-ups) bypass the
        # admission bound and are invisible to callers: no completion
        # surfaced, no latency observed, not counted as submissions.
        self.internal = False


class _Replica:
    """One engine + (in threads mode) its dedicated driver thread.

    The engine is single-threaded by contract; ALL interaction crosses a
    locked inbox of ``("submit", Request)`` / ``("cancel", rid)`` /
    ``("stop",)`` messages, applied between `step` calls by `pump` — which
    is the same code path the thread loop and the inline mode run, so the
    two modes differ only in who calls it."""

    def __init__(
        self,
        id: int,
        engine: Engine,
        router: "Router",
        *,
        watchdog_secs: float | None = None,
    ) -> None:
        self.id = id
        self.engine = engine
        self.router = router
        self.inbox: deque = deque()
        self.inbox_lock = threading.Lock()
        self.wake = threading.Event()
        self.thread: threading.Thread | None = None
        self.dead = False  # router-side quarantine flag (router thread only)
        self.error: str | None = None
        self.wedged = threading.Event()
        self.inflight: set[int] = set()  # rids dispatched here (router thread)
        self.dispatched = 0
        self.completed = 0
        self._stopping = False
        self._watchdog_secs = watchdog_secs
        # Re-admission state (router thread only): when the router has
        # readmit enabled, a quarantine schedules a probe at ``probe_at``;
        # a readmitted replica serves under probation (dispatch capped to
        # one in-flight) until ``probation_left`` clean completions.
        self.quarantines = 0
        self.probe_at: float | None = None
        self.probation_left = 0
        self.rebuilds = 0
        self.watchdog: resilience.Watchdog | None = None
        if watchdog_secs:
            # The abort seam turns the watchdog's process-kill into a
            # per-replica quarantine: the fleet survives one wedged engine.
            self.watchdog = resilience.Watchdog(
                watchdog_secs,
                first_deadline_secs=watchdog_secs * 10.0,  # compile headroom
                abort=self._wedge,
            )

    def _wedge(self) -> None:
        self.wedged.set()
        self.router._results.put((
            "down", self.id,
            f"wedged: step exceeded its {self.watchdog.deadline:.1f}s "
            "deadline (ATX_SERVE_REPLICA_WATCHDOG_SECS)",
        ))

    def send(self, msg: tuple) -> None:
        with self.inbox_lock:
            self.inbox.append(msg)
        self.wake.set()

    def pump(self) -> list[Completion]:
        """Apply queued messages, then run at most one engine step. Runs on
        the replica thread (threads mode) or the caller (inline mode)."""
        out: list[Completion] = []
        with self.inbox_lock:
            msgs = list(self.inbox)
            self.inbox.clear()
        for msg in msgs:
            if msg[0] == "submit":
                self.engine.submit_request(msg[1])
            elif msg[0] == "cancel":
                c = self.engine.cancel(msg[1])
                if c is not None:
                    out.append(c)
            elif msg[0] == "stop":
                self._stopping = True
        if self.engine.busy:
            if self.watchdog is not None:
                self.watchdog.arm()
            resilience.fault_point(f"router.replica{self.id}.step")
            out.extend(self.engine.step())
            if self.watchdog is not None:
                self.watchdog.disarm()
        return out

    def start(self) -> None:
        self.thread = threading.Thread(
            target=self._run, name=f"atx-replica{self.id}", daemon=True
        )
        self.thread.start()

    def _run(self) -> None:
        try:
            while True:
                for c in self.pump():
                    self.router._results.put(("done", self.id, c))
                if self._stopping and not self.engine.busy and not self.inbox:
                    return
                if not self.engine.busy and not self.inbox:
                    self.wake.wait(0.002)
                    self.wake.clear()
        except BaseException as e:  # any replica death is a quarantine event
            self.router._results.put(
                ("down", self.id, f"{type(e).__name__}: {e}")
            )
        finally:
            if self.watchdog is not None:
                self.watchdog.stop()

    def respawn(self) -> None:
        """Bring a quarantined replica back after a successful probe:
        fresh liveness state, fresh watchdog, and (threads mode) a fresh
        driver thread. The old thread is guaranteed gone or permanently
        parked (a wedged replica is only respawned after an engine
        rebuild), so single-thread engine ownership is preserved."""
        if self.watchdog is not None:
            self.watchdog.stop()
        with self.inbox_lock:
            self.inbox.clear()
        self.dead = False
        self.error = None
        self.wedged = threading.Event()
        self.wake = threading.Event()
        self._stopping = False
        self.probe_at = None
        self.watchdog = None
        if self._watchdog_secs:
            self.watchdog = resilience.Watchdog(
                self._watchdog_secs,
                first_deadline_secs=self._watchdog_secs * 10.0,
                abort=self._wedge,
            )
        if self.router.threads:
            self.start()


def _pct(xs: list[float], q: float) -> float | None:
    if not xs:
        return None
    s = sorted(xs)
    return round(s[min(len(s) - 1, int(q * len(s)))], 2)


def _hq(hist: Any, q: float, labels: dict) -> float | None:
    """Histogram-estimated percentile, rounded like the old exact `_pct`
    (None until data) so `metrics()` keeps its field contract."""
    value = hist.quantile(q, **labels)
    return None if value is None else round(value, 2)


class Router:
    """Bounded-admission front-end over N `Engine` replicas (module
    docstring has the full design). Typical use::

        with Router([engine_a, engine_b]) as router:
            completions = router.serve(trace, realtime=True)

    or incrementally: `submit`/`submit_request` -> `poll` (one tick) ->
    `pop_completions`, with `join` to run everything outstanding down.
    All Router methods must be called from ONE thread (the replicas have
    their own); completions come back in finish order with
    ``submitted_at`` rewritten to router admission time, so TTFT/e2e
    latencies include queueing delay."""

    def __init__(
        self,
        engines: Sequence[Engine],
        *,
        queue_depth: int | None = None,
        affinity: str = "prefix",
        affinity_min_tokens: int | None = None,
        affinity_max_imbalance: int | None = None,
        max_retries: int = 2,
        watchdog_secs: float | None = None,
        threads: bool = True,
        scheduling: str = "edf",
        readmit_secs: float | None = None,
        probation_completions: int | None = None,
        retry_budget: int | None = None,
        retry_refill_per_sec: float | None = None,
        migrate_prefixes: int | None = None,
        engine_factory: Callable[[], Engine] | None = None,
    ) -> None:
        engines = list(engines)
        if not engines:
            raise ValueError("Router needs at least one engine replica")
        ref = engines[0]
        for i, e in enumerate(engines[1:], start=1):
            if e.buckets != ref.buckets or e.max_len != ref.max_len:
                raise ValueError(
                    "replicas must be identically configured (admission "
                    "validates against replica 0 and failover replays on any "
                    f"healthy replica): replica {i} has buckets={e.buckets} "
                    f"max_len={e.max_len}, replica 0 has buckets="
                    f"{ref.buckets} max_len={ref.max_len}"
                )
        self._ref = ref
        self.threads = threads
        if queue_depth is None:
            queue_depth = get_int_from_env(
                ("ATX_SERVE_QUEUE_DEPTH",), 4 * sum(e.n_slots for e in engines)
            )
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self.queue_depth = queue_depth
        if affinity not in ("prefix", "least-loaded"):
            raise ValueError(
                f"affinity must be 'prefix' or 'least-loaded', got {affinity!r}"
            )
        self.affinity = affinity
        self.affinity_min_tokens = (
            affinity_min_tokens
            if affinity_min_tokens is not None
            else ref.buckets[0]
        )
        self.affinity_max_imbalance = (
            affinity_max_imbalance
            if affinity_max_imbalance is not None
            else max(1, ref.n_slots - 1)
        )
        self.max_retries = max_retries
        if scheduling not in ("edf", "fifo"):
            raise ValueError(
                f"scheduling must be 'edf' or 'fifo', got {scheduling!r}"
            )
        self.scheduling = scheduling
        # Re-admission: None/<=0 disables (a quarantined replica stays
        # dead forever — the pre-PR-14 behaviour, and what the fail-stop
        # tests rely on). Env: ATX_SERVE_READMIT_SECS.
        if readmit_secs is None:
            raw = os.environ.get("ATX_SERVE_READMIT_SECS", "")
            try:
                readmit_secs = float(raw) if raw else None
            except ValueError:
                readmit_secs = None
        if readmit_secs is not None and readmit_secs <= 0:
            readmit_secs = None
        self.readmit_secs = readmit_secs
        self.probation_completions = (
            probation_completions
            if probation_completions is not None
            else get_int_from_env(("ATX_SERVE_PROBATION_COMPLETIONS",), 3)
        )
        # Fleet-wide failover retry budget (token bucket). Capacity < 0
        # means unlimited (the pre-PR-14 behaviour).
        self.retry_budget = (
            retry_budget
            if retry_budget is not None
            else get_int_from_env(("ATX_SERVE_RETRY_BUDGET",), 16)
        )
        if retry_refill_per_sec is None:
            raw = os.environ.get("ATX_SERVE_RETRY_REFILL_PER_SEC", "")
            try:
                retry_refill_per_sec = float(raw) if raw else 1.0
            except ValueError:
                retry_refill_per_sec = 1.0
        self.retry_refill_per_sec = max(0.0, retry_refill_per_sec)
        self._retry_tokens = float(max(self.retry_budget, 0))
        self._retry_refill_at = time.perf_counter()
        self.migrate_prefixes = (
            migrate_prefixes
            if migrate_prefixes is not None
            else get_int_from_env(("ATX_SERVE_MIGRATE_PREFIXES",), 4)
        )
        self.engine_factory = engine_factory
        # Probe-backoff jitter only perturbs WHEN a probe runs, never what
        # any request computes, so a fixed seed keeps runs comparable.
        self._rng = random.Random(0xA7C)
        # Canary recorded from real traffic: (prompt, seed, ref_tokens, k).
        # A probe replays it on the quarantined engine and the first k
        # tokens must match bit-for-bit (greedy determinism).
        self._canary: tuple[np.ndarray, int, np.ndarray, int] | None = None
        if watchdog_secs is None:
            raw = os.environ.get("ATX_SERVE_REPLICA_WATCHDOG_SECS", "")
            try:
                watchdog_secs = float(raw) if raw else None
            except ValueError:
                watchdog_secs = None
        if watchdog_secs is not None and watchdog_secs <= 0:
            watchdog_secs = None
        self.replicas = [
            # Inline mode gets no watchdog: a wedged step stalls the caller
            # itself, so there is nobody left to act on the firing.
            _Replica(i, e, self, watchdog_secs=watchdog_secs if threads else None)
            for i, e in enumerate(engines)
        ]
        self._affinity = AffinityIndex()
        self._results: queue.Queue = queue.Queue()
        self._pending: deque[_Ticket] = deque()  # accepted, not yet dispatched
        self._tickets: dict[int, _Ticket] = {}
        self._completions: list[Completion] = []
        self._next_rid = 0
        self._next_seq = 0
        self._outstanding = 0
        self._draining = False
        self.drain_reason: str | None = None
        self._classes_seen: set[int] = set()
        self._shed_by_class: dict[int, int] = {}
        self._migrated_prefixes = 0
        # Latency recording + counters live on the telemetry registry
        # (docs/observability.md): fixed-bucket histograms replace the old
        # unbounded p50/p99 lists, and `metrics()` reads its percentiles
        # from the same series the `/metrics` endpoint exports.
        self._tel_labels = {"router": _telemetry.views._next_instance()}
        _labels = ("router",)
        self._h_ttft = _telemetry.histogram(
            "router_ttft_ms", "admission -> first token", labels=_labels
        )
        self._h_e2e = _telemetry.histogram(
            "router_e2e_ms", "admission -> completion", labels=_labels
        )
        self._h_queue_wait = _telemetry.histogram(
            "router_queue_wait_ms", "admission -> replica dispatch",
            labels=_labels,
        )
        self._g_queue = _telemetry.gauge(
            "router_queue_depth", "pending admissions", labels=_labels
        )
        # Self-healing / overload series (ISSUE names keep the Prometheus
        # `_total` suffix convention for monotone counters).
        self._c_shed = _telemetry.counter(
            "router_shed_total",
            "requests evicted from the admission queue under overload",
            labels=("router", "class"),
        )
        self._c_readmit = _telemetry.counter(
            "router_readmissions_total",
            "quarantined replicas probed healthy and re-admitted",
            labels=_labels,
        )
        self._c_probe_fail = _telemetry.counter(
            "router_probe_failures_total",
            "re-admission probes that failed (canary mismatch or error)",
            labels=_labels,
        )
        self._c_retry_exhausted = _telemetry.counter(
            "router_retry_budget_exhausted_total",
            "failover retries denied by the fleet retry budget",
            labels=_labels,
        )
        self._c_infeasible = _telemetry.counter(
            "router_deadline_infeasible_total",
            "requests rejected at admission: deadline unmeetable",
            labels=_labels,
        )
        self._c_migrated = _telemetry.counter(
            "router_migrated_prefixes_total",
            "hot prefix-cache entries re-seeded into survivors on quarantine",
            labels=_labels,
        )
        self._h_class_ttft = _telemetry.histogram(
            "router_class_ttft_ms", "admission -> first token, per class",
            labels=("router", "class"),
        )
        self._h_class_e2e = _telemetry.histogram(
            "router_class_e2e_ms", "admission -> completion, per class",
            labels=("router", "class"),
        )
        # Request-scoped tracing flag, snapshotted once (the engines do
        # the same): admission/dispatch/stream spans cost zero when off.
        self._trace = _flight.trace_requests_enabled()
        self.stats = _telemetry.StatsView(
            "router",
            (
                "submitted",
                "rejects",
                "drain_rejected",
                "dispatched",
                "completed",
                "retries",
                "cancelled",
                "failed",
                "replicas_lost",
                "queue_peak",
            ),
            label="router",
            instance=self._tel_labels["router"],
            gauges=("queue_peak",),
        )
        if threads:
            for r in self.replicas:
                r.start()

    # ------------------------------------------------------------- submit
    def submit(
        self,
        prompt: Any,
        max_new_tokens: int | None = None,
        *,
        seed: int = 0,
        stream: Callable[[int, int, str | None], None] | None = None,
        arrival: float | None = None,
        stop_sequences: Sequence[Sequence[int]] | None = None,
        timeout: float | None = None,
        priority: int = 1,
    ) -> int:
        """Admit one request; returns its fleet-global request id. Raises
        `QueueFullError` when the admission queue is at ``queue_depth``
        (unless this request outranks a queued one, which is then shed),
        `DeadlineInfeasibleError` when ``timeout`` is unmeetable, and
        `RouterDraining` once drain has started. ``timeout`` is the
        request's deadline in seconds from now; ``priority`` its class
        (lower = more important)."""
        return self.submit_request(
            Request(
                prompt=np.asarray(prompt, np.int32).reshape(-1),
                max_new_tokens=max_new_tokens,
                seed=seed,
                arrival=arrival,
                stream=stream,
                stop_sequences=stop_sequences,
                timeout=timeout,
                priority=priority,
            )
        )

    def _public_pending(self) -> int:
        """Queued tickets that count against ``queue_depth`` (internal
        migration warm-ups don't — they must never cause user rejects)."""
        return sum(1 for t in self._pending if not t.internal)

    def submit_request(self, req: Request) -> int:
        if self._draining:
            self.stats["drain_rejected"] += 1
            if self._trace:
                _flight.record_span(
                    "admission", rid=req.rid, decision="drain_rejected",
                    cause=str(self.drain_reason),
                )
            raise RouterDraining(
                f"router is draining ({self.drain_reason}): "
                "not admitting new requests"
            )
        if self._public_pending() >= self.queue_depth:
            # Priority shedding (EDF mode): an arrival that strictly
            # outranks the least important queued class evicts that
            # class's newest ticket instead of being rejected.
            if not (self.scheduling == "edf" and self._shed_for(req)):
                self.stats["rejects"] += 1
                if self._trace:
                    _flight.record_span(
                        "admission", rid=req.rid, decision="rejected",
                        cause="queue_full", pending=self._public_pending(),
                    )
                raise QueueFullError(
                    f"admission queue full ({self._public_pending()}/"
                    f"{self.queue_depth} pending; ATX_SERVE_QUEUE_DEPTH raises "
                    "the bound) — retry with backoff"
                )
        # Validate at the front door (engine capacity, bucket-padded plan
        # fit) so a bad request raises HERE, not inside a replica thread.
        self._ref.validate_request(req)
        if self.scheduling == "edf" and self._deadline_infeasible(req):
            self._c_infeasible.inc(**self._tel_labels)
            if self._trace:
                _flight.record_span(
                    "admission", rid=req.rid, decision="rejected",
                    cause="deadline_infeasible",
                )
            raise DeadlineInfeasibleError(
                f"deadline {req.timeout:.3f}s is infeasible given observed "
                "service time and the queue ahead — rejected at admission"
            )
        if req.rid < 0:
            req.rid = self._next_rid
        self._next_rid = max(self._next_rid, req.rid) + 1
        t = _Ticket(req, seq=self._next_seq)
        self._next_seq += 1
        self._tickets[req.rid] = t
        self._pending.append(t)
        self._outstanding += 1
        self._classes_seen.add(int(req.priority))
        if self._trace:
            # The EDF key the dispatcher will sort this ticket by — the
            # scheduling decision, captured at the moment it was made.
            _flight.record_span(
                "admission", rid=req.rid, decision="accepted",
                priority=int(req.priority),
                deadline_ms=(
                    round(req.timeout * 1e3, 3)
                    if req.timeout is not None else None
                ),
                seq=t.seq,
            )
        self.stats["submitted"] += 1
        self.stats["queue_peak"] = max(
            self.stats["queue_peak"], self._public_pending()
        )
        self._g_queue.set(self._public_pending(), **self._tel_labels)
        return req.rid

    def _shed_for(self, req: Request) -> bool:
        """Make room for ``req`` by shedding the newest queued ticket of
        the least important class, IF ``req`` strictly outranks it.
        (Internal warm-ups don't count against the bound, so shedding
        them can't make room — only real tickets are candidates.)"""
        victims = [t for t in self._pending if not t.done and not t.internal]
        if not victims:
            return False
        worst = max(t.req.priority for t in victims)
        if int(req.priority) >= worst:
            return False
        victim = max(
            (t for t in victims if t.req.priority == worst),
            key=lambda t: t.seq,
        )
        self._pending.remove(victim)
        cls = int(victim.req.priority)
        if self._trace:
            _flight.record_span(
                "admission", rid=victim.req.rid, decision="shed",
                cause=f"displaced_by_class_{int(req.priority)}",
            )
        self._c_shed.inc(**{**self._tel_labels, "class": str(cls)})
        self._shed_by_class[cls] = self._shed_by_class.get(cls, 0) + 1
        c = self._local_cancel_completion(victim)
        c.finish_reason = "shed"
        self._resolve(victim, c)
        return True

    def _deadline_infeasible(self, req: Request) -> bool:
        """Admission-time feasibility: estimated finish = now + observed
        service time x (1 + work ahead / fleet slots). Conservative only
        once the e2e histogram has >= 5 samples (a cold router admits
        everything — there is nothing to estimate from)."""
        if req.timeout is None:
            return False
        labels = self._tel_labels
        if self._h_e2e.count(**labels) < 5:
            return False
        e2e = self._h_e2e.mean(**labels)
        if not e2e:
            return False
        queue_wait = self._h_queue_wait.mean(**labels) or 0.0
        service_ms = e2e - queue_wait
        if service_ms <= 0.0:
            service_ms = e2e
        slots = sum(
            r.engine.n_slots for r in self.replicas if not r.dead
        ) or 1
        key = (int(req.priority), time.perf_counter() + req.timeout, self._next_seq)
        ahead = sum(
            1
            for t in self._pending
            if not t.done and self._order_key(t) <= key
        )
        est_ms = service_ms * (1.0 + ahead / slots)
        return est_ms > req.timeout * 1000.0

    def _internal_submit(self, req: Request) -> None:
        """Queue a router-internal warm-up request (prefix migration):
        bypasses the admission bound and drain, surfaces no completion,
        but counts against ``_outstanding`` so `join` finishes it."""
        self._ref.validate_request(req)
        req.rid = self._next_rid
        self._next_rid += 1
        t = _Ticket(req, seq=self._next_seq)
        self._next_seq += 1
        t.internal = True
        self._tickets[req.rid] = t
        self._pending.append(t)
        self._outstanding += 1

    # ------------------------------------------------------------- cancel
    def cancel(self, rid: int) -> bool:
        """Cancel an accepted request (queued or dispatched). The
        ``finish_reason="cancelled"`` completion surfaces through the
        normal `poll`/`join` path; returns False for unknown/finished
        rids."""
        t = self._tickets.get(rid)
        if t is None or t.done:
            return False
        self._cancel_ticket(t)
        return True

    def _cancel_ticket(self, t: _Ticket) -> None:
        if t.replica is None:
            self._pending.remove(t)
            self._resolve(t, self._local_cancel_completion(t))
        elif not t.cancel_sent:
            t.cancel_sent = True
            self.replicas[t.replica].send(("cancel", t.req.rid))

    def _local_cancel_completion(self, t: _Ticket) -> Completion:
        return self._ref._cancelled_completion(
            t.req,
            np.full(
                (t.req.max_new_tokens,), self._ref.config.pad_token_id, np.int32
            ),
            0,
            0.0,
        )

    # -------------------------------------------------------------- drain
    def drain(self, reason: str = "manual") -> None:
        """Flip to drain mode: stop admitting (`RouterDraining`), let
        everything already accepted finish. `poll` calls this with
        ``reason="preemption"`` when `resilience.preemption_requested()`
        goes high; `atx serve` then exits 75 after `join` so the elastic
        launcher resumes the process."""
        if not self._draining:
            self._draining = True
            self.drain_reason = reason

    @property
    def draining(self) -> bool:
        return self._draining

    # --------------------------------------------------------------- tick
    def poll(self, timeout: float = 0.0) -> None:
        """One router tick: poll the preemption flag, quarantine dead
        replicas, expire deadlines, dispatch what fits, ingest results
        (blocking up to ``timeout`` seconds for the first one in threads
        mode)."""
        if not self._draining and resilience.preemption_requested():
            self.drain("preemption")
        if self.threads:
            self._check_threads()
        self._refill_retry_budget()
        self._maybe_readmit()
        self._check_deadlines()
        self._dispatch()
        if self.threads:
            self._pump_results(timeout)
        else:
            worked = self._pump_inline()
            if not worked and timeout > 0:
                time.sleep(timeout)
        # Quarantine/ingest may have freed slots or requeued orphans.
        self._dispatch()

    def _check_threads(self) -> None:
        for r in self.replicas:
            if (
                not r.dead
                and not r._stopping
                and r.thread is not None
                and not r.thread.is_alive()
            ):
                self._quarantine(r.id, r.error or "replica thread exited")

    def _check_deadlines(self) -> None:
        now = time.perf_counter()
        for t in list(self._pending):
            if t.deadline is not None and now >= t.deadline:
                self._pending.remove(t)
                self._resolve(t, self._local_cancel_completion(t))
        for r in self.replicas:
            if r.dead:
                continue
            for rid in list(r.inflight):
                t = self._tickets.get(rid)
                if (
                    t is not None
                    and not t.done
                    and not t.cancel_sent
                    and t.deadline is not None
                    and now >= t.deadline
                ):
                    t.cancel_sent = True
                    r.send(("cancel", rid))

    def _order_key(self, t: _Ticket) -> tuple:
        """EDF dispatch order: priority class first (lower = more
        important), earliest absolute deadline within a class (no deadline
        sorts last), admission seq as the FIFO tiebreak."""
        return (
            int(t.req.priority),
            t.deadline if t.deadline is not None else float("inf"),
            t.seq,
        )

    def _dispatch(self) -> None:
        # EDF: the best-ranked pending ticket dispatches first; FIFO mode
        # keeps the old strict head-only order. Either way a ticket that
        # can't place (no replica capacity) stops dispatch — capacity is
        # request-agnostic, so nothing behind it could place either.
        while self._pending:
            if self.scheduling == "edf":
                t = min(self._pending, key=self._order_key)
            else:
                t = self._pending[0]
            r = self._pick_replica(t.req)
            if r is None:
                return
            self._pending.remove(t)
            self._dispatch_to(t, r)

    def _replica_capacity(self, r: _Replica) -> int:
        # Probation: a freshly re-admitted replica gets one request at a
        # time until it proves itself with clean completions.
        return 1 if r.probation_left > 0 else r.engine.n_slots

    def _pick_replica(self, req: Request) -> _Replica | None:
        cands = [
            r
            for r in self.replicas
            if not r.dead and len(r.inflight) < self._replica_capacity(r)
        ]
        if not cands:
            return None
        least = min(cands, key=lambda r: (len(r.inflight), r.id))
        if self.affinity == "prefix":
            matches = self._affinity.best(req.prompt)
            best, best_m = None, 0
            for r in cands:
                m = matches.get(r.id, 0)
                if m >= self.affinity_min_tokens and m > best_m:
                    best, best_m = r, m
            if (
                best is not None
                and len(best.inflight) - len(least.inflight)
                <= self.affinity_max_imbalance
            ):
                return best
        return least

    def _dispatch_to(self, t: _Ticket, r: _Replica) -> None:
        t.replica = r.id
        t.attempts += 1
        t.generation += 1
        t.cancel_sent = False
        t.req.stream = self._make_stream(t)
        if self._trace and not t.internal:
            # The engine's phase_queue span starts here, not at engine
            # dispatch, so router queue wait lands in the attribution.
            t.req.router_submitted_at = t.submitted_at  # type: ignore[attr-defined]
        r.inflight.add(t.req.rid)
        r.dispatched += 1
        if not t.internal:
            self.stats["dispatched"] += 1
            if self._trace:
                # attempts > 1 marks a failover re-dispatch: a retried
                # request's trace shows BOTH the failed and replayed
                # dispatch (exactly-once tests key on this).
                _flight.record_span(
                    "dispatch", rid=t.req.rid, replica=r.id,
                    attempt=t.attempts, retry=t.attempts > 1,
                )
            self._h_queue_wait.observe(
                (time.perf_counter() - t.submitted_at) * 1e3, **self._tel_labels
            )
        self._g_queue.set(self._public_pending(), **self._tel_labels)
        if self.affinity == "prefix":
            # Record at dispatch (not completion) so a burst of same-prefix
            # requests steers together from the second one on.
            self._affinity.insert(t.req.prompt, r.id)
        r.send(("submit", t.req))

    def _make_stream(
        self, t: _Ticket
    ) -> Callable[[int, int, str | None], None]:
        """Exactly-once stream delivery across retries: greedy determinism
        means a retried attempt replays the identical token sequence, so
        the wrapper skips the ``t.streamed`` tokens the dead attempt
        already delivered and drops callbacks from superseded attempts
        (generation mismatch) entirely."""
        gen = t.generation
        count = 0
        trace = self._trace and not t.internal

        def stream(rid: int, tok: int, text: str | None) -> None:
            nonlocal count
            count += 1
            if t.generation != gen:
                return  # superseded attempt still unwinding
            if count > t.streamed:
                t.streamed = count
                if trace:
                    # Recorded only on actual delivery — a replayed
                    # attempt's deduplicated tokens leave no span, so a
                    # trace counts each streamed token exactly once.
                    _flight.record_span("stream", rid=rid, index=count)
                if t.user_stream is not None:
                    t.user_stream(rid, tok, text)

        return stream

    def _pump_results(self, timeout: float) -> None:
        block = timeout
        while True:
            try:
                kind, rid, payload = (
                    self._results.get(timeout=block)
                    if block > 0
                    else self._results.get_nowait()
                )
            except queue.Empty:
                return
            block = 0.0
            if kind == "done":
                self._ingest(rid, payload)
            else:
                self._quarantine(rid, payload)

    def _pump_inline(self) -> bool:
        worked = False
        for r in self.replicas:  # fixed order: deterministic replay
            if r.dead:
                continue
            try:
                completions = r.pump()
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                self._quarantine(r.id, f"{type(e).__name__}: {e}")
                worked = True
                continue
            for c in completions:
                self._ingest(r.id, c)
            worked = worked or bool(completions) or r.engine.busy
        return worked

    def _ingest(self, replica_id: int, c: Completion) -> None:
        t = self._tickets.get(c.rid)
        if t is None or t.done or t.replica != replica_id:
            return  # stale: resolved elsewhere or reassigned after quarantine
        r = self.replicas[replica_id]
        r.completed += 1
        if r.probation_left > 0 and c.finish_reason not in ("cancelled", "failed"):
            r.probation_left -= 1  # one clean completion toward full share
        self._resolve(t, c)

    def _resolve(self, t: _Ticket, c: Completion) -> None:
        t.done = True
        t.generation += 1  # silence any attempt still unwinding
        if t.replica is not None:
            self.replicas[t.replica].inflight.discard(t.req.rid)
            t.replica = None
        if t.internal:
            # Migration warm-up: no caller to surface it to. A successful
            # prefill means the survivor's radix cache now holds the path.
            if c.finish_reason not in ("cancelled", "failed", "shed"):
                self._migrated_prefixes += 1
                self._c_migrated.inc(**self._tel_labels)
            self._outstanding -= 1
            return
        # Router admission time, so latency includes queueing delay.
        c.submitted_at = t.submitted_at
        if c.finish_reason == "cancelled":
            self.stats["cancelled"] += 1
        if c.finish_reason not in ("cancelled", "failed", "shed"):
            cls_labels = {
                **self._tel_labels, "class": str(int(t.req.priority)),
            }
            if c.first_token_at:
                ttft_ms = (c.first_token_at - t.submitted_at) * 1000.0
                self._h_ttft.observe(ttft_ms, **self._tel_labels)
                self._h_class_ttft.observe(ttft_ms, **cls_labels)
            e2e_ms = (c.finished_at - t.submitted_at) * 1000.0
            self._h_e2e.observe(e2e_ms, **self._tel_labels)
            self._h_class_e2e.observe(e2e_ms, **cls_labels)
            if (
                self._canary is None
                and c.finish_reason in ("eos", "length")
                and c.n_new > 0
                and t.req.stop_sequences is None
            ):
                # Record the probe canary from real traffic: replaying
                # this prompt/seed must reproduce these first k tokens on
                # ANY healthy replica (greedy determinism).
                k = min(4, int(c.n_new))
                self._canary = (
                    t.req.prompt.copy(), int(t.req.seed),
                    c.tokens[:k].copy(), k,
                )
        if self._trace:
            _flight.record_span(
                "complete", rid=c.rid, t0=t.submitted_at, t1=c.finished_at,
                finish_reason=c.finish_reason, n_new=int(c.n_new),
                attempts=t.attempts,
            )
        self.stats["completed"] += 1
        self._outstanding -= 1
        self._completions.append(c)

    def _quarantine(self, replica_id: int, reason: str) -> None:
        r = self.replicas[replica_id]
        if r.dead:
            return
        r.dead = True
        r.error = reason
        self.stats["replicas_lost"] += 1
        if self._trace:
            _flight.record_span(
                "quarantine", rid=-1, replica=replica_id, cause=reason,
                inflight=len(r.inflight),
            )
        # Black-box dump: the flight recorder's last-N spans at the moment
        # a replica died (no-op unless ATX_POSTMORTEM_DIR is set).
        _flight.dump_postmortem(
            f"quarantine_replica{replica_id}",
            extra={"replica": replica_id, "reason": reason,
                   "inflight": sorted(r.inflight)},
        )
        # Prefix-cache migration: re-seed the dead replica's hottest
        # committed radix paths into a survivor (host token ids only — the
        # warm-up PREFILLS there; KV bytes never cross devices) and
        # re-point its affinity entries at that survivor so the families
        # keep steering at warm KV.
        survivors = [x for x in self.replicas if not x.dead]
        migrated = 0
        if survivors and not self._draining:
            migrated = self._migrate_prefix_cache(r)
        if survivors and migrated:
            target = min(survivors, key=lambda x: (len(x.inflight), x.id))
            self._affinity.retarget(replica_id, target.id)
        else:
            self._affinity.remove_replica(replica_id)
        orphans = [
            self._tickets[rid]
            for rid in sorted(r.inflight)
            if rid in self._tickets
        ]
        r.inflight.clear()
        # Retries jump the queue (appendleft, original order preserved):
        # they already waited once, and FIFO age order stays intact. (In
        # EDF mode the kept original seq achieves the same thing.) Each
        # retry costs a token from the fleet-wide budget — a sick fleet
        # runs out and degrades to visible ``failed`` completions instead
        # of a retry storm.
        for t in reversed(orphans):
            if t.done:
                continue
            t.replica = None
            t.generation += 1
            if t.attempts > self.max_retries:
                self.stats["failed"] += 1
                fc = self._local_cancel_completion(t)
                fc.finish_reason = "failed"
                self._resolve(t, fc)
                continue
            if self.retry_budget >= 0:
                if self._retry_tokens < 1.0:
                    self._c_retry_exhausted.inc(**self._tel_labels)
                    self.stats["failed"] += 1
                    fc = self._local_cancel_completion(t)
                    fc.finish_reason = "failed"
                    self._resolve(t, fc)
                    continue
                self._retry_tokens -= 1.0
            self.stats["retries"] += 1
            self._pending.appendleft(t)
        if self.readmit_secs is not None:
            self._schedule_probe(r)

    def _migrate_prefix_cache(self, r: _Replica) -> int:
        """Queue internal warm-up prefills of the dead replica's hottest
        cached prefixes. Best-effort: any failure just skips the entry."""
        if self.migrate_prefixes <= 0 or r.engine.prefix_cache is None:
            return 0
        try:
            paths = r.engine.prefix_cache.hot_entries(self.migrate_prefixes)
        except Exception:
            return 0
        n = 0
        for toks in paths:
            if len(toks) < 1 or len(toks) + 1 > self._ref.max_len:
                continue
            try:
                self._internal_submit(
                    Request(
                        prompt=np.asarray(toks, np.int32),
                        max_new_tokens=1,
                        seed=0,
                        priority=_INTERNAL_PRIORITY,
                    )
                )
            except ValueError:
                continue  # e.g. bucket-padded plan doesn't fit — skip
            n += 1
        return n

    # --------------------------------------------------- retry budget
    def _refill_retry_budget(self) -> None:
        now = time.perf_counter()
        if self.retry_budget < 0:
            self._retry_refill_at = now
            return
        dt = now - self._retry_refill_at
        self._retry_refill_at = now
        self._retry_tokens = min(
            float(self.retry_budget),
            self._retry_tokens + dt * self.retry_refill_per_sec,
        )

    # ------------------------------------------------- probation & probe
    def _schedule_probe(self, r: _Replica) -> None:
        """Capped-exponential + jittered backoff before the next probe."""
        r.quarantines += 1
        base = self.readmit_secs * (2.0 ** (r.quarantines - 1))
        backoff = min(base, max(self.readmit_secs, 60.0))
        r.probe_at = time.perf_counter() + backoff * (
            1.0 + 0.1 * self._rng.random()
        )

    def _maybe_readmit(self) -> None:
        if self.readmit_secs is None:
            return
        now = time.perf_counter()
        for r in self.replicas:
            if r.dead and r.probe_at is not None and now >= r.probe_at:
                self._probe(r)

    def _probe(self, r: _Replica) -> None:
        """Health-check a quarantined replica from the router thread (the
        old driver thread is gone — it raised — or permanently parked — it
        wedged; either way nothing else touches the engine, so a direct
        canary run preserves single-thread ownership). On success the
        replica re-enters dispatch under probation; on failure the engine
        is rebuilt from ``engine_factory`` (when available) and re-probed
        once, else the backoff doubles."""
        r.probe_at = None
        ok = False
        if r.wedged.is_set():
            # A wedged engine may have been interrupted mid-step (an
            # arbitrary stall, not just the pre-step fault hook): its
            # device state is not trustworthy. Only a rebuild recovers it.
            if self.engine_factory is None:
                self._c_probe_fail.inc(**self._tel_labels)
                return  # permanently quarantined (join() may fail the fleet)
            self._rebuild(r)
            ok = self._canary_ok(r.engine)
            if not ok:
                self._c_probe_fail.inc(**self._tel_labels)
        else:
            ok = self._canary_ok(r.engine)
            if not ok:
                self._c_probe_fail.inc(**self._tel_labels)
                if self.engine_factory is not None:
                    self._rebuild(r)
                    ok = self._canary_ok(r.engine)
                    if not ok:
                        self._c_probe_fail.inc(**self._tel_labels)
        if ok:
            self._readmit(r)
        else:
            self._schedule_probe(r)

    def _rebuild(self, r: _Replica) -> None:
        r.engine = self.engine_factory()
        r.rebuilds += 1
        r.wedged = threading.Event()

    def _canary_ok(self, engine: Engine) -> bool:
        """Replay the recorded canary directly on ``engine``; healthy
        means bit-identical first-k tokens (or, before any traffic has
        recorded a canary, simply completing a synthetic request)."""
        try:
            engine.abort_inflight()  # whatever the fault left mid-flight
            if self._canary is not None:
                prompt, seed, ref, k = self._canary
                req = Request(
                    prompt=prompt.copy(), max_new_tokens=k, seed=seed
                )
            else:
                ref, k = None, 0
                req = Request(
                    prompt=np.asarray(
                        [int(self._ref.config.pad_token_id)], np.int32
                    ),
                    max_new_tokens=2,
                    seed=0,
                )
            rid = engine.submit_request(req)
            for _ in range(10_000):
                for c in engine.step():
                    if c.rid != rid:
                        continue  # stale orphan unwound by abort_inflight
                    if ref is not None:
                        return bool(np.array_equal(c.tokens[:k], ref))
                    return c.finish_reason in ("eos", "length", "stop")
                if not engine.busy:
                    return False
            engine.abort_inflight()  # step cap hit: leave the engine idle
            return False
        except Exception:
            try:
                engine.abort_inflight()
            except Exception:
                pass
            return False

    def _readmit(self, r: _Replica) -> None:
        r.respawn()
        r.probation_left = max(0, self.probation_completions)
        self._c_readmit.inc(**self._tel_labels)

    # ---------------------------------------------------------- lifecycle
    def pop_completions(self) -> list[Completion]:
        out, self._completions = self._completions, []
        return out

    def join(self, timeout: float | None = None) -> list[Completion]:
        """Run until every accepted request resolves; returns completions
        gathered since the last pop, in finish order. Raises
        `NoHealthyReplicaError` when the whole fleet is quarantined with
        work outstanding, `TimeoutError` past ``timeout`` seconds."""
        t0 = time.perf_counter()
        while self._outstanding > 0:
            if all(r.dead for r in self.replicas) and not any(
                # With re-admission enabled a fully-dead fleet can still
                # recover: keep polling while any probe is scheduled
                # (``timeout`` still bounds the wait).
                r.probe_at is not None
                for r in self.replicas
            ):
                errors = "; ".join(
                    f"replica {r.id}: {r.error}" for r in self.replicas
                )
                raise NoHealthyReplicaError(
                    f"{self._outstanding} request(s) outstanding with every "
                    f"replica quarantined ({errors})"
                )
            if timeout is not None and time.perf_counter() - t0 > timeout:
                raise TimeoutError(
                    f"router join timed out after {timeout}s with "
                    f"{self._outstanding} request(s) outstanding"
                )
            self.poll(0.002 if self.threads else 0.0)
        return self.pop_completions()

    def serve(
        self, requests: Iterable[Request], *, realtime: bool = False
    ) -> list[Completion]:
        """Drive a whole trace through the fleet (the `Engine.serve`
        contract at router level). ``realtime=True`` honours arrival
        offsets and REJECTS on a full queue (the latency-measuring mode);
        otherwise submission blocks on backpressure so every request is
        eventually admitted. Drain (preemption or `drain()`) stops
        admissions mid-trace — unsubmitted requests are counted in
        ``stats["drain_rejected"]`` — then everything accepted runs to
        completion, preserving the exit-75 resume contract."""
        reqs = sorted(requests, key=lambda r: (r.arrival or 0.0))
        t0 = time.perf_counter()
        i = 0
        while i < len(reqs):
            if self._draining:
                self.stats["drain_rejected"] += len(reqs) - i
                break
            if realtime and (reqs[i].arrival or 0.0) > time.perf_counter() - t0:
                self.poll(0.002)
                continue
            if not realtime and self._public_pending() >= self.queue_depth:
                self.poll(0.002)  # backpressure: wait for queue space
                continue
            try:
                self.submit_request(reqs[i])
            except QueueFullError:
                pass  # realtime: visible reject, request is shed
            except RouterDraining:
                continue  # top of loop accounts the rest as drain_rejected
            i += 1
        return self.join()

    def close(self) -> None:
        """Stop replica threads and watchdogs. Wedged threads (blocked
        inside a stuck step) are daemons and are left behind."""
        if self.threads:
            for r in self.replicas:
                if r.thread is not None:
                    r.send(("stop",))
            for r in self.replicas:
                if r.thread is not None and not r.wedged.is_set():
                    r.thread.join(timeout=5.0)
        for r in self.replicas:
            if r.watchdog is not None:
                r.watchdog.stop()

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # ------------------------------------------------------------ metrics
    def metrics(self) -> dict:
        """Point-in-time fleet snapshot: router counters, latency
        percentiles (ms, None until data), and one dict per replica —
        the payload `atx serve` flattens into its JSON line."""
        per = []
        for r in self.replicas:
            es = r.engine.stats
            pm = r.engine.prefix_metrics()
            per.append(
                {
                    "replica": r.id,
                    "dispatched": r.dispatched,
                    "completed": r.completed,
                    "inflight": len(r.inflight),
                    "occupancy": round(
                        es["decode_slot_steps"]
                        / max(es["decode_steps"] * r.engine.n_slots, 1),
                        3,
                    ),
                    "prefix_hit_rate": pm.get("prefix_hit_rate", 0.0),
                    "quarantined": int(r.dead),
                    "wedged": int(r.wedged.is_set()),
                    "probation": r.probation_left,
                    "quarantines": r.quarantines,
                    "rebuilds": r.rebuilds,
                    "error": r.error,
                }
            )
        labels = self._tel_labels
        per_class = {}
        for cls in sorted(self._classes_seen):
            cl = {**labels, "class": str(cls)}
            per_class[str(cls)] = {
                "completed": self._h_class_e2e.count(**cl),
                "ttft_p50_ms": _hq(self._h_class_ttft, 0.50, cl),
                "e2e_p50_ms": _hq(self._h_class_e2e, 0.50, cl),
                "e2e_p99_ms": _hq(self._h_class_e2e, 0.99, cl),
                "shed": self._shed_by_class.get(cls, 0),
            }
        m: dict = dict(self.stats)
        m.update(
            replicas=len(self.replicas),
            replicas_alive=sum(1 for r in self.replicas if not r.dead),
            queue_depth=self._public_pending(),
            queue_capacity=self.queue_depth,
            draining=int(self._draining),
            drain_reason=self.drain_reason,
            scheduling=self.scheduling,
            shed=sum(self._shed_by_class.values()),
            shed_by_class={str(k): v for k, v in sorted(self._shed_by_class.items())},
            deadline_infeasible=int(self._c_infeasible.value(**labels)),
            readmissions=int(self._c_readmit.value(**labels)),
            probe_failures=int(self._c_probe_fail.value(**labels)),
            retry_budget_exhausted=int(self._c_retry_exhausted.value(**labels)),
            retry_tokens=(
                round(self._retry_tokens, 2) if self.retry_budget >= 0 else None
            ),
            migrated_prefixes=self._migrated_prefixes,
            per_class=per_class,
            ttft_p50_ms=_hq(self._h_ttft, 0.50, self._tel_labels),
            ttft_p99_ms=_hq(self._h_ttft, 0.99, self._tel_labels),
            e2e_p50_ms=_hq(self._h_e2e, 0.50, self._tel_labels),
            e2e_p99_ms=_hq(self._h_e2e, 0.99, self._tel_labels),
            per_replica=per,
        )
        return m

"""Continuous-batching serving: slot-paged KV cache, bucketed chunked
prefill, iteration-level scheduling, automatic prefix caching
(radix-tree KV reuse across requests), and a multi-replica front-end
(prefix-affinity routing, EDF/priority admission scheduling with
load shedding, graceful drain, replica failover with probation &
re-admission, and prefix-cache migration on quarantine). See
`serving/engine.py`, `serving/prefix_cache.py`, `serving/router.py`,
and docs/serving.md."""

from .engine import (
    Completion,
    Engine,
    Request,
    default_buckets,
    poisson_trace,
    shared_prefix_trace,
)
from .prefix_cache import PrefixCache
from .router import (
    AffinityIndex,
    DeadlineInfeasibleError,
    NoHealthyReplicaError,
    QueueFullError,
    Router,
    RouterDraining,
)

__all__ = [
    "Engine",
    "Request",
    "Completion",
    "poisson_trace",
    "shared_prefix_trace",
    "default_buckets",
    "PrefixCache",
    "Router",
    "AffinityIndex",
    "QueueFullError",
    "RouterDraining",
    "DeadlineInfeasibleError",
    "NoHealthyReplicaError",
]

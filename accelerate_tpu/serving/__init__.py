"""Continuous-batching serving: slot-paged KV cache, bucketed chunked
prefill, iteration-level scheduling. See `serving/engine.py` and
docs/serving.md."""

from .engine import Completion, Engine, Request, default_buckets, poisson_trace

__all__ = ["Engine", "Request", "Completion", "poisson_trace", "default_buckets"]

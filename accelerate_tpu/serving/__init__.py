"""Continuous-batching serving: slot-paged KV cache, bucketed chunked
prefill, iteration-level scheduling, and automatic prefix caching
(radix-tree KV reuse across requests). See `serving/engine.py`,
`serving/prefix_cache.py`, and docs/serving.md."""

from .engine import (
    Completion,
    Engine,
    Request,
    default_buckets,
    poisson_trace,
    shared_prefix_trace,
)
from .prefix_cache import PrefixCache

__all__ = [
    "Engine",
    "Request",
    "Completion",
    "poisson_trace",
    "shared_prefix_trace",
    "default_buckets",
    "PrefixCache",
]

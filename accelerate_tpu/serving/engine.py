"""Iteration-level continuous-batching serving engine.

The fixed-batch `generation.Generator` serves OFFLINE workloads well (one
batch in, one batch out) but wastes the chip under traffic: every request
pads to the longest prompt in its batch, the batch decodes until its LAST
row finishes, and new arrivals wait for the whole batch to drain. BENCH_r05
quantifies the lever: `decode_b1_tokens_per_sec 421.7` vs batch-8
`decode_tokens_per_sec 3736.5` — keeping the decode batch full is ~8x.

This engine applies the Orca iteration-level-scheduling idea in its
XLA-native form (the vLLM slot/page design reduced to what a TPU actually
needs — static shapes):

- the KV cache is a fixed pool of ``slots`` (batch rows of one
  slot-batched family cache); requests are admitted into free slots and
  evicted on EOS / token budget, so the compiled decode step never sees a
  shape change as traffic comes and goes;
- per-slot length cursors ride the family cache contract
  (``cache['length']`` as a (B,) vector, `models/layers.py:cache_write`) —
  the cursors live on the HOST (the scheduler knows them deterministically)
  and are shipped as a tiny (N,) int32 each step, which keeps the device
  step pure and the whole engine replayable;
- prefill is **bucketed and chunked**: prompts are split into chunks, each
  padded to one of a small static set of bucket lengths, and each chunk is
  computed on a single slot's cache ROW (`models/layers.py:cache_slot_view`
  / `cache_slot_write`, slot index traced) — so prefill compiles at most
  once per bucket (validated by the ATX302 drift checker in tests) and a
  long prompt never stalls in-flight decodes: chunks interleave with decode
  steps at a configurable ratio;
- one jitted decode step runs over the FULL slot batch every time (free
  slots compute garbage that is never read — the price of static shapes);
  greedy outputs are bit-identical to solo `generate()` per request
  (tested), because masked-out cache positions contribute exactly zero to
  the fp32 softmax.

Knobs: ``ATX_SERVE_SLOTS`` / ``ATX_SERVE_BUCKETS`` (comma-separated bucket
lengths) set the defaults; see docs/serving.md for sizing guidance and when
the plain `Generator` is still the right tool.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import resilience
from .. import telemetry as _telemetry
from ..telemetry import flight as _flight
from ..generation import GenerationConfig, warp_logits
from ..models.layers import cache_slot_copy, cache_slot_view, cache_slot_write
from ..utils.environment import (
    get_int_from_env,
    get_str_from_env,
    parse_flag_from_env,
)
from .prefix_cache import PrefixCache

__all__ = [
    "Engine",
    "Request",
    "Completion",
    "poisson_trace",
    "shared_prefix_trace",
    "default_buckets",
]

ApplyFn = Callable[[Any, jax.Array, Any], tuple[jax.Array, Any]]

_DEFAULT_BUCKETS = (32, 64, 128, 256)


def default_buckets() -> tuple[int, ...]:
    """Prefill bucket lengths from ``ATX_SERVE_BUCKETS`` (comma-separated,
    e.g. ``"16,64,256"``), else the built-in (32, 64, 128, 256)."""
    raw = get_str_from_env(("ATX_SERVE_BUCKETS",), "")
    if not raw:
        return _DEFAULT_BUCKETS
    try:
        buckets = tuple(sorted({int(x) for x in raw.split(",") if x.strip()}))
    except ValueError:
        raise ValueError(
            f"ATX_SERVE_BUCKETS={raw!r}: expected comma-separated ints"
        ) from None
    if not buckets or buckets[0] <= 0:
        raise ValueError(f"ATX_SERVE_BUCKETS={raw!r}: buckets must be positive")
    return buckets


@dataclasses.dataclass
class Request:
    """One generation request. ``arrival`` is seconds relative to the trace
    start (used by `Engine.serve(realtime=True)` and the bench); ``seed``
    drives the per-request sampling stream, so a request's tokens don't
    depend on which other requests share the batch. ``max_new_tokens=None``
    falls back to the engine config's budget; ``stop_sequences`` are
    multi-token stop strings matched HOST-side against the emitted tail
    (the device step never sees them — no recompiles per stop set).
    ``priority`` is the request's admission class for `serving.Router`
    (lower = more important, default 1; the engine itself ignores it):
    under EDF scheduling a lower class is dispatched first at equal
    deadlines and is the last to be shed under overload."""

    prompt: np.ndarray
    max_new_tokens: int | None = None
    rid: int = -1
    seed: int = 0
    arrival: float | None = None
    stream: Callable[[int, int, str | None], None] | None = None
    stop_sequences: Sequence[Sequence[int]] | None = None
    # Deadline in seconds from submission, enforced by `serving.Router`
    # (the engine itself never expires a request): on expiry the request
    # is cancelled mid-queue or mid-decode with finish_reason="cancelled".
    timeout: float | None = None
    priority: int = 1


@dataclasses.dataclass
class Completion:
    """A finished request. ``tokens`` is (max_new_tokens,) int32 padded with
    ``pad_token_id`` after EOS — the exact layout solo `generate()` emits
    for the generated region, so bit-identity checks are a slice compare.
    Timestamps are absolute `time.perf_counter()` values. ``finish_reason``
    is ``"eos"`` / ``"stop"`` (a stop sequence matched; its tokens stay in
    ``tokens``) / ``"length"`` (budget exhausted) / ``"cancelled"``
    (`Engine.cancel` — deadline expiry or caller cancellation; ``tokens``
    holds whatever was generated before the cancel) / ``"failed"``
    (`serving.Router` only: replica deaths exhausted the retry budget) /
    ``"shed"`` (`serving.Router` only: evicted from the admission queue
    under overload to make room for a higher-priority request)."""

    rid: int
    prompt: np.ndarray
    tokens: np.ndarray
    n_new: int
    text: str | None
    submitted_at: float
    first_token_at: float
    finished_at: float
    finish_reason: str = "length"


class _Slot:
    __slots__ = (
        "req", "chunks", "cursor", "n_new", "last_token", "out",
        "first_token_at", "decoding", "pending_copy",
        "t_prefill0", "occ_sum", "occ_n",
    )

    def __init__(
        self, req: Request, chunks: list, pad: int, *, matched: int = 0,
        pending_copy=None,
    ) -> None:
        self.req = req
        self.chunks = chunks  # [(padded (1, bucket) np.int32, real_len), ...]
        # KV positions written & committed so far. A prefix-cache hit
        # starts the cursor at the match boundary; the pinned source node
        # in ``pending_copy`` is copied into the slot row right before the
        # slot's first prefill chunk (same device order: copy, then chunk).
        self.cursor = matched
        self.pending_copy = pending_copy  # (CacheNode, matched) | None
        self.n_new = 0
        self.last_token = 0
        self.out = np.full((req.max_new_tokens,), pad, np.int32)
        self.first_token_at = 0.0
        self.decoding = False
        # Tracing residuals (ATX_TRACE_REQUESTS=1): first prefill-chunk
        # dispatch time, plus decode-residency accumulators (sum of batch
        # occupancy over resident iterations) — plain float/int adds in the
        # decode loop, emitted as ONE span at completion.
        self.t_prefill0 = 0.0
        self.occ_sum = 0
        self.occ_n = 0


class Engine:
    """Continuous-batching engine over a family cached forward.

    ``apply_fn(params, tokens, cache) -> (logits, cache)`` and
    ``init_cache_fn(batch, max_len) -> cache`` follow the model-family
    cache contract (e.g. `models/llama.py:forward_with_cache` /
    ``init_cache``); every family cache whose non-``length`` leaves are
    layer-stacked ``(L, B, T, ...)`` buffers works (bf16/fp32/int8).

    ``max_len`` is the per-slot KV capacity (prompt + new tokens must fit);
    defaults to ``2 * max(buckets)``. ``prefill_interleave`` is the number
    of decode steps granted between two prefill chunks while both kinds of
    work are pending (1 = strict alternation; 0 = prefill-first, which
    stalls in-flight decodes for the whole prompt — the fixed-batch
    behaviour this engine exists to avoid).

    ``prefix_cache`` (default on; ``ATX_SERVE_PREFIX_CACHE=0`` disables)
    retains committed prompt-prefix KV in a dedicated device pool and
    serves future requests' shared prefixes by device-to-device copy
    instead of prefill (docs/serving.md). ``prefix_cache_mib``
    (``ATX_SERVE_PREFIX_CACHE_MIB``, default 64) is the pool's byte
    budget; ``prefix_cache_rows`` overrides the derived row count
    directly (tests / exact sizing). Greedy outputs are bit-identical
    with the cache on or off.

    **Thread ownership**: an Engine is NOT thread-safe. Exactly one thread
    may drive it — every `submit`/`submit_request`/`step`/`cancel`/`serve`
    call must come from that same thread (the host-side scheduler state
    and the device dispatch order both assume a single driver). The
    multi-replica `serving.Router` honours this by giving each replica
    engine its own dedicated thread and forwarding submissions and
    cancellations through a per-replica inbox.
    """

    def __init__(
        self,
        apply_fn: ApplyFn,
        init_cache_fn: Callable[[int, int], Any],
        params: Any,
        config: GenerationConfig | None = None,
        *,
        slots: int | None = None,
        buckets: Sequence[int] | None = None,
        max_len: int | None = None,
        prefill_interleave: int = 1,
        decode_block: int = 1,
        detokenize: Callable[[Sequence[int]], str] | None = None,
        prefix_cache: bool | None = None,
        prefix_cache_mib: float | None = None,
        prefix_cache_rows: int | None = None,
    ) -> None:
        self.config = config or GenerationConfig()
        self.n_slots = (
            slots if slots is not None else get_int_from_env(("ATX_SERVE_SLOTS",), 8)
        )
        if self.n_slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.n_slots}")
        self.buckets = tuple(sorted(set(buckets))) if buckets else default_buckets()
        if self.buckets[0] <= 0:
            raise ValueError(f"buckets must be positive, got {self.buckets}")
        self.max_len = max_len if max_len is not None else 2 * self.buckets[-1]
        self.prefill_interleave = prefill_interleave
        # Decode steps dispatched per host sync. 1 = fetch every token
        # (lowest admission/eviction latency); >1 chains steps on device and
        # fetches their tokens in one device_get — the per-step round trip
        # amortizes away (the speculative.py host-loop design). A slot that
        # hits EOS mid-block zombie-decodes to the block end; its post-EOS
        # tokens are discarded, so outputs still match solo generate()'s
        # truncation exactly (tested).
        self.decode_block = max(1, decode_block)
        self.detokenize = detokenize
        self.params = params
        cache = init_cache_fn(self.n_slots, self.max_len)
        kv = {k: v for k, v in cache.items() if k != "length"}
        # Commit the slot pool (and remember its device): every decode /
        # prefill output inherits this placement, so the jit signatures
        # (which key on argument committedness) stay IDENTICAL from the
        # first call on — one compile for decode, one per prefill bucket.
        try:
            self._device = sorted(
                next(iter(jax.tree.leaves(kv))).devices(), key=str
            )[0]
        except Exception:
            self._device = jax.devices()[0]
        self._kv = jax.device_put(kv, self._device)
        config_ = self.config
        eos, pad = config_.eos_token_id, config_.pad_token_id

        def _sample(logits, seed, n):
            # Token n of a request draws from fold_in(PRNGKey(seed), n):
            # stateless, so the stream is reproducible regardless of batch
            # composition (solo replay gives the same tokens).
            if not config_.do_sample:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), n)
            return jax.random.categorical(key, warp_logits(logits, config_)).astype(
                jnp.int32
            )

        def decode_fn(params, tokens, lengths, kv, seeds, steps):
            """One token for every slot. Free/mid-prefill slots compute too
            (static shapes) — their write lands at their cursor, a position
            the next prefill chunk fully overwrites, and their output is
            dropped by the host scheduler.

            The T=1 attention inside ``apply_fn`` routes through the
            `flash-decode Pallas kernel <native/pallas/decode_attention.py>`
            when enabled (``ATX_KERNELS`` / ``ATX_KERNEL_DECODE_ATTN``,
            read at trace time): split-K over the slot KV cache, masked by
            each row's length cursor, with int8 KV dequantized in-kernel."""
            logits, new = apply_fn(params, tokens[:, None], dict(kv, length=lengths))
            nxt = jax.vmap(_sample)(logits[:, -1, :], seeds, steps)
            return nxt, {k: new[k] for k in kv}

        def prefill_fn(params, tokens, kv, slot, cursor, sample_pos, seed):
            """One bucket-padded prompt chunk into slot row ``slot`` at
            ``cursor``. Pad-tail KV lands at positions >= the row's real
            cursor — never attended before decode overwrites it. The
            returned token (sampled at ``sample_pos``, the chunk's last
            REAL position) is only meaningful on a prompt's final chunk."""
            row = cache_slot_view(kv, slot)
            logits, new = apply_fn(params, tokens, dict(row, length=cursor))
            kv = cache_slot_write(kv, {k: new[k] for k in row}, slot)
            last = jnp.take_along_axis(logits[0], sample_pos[None, None], axis=0)[0]
            tok = _sample(last, seed, jnp.zeros((), jnp.int32))
            return tok, kv

        self._decode_fn = decode_fn
        self._prefill_fn = prefill_fn
        self._decode = jax.jit(decode_fn, donate_argnums=(3,))
        self._prefill = jax.jit(prefill_fn, donate_argnums=(2,))

        # Prefix cache: a dedicated pool of KV rows (same leaf layout as the
        # slot pool) indexed by a host-side radix tree. Hit/promotion copies
        # go through ONE jitted cache_slot_copy whose chunk length is a
        # static drawn from the bucket set (slots/cursor traced), so its jit
        # cache is bounded by 2 x len(buckets) — hit copies (dst = slot kv)
        # and promotions (dst = pool) have different dst/src shapes when the
        # pool row count differs from the slot count.
        # Per-engine wrapper (not cache_slot_copy itself): jit caches key on
        # the function object, so a shared callee would pool compile counts
        # across engines and make prefix_copy_compiles meaningless.
        def copy_fn(dst, src, dst_slot, src_slot, start, length: int):
            return cache_slot_copy(dst, src, dst_slot, src_slot, start, length)

        self._copy_fn = copy_fn
        self._copy = jax.jit(copy_fn, static_argnums=(5,), donate_argnums=(0,))
        self.copy_signatures: list[int] = []  # chunk length per issued copy
        enabled = (
            parse_flag_from_env("ATX_SERVE_PREFIX_CACHE", True)
            if prefix_cache is None
            else prefix_cache
        )
        self.prefix_cache: PrefixCache | None = None
        self._pool: Any = None
        if enabled:
            rows = prefix_cache_rows
            if rows is None:
                mib = (
                    prefix_cache_mib
                    if prefix_cache_mib is not None
                    else get_int_from_env(("ATX_SERVE_PREFIX_CACHE_MIB",), 64)
                )
                row_bytes = sum(
                    int(np.prod(v.shape)) * v.dtype.itemsize
                    for v in jax.tree.leaves(kv)
                ) // self.n_slots
                rows = int(mib * 2**20 // max(row_bytes, 1))
            rows = min(rows, 1024)  # bound host tree bookkeeping
            if rows >= 1:
                pool = init_cache_fn(rows, self.max_len)
                self._pool = jax.device_put(
                    {k: v for k, v in pool.items() if k != "length"}, self._device
                )
                self.prefix_cache = PrefixCache(rows, self.buckets, self.max_len)

        # Static capacity guard (ATX_SERVE_CAPACITY_CHECK, default "warn"):
        # weights + slot pool + prefix pool are all committed by this point,
        # so a config that cannot fit the chip is known *now*, not at the
        # first burst of traffic. docs/serving.md#capacity-planner.
        from ..analysis.capacity import check_engine_capacity

        check_engine_capacity(self)

        self._queue: deque[Request] = deque()
        self._slots: list[_Slot | None] = [None] * self.n_slots
        self._free: deque[int] = deque(range(self.n_slots))
        self._prefill_order: deque[int] = deque()  # slots with pending chunks
        self._decode_credit = 0
        self._next_rid = 0
        self.prefill_signatures: list[int] = []  # bucket length per issued chunk
        # Counters live on the telemetry registry (docs/observability.md):
        # this dict-shaped view keeps every historical `stats[...]` use and
        # snapshot working while `/metrics` reads the same series — one
        # source of truth. Keys: decode_slot_steps sums active rows over
        # decode steps; prefill_tokens_saved counts prompt tokens served by
        # KV copy instead of prefill compute.
        self.stats = _telemetry.StatsView(
            "serve",
            (
                "admitted",
                "completed",
                "prefill_chunks",
                "decode_steps",
                "decode_slot_steps",
                "prompt_tokens",
                "prefix_hits",
                "prefill_tokens_saved",
                "prefix_copy_chunks",
                "prefix_promotions",
                "cancelled",
            ),
            label="engine",
        )
        _labels = ("engine",)
        self._tel_labels = self.stats.labels
        self._h_queue_wait = _telemetry.histogram(
            "serve_queue_wait_ms", "submit -> slot admission", labels=_labels
        )
        self._h_prefill_ms = _telemetry.histogram(
            "serve_prefill_step_ms", "wall per prefill scheduler step",
            labels=_labels,
        )
        self._h_decode_ms = _telemetry.histogram(
            "serve_decode_step_ms",
            "wall per decode scheduler step (includes the token fetch sync)",
            labels=_labels,
        )
        self._h_ttft = _telemetry.histogram(
            "serve_ttft_ms", "engine submit -> first token", labels=_labels
        )
        self._h_e2e = _telemetry.histogram(
            "serve_e2e_ms", "engine submit -> completion", labels=_labels
        )
        self._c_tokens = _telemetry.counter(
            "serve_generated_tokens", "tokens emitted", labels=_labels
        )
        self.actions: list[str] = []  # "prefill" / "decode", for tests/traces
        # Request-scoped tracing (telemetry/flight.py), snapshotted ONCE so
        # the decode inner loop pays zero cost while off. Spans time the
        # HOST dispatch only — recording never adds a device sync, so
        # greedy outputs are bit-identical with tracing on or off.
        self._trace = _flight.trace_requests_enabled()

    # ------------------------------------------------------------- submit
    def submit(
        self,
        prompt: Any,
        max_new_tokens: int | None = None,
        *,
        seed: int = 0,
        stream: Callable[[int, int, str | None], None] | None = None,
        arrival: float | None = None,
        stop_sequences: Sequence[Sequence[int]] | None = None,
    ) -> int:
        """Queue one request; returns its request id. ``stream`` is called
        as ``stream(rid, token_id, text)`` for every generated token (text
        is the detokenized piece when the engine has a detokenizer).
        ``max_new_tokens`` overrides the engine config's budget per
        request; ``stop_sequences`` end the request early when the emitted
        tail matches any of the token sequences (host-side — see
        `Request`)."""
        req = Request(
            prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=max_new_tokens,
            seed=seed,
            arrival=arrival,
            stream=stream,
            stop_sequences=stop_sequences,
        )
        return self.submit_request(req)

    def validate_request(self, req: Request) -> Request:
        """Resolve per-request defaults and validate against this engine's
        capacity WITHOUT queueing anything (raises ValueError on a request
        that could never run here). `submit_request` calls this; the
        multi-replica Router calls it at admission so a bad request is
        rejected at the front door instead of killing a replica thread."""
        if req.max_new_tokens is None:
            req.max_new_tokens = self.config.max_new_tokens
        if req.max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {req.max_new_tokens}")
        if req.stop_sequences is not None:
            req.stop_sequences = tuple(
                tuple(int(t) for t in seq) for seq in req.stop_sequences
            )
            if any(len(seq) == 0 for seq in req.stop_sequences):
                raise ValueError("empty stop sequence")
        S = int(req.prompt.shape[0])
        if S < 1:
            raise ValueError("empty prompt")
        if S + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({S}) + max_new_tokens ({req.max_new_tokens}) exceeds "
                f"the engine's per-slot KV capacity max_len={self.max_len}"
            )
        # Bucket-padding fit: every prefill chunk writes a full BUCKET of KV
        # positions (pad tail included), so the padded plan — not just the
        # raw prompt — must fit max_len. Validate here, at submit time, so
        # an oversized prompt raises a clear error instead of the padded
        # final chunk's clamped cache write corrupting committed KV deep
        # inside the prefill path.
        self._chunk_plan(req.prompt)
        return req

    def submit_request(self, req: Request) -> int:
        self.validate_request(req)
        if req.rid < 0:
            req.rid = self._next_rid
        self._next_rid = max(self._next_rid, req.rid) + 1
        req.submitted_at = time.perf_counter()  # type: ignore[attr-defined]
        self._queue.append(req)
        return req.rid

    # ------------------------------------------------------------- cancel
    def cancel(self, rid: int) -> Completion | None:
        """Cancel a queued or in-flight request. Returns a `Completion`
        with ``finish_reason="cancelled"`` carrying whatever tokens were
        generated before the cancel (none for a still-queued request), or
        None when ``rid`` is unknown or already finished. Must be called
        from the engine-owning thread, between `step` calls (the Router's
        per-replica inbox serializes this). The cancelled slot's committed
        prefix is NOT promoted to the prefix cache — a partial request is
        a poor reuse candidate and the slot is recycled immediately."""
        for i, req in enumerate(self._queue):
            if req.rid == rid:
                del self._queue[i]
                self.stats["cancelled"] += 1
                return self._cancelled_completion(
                    req,
                    np.full((req.max_new_tokens,), self.config.pad_token_id, np.int32),
                    0,
                    0.0,
                )
        for slot_id, slot in enumerate(self._slots):
            if slot is not None and slot.req.rid == rid:
                if slot.pending_copy is not None:
                    self.prefix_cache.release(slot.pending_copy[0])
                    slot.pending_copy = None
                try:
                    self._prefill_order.remove(slot_id)
                except ValueError:
                    pass  # already decoding
                # The slot's partial KV is garbage to the next occupant:
                # its first prefill chunk overwrites from cursor 0 (the
                # same free-slot invariant every eviction relies on).
                self._slots[slot_id] = None
                self._free.append(slot_id)
                self.stats["cancelled"] += 1
                return self._cancelled_completion(
                    slot.req, slot.out, slot.n_new, slot.first_token_at
                )
        return None

    def _cancelled_completion(
        self, req: Request, tokens: np.ndarray, n_new: int, first_token_at: float
    ) -> Completion:
        return Completion(
            rid=req.rid,
            prompt=req.prompt,
            tokens=tokens,
            n_new=n_new,
            text=self.detokenize(tokens[:n_new].tolist()) if self.detokenize else None,
            submitted_at=getattr(req, "submitted_at", 0.0),
            first_token_at=first_token_at,
            finished_at=time.perf_counter(),
            finish_reason="cancelled",
        )

    def abort_inflight(self) -> list[Completion]:
        """Cancel EVERYTHING queued or in a slot, returning the cancelled
        completions. Leaves the engine idle with every slot free — used to
        sanitize an engine between chaos episodes and before a re-admission
        probe replays the canary on a quarantined replica (whatever the
        fault left mid-flight must not contaminate the probe)."""
        rids = [req.rid for req in self._queue]
        rids += [s.req.rid for s in self._slots if s is not None]
        out = []
        for rid in rids:
            c = self.cancel(rid)
            if c is not None:
                out.append(c)
        return out

    # ---------------------------------------------------------- scheduler
    @property
    def busy(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def _chunk_plan(
        self, prompt: np.ndarray, start: int = 0
    ) -> list[tuple[np.ndarray, int]]:
        """Bucket-padded prefill chunks for ``prompt[start:]`` (``start`` is
        the prefix-cache match boundary — 0 when there's no hit)."""
        chunks = []
        pos, S = start, len(prompt)
        while pos < S:
            rem = S - pos
            if rem > self.buckets[-1]:
                bucket = self.buckets[-1]
            else:
                bucket = min(b for b in self.buckets if b >= rem)
            real = min(rem, bucket)
            if pos + bucket > self.max_len:
                raise ValueError(
                    f"prompt length {S}: the prefill chunk covering positions "
                    f"[{pos}, {pos + bucket}) (bucket {bucket}, buckets "
                    f"{self.buckets}) pads past the per-slot KV capacity "
                    f"max_len={self.max_len}; raise max_len or add a bucket "
                    f"<= {self.max_len - pos} so bucket-padded prefill fits"
                )
            buf = np.full((1, bucket), self.config.pad_token_id, np.int32)
            buf[0, :real] = prompt[pos : pos + real]
            chunks.append((buf, real))
            pos += real
        return chunks

    def _admit(self) -> None:
        while self._queue and self._free:
            req = self._queue.popleft()
            slot_id = self._free.popleft()
            node, matched = None, 0
            if self.prefix_cache is not None:
                # Cap the match one token short of the prompt: the final
                # prefill chunk must forward at least one real token to
                # produce the first sampling logits. The returned node is
                # pinned until the copy dispatch in _prefill_step — LRU
                # eviction cannot recycle its row in between, however many
                # promotions other slots' completions trigger first.
                node, matched = self.prefix_cache.match(
                    req.prompt, limit=len(req.prompt) - 1, rid=req.rid
                )
            try:
                chunks = self._chunk_plan(req.prompt, start=matched)
            except ValueError:
                # The match-shifted plan can pad past max_len even when the
                # start=0 plan (validated at submit) fits — a hit is an
                # optimization, never a requirement, so fall back to a full
                # prefill rather than rejecting the request.
                self.prefix_cache.release(node)
                node, matched = None, 0
                chunks = self._chunk_plan(req.prompt)
            self._slots[slot_id] = _Slot(
                req,
                chunks,
                self.config.pad_token_id,
                matched=matched,
                pending_copy=(node, matched) if node is not None else None,
            )
            self._prefill_order.append(slot_id)
            self.stats["admitted"] += 1
            submitted = getattr(req, "submitted_at", 0.0)
            if submitted:
                self._h_queue_wait.observe(
                    (time.perf_counter() - submitted) * 1e3, **self._tel_labels
                )
            self.stats["prompt_tokens"] += len(req.prompt)
            if matched:
                self.stats["prefix_hits"] += 1
                self.stats["prefill_tokens_saved"] += matched
            if self._trace:
                _flight.record_span(
                    "admit",
                    rid=req.rid,
                    slot=slot_id,
                    prefix_hit=bool(matched),
                    prefix_matched=int(matched),
                    prompt_tokens=len(req.prompt),
                )

    def step(self) -> list[Completion]:
        """One scheduler iteration: admit what fits, then run EITHER one
        prefill chunk OR one decode step over the slot batch (prefill and
        decode alternate per ``prefill_interleave`` when both are pending).
        Returns the requests that finished this iteration."""
        # Engine-level chaos injection point (test_utils/faults.py): a
        # cheap env-membership check when no fault is armed.
        resilience.fault_point("engine.step")
        self._admit()
        decoding = [i for i, s in enumerate(self._slots) if s is not None and s.decoding]
        if self._prefill_order and (not decoding or self._decode_credit <= 0):
            self._decode_credit = self.prefill_interleave
            self.actions.append("prefill")
            t0 = time.perf_counter()
            with _telemetry.span("serve_prefill"):
                out = self._prefill_step()
            self._h_prefill_ms.observe(
                (time.perf_counter() - t0) * 1e3, **self._tel_labels
            )
            return out
        if decoding:
            self._decode_credit -= 1
            self.actions.append("decode")
            t0 = time.perf_counter()
            with _telemetry.span("serve_decode"):
                out = self._decode_step(decoding)
            self._h_decode_ms.observe(
                (time.perf_counter() - t0) * 1e3, **self._tel_labels
            )
            return out
        return []

    def run_until_idle(self) -> list[Completion]:
        out: list[Completion] = []
        while self.busy:
            out.extend(self.step())
        return out

    def serve(
        self, requests: Iterable[Request], *, realtime: bool = False
    ) -> list[Completion]:
        """Drive a whole trace. ``realtime=True`` honours each request's
        ``arrival`` offset on the wall clock (idle gaps are slept through)
        — the latency-measuring mode; otherwise requests are submitted in
        arrival order as fast as the engine drains them."""
        reqs = sorted(requests, key=lambda r: (r.arrival or 0.0))
        out: list[Completion] = []
        t0 = time.perf_counter()
        i = 0
        while i < len(reqs) or self.busy:
            if i < len(reqs):
                now = time.perf_counter() - t0
                while i < len(reqs) and (
                    not realtime or (reqs[i].arrival or 0.0) <= now
                ):
                    self.submit_request(reqs[i])
                    i += 1
                if realtime and not self.busy and i < len(reqs):
                    time.sleep(
                        max((reqs[i].arrival or 0.0) - (time.perf_counter() - t0), 0.0)
                    )
                    continue
            out.extend(self.step())
        return out

    # ------------------------------------------------------------ actions
    def _prefill_step(self) -> list[Completion]:
        slot_id = self._prefill_order[0]
        slot = self._slots[slot_id]
        if slot.pending_copy is not None:
            # Prefix-cache hit: copy the matched KV span out of the pool
            # into this slot's row, chunked at bucket lengths (static per
            # chunk — the jit cache stays bounded by the bucket set). The
            # copies are dispatched BEFORE this slot's first prefill chunk,
            # so in device order the chunk's attention over [0, cursor)
            # reads committed prefix KV, never the pool row's future state.
            node, matched = slot.pending_copy
            t_copy0 = time.perf_counter() if self._trace else 0.0
            off = 0
            n_copy = 0
            for ln in self.prefix_cache.chunks(matched):
                self._kv = self._copy(
                    self._kv, self._pool,
                    np.int32(slot_id), np.int32(node.row), np.int32(off), ln,
                )
                self.copy_signatures.append(ln)
                self.stats["prefix_copy_chunks"] += 1
                off += ln
                n_copy += 1
            self.prefix_cache.release(node)
            slot.pending_copy = None
            if self._trace:
                # Dispatch time only — the copies are async on device.
                _flight.record_span(
                    "prefix_copy",
                    rid=slot.req.rid,
                    t0=t_copy0,
                    tokens=int(matched),
                    chunks=n_copy,
                )
        buf, real = slot.chunks.pop(0)
        t_chunk0 = 0.0
        compiles_before = 0
        if self._trace:
            if slot.t_prefill0 == 0.0:
                slot.t_prefill0 = time.perf_counter()
            t_chunk0 = time.perf_counter()
            compiles_before = self._prefill._cache_size()
        tok, self._kv = self._prefill(
            self.params,
            buf,
            self._kv,
            np.int32(slot_id),
            np.int32(slot.cursor),
            np.int32(real - 1),
            np.uint32(slot.req.seed),
        )
        slot.cursor += real
        self.stats["prefill_chunks"] += 1
        self.prefill_signatures.append(buf.shape[1])
        if self._trace:
            _flight.record_span(
                "prefill_chunk",
                rid=slot.req.rid,
                t0=t_chunk0,
                bucket=int(buf.shape[1]),
                tokens=int(real),
                compile_miss=self._prefill._cache_size() > compiles_before,
            )
        if slot.chunks:
            return []  # more prompt to go; tok was a throwaway
        self._prefill_order.popleft()
        slot.first_token_at = time.perf_counter()
        slot.decoding = True
        return self._emit(slot_id, int(tok))

    def _decode_step(self, decoding: list[int]) -> list[Completion]:
        lengths = np.zeros((self.n_slots,), np.int32)
        seeds = np.zeros((self.n_slots,), np.uint32)
        steps = np.zeros((self.n_slots,), np.int32)
        tokens: Any = np.zeros((self.n_slots,), np.int32)
        for i, s in enumerate(self._slots):
            if s is None:
                continue  # free slot: garbage write at 0, overwritten by the
                # next admission's first prefill chunk
            # Mid-prefill slots ride along too: their cursor points at the
            # next chunk's start, so the row's garbage write lands exactly
            # where that chunk will overwrite it — never on committed KV.
            tokens[i] = s.last_token
            lengths[i] = s.cursor
            seeds[i] = s.req.seed
            steps[i] = s.n_new
        # Block dispatch: chain up to decode_block steps on device, bounded
        # by the smallest remaining budget (so no step past a known budget
        # eviction), then fetch all their tokens in ONE sync. Interleave
        # granularity wins while prefill work is pending: block = 1.
        block = min(self.decode_block, *(
            self._slots[i].req.max_new_tokens - self._slots[i].n_new
            for i in decoding
        ))
        if self._prefill_order:
            block = 1
        if self._trace:
            # Residency accounting: two attribute adds per resident slot —
            # no per-iteration span, no allocation, nothing device-side.
            occ = len(decoding)
            for i in decoding:
                s = self._slots[i]
                s.occ_sum += occ * block
                s.occ_n += block
        fetched = []
        # Commit the seed tokens to the cache's device so the chained calls
        # (whose token input is the previous step's committed OUTPUT) share
        # one jit signature with the first — otherwise the decode step
        # silently compiles twice (committed vs uncommitted int32 (N,)).
        tokens = jax.device_put(tokens, self._device)
        for _ in range(block):
            tokens, self._kv = self._decode(
                self.params, tokens, lengths, self._kv, seeds, steps
            )
            fetched.append(tokens)
            lengths[decoding] += 1
            steps[decoding] += 1
        host_tokens = [np.asarray(t) for t in jax.device_get(fetched)]
        self.stats["decode_steps"] += block
        self.stats["decode_slot_steps"] += block * len(decoding)
        out: list[Completion] = []
        for nxt in host_tokens:
            for i in decoding:
                slot = self._slots[i]
                if slot is None or not slot.decoding:
                    continue  # finished mid-block: later tokens are zombies
                slot.cursor += 1
                out.extend(self._emit(i, int(nxt[i])))
        return out

    def _emit(self, slot_id: int, tok: int) -> list[Completion]:
        """Record one generated token for a slot; finish/evict on EOS, a
        stop-sequence match, or budget exhaustion."""
        slot = self._slots[slot_id]
        req = slot.req
        slot.out[slot.n_new] = tok
        slot.n_new += 1
        slot.last_token = tok
        if req.stream is not None:
            piece = self.detokenize([tok]) if self.detokenize else None
            req.stream(req.rid, tok, piece)
        eos_hit = (
            self.config.eos_token_id is not None and tok == self.config.eos_token_id
        )
        stop_hit = False
        if req.stop_sequences and not eos_hit:
            for seq in req.stop_sequences:
                n = len(seq)
                if n <= slot.n_new and slot.out[slot.n_new - n : slot.n_new].tolist() == list(seq):
                    stop_hit = True
                    break
        if not eos_hit and not stop_hit and slot.n_new < req.max_new_tokens:
            return []
        t_decode_end = time.perf_counter() if self._trace else 0.0
        completion = Completion(
            rid=req.rid,
            prompt=req.prompt,
            tokens=slot.out,
            n_new=slot.n_new,
            text=self.detokenize(slot.out[: slot.n_new].tolist())
            if self.detokenize
            else None,
            submitted_at=getattr(req, "submitted_at", 0.0),
            first_token_at=slot.first_token_at,
            finished_at=time.perf_counter(),
            finish_reason="eos" if eos_hit else ("stop" if stop_hit else "length"),
        )
        if self._trace:
            # Contiguous phase spans — queue / prefill / decode / emit tile
            # [submitted_at, finished_at] exactly, so the `atx trace`
            # attribution table sums to the request's e2e by construction.
            # A router stamps its admission time on the request so queue
            # time spent BEFORE engine dispatch is attributed too (the
            # `complete` span's e2e starts at router admission).
            submitted = (
                getattr(req, "router_submitted_at", 0.0)
                or getattr(req, "submitted_at", 0.0)
                or slot.t_prefill0
            )
            t_p0 = slot.t_prefill0 or submitted
            t_first = slot.first_token_at or t_p0
            _flight.record_span("phase_queue", rid=req.rid, t0=submitted, t1=t_p0)
            _flight.record_span("phase_prefill", rid=req.rid, t0=t_p0, t1=t_first)
            _flight.record_span(
                "phase_decode",
                rid=req.rid,
                t0=t_first,
                t1=t_decode_end,
                iterations=slot.occ_n,
                tokens=slot.n_new,
                occupancy=round(
                    slot.occ_sum / max(slot.occ_n * self.n_slots, 1), 4
                ),
            )
            _flight.record_span(
                "phase_emit",
                rid=req.rid,
                t0=t_decode_end,
                t1=completion.finished_at,
                finish_reason=completion.finish_reason,
            )
        if self.prefix_cache is not None:
            self._promote(slot_id, slot)
        self._slots[slot_id] = None  # evict: the slot is immediately reusable
        self._free.append(slot_id)
        self.stats["completed"] += 1
        self._c_tokens.inc(slot.n_new, **self._tel_labels)
        submitted = completion.submitted_at
        if submitted:
            if completion.first_token_at:
                self._h_ttft.observe(
                    (completion.first_token_at - submitted) * 1e3,
                    **self._tel_labels,
                )
            self._h_e2e.observe(
                (completion.finished_at - submitted) * 1e3, **self._tel_labels
            )
        return [completion]

    def _promote(self, slot_id: int, slot: _Slot) -> None:
        """Offer an evicted slot's committed prefix to the cache: the
        chunk-aligned front of [0, cursor) — the prompt plus every
        generated token whose KV has been committed (all but the last, so
        multi-turn follow-ups hit past the original prompt). The copies
        read the slot row BEFORE any later admission overwrites it (host
        dispatch order is device order), and a dedup/full-pool insert
        returns None, in which case promotion is just skipped — hits are
        an optimization, never a correctness dependency."""
        committed = slot.cursor
        cached_len = self.prefix_cache.aligned(committed)
        if cached_len <= 0:
            return
        tokens = slot.req.prompt
        if cached_len > len(tokens):
            tokens = np.concatenate([tokens, slot.out[: cached_len - len(tokens)]])
        else:
            tokens = tokens[:cached_len]
        row = self.prefix_cache.insert(tokens)
        if row is None:
            return
        off = 0
        for ln in self.prefix_cache.chunks(cached_len):
            self._pool = self._copy(
                self._pool, self._kv,
                np.int32(row), np.int32(slot_id), np.int32(off), ln,
            )
            self.copy_signatures.append(ln)
            self.stats["prefix_copy_chunks"] += 1
            off += ln
        self.stats["prefix_promotions"] += 1

    # ------------------------------------------------------------ metrics
    def latency_summary(self) -> dict:
        """Registry-backed request-latency percentiles (ms, None until the
        first completion) — the numbers behind `atx serve`'s ``serve_p50_ms``
        / ``serve_ttft_p50_ms`` fields, estimated from the same histogram
        series the `/metrics` endpoint exports."""
        labels = self._tel_labels
        return {
            "p50_ms": self._h_e2e.quantile(0.50, **labels),
            "p99_ms": self._h_e2e.quantile(0.99, **labels),
            "ttft_p50_ms": self._h_ttft.quantile(0.50, **labels),
            "ttft_p99_ms": self._h_ttft.quantile(0.99, **labels),
            "mean_ms": self._h_e2e.mean(**labels),
        }

    def prefix_metrics(self) -> dict:
        """Prefix-cache counters in reporting shape (`atx serve` JSON /
        bench.py serve phase). ``prefill_saved_frac`` is the fraction of
        all admitted prompt tokens that were served by KV copy instead of
        prefill compute — the headline number for shared-prefix traffic."""
        if self.prefix_cache is None:
            return {"prefix_cache": 0}
        pc = self.prefix_cache
        return {
            "prefix_cache": 1,
            "prefix_rows": pc.n_rows,
            "prefix_rows_used": pc.used_rows,
            "prefix_hit_rate": round(
                self.stats["prefix_hits"] / max(pc.stats["lookups"], 1), 3
            ),
            "prefill_tokens_saved": self.stats["prefill_tokens_saved"],
            "prefill_saved_frac": round(
                self.stats["prefill_tokens_saved"]
                / max(self.stats["prompt_tokens"], 1),
                3,
            ),
            "prefix_promotions": self.stats["prefix_promotions"],
            "prefix_evictions": pc.stats["evictions"],
            "prefix_copy_compiles": self._copy._cache_size(),
        }

    # --------------------------------------------------------------- lint
    def abstract_decode_args(self) -> tuple:
        """ShapeDtypeStructs matching one decode-step call — feed to
        `analysis.lint_step(engine._decode_fn, *engine.abstract_decode_args(),
        donate_argnums=(3,))` (the `atx lint serving` scenario and the
        smoke-serve lane gate on its error findings)."""
        sds = lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype)
        vec = lambda dt: jax.ShapeDtypeStruct((self.n_slots,), dt)
        return (
            jax.tree.map(sds, self.params),
            vec(np.int32),
            vec(np.int32),
            jax.tree.map(sds, self._kv),
            vec(np.uint32),
            vec(np.int32),
        )

    def copy_fn_for_bucket(self, bucket: int):
        """The prefix-copy computation at one static chunk length, for
        linting: `analysis.lint_step(engine.copy_fn_for_bucket(b),
        *engine.abstract_copy_args(), donate_argnums=(0,))` — the `atx
        lint serving` scenario runs it alongside the decode step."""
        return lambda dst, src, dst_slot, src_slot, start: self._copy_fn(
            dst, src, dst_slot, src_slot, start, bucket
        )

    def abstract_copy_args(self) -> tuple:
        """ShapeDtypeStructs matching one hit-direction prefix-copy call
        (dst = the slot kv pool, src = the prefix pool); pairs with
        `copy_fn_for_bucket`. Requires the prefix cache to be enabled."""
        if self._pool is None:
            raise RuntimeError("prefix cache is disabled on this engine")
        sds = lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype)
        scalar = lambda dt: jax.ShapeDtypeStruct((), dt)
        return (
            jax.tree.map(sds, self._kv),
            jax.tree.map(sds, self._pool),
            scalar(np.int32),
            scalar(np.int32),
            scalar(np.int32),
        )


def poisson_trace(
    n: int,
    rate: float,
    *,
    vocab_size: int,
    prompt_lens: tuple[int, int] = (8, 96),
    new_tokens: tuple[int, int] = (8, 48),
    seed: int = 0,
    stop_sequences: Sequence[Sequence[int]] | None = None,
) -> list[Request]:
    """Synthetic mixed-length request trace with Poisson arrivals at
    ``rate`` requests/sec — the bench.py / `atx serve` workload shape.
    ``stop_sequences`` (if given) is attached to every request."""
    rng = np.random.RandomState(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n))
    reqs = []
    for i in range(n):
        S = int(rng.randint(prompt_lens[0], prompt_lens[1] + 1))
        reqs.append(
            Request(
                prompt=rng.randint(0, vocab_size, (S,)).astype(np.int32),
                max_new_tokens=int(rng.randint(new_tokens[0], new_tokens[1] + 1)),
                rid=i,
                seed=i,
                arrival=float(arrivals[i]),
                stop_sequences=stop_sequences,
            )
        )
    return reqs


def shared_prefix_trace(
    n: int,
    rate: float,
    *,
    vocab_size: int,
    n_prefixes: int = 2,
    prefix_len: int = 64,
    tail_lens: tuple[int, int] = (4, 24),
    new_tokens: tuple[int, int] = (4, 16),
    seed: int = 0,
    stop_sequences: Sequence[Sequence[int]] | None = None,
) -> list[Request]:
    """Poisson trace where every prompt is one of ``n_prefixes`` shared
    system prompts (``prefix_len`` tokens) plus a unique tail — the
    workload shape automatic prefix caching targets. With the cache on,
    hit-rate approaches ``(n - n_prefixes) / n`` once each prefix has been
    promoted; make ``prefix_len`` a sum of bucket lengths so the whole
    prefix is reusable (docs/serving.md)."""
    rng = np.random.RandomState(seed)
    prefixes = [
        rng.randint(0, vocab_size, (prefix_len,)).astype(np.int32)
        for _ in range(n_prefixes)
    ]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n))
    reqs = []
    for i in range(n):
        tail = rng.randint(
            0, vocab_size, (int(rng.randint(tail_lens[0], tail_lens[1] + 1)),)
        ).astype(np.int32)
        reqs.append(
            Request(
                prompt=np.concatenate([prefixes[i % n_prefixes], tail]),
                max_new_tokens=int(rng.randint(new_tokens[0], new_tokens[1] + 1)),
                rid=i,
                seed=i,
                arrival=float(arrivals[i]),
                stop_sequences=stop_sequences,
            )
        )
    return reqs

"""Profiling: `jax.profiler` traces behind the reference's profile API.

Analog of `ProfileKwargs` (reference `utils/dataclasses.py:436-549`) and
`Accelerator.profile()` (reference `accelerator.py:3614-3672`). The reference
wraps `torch.profiler` and exports Chrome traces; the TPU equivalent captures
XPlane traces via `jax.profiler.trace` — viewable in TensorBoard or Perfetto —
plus device-memory snapshots (`jax.profiler.device_memory_profile`).

Differences by design:
- No activity list (CPU/CUDA): a JAX trace always captures host + device
  timelines; `host_tracer_level` / `python_tracer_level` tune host detail.
- No schedule(wait/warmup/active): JAX traces are span-based. The
  `skip_first` analog is the caller running warmup steps before entering the
  context (compile time would otherwise dominate the trace).
- `with_flops` analog: `estimate_step_flops` uses XLA's own cost analysis of
  a compiled step instead of operator-level bookkeeping.
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass
from typing import Any, Callable, Iterator

import jax

PROFILE_DIR_DEFAULT = "atx_profile"

# Live XPlane captures started through profile(). telemetry/spans.py keys its
# TraceAnnotation bridging off this, and the Accelerator step helper only
# enters StepTraceAnnotation while a capture is running (docs/observability.md).
_ACTIVE_TRACES = 0


def trace_active() -> bool:
    """True while a `profile()` XPlane capture is running in this process."""
    return _ACTIVE_TRACES > 0


@dataclass
class ProfileKwargs:
    """Trace-capture configuration (reference `ProfileKwargs`,
    `utils/dataclasses.py:436`).

    ``output_trace_dir``: where XPlane trace files land (TensorBoard
    `logdir`); defaults to ``atx_profile`` under the project dir.
    ``host_tracer_level``: 0-3, host-side instrumentation detail.
    ``python_tracer_level``: 0/1, Python-call capture (costly; off by default).
    ``create_perfetto_trace``: also emit a ``.perfetto-trace`` file.
    ``on_trace_ready``: called with the trace directory after capture
    (reference on_trace_ready callback).
    """

    output_trace_dir: str | None = None
    host_tracer_level: int = 2
    python_tracer_level: int = 0
    create_perfetto_trace: bool = False
    on_trace_ready: Callable[[str], None] | None = None

    def build_options(self) -> Any | None:
        """Map to `jax.profiler.ProfileOptions` when this jax version has it."""
        options_cls = getattr(jax.profiler, "ProfileOptions", None)
        if options_cls is None:
            return None
        options = options_cls()
        options.host_tracer_level = self.host_tracer_level
        options.python_tracer_level = self.python_tracer_level
        return options


@contextlib.contextmanager
def profile(
    profile_kwargs: ProfileKwargs | None = None,
    *,
    logging_dir: str | None = None,
) -> Iterator[ProfileKwargs]:
    """Capture a device+host trace of the enclosed block.

    Every process traces (each host's runtime only sees its own chips); the
    XPlane files are written under per-host subdirectories so one TensorBoard
    logdir aggregates a pod's capture.
    """
    kwargs = profile_kwargs or ProfileKwargs()
    trace_dir = kwargs.output_trace_dir or os.path.join(
        logging_dir or ".", PROFILE_DIR_DEFAULT
    )
    os.makedirs(trace_dir, exist_ok=True)
    options = kwargs.build_options()
    start_kwargs: dict[str, Any] = {}
    if kwargs.create_perfetto_trace:
        start_kwargs["create_perfetto_trace"] = True
    if options is not None:
        start_kwargs["profiler_options"] = options
    try:
        jax.profiler.start_trace(trace_dir, **start_kwargs)
    except TypeError:
        # Older jax: no profiler_options / perfetto kwargs.
        if start_kwargs:
            import warnings

            warnings.warn(
                "this jax version's start_trace does not accept "
                f"{sorted(start_kwargs)}; tracing with defaults instead",
                stacklevel=3,
            )
        jax.profiler.start_trace(trace_dir)
    global _ACTIVE_TRACES
    _ACTIVE_TRACES += 1
    try:
        yield kwargs
    finally:
        _ACTIVE_TRACES -= 1
        jax.profiler.stop_trace()
        if kwargs.on_trace_ready is not None:
            kwargs.on_trace_ready(trace_dir)


def annotate(name: str, **kwargs: Any):
    """Named span visible in the trace timeline (reference
    `torch.profiler.record_function` analog)."""
    return jax.profiler.TraceAnnotation(name, **kwargs)


def step_annotation(step: int, name: str = "train"):
    """Mark one training step so TensorBoard's step-time views group ops."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


def maybe_step_annotation(step: int, name: str = "train"):
    """Step boundary for the Accelerator step helper: a
    ``StepTraceAnnotation`` while a `profile()` capture is running (so XPlane
    traces show numbered steps), a no-op context otherwise — keeping the
    training hot path annotation-free when nobody is tracing."""
    if trace_active():
        return step_annotation(step, name=name)
    return contextlib.nullcontext()


def save_memory_profile(path: str) -> str:
    """Write a pprof-format snapshot of live device memory
    (`jax.profiler.save_device_memory_profile`)."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    jax.profiler.save_device_memory_profile(path)
    return path


def estimate_step_flops(compiled: Any) -> float | None:
    """FLOPs XLA attributes to one invocation of a compiled function
    (`with_flops` analog). Returns None when cost analysis is unavailable."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    flops = (cost or {}).get("flops")
    return float(flops) if flops is not None else None

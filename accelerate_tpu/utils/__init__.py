from .dataclasses import (
    DataLoaderConfiguration,
    DistributedType,
    FsdpPlugin,
    GradientAccumulationPlugin,
    MixedPrecisionPolicy,
    PrecisionType,
    ProjectConfiguration,
    RNGType,
    ShardingStrategyType,
    TensorParallelPlugin,
)
from .ds_config import (
    accelerator_kwargs_from_deepspeed_config,
    optax_from_deepspeed_config,
)
from .environment import (
    clear_environment,
    get_int_from_env,
    get_str_from_env,
    parse_flag_from_env,
    patch_environment,
    purge_framework_environment,
    str_to_bool,
)
from .memory import (
    clear_device_cache,
    find_executable_batch_size,
    get_memory_stats,
    release_memory,
    should_reduce_batch_size,
)
from .profiler import (
    ProfileKwargs,
    annotate,
    estimate_step_flops,
    save_memory_profile,
    step_annotation,
)
from .quantization import (
    dequantize_pytree,
    quantize_pytree,
)
from .tqdm import tqdm
from .random import (
    key_for_process,
    key_for_step,
    load_rng_state_dict,
    rng_state_dict,
    set_seed,
    synchronize_rng_states,
)

from .dataclasses import (
    DataLoaderConfiguration,
    DistributedType,
    FsdpPlugin,
    GradientAccumulationPlugin,
    MixedPrecisionPolicy,
    PrecisionType,
    ProjectConfiguration,
    RNGType,
    ShardingStrategyType,
    TensorParallelPlugin,
)
from .environment import (
    clear_environment,
    get_int_from_env,
    get_str_from_env,
    parse_flag_from_env,
    patch_environment,
    purge_framework_environment,
    str_to_bool,
)
from .profiler import (
    ProfileKwargs,
    annotate,
    estimate_step_flops,
    save_memory_profile,
    step_annotation,
)
from .random import (
    key_for_process,
    key_for_step,
    load_rng_state_dict,
    rng_state_dict,
    set_seed,
    synchronize_rng_states,
)

"""DeepSpeed JSON config ingestion.

Reference parity: `deepspeed_with_config_support` trains from a
user-supplied ``ds_config.json`` (reference
`examples/by_feature/deepspeed_with_config_support.py`,
`utils/deepspeed.py:119` `HfDeepSpeedConfig`). Teams migrating to TPU
usually HAVE such a file; this module maps it onto this framework's
equivalents instead of asking them to re-derive the run configuration:

- ``zero_optimization.stage`` -> `ShardingStrategy` kind (0 = data
  parallel, 1/2 = ZERO1/ZERO2 optimizer-state sharding, 3 = FSDP);
- ``zero_optimization.offload_optimizer.device: cpu`` -> the pinned-host
  optimizer offload (`parallel/host_offload.py`, the ZeRO-Offload analog);
  ``device: nvme`` + ``nvme_path`` -> the disk tier
  (`parallel/disk_offload.py`, the ZeRO-Infinity analog: moments live in
  memmaps under nvme_path and persist across restarts);
- ``fp16`` / ``bf16`` -> ``mixed_precision`` (fp16 keeps dynamic loss
  scaling semantics — the reference's GradScaler/DeepSpeed scaler path —
  and ``loss_scale``/``initial_scale_power``/``loss_scale_window`` map
  onto `DynamicLossScale` via ``loss_scale_config``);
- ``gradient_accumulation_steps`` / ``gradient_clipping`` -> the same-named
  Accelerator knobs;
- ``optimizer`` / ``scheduler`` blocks -> an optax chain
  (`optax_from_deepspeed_config`), covering the Adam/AdamW + WarmupLR /
  WarmupDecayLR configs DeepSpeed examples actually ship.

Knobs that configure NCCL/engine mechanics XLA owns on TPU
(``overlap_comm``, ``contiguous_gradients``, bucket sizes,
``round_robin_gradients``, the ``aio`` IO-engine tuning...) are reported
once via warning and dropped — the compiler schedules collectives and the
disk tier streams via memmaps. Capabilities with no training-time analog
here (parameter CPU/NVMe offload) fail loudly rather than silently
training something else.
"""

from __future__ import annotations

import json
import os
import warnings
from typing import Any

__all__ = [
    "accelerator_kwargs_from_deepspeed_config",
    "optax_from_deepspeed_config",
]

# Engine-mechanics keys XLA owns under GSPMD: dropped with one warning.
_IGNORED_ZERO_KEYS = frozenset(
    {
        "overlap_comm",
        "contiguous_gradients",
        "reduce_bucket_size",
        "allgather_bucket_size",
        "allgather_partitions",
        "reduce_scatter",
        "round_robin_gradients",
        "stage3_prefetch_bucket_size",
        "stage3_param_persistence_threshold",
        "stage3_max_live_parameters",
        "stage3_max_reuse_distance",
        "stage3_gather_16bit_weights_on_model_save",
        "sub_group_size",
        "zero_hpz_partition_size",
        "memory_efficient_linear",
    }
)
_IGNORED_TOP_KEYS = frozenset(
    {
        "steps_per_print",
        "wall_clock_breakdown",
        "zero_allow_untested_optimizer",
        "prescale_gradients",
        "communication_data_type",
        "comms_logger",
        "flops_profiler",
        # Batch sizing belongs to the dataloader here, exactly as the
        # reference computes train_batch_size FROM the prepared loader
        # (`accelerator.py:1745` _prepare_deepspeed) rather than the other
        # way around.
        "train_batch_size",
        "train_micro_batch_size_per_gpu",
    }
)
# Top-level sections this translator consumes (everything else — including
# typos — is refused: an unrecognized section silently changing semantics
# is exactly what this module exists to prevent).
_CONSUMED_TOP_KEYS = frozenset(
    {
        "zero_optimization",
        "fp16",
        "bf16",
        "gradient_accumulation_steps",
        "gradient_clipping",
        "optimizer",
        "scheduler",
        "aio",
    }
)


def _load(config: Any) -> dict:
    if isinstance(config, (str, os.PathLike)):
        with open(os.fspath(config)) as f:
            return json.load(f)
    return dict(config)


def _auto(value: Any, default: Any) -> Any:
    return default if value == "auto" else value


def _require_nvme_path(nvme_path: Any) -> str:
    """Shared nvme validation for both translators — silently downgrading
    to device-resident moments is the failure mode this module refuses."""
    if not nvme_path:
        raise ValueError(
            "offload_optimizer.device='nvme' needs nvme_path (the directory "
            "for the moment memmaps — DeepSpeed requires it too)."
        )
    return nvme_path


def _check_params_block(
    block: str, leftover: dict, *, ignored: tuple[str, ...] = ()
) -> None:
    """Apply the module's warn/refuse policy to a sub-block's REMAINING keys
    (callers pop what they consume first): known-no-analog keys are dropped
    with one warning, anything else raises — a typo'd scheduler param
    silently changing the LR trajectory is exactly the divergence this
    module exists to prevent."""
    dropped = sorted(k for k in leftover if k in ignored)
    if dropped:
        warnings.warn(
            f"ds_config {block} keys with no TPU analog were dropped: {dropped}",
            stacklevel=3,
        )
    unknown = sorted(k for k in leftover if k not in ignored)
    if unknown:
        raise ValueError(
            f"Unrecognized ds_config {block} keys {unknown}; refusing to "
            "silently drop configuration that may change training semantics."
        )


def _warmup_schedule(min_lr: float, max_lr: float, warmup: int, warmup_type: str):
    """DeepSpeed's WarmupLR ramp. Default warmup_type is 'log'
    (deepspeed lr_schedules.WARMUP_LOG_RATE): gamma(t) = log(1+t)/log(W)
    for t < W, then 1 — NOT linear; translating it as linear silently gives
    a different LR trajectory than the team's GPU run."""
    import math

    import optax

    if warmup_type not in ("log", "linear"):
        raise ValueError(
            f"ds scheduler warmup_type={warmup_type!r} is not a DeepSpeed "
            "warmup type; expected 'log' (default) or 'linear'."
        )
    if warmup_type == "linear" or warmup <= 1:
        return optax.schedules.linear_schedule(min_lr, max_lr, max(warmup, 1))
    inv = 1.0 / math.log(warmup)

    def sched(count):
        import jax.numpy as jnp

        t = jnp.minimum(jnp.asarray(count, jnp.float32), float(warmup - 1))
        gamma = jnp.minimum(jnp.log1p(t) * inv, 1.0)
        return min_lr + (max_lr - min_lr) * gamma

    return sched


def accelerator_kwargs_from_deepspeed_config(config: Any) -> dict[str, Any]:
    """ds_config (path or dict) -> keyword arguments for `Accelerator`.

    Returns a dict with (some of) ``strategy``, ``mixed_precision``,
    ``gradient_accumulation_steps``, ``max_grad_norm`` — splat it:
    ``Accelerator(**accelerator_kwargs_from_deepspeed_config(path))``."""
    from ..parallel.sharding import ShardingStrategy, ShardingStrategyType

    cfg = _load(config)
    kwargs: dict[str, Any] = {}

    zero = dict(cfg.get("zero_optimization", {}))
    stage = _auto(zero.pop("stage", 0), 0)
    offload_opt = zero.pop("offload_optimizer", None)
    offload_param = zero.pop("offload_param", None)
    if offload_param and offload_param.get("device", "none") != "none":
        raise ValueError(
            "zero_optimization.offload_param is a training-time parameter "
            "offload; this framework offloads parameters for INFERENCE "
            "(big_modeling.offload_blocks) but declines it for training — "
            "use FSDP sharding (stage 3) plus offload_optimizer instead."
        )
    if cfg.get("aio"):
        # aio tunes DeepSpeed's async-IO engine (queue depth, block size);
        # the disk tier here streams through numpy memmaps — engine
        # mechanics with no analog, same policy as the NCCL knobs.
        warnings.warn(
            "ds_config aio block tunes DeepSpeed's NVMe IO engine and has "
            "no analog here (the disk tier streams via memmaps); dropped.",
            stacklevel=2,
        )
    offload = False
    offload_device: str | None = None
    if offload_opt is not None:
        offload_opt = dict(offload_opt)
        device = offload_opt.pop("device", "none")
        nvme_path = offload_opt.pop("nvme_path", None)
        _check_params_block(
            "zero_optimization.offload_optimizer",
            offload_opt,
            # IO-engine tuning knobs: the memmap tier has no analog.
            ignored=(
                "pin_memory",
                "buffer_count",
                "fast_init",
                "ratio",
                "pipeline",
                "pipeline_read",
                "pipeline_write",
            ),
        )
        if device == "cpu":
            offload = True
            offload_device = "cpu"
        elif device == "nvme":
            # ZeRO-Infinity NVMe tier: moments live on disk. Handled by the
            # OPTIMIZER object (optax_from_deepspeed_config returns
            # disk_offloaded_adamw bound to nvme_path), not by the sharding
            # placement machinery — so `offload` stays False here. The
            # REQUEST is still recorded on the strategy
            # (offload_optimizer_device) so create_train_state fails loudly
            # when handed a non-disk-offloaded optimizer, exactly as the
            # cpu tier refuses a non-streamable one.
            _require_nvme_path(nvme_path)
            offload_device = "nvme"
        elif device not in ("none",):
            raise ValueError(
                f"offload_optimizer.device={device!r} is not supported; "
                "'cpu' maps to the pinned-host optimizer offload, 'nvme' "
                "to the disk tier (parallel/disk_offload.py)."
            )

    kind = {
        0: ShardingStrategyType.DATA_PARALLEL,
        1: ShardingStrategyType.ZERO1,
        2: ShardingStrategyType.ZERO2,
        3: ShardingStrategyType.FSDP,
    }.get(int(stage))
    if kind is None:
        raise ValueError(f"zero_optimization.stage={stage!r} is not a DeepSpeed stage.")
    if kind != ShardingStrategyType.DATA_PARALLEL or offload_device is not None:
        kwargs["strategy"] = ShardingStrategy(
            kind=kind,
            offload_optimizer=offload,
            offload_optimizer_device=offload_device,
        )

    fp16 = dict(cfg.get("fp16", {}))
    fp16_enabled = _auto(fp16.pop("enabled", False), False)
    # DeepSpeed fp16 loss-scaling knobs map onto DynamicLossScale (the
    # GradScaler analog): loss_scale=0 means dynamic, >0 pins a static
    # scale (growth/backoff disabled); initial_scale_power and
    # loss_scale_window carry their DeepSpeed meanings.
    ls_cfg: dict[str, Any] = {}
    static_scale = float(_auto(fp16.pop("loss_scale", 0), 0))
    power = fp16.pop("initial_scale_power", None)
    window = fp16.pop("loss_scale_window", None)
    if static_scale:
        ls_cfg = {
            "init_scale": static_scale,
            "growth_factor": 1.0,
            "backoff_factor": 1.0,
        }
    else:
        if power is not None:
            ls_cfg["init_scale"] = 2.0 ** int(_auto(power, 16))
        if window is not None:
            ls_cfg["growth_interval"] = int(_auto(window, 1000))
    if fp16_enabled:
        # Disabled blocks are inert — their keys cannot change semantics,
        # so only an ENABLED block gets the warn/refuse policy.
        _check_params_block(
            "fp16",
            fp16,
            ignored=(
                "hysteresis",
                "consecutive_hysteresis",
                "min_loss_scale",
                "auto_cast",
                "fp16_master_weights_and_grads",
            ),
        )
    bf16 = dict(cfg.get("bf16", {}))
    bf16_enabled = _auto(bf16.pop("enabled", False), False)
    if bf16_enabled:
        _check_params_block("bf16", bf16, ignored=("immediate_grad_update",))
    if fp16_enabled:
        kwargs["mixed_precision"] = "fp16"
        if ls_cfg:
            kwargs["loss_scale_config"] = ls_cfg
    elif bf16_enabled:
        kwargs["mixed_precision"] = "bf16"

    accum = _auto(cfg.get("gradient_accumulation_steps", 1), 1)
    if accum != 1:
        kwargs["gradient_accumulation_steps"] = int(accum)
    clip = _auto(cfg.get("gradient_clipping", None), None)
    if clip is not None:
        kwargs["max_grad_norm"] = float(clip)

    dropped = sorted(
        [k for k in zero if k in _IGNORED_ZERO_KEYS]
        + [k for k in cfg if k in _IGNORED_TOP_KEYS]
    )
    if dropped:
        warnings.warn(
            "ds_config keys with no TPU analog were dropped (XLA owns the "
            f"collective schedule; batch size belongs to the loader): {dropped}",
            stacklevel=2,
        )
    unknown = sorted(k for k in zero if k not in _IGNORED_ZERO_KEYS)
    if unknown:
        raise ValueError(
            f"Unrecognized zero_optimization keys {unknown}; refusing to "
            "silently drop configuration that may change training semantics."
        )
    unknown_top = sorted(
        k for k in cfg if k not in _CONSUMED_TOP_KEYS and k not in _IGNORED_TOP_KEYS
    )
    if unknown_top:
        raise ValueError(
            f"Unrecognized ds_config sections {unknown_top} (typo, or a "
            "capability with no analog here — e.g. activation_checkpointing "
            "maps to the model config's remat=True); refusing to silently "
            "train something else."
        )
    return kwargs


def optax_from_deepspeed_config(config: Any, *, total_num_steps: int | None = None):
    """Build the optax optimizer (+LR schedule) the ds_config's
    ``optimizer``/``scheduler`` blocks describe.

    Covers what DeepSpeed configs actually ship: Adam/AdamW (torch_adam or
    fused makes no difference here) and WarmupLR / WarmupDecayLR.
    ``total_num_steps`` substitutes a WarmupDecayLR whose
    ``total_num_steps`` is "auto" (the reference fills these from the
    prepared dataloader the same way)."""
    import optax

    cfg = _load(config)
    opt_block = cfg.get("optimizer")
    if opt_block is None:
        raise ValueError(
            "ds_config has no optimizer block; construct the optax chain "
            "directly instead of calling optax_from_deepspeed_config."
        )
    name = opt_block.get("type", "AdamW")
    p = {k.lower(): v for k, v in dict(opt_block.get("params", {})).items()}
    lr = float(_auto(p.pop("lr", 1e-3), 1e-3))
    # Remaining params are consumed PER OPTIMIZER below, so e.g. `momentum`
    # on AdamW (torch would reject it) or `betas` on SGD hit the same
    # warn/refuse policy instead of being silently eaten.

    sched_block = cfg.get("scheduler")
    schedule = lr
    if sched_block is not None:
        sname = sched_block.get("type")
        sp = dict(sched_block.get("params", {}))
        warmup = int(_auto(sp.pop("warmup_num_steps", 0), 0))
        max_lr = float(_auto(sp.pop("warmup_max_lr", lr), lr))
        min_lr = float(_auto(sp.pop("warmup_min_lr", 0.0), 0.0))
        # DeepSpeed's default warmup ramp is LOG, not linear.
        warmup_type = str(_auto(sp.pop("warmup_type", "log"), "log"))
        if sname == "WarmupLR":
            _check_params_block(
                "scheduler.params", sp, ignored=("last_batch_iteration",)
            )
            # DeepSpeed WarmupLR: min->max over warmup (log by default),
            # then CONSTANT at max.
            schedule = _warmup_schedule(min_lr, max_lr, warmup, warmup_type)
        elif sname == "WarmupDecayLR":
            total = _auto(sp.pop("total_num_steps", total_num_steps), total_num_steps)
            _check_params_block(
                "scheduler.params", sp, ignored=("last_batch_iteration",)
            )
            if total is None:
                raise ValueError(
                    "WarmupDecayLR.total_num_steps is 'auto'/absent: pass "
                    "total_num_steps= (the reference fills it from the "
                    "prepared dataloader length the same way)."
                )
            total = int(total)
            if total <= warmup:
                raise ValueError(
                    f"WarmupDecayLR needs total_num_steps ({total}) > "
                    f"warmup_num_steps ({warmup})."
                )
            # DeepSpeed WarmupDecayLR: warmup ramp (log by default), then
            # LINEAR max->0 at total_num_steps (NOT cosine — the schedule
            # must match or the loss trajectory silently diverges from the
            # team's GPU run).
            schedule = optax.schedules.join_schedules(
                [
                    _warmup_schedule(min_lr, max_lr, warmup, warmup_type),
                    optax.schedules.linear_schedule(max_lr, 0.0, total - warmup),
                ],
                boundaries=[max(warmup, 1)],
            )
        else:
            raise ValueError(
                f"Unimplemented ds scheduler type {sname!r}; implemented: "
                "WarmupLR, WarmupDecayLR."
            )

    # The SAME config's offload request changes which optimizer object is
    # valid: Accelerator.create_train_state refuses offload_optimizer with
    # a non-streamable optimizer (accelerator.py `_offload_opt_placement`),
    # so the translator must hand back the offload-aware one. 'nvme' maps
    # to the disk tier (`parallel/disk_offload.py`), whose moments live in
    # memmaps under nvme_path.
    offload_block = (
        dict(cfg.get("zero_optimization", {})).get("offload_optimizer", {}) or {}
    )
    offload = offload_block.get("device") == "cpu"
    nvme_path = None
    if offload_block.get("device") == "nvme":
        nvme_path = _require_nvme_path(offload_block.get("nvme_path"))

    lname = name.lower()
    if lname in ("adam", "adamw"):
        betas = p.pop("betas", (0.9, 0.999))
        b1, b2 = (0.9, 0.999) if betas == "auto" else tuple(float(b) for b in betas)
        eps = float(_auto(p.pop("eps", 1e-8), 1e-8))
        wd = float(_auto(p.pop("weight_decay", 0.0), 0.0))
        adam_w_mode = p.pop("adam_w_mode", True)
        # torch_adam/fused pick a kernel, not semantics, on the reference side.
        _check_params_block("optimizer.params", p, ignored=("torch_adam", "fused"))
        decoupled = lname == "adamw" or adam_w_mode or wd == 0.0
        if not decoupled:
            # DeepSpeed plain Adam applies weight decay as L2-in-loss;
            # nothing here reproduces that silently.
            if offload or nvme_path:
                raise ValueError(
                    "offload_optimizer with non-decoupled Adam weight decay "
                    "(adam_w_mode=false) has no analog; use AdamW."
                )
            opt = optax.adam(schedule, b1=b1, b2=b2, eps=eps)
            return optax.chain(optax.add_decayed_weights(wd), opt)
        if nvme_path:
            from ..parallel.disk_offload import disk_offloaded_adamw

            return disk_offloaded_adamw(
                schedule, offload_dir=nvme_path, b1=b1, b2=b2, eps=eps,
                weight_decay=wd,
            )
        if offload:
            from ..parallel.host_offload import host_offloaded_adamw

            return host_offloaded_adamw(
                schedule, b1=b1, b2=b2, eps=eps, weight_decay=wd
            )
        return optax.adamw(schedule, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    if offload or nvme_path:
        raise ValueError(
            f"offload_optimizer is implemented for Adam/AdamW only, not {name!r}."
        )
    if lname == "sgd":
        momentum = float(_auto(p.pop("momentum", 0.0), 0.0))
        wd = float(_auto(p.pop("weight_decay", 0.0), 0.0))
        _check_params_block("optimizer.params", p)
        opt = optax.sgd(schedule, momentum=momentum)
        if wd:
            # torch SGD weight decay is coupled L2 (added to the gradient
            # BEFORE momentum) — add_decayed_weights ahead of the update
            # reproduces it exactly.
            return optax.chain(optax.add_decayed_weights(wd), opt)
        return opt
    raise ValueError(
        f"Unimplemented ds optimizer type {name!r}; implemented: AdamW, "
        "Adam, SGD."
    )

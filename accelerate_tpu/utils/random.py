"""Seeding & PRNG management.

Analog of the reference `utils/random.py` (`set_seed` :39,
`synchronize_rng_states` :154). The reference must *broadcast* rank-0 RNG
state to keep torch generators aligned; JAX PRNG keys are pure values derived
from an integer seed, so cross-process agreement is achieved by construction —
every process derives the same root key, and per-process/per-step streams are
``fold_in``s of it. What still needs explicit state management is the *host*
RNG used for data shuffling (numpy / python random), which checkpointing must
capture (reference `checkpointing.py:148-171`).
"""

from __future__ import annotations

import random as _py_random
from typing import Any, Iterable

import jax
import numpy as np


def set_seed(seed: int, *, device_specific: bool = False) -> jax.Array:
    """Seed python/numpy RNGs and return the root JAX PRNG key.

    With ``device_specific=True`` the returned key is folded with the process
    index (reference `set_seed(..., device_specific=True)` adds rank to seed).
    """
    _py_random.seed(seed)
    np.random.seed(seed % (2**32))
    key = jax.random.PRNGKey(seed)
    if device_specific:
        key = jax.random.fold_in(key, jax.process_index())
    return key


def key_for_step(root: jax.Array, step: int) -> jax.Array:
    """Deterministic per-step stream: fold the step counter into the root key."""
    return jax.random.fold_in(root, step)


def key_for_process(root: jax.Array, process_index: int | None = None) -> jax.Array:
    if process_index is None:
        process_index = jax.process_index()
    return jax.random.fold_in(root, process_index)


def split_for_devices(root: jax.Array, n: int) -> jax.Array:
    return jax.random.split(root, n)


def rng_state_dict() -> dict[str, Any]:
    """Capture host RNG state (python, numpy) for checkpointing."""
    return {
        "python": _py_random.getstate(),
        "numpy": np.random.get_state(),
    }


def load_rng_state_dict(state: dict[str, Any]) -> None:
    if "python" in state:
        _py_random.setstate(state["python"])
    if "numpy" in state:
        np_state = state["numpy"]
        if isinstance(np_state, (list, tuple)) and len(np_state) == 5:
            np_state = (
                np_state[0],
                np.asarray(np_state[1], dtype=np.uint32),
                int(np_state[2]),
                int(np_state[3]),
                float(np_state[4]),
            )
        np.random.set_state(np_state)


def synchronize_rng_states(kinds: Iterable[str] = ("python", "numpy")) -> None:
    """Force all processes to the main process's host RNG state.

    Cross-process host RNG agreement (reference `utils/random.py:78-156`).
    JAX device PRNG never needs this; only host-side shuffling does, and the
    framework's samplers are seeded deterministically anyway — this exists for
    user code that consumed host randomness unevenly across ranks.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    seed = np.zeros((), dtype=np.int64)
    if jax.process_index() == 0:
        seed = np.asarray(np.random.randint(0, 2**31 - 1), dtype=np.int64)
    seed = int(multihost_utils.broadcast_one_to_all(seed))
    kinds = set(kinds)
    if "python" in kinds:
        _py_random.seed(seed)
    if "numpy" in kinds:
        np.random.seed(seed % (2**32))

"""Per-device-kind fp8 matmul speedup telemetry.

fp8 on a chip without fp8 MXU support is a lose-lose: XLA upcasts the
scaled values, so you pay quantization error for zero speedup (measured
0.51x on TPU v5e, BENCH_r03 `fp8_matmul_speedup`). The launcher refuses
`--mixed_precision fp8` on device kinds with recorded speedup <= 1 unless
`--force_fp8` is passed (reference analog: the TE/ao fp8 recipes are only
wired for hardware that benefits, `utils/ao.py:103`).

`bench.py` records fresh measurements here, so the table self-updates the
first time a bench runs on a new chip generation.
"""

from __future__ import annotations

import json
import os

# Measured by bench.py on real hardware (kind -> fp8/bf16 matmul speedup).
# v5e has no fp8 MXU: the fp8 path lowers to upcast-and-multiply.
_BUILTIN: dict[str, float] = {
    "TPU v5 lite": 0.51,  # BENCH_r03 fp8_matmul_speedup
}


def _store_path() -> str:
    root = os.environ.get("ATX_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "accelerate_tpu"
    )
    return os.path.join(root, "fp8_telemetry.json")


def record(device_kind: str, speedup: float) -> None:
    """Persist a measured fp8 speedup for this device kind (bench.py)."""
    path = _store_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    data: dict[str, float] = {}
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        pass
    data[device_kind] = float(speedup)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f)
    os.replace(tmp, path)


def lookup(device_kind: str) -> float | None:
    """Recorded speedup for this device kind; measurements override the
    built-in table, None when the kind has never been measured."""
    try:
        with open(_store_path()) as f:
            data = json.load(f)
        if device_kind in data:
            return float(data[device_kind])
    except (OSError, ValueError):
        pass
    return _BUILTIN.get(device_kind)

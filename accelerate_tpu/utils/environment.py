"""Environment-variable helpers.

TPU-native analog of the reference environment layer
(`/root/reference/src/accelerate/utils/environment.py`): typed env parsing, a
context manager for temporarily patching the environment (used heavily by the
test suite), and detection of the JAX runtime platform.

All framework env vars use the ``ATX_`` prefix (mirroring the reference's
``ACCELERATE_`` contract, `utils/launch.py:98-470`) so the launcher can
configure the library in child processes purely through the environment.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Iterator

_TRUE = {"1", "true", "yes", "y", "on"}
_FALSE = {"0", "false", "no", "n", "off", ""}


def str_to_bool(value: str) -> bool:
    value = value.strip().lower()
    if value in _TRUE:
        return True
    if value in _FALSE:
        return False
    raise ValueError(f"Cannot interpret {value!r} as a boolean")


def parse_flag_from_env(key: str, default: bool = False) -> bool:
    value = os.environ.get(key)
    if value is None:
        return default
    return str_to_bool(value)


def get_int_from_env(keys: list[str] | tuple[str, ...], default: int) -> int:
    for key in keys:
        value = os.environ.get(key)
        if value is not None and value != "":
            return int(value)
    return default


def get_str_from_env(keys: list[str] | tuple[str, ...], default: str = "") -> str:
    for key in keys:
        value = os.environ.get(key)
        if value is not None and value != "":
            return value
    return default


@contextmanager
def patch_environment(**kwargs: Any) -> Iterator[None]:
    """Temporarily set env vars (upper-cased keys), restoring prior state on exit.

    Mirrors the reference helper at `utils/environment.py:291-360`; pass
    ``key=None`` to unset a variable for the duration of the block.
    """
    saved: dict[str, str | None] = {}
    for key, value in kwargs.items():
        key = key.upper()
        saved[key] = os.environ.get(key)
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = str(value)
    try:
        yield
    finally:
        for key, old in saved.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old


@contextmanager
def clear_environment(prefixes: tuple[str, ...] = ("ATX_",)) -> Iterator[None]:
    """Remove all framework env vars for the duration of the block."""
    saved = {k: v for k, v in os.environ.items() if k.startswith(prefixes)}
    for k in saved:
        del os.environ[k]
    try:
        yield
    finally:
        os.environ.update(saved)


def purge_framework_environment() -> None:
    """Unconditionally remove every ``ATX_*`` env var (test isolation helper)."""
    for key in [k for k in os.environ if k.startswith("ATX_")]:
        del os.environ[key]

"""Configuration dataclasses & enums.

Analog of the reference `utils/dataclasses.py` (2,620 LoC of plugins/enums).
The TPU design needs far fewer knobs because whole subsystems (DDP comm hooks,
GradScaler, dynamo backends, per-vendor process groups) have no equivalent —
they collapse into mesh shape + PartitionSpecs + dtype policy. Every config
here supports the same env-var fallback contract as the reference (plugin
``__post_init__`` reading ``ACCELERATE_*`` — here ``ATX_*``) so the launcher
can configure child processes through the environment.
"""

from __future__ import annotations

import dataclasses
import enum
import os
from dataclasses import dataclass, field
from typing import Any

import jax.numpy as jnp

from .environment import get_int_from_env, parse_flag_from_env


class BaseEnum(str, enum.Enum):
    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return self.value

    @classmethod
    def list(cls) -> list[str]:
        return [e.value for e in cls]


class DistributedType(BaseEnum):
    """Runtime topology (reference `DistributedType`, `dataclasses.py:552`).

    The reference enumerates backends (MULTI_GPU/DEEPSPEED/FSDP/XLA/...); on
    TPU the runtime question is only "how many processes/devices", and the
    *strategy* question lives in `ShardingStrategyType`.
    """

    NO = "NO"
    MULTI_DEVICE = "MULTI_DEVICE"  # 1 process, >1 local device (SPMD)
    MULTI_HOST = "MULTI_HOST"  # >1 process (TPU pod slice / DCN)


class ShardingStrategyType(BaseEnum):
    """How params/grads/optimizer state are laid out on the mesh.

    Maps the reference's plugin zoo onto PartitionSpec policies:
    - DATA_PARALLEL: replicate params (reference DDP, `accelerator.py:1519`)
    - ZERO1: replicate params, shard optimizer state over data axis
      (DeepSpeed stage 1, `utils/dataclasses.py:1019`)
    - ZERO2: accepted as an alias of ZERO1. DeepSpeed stage 2 additionally
      shards GRADIENT buffers; in a fused XLA step gradients are ephemeral
      intermediates with no persistent buffer to shard, and XLA already
      lowers the update to reduce-scatter + sharded-moment updates when the
      optimizer state is sharded — the two stages compile to the same
      program here, so the distinction is intentionally collapsed.
    - FSDP: shard params+grads+opt over the fsdp axis (torch FSDP
      FULL_SHARD / ZeRO-3, `utils/dataclasses.py:1449`)
    - TENSOR_PARALLEL: shard weight matrices over the tensor axis
      (`utils/dataclasses.py:1863`)
    - HYBRID: any combination via explicit mesh shape + rules.
    """

    DATA_PARALLEL = "DATA_PARALLEL"
    ZERO1 = "ZERO1"
    ZERO2 = "ZERO2"
    FSDP = "FSDP"
    TENSOR_PARALLEL = "TENSOR_PARALLEL"
    HYBRID = "HYBRID"


class PrecisionType(BaseEnum):
    NO = "no"
    BF16 = "bf16"
    FP16 = "fp16"
    FP8 = "fp8"


class RNGType(BaseEnum):
    PYTHON = "python"
    NUMPY = "numpy"
    JAX = "jax"


_DTYPES = {
    PrecisionType.NO: jnp.float32,
    PrecisionType.BF16: jnp.bfloat16,
    PrecisionType.FP16: jnp.float16,
}


@dataclass
class MixedPrecisionPolicy:
    """Dtype policy: fp32 master params, low-precision compute.

    Replaces torch autocast + GradScaler (reference `accelerator.py:528-577`,
    `utils/modeling.py:2011-2054`): bf16 is the TPU-native choice and needs
    no loss scaling; fp16 is supported and automatically paired with a
    dynamic loss scaler inside the train step (`DynamicLossScale`,
    accelerator.py).
    """

    # None = leave params / reported metrics at whatever dtype the model was
    # initialized with (the bf16-weights training recipe inits params in
    # bf16 on purpose — a blanket fp32 default would silently undo it).
    # Set explicitly to force master-param or metric dtypes:
    # param_dtype is consumed by `Accelerator.create_train_state`,
    # output_dtype by the train step's reported metrics.
    param_dtype: Any = None
    compute_dtype: Any = jnp.float32
    output_dtype: Any = None
    # fp8 is NOT a blanket cast (that would silently produce garbage): it
    # keeps bf16 activations/params at call boundaries and routes the
    # matmul-shaped einsums through dynamically-scaled e4m3/e5m2
    # contractions (`ops/fp8.py` — the torchao-recipe analog of the
    # reference's `utils/ao.py:103` `convert_model_to_fp8_ao`).
    fp8: bool = False

    @classmethod
    def from_precision(cls, precision: str | PrecisionType) -> "MixedPrecisionPolicy":
        precision = PrecisionType(precision)
        if precision == PrecisionType.FP8:
            return cls(compute_dtype=jnp.bfloat16, fp8=True)
        if precision == PrecisionType.NO:
            return cls()
        return cls(compute_dtype=_DTYPES[precision])

    def cast_for_compute(self, tree: Any) -> Any:
        import jax

        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if hasattr(x, "astype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )


@dataclass
class GradientAccumulationPlugin:
    """Reference `GradientAccumulationPlugin` (`dataclasses.py:920`).

    ``adjust_scheduler`` keeps its reference meaning (`scheduler.py:62`: the
    LR schedule advances once per *microbatch*, not once per optimizer
    update): it is consumed by `Accelerator.prepare_scheduler`, which wraps
    an optax schedule so ``schedule(count)`` is evaluated at
    ``count * num_steps``.

    ``sync_with_dataloader=True`` (reference `accelerator.py:1092`: reset
    the accumulation window at end of dataloader) is guaranteed *by
    construction* here — the whole window lives inside one compiled step, so
    a window can never span a dataloader boundary. ``False`` (let a window
    straddle epochs) is inexpressible in the intra-step design and is
    rejected loudly rather than silently ignored.

    ``sync_each_batch`` is irrelevant on TPU (there is no unsynced gradient
    hook to manage) and intentionally has no field.
    """

    num_steps: int | None = None
    adjust_scheduler: bool = True
    sync_with_dataloader: bool = True

    def __post_init__(self) -> None:
        if self.num_steps is None:
            self.num_steps = get_int_from_env(("ATX_GRADIENT_ACCUMULATION_STEPS",), 1)
        if not self.sync_with_dataloader:
            raise ValueError(
                "sync_with_dataloader=False (accumulation windows spanning a "
                "dataloader boundary) is not supported: accumulation runs "
                "inside one compiled step, so every window both starts and "
                "syncs within a single global batch. Drop the flag — the "
                "True behavior is structural."
            )


@dataclass
class DataLoaderConfiguration:
    """Reference `DataLoaderConfiguration` (`dataclasses.py:762`).

    Two reference knobs intentionally have no analog here: samplers are
    always deterministic-seedable (`use_seedable_sampler` is permanently on
    by construction, `data/sampler.py`), and host->device prefetch is always
    asynchronous (`non_blocking`).

    ``dispatch_batches=None`` resolves per dataset kind exactly like the
    reference (`data_loader.py:1085-1089`): False for indexable datasets
    (the seeded sampler guarantees identical shards), True for iterable
    datasets (per-process streams may diverge; the main process reads and
    broadcasts)."""

    split_batches: bool = False
    dispatch_batches: bool | None = None
    even_batches: bool = True
    prefetch_size: int = 2


@dataclass
class ProjectConfiguration:
    """Reference `ProjectConfiguration` (`dataclasses.py:857`)."""

    project_dir: str | None = None
    logging_dir: str | None = None
    automatic_checkpoint_naming: bool = False
    total_limit: int | None = None
    iteration: int = 0
    save_on_each_node: bool = False

    def set_directories(self, project_dir: str | None = None) -> None:
        self.project_dir = project_dir
        if self.logging_dir is None:
            self.logging_dir = project_dir

    def __post_init__(self) -> None:
        self.set_directories(self.project_dir)


@dataclass
class FsdpPlugin:
    """FSDP/ZeRO-3-style sharding config (reference `dataclasses.py:1449-1861`).

    ``min_weight_size`` mirrors size-based auto-wrap: tensors smaller than
    this stay replicated (sharding tiny params wastes collective latency).
    ``state_dict_type`` chooses consolidated vs sharded layout for
    `Accelerator.save_model` (reference FULL_STATE_DICT / SHARDED_STATE_DICT,
    `constants.py:39`).

    Reference knobs with no analog here:
    - ``reshard_after_forward``: XLA owns the gather/reshard schedule under
      GSPMD — there is no user-visible FULL_SHARD vs SHARD_GRAD_OP choice.
    - training-time ``cpu_offload``: host offload exists for inference in
      `big_modeling.offload_blocks`.
    - ``activation_checkpointing``: activation remat must be segmented
      per block *inside* the layer scan to reduce peak memory (one
      `jax.checkpoint` around the whole loss recomputes everything while
      changing peak HBM ~not at all); it is therefore a model-structure
      concern — set ``remat=True`` (and ``remat_policy``) on the model
      config (`LlamaConfig.remat`, `BertConfig.remat`).
    """

    min_weight_size: int = 2**11
    state_dict_type: str = "SHARDED_STATE_DICT"
    # ZeRO-Offload analog (reference DeepSpeed offload_optimizer,
    # `utils/dataclasses.py:1019-1111`; FSDP cpu_offload, :1449-1861):
    # optimizer moments live in pinned host RAM, moved to HBM only around
    # the update inside the compiled step (parallel/host_offload.py).
    # Env: ATX_OFFLOAD_OPTIMIZER=1 (any strategy, not just FSDP).
    offload_optimizer: bool = False

    def __post_init__(self) -> None:
        if parse_flag_from_env("ATX_OFFLOAD_OPTIMIZER"):
            self.offload_optimizer = True
        if parse_flag_from_env("ATX_FSDP_ACTIVATION_CHECKPOINTING"):
            # Fail loudly instead of silently dropping remat from a run that
            # used the old env contract.
            raise ValueError(
                "ATX_FSDP_ACTIVATION_CHECKPOINTING is no longer consumed: "
                "activation remat is a model-structure concern — set "
                "remat=True on the model config (LlamaConfig.remat / "
                "BertConfig.remat) instead."
            )
        env_sdt = os.environ.get("ATX_FSDP_STATE_DICT_TYPE")
        if env_sdt:
            self.state_dict_type = env_sdt
        if self.state_dict_type not in ("SHARDED_STATE_DICT", "FULL_STATE_DICT"):
            raise ValueError(
                f"state_dict_type must be SHARDED_STATE_DICT or FULL_STATE_DICT, "
                f"got {self.state_dict_type!r}"
            )


@dataclass
class TensorParallelPlugin:
    """TP config (reference `dataclasses.py:1863-1895`): mesh size + plan name."""

    tp_size: int | None = None
    plan: str | None = None  # named rule-set in parallel/tp.py registry

    def __post_init__(self) -> None:
        if self.tp_size is None:
            self.tp_size = get_int_from_env(("ATX_TP_SIZE",), 1)


def asdict_not_none(obj: Any) -> dict[str, Any]:
    return {
        k: v for k, v in dataclasses.asdict(obj).items() if v is not None
    }

"""Memory utilities: OOM-retry batch-size search + HBM introspection.

Analog of the reference `utils/memory.py` (`find_executable_batch_size`,
:120-177; `release_memory` :52; `should_reduce_batch_size` :98). The CUDA
OOM story translates to XLA as follows: an over-HBM allocation surfaces as an
`XlaRuntimeError` whose message carries ``RESOURCE_EXHAUSTED`` — it can be
raised at compile time (XLA's static memory planner rejects the program) or
at execution time (transient allocations). Both are caught; both are retried
at half the batch size after dropping compiled-executable caches (each cached
executable pins its workspace reservation).
"""

from __future__ import annotations

import functools
import gc
import inspect
from typing import Any, Callable

import jax


def _logger():
    # Deferred: utils is imported by state.py, and logging.py imports state —
    # a top-level import here would close that cycle.
    from ..logging import get_logger

    return get_logger(__name__)


def clear_device_cache(garbage_collection: bool = False) -> None:
    """Drop jit caches (and their pinned workspace reservations); optionally
    run the host GC first so dead device buffers are freed too."""
    if garbage_collection:
        gc.collect()
    jax.clear_caches()


def release_memory(*objects: Any) -> list[Any]:
    """Sever references so device buffers can be freed (reference
    `utils/memory.py:52`): ``a, b = release_memory(a, b)``."""
    out = [None for _ in objects]
    del objects
    clear_device_cache(garbage_collection=True)
    return out


# Exact XLA status strings only — broad phrases would misclassify unrelated
# user errors (e.g. "sequence length exceeds the limit") as retryable OOMs.
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "Resource exhausted",
)


def should_reduce_batch_size(exception: BaseException) -> bool:
    """Is this exception an out-of-memory condition worth retrying smaller?
    (reference `should_reduce_batch_size`, `utils/memory.py:98`)."""
    if isinstance(exception, MemoryError):
        return True
    # Execution OOM surfaces as jax.errors.JaxRuntimeError (a RuntimeError
    # subclass); compile-time rejections from the static memory planner can
    # arrive as ValueError. Both carry the RESOURCE_EXHAUSTED status string.
    if isinstance(exception, (RuntimeError, ValueError)):
        msg = str(exception)
        return any(marker in msg for marker in _OOM_MARKERS)
    return False


def find_executable_batch_size(
    function: Callable | None = None,
    starting_batch_size: int = 128,
) -> Callable:
    """Decorator: run ``function(batch_size, ...)``, halving ``batch_size``
    on every XLA OOM until it executes or reaches zero (reference
    `find_executable_batch_size`, `utils/memory.py:120`).

    The wrapped function must take ``batch_size`` as its first parameter —
    the decorator injects it, callers pass only the remaining arguments::

        @find_executable_batch_size(starting_batch_size=512)
        def train(batch_size, state):
            loader = acc.prepare_data_loader(ds, batch_size=batch_size)
            ...

    Each retry clears compiled caches first: the failed compile's workspace
    reservation would otherwise still be held during the smaller attempt.
    """
    if function is None:
        return functools.partial(
            find_executable_batch_size, starting_batch_size=starting_batch_size
        )

    batch_size = starting_batch_size
    params = list(inspect.signature(function).parameters.keys())
    if not params or params[0] == "self":
        # Bound methods would receive batch_size in the `self` slot.
        raise TypeError(
            f"{function.__name__} must be a plain function taking `batch_size` "
            "as its first parameter to use find_executable_batch_size"
        )

    @functools.wraps(function)
    def wrapper(*args: Any, **kwargs: Any):
        nonlocal batch_size
        last_oom: Exception | None = None
        while True:
            if batch_size == 0:
                raise RuntimeError(
                    "No executable batch size found: reached zero after "
                    f"halving from {starting_batch_size}."
                ) from last_oom
            try:
                return function(batch_size, *args, **kwargs)
            except Exception as e:
                if not should_reduce_batch_size(e):
                    raise
                last_oom = e
                _logger().warning(
                    "Batch size %d hit device OOM (%s); retrying with %d",
                    batch_size,
                    type(e).__name__,
                    batch_size // 2,
                )
                batch_size //= 2
                clear_device_cache(garbage_collection=True)

    return wrapper


def get_memory_stats(device: jax.Device | None = None) -> dict[str, int]:
    """Per-device HBM stats from the PJRT client (`bytes_in_use`,
    `peak_bytes_in_use`, `bytes_limit`, ...). Empty dict on backends that
    don't expose them (CPU)."""
    device = device if device is not None else jax.local_devices()[0]
    try:
        return dict(device.memory_stats() or {})
    except Exception:
        return {}

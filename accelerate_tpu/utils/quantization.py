"""Weight-only int8 quantization for inference.

Analog of the reference bitsandbytes integration (`utils/bnb.py:44`
`load_and_quantize_model`: 8-bit weight storage, compute in higher
precision). The TPU-native translation: symmetric per-channel int8 with an
fp32 scale per output channel, stored as a small ``{"__quant__", "scale"}``
pytree node; weights dequantize to the compute dtype AT USE — per layer,
inside the scan — so HBM holds int8 (2x less than bf16, 4x less than fp32)
while the MXU still sees bf16 operands (TPU int8 matmul would need
activation quantization too; weight-only is the accuracy-safe default, same
trade as bnb's int8 with fp16 compute).

Not a training path: quantize AFTER training / at load, for inference.
`models/llama.py` dequantizes transparently when it sees quantized blocks.
"""

from __future__ import annotations

import re
from typing import Any

import jax
import jax.numpy as jnp

_QUANT_KEY = "__quant__"
_QUANT4_KEY = "__quant4__"

# Leaves that stay full precision: cheap, sensitive, integer-indexed, or
# consumed outside the per-block dequant (embedding lookup / head matmul).
DEFAULT_SKIP_PATTERNS = (
    r"norm",
    r"scale",
    r"bias",
    r"router",
    r"(^|/)b$",
    r"embed",
    r"head",
    r"pooler",
    r"classifier",
)


def is_quantized(x: Any) -> bool:
    return isinstance(x, dict) and (_QUANT_KEY in x or _QUANT4_KEY in x)


def _quantize_impl(xp: Any, w32: Any, stack_dims: int | None, bits: int) -> dict[str, Any]:
    """Shared int8/int4 packing math, parameterized on the array namespace
    (``jnp`` on device, ``np`` for the host quantize-on-load path) so the
    two entry points cannot drift apart.

    The numpy path runs IN PLACE through one f32 scratch buffer (``out=``
    on every ufunc): the naive expression allocates ~5 leaf-sized temps,
    and on the 1-core load host those allocations/page faults — not the
    arithmetic — dominated quantize-on-load (measured 41 MiB/s; the 8B
    load spent 817 s here)."""
    import numpy as _np

    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    if stack_dims is None:
        stack_dims = 1 if w32.ndim >= 3 else 0
    stack_dims = min(stack_dims, max(w32.ndim - 2, 0))
    reduce_axes = tuple(range(stack_dims, w32.ndim - 1))
    f32 = xp.float32
    qmax = 7.0 if (bits == 4 and w32.shape[-1] % 2 == 0) else 127.0
    if xp is _np:
        buf = _np.abs(w32, dtype=_np.float32)  # one scratch, reused below
        absmax = _np.max(buf, axis=reduce_axes, keepdims=True)
        scale = _np.maximum(absmax, 1e-12, dtype=_np.float32) / qmax
        _np.divide(w32, scale, out=buf)
        _np.rint(buf, out=buf)
        _np.clip(buf, -qmax, qmax, out=buf)
        q = buf.astype(_np.int8)
    else:
        absmax = xp.max(xp.abs(w32), axis=reduce_axes, keepdims=True)
        scale = xp.maximum(absmax, 1e-12) / qmax
        q = xp.clip(xp.round(w32 / scale), -qmax, qmax).astype(xp.int8)
    if qmax == 7.0:
        q8 = (q + 8).astype(xp.uint8)
        packed = (q8[..., 0::2] << 4) | q8[..., 1::2]
        return {_QUANT4_KEY: packed, "scale": scale.astype(f32)}
    return {_QUANT_KEY: q, "scale": scale.astype(f32)}


def quantize_array(
    w: jax.Array, stack_dims: int | None = None, bits: int = 8
) -> dict[str, jax.Array]:
    """Symmetric int8/int4, one fp32 scale per output channel (last axis) —
    kept separately per leading "stack" axis slice so stacked weights never
    share scales across slices. ``stack_dims`` = number of leading stack axes
    (default: 1 for ndim >= 3, the scan-over-layers layout; pass 2 for
    layer+expert stacked MoE weights so EXPERTS keep independent scales).

    ``bits=4`` (the bnb-4bit analog) packs two values per byte along the
    output axis — 2x smaller than int8, 8x smaller than fp32. Per-channel
    symmetric [-7, 7]: coarser than int8, fine for big matmul weights with
    the sensitive leaves (norms/embeddings/head) excluded by the skip list.
    Falls back to int8 when the output axis is odd (can't pack pairs).
    """
    return _quantize_impl(jnp, jnp.asarray(w, jnp.float32), stack_dims, bits)


# Path patterns whose weights carry EXTRA leading stack axes beyond the
# scan-over-layers one (value = total stack dims). MoE experts are stacked
# (layer, expert, ...): each expert must keep independent scales.
DEFAULT_STACK_DIM_PATTERNS: tuple[tuple[str, int], ...] = (
    (r"moe", 2),
    (r"expert", 2),
)


def quantize_array_host(
    w: "np.ndarray", stack_dims: int | None = None, bits: int = 8
) -> dict[str, "np.ndarray"]:
    """`quantize_array` semantics in pure numpy on the HOST — the
    quantize-on-load path streams checkpoint leaves through here so the
    full-precision tensor never touches HBM (only the packed int8/int4
    values and scales are device_put). Same `_quantize_impl` math, so it
    cannot drift from the device version. The input keeps its storage dtype
    (bf16 checkpoints are NOT pre-cast to a full f32 copy — the in-place
    impl upcasts per ufunc into its single scratch buffer)."""
    import numpy as np

    return _quantize_impl(np, np.asarray(w), stack_dims, bits)


def leaf_quant_plan(
    path_s: str,
    shape: tuple[int, ...],
    dtype: Any,
    *,
    skip_patterns: tuple[str, ...] = DEFAULT_SKIP_PATTERNS,
    min_size: int = 4096,
    stack_dim_patterns: tuple[tuple[str, int], ...] = DEFAULT_STACK_DIM_PATTERNS,
) -> tuple[bool, int | None]:
    """Shared eligibility rule for quantization: ``(eligible, stack_dims)``.
    Used by both `quantize_pytree` (in-memory) and the streaming
    quantize-on-load path (`models/hf.py`) so the two can't disagree."""
    import numpy as np

    if any(re.search(pat, path_s) for pat in skip_patterns):
        return False, None
    if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        return False, None
    if int(np.prod(shape)) < min_size or len(shape) < 2:
        return False, None
    stack = None
    for pat, dims in stack_dim_patterns:
        if re.search(pat, path_s) and len(shape) >= dims + 2:
            stack = dims
            break
    return True, stack


def dequantize_array(d: dict[str, jax.Array], dtype: Any = jnp.bfloat16) -> jax.Array:
    if _QUANT4_KEY in d:
        packed = d[_QUANT4_KEY]
        hi = (packed >> 4).astype(jnp.int8) - 8
        lo = (packed & 0xF).astype(jnp.int8) - 8
        q = jnp.stack([hi, lo], axis=-1).reshape(packed.shape[:-1] + (packed.shape[-1] * 2,))
        return (q.astype(jnp.float32) * d["scale"]).astype(dtype)
    return (d[_QUANT_KEY].astype(jnp.float32) * d["scale"]).astype(dtype)




def quantize_pytree(
    tree: Any,
    *,
    skip_patterns: tuple[str, ...] = DEFAULT_SKIP_PATTERNS,
    min_size: int = 4096,
    stack_dim_patterns: tuple[tuple[str, int], ...] = DEFAULT_STACK_DIM_PATTERNS,
    bits: int = 8,
) -> Any:
    """Quantize eligible float leaves (big matmul weights); embeddings and
    anything matching ``skip_patterns`` stay full precision.

    ``stack_dim_patterns`` maps path regexes to the number of leading stack
    axes whose slices must keep independent scales — extend it when a model
    stacks weights along extra axes under different names. ``bits=4`` packs
    two weights per byte (see `quantize_array`).
    """

    from ..parallel.sharding import _path_str  # lazy: avoids an import cycle

    def visit(path, leaf):
        if not hasattr(leaf, "dtype"):
            return leaf
        eligible, stack = leaf_quant_plan(
            _path_str(path),
            tuple(leaf.shape),
            leaf.dtype,
            skip_patterns=skip_patterns,
            min_size=min_size,
            stack_dim_patterns=stack_dim_patterns,
        )
        if not eligible:
            return leaf
        return quantize_array(leaf, stack_dims=stack, bits=bits)

    return jax.tree_util.tree_map_with_path(visit, tree)


def dequantize_pytree(tree: Any, dtype: Any = jnp.bfloat16) -> Any:
    return jax.tree.map(
        lambda x: dequantize_array(x, dtype) if is_quantized(x) else x,
        tree,
        is_leaf=is_quantized,
    )


def has_quantized(tree: Any) -> bool:
    found = False

    def check(x):
        nonlocal found
        if is_quantized(x):
            found = True
        return x

    jax.tree.map(check, tree, is_leaf=is_quantized)
    return found


def quantized_nbytes(tree: Any) -> int:
    """Total bytes of the (possibly partially quantized) pytree."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        total += leaf.size * leaf.dtype.itemsize
    return total

"""Rank-aware tqdm (reference `utils/tqdm.py`): progress bars render on the
main process only, so an N-host job prints one bar instead of N interleaved
ones. Usage matches the reference::

    from accelerate_tpu.utils import tqdm
    for batch in tqdm(loader, desc="train"):
        ...

Pass ``main_process_only=False`` to show a bar on every process (each
prefixed with its rank via ``position``).
"""

from __future__ import annotations

from typing import Any


def tqdm(*args: Any, main_process_only: bool = True, **kwargs: Any):
    try:
        from tqdm.auto import tqdm as _tqdm
    except ImportError as e:  # pragma: no cover - env dependent
        raise ImportError(
            "tqdm is not installed; `pip install tqdm` to use the progress bar"
        ) from e

    from ..state import ProcessState

    state = ProcessState()
    if main_process_only and not state.is_main_process:
        kwargs["disable"] = True
    elif not main_process_only and state.num_processes > 1:
        kwargs.setdefault("position", state.process_index)
        desc = kwargs.get("desc", "")
        kwargs["desc"] = f"[rank {state.process_index}] {desc}".strip()
    return _tqdm(*args, **kwargs)

"""Experiment trackers.

Analog of the reference tracking subsystem (`tracking.py:91` `GeneralTracker`
ABC + seven SaaS integrations, glued in `accelerator.py:2804-2932`). The TPU
redesign keeps the same three-phase contract —

    accelerator.init_trackers("project", config={...})
    accelerator.log({"loss": ...}, step=...)
    accelerator.end_training()

— with two deliberate shifts:

- metric values arriving from compiled steps are **device arrays**; the
  Accelerator glue converts them to host scalars *once*, so individual
  trackers never block on device sync;
- a dependency-free :class:`JSONTracker` is the always-available default
  (TPU VMs are frequently headless with no SaaS egress); the SaaS trackers
  (`wandb`, `comet_ml`, `mlflow`, `aim`, `clearml`, `dvclive`) are
  import-gated exactly like the reference's `is_wandb_available()` family.

Every tracker implements: ``name``, ``requires_logging_directory``,
``tracker`` (the raw underlying object, reference `tracking.py:98-106`),
``store_init_configuration(values)``, ``log(values, step)``, ``finish()``.
"""

from __future__ import annotations

import functools
import importlib.util
import json
import os
import time
from typing import Any

from .logging import get_logger

logger = get_logger(__name__)


# --------------------------------------------------------------- availability
def _available(module: str) -> bool:
    try:
        return importlib.util.find_spec(module) is not None
    except (ModuleNotFoundError, ValueError):
        # find_spec raises (not returns None) when a dotted module's parent
        # package is itself absent, e.g. "torch.utils.tensorboard" sans torch.
        return False


def is_tensorboard_available() -> bool:
    # Only backends TensorBoardTracker can actually construct a writer from;
    # the bare TF `tensorboard` package has no SummaryWriter we use.
    return _available("torch.utils.tensorboard") or _available("tensorboardX")


def is_wandb_available() -> bool:
    return _available("wandb")


def is_comet_ml_available() -> bool:
    return _available("comet_ml")


def is_mlflow_available() -> bool:
    return _available("mlflow")


def is_aim_available() -> bool:
    return _available("aim")


def is_clearml_available() -> bool:
    return _available("clearml")


def is_dvclive_available() -> bool:
    return _available("dvclive")


def on_main_process(method):
    """Run the wrapped tracker method only on the main process when the
    tracker's ``main_process_only`` flag is set (reference `tracking.py:67`).

    Process identity comes from `ProcessState` (jax.process_index) rather
    than a torch process group.
    """

    @functools.wraps(method)
    def wrapper(self, *args: Any, **kwargs: Any):
        if getattr(self, "main_process_only", True):
            from .state import ProcessState

            if not ProcessState().is_main_process:
                return None
        return method(self, *args, **kwargs)

    return wrapper


# ------------------------------------------------------------------- base ABC
class GeneralTracker:
    """Base class for experiment trackers (reference `tracking.py:91`).

    Subclasses must define class attributes ``name`` and
    ``requires_logging_directory`` and implement ``tracker``,
    ``store_init_configuration``, and ``log``.
    """

    main_process_only: bool = True

    def __init__(self, _blank: bool = False) -> None:
        self._blank = _blank
        if _blank:
            return
        missing = [
            attr
            for attr in ("name", "requires_logging_directory")
            if not hasattr(self, attr)
        ]
        if missing:
            raise NotImplementedError(
                f"{type(self).__name__} must define class attribute(s): "
                + ", ".join(f"`{m}`" for m in missing)
            )

    # A `GeneralTracker(_blank=True)` instance is the safe do-nothing tracker
    # that `Accelerator.get_tracker` hands to non-main processes (reference
    # `accelerator.py:2878-2881`), so user code can log through it unguarded.
    # Real subclasses that forget to implement a method still fail loudly.
    @property
    def tracker(self) -> Any:
        """The raw underlying run/writer object, for direct library access."""
        if getattr(self, "_blank", False):
            return None
        raise NotImplementedError

    def store_init_configuration(self, values: dict) -> None:
        if getattr(self, "_blank", False):
            return
        raise NotImplementedError

    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        if getattr(self, "_blank", False):
            return
        raise NotImplementedError

    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        if getattr(self, "_blank", False):
            return
        raise NotImplementedError(f"{type(self).__name__} does not support images")

    def finish(self) -> None:  # optional
        pass


# ---------------------------------------------------------------- JSONTracker
class JSONTracker(GeneralTracker):
    """Dependency-free tracker: JSONL metrics + a config JSON on disk, plus an
    in-memory history for programmatic access (no reference analog — the TPU
    replacement for "no tracker available on this VM").

    Layout under ``logging_dir/run_name``:
    - ``config.json``  — the `store_init_configuration` payload
    - ``metrics.jsonl`` — one `{"step": .., "_timestamp": .., **values}` per log
    """

    name = "json"
    requires_logging_directory = True

    def __init__(self, run_name: str, logging_dir: str, **kwargs: Any) -> None:
        super().__init__()
        self.run_name = run_name
        self.run_dir = os.path.join(logging_dir, run_name)
        os.makedirs(self.run_dir, exist_ok=True)
        self.history: list[dict] = []
        self._fh = open(os.path.join(self.run_dir, "metrics.jsonl"), "a")
        logger.debug("JSONTracker run at %s", self.run_dir)

    @property
    def tracker(self) -> Any:
        return self.history

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        with open(os.path.join(self.run_dir, "config.json"), "w") as f:
            json.dump(values, f, indent=2, default=str)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        record = {"step": step, "_timestamp": time.time(), **values}
        self.history.append(record)
        self._fh.write(json.dumps(record, default=float) + "\n")
        self._fh.flush()

    @on_main_process
    def finish(self) -> None:
        self._fh.close()


# --------------------------------------------------------- TensorBoardTracker
class TensorBoardTracker(GeneralTracker):
    """TensorBoard event files (reference `tracking.py:165`), via
    `torch.utils.tensorboard` or `tensorboardX` — whichever is installed."""

    name = "tensorboard"
    requires_logging_directory = True

    def __init__(self, run_name: str, logging_dir: str, **kwargs: Any) -> None:
        super().__init__()
        try:
            from torch.utils import tensorboard as _tb
        except ImportError:  # pragma: no cover - environment dependent
            import tensorboardX as _tb
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir, run_name)
        self.writer = _tb.SummaryWriter(self.logging_dir, **kwargs)
        logger.debug("TensorBoard run at %s", self.logging_dir)

    @property
    def tracker(self) -> Any:
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        # hparams requires flat scalar/str values; project the config onto that.
        flat = {
            k: v if isinstance(v, (int, float, str, bool)) else str(v)
            for k, v in values.items()
        }
        try:
            self.writer.add_hparams(flat, metric_dict={})
        except Exception:
            self.writer.add_text("config", json.dumps(flat, default=str))
        # Also keep a greppable copy next to the event files.
        with open(os.path.join(self.logging_dir, "hparams.json"), "w") as f:
            json.dump(flat, f, indent=2)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        for k, v in values.items():
            if isinstance(v, str):
                self.writer.add_text(k, v, global_step=step)
            elif isinstance(v, dict):
                self.writer.add_scalars(k, v, global_step=step, **kwargs)
            else:
                self.writer.add_scalar(k, float(v), global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        for k, v in values.items():
            self.writer.add_images(k, v, global_step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.writer.close()


# --------------------------------------------------------------- SaaS trackers
class _GatedTracker(GeneralTracker):
    """Shared shape for import-gated SaaS trackers: raise a clear error at
    construction when the client library is absent (reference pattern:
    `require_wandb` + `is_wandb_available`, `tracking.py:276`)."""

    _module: str = ""

    def _require(self) -> None:
        if not _available(self._module):
            raise ImportError(
                f"{type(self).__name__} requires the `{self._module}` package, "
                f"which is not installed in this environment. Install it or "
                f'use log_with="json" / "tensorboard".'
            )


class WandBTracker(_GatedTracker):
    """Weights & Biases (reference `tracking.py:276`)."""

    name = "wandb"
    requires_logging_directory = False
    main_process_only = False
    _module = "wandb"

    def __init__(self, run_name: str, **kwargs: Any) -> None:
        super().__init__()
        self._require()
        import wandb

        self.run_name = run_name
        self.run = wandb.init(project=run_name, **kwargs)

    @property
    def tracker(self) -> Any:
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import wandb

        wandb.config.update(values, allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        self.run.log(values, step=step, **kwargs)

    @on_main_process
    def log_images(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        import wandb

        self.run.log(
            {k: [wandb.Image(img) for img in v] for k, v in values.items()},
            step=step,
            **kwargs,
        )

    @on_main_process
    def finish(self) -> None:
        self.run.finish()


class MLflowTracker(_GatedTracker):
    """MLflow (reference `tracking.py:579`)."""

    name = "mlflow"
    requires_logging_directory = False
    _module = "mlflow"

    def __init__(self, run_name: str, **kwargs: Any) -> None:
        super().__init__()
        self._require()
        import mlflow

        self.run_name = run_name
        self.run = mlflow.start_run(run_name=run_name, **kwargs)

    @property
    def tracker(self) -> Any:
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        import mlflow

        # mlflow caps param value length; stringify and truncate like the
        # reference (`tracking.py:662-688`).
        mlflow.log_params(
            {k: str(v)[:500] for k, v in values.items()}
        )

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        import mlflow

        metrics = {k: v for k, v in values.items() if isinstance(v, (int, float))}
        mlflow.log_metrics(metrics, step=step)

    @on_main_process
    def finish(self) -> None:
        import mlflow

        mlflow.end_run()


class CometMLTracker(_GatedTracker):
    """Comet ML (reference `tracking.py:399`)."""

    name = "comet_ml"
    requires_logging_directory = False
    _module = "comet_ml"

    def __init__(self, run_name: str, **kwargs: Any) -> None:
        super().__init__()
        self._require()
        import comet_ml

        self.run_name = run_name
        self.run = comet_ml.Experiment(project_name=run_name, **kwargs)

    @property
    def tracker(self) -> Any:
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.run.log_parameters(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        if step is not None:
            self.run.set_step(step)
        self.run.log_metrics(values, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.run.end()


class AimTracker(_GatedTracker):
    """Aim (reference `tracking.py:480`)."""

    name = "aim"
    requires_logging_directory = True
    _module = "aim"

    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs: Any) -> None:
        super().__init__()
        self._require()
        from aim import Run

        self.run_name = run_name
        self.run = Run(repo=logging_dir, experiment=run_name, **kwargs)

    @property
    def tracker(self) -> Any:
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.run["hparams"] = values

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        for k, v in values.items():
            self.run.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def finish(self) -> None:
        self.run.close()


class ClearMLTracker(_GatedTracker):
    """ClearML (reference `tracking.py:777`)."""

    name = "clearml"
    requires_logging_directory = False
    _module = "clearml"

    def __init__(self, run_name: str | None = None, **kwargs: Any) -> None:
        super().__init__()
        self._require()
        from clearml import Task

        self.run_name = run_name
        self.task = Task.init(project_name=run_name, **kwargs)

    @property
    def tracker(self) -> Any:
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.task.connect_configuration(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        clogger = self.task.get_logger()
        for k, v in values.items():
            if isinstance(v, (int, float)):
                clogger.report_scalar(title=k, series=k, value=float(v), iteration=step or 0)
            else:
                clogger.report_text(f"{k}: {v}", print_console=False)

    @on_main_process
    def finish(self) -> None:
        self.task.close()


class DVCLiveTracker(_GatedTracker):
    """DVCLive (reference `tracking.py:929`)."""

    name = "dvclive"
    requires_logging_directory = False
    _module = "dvclive"

    def __init__(self, run_name: str | None = None, live: Any = None, **kwargs: Any) -> None:
        super().__init__()
        self._require()
        from dvclive import Live

        self.live = live if live is not None else Live(**kwargs)

    @property
    def tracker(self) -> Any:
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict) -> None:
        self.live.log_params(values)

    @on_main_process
    def log(self, values: dict, step: int | None = None, **kwargs: Any) -> None:
        if step is not None:
            self.live.step = step
        for k, v in values.items():
            self.live.log_metric(k, v, **kwargs)
        self.live.next_step()

    @on_main_process
    def finish(self) -> None:
        self.live.end()


# ------------------------------------------------------------------ resolution
LOGGER_TYPE_TO_CLASS: dict[str, type[GeneralTracker]] = {
    "json": JSONTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "mlflow": MLflowTracker,
    "comet_ml": CometMLTracker,
    "aim": AimTracker,
    "clearml": ClearMLTracker,
    "dvclive": DVCLiveTracker,
}

_AVAILABILITY = {
    "json": lambda: True,
    "tensorboard": is_tensorboard_available,
    "wandb": is_wandb_available,
    "mlflow": is_mlflow_available,
    "comet_ml": is_comet_ml_available,
    "aim": is_aim_available,
    "clearml": is_clearml_available,
    "dvclive": is_dvclive_available,
}


def get_available_trackers() -> list[str]:
    """Names of trackers whose client library is importable (reference
    `tracking.py:86`)."""
    return [name for name, check in _AVAILABILITY.items() if check()]


def filter_trackers(
    log_with: Any,
    logging_dir: str | None = None,
) -> list[type[GeneralTracker] | GeneralTracker]:
    """Resolve a `log_with` value into tracker classes/instances (reference
    `tracking.py:1023` `filter_trackers`).

    Accepts: ``"all"``, a tracker name, a `GeneralTracker` instance, a
    class, or a list of any of those. Unavailable trackers are dropped with
    a warning (matching reference behavior); names that require a logging
    dir when none is configured raise.
    """
    if log_with is None:
        return []
    if not isinstance(log_with, (list, tuple)):
        log_with = [log_with]
    out: list[Any] = []
    for item in log_with:
        if isinstance(item, GeneralTracker):
            out.append(item)
            continue
        if isinstance(item, type) and issubclass(item, GeneralTracker):
            out.append(item)
            continue
        name = str(item).lower()
        if name == "all":
            out.extend(
                LOGGER_TYPE_TO_CLASS[n]
                for n in get_available_trackers()
                if not (LOGGER_TYPE_TO_CLASS[n].requires_logging_directory and logging_dir is None)
            )
            continue
        if name not in LOGGER_TYPE_TO_CLASS:
            raise ValueError(
                f"Unknown tracker {item!r}; expected one of "
                f"{sorted(LOGGER_TYPE_TO_CLASS)} or 'all'"
            )
        if not _AVAILABILITY[name]():
            logger.warning(
                "Tracker %r requested but its library is not installed; skipping.",
                name,
            )
            continue
        cls = LOGGER_TYPE_TO_CLASS[name]
        if cls.requires_logging_directory and logging_dir is None:
            raise ValueError(
                f"Tracker {name!r} requires a logging directory: pass "
                "`project_dir=` (or a ProjectConfiguration with logging_dir) "
                "to Accelerator."
            )
        out.append(cls)
    return out

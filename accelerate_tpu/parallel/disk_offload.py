"""Disk-tier (NVMe-analog) optimizer-state offload — beyond the host tier.

Reference: DeepSpeed ZeRO-Infinity offloads optimizer state to NVMe
(`utils/dataclasses.py:1055-1111` ``offload_optimizer.device: nvme`` +
``nvme_path``, `utils/deepspeed.py:29` — requires DeepSpeedCPUAdam); the
repo's host tier (`parallel/host_offload.py`) stops at pinned host RAM.
This module adds the disk tier: adam moments live in fp32 **memmaps** on
disk and never reside in HBM *or* host RAM beyond one layer's working set.

Design (TPU-native split, mirroring DeepSpeed's CPU-adam shape):

- the COMPILED step computes loss/grads (+ the global-norm clip scale) on
  device — all the MXU math stays under jit;
- the UPDATE runs on the host, streamed one layer-slice at a time: read
  the slice's mu/nu from the memmap, fetch the grad slice, run the SAME
  ``_adamw_slice`` body as the in-jit host tier (numpy namespace — one
  implementation, no numeric drift), write the moments back, and stage
  the parameter update;
- params are then updated on device with one transfer per leaf.

The memmaps double as the optimizer checkpoint: they persist in
``offload_dir`` across process restarts (`DiskMomentStore` reopens them),
so `save_state`/`load_state` only need the step count — the moments are
already on disk, exactly like DeepSpeed's NVMe swap files.

Single-process by design (like DeepSpeed's per-node NVMe swap): sharded
non-addressable params are refused loudly with the remediation (use the
pinned-host tier, whose update runs inside the compiled SPMD program).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, NamedTuple

import jax
import numpy as np

from ..telemetry import flight as _flight
from .host_offload import _adamw_slice

__all__ = ["DiskMomentStore", "DiskOffloadedAdamW", "disk_offloaded_adamw"]

# In-flight async moment writebacks (flush + sentinel clear), keyed by the
# store directory's realpath. A second store instance over the same dir
# (checkpoint-resume tests, same-process handoff) joins the pending flush
# before judging the dirty sentinel.
_PENDING_WRITEBACK: dict[str, Any] = {}
_PENDING_LOCK = threading.Lock()


class DiskMomentStore:
    """fp32 adam moments as memmaps under ``offload_dir`` (one ``.mu.bin``/
    ``.nu.bin`` pair per param leaf, plus a manifest with shapes so a
    restart can validate it is resuming the same model).

    Crash safety: `begin_update` writes a dirty sentinel (``dirty.json``)
    BEFORE the first memmap mutation of a step and `end_update` removes it
    after the flush — a process that dies mid-update leaves the sentinel
    behind, and both resume (this constructor) and same-process retry
    (`begin_update`) refuse while it is set. Without it, a crash between
    two leaves would let a retry re-apply the update to already-written
    moments (double-stepped mu/nu — round-5 advisor finding)."""

    def __init__(self, offload_dir: str) -> None:
        self.dir = offload_dir
        os.makedirs(offload_dir, exist_ok=True)
        self._maps: dict[str, tuple[np.memmap, np.memmap]] = {}
        # Join any async flush still in flight over this dir before judging
        # the sentinel (a clean in-progress writeback is not a crash).
        self.wait_writeback()
        self._refuse_if_dirty(resuming=True)

    # ------------------------------------------------ dirty-sentinel guard
    def _dirty_path(self) -> str:
        return os.path.join(self.dir, "dirty.json")

    def _refuse_if_dirty(self, resuming: bool) -> None:
        path = self._dirty_path()
        if not os.path.exists(path):
            return
        try:
            with open(path) as f:
                at = json.load(f).get("count")
        except ValueError:
            at = "?"
        raise ValueError(
            f"disk-offloaded moments in {self.dir!r} carry a dirty sentinel: "
            f"a moment update (toward step {at}) died mid-update, so some "
            "leaves hold step-N moments and others step-N-1 — "
            + ("resuming" if resuming else "retrying")
            + " would re-apply the update to the already-written leaves "
            "(double-stepped mu/nu). Point offload_dir at a fresh directory "
            "to restart the optimizer, or restore a full checkpoint."
        )

    def begin_update(self, count: int) -> None:
        """Mark the store dirty BEFORE the first memmap mutation of the
        update toward ``count``; refuses if a previous update never
        completed (crash or mid-update exception)."""
        self._refuse_if_dirty(resuming=False)
        path = self._dirty_path()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"count": int(count)}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def end_update(self) -> None:
        """Clear the dirty sentinel (the update fully hit the memmaps and
        the flush completed)."""
        try:
            os.remove(self._dirty_path())
        except FileNotFoundError:
            pass

    # ------------------------------------------------- async moment flush
    def _pending_key(self) -> str:
        return os.path.realpath(self.dir)

    def wait_writeback(self) -> None:
        """Join the in-flight async flush for this dir, re-raising any
        writeback error here (the overlap contract: step N's flush must
        complete — successfully — before step N+1 touches the moments)."""
        with _PENDING_LOCK:
            fut = _PENDING_WRITEBACK.pop(self._pending_key(), None)
        if fut is not None:
            fut.result()

    def flush_async(self, count: int, engine: Any | None = None) -> None:
        """`flush` + `end_update` on a transfer-engine worker so the msync
        and count.json write overlap the NEXT step's compute instead of
        blocking this one (the D2H-drain completion-future pattern —
        `parallel/transfer.py`). `wait_writeback` joins it."""
        from .transfer import get_transfer_engine

        eng = engine if engine is not None else get_transfer_engine()

        def _do():
            self.flush(count=count)
            self.end_update()

        with _PENDING_LOCK:
            prev = _PENDING_WRITEBACK.get(self._pending_key())
            if prev is not None and not prev.done():
                # Never reorder two writebacks over one dir.
                fut = eng.submit(lambda: (prev.result(), _do())[1])
            else:
                fut = eng.submit(_do)
            _PENDING_WRITEBACK[self._pending_key()] = fut

    def _paths(self, key: str) -> tuple[str, str, str]:
        safe = key.replace("/", "__")
        return (
            os.path.join(self.dir, f"{safe}.mu.bin"),
            os.path.join(self.dir, f"{safe}.nu.bin"),
            os.path.join(self.dir, f"{safe}.json"),
        )

    def open(self, key: str, shape: tuple[int, ...]) -> tuple[np.memmap, np.memmap]:
        """Open (or create zero-initialized) moment memmaps for a leaf."""
        if key in self._maps:
            return self._maps[key]
        mu_p, nu_p, man_p = self._paths(key)
        if os.path.exists(man_p):
            with open(man_p) as f:
                manifest = json.load(f)
            if tuple(manifest["shape"]) != tuple(shape):
                raise ValueError(
                    f"disk-offloaded moments at {man_p} were written for "
                    f"shape {manifest['shape']}, not {tuple(shape)} — the "
                    "offload_dir belongs to a different model; point "
                    "offload_dir somewhere fresh."
                )
            mode = "r+"
        else:
            for p in (mu_p, nu_p):
                with open(p, "wb") as f:
                    f.truncate(int(np.prod(shape)) * 4)  # zero-filled fp32
            with open(man_p, "w") as f:
                json.dump({"shape": list(shape), "dtype": "float32"}, f)
            mode = "r+"
        pair = (
            np.memmap(mu_p, mode=mode, dtype=np.float32, shape=tuple(shape)),
            np.memmap(nu_p, mode=mode, dtype=np.float32, shape=tuple(shape)),
        )
        self._maps[key] = pair
        return pair

    def flush(self, count: int | None = None) -> None:
        for mu, nu in self._maps.values():
            mu.flush()
            nu.flush()
        if count is not None:
            # Atomic replace: this rewrites every step, and a crash inside a
            # plain open('w') would leave an empty file that blocks resume.
            path = os.path.join(self.dir, "count.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"count": int(count)}, f)
            os.replace(tmp, path)

    def count(self) -> int | None:
        """The step count the moments were last flushed at (None = fresh
        store). Lets resume detect a state/moments mismatch: restoring any
        checkpoint other than the latest would otherwise silently pair an
        old count with newer moments. Joins any in-flight async flush first
        so the answer reflects the latest completed update."""
        self.wait_writeback()
        path = os.path.join(self.dir, "count.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return int(json.load(f)["count"])


class DiskOffloadedAdamW(NamedTuple):
    """Duck-types as `optax.GradientTransformation` (init/update first) —
    but the real update path is `Accelerator.make_train_step`'s disk
    branch, which streams through ``store``. The plain ``update`` exists
    so the object is still a valid optax transformation for code that
    inspects it; calling it raises with the remediation."""

    init: Any
    update: Any
    learning_rate: Any
    b1: float
    b2: float
    eps: float
    weight_decay: float
    store: DiskMomentStore
    stacked_paths: tuple


def disk_offloaded_adamw(
    learning_rate: Any,
    *,
    offload_dir: str,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 1e-4,
    stacked_paths: tuple = ("blocks",),
) -> DiskOffloadedAdamW:
    """AdamW whose moments live on DISK (the ZeRO-Infinity ``nvme`` tier).

    Use with ``Accelerator.create_train_state``/``make_train_step`` — the
    step splits into a compiled grad pass and a host-streamed update (see
    module docstring). ``offload_dir`` holds the fp32 moment memmaps and
    persists across restarts (it IS the optimizer checkpoint)."""
    import jax.numpy as jnp

    store = DiskMomentStore(offload_dir)

    def init(params):
        # Touch every leaf's memmaps now so resume-shape mismatches fail at
        # create_train_state, not mid-training.
        flat, _ = jax.tree_util.tree_flatten_with_path(params)
        for path, leaf in flat:
            store.open(_key(path), tuple(leaf.shape))
        return {"count": jnp.zeros((), jnp.int32)}

    def update(grads, state, params=None):
        raise NotImplementedError(
            "disk_offloaded_adamw cannot run as a plain optax transformation "
            "(its moments are disk memmaps outside the jit); drive it through "
            "Accelerator.make_train_step, which builds the split "
            "grad-pass + streamed-host-update step."
        )

    return DiskOffloadedAdamW(
        init, update, learning_rate, b1, b2, eps, weight_decay, store,
        tuple(stacked_paths),
    )


def _key(path: tuple) -> str:
    from ..parallel.sharding import _path_str

    return _path_str(path)


def disk_streamed_update(
    tx: DiskOffloadedAdamW,
    grads: Any,
    params: Any,
    count: int,
    grad_scale: float | None,
    *,
    overlap: bool | None = None,
) -> Any:
    """Host-side streamed adamw over disk-resident moments.

    ``grads``/``params`` are device arrays (fully addressable — the single
    -process constraint is checked by the caller); returns a pytree of
    numpy UPDATES (same structure/dtype as params) for the caller to apply
    on device. Layer-stacked leaves stream one layer at a time, so peak
    host RAM is a small window of layers' (grad + 2 moments); moments hit
    the memmaps (page cache -> disk) as they are produced.

    Overlap mode (default ON — ``ATX_OFFLOAD_OVERLAP``, see
    `parallel/transfer.py`): the D2H drain of slice *i+1*'s grad/param
    runs on the transfer engine's workers while slice *i*'s numpy math
    executes, and the final memmap flush + count bump is handed to a
    writeback worker whose completion future the NEXT update joins — so
    the msync overlaps step N+1's compiled grad pass instead of blocking
    step N. The math (and therefore the moments) is bit-identical with
    overlap on or off: the same slices run the same ops in the same
    order; only the scheduling moves (tested)."""
    from .transfer import get_transfer_engine, overlap_enabled

    do_overlap = overlap_enabled() if overlap is None else bool(overlap)
    engine = get_transfer_engine()
    # Transfer-overlap spans (docs/observability.md, BENCH_r05 follow-up):
    # host clocks only, so the update math stays bit-identical either way.
    trace = _flight.trace_requests_enabled()
    t_update0 = time.perf_counter() if trace else 0.0
    # Step N-1's async flush must have COMPLETED (successfully) before this
    # update reads or mutates the memmaps; its errors re-raise here.
    t_wb0 = time.perf_counter() if trace else 0.0
    tx.store.wait_writeback()
    if trace:
        # How long step N stalls on step N-1's memmap flush — the overlap
        # mode exists to drive this span toward zero.
        _flight.record_span(
            "hostoffload_writeback_wait", t0=t_wb0, overlap=do_overlap
        )
    # Dirty sentinel BEFORE the first memmap mutation: a crash anywhere in
    # the loop below leaves it set, and resume/retry refuse loudly instead
    # of re-applying the update to already-written leaves.
    tx.store.begin_update(count)
    from ..resilience.commit import fault_point

    fault_point("disk.after_sentinel")
    # One host float per step: a schedule returns a jax scalar, and letting
    # it into the numpy slice math would silently promote every slice to a
    # device op (round-tripping each layer through the slow link twice —
    # the exact traffic this tier exists to avoid). Schedule at the
    # PRE-increment count (optax convention: schedule(0) on the first step).
    lr_t = (
        float(tx.learning_rate(count - 1)) if callable(tx.learning_rate)
        else float(tx.learning_rate)
    )
    c = np.float32(count)
    flat_g, treedef = jax.tree_util.tree_flatten_with_path(grads)
    flat_p = jax.tree.leaves(params)

    # Flat worklist of (leaf index, layer index | None) slices spanning ALL
    # leaves, so the D2H prefetch pipelines across leaf boundaries too.
    jobs: list[tuple[int, int | None]] = []
    opened: list[tuple[np.memmap, np.memmap]] = []
    stacked_flags: list[bool] = []
    updates: list[np.ndarray] = []
    for li, ((path, g), p) in enumerate(zip(flat_g, flat_p)):
        key = _key(path)
        opened.append(tx.store.open(key, tuple(g.shape)))
        stacked = (
            len(path) > 0
            and getattr(path[0], "key", None) in tx.stacked_paths
            and g.ndim >= 2
        )
        stacked_flags.append(stacked)
        updates.append(np.empty(g.shape, dtype=np.dtype(p.dtype)))
        if stacked:
            jobs.extend((li, i) for i in range(g.shape[0]))
        else:
            jobs.append((li, None))

    def fetch(job: tuple[int, int | None]) -> tuple[np.ndarray, np.ndarray]:
        li, i = job
        g, p = flat_g[li][1], flat_p[li]
        if i is not None:
            g, p = g[i], p[i]
        return (
            np.asarray(jax.device_get(g), np.float32),
            np.asarray(jax.device_get(p), np.float32),
        )

    if do_overlap:
        fetched = engine.prefetch(
            len(jobs), lambda idx: engine.submit(fetch, jobs[idx])
        )
    else:
        fetched = (fetch(job) for job in jobs)

    d2h_wait = [0.0]
    if trace:
        # Host-visible D2H stall: time blocked pulling the next fetched
        # slice (with prefetch armed, work already in flight hides here).
        def _timed(it: Any) -> Any:
            while True:
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    return
                d2h_wait[0] += time.perf_counter() - t0
                yield item

        fetched = _timed(iter(fetched))

    for (li, i), (g_h, p_h) in zip(jobs, fetched):
        mu, nu = opened[li]
        out = updates[li]
        if i is not None:
            u_i, mu_i, nu_i = _adamw_slice(
                g_h, mu[i], nu[i], p_h, c, lr_t,
                tx.b1, tx.b2, tx.eps, tx.weight_decay,
                grad_scale=grad_scale, xp=np,
            )
            mu[i] = mu_i
            nu[i] = nu_i
            out[i] = u_i.astype(out.dtype)
        else:
            u, mu_n, nu_n = _adamw_slice(
                g_h, mu[...], nu[...], p_h, c, lr_t,
                tx.b1, tx.b2, tx.eps, tx.weight_decay,
                grad_scale=grad_scale, xp=np,
            )
            mu[...] = mu_n
            nu[...] = nu_n
            out[...] = u.astype(out.dtype)

    t_flush0 = time.perf_counter() if trace else 0.0
    if do_overlap:
        # msync + count bump + sentinel clear overlap step N+1's compute;
        # the next update (or the next store over this dir) joins it.
        tx.store.flush_async(count=count, engine=engine)
    else:
        tx.store.flush(count=count)
        tx.store.end_update()
    if trace:
        _flight.record_span(
            "hostoffload_memmap_flush", t0=t_flush0, overlap=do_overlap
        )
        _flight.record_span(
            "hostoffload_update",
            t0=t_update0,
            step=int(count),
            slices=len(jobs),
            overlap=do_overlap,
            d2h_wait_ms=round(d2h_wait[0] * 1e3, 3),
        )
    return jax.tree_util.tree_unflatten(treedef, updates)

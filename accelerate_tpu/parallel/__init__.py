from .mesh import (
    BATCH_AXES,
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MESH_AXES,
    SEQUENCE_AXIS,
    TENSOR_AXIS,
    Mesh,
    MeshConfig,
    batch_sharding,
    batch_spec,
    build_mesh,
    data_parallel_size,
    mesh_axis_size,
    replicated_sharding,
    single_device_mesh,
)
from .tp import get_tp_plan, list_tp_plans, register_tp_plan
from .transfer import TransferEngine, get_transfer_engine
from .pipeline import (
    Pipeline,
    build_pipeline,
    llama_pipeline,
    pipeline_mesh,
    split_stages,
)

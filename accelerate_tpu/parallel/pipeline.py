"""Pipeline-parallel inference: GPipe microbatch schedule over a stage mesh.

Analog of the reference PP-inference subsystem (`inference.py:73-184`
`build_pipeline` / `prepare_pippy`, which wraps torch.distributed.pipelining:
split the model into stages, one device per stage, microbatches streamed
through). The TPU-native construction:

- stage parameters are a pytree with a leading ``[n_stages]`` axis (the
  scan-over-layers layout the in-repo models already use), sharded over a
  dedicated 1-D ``stage`` mesh — each device holds exactly its stage's
  weights;
- one `shard_map` program runs the classic GPipe schedule: at tick ``t``
  stage ``s`` processes microbatch ``t-s``; activations hop to the next
  stage via `ppermute` over ICI. ``M`` microbatches drain in ``M+S-1``
  ticks, so per-device idle time (the pipeline bubble) is ``(S-1)/(M+S-1)``;
- the last stage's outputs are collected into a buffer and replicated with
  a `psum` at the end, so callers see an ordinary ``[M*mb, ...]`` array.

Stages must be shape-homogeneous (stage output shape == stage input shape)
— true of transformer blocks, which is the case PP exists for. Embedding /
head layers run replicated outside the pipeline (they are a few percent of
FLOPs; the reference makes the same split, `inference.py:124-145`).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:  # jax >= 0.4.35 exposes shard_map at the top level
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - version dependent
    from jax.experimental.shard_map import shard_map

STAGE_AXIS = "stage"


def pipeline_mesh(n_stages: int, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """A dedicated 1-D mesh for PP inference (separate from the training
    mesh: stage layout is an inference-serving topology choice)."""
    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) < n_stages:
        raise ValueError(f"{n_stages} stages need {n_stages} devices, found {len(devices)}")
    return Mesh(np.asarray(devices[:n_stages]), (STAGE_AXIS,))


def split_stages(stacked: Any, n_stages: int) -> Any:
    """Reshape a scan-over-layers pytree ``[L, ...] -> [S, L/S, ...]`` so each
    pipeline stage owns a contiguous group of layers."""

    def reshape(x):
        L = x.shape[0]
        if L % n_stages != 0:
            raise ValueError(f"{L} layers do not divide into {n_stages} stages")
        return x.reshape((n_stages, L // n_stages) + x.shape[1:])

    return jax.tree.map(reshape, stacked)


def shard_stages(stage_params: Any, mesh: Mesh) -> Any:
    """Place the ``[S, ...]`` stage pytree so each device holds its stage."""
    sharding = NamedSharding(mesh, PartitionSpec(STAGE_AXIS))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), stage_params)


def build_pipeline(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
) -> Callable[[Any, jax.Array], jax.Array]:
    """Compile the GPipe schedule.

    ``stage_fn(stage_params, x) -> y`` runs ONE stage (e.g. a scan over that
    stage's transformer blocks); ``y.shape == x.shape``. The returned callable
    maps ``(stage_params [S, ...], microbatches [M, mb, ...]) -> [M, mb, ...]``.
    """
    n_stages = mesh.shape[STAGE_AXIS]

    def schedule(params_blk: Any, mb_all: jax.Array) -> jax.Array:
        params_local = jax.tree.map(lambda x: x[0], params_blk)
        s = jax.lax.axis_index(STAGE_AXIS)
        n_micro = mb_all.shape[0]
        ticks = n_micro + n_stages - 1
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(t, carry):
            cur, out = carry
            # Stage 0 feeds fresh microbatches (clamped past the end — those
            # ticks produce garbage that is never collected); later stages
            # consume what ppermute delivered last tick.
            feed = jax.lax.dynamic_index_in_dim(
                mb_all, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
            )
            inp = jnp.where(s == 0, feed, cur)
            y = stage_fn(params_local, inp)
            m_idx = t - (n_stages - 1)
            valid = (s == n_stages - 1) & (m_idx >= 0)
            collected = jax.lax.dynamic_update_index_in_dim(
                out, y.astype(out.dtype), jnp.clip(m_idx, 0, n_micro - 1), 0
            )
            out = jnp.where(valid, collected, out)
            # Cast back to the carry dtype: a stage computing in reduced
            # precision (bf16 out of fp32 in) must not change the loop carry
            # type between ticks.
            y = y.astype(cur.dtype)
            cur = jax.lax.ppermute(y, STAGE_AXIS, perm) if perm else y
            return cur, out

        # Mark the zero-init carries as device-varying over the stage axis:
        # the loop body writes stage-dependent values into them, and
        # shard_map's typing rejects an unvarying->varying carry.
        def _varying(x):
            try:
                return jax.lax.pcast(x, (STAGE_AXIS,), to="varying")
            except (AttributeError, TypeError):  # pragma: no cover - jax version
                pvary = getattr(jax.lax, "pvary", None)
                # jax < 0.5 has neither pcast nor pvary; its shard_map runs
                # without replication typing (check_rep=False here), so the
                # marker is a no-op there.
                return pvary(x, (STAGE_AXIS,)) if pvary is not None else x

        cur0 = _varying(jnp.zeros(mb_all.shape[1:], mb_all.dtype))
        out0 = _varying(jnp.zeros_like(mb_all))
        _, out = jax.lax.fori_loop(0, ticks, tick, (cur0, out0))
        # Only the last stage holds real outputs; replicate to all.
        return jax.lax.psum(jnp.where(s == n_stages - 1, out, 0), STAGE_AXIS)

    from ..ops.in_jit import shard_map_over

    # check_vma=False: the stage-varying carries and the final psum are
    # deliberate; old jax's replication checker has no rule for them anyway.
    sharded = shard_map_over(
        schedule,
        mesh=mesh,
        in_specs=(PartitionSpec(STAGE_AXIS), PartitionSpec()),
        out_specs=PartitionSpec(),
        check_vma=False,
    )
    return jax.jit(sharded)


class Pipeline:
    """User-facing PP runner (reference `prepare_pippy`, `inference.py:124`).

    >>> pipe = Pipeline(stage_fn, n_stages=4)
    >>> params = pipe.prepare(stacked_layer_params)   # [L,...] -> sharded [S,L/S,...]
    >>> y = pipe(params, x, microbatch_size=8)        # x: [B, ...]
    """

    def __init__(
        self,
        stage_fn: Callable[[Any, jax.Array], jax.Array],
        n_stages: int,
        devices: Sequence[jax.Device] | None = None,
    ) -> None:
        self.mesh = pipeline_mesh(n_stages, devices)
        self.n_stages = n_stages
        self._forward = build_pipeline(stage_fn, self.mesh)

    def prepare(self, stacked_layers: Any) -> Any:
        return shard_stages(split_stages(stacked_layers, self.n_stages), self.mesh)

    def __call__(self, stage_params: Any, x: jax.Array, *, microbatch_size: int) -> jax.Array:
        B = x.shape[0]
        if B % microbatch_size != 0:
            raise ValueError(
                f"Batch {B} is not divisible by microbatch_size {microbatch_size}"
            )
        m = B // microbatch_size
        mb = x.reshape((m, microbatch_size) + x.shape[1:])
        out = self._forward(stage_params, mb)
        return out.reshape((B,) + out.shape[2:])


def llama_pipeline(
    params: Any,
    config: Any,
    n_stages: int,
    devices: Sequence[jax.Device] | None = None,
) -> tuple[Pipeline, Any, Callable[[jax.Array, int], jax.Array]]:
    """Wire a Llama checkpoint into a pipeline: blocks are staged; embedding,
    final norm and head run replicated around it.

    Returns ``(pipe, stage_params, forward)`` with
    ``forward(tokens [B,S], microbatch_size) -> logits [B,S,V]``.
    """
    from ..models import llama as _llama
    from ..models.layers import rms_norm

    # _rope_tables honours config.rope_scaling (Llama-3.1-style checkpoints
    # would otherwise silently run plain RoPE through the pipeline path).
    cos, sin = _llama._rope_tables(config)

    def stage_fn(stage_blocks: Any, x: jax.Array) -> jax.Array:
        B, S = x.shape[0], x.shape[1]
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        mask = (
            _llama._window_mask(None, positions, S, config.sliding_window)
            if getattr(config, "sliding_window", None) is not None
            else None
        )
        body = partial(
            _llama.block_forward,
            config=config,
            cos=cos,
            sin=sin,
            positions=positions,
            mask=mask,
        )

        def scan_body(carry, block):
            new_x, _aux = body(block, carry)  # MoE aux unused at inference
            return new_x, None

        x, _ = jax.lax.scan(scan_body, x, stage_blocks)
        return x

    pipe = Pipeline(stage_fn, n_stages, devices)
    stage_params = pipe.prepare(params["blocks"])
    embed = params["embed"]
    final_norm = params["final_norm"]
    head = embed.T if config.tie_embeddings else params["lm_head"]

    def forward(tokens: jax.Array, microbatch_size: int) -> jax.Array:
        x = embed[tokens]
        x = pipe(stage_params, x, microbatch_size=microbatch_size)
        x = rms_norm(x, final_norm, config.norm_eps)
        return jnp.einsum("bsd,dv->bsv", x, head.astype(x.dtype))

    return pipe, stage_params, forward

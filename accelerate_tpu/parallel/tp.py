"""Tensor-parallel sharding plan registry.

The reference gets its TP plan from `transformers` (`model.tensor_parallel`
requires `supports_tp_plan`/`base_model_tp_plan`; reference
`accelerator.py:1545-1554`, `utils/dataclasses.py:1863-1895`). This framework
owns the plans: each model family registers a named rule-set of
``(path_regex, PartitionSpec)`` pairs consumed by
`parallel.sharding.infer_param_specs`.

Plans use **2-D specs** (megatron-style column/row parallel over ``tensor``,
weight-dim sharding over ``fsdp``): on a pure-TP mesh the fsdp axis has size 1
and those entries are no-ops, so one plan serves TP, FSDP+TP, and 3-D
(data × fsdp × tensor) meshes. Param paths follow the scan-over-layers layout
(leading layer axis, always unsharded → `None` first).
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from .mesh import EXPERT_AXIS as E
from .mesh import FSDP_AXIS as F
from .mesh import TENSOR_AXIS as T

Rules = tuple[tuple[str, P], ...]

_REGISTRY: dict[str, Rules] = {}


def register_tp_plan(name: str, rules: Rules) -> None:
    _REGISTRY[name] = tuple(rules)


def get_tp_plan(name: str) -> Rules:
    if name not in _REGISTRY:
        raise KeyError(f"No TP plan named {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def list_tp_plans() -> list[str]:
    return sorted(_REGISTRY)


# --------------------------------------------------------------------- llama
# Layout (llama.init): blocks/* leaves have a leading n_layers axis.
#   attn/wq (L, D, H, h)   — heads column-parallel, D sharded over fsdp
#   attn/wk|wv (L, D, K, h) — kv heads column-parallel
#   attn/wo (L, H, h, D)   — row-parallel (output proj reduces over heads)
#   mlp/w_gate|w_up (L, D, F) — column-parallel
#   mlp/w_down (L, F, D)   — row-parallel
#   embed (V, D)           — vocab over (tensor, fsdp): the Megatron parallel
#     embedding (local masked gather + all-reduce). The gathered D dim must
#     stay UNSHARDED: sharding it over fsdp hands the partitioner a
#     (B,S,D)-activation layout (D over fsdp) that collides with the
#     batch-over-(data,fsdp) activation constraint — two tilings of the same
#     axis with permuted device orders, which GSPMD can only bridge by
#     involuntary full rematerialization (replicate-then-slice) inside the
#     train step.
#   lm_head (D, V)         — vocab column-parallel
register_tp_plan(
    "llama",
    (
        (r"blocks/attn/wq$", P(None, F, T, None)),
        (r"blocks/attn/w[kv]$", P(None, F, T, None)),
        (r"blocks/attn/wo$", P(None, T, None, F)),
        (r"blocks/attn/b[qkv]$", P(None, T, None)),
        (r"blocks/mlp/w_(gate|up)$", P(None, F, T)),
        (r"blocks/mlp/w_down$", P(None, T, F)),
        # MoE (present when LlamaConfig.n_experts > 0): experts shard over
        # the `expert` axis — the dispatch einsum then lowers to an
        # all-to-all; within each expert, megatron column/row split as above.
        (r"blocks/moe/router$", P()),
        (r"blocks/moe/w_(gate|up)$", P(None, E, F, T)),
        (r"blocks/moe/w_down$", P(None, E, T, F)),
        (r"^embed$", P((T, F), None)),
        (r"^lm_head$", P(F, T)),
        (r"norm", P()),
    ),
)

# ----------------------------------------------------------------------- gpt
# Layout (gpt.init): MHA (no GQA), gelu MLP with biases, learned positions.
register_tp_plan(
    "gpt",
    (
        (r"blocks/attn/w[qkv]$", P(None, F, T, None)),
        (r"blocks/attn/wo$", P(None, T, None, F)),
        (r"blocks/attn/b[qkv]$", P(None, T, None)),
        (r"blocks/attn/bo$", P()),
        (r"blocks/mlp/w_in$", P(None, F, T)),
        (r"blocks/mlp/b_in$", P(None, T)),
        (r"blocks/mlp/w_out$", P(None, T, F)),
        # Gathered-table rows shard over (tensor, fsdp); the embedded D dim
        # stays unsharded (see the llama plan note on involuntary SPMD
        # rematerialization).
        (r"^wte$", P((T, F), None)),
        (r"^wpe$", P(F, None)),
        (r"^lm_head$", P(F, T)),
        (r"ln", P()),
    ),
)

# ------------------------------------------------------------------------ t5
# Layout (t5.init): encoder/decoder stacks with self/cross attention and
# gated-gelu MLPs; per-stack relative-bias tables stay replicated.
register_tp_plan(
    "t5",
    (
        (r"(encoder|decoder)/(self_|cross_)?attn/w[qkv]$", P(None, F, T, None)),
        (r"(encoder|decoder)/(self_|cross_)?attn/wo$", P(None, T, None, F)),
        (r"(encoder|decoder)/mlp/w_(gate|up)$", P(None, F, T)),
        (r"(encoder|decoder)/mlp/w_down$", P(None, T, F)),
        (r"^embed$", P((T, F), None)),
        (r"^lm_head$", P(F, T)),
        (r"rel_bias|norm", P()),
    ),
)

# ----------------------------------------------------------------------- vit
register_tp_plan(
    "vit",
    (
        (r"blocks/attn/w[qkv]$", P(None, F, T, None)),
        (r"blocks/attn/wo$", P(None, T, None, F)),
        (r"blocks/attn/b[qkv]$", P(None, T, None)),
        (r"blocks/attn/bo$", P()),
        (r"blocks/mlp/w_in$", P(None, F, T)),
        (r"blocks/mlp/b_in$", P(None, T)),
        (r"blocks/mlp/w_out$", P(None, T, F)),
        (r"patch_proj/w$", P(F, T)),
        (r"pos_embed|cls_token|patch_proj/b", P()),
        (r"ln|head", P()),
    ),
)

# ---------------------------------------------------------------------- bert
register_tp_plan(
    "bert",
    (
        (r"blocks/attn/w[qkv]$", P(None, F, T, None)),
        (r"blocks/attn/wo$", P(None, T, None, F)),
        (r"blocks/attn/b[qkv]$", P(None, T, None)),
        (r"blocks/attn/bo$", P()),
        (r"blocks/mlp/w_in$", P(None, F, T)),
        (r"blocks/mlp/b_in$", P(None, T)),
        (r"blocks/mlp/w_out$", P(None, T, F)),
        (r"embed", P()),
        (r"norm", P()),
        (r"pooler|classifier", P()),
    ),
)

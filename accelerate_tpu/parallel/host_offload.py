"""Host-resident optimizer state — the ZeRO-Offload analog.

The reference trains over-HBM models by pushing optimizer state (and
optionally params) to host memory: DeepSpeed ``offload_optimizer`` /
``offload_param`` incl. NVMe (`utils/dataclasses.py:1019-1111`,
`utils/deepspeed.py:29`) and FSDP ``cpu_offload``
(`utils/dataclasses.py:1449-1861`).

The TPU-native mechanism is JAX memory kinds plus a layer-streamed update:
a ``NamedSharding(..., memory_kind="pinned_host")`` places the moments in
the host's pinned RAM while keeping them addressable by the compiled
program, and the train step updates them one layer at a time inside a
``lax.scan`` — each iteration DMAs one layer's moment slices into HBM,
runs the (MXU-adjacent, vectorized) adamw math, and DMAs the new slices
back, so peak HBM grows by ONE layer's moments instead of all of them.
Measured on v5e at 1.6B-adamw: whole-tree approaches compile every moment
(or every gradient copy) into simultaneous HLO temps — 13.5-33 GiB of
temps against 16 GiB of HBM — while the scan form holds temps at the
per-layer working set.

Like DeepSpeed's CPU-adam (`utils/deepspeed.py:29` — offload requires
DeepSpeedCPUAdam, not an arbitrary torch optimizer), the streaming step
must know the optimizer's math: use `host_offloaded_adamw(...)`, which is
also a plain whole-tree adamw wherever offload is inactive (so the same
training script runs under the CPU-simulated mesh).

On one 16 GiB v5e this is the difference between "adafactor-only 1.6B"
and "adam-class 8B fine-tune": adamw's two fp32 moments cost 8 bytes/param
— more than the bf16 weights themselves — and sit idle between updates.

Not every backend implements the placement custom-call (the CPU simulator
used for the 8-device mesh tests does not); `host_offload_supported()`
probes once, and callers fall back loudly to device-resident state.
"""

from __future__ import annotations

import functools
import warnings
from typing import Any, NamedTuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

HOST_MEMORY_KIND = "pinned_host"


def offload_requested_from_env() -> bool:
    from ..utils.dataclasses import parse_flag_from_env

    return parse_flag_from_env("ATX_OFFLOAD_OPTIMIZER")


def place_opt_state(opt_state: Any, shardings: Any, engine: Any | None = None) -> Any:
    """Move a concrete optimizer-state pytree onto its (pinned-host)
    shardings through the shared transfer engine (`parallel/transfer.py`):
    big moment leaves stream in chunks from the worker pool instead of one
    blocking ``jax.device_put`` per leaf. Used by
    `Accelerator.prepare_train_state` when restoring host-offloaded state —
    the Python-level sibling of the in-jit streamed update below (which XLA
    already overlaps with compute)."""
    from ..telemetry import flight as _flight
    from .transfer import get_transfer_engine

    eng = engine if engine is not None else get_transfer_engine()
    if _flight.trace_requests_enabled():
        import time

        n_leaves = len(jax.tree_util.tree_leaves(opt_state))
        t0 = time.perf_counter()
        out = eng.put_tree(opt_state, shardings).result()
        _flight.record_span("hostoffload_h2d_place", t0=t0, leaves=n_leaves)
        return out
    return eng.put_tree(opt_state, shardings).result()


def host_opt_shardings(opt_shapes: Any, opt_shardings: Any) -> Any:
    """Placement for offloaded optimizer state: float leaves (the moments)
    move to pinned host; integer leaves (adam's step count) stay in device
    memory, where the streamed update reads them every step."""
    import jax.numpy as jnp

    def place(shape_leaf, sharding):
        if not isinstance(sharding, NamedSharding):
            return sharding
        if jnp.issubdtype(shape_leaf.dtype, jnp.floating):
            return sharding.with_memory_kind(HOST_MEMORY_KIND)
        return sharding

    return jax.tree.map(place, opt_shapes, opt_shardings)


@functools.lru_cache(maxsize=None)
def host_offload_supported() -> bool:
    """Can this backend keep state in pinned host memory AND run a
    computation there inside jit (`compute_on('device_host')`)? Probed with
    a tiny host-side update — exactly the shape the offloaded train step
    uses. The failure modes are compile-time (unimplemented placement
    custom-call on the CPU simulator), so the probe is cheap and safe."""
    import jax.numpy as jnp
    from jax.experimental.compute_on import compute_on

    try:
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("_probe",))
        host = NamedSharding(mesh, PartitionSpec(), memory_kind=HOST_MEMORY_KIND)

        def host_update(m, g):
            with compute_on("device_host"):
                return 0.9 * m + g

        m = jax.device_put(jnp.zeros((8,), jnp.float32), host)
        g = jax.device_put(jnp.ones((8,), jnp.float32), host)
        out = jax.jit(host_update, out_shardings=host)(m, g)
        return out.sharding.memory_kind == HOST_MEMORY_KIND
    except Exception:
        return False


def warn_host_offload_unsupported() -> None:
    warnings.warn(
        "offload_optimizer was requested but this backend cannot place "
        "arrays in pinned host memory (the CPU simulator lacks the "
        "placement custom-call); optimizer state stays in device memory. "
        "On real TPU hardware the offload is active.",
        stacklevel=3,
    )


# ------------------------------------------------- offload-aware optimizer
class HostOffloadedAdamW(NamedTuple):
    """Duck-types as an `optax.GradientTransformation` (init/update are the
    first two fields) while carrying the hyperparameters the streaming
    train-step path needs to re-derive the math per layer slice."""

    init: Any
    update: Any
    learning_rate: Any  # float or optax schedule (called with the count)
    b1: float
    b2: float
    eps: float
    weight_decay: float
    mu_dtype: Any
    # Top-level param-tree keys whose leaves are layer-stacked (leading dim
    # = n_layers) and therefore updated via the streaming scan. The in-house
    # model zoo stacks under "blocks"; custom models declare their own.
    stacked_paths: tuple


def host_offloaded_adamw(
    learning_rate: Any,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 1e-4,
    mu_dtype: Any = None,
    stacked_paths: tuple = ("blocks",),
) -> HostOffloadedAdamW:
    """AdamW that the offloaded train step can stream layer-by-layer
    (reference: DeepSpeed requires its own CPU-adam for offload_optimizer,
    `utils/deepspeed.py:29`). Without offload it behaves exactly like
    ``optax.adamw`` (same update rule, tested for parity), so one training
    script serves both the real chip and the CPU-simulated mesh."""
    import jax.numpy as jnp

    def init(params):
        def zeros(p, dt=None):
            return jnp.zeros(p.shape, dt or p.dtype)

        return {
            "count": jnp.zeros((), jnp.int32),
            "mu": jax.tree.map(lambda p: zeros(p, mu_dtype), params),
            "nu": jax.tree.map(lambda p: zeros(p, mu_dtype), params),
        }

    def _lr(count):
        return learning_rate(count) if callable(learning_rate) else learning_rate

    def update(grads, state, params):
        # Whole-tree path (used when offload is inactive).
        count = state["count"] + 1
        # optax convention: the schedule sees the number of PREVIOUS updates
        # (schedule(0) on the first step); bias correction uses `count`.
        lr_t = _lr(state["count"])

        def leaf(g, mu, nu, p):
            return _adamw_slice(
                g, mu, nu, p, count, lr_t, b1, b2, eps, weight_decay
            )

        out = jax.tree.map(leaf, grads, state["mu"], state["nu"], params)
        updates = jax.tree.map(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_mu = jax.tree.map(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        new_nu = jax.tree.map(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return updates, {"count": count, "mu": new_mu, "nu": new_nu}

    return HostOffloadedAdamW(
        init, update, learning_rate, b1, b2, eps, weight_decay, mu_dtype,
        tuple(stacked_paths),
    )


def _adamw_slice(
    g, mu, nu, p, count, lr_t, b1, b2, eps, weight_decay, grad_scale=None, xp=None
):
    """One adamw step for one leaf (or one layer slice of one leaf); fp32
    moment math, update returned in fp32 (caller casts to param dtype).
    ``grad_scale`` applies global-norm clipping per slice (so the caller
    never materializes a scaled copy of the whole gradient tree).

    ``xp`` is the array namespace: jnp (default — the in-jit streamed
    update) or numpy (the disk-tier update runs on the host against
    memmapped moments, `parallel/disk_offload.py`); one body serves both
    so the two tiers cannot drift numerically.

    On the jnp path, the `fused_adamw` Pallas kernel (`native/pallas/`)
    replaces this body with a single in-place pass when enabled and the
    leaf tiles; the numpy (disk-tier) path never dispatches."""
    if xp is None:
        import jax.numpy as xp  # type: ignore[no-redef]

        try:
            from ..native.pallas.fused_adamw import maybe_fused_adamw
        except Exception:  # pragma: no cover - environment dependent
            maybe_fused_adamw = None
        if maybe_fused_adamw is not None:
            fused = maybe_fused_adamw(
                g, mu, nu, p, count, lr_t, b1, b2, eps, weight_decay, grad_scale
            )
            if fused is not None:
                return fused

    g32 = g.astype(mu.dtype)
    if grad_scale is not None:
        g32 = g32 * xp.asarray(grad_scale, dtype=mu.dtype)
    new_mu = b1 * mu + (1.0 - b1) * g32
    new_nu = b2 * nu + (1.0 - b2) * xp.square(g32)
    c = count.astype(new_mu.dtype) if hasattr(count, "astype") else new_mu.dtype.type(count)
    mu_hat = new_mu / (1.0 - b1**c)
    nu_hat = new_nu / (1.0 - b2**c)
    step = mu_hat / (xp.sqrt(nu_hat) + eps) + weight_decay * p.astype(new_mu.dtype)
    return (-lr_t * step), new_mu, new_nu


def streaming_adamw_update(
    tx: HostOffloadedAdamW,
    grads: Any,
    opt_state: Any,
    params: Any,
    param_specs: Any,
    mesh: Mesh,
    grad_scale: Any = None,
) -> tuple[Any, Any]:
    """The offloaded update: moments live in pinned host RAM; every leaf
    whose param is layer-stacked (our scan-over-layers model layout —
    leading dim = n_layers, leading spec entry None) is updated inside a
    `lax.scan` that DMAs one layer's moment slices HBM-ward, computes, and
    DMAs them back, bounding HBM temps at one layer's working set. Unstacked
    leaves (embeddings, norms, heads) round-trip whole.

    Runs INSIDE the train-step jit; XLA overlaps the per-layer DMAs with
    neighbouring compute."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec

    count = opt_state["count"] + 1
    # Schedule at the PRE-increment count (optax convention; see update()).
    lr_t = (
        tx.learning_rate(opt_state["count"])
        if callable(tx.learning_rate)
        else tx.learning_rate
    )

    flat_g, treedef = jax.tree_util.tree_flatten_with_path(grads)
    flat_mu = jax.tree.leaves(opt_state["mu"])
    flat_nu = jax.tree.leaves(opt_state["nu"])
    flat_p = jax.tree.leaves(params)
    flat_spec = jax.tree.leaves(param_specs, is_leaf=lambda x: isinstance(x, PartitionSpec))

    upd_leaves, mu_leaves, nu_leaves = [], [], []
    unstacked_bytes = 0
    for (path, g), mu, nu, p, spec in zip(flat_g, flat_mu, flat_nu, flat_p, flat_spec):
        stacked = (
            len(path) > 0
            and getattr(path[0], "key", None) in tx.stacked_paths
            and g.ndim >= 2
        )
        if not stacked:
            unstacked_bytes += 2 * int(np.prod(mu.shape)) * mu.dtype.itemsize
        sliced_spec = PartitionSpec(*spec[1:]) if len(spec) > 0 else PartitionSpec()
        host_slice = NamedSharding(mesh, sliced_spec, memory_kind=HOST_MEMORY_KIND)
        dev_slice = NamedSharding(mesh, sliced_spec)
        if stacked:
            L = g.shape[0]

            def body(carry, i, g=g, mu=mu, nu=nu, p=p, hs=host_slice, ds=dev_slice):
                mu_i = jax.device_put(
                    jax.lax.dynamic_index_in_dim(mu, i, 0, keepdims=False), ds
                )
                nu_i = jax.device_put(
                    jax.lax.dynamic_index_in_dim(nu, i, 0, keepdims=False), ds
                )
                g_i = jax.lax.dynamic_index_in_dim(g, i, 0, keepdims=False)
                p_i = jax.lax.dynamic_index_in_dim(p, i, 0, keepdims=False)
                u_i, mu2, nu2 = _adamw_slice(
                    g_i, mu_i, nu_i, p_i, count, lr_t,
                    tx.b1, tx.b2, tx.eps, tx.weight_decay,
                    grad_scale=grad_scale,
                )
                return carry, (
                    u_i.astype(p.dtype),
                    jax.device_put(mu2, hs),
                    jax.device_put(nu2, hs),
                )

            _, (u, new_mu, new_nu) = jax.lax.scan(
                body, 0, jnp.arange(L, dtype=jnp.int32)
            )
        else:
            full_host = NamedSharding(mesh, spec, memory_kind=HOST_MEMORY_KIND)
            full_dev = NamedSharding(mesh, spec)
            mu_d = jax.device_put(mu, full_dev)
            nu_d = jax.device_put(nu, full_dev)
            u, mu2, nu2 = _adamw_slice(
                g, mu_d, nu_d, p, count, lr_t,
                tx.b1, tx.b2, tx.eps, tx.weight_decay,
                grad_scale=grad_scale,
            )
            u = u.astype(p.dtype)
            new_mu = jax.device_put(mu2, full_host)
            new_nu = jax.device_put(nu2, full_host)
        upd_leaves.append(u)
        mu_leaves.append(new_mu)
        nu_leaves.append(new_nu)

    if unstacked_bytes > (2 << 30):
        # Whole-leaf round trips become simultaneous HBM temps; past ~2 GiB
        # that silently erodes the headroom offload exists to create.
        warnings.warn(
            f"{unstacked_bytes / 2**30:.1f} GiB of offloaded moments belong "
            "to leaves outside the declared layer-stacked paths "
            f"{tx.stacked_paths}; they round-trip through HBM whole. If the "
            "model stacks its layers under a different key, pass "
            "host_offloaded_adamw(..., stacked_paths=(<key>,)).",
            stacklevel=2,
        )
    unflatten = jax.tree_util.tree_unflatten
    updates = unflatten(treedef, upd_leaves)
    return updates, {
        "count": count,
        "mu": unflatten(treedef, mu_leaves),
        "nu": unflatten(treedef, nu_leaves),
    }

"""Device-mesh construction for SPMD parallelism.

This replaces the reference's backend zoo (`state.py:734-799` selecting
nccl/gloo/mpi/xla process groups) with a single concept: a
`jax.sharding.Mesh` over all devices with the canonical axes

    (data, fsdp, tensor, sequence, expert)

Every parallelism strategy in the framework is a choice of mesh shape plus
PartitionSpecs over these axes:

- pure DP            -> data=N, everything else 1 (reference DDP,
  `accelerator.py:1519-1544`)
- FSDP / ZeRO-3      -> shard params over ``fsdp`` (reference FSDP plugin,
  `utils/dataclasses.py:1449-1861`)
- tensor parallel    -> shard weight matrices over ``tensor`` (reference TP,
  `utils/dataclasses.py:1863-1895`)
- sequence/context   -> shard the sequence dim over ``sequence`` (reference:
  Megatron-only flag, `utils/dataclasses.py:2001`; first-class here)
- expert parallel    -> shard MoE experts over ``expert``

The batch dimension of inputs is sharded over (data, fsdp) jointly — the
standard TPU recipe where the fsdp axis doubles as a data axis for the input
pipeline while parameters are sharded over it.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical mesh axis names, in fixed order (outermost/slowest-varying first).
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TENSOR_AXIS = "tensor"
SEQUENCE_AXIS = "sequence"
EXPERT_AXIS = "expert"

MESH_AXES: tuple[str, ...] = (DATA_AXIS, FSDP_AXIS, TENSOR_AXIS, SEQUENCE_AXIS, EXPERT_AXIS)

# Axes over which the global batch is sharded (input pipeline + activations).
BATCH_AXES: tuple[str, ...] = (DATA_AXIS, FSDP_AXIS)


@dataclasses.dataclass
class MeshConfig:
    """Declarative mesh shape. ``-1`` on ``data`` means "all remaining devices".

    Replaces the reference's DistributedType selection: instead of picking a
    backend, the user (or the strategy plugin) picks a mesh factorization.
    """

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    expert: int = 1
    # Optional explicit device list (defaults to jax.devices()).
    devices: Sequence[jax.Device] | None = None
    allow_split_physical_axes: bool = False

    @classmethod
    def from_env(cls) -> "MeshConfig | None":
        """Mesh shape from the launcher env contract (``ATX_MESH_*``); None
        when the launcher set nothing (reference pattern: plugins read
        ``ACCELERATE_*`` in __post_init__, `utils/dataclasses.py:1123`)."""
        import os

        keys = ("DATA", "FSDP", "TENSOR", "SEQUENCE", "EXPERT")
        values = {k: os.environ.get(f"ATX_MESH_{k}") for k in keys}
        if all(v is None for v in values.values()):
            return None
        defaults = {"DATA": -1, "FSDP": 1, "TENSOR": 1, "SEQUENCE": 1, "EXPERT": 1}
        resolved = {
            k.lower(): int(v) if v is not None else defaults[k]
            for k, v in values.items()
        }
        return cls(**resolved)

    def resolved_shape(self, n_devices: int) -> tuple[int, ...]:
        fixed = self.fsdp * self.tensor * self.sequence * self.expert
        data = self.data
        if data == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"Mesh axes fsdp*tensor*sequence*expert={fixed} does not divide "
                    f"device count {n_devices}"
                )
            data = n_devices // fixed
        total = data * fixed
        if total != n_devices:
            raise ValueError(
                f"Mesh shape {(data, self.fsdp, self.tensor, self.sequence, self.expert)} "
                f"uses {total} devices but {n_devices} are available"
            )
        return (data, self.fsdp, self.tensor, self.sequence, self.expert)


def build_mesh(config: MeshConfig | None = None) -> Mesh:
    """Construct the global device mesh.

    Uses `mesh_utils.create_device_mesh` so the logical axes are laid out to
    maximize ICI bandwidth on real TPU topologies (nearest-neighbour torus
    links for the innermost axes); falls back to a plain reshape when the
    topology is unknown (CPU simulation).
    """
    config = config or MeshConfig()
    devices = list(config.devices) if config.devices is not None else jax.devices()
    shape = config.resolved_shape(len(devices))
    try:
        device_array = mesh_utils.create_device_mesh(
            shape,
            devices=devices,
            allow_split_physical_axes=config.allow_split_physical_axes,
        )
    except (ValueError, AssertionError, NotImplementedError):
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, MESH_AXES)


def resize_mesh_config(
    mesh: Mesh,
    n_devices: int,
    devices: "Sequence[jax.Device] | None" = None,
) -> MeshConfig:
    """A `MeshConfig` with the same parallelism layout as ``mesh`` at a
    different device count — the elastic shrink/grow resize policy.

    Model-parallel axes (tensor/sequence/expert) are preserved: their sizes
    encode how the model is cut up, and changing them would change every
    per-leaf layout. The size delta is absorbed by ``fsdp`` when the mesh is
    FSDP-sharded (fsdp > 1), else by ``data``; a mesh using both keeps fsdp
    and scales data (the outermost, cheapest axis to resize). Raises
    ``ValueError`` when ``n_devices`` doesn't factor — callers fall back to
    the relaunch path rather than invent a different layout.
    """
    shape = dict(zip(MESH_AXES, mesh.devices.shape))
    fixed = shape[TENSOR_AXIS] * shape[SEQUENCE_AXIS] * shape[EXPERT_AXIS]
    if n_devices <= 0 or n_devices % fixed != 0:
        raise ValueError(
            f"cannot resize mesh {dict(shape)} to {n_devices} devices: "
            f"model axes tensor*sequence*expert={fixed} must divide the "
            "new device count"
        )
    flex = n_devices // fixed
    data, fsdp = shape[DATA_AXIS], shape[FSDP_AXIS]
    if fsdp > 1 and data > 1:
        if flex % fsdp != 0:
            raise ValueError(
                f"cannot resize mesh {dict(shape)} to {n_devices} devices: "
                f"fsdp={fsdp} is kept fixed and must divide the remaining "
                f"factor {flex}"
            )
        data = flex // fsdp
    elif fsdp > 1:
        data, fsdp = 1, flex
    else:
        data, fsdp = flex, 1
    return MeshConfig(
        data=data,
        fsdp=fsdp,
        tensor=shape[TENSOR_AXIS],
        sequence=shape[SEQUENCE_AXIS],
        expert=shape[EXPERT_AXIS],
        devices=devices,
    )


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    device = device or jax.devices()[0]
    return Mesh(np.asarray([device]).reshape((1,) * len(MESH_AXES)), MESH_AXES)


def spec_entry_axes(entry: object) -> tuple[str, ...]:
    """Axis names referenced by one PartitionSpec entry (None/UNCONSTRAINED
    reference none; an entry is either one axis name or a tuple of them)."""
    if entry is None or entry is PartitionSpec.UNCONSTRAINED:
        return ()
    return (entry,) if isinstance(entry, str) else tuple(entry)


def unknown_spec_axes(spec: PartitionSpec, mesh: Mesh) -> tuple[str, ...]:
    """Axis names a spec references that the mesh does not define, in spec
    order. The static-analysis (ATX102) and eager-validation entry point:
    ``mesh.shape[axis]`` on a missing axis raises a bare ``KeyError`` with no
    param context, and deferring to ``NamedSharding`` construction is worse."""
    known = set(mesh.axis_names)
    seen: list[str] = []
    for entry in spec:
        for axis in spec_entry_axes(entry):
            if axis not in known and axis not in seen:
                seen.append(axis)
    return tuple(seen)


def validate_spec_axes(spec: PartitionSpec, mesh: Mesh, path: str = "") -> None:
    """Raise eagerly (with the param path) when a spec names mesh axes that
    don't exist — instead of the opaque ``KeyError: 'model'`` the first
    ``mesh.shape[...]`` lookup would produce deep inside spec plumbing."""
    unknown = unknown_spec_axes(spec, mesh)
    if unknown:
        where = f" for param {path!r}" if path else ""
        raise ValueError(
            f"PartitionSpec {spec}{where} references mesh axes "
            f"{list(unknown)} that are not in the mesh (axes: "
            f"{tuple(mesh.axis_names)}). Fix the sharding rule/spec, or add "
            "the axis to the mesh (MeshConfig / ATX_MESH_*)."
        )


def mesh_axis_size(mesh: Mesh, axis: str | Sequence[str]) -> int:
    if isinstance(axis, str):
        return mesh.shape[axis]
    return math.prod(mesh.shape[a] for a in axis)


def data_parallel_size(mesh: Mesh) -> int:
    """Number of data-parallel replicas = product of the batch axes."""
    return mesh_axis_size(mesh, BATCH_AXES)


def batch_spec(extra: PartitionSpec | None = None) -> PartitionSpec:
    """PartitionSpec for a batch-leading array: batch over (data, fsdp)."""
    if extra is None:
        return PartitionSpec(BATCH_AXES)
    return PartitionSpec(BATCH_AXES, *extra)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(BATCH_AXES))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def use_mesh(mesh: Mesh):
    """Ambient-mesh context manager: ``jax.sharding.set_mesh`` where it
    exists (jax >= 0.5.x), the legacy ``with mesh:`` context on older jax —
    one call site, both jax generations."""
    set_mesh = getattr(jax.sharding, "set_mesh", None)
    if set_mesh is not None:
        return set_mesh(mesh)
    return mesh


def ambient_mesh():
    """The active ambient mesh, or None. ``jax.sharding.get_abstract_mesh``
    on new jax; the thread-resources physical mesh on 0.4.x (private path,
    so failures degrade to "no ambient mesh" instead of crashing)."""
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    if get is not None:
        return get()
    try:
        from jax._src import mesh as _mesh_lib

        m = _mesh_lib.thread_resources.env.physical_mesh
        return None if m.empty else m
    except Exception:
        return None


def constrain_batch(x: jax.Array) -> jax.Array:
    """Pin dim 0 of an activation to the global batch axes when an ambient
    mesh is active (``jax.sharding.set_mesh`` — `Accelerator.make_train_step`
    traces under it); identity otherwise.

    Without this, the partitioner is free to drop the fsdp component of the
    batch sharding mid-model — at 256 chips that turned the remat-saved
    attention activations into 34 GiB-per-chip buffers (caught by
    tests/test_pod_aot.py). Explicit activation annotation is the standard
    TPU recipe: pick a mesh, annotate, let XLA insert the collectives."""
    am = ambient_mesh()
    if am is None or not am.axis_names:
        return x
    axes = tuple(a for a in BATCH_AXES if a in am.axis_names and am.shape[a] > 1)
    if not axes:
        return x
    # Non-batch dims stay UNCONSTRAINED (not None): pinning them replicated
    # would force-gather sequence-sharded activations (ring/ulysses) at the
    # top of every layer.
    return jax.lax.with_sharding_constraint(
        x,
        PartitionSpec(axes, *([PartitionSpec.UNCONSTRAINED] * (x.ndim - 1))),
    )


def local_batch_count(mesh: Mesh) -> int:
    """How many batch shards live on this process (for host-sharded loading)."""
    return data_parallel_size(mesh) // jax.process_count()


# ----------------------------------------------------- topology fingerprints
def topology_signature(mesh: Mesh) -> dict:
    """JSON-serializable fingerprint of the save-time topology, recorded in
    checkpoint metadata (checkpointing.py metadata v2 + COMMIT marker) so
    ``load_state(resume="latest")`` can detect that the pod came back at a
    different size/slice and switch to the elastic reshard-on-restore path
    instead of silently assuming shard files line up."""
    return {
        "mesh": {axis: int(size) for axis, size in mesh.shape.items()},
        "num_processes": int(jax.process_count()),
        "num_devices": int(mesh.size),
    }


def topology_matches(saved: dict | None, mesh: Mesh) -> bool:
    """Does a saved topology signature describe the CURRENT world? ``None``
    (legacy pre-metadata checkpoint) and partially-recorded signatures
    compare permissively — only the recorded fields are checked, so old
    checkpoints keep loading exactly as before at a matching topology."""
    if not saved:
        return True
    current = topology_signature(mesh)
    for key in ("mesh", "num_processes", "num_devices"):
        if key in saved and saved[key] is not None:
            want = saved[key]
            have = current[key]
            if key == "mesh":
                if {a: int(s) for a, s in dict(want).items()} != have:
                    return False
            elif int(want) != int(have):
                return False
    return True


def describe_topology(sig: dict | None) -> str:
    """Human-readable one-liner for elastic-restore log lines and errors."""
    if not sig:
        return "unknown topology (legacy checkpoint, no metadata)"
    mesh_part = (
        "x".join(f"{a}={s}" for a, s in dict(sig["mesh"]).items())
        if sig.get("mesh")
        else "mesh=?"
    )
    return (
        f"{sig.get('num_devices', '?')} device(s) / "
        f"{sig.get('num_processes', '?')} process(es) [{mesh_part}]"
    )

"""Parameter / optimizer-state sharding strategies.

This is where the reference's strategy plugin zoo collapses into PartitionSpec
policies (SURVEY.md §7 mapping table):

- DATA_PARALLEL  — params replicated; grads all-reduced implicitly by GSPMD
  (reference DDP wrap, `accelerator.py:1519-1544`).
- ZERO1          — params replicated, optimizer state sharded over the batch
  axes (DeepSpeed ZeRO stage-1, `utils/dataclasses.py:1019`).
- FSDP           — params + grads + optimizer state sharded over the ``fsdp``
  axis (torch FSDP FULL_SHARD / ZeRO-3, `utils/dataclasses.py:1449`); XLA
  inserts the all-gather-on-use / reduce-scatter-on-grad collectives.
- TENSOR_PARALLEL— weight matrices sharded over ``tensor`` by rule table
  (reference TP plugin + transformers tp_plan, `utils/dataclasses.py:1863`).
- HYBRID         — rules first, FSDP fallback, over an arbitrary mesh.

Rules are ``(path_regex, PartitionSpec)`` pairs matched against the
``/``-joined param path — the analog of transformers' `base_model_tp_plan`,
owned by the framework instead (model families register plans in
`parallel/tp.py`).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..utils.dataclasses import FsdpPlugin, ShardingStrategyType, TensorParallelPlugin
from .mesh import (
    BATCH_AXES,
    FSDP_AXIS,
    TENSOR_AXIS,
    spec_entry_axes,
    validate_spec_axes,
)

Rules = Sequence[tuple[str, PartitionSpec]]


class ShardingSpecWarning(UserWarning):
    """A requested PartitionSpec entry was dropped because the dim is not
    divisible by the mesh axis-group size, so the dim replicates instead.

    Structured (``path``/``dim``/``entry``/``dim_size``/``group``/``axes``
    attributes) so tooling can consume it — the static analyzer re-emits it
    as rule ATX101. On TPU this replication is the silent 5-50x slowdown
    mode: XLA inserts a full copy per device instead of erroring.
    """

    def __init__(
        self,
        path: str,
        dim: int,
        entry: Any,
        dim_size: int,
        group: int,
        axes: tuple[str, ...],
    ) -> None:
        self.path = path
        self.dim = dim
        self.entry = entry
        self.dim_size = dim_size
        self.group = group
        self.axes = axes
        super().__init__(
            f"PartitionSpec entry {entry!r} dropped for "
            f"{path or '<param>'} dim {dim}: size {dim_size} is not "
            f"divisible by mesh axes {list(axes)} (group size {group}); "
            "the dim stays replicated on every device"
        )


@dataclass
class ShardingStrategy:
    """Resolved sharding policy applied to a params pytree."""

    kind: ShardingStrategyType = ShardingStrategyType.DATA_PARALLEL
    rules: Rules = ()
    fsdp: FsdpPlugin = field(default_factory=FsdpPlugin)
    # Axes used for FSDP-style sharding of params and for ZeRO-1 opt-state
    # sharding respectively.
    fsdp_axes: tuple[str, ...] = (FSDP_AXIS,)
    zero1_axes: tuple[str, ...] = BATCH_AXES
    # Optimizer moments in pinned host RAM (parallel/host_offload.py).
    offload_optimizer: bool = False
    # Which offload tier the run configuration REQUESTED ("cpu" | "nvme" |
    # None). Recorded so create_train_state can refuse an optimizer that
    # does not match the request — the 'cpu' tier always had this
    # cross-check (HostOffloadedAdamW required); 'nvme' rides the optimizer
    # object (disk_offloaded_adamw), so without this field a plain optax
    # adamw would silently train with device-resident moments.
    offload_optimizer_device: str | None = None

    @classmethod
    def resolve(cls, strategy: Any, rules: Rules = ()) -> "ShardingStrategy":
        from .host_offload import offload_requested_from_env

        if isinstance(strategy, ShardingStrategy):
            return strategy
        if strategy is None:
            return cls(
                kind=ShardingStrategyType.DATA_PARALLEL,
                rules=rules,
                offload_optimizer=offload_requested_from_env(),
            )
        if isinstance(strategy, FsdpPlugin):
            return cls(
                kind=ShardingStrategyType.FSDP,
                rules=rules,
                fsdp=strategy,
                offload_optimizer=strategy.offload_optimizer,
            )
        if isinstance(strategy, TensorParallelPlugin):
            if strategy.plan is not None:
                from .tp import get_tp_plan

                if rules:
                    raise ValueError(
                        "Pass either TensorParallelPlugin(plan=...) or "
                        "explicit sharding_rules, not both — the plugin's "
                        "named plan would silently shadow the rules."
                    )
                rules = tuple(get_tp_plan(strategy.plan))
            elif not rules:
                raise ValueError(
                    "TENSOR_PARALLEL needs sharding rules: set "
                    "TensorParallelPlugin(plan='<family>') (registered plans: "
                    "parallel.tp.list_tp_plans()) or pass sharding_rules."
                )
            return cls(
                kind=ShardingStrategyType.TENSOR_PARALLEL,
                rules=rules,
                offload_optimizer=offload_requested_from_env(),
            )
        return cls(
            kind=ShardingStrategyType(str(strategy).upper()),
            rules=rules,
            offload_optimizer=offload_requested_from_env(),
        )


def _path_str(path: tuple) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _shard_largest_dim(
    shape: tuple[int, ...], axes: tuple[str, ...], mesh: Mesh, min_size: int
) -> PartitionSpec:
    """Shard the largest dimension divisible by the axis-group size; replicate
    tensors that are too small or indivisible (the size-based auto-wrap analog
    of the reference FSDP plugin, `utils/constants.py:37`)."""
    group = int(np.prod([mesh.shape[a] for a in axes]))
    if group <= 1 or int(np.prod(shape)) < min_size:
        return PartitionSpec()
    candidates = [d for d in range(len(shape)) if shape[d] % group == 0 and shape[d] >= group]
    if not candidates:
        return PartitionSpec()
    best = max(candidates, key=lambda d: shape[d])
    spec: list[Any] = [None] * len(shape)
    spec[best] = axes if len(axes) > 1 else axes[0]
    return PartitionSpec(*spec)


def _sanitize_spec(
    spec: PartitionSpec, shape: tuple[int, ...], mesh: Mesh, path: str = ""
) -> PartitionSpec:
    """Drop sharding on dims the mesh can't divide evenly, replicating them
    instead. This is what makes one plan serve many topologies — e.g. GQA
    kv-head projections replicate when num_kv_heads < tensor-parallel size
    (the analog of torch TP falling back to replicated DTensor placements).
    The drop is never silent: a structured :class:`ShardingSpecWarning`
    (carrying the param path) fires so the replicated copy is visible before
    a pod run pays for it, and unknown axis names raise eagerly with the
    path instead of a bare ``KeyError``."""
    import warnings

    validate_spec_axes(spec, mesh, path)
    out: list[Any] = []
    for d, entry in enumerate(spec):
        if entry is None:
            out.append(None)
            continue
        axes = spec_entry_axes(entry)
        group = int(np.prod([mesh.shape[a] for a in axes]))
        if group > 1 and shape[d] % group == 0:
            out.append(entry)
        else:
            if group > 1:
                # Size-1 axis groups shard nothing by construction (the
                # canonical form drops them too) — only an indivisible dim
                # is a real "you asked for sharding, got replication" event.
                warnings.warn(
                    ShardingSpecWarning(path, d, entry, shape[d], group, axes),
                    stacklevel=2,
                )
            out.append(None)
    return PartitionSpec(*out)


def _apply_rules(path: str, shape: tuple[int, ...], rules: Rules) -> PartitionSpec | None:
    for pattern, spec in rules:
        if re.search(pattern, path):
            if len(spec) > len(shape):
                raise ValueError(
                    f"Sharding rule {pattern!r} -> {spec} has more axes than param "
                    f"{path} with shape {shape}"
                )
            return spec
    return None


def infer_param_specs(
    params_shapes: Any, mesh: Mesh, strategy: ShardingStrategy
) -> Any:
    """PartitionSpec pytree for a params pytree (shapes or concrete arrays)."""
    kind = strategy.kind

    def leaf_spec(path: tuple, leaf: Any) -> PartitionSpec:
        shape = tuple(getattr(leaf, "shape", ()))
        path_s = _path_str(path)
        if kind in (
            ShardingStrategyType.DATA_PARALLEL,
            ShardingStrategyType.ZERO1,
            ShardingStrategyType.ZERO2,  # same program under XLA; see dataclasses.py
        ):
            return PartitionSpec()
        matched = _apply_rules(path_s, shape, strategy.rules)
        if matched is not None:
            return _sanitize_spec(matched, shape, mesh, path=path_s)
        if kind == ShardingStrategyType.TENSOR_PARALLEL:
            return PartitionSpec()
        # FSDP and HYBRID fall back to sharding the largest divisible dim.
        return _shard_largest_dim(
            shape, strategy.fsdp_axes, mesh, strategy.fsdp.min_weight_size
        )

    return jax.tree_util.tree_map_with_path(leaf_spec, params_shapes)


def infer_opt_specs(
    opt_state_shapes: Any, params_shapes: Any, param_specs: Any, mesh: Mesh, strategy: ShardingStrategy
) -> Any:
    """PartitionSpec pytree for optimizer state.

    Optimizer moments mirror the params pytree (optax convention), so any
    subtree structurally identical to params inherits the param specs —
    except under ZeRO-1, where moments shard over the batch axes even though
    params stay replicated (optimizer-state sharding is ZeRO-1's whole
    point). Scalars and other non-param-like leaves replicate.
    """
    params_struct = jax.tree.structure(params_shapes)

    if strategy.kind in (ShardingStrategyType.ZERO1, ShardingStrategyType.ZERO2):
        moment_specs = jax.tree.map(
            lambda leaf: _shard_largest_dim(
                tuple(leaf.shape), strategy.zero1_axes, mesh, strategy.fsdp.min_weight_size
            ),
            params_shapes,
        )
    else:
        moment_specs = param_specs

    params_shapes_list = [tuple(l.shape) for l in jax.tree.leaves(params_shapes)]

    def is_params_like(x: Any) -> bool:
        # Structure equality alone is degenerate when params is a single bare
        # array (every leaf matches a leaf treedef) — require leaf shapes to
        # match too, so e.g. adam's scalar `count` never inherits param specs.
        if x is None:
            return False
        try:
            if jax.tree.structure(x) != params_struct:
                return False
            return [tuple(l.shape) for l in jax.tree.leaves(x)] == params_shapes_list
        except Exception:
            return False

    def map_subtree(sub: Any) -> Any:
        if is_params_like(sub):
            return moment_specs
        return jax.tree.map(lambda _: PartitionSpec(), sub)

    return jax.tree.map(map_subtree, opt_state_shapes, is_leaf=is_params_like)


def canonicalize_spec(spec: PartitionSpec, mesh: Mesh, path: str = "") -> PartitionSpec:
    """Normalize a spec to the form XLA hands back: size-1 mesh axes shard
    nothing (drop them) and trailing ``None`` entries are implicit. Without
    this, a planned ``P(('data','fsdp'), None)`` on an fsdp=1 mesh and the
    ``P('data')`` XLA returns for it compare unequal, so a train step whose
    output constraint uses the planned form recompiles when the state round
    -trips into the next call.

    Axis names the mesh doesn't define raise HERE, eagerly, with ``path``
    in the message — not at ``NamedSharding`` construction, whose error
    names neither the param nor the offending axis."""
    validate_spec_axes(spec, mesh, path)
    entries: list[Any] = []
    for e in spec:
        if e is None:
            entries.append(None)
            continue
        axes = spec_entry_axes(e)
        axes = tuple(a for a in axes if mesh.shape[a] > 1)
        entries.append(None if not axes else (axes[0] if len(axes) == 1 else axes))
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def to_named_shardings(spec_tree: Any, mesh: Mesh) -> Any:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, canonicalize_spec(s, mesh)),
        spec_tree,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def shard_pytree(tree: Any, spec_tree: Any, mesh: Mesh) -> Any:
    """Place a concrete pytree onto the mesh per the spec tree. Rides the
    shared transfer engine: host-resident leaves stream in pinned chunks
    from a worker pool instead of one blocking ``device_put`` per leaf
    (`parallel/transfer.py`); device-resident leaves reshard as before."""
    from .transfer import get_transfer_engine

    shardings = to_named_shardings(spec_tree, mesh)
    return get_transfer_engine().put_tree(tree, shardings).result()

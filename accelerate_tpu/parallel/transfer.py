"""Async chunked host<->device transfer engine — the shared hot path for
every Python-dispatched byte that crosses the host/device link.

Why it exists (BENCH_r05, one v5e through the dev tunnel): raw disk reads
run 2655.9 MiB/s while a blocking whole-leaf ``jax.device_put`` moves
23.9 MiB/s — a ~110x gap that made the 8B big-model load 269 s, held
host-offloaded AdamW at 0.09 MFU (vs 0.55 device-resident), and capped
over-RAM streamed decode at 0.019 tok/s. None of that is hardware: the
link serializes behind Python-level per-leaf dispatch (one giant
``device_put`` call at a time), and a second concurrent stream was already
measured to aggregate bandwidth (~50 -> ~63 MiB/s with two). This module
turns every such transfer into *chunks issued concurrently from a worker
pool*, with prefetch and completion futures so traffic overlaps compute
instead of blocking it.

Three mechanisms, one engine:

- **Chunked H2D** (`TransferEngine.put`): a large host leaf is split into
  row-chunks; each chunk is read (memmap -> RAM), cast, and
  ``jax.device_put`` from the pool (multiple streams in flight), then
  folded into a preallocated device buffer with a donated
  ``dynamic_update_slice`` — device memory holds the destination buffer
  plus a bounded window of chunks, never 2x the leaf.
- **Layer prefetch queue** (`TransferEngine.prefetch`): while layer *k*
  executes, layers *k+1..k+depth* are already in flight (double-buffered
  device slots; ``big_modeling.streamed_scan`` rides this).
- **D2H draining** (`TransferEngine.get` / `get_tree`): device->host
  copies start asynchronously and complete on the pool, returning
  futures — optimizer-moment writeback overlaps the next step's compute
  (``parallel/disk_offload.py`` rides this).

Consumers (the three hot paths the engine unifies): big-model load +
over-RAM layer streaming (`big_modeling.py`), host-offloaded /
disk-offloaded AdamW (`accelerator.py` + `parallel/disk_offload.py`), and
generic pytree placement (`parallel/sharding.shard_pytree`).

Knobs (read at engine construction; defaults chosen for the measured v5e
tunnel, all safe to leave alone):

- ``ATX_TRANSFER_CHUNK_MIB`` (default 64): chunk size; smaller chunks
  overlap better through high-latency links, larger chunks amortize
  per-call overhead on fast PCIe hosts.
- ``ATX_TRANSFER_WORKERS`` (default 4): concurrent transfer streams.
- ``ATX_TRANSFER_PREFETCH`` (default 2): layer prefetch depth (>= 2 keeps
  one layer computing while the next is fully in flight).
- ``ATX_OFFLOAD_OVERLAP`` (default on): lets the offloaded-optimizer
  tiers overlap step *N* moment traffic with step *N+1* compute
  (`overlap_enabled`); set to 0 to force the old blocking behavior.
"""

from __future__ import annotations

import collections
import functools
import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Iterator

import jax
import numpy as np
from jax.sharding import NamedSharding, SingleDeviceSharding

__all__ = [
    "TransferEngine",
    "TreeFuture",
    "get_transfer_engine",
    "overlap_enabled",
]

DEFAULT_CHUNK_MIB = 64
DEFAULT_WORKERS = 4
DEFAULT_PREFETCH_DEPTH = 2


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def overlap_enabled() -> bool:
    """Offloaded-optimizer overlap mode (``ATX_OFFLOAD_OVERLAP``): ON by
    default — step N's moment D2H/writeback/flush overlaps step N+1's
    compute. Opt out with 0/false/off (the result is bit-identical either
    way — overlap changes scheduling, never the math; tested)."""
    v = os.environ.get("ATX_OFFLOAD_OVERLAP", "1").strip().lower()
    return v not in ("0", "false", "no", "off", "")


class TreeFuture:
    """Future over a pytree of per-leaf transfer futures (what
    `TransferEngine.put_tree` / `get_tree` return)."""

    def __init__(self, treedef: Any, futures: list) -> None:
        self._treedef = treedef
        self._futures = futures

    def result(self, timeout: float | None = None) -> Any:
        leaves = [f.result(timeout) for f in self._futures]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def done(self) -> bool:
        return all(f.done() for f in self._futures)


class TransferEngine:
    """Shared async chunked transfer engine (module docstring). One
    instance per process is the intent (`get_transfer_engine`); tests
    construct their own with tiny ``chunk_bytes`` to force the chunk
    path on small arrays.

    Thread model: ``workers`` pool threads run chunk reads + device_put
    dispatch (the concurrent streams); a small assembler pool folds chunks
    into destination buffers and completes leaf futures. Worker exceptions
    propagate through ``Future.result()`` — nothing is swallowed."""

    def __init__(
        self,
        *,
        chunk_bytes: int | None = None,
        workers: int | None = None,
        prefetch_depth: int | None = None,
    ) -> None:
        self.chunk_bytes = int(
            chunk_bytes
            if chunk_bytes is not None
            else _env_int("ATX_TRANSFER_CHUNK_MIB", DEFAULT_CHUNK_MIB) << 20
        )
        self.chunk_bytes = max(1, self.chunk_bytes)
        self.workers = max(
            1,
            int(
                workers
                if workers is not None
                else _env_int("ATX_TRANSFER_WORKERS", DEFAULT_WORKERS)
            ),
        )
        self.prefetch_depth = max(
            1,
            int(
                prefetch_depth
                if prefetch_depth is not None
                else _env_int("ATX_TRANSFER_PREFETCH", DEFAULT_PREFETCH_DEPTH)
            ),
        )
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="atx-transfer"
        )
        # Assembly only ever waits on _pool futures (never on other
        # assembly jobs), so the two pools cannot deadlock each other.
        self._assembler = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="atx-transfer-asm"
        )
        self._jit_lock = threading.Lock()
        self._fold_jits: dict = {}
        self._alloc_jits: dict = {}
        # Link-traffic telemetry (docs/observability.md). Counters are
        # thread-safe; incremented from pool workers alongside the copies
        # they describe, so the registry view tracks in-flight progress.
        from .. import telemetry as _telemetry

        self._c_h2d = _telemetry.counter(
            "transfer_h2d_bytes", "Host-to-device bytes moved by TransferEngine")
        self._c_d2h = _telemetry.counter(
            "transfer_d2h_bytes", "Device-to-host bytes drained by TransferEngine")
        self._c_chunks = _telemetry.counter(
            "transfer_chunks", "Chunked H2D copy windows dispatched")
        self._h_chunk = _telemetry.histogram(
            "transfer_chunk_bytes",
            "Size of each H2D transfer (whole leaf or chunk window)",
            buckets=_telemetry.DEFAULT_BYTES_BUCKETS,
        )

    # ------------------------------------------------------------- generic
    def submit(self, fn: Callable, *args: Any, **kwargs: Any) -> Future:
        """Run ``fn`` on the transfer worker pool (host-side staging,
        writeback, or any transfer-adjacent work that should overlap the
        caller). Exceptions surface at ``.result()``."""
        return self._pool.submit(fn, *args, **kwargs)

    def close(self) -> None:
        self._pool.shutdown(wait=True)
        self._assembler.shutdown(wait=True)

    # ----------------------------------------------------------------- H2D
    def _should_chunk(self, x: Any, sharding: Any) -> bool:
        """Chunk host (numpy/memmap) leaves whose leading dim is not
        partitioned — a chunk then satisfies the same sharding as the whole
        leaf, and the fold preserves the layout. Device-resident arrays and
        dim-0-sharded leaves take the single-shot path (resharding and
        scatter belong to XLA / make_array, not to row chunking)."""
        if not isinstance(x, np.ndarray):
            return False
        if x.ndim == 0 or x.shape[0] <= 1:
            return False
        if x.nbytes <= self.chunk_bytes:
            return False
        if sharding is None or isinstance(sharding, SingleDeviceSharding):
            return True
        if isinstance(sharding, NamedSharding):
            spec = sharding.spec
            return len(spec) == 0 or spec[0] is None
        return False

    def _fold_fn(self, sharding: Any):
        """Jitted ``buf[start:start+rows] = chunk`` with a donated buffer:
        the destination updates in place, so device memory holds the buffer
        plus one in-flight chunk window, never a full second copy."""
        key = sharding
        with self._jit_lock:
            fn = self._fold_jits.get(key)
            if fn is None:

                def fold(buf, chunk, start):
                    return jax.lax.dynamic_update_slice_in_dim(
                        buf, chunk, start, axis=0
                    )

                kwargs: dict = {"donate_argnums": (0,)}
                if isinstance(sharding, NamedSharding):
                    kwargs["out_shardings"] = sharding
                fn = jax.jit(fold, **kwargs)
                self._fold_jits[key] = fn
            return fn

    def _alloc(self, shape: tuple, dtype: Any, sharding: Any):
        if sharding is None:
            import jax.numpy as jnp

            return jnp.zeros(shape, dtype)
        if isinstance(sharding, SingleDeviceSharding):
            import jax.numpy as jnp

            return jax.device_put(jnp.zeros(shape, dtype), sharding)
        key = (tuple(shape), np.dtype(dtype).str, sharding)
        with self._jit_lock:
            fn = self._alloc_jits.get(key)
            if fn is None:
                import jax.numpy as jnp

                if len(self._alloc_jits) > 512:  # runaway-shape backstop
                    self._alloc_jits.clear()
                fn = jax.jit(
                    functools.partial(jnp.zeros, tuple(shape), dtype),
                    out_shardings=sharding,
                )
                self._alloc_jits[key] = fn
        return fn()

    def put(self, x: Any, sharding: Any = None, dtype: Any = None) -> Future:
        """Asynchronously place one leaf on device; returns a Future whose
        result is the device array. Host leaves larger than ``chunk_bytes``
        (leading dim unsharded) go through the chunked multi-stream path;
        everything else is a single pooled ``device_put``. ``dtype`` casts
        on the worker (per chunk — the full-precision leaf is never
        materialized twice on the host)."""
        if self._should_chunk(x, sharding):
            return self._put_chunked(x, sharding, dtype)

        def _single(x=x, sharding=sharding, dtype=dtype):
            if dtype is not None:
                if isinstance(x, np.ndarray):
                    x = np.asarray(x, dtype=np.dtype(dtype))
                elif hasattr(x, "astype"):
                    x = x.astype(dtype)
            nbytes = int(getattr(x, "nbytes", 0) or 0)
            if nbytes:
                self._c_h2d.inc(nbytes)
                self._h_chunk.observe(nbytes)
            if sharding is None:
                return jax.device_put(x)
            return jax.device_put(x, sharding)

        return self._pool.submit(_single)

    def _put_chunked(self, x: np.ndarray, sharding: Any, dtype: Any) -> Future:
        shape = tuple(x.shape)
        out_dtype = np.dtype(dtype) if dtype is not None else np.dtype(x.dtype)
        row_bytes = int(np.prod(shape[1:], dtype=np.int64)) * out_dtype.itemsize
        rows = max(1, self.chunk_bytes // max(1, row_bytes))
        starts = list(range(0, shape[0], rows))

        def read_put(s: int):
            # The memmap/RAM read, the cast, and the device_put all happen
            # here on a pool worker — concurrent chunks are the multiple
            # streams that aggregate link bandwidth.
            chunk = np.asarray(x[s : s + rows], dtype=out_dtype)
            self._c_h2d.inc(chunk.nbytes)
            self._c_chunks.inc()
            self._h_chunk.observe(chunk.nbytes)
            if sharding is None:
                return jax.device_put(chunk)
            return jax.device_put(chunk, sharding)

        # Bounded in-flight window: the first chunks start transferring
        # NOW (before the assembler gets scheduled), the rest are issued
        # as the fold consumes — host+device never hold the whole leaf
        # twice.
        window = self.workers + 2
        pending: collections.deque = collections.deque(
            self._pool.submit(read_put, s) for s in starts[:window]
        )
        result: Future = Future()

        def assemble():
            try:
                buf = self._alloc(shape, out_dtype, sharding)
                fold = self._fold_fn(sharding)
                for i, s in enumerate(starts):
                    f = pending.popleft()
                    if i + window < len(starts):
                        pending.append(self._pool.submit(read_put, starts[i + window]))
                    buf = fold(buf, f.result(), s)
                result.set_result(buf)
            except BaseException as e:  # propagate worker errors verbatim
                for f in pending:
                    f.cancel()
                result.set_exception(e)

        self._assembler.submit(assemble)
        return result

    def put_tree(self, tree: Any, shardings: Any = None, dtype: Any = None) -> TreeFuture:
        """`put` over a pytree. ``shardings`` is None (default placement),
        one Sharding applied to every leaf, or a matching pytree of
        Shardings (None leaves allowed)."""
        flat, treedef = jax.tree_util.tree_flatten(tree)
        if shardings is None:
            sh_flat = [None] * len(flat)
        elif isinstance(shardings, jax.sharding.Sharding):
            sh_flat = [shardings] * len(flat)
        else:
            sh_flat, _ = jax.tree_util.tree_flatten(
                shardings,
                is_leaf=lambda s: s is None or isinstance(s, jax.sharding.Sharding),
            )
            if len(sh_flat) != len(flat):
                raise ValueError(
                    f"put_tree: shardings tree has {len(sh_flat)} leaves but "
                    f"the value tree has {len(flat)}."
                )
        futures = [self.put(x, s, dtype) for x, s in zip(flat, sh_flat)]
        return TreeFuture(treedef, futures)

    # ----------------------------------------------------------------- D2H
    def get(self, x: Any) -> Future:
        """Asynchronous device->host drain of one leaf: the copy starts
        immediately (``copy_to_host_async``) and completes on a pool
        worker; the Future resolves to a numpy array."""
        if isinstance(x, jax.Array):
            try:
                x.copy_to_host_async()
            except Exception:
                pass  # backends without async copy fall through to asarray

        def _drain(x=x):
            out = np.asarray(x)
            self._c_d2h.inc(out.nbytes)
            return out

        return self._pool.submit(_drain)

    def get_tree(self, tree: Any) -> TreeFuture:
        """`get` over a pytree — all leaves drain concurrently."""
        flat, treedef = jax.tree_util.tree_flatten(tree)
        futures = [self.get(x) for x in flat]
        return TreeFuture(treedef, futures)

    # ------------------------------------------------------------ prefetch
    def prefetch(
        self, n: int, stage: Callable[[int], Any], depth: int | None = None
    ) -> Iterator[Any]:
        """Layer-granularity prefetch queue: yields ``stage(0..n-1)``
        results in order, keeping ``depth`` stages in flight — while the
        caller consumes item *k*, items *k+1..k+depth* are transferring
        (the double-buffered device slots of `big_modeling.streamed_scan`).

        ``stage(i)`` is called exactly once per index, in order, and may
        return a Future/TreeFuture (resolved here) or a plain value. A
        stage that raised re-raises at its yield point."""
        depth = self.prefetch_depth if depth is None else max(1, int(depth))

        def gen():
            pending: collections.deque = collections.deque()
            for i in range(min(depth, n)):
                pending.append(stage(i))
            for i in range(n):
                item = pending.popleft()
                if i + depth < n:
                    # Refill BEFORE blocking on the current item so the
                    # pipeline stays `depth` deep while we wait.
                    pending.append(stage(i + depth))
                yield item.result() if hasattr(item, "result") else item

        return gen()


_ENGINE: TransferEngine | None = None
_ENGINE_LOCK = threading.Lock()


def get_transfer_engine() -> TransferEngine:
    """The process-wide engine (one worker pool shared by every consumer —
    concurrent loads/steps share the link fairly instead of oversubscribing
    it with private pools)."""
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is None:
            _ENGINE = TransferEngine()
        return _ENGINE

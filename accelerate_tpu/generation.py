"""Autoregressive generation over KV caches.

The reference has no generation loop of its own — `generate()` arrives via
transformers, and accelerate's contribution is keeping the sharded/offloaded
model callable (`big_modeling.py:511`, benchmark
`benchmarks/big_model_inference/`). A TPU-native framework must own the loop,
because the performant shape is specific to XLA:

- prefill and decode are two jit specializations of the same cached forward
  (static prompt length / static 1-token step), each fused with its sampling;
- the decode loop runs on the host over the jitted step with the KV cache
  donated — tokens never round-trip to the host mid-loop (the loop chains
  on-device values; only the final tensor is fetched). An all-in-jit
  `lax.scan` decode was measured to explode XLA compile time when the decode
  scan nests over a scan-over-layers model, while the host loop costs ~8 ms
  per token for a 450M model on v5e — the per-call overhead, amortized away
  at real batch sizes;
- EOS handling uses a carried `done` flag + `where` (no data-dependent
  control flow under jit); finished rows emit ``pad_token_id``. The host
  loop polls the carried mask every ``eos_check_every`` steps and exits
  once every row is done, so short completions don't pay the full
  ``max_new_tokens`` of decode steps;
- sampling (greedy/temperature/top-k/top-p) is pure `jax.random` given the
  carried PRNG key, so generations are reproducible by seed.

For over-HBM models use ``jit_loop=False``: the loop still runs in Python but
nothing is jitted end-to-end, so ``apply_fn`` may stream host-offloaded
layers (`big_modeling.streamed_scan`).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["GenerationConfig", "Generator", "sample_tokens", "warp_logits", "generate"]


@dataclass(frozen=True)
class GenerationConfig:
    max_new_tokens: int = 32
    do_sample: bool = False  # False -> greedy argmax
    temperature: float = 1.0
    top_k: int | None = None
    top_p: float | None = None
    eos_token_id: int | None = None
    pad_token_id: int = 0
    # KV cache storage: "bf16" (default), "fp32", or "int8" (per-token-scale
    # quantized — half the cache bytes per decode step; llama family).
    kv_cache_dtype: str = "bf16"


def cache_dtype(config: "GenerationConfig"):
    try:
        return {"bf16": jnp.bfloat16, "fp32": jnp.float32, "int8": jnp.int8}[
            config.kv_cache_dtype
        ]
    except KeyError:
        raise ValueError(
            f"kv_cache_dtype={config.kv_cache_dtype!r}; expected bf16, fp32, "
            "or int8."
        ) from None


def warp_logits(logits: jax.Array, config: GenerationConfig) -> jax.Array:
    """Apply the sampling config's logit warps (temperature / top-k / top-p)
    to (..., V) logits. Shared by `sample_tokens` and speculative decoding
    (which needs the warped DISTRIBUTIONS of draft and target, not just a
    draw, for the accept/residual math)."""
    logits = logits.astype(jnp.float32)
    if config.temperature != 1.0:
        logits = logits / jnp.maximum(config.temperature, 1e-6)
    if config.top_k is not None:
        kth = jax.lax.top_k(logits, config.top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if config.top_p is not None:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative prob >= top_p (always
        # keeping the most likely token).
        cutoff_idx = jnp.sum(cumulative < config.top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def sample_tokens(logits: jax.Array, rng: jax.Array, config: GenerationConfig) -> jax.Array:
    """Draw next tokens from (B, V) logits per the sampling config."""
    if not config.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, warp_logits(logits, config), axis=-1).astype(jnp.int32)


class Generator:
    """Reusable generation harness: builds the jitted prefill/decode steps
    once; calls retrace only on new (batch, prompt-length) shapes.

    ``apply_fn(params, tokens, cache) -> (logits, cache)`` is an incremental
    cached forward (e.g. `models/llama.py:forward_with_cache`);
    ``init_cache_fn(batch_size, max_len)`` builds the empty cache.
    """

    def __init__(
        self,
        apply_fn: Callable[[Any, jax.Array, Any], tuple[jax.Array, Any]],
        init_cache_fn: Callable[[int, int], Any],
        config: GenerationConfig | None = None,
        *,
        jit_loop: bool = True,
        eos_check_every: int = 8,
    ) -> None:
        self.config = config or GenerationConfig()
        self.init_cache_fn = init_cache_fn
        # With an eos configured, the host loop syncs the carried `done`
        # mask every `eos_check_every` dispatched steps and stops early once
        # every row has finished — shorter completions cost fewer decode
        # steps instead of always paying max_new_tokens. The chunking keeps
        # the early exit from serializing every step on a device->host
        # round trip (the same amortization speculative.py's host loop
        # uses); the skipped tail is pure pad by the done/where discipline,
        # so outputs are bit-identical with the exit on or off (tested).
        # `lax.while_loop`/`lax.cond` variants were rejected deliberately:
        # an end-to-end on-device loop explodes compile time over a
        # scan-over-layers model (module docstring), and a cond-guarded
        # step risks silently breaking the cache donation aliasing.
        self.eos_check_every = max(1, eos_check_every)
        # Forward passes (prefill + decode) the last __call__ dispatched —
        # observability for the early-exit tests and bench.
        self.last_steps = 0
        config_ = self.config

        def first_token(params, prompt, cache, rng):
            logits, cache = apply_fn(params, prompt, cache)
            rng, sub = jax.random.split(rng)
            first = sample_tokens(logits[:, -1, :], sub, config_)
            done = (
                first == config_.eos_token_id
                if config_.eos_token_id is not None
                else jnp.zeros((prompt.shape[0],), bool)
            )
            return first, cache, rng, done

        def decode_step(params, token, cache, rng, done):
            rng, sub = jax.random.split(rng)
            logits, cache = apply_fn(params, token[:, None], cache)
            nxt = sample_tokens(logits[:, -1, :], sub, config_)
            if config_.eos_token_id is not None:
                nxt = jnp.where(done, config_.pad_token_id, nxt)
                done = done | (nxt == config_.eos_token_id)
            return nxt, cache, rng, done

        if jit_loop:
            # Donate the cache so each step updates it in place (no per-step
            # HBM copy of the whole KV store).
            first_token = jax.jit(first_token, donate_argnums=(2,))
            decode_step = jax.jit(decode_step, donate_argnums=(2,))
        self._first_token = first_token
        self._decode_step = decode_step

    def __call__(
        self, params: Any, prompt: jax.Array, *, rng: jax.Array | None = None
    ) -> jax.Array:
        """(B, S_prompt) int32 -> (B, S_prompt + max_new_tokens); rows that
        hit EOS are padded."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        if self.config.max_new_tokens <= 0:
            return prompt
        B, S_prompt = prompt.shape
        cache = self.init_cache_fn(B, S_prompt + self.config.max_new_tokens)
        token, cache, rng, done = self._first_token(params, prompt, cache, rng)
        tokens = [token]
        n_rest = self.config.max_new_tokens - 1
        ran = 0
        if self.config.eos_token_id is None:
            # No eos -> `done` never flips; dispatch the whole loop with no
            # host syncs (the original fire-and-forget pipeline).
            for _ in range(n_rest):
                token, cache, rng, done = self._decode_step(
                    params, token, cache, rng, done
                )
                tokens.append(token)
            ran = n_rest
        else:
            while ran < n_rest:
                if bool(np.all(jax.device_get(done))):
                    break
                for _ in range(min(self.eos_check_every, n_rest - ran)):
                    token, cache, rng, done = self._decode_step(
                        params, token, cache, rng, done
                    )
                    tokens.append(token)
                    ran += 1
            if ran < n_rest:
                # Every row is done: the skipped steps would each emit pure
                # pad (decode_step's where(done, pad, .) discipline), so
                # fill without running them.
                pad = jnp.full((B,), self.config.pad_token_id, jnp.int32)
                tokens.extend([pad] * (n_rest - ran))
        self.last_steps = 1 + ran
        return jnp.concatenate([prompt] + [t[:, None] for t in tokens], axis=1)


def generate(
    params: Any,
    prompt: jax.Array,
    *,
    apply_fn: Callable[[Any, jax.Array, Any], tuple[jax.Array, Any]],
    init_cache_fn: Callable[[int, int], Any],
    config: GenerationConfig | None = None,
    rng: jax.Array | None = None,
    jit_loop: bool = True,
) -> jax.Array:
    """One-shot convenience over `Generator` (rebuilds the jitted steps per
    call — construct a `Generator` for repeated generation)."""
    gen = Generator(apply_fn, init_cache_fn, config, jit_loop=jit_loop)
    return gen(params, prompt, rng=rng)

"""`accelerate-tpu tpu-config` — run setup commands on every pod worker.

Analog of the reference `commands/tpu.py` (`tpu_command_launcher`: gcloud
ssh --worker=all to prepare a pod before `accelerate launch`). Commands are
joined with `;` and executed on each worker; `--install_accelerate_tpu`
prepends the framework install. `--debug` prints instead of running.
"""

from __future__ import annotations

import argparse
import shlex
import subprocess

from .config import load_default_config


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser(
        "tpu-config", help="Run setup commands on all TPU pod workers"
    )
    p.add_argument("--config_file", default=None, help="Launch config file with tpu_name/zone")
    p.add_argument("--tpu_name", default=None, help="GCE TPU name")
    p.add_argument("--tpu_zone", default=None)
    p.add_argument("--tpu_project", default=None)
    p.add_argument(
        "--command",
        action="append",
        dest="worker_commands",  # `command` is the CLI subparser dest
        default=None,
        help="Command to run on each worker; repeatable",
    )
    p.add_argument(
        "--command_file",
        default=None,
        help="File with one command per line to run on each worker",
    )
    p.add_argument(
        "--install_accelerate_tpu",
        action="store_true",
        help="Prepend a pip install of this framework",
    )
    p.add_argument(
        "--accelerate_tpu_version",
        default="latest",
        help="Version to install ('latest' = upgrade to newest release)",
    )
    p.add_argument(
        "--debug", action="store_true", help="Print the gcloud command, don't run it"
    )
    p.set_defaults(func=run)


def build_gcloud_command(args: argparse.Namespace) -> tuple[list[str], str]:
    cfg = None
    if args.config_file:
        from .config import LaunchConfig

        cfg = LaunchConfig.load(args.config_file)
    else:
        cfg = load_default_config()

    tpu_name = args.tpu_name or (cfg.tpu_name if cfg else None)
    tpu_zone = args.tpu_zone or (cfg.tpu_zone if cfg else None)
    tpu_project = args.tpu_project or (cfg.tpu_project if cfg else None)
    if not tpu_name or not tpu_zone:
        raise ValueError(
            "tpu-config needs --tpu_name and --tpu_zone (or a config file "
            "that sets them)"
        )

    commands: list[str] = []
    if args.install_accelerate_tpu:
        if args.accelerate_tpu_version == "latest":
            commands.append("pip install -U accelerate-tpu")
        else:
            commands.append(f"pip install accelerate-tpu=={args.accelerate_tpu_version}")
    if args.command_file:
        with open(args.command_file) as f:
            commands.extend(line.strip() for line in f if line.strip())
    if args.worker_commands:
        commands.extend(args.worker_commands)
    if not commands:
        raise ValueError(
            "Nothing to run: pass --command / --command_file / --install_accelerate_tpu"
        )

    remote = "; ".join(commands)
    from .launch import build_tpu_ssh_command

    return build_tpu_ssh_command(tpu_name, tpu_zone, tpu_project, remote), tpu_name


def run(args: argparse.Namespace) -> int:
    gcloud, tpu_name = build_gcloud_command(args)
    if args.debug:
        print(" ".join(shlex.quote(c) for c in gcloud))
        return 0
    print(f"Running {gcloud[-1][len('--command='):]!r} on all workers of {tpu_name}")
    return subprocess.call(gcloud)

"""`accelerate-tpu` / `atx` CLI entry point.

Analog of the reference `commands/accelerate_cli.py:27-48` subcommand
registry. Subcommands are registered lazily so importing the CLI stays cheap;
full implementations arrive with the launcher milestone (`commands/launch.py`,
`commands/config.py`, ...).
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="accelerate-tpu",
        description="TPU-native training & inference framework CLI",
    )
    subparsers = parser.add_subparsers(dest="command")

    from . import env as env_cmd

    env_cmd.register(subparsers)
    try:
        from . import config as config_cmd

        config_cmd.register(subparsers)
    except ImportError:  # pragma: no cover
        pass
    try:
        from . import launch as launch_cmd

        launch_cmd.register(subparsers)
    except ImportError:  # pragma: no cover
        pass
    try:
        from . import estimate as estimate_cmd

        estimate_cmd.register(subparsers)
    except ImportError:  # pragma: no cover
        pass
    try:
        from . import test as test_cmd

        test_cmd.register(subparsers)
    except ImportError:  # pragma: no cover
        pass
    try:
        from . import merge as merge_cmd

        merge_cmd.register(subparsers)
    except ImportError:  # pragma: no cover
        pass

    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 1
    return args.func(args) or 0


if __name__ == "__main__":
    sys.exit(main())

"""`accelerate-tpu` / `atx` CLI entry point.

Analog of the reference `commands/accelerate_cli.py:27-48` subcommand
registry. Subcommands are registered lazily so importing the CLI stays cheap;
full implementations arrive with the launcher milestone (`commands/launch.py`,
`commands/config.py`, ...).
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="accelerate-tpu",
        description="TPU-native training & inference framework CLI",
    )
    subparsers = parser.add_subparsers(dest="command")

    import importlib

    for name in (
        "env", "config", "launch", "estimate", "lint", "serve", "test",
        "merge", "tpu", "chaos", "trace",
    ):
        try:
            module = importlib.import_module(f".{name}", package=__package__)
        except ImportError as e:
            # Only tolerate the subcommand module itself being absent; a
            # broken import *inside* an existing module must surface.
            if e.name == f"{__package__}.{name}":
                continue
            raise
        module.register(subparsers)

    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 1
    return args.func(args) or 0


if __name__ == "__main__":
    sys.exit(main())

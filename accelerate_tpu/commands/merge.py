"""`accelerate-tpu merge` — consolidate a sharded checkpoint into one file.

Analog of `accelerate merge-weights` (reference `commands/merge.py:26-61` →
`merge_fsdp_weights`, `utils/fsdp_utils.py:247-329`). Works on any directory
written by `save_pytree`/`save_state` (pass the ``train_state`` or ``model``
subdirectory)."""

from __future__ import annotations

import argparse


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser(
        "merge", help="Merge a sharded checkpoint dir into a single .npz or .safetensors file"
    )
    p.add_argument("checkpoint_dir", help="Directory containing shards_*.npz + index_*.json")
    p.add_argument(
        "output_path",
        help="Output path: .safetensors writes an HF-interchange file, "
        "anything else writes .npz",
    )
    p.set_defaults(func=run)


def run(args: argparse.Namespace) -> int:
    from ..checkpointing import consolidate_checkpoint

    out = consolidate_checkpoint(args.checkpoint_dir, args.output_path)
    print(f"Merged checkpoint written to {out}")
    return 0

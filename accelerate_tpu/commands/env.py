"""`accelerate-tpu env` — print platform diagnostics for bug reports.

Analog of reference `commands/env.py:47`.
"""

from __future__ import annotations

import argparse
import os
import platform


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser("env", help="Print environment diagnostics")
    p.set_defaults(func=run)


def run(args: argparse.Namespace) -> int:
    import jax

    import accelerate_tpu

    info = {
        "accelerate_tpu version": accelerate_tpu.__version__,
        "Platform": platform.platform(),
        "Python version": platform.python_version(),
        "JAX version": jax.__version__,
        "JAX backend": jax.default_backend(),
        "Device count": jax.device_count(),
        "Local devices": [str(d) for d in jax.local_devices()],
        "Process count": jax.process_count(),
    }
    env_vars = {k: v for k, v in os.environ.items() if k.startswith(("ATX_", "JAX_", "XLA_"))}
    print("\nCopy-and-paste the text below in your bug report.\n")
    for key, value in info.items():
        print(f"- `{key}`: {value}")
    if env_vars:
        print("- Framework/JAX environment variables:")
        for k, v in sorted(env_vars.items()):
            print(f"  - {k}={v}")
    return 0

"""`accelerate-tpu lint` / `atx lint` — ahead-of-time step analyzer CLI.

Lints the `examples/` entry points (and any registered scenario) without
running them: each scenario rebuilds the example's exact training
configuration — model family/config, strategy, precision, batch shapes —
abstractly via `analysis.lint_training`, so the REAL compiled train step is
traced, lowered, and byte-audited with zero parameters materialized and
zero steps executed. Exit code 1 when any finding at/above ``--severity``
(default: error) is present — the `make lint-graph` CI gate.

Rule catalogue: docs/static_analysis.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Callable


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser(
        "lint",
        help="Ahead-of-time sharding/donation/recompilation lint for train steps",
    )
    p.add_argument(
        "targets",
        nargs="*",
        help="example scripts, directories of them, or scenario names "
        "(default: every built-in example scenario; see --list)",
    )
    p.add_argument(
        "--severity",
        default="error",
        choices=["info", "warning", "error"],
        help="exit non-zero when a finding at/above this severity exists",
    )
    p.add_argument(
        "--show",
        default="info",
        choices=["info", "warning", "error"],
        help="minimum severity to print",
    )
    p.add_argument("--format", dest="fmt", default="text", choices=["text", "json"])
    p.add_argument(
        "--json",
        dest="json_lines",
        action="store_true",
        help="emit findings as JSON lines (one finding object per line, "
        "machine-readable `data` included — e.g. the ATX404 byte table)",
    )
    p.add_argument(
        "--multihost",
        type=int,
        default=0,
        metavar="N",
        help="also verify multi-host SPMD consistency (ATX5xx) by replaying "
        "each scenario under N simulated processes; adds the host-loop "
        "scenarios (save_path, preemption_exit, router_drain, "
        "replicated_save, elastic_restore, telemetry, tracing) to the "
        "default set",
    )
    p.add_argument(
        "--chip",
        default=None,
        metavar="GEN",
        help="chip generation the ATX6xx roofline rates against (v4, v5e, "
        "v5p, v6e, cpu; default: auto-detect the local device). The "
        "lint-perf lane pins v5e so the budget series is TPU-shaped even "
        "on the CPU container",
    )
    p.add_argument(
        "--budgets",
        metavar="FILE",
        default=None,
        help="ratchet the static series against this committed budgets "
        "JSON: the ATX601 roofline series (static_mfu_bound, "
        "exposed_comms_bytes, padding_waste_fraction), the ATX701 "
        "peak_hbm_mib, and the ATX706 serve_static_max_slots; any "
        "regression past tolerance fails the run (the `make lint-perf` / "
        "`make lint-memory` gates, docs/performance.md)",
    )
    p.add_argument(
        "--write-budgets",
        dest="write_budgets",
        metavar="FILE",
        default=None,
        help="write/re-baseline the budgets JSON from this run's "
        "ATX601/ATX701/ATX706 series (one entry per scenario that "
        "produced any)",
    )
    p.add_argument("--list", action="store_true", help="list lintable scenarios")
    p.add_argument(
        "--rules", action="store_true", help="list the registered rule catalogue"
    )
    p.add_argument(
        "--host_devices",
        type=int,
        default=None,
        help="simulate N host devices (XLA_FLAGS) so sharding/collective "
        "rules see a real mesh on CPU; must be set before jax initializes",
    )
    p.set_defaults(func=run)


# --------------------------------------------------------------- scenarios
# Each scenario mirrors one examples/ entry point's training configuration.
# Builders return (description, Report).


def _fresh_accelerator(**kwargs: Any):
    from ..accelerator import Accelerator
    from ..state import AcceleratorState

    AcceleratorState._reset_state()
    return Accelerator(seed=0, **kwargs)


def _scenario_nlp_example(**options: Any):
    """examples/nlp_example.py: BERT-tiny pair classification, DP, fp32."""
    import jax.numpy as jnp
    import numpy as np
    import optax

    from .. import analysis
    from ..models import bert
    from ..utils.dataclasses import DataLoaderConfiguration

    acc = _fresh_accelerator(
        max_grad_norm=1.0,
        dataloader_config=DataLoaderConfiguration(split_batches=True),
    )
    config = bert.BertConfig.tiny(
        vocab_size=128, max_seq_len=64, d_model=64, d_ff=128
    )
    batch_size, seq_len = 64, 64
    batch = {
        "input_ids": np.zeros((batch_size, seq_len), np.int32),
        "token_type_ids": np.zeros((batch_size, seq_len), np.int32),
        "attention_mask": np.zeros((batch_size, seq_len), np.int32),
        "labels": np.zeros((batch_size,), np.int32),
    }
    report = analysis.lint_training(
        acc,
        lambda r: bert.init(r, config),
        optax.adamw(2e-3, weight_decay=0.01),
        lambda params, b, rng: bert.loss_fn(params, b, config, rng),
        batch,
        target="examples/nlp_example.py",
        **options,
    )
    desc = f"BERT-tiny pair classification, {acc!r}"
    return desc, report


def _scenario_lm_example(**options: Any):
    """examples/lm_example.py: GPT causal LM, bf16, grad clipping."""
    import numpy as np
    import optax

    from .. import analysis
    from ..models import gpt

    acc = _fresh_accelerator(mixed_precision="bf16", max_grad_norm=1.0)
    config = gpt.GPTConfig(
        vocab_size=128, d_model=128, n_layers=4, num_heads=4, d_ff=512,
        max_seq_len=64,
    )
    batch = {"input_ids": np.zeros((8, 64), np.int32)}
    report = analysis.lint_training(
        acc,
        lambda r: gpt.init(r, config),
        optax.adamw(3e-3),
        lambda params, b, rng: gpt.loss_fn(params, b, config, rng),
        batch,
        target="examples/lm_example.py",
        **options,
    )
    return f"GPT causal LM, {acc!r}", report


def _scenario_llama2b(**options: Any):
    """llama 1.64B train step (the bench.py llama2b phase), linted fully
    abstractly: the real 24-layer seq-4096 config with remat +
    adafactor is traced/lowered/compiled with zero parameters
    materialized — the scenario the ATX601 roofline bounds for real
    (attention_impl="dot": the pallas flash kernel has no abstract CPU
    lowering; same dot/collective structure either way). Sharded FSDP
    over the 8 simulated devices: that is the deployment the v5e-rated
    lanes judge — a fully-replicated 1.64B fp32 state (~21 GiB static)
    cannot fit one 16 GiB chip, which the ATX702 OOM-ahead-of-time gate
    would rightly fail."""
    import numpy as np
    import optax

    from .. import analysis
    from ..parallel.mesh import MeshConfig

    from ..models import llama

    acc = _fresh_accelerator(
        mixed_precision="bf16",
        max_grad_norm=1.0,
        mesh_config=MeshConfig(data=1, fsdp=8),
        strategy="FSDP",
    )
    config = llama.LlamaConfig(
        vocab_size=32000,
        d_model=2048,
        n_layers=24,
        num_heads=16,
        num_kv_heads=8,
        head_dim=128,
        d_ff=8192,
        max_seq_len=4096,
        remat=True,
        remat_policy="attn_and_outputs",
        attention_impl="dot",
        loss_chunk_size=512,
    )
    # bench trains batch 2 on one chip; abstractly the batch axis must
    # divide the 8 simulated devices the lint lanes force.
    batch = {"input_ids": np.zeros((8, 4096), np.int32)}
    report = analysis.lint_training(
        acc,
        lambda r: llama.init(r, config),
        optax.adafactor(3e-4),
        lambda params, b, rng: llama.loss_fn(params, b, config, rng),
        batch,
        target="llama2b",
        **options,
    )
    return f"llama 1.64B seq-4096 train step, {acc!r}", report


def _scenario_cv_example(**options: Any):
    """examples/cv_example.py: inline convnet quadrant classification, DP."""
    import importlib.util

    import numpy as np
    import optax

    from .. import analysis

    path = _examples_dir() / "cv_example.py"
    spec = importlib.util.spec_from_file_location("atx_lint_cv_example", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    from ..utils.dataclasses import DataLoaderConfiguration

    acc = _fresh_accelerator(
        dataloader_config=DataLoaderConfiguration(split_batches=True)
    )
    image_size = 32
    batch = {
        "image": np.zeros((64, image_size, image_size, 1), np.float32),
        "label": np.zeros((64,), np.int32),
    }
    report = analysis.lint_training(
        acc,
        lambda r: mod.init_convnet(r, image_size=image_size),
        optax.adam(1e-3),
        mod.loss_fn,
        batch,
        target="examples/cv_example.py",
        **options,
    )
    return f"convnet quadrant classifier, {acc!r}", report


def _scenario_serving(**options: Any):
    """serving hot paths behind a 2-replica Router: EACH replica engine's
    slot-batched decode function is linted with its own abstract call
    signature (donation of the slot cache, no host syncs/callbacks in the
    compiled step, stable shapes), and when the prefix cache is on, each
    replica's bucketed prefix-copy function too — the per-replica device
    programs the multi-replica front-end dispatches (docs/serving.md)."""
    import jax
    import jax.numpy as jnp

    from .. import analysis
    from ..generation import GenerationConfig
    from ..models import llama
    from ..serving import Engine, Router

    config = llama.LlamaConfig.tiny(vocab_size=128, max_seq_len=128)
    params = llama.init(jax.random.PRNGKey(0), config)

    def mk_engine() -> Engine:
        return Engine(
            lambda p, t, c: llama.forward_with_cache(p, t, c, config),
            lambda b, m: llama.init_cache(config, b, m),
            params,
            GenerationConfig(eos_token_id=0),
            slots=4,
            buckets=(16, 32),
            max_len=96,
        )

    # threads=False: nothing is dispatched here, so no replica threads —
    # the router only names/owns the replica engines being linted.
    router = Router([mk_engine(), mk_engine()], threads=False)
    findings: list = []
    for rep in router.replicas:
        engine = rep.engine
        report = analysis.lint_step(
            engine._decode_fn,
            *engine.abstract_decode_args(),
            donate_argnums=(3,),
            target=f"serving.Router.replica{rep.id}.decode",
            **options,
        )
        findings += report.findings
        if rep.id == router.replicas[0].id:
            # ATX706 capacity plan for the fleet's engine shape (replicas
            # are identical): weights + slot pool + prefix pool vs the
            # chip, with the decode step's at-peak working bytes from the
            # ATX701 timeline just computed. Emitted here — not as a
            # registered rule — because the planner needs a constructed
            # engine, not a step function.
            atx701 = next(
                (f for f in report.findings if f.rule_id == "ATX701"), None
            )
            act = 0
            if atx701 is not None and atx701.data:
                cats = atx701.data.get("categories_at_peak", {})
                act = sum(
                    v for k, v in cats.items()
                    if k in ("activations", "xla_temp", "collective")
                )
            findings += analysis.capacity_findings(
                engine, chip=options.get("roofline_chip"), act_peak_bytes=act
            )
        if engine.prefix_cache is not None:
            copy_report = analysis.lint_step(
                engine.copy_fn_for_bucket(engine.buckets[0]),
                *engine.abstract_copy_args(),
                donate_argnums=(0,),
                target=f"serving.Router.replica{rep.id}.prefix_copy",
                **options,
            )
            findings += copy_report.findings
    n_slots = router.replicas[0].engine.n_slots
    desc = (
        f"2-replica router: decode + prefix copy per replica, "
        f"{n_slots} slots each"
    )
    return desc, analysis.Report(
        findings=findings, target="serving.Router.decode+prefix_copy"
    )


def _scenario_kernels(**options: Any):
    """Pallas kernel tier (`native/pallas/`): the serving decode step and a
    host-offloaded-AdamW train step with every kernel forced into interpret
    mode — proving the kernel lowerings keep the donation and host-sync
    contracts (no new ATX2xx/3xx findings relative to the fallbacks the
    other scenarios lint)."""
    import jax
    import numpy as np

    from .. import analysis
    from ..generation import GenerationConfig
    from ..models import gpt, llama
    from ..native.pallas import force_kernels
    from ..parallel import host_offload
    from ..serving import Engine

    config = llama.LlamaConfig.tiny(vocab_size=128, max_seq_len=128)
    params = llama.init(jax.random.PRNGKey(0), config)
    findings: list = []
    with force_kernels("interpret"):
        engine = Engine(
            lambda p, t, c: llama.forward_with_cache(p, t, c, config),
            lambda b, m: llama.init_cache(config, b, m),
            params,
            GenerationConfig(eos_token_id=0),
            slots=4,
            buckets=(16, 32),
            max_len=96,
        )
        report = analysis.lint_step(
            engine._decode_fn,
            *engine.abstract_decode_args(),
            donate_argnums=(3,),
            target="kernels.decode_attn",
            **options,
        )
        findings += report.findings

        acc = _fresh_accelerator(mixed_precision="bf16", max_grad_norm=1.0)
        gpt_config = gpt.GPTConfig(
            vocab_size=128, d_model=128, n_layers=4, num_heads=4, d_ff=512,
            max_seq_len=64,
        )
        batch = {"input_ids": np.zeros((8, 64), np.int32)}
        train_report = analysis.lint_training(
            acc,
            lambda r: gpt.init(r, gpt_config),
            host_offload.host_offloaded_adamw(3e-3),
            lambda params, b, rng: gpt.loss_fn(params, b, gpt_config, rng),
            batch,
            target="kernels.fused_adamw",
            **options,
        )
        findings += train_report.findings
    desc = "kernel-tier decode + fused-AdamW train step, interpret mode"
    return desc, analysis.Report(findings=findings, target="kernels")


SCENARIOS: dict[str, Callable[..., tuple[str, Any]]] = {
    "nlp_example": _scenario_nlp_example,
    "lm_example": _scenario_lm_example,
    "cv_example": _scenario_cv_example,
    "llama2b": _scenario_llama2b,
    "serving": _scenario_serving,
    "kernels": _scenario_kernels,
}

# `atx lint perf`: the scenario set the ATX6xx budget ratchet covers
# (`make lint-perf`) — the example train steps plus the bench-scale llama.
PERF_SCENARIOS = ("nlp_example", "lm_example", "cv_example", "llama2b")

# `atx lint memory`: the ATX7xx HBM-timeline set (`make lint-memory`) —
# the perf scenarios plus the serving scenario, whose ATX706 capacity
# plan feeds the serve_static_max_slots budget series.
MEMORY_SCENARIOS = PERF_SCENARIOS + ("serving",)


# ----------------------------------------------- multi-host (ATX5xx) scenarios
# Host-side loops replayed under N simulated processes via
# `analysis.lint_host_loop` — these verify the COLLECTIVE SCHEDULE (barrier /
# commit / broadcast ordering across processes), not the compiled step.
# Builders take `processes` and return (description, Report).


def _mh_scenario_save_path(processes: int = 2):
    """checkpointing.save_state: train one step then save synchronously,
    then another step and an ASYNC save — the precommit markers, commit
    barrier, and final-dir broadcast must issue an identical collective
    schedule on every process, in both save modes (the replay models the
    async writer by running the submitted job inline, so its precommit
    file-barrier schedule is checked too)."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from .. import analysis, checkpointing
    from ..accelerator import Accelerator, TrainState
    from ..state import AcceleratorState
    from ..utils.dataclasses import ProjectConfiguration

    def save_loop():
        AcceleratorState._reset_state()
        root = tempfile.mkdtemp(prefix="atx_lint_mh_save_")
        acc = Accelerator(
            seed=0,
            project_config=ProjectConfiguration(
                project_dir=root, automatic_checkpoint_naming=True
            ),
        )
        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (8, 8), jnp.float32)}
        state = acc.prepare_train_state(
            TrainState.create(params=params, tx=optax.sgd(1e-2))
        )
        step = acc.make_train_step(
            lambda p, b, r=None: jnp.mean((b["x"] @ p["w"]) ** 2)
        )
        state, _ = step(state, {"x": np.ones((8, 8), np.float32)})
        checkpointing.save_state(acc, None, state, async_save=False)
        state, _ = step(state, {"x": np.ones((8, 8), np.float32)})
        checkpointing.save_state(acc, None, state, async_save=True)
        checkpointing.wait_for_checkpoint()

    report = analysis.lint_host_loop(
        save_loop, processes=processes, target="save_path"
    )
    return (
        f"train step + sync save_state + async save_state, "
        f"{processes} processes",
        report,
    )


def _mh_scenario_preemption_exit(processes: int = 2):
    """Emergency-save path: one process gets the preemption notice; the
    group must still agree (or-reduce) before the synchronized emergency
    checkpoint + exit — the schedule every process runs must match."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from .. import analysis
    from ..accelerator import Accelerator, TrainState
    from ..state import AcceleratorState
    from ..utils.dataclasses import ProjectConfiguration

    def train_loop():
        AcceleratorState._reset_state()
        root = tempfile.mkdtemp(prefix="atx_lint_mh_preempt_")
        acc = Accelerator(
            seed=0,
            project_config=ProjectConfiguration(
                project_dir=root, automatic_checkpoint_naming=True
            ),
        )
        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (8, 8), jnp.float32)}
        state = acc.prepare_train_state(
            TrainState.create(params=params, tx=optax.sgd(1e-2))
        )
        step = acc.make_train_step(
            lambda p, b, r=None: jnp.mean((b["x"] @ p["w"]) ** 2)
        )
        batch = {"x": np.ones((8, 8), np.float32)}
        for _ in range(3):
            state, _ = step(state, batch)

    report = analysis.lint_host_loop(
        train_loop,
        processes=processes,
        preempted=[0],
        target="preemption_exit",
    )
    return (
        f"preemption notice on process 0 of {processes} — emergency save + exit",
        report,
    )


def _mh_scenario_router_drain(processes: int = 2):
    """serving.Router drain + failover host loop (the ROADMAP follow-up
    for serving's multi-host dispatch): a 2-replica inline router serves a
    small trace while replica 0 is fault-injected dead mid-trace and a
    preemption notice arrives — the dispatch/flag schedule every process
    replays must stay identical (deterministic inline routing), and the
    drain must finish every accepted request."""
    from .. import analysis

    def router_loop():
        import jax
        import numpy as np

        from .. import resilience
        from ..generation import GenerationConfig
        from ..models import llama
        from ..serving import Engine, Request, Router
        from ..test_utils import faults
        from ..utils.environment import patch_environment

        config = llama.LlamaConfig.tiny(vocab_size=64, max_seq_len=64)
        params = llama.init(jax.random.PRNGKey(0), config)

        def mk_engine() -> Engine:
            return Engine(
                lambda p, t, c: llama.forward_with_cache(p, t, c, config),
                lambda b, m: llama.init_cache(config, b, m),
                params,
                GenerationConfig(
                    max_new_tokens=4, eos_token_id=None, pad_token_id=0
                ),
                slots=2,
                buckets=(8,),
                max_len=32,
                prefix_cache=False,
            )

        rng = np.random.RandomState(0)
        reqs = [
            Request(prompt=rng.randint(1, 64, (6,)).astype(np.int32), rid=i)
            for i in range(4)
        ]
        faults._reset_counters()  # the @N counter must restart per process
        with patch_environment(ATX_FAULT_RAISE_AT="router.replica0.step@2"):
            router = Router([mk_engine(), mk_engine()], threads=False)
            for r in reqs:
                router.submit_request(r)
            for _ in range(3):  # replica 0 dies on its second pumped step
                router.poll()
            resilience.request_preemption()
            out = router.join()
            router.close()
        assert len(out) == len(reqs), f"drain lost requests: {len(out)}"
        assert router.draining and router.drain_reason == "preemption"
        assert router.stats["replicas_lost"] == 1

    report = analysis.lint_host_loop(
        router_loop, processes=processes, target="router_drain"
    )
    return (
        f"2-replica router, replica-0 fault + preemption drain, "
        f"{processes} processes",
        report,
    )


def _mh_scenario_replicated_save(processes: int = 2):
    """checkpointing.save_state WITH checkpoint replication enabled
    (ATX_REPLICATE_URL): the collective schedule must be IDENTICAL to the
    plain save path — replication is queue + background object IO on the
    committing process only, so turning it on must add zero collectives
    (the acceptance gate for resilience/replicate.py). The loop also
    drains the replicator and asserts the committing process actually
    uploaded a remote-committed checkpoint."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from .. import analysis, checkpointing
    from ..accelerator import Accelerator, TrainState
    from ..state import AcceleratorState
    from ..utils.dataclasses import ProjectConfiguration
    from ..utils.environment import patch_environment

    def replicated_save_loop():
        AcceleratorState._reset_state()
        root = tempfile.mkdtemp(prefix="atx_lint_mh_repl_")
        store_root = tempfile.mkdtemp(prefix="atx_lint_mh_repl_store_")
        with patch_environment(ATX_REPLICATE_URL=store_root):
            acc = Accelerator(
                seed=0,
                project_config=ProjectConfiguration(
                    project_dir=root, automatic_checkpoint_naming=True
                ),
            )
            assert acc._replicator is not None, "replication did not arm"
            params = {
                "w": jax.random.normal(jax.random.PRNGKey(0), (8, 8), jnp.float32)
            }
            state = acc.prepare_train_state(
                TrainState.create(params=params, tx=optax.sgd(1e-2))
            )
            step = acc.make_train_step(
                lambda p, b, r=None: jnp.mean((b["x"] @ p["w"]) ** 2)
            )
            state, _ = step(state, {"x": np.ones((8, 8), np.float32)})
            checkpointing.save_state(acc, None, state, async_save=False)
            assert acc._replicator.drain(60.0), "replication queue stuck"
            if jax.process_index() == 0:
                from ..resilience import replicate

                assert acc._replicator.failures == 0, acc._replicator.last_error
                remote = replicate.remote_committed_checkpoints(
                    acc._replicator.store
                )
                assert remote, "committing process uploaded no remote commit"

    report = analysis.lint_host_loop(
        replicated_save_loop, processes=processes, target="replicated_save"
    )
    return (
        f"train step + synchronous save_state with replication armed, "
        f"{processes} processes",
        report,
    )


def _mh_scenario_elastic_restore(processes: int = 2):
    """Elastic reshard-on-restore: save a committed checkpoint, doctor its
    recorded topology signature so the restore sees a world-size mismatch,
    then ``load_state(resume="latest")``. The whole restore — discovery,
    verification, topology detection, peer-shard coverage probing, shard
    assembly — must be COLLECTIVE-FREE (sentinel polling + file IO only):
    a SMALLER surviving group restores without the dead ranks, so any
    collective here would hang the resume. The replay pins exactly that:
    zero new collective-log events between save and restored state."""
    import json as _json
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from .. import analysis, checkpointing
    from ..accelerator import Accelerator, TrainState
    from ..state import AcceleratorState
    from ..utils.dataclasses import ProjectConfiguration

    # ONE root shared by every simulated process (and every replay round):
    # the save path broadcasts process 0's directory choice, so per-process
    # roots would leave process 1's own root empty at restore time. Rounds
    # just stack checkpoint_<n> dirs; names never enter event signatures.
    root = tempfile.mkdtemp(prefix="atx_lint_mh_elastic_")

    def restore_loop():
        AcceleratorState._reset_state()
        # save_on_each_node: each simulated process commits a self-contained
        # checkpoint (the per-node-filesystem shape), so whichever process
        # committed last, the directory it restores from is complete.
        acc = Accelerator(
            seed=0,
            project_config=ProjectConfiguration(
                project_dir=root,
                automatic_checkpoint_naming=True,
                save_on_each_node=True,
            ),
        )
        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (8, 8), jnp.float32)}
        state = acc.prepare_train_state(
            TrainState.create(params=params, tx=optax.sgd(1e-2))
        )
        step = acc.make_train_step(
            lambda p, b, r=None: jnp.mean((b["x"] @ p["w"]) ** 2)
        )
        state, _ = step(state, {"x": np.ones((8, 8), np.float32)})
        final_dir = checkpointing.save_state(acc, None, state, async_save=False)
        # Doctor the recorded topology (num_devices) so the restore takes
        # the elastic mismatch path — detection, coverage probe and all.
        from ..resilience.commit import COMMIT_MARKER

        marker = os.path.join(final_dir, COMMIT_MARKER)
        with open(marker) as f:
            meta = _json.load(f)
        meta["num_devices"] = int(meta.get("num_devices") or 1) * 2
        with open(marker, "w") as f:
            _json.dump(meta, f)
        from ..analysis import host_trace

        rec = host_trace._ACTIVE_RECORDER
        before = len(rec.collective_events) if rec is not None else None
        restored = checkpointing.load_state(acc, None, state, resume="latest")
        if rec is not None:
            grew = len(rec.collective_events) - before
            assert grew == 0, (
                f"elastic restore issued {grew} collective(s); the restore "
                "path must stay collective-free so a smaller surviving "
                "group can resume without the dead ranks"
            )
        assert int(jax.device_get(restored.step)) == int(
            jax.device_get(state.step)
        ), "restore returned the wrong step"

    report = analysis.lint_host_loop(
        restore_loop, processes=processes, target="elastic_restore"
    )
    return (
        f"committed save + topology-mismatched resume='latest' restore "
        f"(must add zero collectives), {processes} processes",
        report,
    )


def _mh_scenario_shrink(processes: int = 2):
    """Shrink-in-place (resilience/elastic.py): a devices-file retarget
    escalates at a step boundary, survivors run the agreement round, and
    the accelerator reshards params/opt-state/step in memory onto the
    smaller mesh — then keeps training. The whole escalate -> agree ->
    reshard window must be COLLECTIVE-FREE (proposal/decision objects +
    file IO only): in a real shrink the departed peer is dead, and any
    collective in this window would park the survivors forever. The replay
    pins exactly that, plus identical post-shrink schedules across the
    surviving processes (the ATX501/502/503 gates)."""
    import math
    import tempfile

    import jax

    from .. import analysis
    from ..resilience import elastic as _elastic

    total = jax.device_count()
    host = total // processes if processes else 0
    if host < 2 or total % processes != 0:
        raise RuntimeError(
            f"the shrink scenario needs >= 2 simulated devices per process "
            f"(got {total} device(s) for {processes} process(es)); run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    new_host = host - 1
    new_total = processes * new_host
    # Batch rows must divide the data axis both before and after the shrink
    # (constrain_batch binds activations to the mesh).
    rows = math.lcm(total, new_total)

    # ONE root shared by every simulated process and every replay round:
    # the agreement surface is how the survivors see each other. The
    # devices file, peer proposals, and decision are seeded ONCE up front —
    # the replay runs simulated processes SEQUENTIALLY, so a blocking
    # follower could never observe a live coordinator; pre-seeding plus the
    # coordinator's idempotent decision write make every round converge on
    # identical bytes.
    root = tempfile.mkdtemp(prefix="atx_lint_mh_shrink_")
    edir = os.path.join(root, "elastic")
    dfile = os.path.join(root, "devices")
    with open(dfile, "w") as f:
        f.write(f"{processes} {new_host}\n")
    decision = _elastic.TopologyDecision(
        epoch=1,
        survivors=tuple(range(processes)),
        host_devices=new_host,
        step=0,
    )
    surface = _elastic._FileSurface(edir)
    _elastic.post_peer_proposals(surface, range(processes), decision)
    surface.write(_elastic.DECISION_FILE.format(epoch=1), decision.to_payload())

    env = {
        "ATX_ELASTIC_SHRINK": "1",
        "ATX_ELASTIC_DIR": edir,
        "ATX_ELASTIC_DEVICES_FILE": dfile,
        "ATX_ELASTIC_AGREE_SECS": "5",
    }

    def shrink_loop():
        import jax.numpy as jnp
        import numpy as np
        import optax

        from ..accelerator import Accelerator, TrainState
        from ..analysis import host_trace
        from ..state import AcceleratorState

        AcceleratorState._reset_state()
        acc = Accelerator(seed=0)
        assert acc._elastic is not None, "elastic controller did not arm"
        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (8, 8), jnp.float32)}
        state = acc.prepare_train_state(
            TrainState.create(params=params, tx=optax.sgd(1e-2))
        )
        step = acc.make_train_step(
            lambda p, b, r=None: jnp.mean((b["x"] @ p["w"]) ** 2)
        )
        rec = host_trace._ACTIVE_RECORDER
        before = len(rec.collective_events) if rec is not None else None
        resized = acc._maybe_elastic_resize(state, 0)
        if rec is not None:
            grew = len(rec.collective_events) - before
            assert grew == 0, (
                f"shrink agreement+reshard issued {grew} collective(s); the "
                "escalate -> agree -> reshard window must stay collective-"
                "free — the departed peer is dead and would park any "
                "collective forever"
            )
        assert resized is not None, "in-place shrink did not engage"
        assert acc.mesh.devices.size == new_total, (
            f"mesh has {acc.mesh.devices.size} devices after shrink, "
            f"wanted {new_total}"
        )
        state = resized
        batch = {"x": np.ones((rows, 8), np.float32)}
        state, _ = step(state, batch)
        state, _ = step(state, batch)
        assert int(jax.device_get(state.step)) == 2, "post-shrink steps lost"
        assert acc.mesh.devices.size == new_total, "mesh reverted after steps"

    report = analysis.lint_host_loop(
        shrink_loop, processes=processes, env=env, target="shrink"
    )
    return (
        f"live shrink-in-place: devices-file retarget {total} -> {new_total} "
        f"devices, collective-free agree + in-memory reshard + resumed "
        f"steps, {processes} processes",
        report,
    )


def _mh_scenario_telemetry(processes: int = 2):
    """Runtime telemetry (telemetry/): train steps with ATX_METRICS=1 plus
    the cross-host export path — per-process snapshot write, proc-0 merge,
    Prometheus render — must add ZERO collectives to the step schedule
    (PR-11 shared-surface rule: metrics travel as files, never as
    collectives; a collective here would park survivors when a peer dies
    mid-step). The replay also pins the schedule identical across
    processes with metrics armed (the ATX5xx gates)."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from .. import analysis
    from ..accelerator import Accelerator, TrainState
    from ..state import AcceleratorState
    from ..utils.environment import patch_environment

    def telemetry_loop():
        from .. import telemetry
        from ..analysis import host_trace

        AcceleratorState._reset_state()
        snap_dir = tempfile.mkdtemp(prefix="atx_lint_mh_tel_")
        with patch_environment(
            ATX_METRICS="1", ATX_METRICS_SAMPLE_EVERY="2"
        ):
            acc = Accelerator(seed=0)
            params = {
                "w": jax.random.normal(jax.random.PRNGKey(0), (8, 8), jnp.float32)
            }
            state = acc.prepare_train_state(
                TrainState.create(params=params, tx=optax.sgd(1e-2))
            )
            step = acc.make_train_step(
                lambda p, b, r=None: jnp.mean((b["x"] @ p["w"]) ** 2)
            )
            batch = {"x": np.ones((8, 8), np.float32)}
            for _ in range(3):
                state, _ = step(state, batch)
            assert step.step_stats is not None, "ATX_METRICS=1 did not arm"
            assert step.step_stats.steps == 3
            # The export surface is pure file IO + host math: pin the
            # collective count across it.
            rec = host_trace._ACTIVE_RECORDER
            before = len(rec.collective_events) if rec is not None else 0
            telemetry.write_snapshot(snap_dir, process_index=0)
            telemetry.write_snapshot(snap_dir, process_index=1)
            merged = telemetry.aggregate_snapshots(snap_dir)
            text = telemetry.render_snapshot_prometheus(merged)
            after = len(rec.collective_events) if rec is not None else 0
            assert after == before, (
                f"telemetry export added {after - before} collective(s)"
            )
            # Two identical snapshots merged: counters double, gauges
            # reduce — the cross-host invariant the fleet endpoint serves.
            def _val(snap, name):
                for entry in snap["metrics"]:
                    if entry["name"] == name:
                        return entry["series"][0]["value"]
                raise AssertionError(f"{name} missing from snapshot")

            local = telemetry.snapshot()
            assert _val(merged, "train_steps") == 2 * _val(
                local, "train_steps"
            ), "cross-host counter merge did not sum"
            assert "train_steps" in text and "# TYPE" in text

    report = analysis.lint_host_loop(
        telemetry_loop, processes=processes, target="telemetry"
    )
    return (
        f"3 train steps with ATX_METRICS=1 + snapshot write/merge/render, "
        f"{processes} processes",
        report,
    )


def _mh_scenario_router_recovery(processes: int = 2):
    """Self-healing router path (docs/serving.md): quarantine ->
    prefix-cache migration -> probation probe -> re-admission is pure host
    logic plus single-replica device steps, so it must add ZERO collectives
    to the schedule (a collective inside recovery would park every healthy
    process on the dead peer), and the recovery schedule every process
    replays must be identical."""
    from .. import analysis

    def recovery_loop():
        import time as _time

        import jax
        import numpy as np

        from ..analysis import host_trace
        from ..generation import GenerationConfig
        from ..models import llama
        from ..serving import Engine, Request, Router
        from ..test_utils import faults
        from ..utils.environment import patch_environment

        config = llama.LlamaConfig.tiny(vocab_size=64, max_seq_len=64)
        params = llama.init(jax.random.PRNGKey(0), config)

        def mk_engine() -> Engine:
            return Engine(
                lambda p, t, c: llama.forward_with_cache(p, t, c, config),
                lambda b, m: llama.init_cache(config, b, m),
                params,
                GenerationConfig(
                    max_new_tokens=4, eos_token_id=None, pad_token_id=0
                ),
                slots=2,
                buckets=(8,),
                max_len=32,
                prefix_cache=True,
            )

        rng = np.random.RandomState(0)
        prefix = rng.randint(1, 64, (8,)).astype(np.int32)

        def req(i):
            tail = rng.randint(1, 64, (2,)).astype(np.int32)
            return Request(prompt=np.concatenate([prefix, tail]), rid=i)

        engines = [mk_engine(), mk_engine()]
        # Warm replica 0's prefix cache (and both compile caches) OUTSIDE
        # the router so quarantine deterministically has a hot committed
        # prefix to migrate.
        for eng in engines:
            eng.submit(np.concatenate([prefix, np.asarray([1, 2], np.int32)]), 2)
            eng.run_until_idle()
        reqs = [req(i) for i in range(4)]
        faults._reset_counters()  # the @N counter must restart per process
        rec = host_trace._ACTIVE_RECORDER

        def n_collectives() -> int:
            # Jitted single-replica dispatches (canary replay, migration
            # warm-ups) are aligned schedule events but not cross-process
            # traffic; the recovery ban is on TRUE collectives.
            if rec is None:
                return 0
            return sum(1 for e in rec.collective_events if e.kind != "dispatch")

        before = n_collectives()
        with patch_environment(ATX_FAULT_RAISE_AT="router.replica0.step@2"):
            router = Router(
                engines,
                threads=False,
                readmit_secs=0.001,
                probation_completions=1,
                engine_factory=mk_engine,
            )
            for r in reqs:
                router.submit_request(r)
            out = router.join()
            deadline = _time.time() + 30.0
            while int(router.metrics()["readmissions"]) < 1:
                assert _time.time() < deadline, "no re-admission within 30s"
                router.poll(0.002)
            router.close()
        after = n_collectives()
        m = router.metrics()
        assert len(out) == len(reqs), f"recovery lost requests: {len(out)}"
        assert m["replicas_lost"] == 1 and m["readmissions"] >= 1, m
        assert m["migrated_prefixes"] >= 1, m
        assert m["replicas_alive"] == 2, m
        assert after == before, (
            f"quarantine/probe/readmit/migration added {after - before} "
            "collective(s)"
        )

    report = analysis.lint_host_loop(
        recovery_loop, processes=processes, target="router_recovery"
    )
    return (
        f"2-replica router: replica-0 fault, prefix migration, probe + "
        f"re-admission, {processes} processes",
        report,
    )


def _mh_scenario_tracing(processes: int = 2):
    """Request-scoped tracing (telemetry/flight.py): a full 2-replica serve
    pass with ATX_TRACE_REQUESTS=1 — admission/dispatch spans, prefix
    match, prefill chunks, decode residency, stream + completion, and a
    postmortem bundle dump — must add ZERO collectives to the schedule
    (spans are host dicts in a preallocated ring; a collective here would
    couple request latency to peer health), and greedy outputs must be
    bit-identical to the same trace served with tracing off."""
    from .. import analysis

    def tracing_loop():
        import tempfile

        import jax
        import numpy as np

        from ..analysis import host_trace
        from ..generation import GenerationConfig
        from ..models import llama
        from ..serving import Engine, Request, Router
        from ..telemetry import flight
        from ..utils.environment import patch_environment

        config = llama.LlamaConfig.tiny(vocab_size=64, max_seq_len=64)
        params = llama.init(jax.random.PRNGKey(0), config)

        def mk_engine() -> Engine:
            return Engine(
                lambda p, t, c: llama.forward_with_cache(p, t, c, config),
                lambda b, m: llama.init_cache(config, b, m),
                params,
                GenerationConfig(
                    max_new_tokens=4, eos_token_id=None, pad_token_id=0
                ),
                slots=2,
                buckets=(8,),
                max_len=32,
                prefix_cache=True,
            )

        def trace_reqs() -> list[Request]:
            rng = np.random.RandomState(1)
            return [
                Request(prompt=rng.randint(1, 64, (6,)).astype(np.int32), rid=i)
                for i in range(4)
            ]

        def serve_once() -> dict[int, np.ndarray]:
            router = Router([mk_engine(), mk_engine()], threads=False)
            for r in trace_reqs():
                router.submit_request(r)
            out = {c.rid: c.tokens.copy() for c in router.join()}
            router.close()
            return out

        base = serve_once()  # tracing off: the bit-identity reference
        rec = host_trace._ACTIVE_RECORDER

        def n_collectives() -> int:
            if rec is None:
                return 0
            return sum(1 for e in rec.collective_events if e.kind != "dispatch")

        before = n_collectives()
        with patch_environment(ATX_TRACE_REQUESTS="1"):
            flight.reset_recorder()
            traced = serve_once()
            pm_dir = tempfile.mkdtemp(prefix="atx_lint_pm_")
            path = flight.dump_postmortem("lint_tracing", pm_dir)
            assert path is not None, "postmortem dump returned no path"
            bundle = flight.read_bundle(path)
            assert bundle["spans"], "flight recorder captured no spans"
        after = n_collectives()
        names = {e["name"] for e in flight.recorder().last()}
        for want in (
            "admission", "dispatch", "prefix_match", "prefill_chunk",
            "phase_decode", "stream", "complete",
        ):
            assert want in names, f"missing span {want!r}: {sorted(names)}"
        for rid, toks in base.items():
            assert np.array_equal(toks, traced[rid]), (
                f"rid {rid} diverged with ATX_TRACE_REQUESTS=1"
            )
        assert after == before, (
            f"request tracing added {after - before} collective(s)"
        )

    report = analysis.lint_host_loop(
        tracing_loop, processes=processes, target="tracing"
    )
    return (
        f"2-replica traced serve vs untraced bit-identity + postmortem "
        f"bundle, {processes} processes",
        report,
    )


MULTIHOST_SCENARIOS: dict[str, Callable[..., tuple[str, Any]]] = {
    "save_path": _mh_scenario_save_path,
    "preemption_exit": _mh_scenario_preemption_exit,
    "router_drain": _mh_scenario_router_drain,
    "router_recovery": _mh_scenario_router_recovery,
    "replicated_save": _mh_scenario_replicated_save,
    "elastic_restore": _mh_scenario_elastic_restore,
    "shrink": _mh_scenario_shrink,
    "telemetry": _mh_scenario_telemetry,
    "tracing": _mh_scenario_tracing,
}


def _examples_dir():
    from pathlib import Path

    return Path(__file__).resolve().parents[2] / "examples"


def resolve_targets(
    targets: list[str], multihost: bool = False
) -> tuple[list[str], list[str]]:
    """Map CLI targets (scenario names / example files / directories) to
    scenario names; second element is the unmatched remainder. Multi-host
    scenario names always resolve when given explicitly; ``multihost``
    adds them to the no-target default set."""
    known = {**SCENARIOS, **MULTIHOST_SCENARIOS}
    if not targets:
        names = list(SCENARIOS)
        if multihost:
            names += list(MULTIHOST_SCENARIOS)
        return names, []
    names: list[str] = []
    unmatched: list[str] = []
    for t in targets:
        stem = os.path.splitext(os.path.basename(t.rstrip("/")))[0]
        if t == "perf":
            names.extend(PERF_SCENARIOS)
        elif t == "memory":
            names.extend(MEMORY_SCENARIOS)
        elif t in known:
            names.append(t)
        elif os.path.isdir(t):
            found = [
                os.path.splitext(f)[0]
                for f in sorted(os.listdir(t))
                if os.path.splitext(f)[0] in known and f.endswith(".py")
            ]
            if found:
                names.extend(found)
            else:
                unmatched.append(t)
        elif stem in known:
            names.append(stem)
        else:
            unmatched.append(t)
    # de-dup, keep order
    seen: set[str] = set()
    names = [n for n in names if not (n in seen or seen.add(n))]
    return names, unmatched


def run(args: argparse.Namespace) -> int:
    if args.host_devices and "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.host_devices}"
            )

    from ..analysis import Severity, registered_rules

    if args.rules:
        for spec in registered_rules():
            print(f"{spec.rule_id} [{spec.severity}] ({spec.family}) {spec.summary}")
            if spec.fix_hint:
                print(f"    fix: {spec.fix_hint}")
        return 0
    if args.list:
        for name, builder in SCENARIOS.items():
            print(f"{name}: {builder.__doc__.splitlines()[0]}")
        for name, builder in MULTIHOST_SCENARIOS.items():
            print(f"{name} [multihost]: {builder.__doc__.splitlines()[0]}")
        return 0

    procs = int(args.multihost or 0)
    names, unmatched = resolve_targets(args.targets, multihost=procs >= 2)
    if unmatched:
        print(
            f"lint: no scenario registered for {unmatched} "
            f"(known: {', '.join(list(SCENARIOS) + list(MULTIHOST_SCENARIOS))}); "
            "register one in accelerate_tpu/commands/lint.py:SCENARIOS",
            file=sys.stderr,
        )
        return 2

    gate = Severity.parse(args.severity)
    show = Severity.parse(args.show)
    failed = False
    json_reports = []
    measured_series: dict[str, Any] = {}
    scenario_kw: dict[str, Any] = {}
    if getattr(args, "chip", None):
        scenario_kw["roofline_chip"] = args.chip
    for name in names:
        if name in MULTIHOST_SCENARIOS:
            desc, report = MULTIHOST_SCENARIOS[name](processes=max(procs, 2))
        elif procs >= 2:
            desc, report = SCENARIOS[name](processes=procs, **scenario_kw)
        else:
            desc, report = SCENARIOS[name](**scenario_kw)
        if args.budgets or args.write_budgets:
            from ..analysis import perf_budget

            measured_series[name] = perf_budget.extract_series(report)
        if report.filter(gate):
            failed = True
        if args.json_lines:
            for finding in report.filter(show):
                d = finding.to_dict()
                d["scenario"] = name
                d["target"] = report.target or name
                print(json.dumps(d, sort_keys=True))
        elif args.fmt == "json":
            d = report.to_dict()
            d["scenario"] = name
            d["description"] = desc
            json_reports.append(d)
        else:
            print(f"== {report.target or name} — {desc}")
            print(f"   {report.format(show)}".replace("\n", "\n   "))
    budget_failed = False
    if args.budgets:
        from ..analysis import perf_budget

        problems = perf_budget.check_budgets(
            perf_budget.load_budgets(args.budgets), measured_series
        )
        for problem in problems:
            print(f"lint budget: {problem}", file=sys.stderr)
        if problems:
            budget_failed = True
        else:
            print(
                f"lint budget: ratchet holds for "
                f"{len(perf_budget.load_budgets(args.budgets))} scenario(s)"
            )
    if args.write_budgets:
        from ..analysis import perf_budget

        series = {k: v for k, v in measured_series.items() if v}
        perf_budget.write_budgets(args.write_budgets, series)
        print(
            f"lint budget: wrote {args.write_budgets} "
            f"({len(series)} scenario(s))"
        )
    if args.json_lines:
        pass  # JSON-lines streams findings only; exit code carries the gate
    elif args.fmt == "json":
        print(json.dumps({"reports": json_reports}, indent=2))
    elif failed:
        print(f"\nlint: findings at/above severity '{gate}' — failing")
    else:
        print(f"\nlint: no findings at/above severity '{gate}'")
    return 1 if failed or budget_failed else 0

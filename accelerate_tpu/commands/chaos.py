"""`accelerate-tpu chaos` — seeded chaos campaign over the serving fleet
and the checkpoint-replication path (`resilience/chaos.py`,
docs/fault_tolerance.md "Chaos campaigns").

Every episode's fault schedule derives from ``--seed`` alone, so a
failing campaign is replayed exactly by re-running with the seed it
printed; ``--report`` captures one JSON line per episode for triage."""

from __future__ import annotations

import argparse
import json


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser(
        "chaos",
        help="Run a seeded fault-injection campaign (serving + replication)",
    )
    p.add_argument(
        "--episodes", type=int, default=20,
        help="Inline episodes to run (default 20)",
    )
    p.add_argument(
        "--seed", type=int, default=None,
        help="Campaign seed (default: ATX_FAULT_SEED, else 0); the whole "
        "fault assignment replays from it",
    )
    p.add_argument(
        "--kinds", default="router,engine,replication",
        help="Comma-separated episode subsystems to rotate through "
        "(router, engine, replication)",
    )
    p.add_argument(
        "--report", default=None, metavar="PATH",
        help="Write a JSON-lines per-episode report here",
    )
    p.add_argument(
        "--subprocess-episodes", action="store_true", default=True,
        help="Append the kill-137 and SIGTERM-drain-75 subprocess episodes "
        "(default on)",
    )
    p.add_argument(
        "--no-subprocess-episodes", dest="subprocess_episodes",
        action="store_false",
        help="Inline episodes only (faster; no worker processes)",
    )
    p.set_defaults(func=run)


def run(args: argparse.Namespace) -> int:
    from ..resilience import chaos

    summary = chaos.run_campaign(
        episodes=args.episodes,
        seed=args.seed,
        kinds=tuple(k.strip() for k in args.kinds.split(",") if k.strip()),
        report_path=args.report,
        subprocess_episodes=args.subprocess_episodes,
    )
    print(json.dumps(summary, sort_keys=True))
    return 0 if summary["ok"] else 1

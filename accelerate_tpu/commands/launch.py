"""`accelerate-tpu launch` — spawn training processes with the env contract.

Analog of the reference launcher (`commands/launch.py:142-1194`). Key shift
(SURVEY.md §7): one process **per host**, not per device — JAX SPMD drives all
local chips from a single process, so the reference's elastic-agent / 1-proc-
per-GPU machinery collapses into three modes:

- single host: exec the script in-place with the ``ATX_*`` env contract;
- local multi-process (CPU simulation & single-host multi-proc testing):
  spawn N children with ``ATX_COORDINATOR_ADDRESS/ATX_NUM_PROCESSES/
  ATX_PROCESS_ID`` — the `jax.distributed.initialize` rendezvous analog of
  MASTER_ADDR/RANK/WORLD_SIZE (`utils/launch.py:98-470`);
- TPU pod: run the same command on every pod worker over
  ``gcloud compute tpus tpu-vm ssh --worker=all`` (reference
  `tpu_pod_launcher`, `commands/launch.py:909-965`), where each worker
  self-discovers rank via TPU metadata.

Env contract consumed by the library (`state.py`, `utils/dataclasses.py`):
ATX_COORDINATOR_ADDRESS, ATX_NUM_PROCESSES, ATX_PROCESS_ID, ATX_MULTIHOST,
ATX_MIXED_PRECISION, ATX_SHARDING_STRATEGY, ATX_MESH_{DATA,FSDP,TENSOR,
SEQUENCE,EXPERT}, ATX_GRADIENT_ACCUMULATION_STEPS.
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import subprocess
import sys
import time

from .config import LaunchConfig, load_default_config


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser(
        "launch", help="Launch a training script on this host / a pod"
    )
    p.add_argument("--config_file", default=None, help="Launch config file")
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--coordinator_address", default=None, help="host:port of process 0")
    p.add_argument("--coordinator_port", type=int, default=None)
    p.add_argument("--mixed_precision", default=None, choices=["no", "bf16", "fp16", "fp8"])
    p.add_argument(
        "--strategy",
        default=None,
        help="DATA_PARALLEL | ZERO1 | ZERO2 | FSDP | TENSOR_PARALLEL | HYBRID",
    )
    p.add_argument("--data", type=int, default=None, help="mesh data axis size")
    p.add_argument("--fsdp", type=int, default=None, help="mesh fsdp axis size")
    p.add_argument("--tensor", type=int, default=None, help="mesh tensor axis size")
    p.add_argument("--sequence", type=int, default=None, help="mesh sequence axis size")
    p.add_argument("--expert", type=int, default=None, help="mesh expert axis size")
    p.add_argument("--gradient_accumulation_steps", type=int, default=None)
    p.add_argument("--tpu_name", default=None, help="GCE TPU name (pod launch)")
    p.add_argument("--tpu_zone", default=None)
    p.add_argument("--tpu_project", default=None)
    p.add_argument(
        "--host_devices",
        type=int,
        default=None,
        help="Simulate N CPU devices per process (sets "
        "--xla_force_host_platform_device_count; testing without TPUs)",
    )
    p.add_argument("--dry_run", action="store_true", help="Print commands, don't run")
    p.add_argument("script", help="Training script to run")
    p.add_argument("script_args", nargs=argparse.REMAINDER, help="Script arguments")
    p.set_defaults(func=run)


def _merge_config(args: argparse.Namespace) -> LaunchConfig:
    """CLI > config file > defaults (reference `_validate_launch_command`,
    `commands/launch.py:988-1167`)."""
    if args.config_file:
        cfg = LaunchConfig.load(args.config_file)
    else:
        cfg = load_default_config() or LaunchConfig()
    overrides = {
        "num_processes": args.num_processes,
        "coordinator_address": args.coordinator_address,
        "coordinator_port": args.coordinator_port,
        "mixed_precision": args.mixed_precision,
        "sharding_strategy": args.strategy,
        "mesh_data": args.data,
        "mesh_fsdp": args.fsdp,
        "mesh_tensor": args.tensor,
        "mesh_sequence": args.sequence,
        "mesh_expert": args.expert,
        "gradient_accumulation_steps": args.gradient_accumulation_steps,
        "tpu_name": args.tpu_name,
        "tpu_zone": args.tpu_zone,
        "tpu_project": args.tpu_project,
    }
    for key, value in overrides.items():
        if value is not None:
            setattr(cfg, key, value)
    return cfg


def build_child_env(
    cfg: LaunchConfig,
    process_id: int | None = None,
    *,
    base: dict[str, str] | None = None,
    host_devices: int | None = None,
) -> dict[str, str]:
    """The env contract a child process configures itself from."""
    env = dict(os.environ if base is None else base)
    env["ATX_MIXED_PRECISION"] = cfg.mixed_precision
    env["ATX_SHARDING_STRATEGY"] = cfg.sharding_strategy
    env["ATX_MESH_DATA"] = str(cfg.mesh_data)
    env["ATX_MESH_FSDP"] = str(cfg.mesh_fsdp)
    env["ATX_MESH_TENSOR"] = str(cfg.mesh_tensor)
    env["ATX_MESH_SEQUENCE"] = str(cfg.mesh_sequence)
    env["ATX_MESH_EXPERT"] = str(cfg.mesh_expert)
    env["ATX_GRADIENT_ACCUMULATION_STEPS"] = str(cfg.gradient_accumulation_steps)
    if cfg.num_processes > 1:
        env["ATX_NUM_PROCESSES"] = str(cfg.num_processes)
        if process_id is not None:
            env["ATX_PROCESS_ID"] = str(process_id)
        if cfg.coordinator_address:
            env["ATX_COORDINATOR_ADDRESS"] = cfg.coordinator_address
        else:
            env["ATX_MULTIHOST"] = "1"  # TPU metadata autodetect
    if host_devices:
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={host_devices}".strip()
        )
        env["JAX_PLATFORMS"] = "cpu"
    env.update(cfg.extra_env)
    return env


def _local_multiprocess_launch(cfg: LaunchConfig, cmd: list[str], args) -> int:
    """Spawn num_processes children on this machine (rendezvous over
    localhost) — the CPU-simulation / single-host-multi-proc path that the
    reference covers with its gloo `debug_launcher` (`launchers.py:268`)."""
    if not cfg.coordinator_address:
        cfg.coordinator_address = f"127.0.0.1:{cfg.coordinator_port}"
    procs: list[subprocess.Popen] = []
    if args.dry_run:
        for i in range(cfg.num_processes):
            print(f"[proc {i}] {' '.join(shlex.quote(c) for c in cmd)}")
        return 0
    try:
        for i in range(cfg.num_processes):
            env = build_child_env(cfg, i, host_devices=args.host_devices)
            procs.append(subprocess.Popen(cmd, env=env))
        exit_code = 0
        while procs:
            for p in list(procs):
                ret = p.poll()
                if ret is None:
                    continue
                procs.remove(p)
                if ret != 0:
                    exit_code = ret
                    # One worker died: tear the job down (the reference relies
                    # on torch-elastic for this; here the launcher owns it).
                    for q in procs:
                        q.send_signal(signal.SIGTERM)
            if procs:
                time.sleep(0.2)
        return exit_code
    finally:
        for p in procs:
            p.kill()


def build_tpu_ssh_command(
    tpu_name: str, tpu_zone: str, tpu_project: str | None, remote: str
) -> list[str]:
    """`gcloud compute tpus tpu-vm ssh --worker=all` invocation shared by
    `launch` (pod training) and `tpu-config` (pod setup)."""
    gcloud = [
        "gcloud",
        "compute",
        "tpus",
        "tpu-vm",
        "ssh",
        tpu_name,
        f"--zone={tpu_zone}",
        "--worker=all",
        f"--command={remote}",
    ]
    if tpu_project:
        gcloud.insert(5, f"--project={tpu_project}")
    return gcloud


def _tpu_pod_launch(cfg: LaunchConfig, cmd: list[str], args) -> int:
    """Run the training command on every pod worker via gcloud SSH
    (reference `tpu_pod_launcher`, `commands/launch.py:909`)."""
    env_exports = " ".join(
        f"{k}={shlex.quote(v)}"
        for k, v in build_child_env(cfg, None, base={}).items()
    )
    remote = f"{env_exports} {' '.join(shlex.quote(c) for c in cmd)}"
    gcloud = build_tpu_ssh_command(cfg.tpu_name, cfg.tpu_zone, cfg.tpu_project, remote)
    if args.dry_run:
        print(" ".join(shlex.quote(c) for c in gcloud))
        return 0
    return subprocess.call(gcloud)


def run(args: argparse.Namespace) -> int:
    cfg = _merge_config(args)
    cmd = [sys.executable, args.script, *args.script_args]

    if cfg.tpu_name:
        return _tpu_pod_launch(cfg, cmd, args)
    if cfg.num_processes > 1:
        return _local_multiprocess_launch(cfg, cmd, args)
    # Single host process: exec in place with the env contract.
    env = build_child_env(cfg, None, host_devices=args.host_devices)
    if args.dry_run:
        print(" ".join(shlex.quote(c) for c in cmd))
        return 0
    os.environ.update(env)
    os.execvpe(cmd[0], cmd, os.environ)
    return 0  # pragma: no cover - execvpe does not return

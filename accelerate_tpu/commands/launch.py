"""`accelerate-tpu launch` — spawn training processes with the env contract.

Analog of the reference launcher (`commands/launch.py:142-1194`). Key shift
(SURVEY.md §7): one process **per host**, not per device — JAX SPMD drives all
local chips from a single process, so the reference's elastic-agent / 1-proc-
per-GPU machinery collapses into three modes:

- single host: exec the script in-place with the ``ATX_*`` env contract;
- local multi-process (CPU simulation & single-host multi-proc testing):
  spawn N children with ``ATX_COORDINATOR_ADDRESS/ATX_NUM_PROCESSES/
  ATX_PROCESS_ID`` — the `jax.distributed.initialize` rendezvous analog of
  MASTER_ADDR/RANK/WORLD_SIZE (`utils/launch.py:98-470`);
- TPU pod: run the same command on every pod worker over
  ``gcloud compute tpus tpu-vm ssh --worker=all`` (reference
  `tpu_pod_launcher`, `commands/launch.py:909-965`), where each worker
  self-discovers rank via TPU metadata.

Env contract consumed by the library (`state.py`, `utils/dataclasses.py`):
ATX_COORDINATOR_ADDRESS, ATX_NUM_PROCESSES, ATX_PROCESS_ID, ATX_MULTIHOST,
ATX_MIXED_PRECISION, ATX_SHARDING_STRATEGY, ATX_MESH_{DATA,FSDP,TENSOR,
SEQUENCE,EXPERT}, ATX_GRADIENT_ACCUMULATION_STEPS.
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import subprocess
import sys
import time

from ..resilience.preemption import PREEMPTION_EXIT_CODE
from .config import LaunchConfig, load_default_config


def _term_grace_secs() -> float:
    """How long group teardown waits between SIGTERM and SIGKILL. Children
    trap SIGTERM for emergency checkpoints (resilience/preemption.py), so a
    teardown TERM no longer guarantees death — the grace window lets the
    emergency save commit before escalation."""
    try:
        return float(os.environ.get("ATX_TERM_GRACE_SECS", "") or 30.0)
    except ValueError:
        return 30.0


def _max_preemption_resumes() -> int:
    try:
        return int(os.environ.get("ATX_MAX_PREEMPTION_RESUMES", "") or 100)
    except ValueError:
        return 100


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser(
        "launch", help="Launch a training script on this host / a pod"
    )
    p.add_argument("--config_file", default=None, help="Launch config file")
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--coordinator_address", default=None, help="host:port of process 0")
    p.add_argument("--coordinator_port", type=int, default=None)
    p.add_argument("--mixed_precision", default=None, choices=["no", "bf16", "fp16", "fp8"])
    p.add_argument(
        "--force_fp8",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="Run fp8 even on device kinds whose recorded fp8 matmul "
        "speedup is <= 1x (where fp8 costs accuracy for zero gain)",
    )
    p.add_argument(
        "--strategy",
        default=None,
        help="DATA_PARALLEL | ZERO1 | ZERO2 | FSDP | TENSOR_PARALLEL | HYBRID",
    )
    p.add_argument("--data", type=int, default=None, help="mesh data axis size")
    p.add_argument("--fsdp", type=int, default=None, help="mesh fsdp axis size")
    p.add_argument("--tensor", type=int, default=None, help="mesh tensor axis size")
    p.add_argument("--sequence", type=int, default=None, help="mesh sequence axis size")
    p.add_argument("--expert", type=int, default=None, help="mesh expert axis size")
    p.add_argument("--gradient_accumulation_steps", type=int, default=None)
    p.add_argument(
        "--offload_optimizer",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="Keep optimizer moments in pinned host RAM "
        "(parallel/host_offload.py; the DeepSpeed offload_optimizer "
        "analog); --no-offload_optimizer overrides a config-file true",
    )
    p.add_argument(
        "--log_with",
        default=None,
        help="Comma-separated experiment trackers "
        "(json/tensorboard/wandb/mlflow/comet_ml/aim/clearml/dvclive)",
    )
    p.add_argument(
        "--project_dir", default=None, help="Project/logging directory for trackers"
    )
    p.add_argument("--tpu_name", default=None, help="GCE TPU name (pod launch)")
    p.add_argument("--tpu_zone", default=None)
    p.add_argument("--tpu_project", default=None)
    p.add_argument(
        "--host_devices",
        type=int,
        default=None,
        help="Simulate N CPU devices per process (sets "
        "--xla_force_host_platform_device_count; testing without TPUs)",
    )
    p.add_argument(
        "--max_restarts",
        type=int,
        default=None,
        help="Relaunch the worker group (fresh coordinator port) up to N "
        "times after a worker death (torch-elastic max_restarts analog); "
        "default 0 = fail on first death",
    )
    p.add_argument(
        "--replicate_url",
        default=None,
        help="Object-store URL for durable checkpoint replication "
        "(sets ATX_REPLICATE_URL in every worker: file:///path or a plain "
        "path for the filesystem store, other schemes via "
        "resilience.replicate.register_store_scheme — "
        "docs/fault_tolerance.md)",
    )
    p.add_argument(
        "--elastic_devices_file",
        default=None,
        help="Path to a file holding 'H' (the --host_devices value) or "
        "'P H' (num_processes and host_devices) for each worker-group "
        "(re)start. Re-read before every group launch, so an elastic "
        "restart (preemption exit-75, health escalation) can come back at "
        "a SMALLER topology and the workers reshard their checkpoint on "
        "restore. The path is also exported as ATX_ELASTIC_DEVICES_FILE so "
        "a running group with ATX_ELASTIC_SHRINK=1 can watch it and "
        "shrink/grow IN PLACE without a relaunch "
        "(docs/fault_tolerance.md, shrink/grow in place)",
    )
    p.add_argument("--dry_run", action="store_true", help="Print commands, don't run")
    p.add_argument("script", help="Training script to run")
    p.add_argument("script_args", nargs=argparse.REMAINDER, help="Script arguments")
    p.set_defaults(func=run)


def _merge_config(args: argparse.Namespace) -> LaunchConfig:
    """CLI > config file > defaults (reference `_validate_launch_command`,
    `commands/launch.py:988-1167`)."""
    if args.config_file:
        cfg = LaunchConfig.load(args.config_file)
    else:
        cfg = load_default_config() or LaunchConfig()
    overrides = {
        "num_processes": args.num_processes,
        "coordinator_address": args.coordinator_address,
        "coordinator_port": args.coordinator_port,
        "mixed_precision": args.mixed_precision,
        "sharding_strategy": args.strategy,
        "mesh_data": args.data,
        "mesh_fsdp": args.fsdp,
        "mesh_tensor": args.tensor,
        "mesh_sequence": args.sequence,
        "mesh_expert": args.expert,
        "gradient_accumulation_steps": args.gradient_accumulation_steps,
        "offload_optimizer": args.offload_optimizer,
        "force_fp8": getattr(args, "force_fp8", None),
        "log_with": args.log_with,
        "project_dir": args.project_dir,
        "tpu_name": args.tpu_name,
        "tpu_zone": args.tpu_zone,
        "tpu_project": args.tpu_project,
        "max_restarts": args.max_restarts,
    }
    for key, value in overrides.items():
        if value is not None:
            setattr(cfg, key, value)
    if getattr(args, "replicate_url", None):
        # Replication is plain env contract (workers read ATX_REPLICATE_URL
        # in Accelerator.__init__); extra_env is applied last in
        # build_child_env so the flag also wins over a config-file value.
        cfg.extra_env = {**cfg.extra_env, "ATX_REPLICATE_URL": args.replicate_url}
    if getattr(args, "elastic_devices_file", None):
        # Exported so workers running with ATX_ELASTIC_SHRINK=1 can watch
        # the same file and resize IN PLACE; the launcher keeps re-reading
        # it per group (re)start as the relaunch fallback.
        cfg.extra_env = {
            **cfg.extra_env,
            "ATX_ELASTIC_DEVICES_FILE": args.elastic_devices_file,
        }
    return cfg


def build_child_env(
    cfg: LaunchConfig,
    process_id: int | None = None,
    *,
    base: dict[str, str] | None = None,
    host_devices: int | None = None,
) -> dict[str, str]:
    """The env contract a child process configures itself from."""
    env = dict(os.environ if base is None else base)
    env["ATX_MIXED_PRECISION"] = cfg.mixed_precision
    env["ATX_SHARDING_STRATEGY"] = cfg.sharding_strategy
    env["ATX_MESH_DATA"] = str(cfg.mesh_data)
    env["ATX_MESH_FSDP"] = str(cfg.mesh_fsdp)
    env["ATX_MESH_TENSOR"] = str(cfg.mesh_tensor)
    env["ATX_MESH_SEQUENCE"] = str(cfg.mesh_sequence)
    env["ATX_MESH_EXPERT"] = str(cfg.mesh_expert)
    env["ATX_GRADIENT_ACCUMULATION_STEPS"] = str(cfg.gradient_accumulation_steps)
    if cfg.offload_optimizer:
        env["ATX_OFFLOAD_OPTIMIZER"] = "1"
    if cfg.log_with:
        env["ATX_LOG_WITH"] = cfg.log_with
    if cfg.project_dir:
        env["ATX_PROJECT_DIR"] = cfg.project_dir
    if cfg.num_processes > 1:
        env["ATX_NUM_PROCESSES"] = str(cfg.num_processes)
        if process_id is not None:
            env["ATX_PROCESS_ID"] = str(process_id)
        if cfg.coordinator_address:
            env["ATX_COORDINATOR_ADDRESS"] = cfg.coordinator_address
        else:
            env["ATX_MULTIHOST"] = "1"  # TPU metadata autodetect
    if host_devices:
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={host_devices}".strip()
        )
        env["JAX_PLATFORMS"] = "cpu"
    env.update(cfg.extra_env)
    return env


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _port_stolen(port: int) -> bool:
    """After a group death: is the rendezvous port held by ANOTHER process?
    Our own (dead) coordinator leaves at most a TIME_WAIT entry, which
    SO_REUSEADDR binds through — so a failed bind here means someone else
    grabbed the port between the `_free_port` probe and the coordinator's
    bind, i.e. the failure was the launcher's race, not the workload's."""
    import socket

    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind(("127.0.0.1", port))
            return False
        except OSError:
            return True


def _run_worker_group(cfg: LaunchConfig, cmd: list[str], args) -> int:
    """Spawn one group of num_processes children and babysit it: first
    worker death tears the whole group down (the reference relies on
    torch-elastic for this; here the launcher owns it)."""
    procs: list[subprocess.Popen] = []
    try:
        for i in range(cfg.num_processes):
            env = build_child_env(cfg, i, host_devices=args.host_devices)
            procs.append(subprocess.Popen(cmd, env=env))
        exit_code = 0
        term_deadline = None
        while procs:
            for p in list(procs):
                ret = p.poll()
                if ret is None:
                    continue
                procs.remove(p)
                if ret != 0 and exit_code == 0:
                    # Keep the FIRST failure's code: the peers reaped after
                    # the teardown die with -SIGTERM, which would mask the
                    # root cause in the restart log and the final status.
                    # (A preempted worker's PREEMPTION_EXIT_CODE survives
                    # the same way — its SIGTERMed peers write their own
                    # emergency checkpoints and exit with the same code.)
                    exit_code = ret
                    for q in procs:
                        q.send_signal(signal.SIGTERM)
                    term_deadline = time.time() + _term_grace_secs()
            if procs:
                if term_deadline is not None and time.time() > term_deadline:
                    # Peers trapped the TERM (emergency save wedged, or a
                    # hung collective): escalate so the group actually dies
                    # and the restart policy can run.
                    for q in procs:
                        q.kill()
                    term_deadline = None
                time.sleep(0.2)
        return exit_code
    finally:
        for p in procs:
            p.kill()


def _apply_elastic_devices(args, cfg=None) -> None:
    """Re-read ``--elastic_devices_file`` (when given) before a worker-group
    (re)start: the file holds either ``H`` (the ``--host_devices`` value) or
    ``P H`` (num_processes and host_devices) for the NEXT group, so an
    external controller (or a test) can shrink the simulated topology
    between an emergency exit and the elastic resume. Unreadable /
    non-integer content keeps the previous values — a live elastic loop must
    not die on a torn write."""
    path = getattr(args, "elastic_devices_file", None)
    if not path:
        return
    try:
        with open(path) as f:
            fields = [int(tok) for tok in f.read().split()]
        if len(fields) == 1:
            processes, devices = None, fields[0]
        elif len(fields) == 2:
            processes, devices = fields
        else:
            raise ValueError(f"expected 'H' or 'P H', got {len(fields)} fields")
    except (OSError, ValueError) as e:
        print(
            f"[accelerate-tpu launch] could not read --elastic_devices_file "
            f"{path!r} ({e}); keeping host_devices={args.host_devices}",
            file=sys.stderr,
            flush=True,
        )
        return
    if devices > 0 and devices != args.host_devices:
        print(
            f"[accelerate-tpu launch] elastic devices file: next worker "
            f"group starts with host_devices={devices} "
            f"(was {args.host_devices})",
            file=sys.stderr,
            flush=True,
        )
        args.host_devices = devices
    if (
        cfg is not None
        and processes is not None
        and processes > 0
        and processes != cfg.num_processes
    ):
        print(
            f"[accelerate-tpu launch] elastic devices file: next worker "
            f"group starts with num_processes={processes} "
            f"(was {cfg.num_processes})",
            file=sys.stderr,
            flush=True,
        )
        cfg.num_processes = processes


def _local_multiprocess_launch(cfg: LaunchConfig, cmd: list[str], args) -> int:
    """Spawn num_processes children on this machine (rendezvous over
    localhost) — the CPU-simulation / single-host-multi-proc path that the
    reference covers with its gloo `debug_launcher` (`launchers.py:268`).

    With ``max_restarts > 0``, a dead worker group is relaunched whole, on a
    FRESH coordinator port (the old rendezvous may linger in TIME_WAIT /
    stale `jax.distributed` state), up to the limit — the torch-elastic
    restart policy the reference forwards (`commands/launch.py:142-771`).
    Restarted scripts resume from their own checkpoints exactly as they
    would under torch-elastic.
    """
    if args.dry_run:
        for i in range(cfg.num_processes):
            print(f"[proc {i}] {' '.join(shlex.quote(c) for c in cmd)}")
        return 0
    pinned_address = cfg.coordinator_address  # user-supplied: reuse as-is
    exit_code = 0
    # _free_port probes by bind-and-close, so another process can steal the
    # port in the window before the coordinator binds it. Such a failure is
    # the launcher's fault, not the workload's: retry the same attempt on a
    # fresh port (bounded) instead of burning the user's max_restarts budget.
    rendezvous_retries = 3
    first_group = True
    attempt = 0
    preemption_resumes = 0
    while attempt <= cfg.max_restarts:
        if pinned_address:
            cfg.coordinator_address = pinned_address
        elif first_group:
            cfg.coordinator_address = f"127.0.0.1:{cfg.coordinator_port}"
        else:
            cfg.coordinator_address = f"127.0.0.1:{_free_port()}"
        first_group = False
        _apply_elastic_devices(args, cfg)
        exit_code = _run_worker_group(cfg, cmd, args)
        if exit_code == 0:
            return 0
        if (
            exit_code == PREEMPTION_EXIT_CODE
            and preemption_resumes < _max_preemption_resumes()
        ):
            # Exit-code contract (resilience/preemption.py): the group was
            # preempted AFTER committing an emergency checkpoint — this is
            # not a failure, so resume immediately on a fresh port without
            # consuming a --max_restarts attempt. Bounded by
            # ATX_MAX_PREEMPTION_RESUMES against a pathological script that
            # always exits preempted.
            preemption_resumes += 1
            print(
                "[accelerate-tpu launch] worker group preempted (exit "
                f"{PREEMPTION_EXIT_CODE}, emergency checkpoint committed); "
                f"resuming immediately (resume {preemption_resumes}, not "
                "counted against --max_restarts)",
                file=sys.stderr,
                flush=True,
            )
            continue
        # Only launcher-chosen addresses are "127.0.0.1:<port>"; a pinned
        # address may have no numeric port, so parse under the guard.
        if not pinned_address and rendezvous_retries > 0 and _port_stolen(
            chosen_port := int(cfg.coordinator_address.rsplit(":", 1)[1])
        ):
            rendezvous_retries -= 1
            print(
                "[accelerate-tpu launch] rendezvous port "
                f"{chosen_port} was taken by another process; retrying on a "
                "fresh port (not counted against --max_restarts)",
                file=sys.stderr,
                flush=True,
            )
            continue
        if attempt < cfg.max_restarts:
            print(
                f"[accelerate-tpu launch] worker group failed (exit "
                f"{exit_code}); restarting group "
                f"({attempt + 1}/{cfg.max_restarts})",
                file=sys.stderr,
                flush=True,
            )
        attempt += 1
    return exit_code


def build_tpu_ssh_command(
    tpu_name: str, tpu_zone: str, tpu_project: str | None, remote: str
) -> list[str]:
    """`gcloud compute tpus tpu-vm ssh --worker=all` invocation shared by
    `launch` (pod training) and `tpu-config` (pod setup)."""
    gcloud = [
        "gcloud",
        "compute",
        "tpus",
        "tpu-vm",
        "ssh",
        tpu_name,
        f"--zone={tpu_zone}",
        "--worker=all",
        f"--command={remote}",
    ]
    if tpu_project:
        gcloud.insert(5, f"--project={tpu_project}")
    return gcloud


def _tpu_pod_launch(cfg: LaunchConfig, cmd: list[str], args) -> int:
    """Run the training command on every pod worker via gcloud SSH
    (reference `tpu_pod_launcher`, `commands/launch.py:909`). A nonzero pod
    run is retried up to ``max_restarts`` times (same elastic policy as the
    local group path; the pod re-rendezvouses through TPU metadata, so no
    port rotation is needed).

    Exit-code caveat (docs/fault_tolerance.md §exit-code contract): the
    preemption fast-path below relies on ``gcloud ... ssh --worker=all``
    surfacing the remote training process's exit status, and with multiple
    workers gcloud's SSH fan-out does NOT reliably propagate a specific
    worker's code. A real pod preemption may therefore be classified as an
    ordinary failure and consume a ``--max_restarts`` attempt instead of
    taking the free-resume path. This is safe — the emergency checkpoint
    was committed before the workers exited, and the ordinary restart
    resumes from it via ``load_state(resume="latest")`` — but budget
    ``--max_restarts`` with headroom on preemptible pods. (The local
    worker-group path reaps each child directly and is not affected.)"""
    env_exports = " ".join(
        f"{k}={shlex.quote(v)}"
        for k, v in build_child_env(cfg, None, base={}).items()
    )
    remote = f"{env_exports} {' '.join(shlex.quote(c) for c in cmd)}"
    gcloud = build_tpu_ssh_command(cfg.tpu_name, cfg.tpu_zone, cfg.tpu_project, remote)
    if args.dry_run:
        print(" ".join(shlex.quote(c) for c in gcloud))
        return 0
    attempt = 0
    preemption_resumes = 0
    while True:
        exit_code = subprocess.call(gcloud)
        if exit_code == 0:
            return 0
        if (
            exit_code == PREEMPTION_EXIT_CODE
            and preemption_resumes < _max_preemption_resumes()
        ):
            # Same exit-code contract as the local group path: a preempted
            # pod committed its emergency checkpoint, so the re-run is a
            # resume, not a burned restart attempt.
            preemption_resumes += 1
            print(
                "[accelerate-tpu launch] pod run preempted (exit "
                f"{PREEMPTION_EXIT_CODE}); resuming immediately (resume "
                f"{preemption_resumes}, not counted against --max_restarts)",
                file=sys.stderr,
                flush=True,
            )
            continue
        if attempt >= cfg.max_restarts:
            return exit_code
        print(
            f"[accelerate-tpu launch] pod run failed (exit {exit_code}); "
            f"restarting ({attempt + 1}/{cfg.max_restarts})",
            file=sys.stderr,
            flush=True,
        )
        attempt += 1


def _fp8_speedup_for_local_devices() -> float | None:
    """Recorded fp8 speedup for the local device kind; None when unknown or
    when devices can't be queried (e.g. pod SSH launch — the remote kind is
    unknown here, so the gate stays permissive).

    The device kind is probed in a SUBPROCESS: importing jax here would
    initialize libtpu in the launcher process and hold the chips, so every
    spawned worker would then fail with 'TPU already in use'. The probe
    process exits (releasing the devices) before any worker starts."""
    from ..utils import fp8_telemetry

    kind = _probe_device_kind()
    if not kind:
        return None
    return fp8_telemetry.lookup(kind)


def _probe_device_kind() -> str | None:
    import subprocess

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].device_kind)"],
            capture_output=True, text=True, timeout=120,
        )
        lines = out.stdout.strip().splitlines()
        return lines[-1] if out.returncode == 0 and lines else None
    except Exception:
        return None


def run(args: argparse.Namespace) -> int:
    cfg = _merge_config(args)
    cmd = [sys.executable, args.script, *args.script_args]
    if cfg.mixed_precision == "fp8":
        print(
            "[accelerate-tpu launch] fp8 selected: only beneficial on chips "
            "with native fp8 MXU support; elsewhere XLA upcasts the values — "
            "quantization error with no speedup (see bench.py "
            "fp8_matmul_speedup).",
            file=sys.stderr,
        )
        speedup = _fp8_speedup_for_local_devices()
        if speedup is not None and speedup <= 1.0 and not cfg.force_fp8:
            print(
                "[accelerate-tpu launch] refusing --mixed_precision fp8: "
                f"measured fp8 matmul speedup on this device kind is "
                f"{speedup:.2f}x (<= 1) — you would pay fp8 quantization "
                "error for a slowdown. Pass --force_fp8 to override.",
                file=sys.stderr,
            )
            return 2

    if cfg.tpu_name:
        return _tpu_pod_launch(cfg, cmd, args)
    if cfg.num_processes > 1:
        return _local_multiprocess_launch(cfg, cmd, args)
    # Single host process: exec in place with the env contract.
    if cfg.max_restarts:
        print(
            "[accelerate-tpu launch] --max_restarts applies to worker groups "
            "(num_processes > 1 or pod launches); a single exec'd process is "
            "not restarted.",
            file=sys.stderr,
        )
    _apply_elastic_devices(args, cfg)
    env = build_child_env(cfg, None, host_devices=args.host_devices)
    if args.dry_run:
        print(" ".join(shlex.quote(c) for c in cmd))
        return 0
    os.environ.update(env)
    os.execvpe(cmd[0], cmd, os.environ)
    return 0  # pragma: no cover - execvpe does not return

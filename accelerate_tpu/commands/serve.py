"""`accelerate-tpu serve` / `atx serve` — continuous-batching micro-server.

A benchmarking driver for `serving.Engine` (docs/serving.md): builds a
model-zoo preset with random weights (or loads a local HF repo), replays a
Poisson arrival trace of mixed-length requests through the engine, and
prints one JSON line of serving metrics (`serve_tokens_per_sec`,
`serve_p50_ms`, `serve_p99_ms`, occupancy) — the same fields bench.py's
serve phase reports, runnable standalone on any host:

    atx serve --model llama-tiny --slots 8 --requests 64 --rate 16

``--compare-b1`` additionally runs the same request set sequentially
through batch-1 `generate()` and reports the speedup (the ISSUE-3
acceptance bar is >= 3x on a real chip).

``--replicas N`` (N >= 2) serves the trace through the multi-replica
`serving.Router` instead — N identically configured engines behind
prefix-affinity routing, a bounded EDF/priority admission queue
(``--queue-depth``, ``--affinity``, ``--scheduling``), and optional
replica re-admission after quarantine (``--readmit-secs``); router
fleet metrics join the JSON line as
``serve_router_*`` keys, and a SIGTERM mid-trace drains gracefully and
exits 75 (the elastic-launcher resume contract — docs/serving.md).
"""

from __future__ import annotations

import argparse
import json
import time


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser(
        "serve",
        help="Continuous-batching serving benchmark (Poisson request trace)",
    )
    p.add_argument(
        "--model",
        default="llama-tiny",
        help="model preset (see `atx estimate --list`) or a local HF repo path",
    )
    p.add_argument("--slots", type=int, default=None, help="KV slot pool size (ATX_SERVE_SLOTS)")
    p.add_argument(
        "--buckets",
        default=None,
        help="comma-separated prefill bucket lengths (ATX_SERVE_BUCKETS)",
    )
    p.add_argument("--max-len", type=int, default=None, help="per-slot KV capacity")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--rate", type=float, default=16.0, help="Poisson arrivals/sec")
    p.add_argument("--prompt-lens", default="8:96", help="min:max prompt length")
    p.add_argument("--new-tokens", default="8:48", help="min:max tokens per request")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--do-sample", action="store_true")
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument(
        "--realtime",
        action="store_true",
        help="honour arrival times on the wall clock (latency mode); "
        "default replays the trace as fast as the engine drains it",
    )
    p.add_argument(
        "--prefix-cache",
        dest="prefix_cache",
        action="store_true",
        default=None,
        help="force the prefix cache ON (default: on unless "
        "ATX_SERVE_PREFIX_CACHE=0)",
    )
    p.add_argument(
        "--no-prefix-cache",
        dest="prefix_cache",
        action="store_false",
        help="disable the prefix cache",
    )
    p.add_argument(
        "--prefix-cache-mib",
        type=float,
        default=None,
        help="prefix-cache pool byte budget in MiB (ATX_SERVE_PREFIX_CACHE_MIB)",
    )
    p.add_argument(
        "--shared-prefix",
        type=int,
        default=0,
        metavar="LEN",
        help="give every request one of --shared-prefixes common system "
        "prompts of LEN tokens (the prefix-cache workload shape); "
        "prompt-lens then sizes only the unique tails",
    )
    p.add_argument(
        "--shared-prefixes",
        type=int,
        default=2,
        help="number of distinct shared system prompts (with --shared-prefix)",
    )
    p.add_argument(
        "--stop",
        default=None,
        metavar="IDS",
        help="comma-separated token ids used as one multi-token stop "
        "sequence on every request (host-side tail match)",
    )
    p.add_argument(
        "--compare-b1",
        action="store_true",
        help="also run the request set sequentially through batch-1 "
        "generate() and report the speedup",
    )
    p.add_argument(
        "--replicas",
        type=int,
        default=1,
        help="serve through the multi-replica Router with N engine "
        "replicas (1 = single engine, no router)",
    )
    p.add_argument(
        "--queue-depth",
        type=int,
        default=None,
        help="router admission-queue bound (ATX_SERVE_QUEUE_DEPTH; "
        "default 4x total fleet slots)",
    )
    p.add_argument(
        "--affinity",
        choices=("prefix", "least-loaded"),
        default="prefix",
        help="router placement policy: prefix-affinity steering with "
        "least-loaded fallback, or pure least-loaded",
    )
    p.add_argument(
        "--scheduling",
        choices=("edf", "fifo"),
        default="edf",
        help="router admission order: earliest-deadline-first over "
        "priority classes with load shedding (edf, default) or plain "
        "arrival order (fifo — the pre-self-healing behaviour)",
    )
    p.add_argument(
        "--readmit-secs",
        type=float,
        default=None,
        metavar="SECS",
        help="probe a quarantined replica after SECS (capped-exponential "
        "backoff) and re-admit it under probation once its canary replays "
        "bit-identically (ATX_SERVE_READMIT_SECS; default: off — a lost "
        "replica stays quarantined)",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="expose a Prometheus /metrics endpoint on PORT for the whole "
        "run (0 = pick a free port; the bound URL is printed to stderr). "
        "The endpoint stays up until the trace — and the router drain, "
        "with --replicas — has finished (docs/observability.md)",
    )
    p.set_defaults(func=run)


def _span(text: str) -> tuple[int, int]:
    lo, _, hi = text.partition(":")
    return int(lo), int(hi or lo)


def _build_model(name: str):
    """(apply_fn, init_cache_fn, params, vocab_size) for a preset or local
    HF repo. Presets initialize random bf16 weights — throughput is
    weight-agnostic."""
    import os

    import jax
    import jax.numpy as jnp

    if os.path.isdir(name):
        import accelerate_tpu as atx
        from accelerate_tpu.models import llama

        loaded = atx.load_pretrained(name, dtype=jnp.bfloat16)
        cfg = loaded.config
        return (
            lambda p, t, c: llama.forward_with_cache(p, t, c, cfg),
            lambda b, m: llama.init_cache(cfg, b, m),
            loaded.params,
            cfg.vocab_size,
        )
    from .estimate import _MODEL_PRESETS

    if name not in _MODEL_PRESETS:
        raise SystemExit(
            f"unknown model {name!r}; pick from `atx estimate --list` or "
            "pass a local HF repo path"
        )
    family_name, preset = _MODEL_PRESETS[name]
    import importlib

    family = importlib.import_module(f"accelerate_tpu.models.{family_name}")
    if not hasattr(family, "forward_with_cache"):
        raise SystemExit(
            f"{name} is a {family_name} model — no decode cache path; pick "
            "a decoder preset (llama-*, gpt*)"
        )
    config_cls = {"llama": "LlamaConfig", "gpt": "GPTConfig"}[family_name]
    cfg = getattr(getattr(family, config_cls), preset)()
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16),
        family.init(jax.random.PRNGKey(0), cfg),
    )
    return (
        lambda p, t, c: family.forward_with_cache(p, t, c, cfg),
        lambda b, m: family.init_cache(cfg, b, m),
        params,
        cfg.vocab_size,
    )


def run(args: argparse.Namespace) -> int:
    import numpy as np

    from ..generation import GenerationConfig, Generator
    from ..serving import Engine, poisson_trace, shared_prefix_trace

    apply_fn, init_cache_fn, params, vocab = _build_model(args.model)
    prompt_lens = _span(args.prompt_lens)
    new_tokens = _span(args.new_tokens)
    buckets = (
        tuple(int(b) for b in args.buckets.split(",")) if args.buckets else None
    )
    config = GenerationConfig(
        do_sample=args.do_sample, temperature=args.temperature
    )
    stop_sequences = (
        [tuple(int(t) for t in args.stop.split(","))] if args.stop else None
    )
    max_len = args.max_len
    if max_len is None:
        # Fit the worst-case request: prompt rounded up to a bucket + budget.
        from ..serving import default_buckets

        bs = buckets or default_buckets()
        longest = prompt_lens[1] + args.shared_prefix
        rounded = min((b for b in bs if b >= longest), default=None)
        top = rounded if rounded is not None else -(-longest // bs[-1]) * bs[-1]
        max_len = top + new_tokens[1]
    def mk_engine() -> Engine:
        return Engine(
            apply_fn,
            init_cache_fn,
            params,
            config,
            slots=args.slots,
            buckets=buckets,
            max_len=max_len,
            prefix_cache=args.prefix_cache,
            prefix_cache_mib=args.prefix_cache_mib,
        )

    router = None
    if args.replicas > 1:
        from .. import resilience
        from ..serving import Router

        # SIGTERM now means "drain, then exit 75" instead of dying mid-token.
        resilience.install_preemption_handler()
        engines = [mk_engine() for _ in range(args.replicas)]
        engine = engines[0]
        router = Router(
            engines,
            queue_depth=args.queue_depth,
            affinity=args.affinity,
            scheduling=args.scheduling,
            readmit_secs=args.readmit_secs,
            # A fatally wedged replica is rebuilt from scratch at probe
            # time rather than trusting mid-step engine state.
            engine_factory=mk_engine,
        )
    else:
        engine = mk_engine()
    # Startup capacity line: the static planner verdict for the replica-0
    # engine (atx estimate --serve gives the full table).
    import sys as _sys

    from ..analysis.capacity import plan_for_engine

    _cap_engine = router.replicas[0].engine if router is not None else engine
    try:
        print(
            f"[atx serve] {plan_for_engine(_cap_engine).format()}",
            file=_sys.stderr,
        )
    except Exception:
        pass  # planner is advisory; never block serving on it
    if args.shared_prefix > 0:
        trace = shared_prefix_trace(
            args.requests,
            args.rate,
            vocab_size=vocab,
            n_prefixes=args.shared_prefixes,
            prefix_len=args.shared_prefix,
            tail_lens=prompt_lens,
            new_tokens=new_tokens,
            seed=args.seed,
            stop_sequences=stop_sequences,
        )
    else:
        trace = poisson_trace(
            args.requests,
            args.rate,
            vocab_size=vocab,
            prompt_lens=prompt_lens,
            new_tokens=new_tokens,
            seed=args.seed,
            stop_sequences=stop_sequences,
        )
    metrics_server = None
    if args.metrics_port is not None:
        import sys

        from .. import telemetry

        metrics_server = telemetry.MetricsServer(port=args.metrics_port)
        print(
            f"[atx serve] /metrics listening on {metrics_server.url}",
            file=sys.stderr,
        )
    try:
        t0 = time.perf_counter()
        if router is not None:
            completions = router.serve(trace, realtime=args.realtime)
            router.close()
        else:
            completions = engine.serve(trace, realtime=args.realtime)
        wall = time.perf_counter() - t0

        total_new = sum(c.n_new for c in completions)
        # Latency stats over requests that actually finished (a drained or
        # deadline-cancelled request has no meaningful TTFT/e2e).
        finished = [
            c for c in completions if c.finish_reason not in ("cancelled", "failed")
        ] or completions
        lat_ms = sorted(1e3 * (c.finished_at - c.submitted_at) for c in finished)
        ttft_ms = sorted(1e3 * (c.first_token_at - c.submitted_at) for c in finished)
        pick = lambda xs, q: xs[min(len(xs) - 1, int(q * len(xs)))] if xs else 0.0
        result = {
            "serve_requests": len(completions),
            "serve_tokens_per_sec": round(total_new / max(wall, 1e-9), 1),
            "serve_wall_s": round(wall, 2),
            "serve_p50_ms": round(pick(lat_ms, 0.50), 1),
            "serve_p99_ms": round(pick(lat_ms, 0.99), 1),
            "serve_ttft_p50_ms": round(pick(ttft_ms, 0.50), 1),
            "serve_ttft_p99_ms": round(pick(ttft_ms, 0.99), 1),
            "serve_slots": engine.n_slots,
            "serve_buckets": list(engine.buckets),
            "serve_prefill_compiles": engine._prefill._cache_size(),
            "serve_decode_compiles": engine._decode._cache_size(),
            "serve_occupancy": round(
                engine.stats["decode_slot_steps"]
                / max(engine.stats["decode_steps"] * engine.n_slots, 1),
                3,
            ),
        }
        if router is None:
            # Single-engine runs report the registry histograms' estimates —
            # the SAME series `/metrics` exports, so a scrape and the JSON
            # line always agree (docs/observability.md).
            lat = engine.latency_summary()
            for out_key, reg_key in (
                ("serve_p50_ms", "p50_ms"),
                ("serve_p99_ms", "p99_ms"),
                ("serve_ttft_p50_ms", "ttft_p50_ms"),
                ("serve_ttft_p99_ms", "ttft_p99_ms"),
            ):
                if lat[reg_key] is not None:
                    result[out_key] = round(lat[reg_key], 1)
        for key, val in engine.prefix_metrics().items():
            result["serve_" + key] = val
        if args.compare_b1:
            gens: dict[int, Generator] = {}
            t0 = time.perf_counter()
            for r in trace:
                g = gens.setdefault(
                    r.max_new_tokens,
                    Generator(
                        apply_fn,
                        init_cache_fn,
                        GenerationConfig(
                            max_new_tokens=r.max_new_tokens,
                            do_sample=args.do_sample,
                            temperature=args.temperature,
                        ),
                    ),
                )
                out = g(params, np.asarray(r.prompt)[None])
                int(np.asarray(out[0, -1]))  # fetch barrier
            b1_wall = time.perf_counter() - t0
            result["serve_b1_sequential_s"] = round(b1_wall, 2)
            result["serve_vs_b1_speedup"] = round(b1_wall / max(wall, 1e-9), 2)
        if router is not None:
            from .. import resilience

            fleet = router.metrics()
            per = fleet.pop("per_replica")
            for key, val in fleet.items():
                result["serve_router_" + key] = val
            result["serve_router_occupancy"] = [p["occupancy"] for p in per]
            result["serve_router_hit_rates"] = [p["prefix_hit_rate"] for p in per]
            result["serve_router_quarantined"] = [p["quarantined"] for p in per]
            print(json.dumps(result))
            if router.draining and router.drain_reason == "preemption":
                # The launcher resume contract (docs/fault_tolerance.md):
                # in-flight work finished above; 75 = resume me, free of charge.
                from ..telemetry import flight as _flight

                _flight.dump_postmortem(
                    "preemption_drain_75",
                    extra={"drain_reason": router.drain_reason},
                )
                return resilience.PREEMPTION_EXIT_CODE
            return 0
        print(json.dumps(result))
        return 0
    finally:
        # The endpoint outlives the trace (and the router drain above) so a
        # late scrape still sees the final counters; closed only on exit.
        if metrics_server is not None:
            metrics_server.close()

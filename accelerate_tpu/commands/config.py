"""`accelerate-tpu config` — write the launch configuration file.

Analog of the reference interactive config command (`commands/config/
config.py:31`, `cluster.py:55` Q&A, `config_args.py` schema, default path
``~/.cache/huggingface/accelerate/default_config.yaml``). The TPU schema is
radically smaller: no backend zoo, no DeepSpeed/Megatron/dynamo trees — a
mesh shape, a sharding strategy, precision, and (for pods) host topology.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any

DEFAULT_CONFIG_DIR = os.path.join(
    os.path.expanduser(os.environ.get("ATX_HOME", "~/.cache/accelerate_tpu"))
)
DEFAULT_CONFIG_PATH = os.path.join(DEFAULT_CONFIG_DIR, "default_config.yaml")


@dataclass
class LaunchConfig:
    """Serializable launch configuration (reference `ClusterConfig`,
    `commands/config/config_args.py`)."""

    num_processes: int = 1
    coordinator_address: str = ""
    coordinator_port: int = 7801
    mesh_data: int = -1
    mesh_fsdp: int = 1
    mesh_tensor: int = 1
    mesh_sequence: int = 1
    mesh_expert: int = 1
    mixed_precision: str = "bf16"
    sharding_strategy: str = "DATA_PARALLEL"
    gradient_accumulation_steps: int = 1
    # Optimizer moments in pinned host RAM (parallel/host_offload.py; the
    # DeepSpeed offload_optimizer analog) — forwarded as ATX_OFFLOAD_OPTIMIZER.
    offload_optimizer: bool = False
    # Run fp8 even where the recorded matmul speedup is <= 1 (the launch
    # lose-lose gate, `commands/launch.py`).
    force_fp8: bool = False
    # Comma-separated tracker names (tracking.filter_trackers; "" = none),
    # forwarded as ATX_LOG_WITH; project_dir feeds ProjectConfiguration.
    log_with: str = ""
    project_dir: str = ""
    # Relaunch the whole worker group (fresh coordinator port) up to this
    # many times after a worker death — the torch-elastic max_restarts analog
    # (reference `commands/launch.py:142-771`). 0 = fail on first death.
    max_restarts: int = 0
    # TPU pod orchestration (reference tpu_pod_launcher, commands/launch.py:909)
    tpu_name: str = ""
    tpu_zone: str = ""
    tpu_project: str = ""
    extra_env: dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "LaunchConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def save(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        data = self.to_dict()
        try:
            import yaml

            with open(path, "w") as f:
                yaml.safe_dump(data, f, sort_keys=False)
        except ImportError:  # pragma: no cover - yaml ships with transformers
            path = os.path.splitext(path)[0] + ".json"
            with open(path, "w") as f:
                json.dump(data, f, indent=2)
        return path

    @classmethod
    def load(cls, path: str) -> "LaunchConfig":
        with open(path) as f:
            text = f.read()
        try:
            import yaml

            data = yaml.safe_load(text)
        except ImportError:  # pragma: no cover
            data = json.loads(text)
        return cls.from_dict(data or {})


def load_default_config() -> LaunchConfig | None:
    for path in (DEFAULT_CONFIG_PATH, os.path.splitext(DEFAULT_CONFIG_PATH)[0] + ".json"):
        if os.path.exists(path):
            return LaunchConfig.load(path)
    return None


def _ask(prompt: str, default: Any, cast=str) -> Any:
    raw = input(f"{prompt} [{default}]: ").strip()
    if not raw:
        return default
    try:
        return cast(raw)
    except (TypeError, ValueError):
        print(f"  invalid value {raw!r}; keeping {default}")
        return default


def interactive_config() -> LaunchConfig:
    """Q&A flow (reference `get_cluster_input`, `commands/config/cluster.py:55`)."""
    cfg = LaunchConfig()
    print("accelerate-tpu configuration")
    print("----------------------------")
    cfg.num_processes = _ask("How many host processes (1 per TPU host)?", 1, int)
    if cfg.num_processes > 1:
        cfg.coordinator_address = _ask(
            "Coordinator address (host:port of process 0; blank = TPU metadata autodetect)",
            "",
        )
    shape_help = "devices on each mesh axis; data=-1 means all remaining"
    cfg.mesh_data = _ask(f"Mesh: data-parallel size ({shape_help})", -1, int)
    cfg.mesh_fsdp = _ask("Mesh: fsdp size", 1, int)
    cfg.mesh_tensor = _ask("Mesh: tensor-parallel size", 1, int)
    cfg.mesh_sequence = _ask("Mesh: sequence-parallel size", 1, int)
    cfg.mesh_expert = _ask("Mesh: expert-parallel size", 1, int)
    cfg.sharding_strategy = _ask(
        "Sharding strategy (DATA_PARALLEL/ZERO1/ZERO2/FSDP/TENSOR_PARALLEL/HYBRID)",
        "FSDP" if cfg.mesh_fsdp > 1 else "DATA_PARALLEL",
    ).upper()
    if cfg.sharding_strategy in ("FSDP", "ZERO1", "ZERO2", "HYBRID"):
        cfg.offload_optimizer = (
            _ask(
                "Offload optimizer moments to pinned host RAM? (y/n; the "
                "DeepSpeed offload_optimizer analog — fits ~3x larger "
                "models at a per-step streaming cost)",
                "n",
            )
            .lower()
            .startswith("y")
        )
    cfg.mixed_precision = _ask("Mixed precision (no/bf16/fp16/fp8)", "bf16")
    if cfg.mixed_precision == "fp8":
        print(
            "  NOTE: fp8 only pays off on chips with native fp8 MXU support; "
            "on other hardware (e.g. TPU v5e) XLA upcasts the fp8 values — "
            "you keep the quantization error and get NO speedup. Check "
            "`bench.py`'s fp8_matmul_speedup field on your chip first."
        )
        cfg.force_fp8 = (
            _ask(
                "Force fp8 even where the recorded speedup is <= 1x? (y/n; "
                "otherwise launch refuses the lose-lose configuration)",
                "n",
            )
            .lower()
            .startswith("y")
        )
    cfg.gradient_accumulation_steps = _ask("Gradient accumulation steps", 1, int)
    cfg.max_restarts = _ask(
        "Max worker-group restarts after a crash (torch-elastic "
        "max_restarts analog; 0 = fail on first death)",
        0,
        int,
    )
    cfg.log_with = _ask(
        "Experiment trackers, comma-separated (json/tensorboard/wandb/"
        "mlflow/comet_ml/aim/clearml/dvclive; blank = none)",
        "",
    )
    if cfg.log_with:
        cfg.project_dir = _ask(
            "Project directory (checkpoints + tracker logging dir)", ""
        )
    if _ask("Launching on a GCE TPU pod via gcloud? (y/n)", "n").lower().startswith("y"):
        cfg.tpu_name = _ask("TPU name", "")
        cfg.tpu_zone = _ask("TPU zone", "")
        cfg.tpu_project = _ask("GCP project (blank = default)", "")
    return cfg


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser("config", help="Create the launch configuration file")
    p.add_argument("--config_file", default=DEFAULT_CONFIG_PATH, help="Where to write")
    p.add_argument(
        "--default",
        action="store_true",
        help="Write a non-interactive single-host default config "
        "(reference `write_basic_config`, commands/config/default.py:165)",
    )
    p.set_defaults(func=run)


def run(args: argparse.Namespace) -> int:
    cfg = LaunchConfig() if args.default else interactive_config()
    path = cfg.save(args.config_file)
    print(f"Configuration saved to {path}")
    return 0

"""`accelerate-tpu test` — run the bundled self-diagnostic under the current
config (reference `commands/test.py:22-57`)."""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser(
        "test", help="Run the bundled self-diagnostic script"
    )
    p.add_argument("--config_file", default=None)
    p.add_argument(
        "--host_devices",
        type=int,
        default=None,
        help="Simulate N CPU devices (diagnostic without a TPU)",
    )
    p.set_defaults(func=run)


def run(args: argparse.Namespace) -> int:
    import accelerate_tpu.test_utils.diagnostic as diag

    script = os.path.abspath(diag.__file__)
    cmd = [sys.executable, "-m", "accelerate_tpu.commands.cli", "launch"]
    if args.config_file:
        cmd += ["--config_file", args.config_file]
    if args.host_devices:
        cmd += ["--host_devices", str(args.host_devices)]
    cmd.append(script)
    print(f"Running diagnostic: {' '.join(cmd)}")
    result = subprocess.run(cmd)
    if result.returncode == 0:
        print("Test is a success! You are ready for your distributed training!")
    return result.returncode

"""`accelerate-tpu estimate` — shape-only HBM memory calculator.

Analog of `accelerate estimate-memory` (reference `commands/estimate.py`:
meta-device model load :64, ≈4x-for-Adam training estimate :218, per-dtype
table :253). Here the calculation is exact for the framework's model zoo via
`jax.eval_shape` — no weights are ever materialized — and it understands
sharding: pass a mesh factorization to see per-chip footprints.
"""

from __future__ import annotations

import argparse
import math
from typing import Any

_MODEL_PRESETS = {
    "llama-tiny": ("llama", "tiny"),
    "llama3-8b": ("llama", "llama3_8b"),
    "llama3-70b": ("llama", "llama3_70b"),
    "bert-base": ("bert", "bert_base"),
    "bert-tiny": ("bert", "tiny"),
}


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser(
        "estimate", help="Estimate HBM usage for a model family preset"
    )
    p.add_argument("model", choices=sorted(_MODEL_PRESETS), help="Model preset")
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--seq_len", type=int, default=2048)
    p.add_argument("--precision", default="bf16", choices=["no", "bf16", "fp16"])
    p.add_argument(
        "--optimizer", default="adamw", choices=["adamw", "adam", "sgd", "adafactor"]
    )
    p.add_argument("--shards", type=int, default=1, help="FSDP/ZeRO shard count")
    p.add_argument(
        "--remat", action="store_true", help="Assume full activation rematerialization"
    )
    p.add_argument(
        "--hbm_gb", type=float, default=16.0, help="Per-chip HBM (v5e=16, v4=32, v5p=95)"
    )
    p.set_defaults(func=run)


def _human(n_bytes: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n_bytes) < 1024:
            return f"{n_bytes:.2f} {unit}"
        n_bytes /= 1024
    return f"{n_bytes:.2f} PB"


def estimate(model: str, batch_size: int, seq_len: int, precision: str,
             optimizer: str, shards: int, remat: bool) -> dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from .. import models

    family, preset = _MODEL_PRESETS[model]
    module = getattr(models, family)
    config = getattr(module.__dict__[f"{family.capitalize()}Config"], preset)()

    # Exact parameter count via abstract evaluation — nothing materializes.
    shapes = jax.eval_shape(lambda rng: module.init(rng, config), jax.random.PRNGKey(0))
    n_params = sum(int(math.prod(x.shape)) for x in jax.tree.leaves(shapes))

    compute_bytes = 2 if precision in ("bf16", "fp16") else 4
    master_bytes = 4  # fp32 master params
    moments = {"adamw": 2, "adam": 2, "sgd": 0, "adafactor": 1}[optimizer]

    params_b = n_params * master_bytes / shards
    compute_copy_b = n_params * compute_bytes / shards if precision != "no" else 0
    grads_b = n_params * 4 / shards
    opt_b = n_params * 4 * moments / shards

    d_model = config.d_model
    n_layers = config.n_layers
    per_layer_act = batch_size * seq_len * d_model * compute_bytes
    if remat:
        # One residual stream per layer boundary + current-layer working set.
        act_b = per_layer_act * (n_layers + 8)
    else:
        # ~8 saved tensors per block (attn+mlp intermediates incl. d_ff).
        ff_ratio = getattr(config, "d_ff", 4 * d_model) / d_model
        act_b = per_layer_act * n_layers * (6 + 2 * ff_ratio)
    vocab = getattr(config, "vocab_size", 0)
    logits_b = batch_size * seq_len * vocab * 4 if vocab else 0

    total = params_b + compute_copy_b + grads_b + opt_b + act_b + logits_b
    return {
        "config": config,
        "n_params": n_params,
        "params": params_b,
        "compute_copy": compute_copy_b,
        "grads": grads_b,
        "optimizer": opt_b,
        "activations": act_b,
        "logits": logits_b,
        "total": total,
        "inference_total": n_params * compute_bytes / shards
        + per_layer_act * 4
        + logits_b / 2,
    }


def run(args: argparse.Namespace) -> int:
    r = estimate(
        args.model, args.batch_size, args.seq_len, args.precision,
        args.optimizer, args.shards, args.remat,
    )
    print(f"Model: {args.model}  ({r['n_params']:,} params)")
    print(f"Assumptions: batch={args.batch_size} seq={args.seq_len} "
          f"precision={args.precision} optimizer={args.optimizer} "
          f"shards={args.shards} remat={args.remat}")
    print()
    rows = [
        ("fp32 master params", r["params"]),
        (f"{args.precision} compute copy", r["compute_copy"]),
        ("gradients (fp32)", r["grads"]),
        ("optimizer moments", r["optimizer"]),
        ("activations", r["activations"]),
        ("logits + loss (fp32)", r["logits"]),
    ]
    width = max(len(n) for n, _ in rows)
    for name, val in rows:
        print(f"  {name:<{width}}  {_human(val):>12}")
    print(f"  {'-' * width}  {'-' * 12}")
    print(f"  {'training total/chip':<{width}}  {_human(r['total']):>12}")
    print(f"  {'inference total/chip':<{width}}  {_human(r['inference_total']):>12}")
    hbm = args.hbm_gb * 1024**3
    verdict = "FITS" if r["total"] <= hbm * 0.9 else "DOES NOT FIT"
    print(f"\n{verdict} in {args.hbm_gb:g} GB HBM "
          f"({100 * r['total'] / hbm:.0f}% of chip)")
    if r["total"] > hbm * 0.9 and args.shards == 1:
        need = math.ceil(r["total"] / (hbm * 0.7))
        print(f"Hint: try --shards {need} (FSDP) or gradient accumulation with a smaller batch.")
    return 0

"""`accelerate-tpu estimate` — shape-only HBM memory calculator.

Analog of `accelerate estimate-memory` (reference `commands/estimate.py`:
meta-device model load :64, ≈4x-for-Adam training estimate :218, per-dtype
table :253). The parameter count comes from `jax.eval_shape` and is exact
(no weights materialize); activation/logit terms are documented heuristics.
`--plan` runs the real HBM-budget sharding planner
(`big_modeling.infer_sharding_plan`) and prints the resulting spec summary.
"""

from __future__ import annotations

import argparse
import math
from typing import Any

_MODEL_PRESETS = {
    "llama-tiny": ("llama", "tiny"),
    "llama3-8b": ("llama", "llama3_8b"),
    "llama3-70b": ("llama", "llama3_70b"),
    "bert-base": ("bert", "bert_base"),
    "bert-tiny": ("bert", "tiny"),
    "gpt2": ("gpt", "gpt2"),
    "gpt2-xl": ("gpt", "gpt2_xl"),
    "gpt-tiny": ("gpt", "tiny"),
    "t5-small": ("t5", "t5_small"),
    "t5-base": ("t5", "t5_base"),
    "t5-tiny": ("t5", "tiny"),
    "vit-base": ("vit", "vit_base"),
    "vit-large": ("vit", "vit_large"),
    "vit-tiny": ("vit", "tiny"),
}


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser(
        "estimate", help="Estimate HBM usage for a model family preset"
    )
    p.add_argument(
        "model",
        nargs="?",
        help="Model preset name (see --list) OR a path to a local HF repo / "
        "config.json — any supported model_type estimates without a preset "
        "(the Hub-model analog of reference estimate.py:64; no network, so "
        "the repo must be on disk)",
    )
    p.add_argument("--list", action="store_true", help="List built-in presets")
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--seq_len", type=int, default=2048)
    p.add_argument("--precision", default="bf16", choices=["no", "bf16", "fp16"])
    p.add_argument(
        "--optimizer", default="adamw", choices=["adamw", "adam", "sgd", "adafactor"]
    )
    p.add_argument("--shards", type=int, default=1, help="FSDP/ZeRO shard count")
    p.add_argument(
        "--remat", action="store_true", help="Assume full activation rematerialization"
    )
    p.add_argument(
        "--offload_optimizer",
        action="store_true",
        help="Optimizer moments in pinned host RAM (parallel/host_offload.py "
        "ZeRO-Offload analog): moves their bytes off the HBM budget",
    )
    p.add_argument(
        "--hbm_gb", type=float, default=16.0, help="Per-chip HBM (v5e=16, v4=32, v5p=95)"
    )
    p.add_argument(
        "--plan",
        action="store_true",
        help="Run the HBM-budget sharding planner over an N-device mesh "
        "(N = --shards) and print the plan verdict",
    )
    p.add_argument(
        "--serve",
        action="store_true",
        help="Serving capacity planner (analysis/capacity.py): max KV slots "
        "and paged KV blocks that statically fit beside the serving weights "
        "on the chip (--chip or --hbm_gb; --seq_len is the slot max_len)",
    )
    p.add_argument(
        "--slots", type=int, default=8,
        help="Slot count to judge with --serve (the planner also reports "
        "the static maximum)",
    )
    p.add_argument(
        "--block-size", type=int, default=16,
        help="Paged-KV page size in tokens for the --serve max-blocks row",
    )
    p.add_argument(
        "--chip", default=None,
        help="Chip generation for --serve (v4/v5e/v5p/v6e); its HBM spec "
        "overrides --hbm_gb",
    )
    p.set_defaults(func=run)


def _human(n_bytes: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n_bytes) < 1024:
            return f"{n_bytes:.2f} {unit}"
        n_bytes /= 1024
    return f"{n_bytes:.2f} PB"


def _resolve_model(model: str) -> tuple[str, Any]:
    """Preset name -> (family, config); otherwise treat as a local HF repo
    directory / config.json and translate via `models.hf.from_hf_config`."""
    import os

    from .. import models

    if model in _MODEL_PRESETS:
        family, preset = _MODEL_PRESETS[model]
        module = getattr(models, family)
        config_cls = next(
            v for k, v in module.__dict__.items()
            if k.lower() == f"{family}config" and isinstance(v, type)
        )
        return family, getattr(config_cls, preset)()
    from ..models.hf import from_hf_config

    try:
        # Local repo dir / config.json, or a Hub id resolved cache-first
        # (models.hf.resolve_repo) — the reference estimate's Hub-name
        # ergonomics (`commands/estimate.py:64`).
        return from_hf_config(model)
    except ValueError as e:
        raise SystemExit(
            f"Unknown model {model!r}: not a preset "
            f"({', '.join(sorted(_MODEL_PRESETS))}) and not resolvable as a "
            f"repo path or Hub id ({e})."
        ) from e


def estimate(model: str, batch_size: int, seq_len: int, precision: str,
             optimizer: str, shards: int, remat: bool,
             offload_optimizer: bool = False) -> dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from .. import models

    family, config = _resolve_model(model)
    module = getattr(models, family)

    # Exact parameter count via abstract evaluation — nothing materializes.
    shapes = jax.eval_shape(lambda rng: module.init(rng, config), jax.random.PRNGKey(0))
    n_params = sum(int(math.prod(x.shape)) for x in jax.tree.leaves(shapes))

    compute_bytes = 2 if precision in ("bf16", "fp16") else 4
    master_bytes = 4  # fp32 master params
    moments = {"adamw": 2, "adam": 2, "sgd": 0, "adafactor": 1}[optimizer]

    params_b = n_params * master_bytes / shards
    compute_copy_b = n_params * compute_bytes / shards if precision != "no" else 0
    grads_b = n_params * 4 / shards
    opt_b = n_params * 4 * moments / shards

    d_model = config.d_model
    n_layers = getattr(config, "n_layers", None)
    if n_layers is None:  # encoder-decoder families
        n_layers = config.n_encoder_layers + config.n_decoder_layers
    if hasattr(config, "n_patches"):  # vision: sequence = patches + [CLS]
        seq_len = config.n_patches + 1
    eff_seq = seq_len
    per_layer_act = batch_size * seq_len * d_model * compute_bytes
    if remat:
        # One residual stream per layer boundary + current-layer working set.
        act_b = per_layer_act * (n_layers + 8)
    else:
        # ~8 saved tensors per block (attn+mlp intermediates incl. d_ff).
        ff_ratio = getattr(config, "d_ff", 4 * d_model) / d_model
        act_b = per_layer_act * n_layers * (6 + 2 * ff_ratio)
    vocab = getattr(config, "vocab_size", 0)
    logits_b = batch_size * seq_len * vocab * 4 if vocab else 0

    host_opt_b = 0.0
    if offload_optimizer:
        host_opt_b, opt_b = opt_b, 0.0
    total = params_b + compute_copy_b + grads_b + opt_b + act_b + logits_b
    return {
        "host_optimizer": host_opt_b,
        "family": family,
        "config": config,
        "seq_len": eff_seq,
        "n_params": n_params,
        "params": params_b,
        "compute_copy": compute_copy_b,
        "grads": grads_b,
        "optimizer": opt_b,
        "activations": act_b,
        "logits": logits_b,
        "total": total,
        "inference_total": n_params * compute_bytes / shards
        + per_layer_act * 4
        + logits_b / 2,
    }


def run(args: argparse.Namespace) -> int:
    if args.list:
        for name in sorted(_MODEL_PRESETS):
            print(name)
        return 0
    if args.model is None:
        raise SystemExit("estimate: provide a model preset or HF repo path (see --list)")
    r = estimate(
        args.model, args.batch_size, args.seq_len, args.precision,
        args.optimizer, args.shards, args.remat,
        offload_optimizer=args.offload_optimizer,
    )
    print(f"Model: {args.model}  ({r['n_params']:,} params)")
    print(f"Assumptions: batch={args.batch_size} seq={r['seq_len']} "
          f"precision={args.precision} optimizer={args.optimizer} "
          f"shards={args.shards} remat={args.remat}")
    print()
    rows = [
        ("fp32 master params", r["params"]),
        (f"{args.precision} compute copy", r["compute_copy"]),
        ("gradients (fp32)", r["grads"]),
        ("optimizer moments", r["optimizer"]),
        *([("host-resident moments", r["host_optimizer"])] if r["host_optimizer"] else []),
        ("activations", r["activations"]),
        ("logits + loss (fp32)", r["logits"]),
    ]
    width = max(len(n) for n, _ in rows)
    for name, val in rows:
        print(f"  {name:<{width}}  {_human(val):>12}")
    print(f"  {'-' * width}  {'-' * 12}")
    print(f"  {'training total/chip':<{width}}  {_human(r['total']):>12}")
    print(f"  {'inference total/chip':<{width}}  {_human(r['inference_total']):>12}")
    hbm = args.hbm_gb * 1024**3
    verdict = "FITS" if r["total"] <= hbm * 0.9 else "DOES NOT FIT"
    print(f"\n{verdict} in {args.hbm_gb:g} GB HBM "
          f"({100 * r['total'] / hbm:.0f}% of chip)")
    if r["total"] > hbm * 0.9 and args.shards == 1:
        need = math.ceil(r["total"] / (hbm * 0.7))
        print(f"Hint: try --shards {need} (FSDP) or gradient accumulation with a smaller batch.")
    if args.plan:
        print()
        print(_plan_summary(args, r))
    if args.serve:
        print()
        print(_serve_summary(args, r))
    return 0


def _serve_summary(args: argparse.Namespace, r: dict[str, Any]) -> str:
    """Serving capacity table: per-token/per-slot KV arithmetic from the
    family's attention config + the static max-slots / max-paged-blocks
    solve (docs/serving.md, "Capacity planner")."""
    from ..analysis.capacity import plan_capacity
    from ..analysis.roofline import chip_spec_for

    config = r["config"]
    n_layers = getattr(config, "n_layers", None)
    heads = getattr(config, "num_kv_heads", None) or getattr(config, "num_heads", None)
    head_dim = getattr(config, "head_dim", None)
    if head_dim is None and heads and getattr(config, "d_model", None):
        head_dim = config.d_model // getattr(config, "num_heads", heads)
    if not (n_layers and heads and head_dim):
        raise SystemExit(
            f"estimate --serve: family {r['family']!r} has no decoder "
            "KV-cache config (needs n_layers, num_heads/num_kv_heads, "
            "head_dim) — the planner only applies to decode-serving models"
        )
    kv_itemsize = 2 if args.precision in ("bf16", "fp16") else 4
    # K and V, every layer, every KV head, one position.
    per_token = n_layers * 2 * heads * head_dim * kv_itemsize
    max_len = args.seq_len
    weights = r["n_params"] * (2 if args.precision in ("bf16", "fp16") else 4)
    if args.chip is not None:
        spec = chip_spec_for(args.chip)
        chip, hbm_bytes = spec, None  # chip's HBM spec governs
    else:
        chip, hbm_bytes = None, int(args.hbm_gb * 1024**3)
    plan = plan_capacity(
        chip=chip,
        hbm_bytes=hbm_bytes,
        weights_bytes=weights,
        kv_bytes_per_slot=per_token * max_len,
        n_slots=args.slots,
        max_len=max_len,
    )
    bs = max(args.block_size, 1)
    rows = [
        (f"serving weights ({args.precision})", _human(weights)),
        ("KV bytes / token", _human(per_token)),
        (f"KV bytes / slot (max_len {max_len})", _human(plan.kv_bytes_per_slot)),
        (f"slot pool ({args.slots} slots)", _human(plan.kv_pool_bytes)),
        ("static total", _human(plan.static_total_bytes)),
        ("HBM budget", _human(plan.hbm_bytes)),
        ("static max slots", str(plan.max_slots)),
        (f"static max paged blocks ({bs} tok)", str(plan.max_blocks(bs))),
    ]
    width = max(len(n) for n, _ in rows)
    lines = ["Serving capacity plan:"]
    lines += [f"  {name:<{width}}  {val:>12}" for name, val in rows]
    lines.append(f"  {plan.format()}")
    return "\n".join(lines)


def _plan_summary(args: argparse.Namespace, r: dict[str, Any]) -> str:
    """Shape-only sharding plan over a --shards-device mesh (the
    `infer_auto_device_map` analog, reference `utils/modeling.py:1281`)."""
    import jax
    import jax.numpy as jnp

    from .. import models
    from ..big_modeling import infer_sharding_plan
    from ..parallel.mesh import MeshConfig, build_mesh
    from ..parallel.tp import get_tp_plan, list_tp_plans

    family = r["family"]
    config = r["config"]
    module = getattr(models, family)
    shapes = jax.eval_shape(lambda rng: module.init(rng, config), jax.random.PRNGKey(0))
    n = max(args.shards, 1)
    if n == len(jax.devices()):
        mesh = build_mesh(MeshConfig(data=1, fsdp=n))
    else:
        # Planning is shape-only; an abstract mesh over a replicated device
        # list is enough to compute division factors (build_mesh would
        # reject any n that differs from the local device count).
        devices = (jax.devices() * n)[:n]
        import numpy as np
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices).reshape(1, n, 1, 1, 1),
                    ("data", "fsdp", "tensor", "sequence", "expert"))
    rules = get_tp_plan(family) if family in list_tp_plans() else ()
    dtype = jnp.bfloat16 if args.precision in ("bf16", "fp16") else jnp.float32
    budget = int(args.hbm_gb * 0.95 * 1024**3)
    plan = infer_sharding_plan(shapes, mesh, hbm_budget=budget, rules=rules, dtype=dtype)
    return f"Sharding plan over {n} device(s):\n{plan.summary()}"

"""`accelerate-tpu trace` / `atx trace` — render request-scoped traces.

Reads either surface the flight recorder writes (docs/observability.md):

- a **postmortem bundle** (``postmortem_*.json`` from
  `telemetry.flight.dump_postmortem`) — span records with monotonic
  ``t0``/``t1`` plus the recorder's perf/wall anchors;
- a **live trace dir** (``ATX_TRACE_DIR`` holding ``spans_*.jsonl``
  Chrome-trace lines) — complete events with wall-clock ``ts``/``dur``.

Both normalize to the same record shape, and two views render:

- per-request **waterfalls**: each request's spans as time-offset bars,
  so "where did THIS request spend its time" is one glance;
- a tail-latency **attribution table**: per-phase (queue / prefill /
  decode / emit) p50 and p99 durations plus each phase's share of e2e —
  the "you cannot optimize a tail you cannot attribute" view.

``--check TOL`` turns the renderer into a gate (the `make smoke-trace`
lane): for every completed request the four contiguous phase spans must
sum to its e2e latency within TOL (fraction, e.g. 0.05), else exit 1.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any

PHASES = ("phase_queue", "phase_prefill", "phase_decode", "phase_emit")
_BAR_WIDTH = 48


def register(subparsers: argparse._SubParsersAction) -> None:
    p = subparsers.add_parser(
        "trace",
        help="Render a postmortem bundle or live trace dir as per-request "
        "waterfalls + a tail-latency attribution table",
    )
    p.add_argument(
        "source",
        help="a postmortem bundle (.json) or a trace directory of "
        "spans_*.jsonl files (ATX_TRACE_DIR / ATX_POSTMORTEM_DIR)",
    )
    p.add_argument(
        "--rid", type=int, default=None,
        help="render only this request id's waterfall",
    )
    p.add_argument(
        "--limit", type=int, default=8,
        help="max waterfalls to render (default 8; the attribution table "
        "always covers every request)",
    )
    p.add_argument(
        "--check", type=float, default=None, metavar="TOL",
        help="gate mode: exit 1 unless every completed request's phase "
        "spans sum to its e2e within TOL (fraction, e.g. 0.05)",
    )
    p.add_argument(
        "--json", dest="as_json", action="store_true",
        help="emit the normalized per-request summary as one JSON object "
        "instead of the rendered views",
    )
    p.set_defaults(func=run)


# ------------------------------------------------------------ normalization


def _from_bundle(path: str) -> list[dict[str, Any]]:
    from ..telemetry import flight

    bundle = flight.read_bundle(path)
    out = []
    for rec in bundle.get("spans") or []:
        if not isinstance(rec, dict) or "name" not in rec:
            continue
        out.append(
            {
                "name": rec["name"],
                "rid": int(rec.get("rid", -1)),
                "t0": float(rec.get("t0", 0.0)),
                "t1": float(rec.get("t1", rec.get("t0", 0.0))),
                "attrs": dict(rec.get("attrs") or {}),
            }
        )
    return out


def _from_trace_dir(path: str) -> list[dict[str, Any]]:
    out = []
    for jsonl in sorted(glob.glob(os.path.join(path, "*.jsonl"))):
        with open(jsonl) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # a truncated tail line from a killed process
                if ev.get("ph") != "X":
                    continue
                args = dict(ev.get("args") or {})
                rid = args.pop("rid", -1)
                t0 = float(ev.get("ts", 0.0)) / 1e6
                out.append(
                    {
                        "name": ev.get("name", "?"),
                        "rid": int(rid) if isinstance(rid, (int, float)) else -1,
                        "t0": t0,
                        "t1": t0 + float(ev.get("dur", 0.0)) / 1e6,
                        "attrs": args,
                    }
                )
    return out


def load_records(source: str) -> list[dict[str, Any]]:
    """Normalize a bundle file or a trace dir into span records sorted by
    start time: ``{"name", "rid", "t0", "t1", "attrs"}`` (seconds; the
    time base is only meaningful relative to itself)."""
    if os.path.isdir(source):
        records = _from_trace_dir(source)
    else:
        records = _from_bundle(source)
    records.sort(key=lambda r: (r["t0"], r["t1"]))
    return records


# ---------------------------------------------------------------- analysis


def summarize(records: list[dict[str, Any]]) -> dict[int, dict[str, Any]]:
    """Per-request view: phase durations (ms), e2e from the ``complete``
    span (falling back to the phase envelope), and the raw span list."""
    by_rid: dict[int, dict[str, Any]] = {}
    for rec in records:
        rid = rec["rid"]
        if rid < 0:
            continue
        entry = by_rid.setdefault(
            rid, {"spans": [], "phases": {}, "e2e_ms": None, "attempts": None}
        )
        entry["spans"].append(rec)
        dur_ms = max(0.0, rec["t1"] - rec["t0"]) * 1e3
        if rec["name"] in PHASES:
            entry["phases"][rec["name"]] = dur_ms
        elif rec["name"] == "complete":
            entry["e2e_ms"] = dur_ms
            entry["attempts"] = rec["attrs"].get("attempts")
            entry["finish_reason"] = rec["attrs"].get("finish_reason")
    for entry in by_rid.values():
        if entry["e2e_ms"] is None and entry["phases"]:
            entry["e2e_ms"] = sum(entry["phases"].values())
    return by_rid


def _pctl(xs: list[float], q: float) -> float:
    s = sorted(xs)
    return s[min(len(s) - 1, int(q * len(s)))]


def attribution(by_rid: dict[int, dict[str, Any]]) -> list[dict[str, Any]]:
    """Per-phase p50/p99 and share-of-total-e2e rows over every request
    that recorded all four phases."""
    complete = [
        e for e in by_rid.values()
        if e["e2e_ms"] and all(p in e["phases"] for p in PHASES)
    ]
    total_e2e = sum(e["e2e_ms"] for e in complete)
    rows = []
    for phase in PHASES:
        xs = [e["phases"][phase] for e in complete]
        if not xs:
            continue
        rows.append(
            {
                "phase": phase.removeprefix("phase_"),
                "n": len(xs),
                "p50_ms": round(_pctl(xs, 0.50), 3),
                "p99_ms": round(_pctl(xs, 0.99), 3),
                "share": round(sum(xs) / total_e2e, 4) if total_e2e else 0.0,
            }
        )
    return rows


def check_sums(
    by_rid: dict[int, dict[str, Any]], tol: float
) -> list[str]:
    """The acceptance gate: for every request carrying all four phase
    spans, |sum(phases) - e2e| must be within ``tol`` x e2e."""
    problems = []
    checked = 0
    for rid, e in sorted(by_rid.items()):
        if e["e2e_ms"] is None or not all(p in e["phases"] for p in PHASES):
            continue
        checked += 1
        total = sum(e["phases"][p] for p in PHASES)
        if abs(total - e["e2e_ms"]) > tol * max(e["e2e_ms"], 1e-9):
            problems.append(
                f"rid {rid}: phases sum to {total:.3f}ms but e2e is "
                f"{e['e2e_ms']:.3f}ms (tolerance {tol:.0%})"
            )
    if checked == 0:
        problems.append(
            "no request carried all four phase spans — nothing to check "
            "(was ATX_TRACE_REQUESTS=1 set for the traced run?)"
        )
    return problems


# --------------------------------------------------------------- rendering


def _render_waterfall(rid: int, entry: dict[str, Any], out: Any) -> None:
    spans = entry["spans"]
    t_lo = min(s["t0"] for s in spans)
    t_hi = max(s["t1"] for s in spans)
    window = max(t_hi - t_lo, 1e-9)
    e2e = entry["e2e_ms"]
    head = f"rid {rid}"
    if e2e is not None:
        head += f"  e2e={e2e:.2f}ms"
    if entry.get("attempts") not in (None, 1):
        head += f"  attempts={entry['attempts']}"
    out.write(head + "\n")
    for s in spans:
        lo = int(_BAR_WIDTH * (s["t0"] - t_lo) / window)
        hi = int(_BAR_WIDTH * (s["t1"] - t_lo) / window)
        bar = " " * lo + ("#" * max(hi - lo, 1)).ljust(_BAR_WIDTH - lo)
        dur_ms = (s["t1"] - s["t0"]) * 1e3
        attrs = ""
        if s["attrs"]:
            attrs = " " + ",".join(f"{k}={v}" for k, v in s["attrs"].items())
        out.write(f"  |{bar}| {s['name']:<14} {dur_ms:9.3f}ms{attrs}\n")


def run(args: argparse.Namespace) -> int:
    out = sys.stdout
    try:
        records = load_records(args.source)
    except (OSError, ValueError) as e:
        print(f"atx trace: cannot read {args.source!r}: {e}", file=sys.stderr)
        return 2
    if not records:
        print(f"atx trace: no span records in {args.source!r}", file=sys.stderr)
        return 2
    by_rid = summarize(records)
    if args.rid is not None:
        by_rid = {args.rid: by_rid[args.rid]} if args.rid in by_rid else {}
        if not by_rid:
            print(f"atx trace: rid {args.rid} not in trace", file=sys.stderr)
            return 2
    rows = attribution(by_rid)
    if args.as_json:
        payload = {
            "requests": {
                str(rid): {
                    "e2e_ms": e["e2e_ms"],
                    "phases_ms": e["phases"],
                    "attempts": e["attempts"],
                    "spans": len(e["spans"]),
                }
                for rid, e in sorted(by_rid.items())
            },
            "attribution": rows,
        }
        out.write(json.dumps(payload, sort_keys=True) + "\n")
    else:
        for i, (rid, entry) in enumerate(sorted(by_rid.items())):
            if i >= max(args.limit, 0):
                out.write(
                    f"... {len(by_rid) - i} more request(s) (--limit)\n"
                )
                break
            _render_waterfall(rid, entry, out)
        if rows:
            out.write(
                "\ntail-latency attribution "
                f"({rows[0]['n']} requests with full phase spans):\n"
            )
            out.write(
                f"  {'phase':<10}{'p50_ms':>12}{'p99_ms':>12}{'share':>9}\n"
            )
            for r in rows:
                out.write(
                    f"  {r['phase']:<10}{r['p50_ms']:>12.3f}"
                    f"{r['p99_ms']:>12.3f}{r['share']:>8.1%}\n"
                )
    if args.check is not None:
        problems = check_sums(by_rid, args.check)
        for p in problems:
            print(f"atx trace --check: {p}", file=sys.stderr)
        if problems:
            return 1
        print(
            f"atx trace --check: phase attribution consistent within "
            f"{args.check:.0%} for all checked requests",
            file=sys.stderr,
        )
    return 0

// Native host-side data-path kernels for accelerate_tpu.
//
// The reference's input pipeline rides torch's C++ DataLoader machinery
// (worker pool, pinned-memory batch assembly); this is the TPU-native
// equivalent for the host side of the pipeline: assembling the next global
// batch must outrun the device step, and the Python-loop + np.stack path
// holds the GIL and copies twice. These kernels do the two hot operations
// with no Python in the loop:
//
//   atx_gather_rows  — gather dataset rows by index into one contiguous
//                      batch buffer, multi-threaded memcpy (the collate path
//                      for array-backed datasets).
//   atx_shuffle      — Fisher-Yates permutation driven by splitmix64
//                      (deterministic in the seed, O(n), no numpy RNG
//                      state to carry).
//
// Built on first use by native/__init__.py (_build_and_load) with
// `g++ -O3 -shared -fPIC`; loaded via ctypes
// (no pybind11 in the image). Every entry point is plain C ABI.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Gather n rows of row_bytes each: dst[i] = src[indices[i]] for i in [0, n).
// src must be C-contiguous with rows of exactly row_bytes. Negative indices
// or indices >= src_rows return the offending position (first error);
// returns -1 on success.
long long atx_gather_rows(const char* src, long long src_rows,
                          long long row_bytes, const long long* indices,
                          long long n, char* dst, int n_threads) {
    for (long long i = 0; i < n; ++i) {
        if (indices[i] < 0 || indices[i] >= src_rows) return i;
    }
    if (n_threads <= 1 || n < n_threads * 4) {
        for (long long i = 0; i < n; ++i) {
            std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                        static_cast<size_t>(row_bytes));
        }
        return -1;
    }
    std::vector<std::thread> workers;
    workers.reserve(n_threads);
    long long chunk = (n + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
        long long begin = t * chunk;
        long long end = begin + chunk < n ? begin + chunk : n;
        if (begin >= end) break;
        workers.emplace_back([=]() {
            for (long long i = begin; i < end; ++i) {
                std::memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
                            static_cast<size_t>(row_bytes));
            }
        });
    }
    for (auto& w : workers) w.join();
    return -1;
}

static inline uint64_t splitmix64(uint64_t& state) {
    uint64_t z = (state += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

// In-place Fisher-Yates over indices[0..n) seeded by `seed` (deterministic).
void atx_shuffle(long long* indices, long long n, uint64_t seed) {
    uint64_t state = seed;
    for (long long i = n - 1; i > 0; --i) {
        // Unbiased bounded draw (Lemire); bias is < 2^-64 * n, irrelevant
        // for dataset sizes, so the simple multiply-shift is fine.
        uint64_t r = splitmix64(state);
        __uint128_t m = static_cast<__uint128_t>(r) * static_cast<__uint128_t>(i + 1);
        long long j = static_cast<long long>(m >> 64);
        long long tmp = indices[i];
        indices[i] = indices[j];
        indices[j] = tmp;
    }
}

// iota + shuffle in one call (saves a Python-side arange for big datasets).
void atx_permutation(long long* out, long long n, uint64_t seed) {
    for (long long i = 0; i < n; ++i) out[i] = i;
    atx_shuffle(out, n, seed);
}

}  // extern "C"

"""Native (C++) host data-path: threaded batch gather + seeded shuffle.

The reference's input pipeline delegates its native side to torch's C++
DataLoader core; here the equivalent lives in `hostloader.cpp`, compiled on
first use with the system toolchain (`g++ -O3 -shared -fPIC` — no pybind11
in the image, so bindings are plain-C ABI through ctypes) and cached next to
the source. Everything degrades gracefully: if no toolchain is available,
the numpy fallbacks below keep identical semantics (`gather_rows` is
bit-identical; `permutation` documents its own determinism contract).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import tempfile
import threading
from typing import Any

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "hostloader.cpp")
_LOCK = threading.Lock()
_LIB: Any = None
_LIB_ERR: str | None = None
_DEFAULT_THREADS = min(8, os.cpu_count() or 1)


def _build_and_load() -> Any:
    """Compile (if needed) and dlopen the native library. Raises on failure."""
    # Per-user cache path (uid suffix, like torch's cpp_extension): a shared
    # predictable path in /tmp would let another local user pre-plant a .so
    # that ctypes.CDLL then executes in this process.
    cache_dir = os.environ.get(
        "ATX_NATIVE_CACHE",
        os.path.join(tempfile.gettempdir(), f"atx_native_{os.getuid()}"),
    )
    os.makedirs(cache_dir, mode=0o700, exist_ok=True)
    st = os.stat(cache_dir)
    if st.st_uid != os.getuid() or (st.st_mode & 0o022):
        raise RuntimeError(
            f"Refusing to load native kernels from {cache_dir!r}: the cache "
            f"directory is owned by uid {st.st_uid} with mode "
            f"{oct(st.st_mode & 0o777)} (must be owned by this user and not "
            "group/world-writable). Set ATX_NATIVE_CACHE to a private "
            "directory."
        )
    src_mtime = int(os.path.getmtime(_SRC))
    so_path = os.path.join(cache_dir, f"hostloader_{src_mtime}.so")
    if not os.path.exists(so_path):
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread", _SRC, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, text=True, timeout=120)
        os.replace(tmp, so_path)  # atomic: concurrent builders race safely
    lib = ctypes.CDLL(so_path)
    lib.atx_gather_rows.restype = ctypes.c_longlong
    lib.atx_gather_rows.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.atx_shuffle.restype = None
    lib.atx_shuffle.argtypes = [
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong, ctypes.c_uint64
    ]
    lib.atx_permutation.restype = None
    lib.atx_permutation.argtypes = [
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong, ctypes.c_uint64
    ]
    return lib


def _lib() -> Any:
    """The loaded native library, or None if unavailable (cached verdict)."""
    global _LIB, _LIB_ERR
    if _LIB is not None or _LIB_ERR is not None:
        return _LIB
    with _LOCK:
        if _LIB is None and _LIB_ERR is None:
            if os.environ.get("ATX_DISABLE_NATIVE"):
                _LIB_ERR = "disabled via ATX_DISABLE_NATIVE"
                return None
            try:
                _LIB = _build_and_load()
            except Exception as e:  # no toolchain / sandboxed tmp / bad cc
                _LIB_ERR = f"{type(e).__name__}: {e}"
    return _LIB


def native_available() -> bool:
    return _lib() is not None


def native_error() -> str | None:
    """Why the native path is off (None when it's on)."""
    _lib()
    return _LIB_ERR


def gather_rows(
    src: np.ndarray, indices: Any, *, n_threads: int | None = None
) -> np.ndarray:
    """``src[indices]`` along axis 0 into a freshly-allocated contiguous
    array — the batch-assembly primitive. Native path: multi-threaded
    memcpy outside the GIL; fallback: numpy fancy indexing (bit-identical).
    """
    idx = np.ascontiguousarray(indices, dtype=np.int64)
    if idx.ndim != 1:
        raise ValueError(f"indices must be 1-D, got shape {idx.shape}")
    src = np.asarray(src)
    # One bounds contract on both paths (numpy fancy indexing would silently
    # wrap negatives; the native kernel rejects them).
    if idx.size and (
        int(idx.min()) < 0 or (src.ndim and int(idx.max()) >= src.shape[0])
    ):
        bad = idx[(idx < 0) | (idx >= (src.shape[0] if src.ndim else 0))][0]
        raise IndexError(
            f"index {int(bad)} out of bounds for axis 0 with size "
            f"{src.shape[0] if src.ndim else 0}"
        )
    lib = _lib()
    # Non-contiguous sources: ascontiguousarray would copy the WHOLE dataset
    # per batch; numpy's strided fancy indexing copies only the batch rows.
    if lib is None or src.ndim == 0 or not src.flags.c_contiguous:
        return src[idx]
    out = np.empty((idx.shape[0],) + src.shape[1:], dtype=src.dtype)
    row_bytes = src.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    if row_bytes == 0 or idx.shape[0] == 0:
        return src[idx]
    rc = lib.atx_gather_rows(
        src.ctypes.data_as(ctypes.c_char_p),
        src.shape[0],
        row_bytes,
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        idx.shape[0],
        out.ctypes.data_as(ctypes.c_char_p),
        int(n_threads if n_threads is not None else _DEFAULT_THREADS),
    )
    if rc >= 0:  # unreachable after the Python-side check; kernel backstop
        raise IndexError(f"index {int(idx[rc])} out of bounds (native)")
    return out


def permutation(n: int, seed: int) -> np.ndarray:
    """Deterministic permutation of range(n) keyed by ``seed``.

    The native and fallback paths use DIFFERENT generators (splitmix64
    Fisher-Yates vs numpy PCG64) — both are deterministic in the seed, but
    the orders differ. Callers that must reproduce an order across machines
    with and without a toolchain should use `numpy.random.Generator`
    directly; `SeedableSampler` therefore defaults to its numpy backend and
    routes here only with ``backend="native"`` (`data/sampler.py`).
    """
    lib = _lib()
    if lib is None:
        return np.random.default_rng(seed).permutation(n).astype(np.int64)
    out = np.empty(n, dtype=np.int64)
    if n:
        lib.atx_permutation(
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            n,
            ctypes.c_uint64(seed & 0xFFFFFFFFFFFFFFFF),
        )
    return out

"""Single-pass fused AdamW update.

The fallback (`parallel.host_offload._adamw_slice` under XLA) is a chain of
a dozen elementwise ops; XLA fuses most of them but still materializes the
bias-corrected intermediates and walks param/grad/moments more than once.
This kernel is the whole update — moment EMAs, bias correction, the
weight-decay term, and the learning-rate step — in one pass per block, with
the moment buffers aliased in place (``input_output_aliases``), which is the
shape the ~6x-off ``hostoffload_adamw_mfu`` bench number wants: the
host-offloaded tier's per-layer device-side update becomes one
read-modify-write over the layer slice.

The math replicates `_adamw_slice` literally (same op order, same dtypes,
``jnp`` namespace). Parity is to a few ulps, not bitwise: the divides and
sqrt lower with TPU semantics (reciprocal / rsqrt refinement) inside the
kernel. The disk tier's numpy-namespace call never dispatches here.

Leaves are viewed as (rows, block) over their flattened size; a leaf whose
size has no usable block divisor, or is too small to be worth a kernel
launch, falls back per leaf — mixing kernel and fallback leaves within one
tree step is fine, each leaf's update is independent.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .dispatch import kernel_mode, pallas_available, register_kernel

register_kernel(
    "fused_adamw", "one-pass AdamW step with in-place moment buffers"
)

if pallas_available():
    from jax.experimental import pallas as pl

    from ...ops.autotune import cached_pick_block, tuned_call_kwargs

    def pick_block(dim, candidates=(512, 256, 128, 64, 32, 16, 8)):
        # Persisted autotune table first (ATX_BLOCK_FUSED_ADAMW /
        # $ATX_AUTOTUNE_DIR), divide-exactly heuristic otherwise.
        return cached_pick_block("fused_adamw", dim, candidates)
else:  # pragma: no cover - environment dependent
    pl = None

    def pick_block(dim, candidates=(512, 256, 128, 64, 32, 16, 8)):
        return None

# Below this many elements the launch overhead beats the fusion win
# (norms, biases, tiny heads) — those leaves take the XLA fallback.
_MIN_SIZE = 1024
_BLOCKS = (16384, 8192, 4096, 2048, 1024, 512, 256, 128)


def _adamw_kernel(
    s_ref, g_ref, mu_ref, nu_ref, p_ref, u_ref, mu_out, nu_out,
    *, b1, b2, eps, weight_decay, has_grad_scale,
):
    # `_adamw_slice` verbatim, one (1, block) slab at a time.
    mu = mu_ref[...]
    nu = nu_ref[...]
    g32 = g_ref[...].astype(mu.dtype)
    if has_grad_scale:
        g32 = g32 * s_ref[0, 2].astype(mu.dtype)
    new_mu = b1 * mu + (1.0 - b1) * g32
    new_nu = b2 * nu + (1.0 - b2) * jnp.square(g32)
    c = s_ref[0, 0].astype(new_mu.dtype)
    mu_hat = new_mu / (1.0 - b1**c)
    nu_hat = new_nu / (1.0 - b2**c)
    step = mu_hat / (jnp.sqrt(nu_hat) + eps) + weight_decay * p_ref[...].astype(
        new_mu.dtype
    )
    u_ref[...] = -s_ref[0, 1].astype(new_mu.dtype) * step
    mu_out[...] = new_mu
    nu_out[...] = new_nu


def _plan(size: int):
    if size < _MIN_SIZE:
        return None
    blk = pick_block(size, _BLOCKS)
    if blk is None:
        return None
    return size // blk, blk


def fused_adamw_update(
    g, mu, nu, p, count, lr_t, b1, b2, eps, weight_decay,
    grad_scale=None, *, interpret: bool = False,
):
    """One AdamW step for one leaf: returns ``(update, new_mu, new_nu)``
    exactly like `_adamw_slice`, or ``None`` when the leaf's size doesn't
    tile (caller falls back)."""
    size = int(mu.size)
    plan = _plan(size)
    if plan is None or g.shape != mu.shape or nu.shape != mu.shape or p.shape != mu.shape:
        return None
    # b1/b2/eps/weight_decay are baked into the kernel body; the optimizer
    # passes them as Python floats. A traced value here (someone jitting over
    # the hyperparams) can't be closed over — fall back.
    if not all(isinstance(hp, (int, float)) for hp in (b1, b2, eps, weight_decay)):
        return None
    rows, blk = plan
    scalars = jnp.stack(
        [
            jnp.asarray(count).astype(jnp.float32).reshape(()),
            jnp.asarray(lr_t).astype(jnp.float32).reshape(()),
            (
                jnp.asarray(grad_scale).astype(jnp.float32).reshape(())
                if grad_scale is not None
                else jnp.zeros((), jnp.float32)
            ),
            jnp.zeros((), jnp.float32),
        ]
    ).reshape(1, 4)
    view = lambda a: a.reshape(rows, blk)
    row_spec = pl.BlockSpec((1, blk), lambda i: (i, 0))
    kernel = functools.partial(
        _adamw_kernel,
        b1=b1,
        b2=b2,
        eps=eps,
        weight_decay=weight_decay,
        has_grad_scale=grad_scale is not None,
    )
    u, new_mu, new_nu = pl.pallas_call(
        kernel,
        grid=(rows,),
        in_specs=[pl.BlockSpec((1, 4), lambda i: (0, 0))] + [row_spec] * 4,
        out_specs=[row_spec] * 3,
        out_shape=[
            jax.ShapeDtypeStruct((rows, blk), mu.dtype),
            jax.ShapeDtypeStruct((rows, blk), mu.dtype),
            jax.ShapeDtypeStruct((rows, blk), nu.dtype),
        ],
        # Moments update in place; the scalars/g/p operands stay read-only.
        input_output_aliases={2: 1, 3: 2},
        **tuned_call_kwargs(interpret, ("arbitrary",)),
    )(scalars, view(g), view(mu), view(nu), view(p))
    return u.reshape(mu.shape), new_mu.reshape(mu.shape), new_nu.reshape(mu.shape)


def maybe_fused_adamw(
    g, mu, nu, p, count, lr_t, b1, b2, eps, weight_decay, grad_scale=None
):
    """Dispatch entry for `parallel.host_offload._adamw_slice`."""
    mode = kernel_mode("fused_adamw")
    if mode is None:
        return None
    return fused_adamw_update(
        g, mu, nu, p, count, lr_t, b1, b2, eps, weight_decay, grad_scale,
        interpret=mode == "interpret",
    )

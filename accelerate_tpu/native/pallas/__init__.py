"""Pallas hot-path kernel tier (ROADMAP direction 3).

Custom TPU kernels for the three measured hot paths the XLA lowerings leave
on the table (BENCH_r05): flash-decode attention over the slot KV cache
(`kv16k_int8_speedup` 1.016 — decode attention ignores KV-quantization
bandwidth headroom), fused quantize→dot→rescale matmuls for the int8/fp8
paths (`fp8_matmul_speedup` 1.004 — fp8 round-trips through XLA's upcast),
and a single-pass fused AdamW update (`hostoffload_adamw_mfu` 0.0898).

Every kernel sits behind the dispatch-by-availability registry in
`dispatch.py`: TPU backend + pallas importable + shape/dtype supported →
kernel; anything else → the exact current lowering, byte-identical to a
build without this package. `ATX_KERNELS` / `ATX_KERNEL_<NAME>` force any
kernel off, on, or into interpret mode (the CPU bit-parity test path).
"""

from __future__ import annotations

from .dispatch import (  # noqa: F401
    force_kernels,
    kernel_mode,
    kernel_status,
    pallas_available,
    register_kernel,
)

"""Dispatch-by-availability for the Pallas kernel tier.

Every kernel in this package is OPTIONAL: the call site always carries the
exact current XLA lowering as its fallback, and `kernel_mode(name)` decides
per trace whether the Pallas kernel replaces it. The decision is:

1. a programmatic override (`force_kernels(...)` — tests and the bench's
   on/off comparison phases), else
2. ``ATX_KERNEL_<NAME>`` (per-kernel env knob, e.g.
   ``ATX_KERNEL_DECODE_ATTN=0``), else
3. ``ATX_KERNELS`` (the global knob), else
4. ``auto``.

Knob values:

- ``0`` / ``off`` / ``false``  — never use the kernel (fallback lowering);
- ``1`` / ``on`` / ``auto``    — use the compiled kernel iff the backend is
  TPU and pallas imports; otherwise fall back (so CPU CI and older jax
  run the reference path untouched);
- ``interpret``                — force the kernel in Pallas interpret mode
  (runs anywhere, slowly) — the CPU bit-parity test path.

Like the fp8/int8 modes (`ops/fp8.py`), the mode is read at TRACE time:
jit caches traced inside different modes belong to different function
objects or different traces; the bench phases re-trace per mode.

Shape/dtype support is the CALL SITE's job — `kernel_mode` answers "may
this kernel run", the kernel module's own `supported()` predicate answers
"can it, for these operands". Both must say yes or the fallback runs.
"""

from __future__ import annotations

import contextlib
import functools
import os
import re
import threading
from typing import Any

_FORCE = threading.local()

# name -> one-line description (introspection via `kernel_status`).
_REGISTRY: dict[str, str] = {}

_OFF = {"0", "off", "false", "no"}
_ON = {"1", "on", "auto", "true", "yes", ""}


def register_kernel(name: str, doc: str = "") -> None:
    _REGISTRY[name] = doc


@functools.lru_cache(maxsize=None)
def pallas_available() -> bool:
    try:
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401

        return True
    except Exception:  # pragma: no cover - environment dependent
        return False


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() == "tpu"


def _env_knob(name: str) -> str | None:
    per = os.environ.get("ATX_KERNEL_" + re.sub(r"[^A-Za-z0-9]", "_", name).upper())
    if per is not None:
        return per
    return os.environ.get("ATX_KERNELS")


def _resolve(raw: str | None) -> str | None:
    """Knob string -> None (fallback) | 'compiled' | 'interpret'."""
    if raw is None:
        raw = "auto"
    raw = raw.strip().lower()
    if raw in _OFF:
        return None
    if raw == "interpret":
        return "interpret" if pallas_available() else None
    if raw in _ON:
        return "compiled" if (_on_tpu() and pallas_available()) else None
    raise ValueError(
        f"unknown kernel knob value {raw!r}; expected 0/off, 1/on/auto, "
        "or interpret"
    )


def kernel_mode(name: str) -> str | None:
    """May kernel ``name`` replace its fallback in the current trace?

    Returns ``None`` (run the exact fallback lowering), ``"compiled"`` (TPU
    Pallas), or ``"interpret"`` (Pallas interpret mode — any backend).
    """
    forced = getattr(_FORCE, "mode", None)
    if forced is not None:
        override = forced.get(name, forced.get(None))
        if override is not None:
            return _resolve(override)
    return _resolve(_env_knob(name))


@contextlib.contextmanager
def force_kernels(mode: str, name: str | None = None):
    """Programmatic override of the env knobs while active (including during
    jit tracing): ``force_kernels("interpret")`` puts every kernel in
    interpret mode (the CPU parity-test path), ``force_kernels("off")``
    pins the fallback lowerings, ``force_kernels("on", "fused_adamw")``
    overrides one kernel only. Nests; inner wins for its keys."""
    prev = getattr(_FORCE, "mode", None)
    new = dict(prev or {})
    new[name] = mode
    _FORCE.mode = new
    try:
        yield
    finally:
        _FORCE.mode = prev


def kernel_status() -> list[dict[str, Any]]:
    """Registry snapshot: every registered kernel with its resolved mode
    under the current env/overrides (the `atx lint kernels` / docs
    surface)."""
    out = []
    for name, doc in sorted(_REGISTRY.items()):
        try:
            mode = kernel_mode(name)
        except ValueError as e:
            mode = f"error: {e}"
        out.append(
            {"kernel": name, "doc": doc, "mode": mode or "fallback"}
        )
    return out

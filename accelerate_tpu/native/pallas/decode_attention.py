"""Flash-decode attention over the slot KV cache.

Single query row per sequence (decode: T_new == 1) attending over the whole
cached prefix, split-K over the cache length with an online-softmax merge —
the FlashDecoding / PagedAttention-style kernel reduced to our static-shape
slot cache. Each (batch, kv-head) program walks the cache-length axis in
blocks, carrying running max / normalizer / accumulator in VMEM scratch, and
masks by the host-shipped length cursor so the padded slot tail never enters
the softmax.

The int8-KV variant dequantizes inside the kernel (``k * scale`` per cache
block) — that is the bandwidth win the kv16k bench measures: the fallback
lowering materializes the full bf16 dequant copy of a 16k-token cache before
a single attention flop, this kernel reads the int8 bytes once. When the
kernel takes the quantized operands the call site's dequantized copies are
dead and XLA drops them.

Parity vs `models.layers.dot_product_attention` is to tolerance, not bitwise:
the oracle computes one full-row softmax, this kernel merges per-block
partials (both in f32).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .dispatch import kernel_mode, pallas_available, register_kernel

register_kernel(
    "decode_attn",
    "single-query flash-decode over the slot KV cache (bf16 + int8-dequant)",
)

if pallas_available():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from ...ops.autotune import cached_pick_block, tuned_call_kwargs
    from ...ops.flash_attention import _NEG_INF

    def pick_block(dim, candidates=(512, 256, 128, 64, 32, 16, 8)):
        # Persisted autotune table first (ATX_BLOCK_DECODE_ATTENTION /
        # $ATX_AUTOTUNE_DIR), divide-exactly heuristic otherwise.
        return cached_pick_block("decode_attention", dim, candidates)
else:  # pragma: no cover - environment dependent
    pl = pltpu = None
    _NEG_INF = -1e30

    def pick_block(dim, candidates=(512, 256, 128, 64, 32, 16, 8)):
        return None


def _decode_kernel(
    len_ref,
    q_ref,
    k_ref,
    ks_ref,
    v_ref,
    vs_ref,
    o_ref,
    m_s,
    l_s,
    acc_s,
    *,
    scale: float,
    blk: int,
    n_blocks: int,
):
    """One (B, K) program; grid axis 2 walks the cache length (carried)."""
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    length = len_ref[0, 0]

    # Blocks entirely past the cursor contribute nothing — skip the flops
    # (this is where short sequences in a long-max_len cache win).
    @pl.when(t * blk < length)
    def _block():
        q = q_ref[0, 0].astype(jnp.float32)  # (group, h)
        k = k_ref[0, 0].astype(jnp.float32)  # (blk, h)
        if ks_ref is not None:
            k = k * ks_ref[0, 0].astype(jnp.float32)[:, None]
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )  # (group, blk)
        cols = t * blk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols < length, s, _NEG_INF)

        m_prev = m_s[...]  # (group, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # (group, blk)

        v = v_ref[0, 0].astype(jnp.float32)  # (blk, h)
        if vs_ref is not None:
            v = v * vs_ref[0, 0].astype(jnp.float32)[:, None]

        l_s[...] = l_s[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_s[...] = m_new

    @pl.when(t == n_blocks - 1)
    def _finish():
        o_ref[0, 0] = (acc_s[...] / jnp.maximum(l_s[...], 1e-30)).astype(o_ref.dtype)


def supported(q: jax.Array, k: jax.Array) -> bool:
    """Shape support: one query token per row, GQA-divisible heads, and a
    cache length some tile divides exactly (the kernel never pads)."""
    if q.ndim != 4 or k.ndim != 4 or q.shape[1] != 1:
        return False
    B, _, H, h = q.shape
    T, K = k.shape[1], k.shape[2]
    if k.shape[0] != B or k.shape[3] != h or H % K != 0:
        return False
    return pick_block(T) is not None


def flash_decode(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    lengths: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    scale: float | None = None,
    interpret: bool = False,
) -> jax.Array:
    """q: (B, 1, H, h); k/v: (B, T, K, h) cache buffers (bf16/f32, or int8
    with per-(token, head) ``*_scale`` of shape (B, T, K)); lengths: () or
    (B,) valid-prefix cursors. Returns (B, 1, H, h) in q's dtype."""
    B, S, H, h = q.shape
    if S != 1:
        raise ValueError(f"flash_decode is single-query only, got T_new={S}")
    T, K = k.shape[1], k.shape[2]
    group = H // K
    blk = pick_block(T)
    if blk is None:
        raise ValueError(f"no block tile divides cache length {T}")
    n_blocks = T // blk
    scale = scale if scale is not None else float(1.0 / (h**0.5))

    qt = q.reshape(B, K, group, h)  # head = kk * group + g, the oracle's layout
    kt = k.transpose(0, 2, 1, 3)  # (B, K, T, h)
    vt = v.transpose(0, 2, 1, 3)
    lengths = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32).reshape(-1, 1), (B, 1))

    qkv_specs = [
        pl.BlockSpec((1, 1, group, h), lambda b, kk, t: (b, kk, 0, 0)),
        pl.BlockSpec((1, 1, blk, h), lambda b, kk, t: (b, kk, t, 0)),
    ]
    scale_spec = pl.BlockSpec((1, 1, blk), lambda b, kk, t: (b, kk, t))
    len_spec = pl.BlockSpec((1, 1), lambda b, kk, t: (b, 0))

    operands = [lengths, qt, kt]
    in_specs = [len_spec, qkv_specs[0], qkv_specs[1]]
    if k_scale is not None:
        operands.append(k_scale.transpose(0, 2, 1))
        in_specs.append(scale_spec)
    operands.append(vt)
    in_specs.append(qkv_specs[1])
    if v_scale is not None:
        operands.append(v_scale.transpose(0, 2, 1))
        in_specs.append(scale_spec)

    kernel = functools.partial(
        _kernel_with_optionals,
        has_ks=k_scale is not None,
        has_vs=v_scale is not None,
        scale=scale,
        blk=blk,
        n_blocks=n_blocks,
    )
    out = pl.pallas_call(
        kernel,
        grid=(B, K, n_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, group, h), lambda b, kk, t: (b, kk, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, K, group, h), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, h), jnp.float32),
        ],
        **tuned_call_kwargs(interpret, ("parallel", "parallel", "arbitrary")),
    )(*operands)
    return out.reshape(B, 1, H, h)


def _kernel_with_optionals(len_ref, q_ref, k_ref, *rest, has_ks, has_vs, **kw):
    """Unpack the optional scale operands into the fixed-arity kernel."""
    rest = list(rest)
    ks_ref = rest.pop(0) if has_ks else None
    v_ref = rest.pop(0)
    vs_ref = rest.pop(0) if has_vs else None
    o_ref, m_s, l_s, acc_s = rest
    _decode_kernel(len_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref, m_s, l_s, acc_s, **kw)


def maybe_flash_decode(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    lengths: jax.Array,
    *,
    kv_raw=None,
    scale: float | None = None,
) -> jax.Array | None:
    """Dispatch entry: the kernel output when `decode_attn` is enabled and
    the shapes are supported, else ``None`` (caller runs the exact reference
    lowering). ``kv_raw = (k_q, k_scale, v_q, v_scale)`` hands over the raw
    int8 cache so dequant fuses into the kernel."""
    mode = kernel_mode("decode_attn")
    if mode is None or not supported(q, k):
        return None
    interpret = mode == "interpret"
    if kv_raw is not None:
        kq, ks, vq, vs = kv_raw
        return flash_decode(
            q, kq, vq, lengths, k_scale=ks, v_scale=vs, scale=scale, interpret=interpret
        )
    return flash_decode(q, k, v, lengths, scale=scale, interpret=interpret)

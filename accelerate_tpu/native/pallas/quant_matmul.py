"""Fused quantized matmul kernels for the int8 / fp8 paths.

Every projection in the model zoo funnels through `ops.fp8.matmul_einsum`,
and every equation it (and its `_grad_equations` transposes) emits is
matmul-shaped with the contracted labels a contiguous prefix or suffix of
each operand and ``out == a_rest + b_rest`` — so each one is a 2D matmul in
one of four orientations, reached by reshape (never a physical transpose).
`_parse_matmul_eq` proves that per equation; anything it can't prove falls
back to the reference lowering.

Two kernels share the tiling (grid over (M, N) tiles, contraction axis
resident per program):

- :func:`int8_matmul_fused` — the whole `ops.int8.int8_einsum` body in one
  pass: per-row dynamic activation quantization (amax/127), int8×int8→int32
  dot on the MXU, rescale by ``row scale × per-channel weight scale``.
  Integer accumulation is exact and the elementwise ops replicate
  `quantize_act` literally; the one divergence from the fallback is the
  activation-scale divide, which Pallas lowers with TPU semantics
  (reciprocal-multiply, 1 ulp off IEEE) — parity is ~1e-7 relative, not
  bitwise, and the quantize/rescale never round-trip through HBM.
- :func:`scaled_matmul` — the fp8 contraction `(dot(qx, qw) * scale)` with
  fp8 operands fed to the MXU directly (``preferred_element_type=f32``)
  instead of XLA's materialized upcast (the flat 1.004
  ``fp8_matmul_speedup``). Quantization stays OUTSIDE (the custom_vjp
  residuals carry qx/qw for the backward); parity is to f32 tolerance
  (different accumulation order), not bitwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .dispatch import kernel_mode, pallas_available, register_kernel

register_kernel(
    "int8_matmul", "fused per-row quantize -> int8 MXU dot -> rescale"
)
register_kernel(
    "fp8_matmul", "fp8 dot + scalar rescale without the XLA upcast round-trip"
)

if pallas_available():
    from jax.experimental import pallas as pl

    from ...ops.autotune import cached_pick_block, tuned_call_kwargs

    def pick_block(dim, candidates=(512, 256, 128, 64, 32, 16, 8)):
        # Persisted autotune table first (ATX_BLOCK_QUANT_MATMUL /
        # $ATX_AUTOTUNE_DIR), divide-exactly heuristic otherwise.
        return cached_pick_block("quant_matmul", dim, candidates)
else:  # pragma: no cover - environment dependent
    pl = None

    def pick_block(dim, candidates=(512, 256, 128, 64, 32, 16, 8)):
        return None


# Contraction axes larger than this would blow the resident-operand VMEM
# budget per program; such shapes (none in the model zoo today) fall back.
_MAX_CONTRACT = 65536


def _parse_matmul_eq(eq: str):
    """Prove ``eq`` is a pure matmul: returns ``(oa, ob, a_rest, b_rest)``
    with orientations in {"lead", "trail"} (contracted labels at the front
    or back of the operand, same order in both), or ``None``."""
    if "->" not in eq or "." in eq:
        return None
    lhs, out = eq.split("->")
    if "," not in lhs:
        return None
    a, b = lhs.split(",")
    contracted = "".join(c for c in a if c in b)
    if not contracted or any(c in out for c in contracted):
        return None  # no contraction, or shared batch labels: not this kernel
    if "".join(c for c in b if c in a) != contracted:
        return None  # contracted labels must appear in the same order
    a_rest = "".join(c for c in a if c not in contracted)
    b_rest = "".join(c for c in b if c not in contracted)
    if a_rest + b_rest != out or not a_rest or not b_rest:
        return None
    if a.startswith(contracted):
        oa = "lead"
    elif a.endswith(contracted):
        oa = "trail"
    else:
        return None
    if b.startswith(contracted):
        ob = "lead"
    elif b.endswith(contracted):
        ob = "trail"
    else:
        return None
    return oa, ob, len(a_rest), len(b_rest)


def _plan(eq: str, a_shape, b_shape):
    """2D views + tiles for ``eq``: ``(oa, ob, M, N, C, bm, bn, out_shape)``
    or ``None`` when unsupported."""
    parsed = _parse_matmul_eq(eq)
    if parsed is None:
        return None
    oa, ob, na, nb = parsed
    a_rest = a_shape[:na] if oa == "trail" else a_shape[-na:]
    b_rest = b_shape[-nb:] if ob == "lead" else b_shape[:nb]
    c_dims = a_shape[na:] if oa == "trail" else a_shape[: len(a_shape) - na]
    M = int(functools.reduce(lambda x, y: x * y, a_rest, 1))
    N = int(functools.reduce(lambda x, y: x * y, b_rest, 1))
    C = int(functools.reduce(lambda x, y: x * y, c_dims, 1))
    if M == 0 or N == 0 or C == 0 or C > _MAX_CONTRACT:
        return None
    bm = pick_block(M) or (M if M <= 1024 else None)
    bn = pick_block(N) or (N if N <= 1024 else None)
    if bm is None or bn is None:
        return None
    return oa, ob, M, N, C, bm, bn, tuple(a_rest) + tuple(b_rest)


def _views(oa, ob, a, b, M, N, C):
    a2 = a.reshape(M, C) if oa == "trail" else a.reshape(C, M)
    b2 = b.reshape(C, N) if ob == "lead" else b.reshape(N, C)
    return a2, b2


def _specs(oa, ob, bm, bn, C):
    if oa == "trail":
        a_spec = pl.BlockSpec((bm, C), lambda i, j: (i, 0))
    else:
        a_spec = pl.BlockSpec((C, bm), lambda i, j: (0, i))
    if ob == "lead":
        b_spec = pl.BlockSpec((C, bn), lambda i, j: (0, j))
    else:
        b_spec = pl.BlockSpec((bn, C), lambda i, j: (j, 0))
    return a_spec, b_spec


def _dot_dims(oa, ob):
    ca = 1 if oa == "trail" else 0
    cb = 0 if ob == "lead" else 1
    return (((ca,), (cb,)), ((), ()))


def _int8_kernel(a_ref, b_ref, ws_ref, o_ref, *, dims):
    # `quantize_act` verbatim, per (bm) row block, then an exact integer
    # dot; only the scale divide (TPU reciprocal semantics) can differ
    # from the fallback, by 1 ulp.
    xf = a_ref[...].astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
    sx = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(q, b_ref[...], dims, preferred_element_type=jnp.int32)
    o_ref[...] = (acc.astype(jnp.float32) * (sx * ws_ref[...])).astype(o_ref.dtype)


def _scaled_kernel(a_ref, b_ref, s_ref, o_ref, *, dims):
    acc = jax.lax.dot_general(
        a_ref[...], b_ref[...], dims, preferred_element_type=jnp.float32
    )
    o_ref[...] = (acc * s_ref[0, 0]).astype(o_ref.dtype)


def int8_matmul_fused(
    eq: str,
    x: jax.Array,
    wq: jax.Array,
    w_scale: jax.Array,
    *,
    interpret: bool = False,
) -> jax.Array | None:
    """Fused `ops.int8.int8_einsum`: quantize rows of ``x``, int8 dot with
    ``wq``, rescale by ``row scale × w_scale``. Requires x contracted on its
    trailing axes (per-row groups = rows of the 2D view) and w on its
    leading axes — true for every int8 forward equation. ``None`` when the
    equation/shapes aren't supported (caller falls back)."""
    plan = _plan(eq, x.shape, wq.shape)
    if plan is None:
        return None
    oa, ob, M, N, C, bm, bn, out_shape = plan
    if oa != "trail" or ob != "lead":
        return None
    x2, w2 = _views(oa, ob, x, wq, M, N, C)
    # Contracted axes of w_scale are size 1 (quantizer keepdims): the value
    # layout is exactly the per-output-channel vector.
    ws2 = w_scale.astype(jnp.float32).reshape(1, N)
    a_spec, b_spec = _specs(oa, ob, bm, bn, C)
    out = pl.pallas_call(
        functools.partial(_int8_kernel, dims=_dot_dims(oa, ob)),
        grid=(M // bm, N // bn),
        in_specs=[
            a_spec,
            b_spec,
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        **tuned_call_kwargs(interpret, ("parallel", "parallel")),
    )(x2, w2, ws2)
    return out.reshape(out_shape)


def scaled_matmul(
    eq: str,
    qa: jax.Array,
    qb: jax.Array,
    scale: jax.Array,
    out_dtype,
    *,
    interpret: bool = False,
) -> jax.Array | None:
    """``(einsum(eq, qa, qb, preferred_element_type=f32) * scale).astype``
    as one kernel — the fp8 forward/backward contraction without the
    materialized upcast. ``scale`` is the scalar product of the per-tensor
    scales. ``None`` when unsupported."""
    plan = _plan(eq, qa.shape, qb.shape)
    if plan is None:
        return None
    oa, ob, M, N, C, bm, bn, out_shape = plan
    a2, b2 = _views(oa, ob, qa, qb, M, N, C)
    s2 = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    a_spec, b_spec = _specs(oa, ob, bm, bn, C)
    out = pl.pallas_call(
        functools.partial(_scaled_kernel, dims=_dot_dims(oa, ob)),
        grid=(M // bm, N // bn),
        in_specs=[
            a_spec,
            b_spec,
            pl.BlockSpec((1, 1), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        **tuned_call_kwargs(interpret, ("parallel", "parallel")),
    )(a2, b2, s2)
    return out.reshape(out_shape)


def maybe_int8_matmul(
    eq: str, x: jax.Array, wq: jax.Array, w_scale: jax.Array
) -> jax.Array | None:
    """Dispatch entry for `ops.int8.int8_einsum`."""
    mode = kernel_mode("int8_matmul")
    if mode is None:
        return None
    return int8_matmul_fused(eq, x, wq, w_scale, interpret=mode == "interpret")


def maybe_scaled_matmul(
    eq: str, qa: jax.Array, qb: jax.Array, scale: jax.Array, out_dtype
) -> jax.Array | None:
    """Dispatch entry for the fp8 forward/backward contractions."""
    mode = kernel_mode("fp8_matmul")
    if mode is None:
        return None
    return scaled_matmul(eq, qa, qb, scale, out_dtype, interpret=mode == "interpret")

"""torch interop: accept torch Datasets / DataLoaders at the prepare boundary.

The reference's entire data surface is `torch.utils.data` — its users hand
`Accelerator.prepare` a torch DataLoader and get a wrapped one back
(reference `prepare_data_loader`, `data_loader.py:988`). Migrating code
should not have to rewrite its dataset plumbing first, so:

- a torch **Dataset** (map-style `__len__`/`__getitem__`) works directly as
  this framework's sized dataset; samples are converted tensor->numpy at
  collate time;
- a torch **DataLoader** is unwrapped: its dataset, batch size, drop_last,
  and collate_fn carry over, and the framework's own sharding/shuffling
  replaces the torch sampler (exactly what the reference does — it swaps
  the sampler for its sharded one, keeping the dataset).

torch is an optional dependency: everything here degrades to no-ops when it
is not importable.
"""

from __future__ import annotations

from typing import Any

import numpy as np


def _torch():
    try:
        import torch

        return torch
    except ImportError:  # pragma: no cover - torch is baked into CI images
        return None


def is_torch_dataloader(obj: Any) -> bool:
    torch = _torch()
    return torch is not None and isinstance(obj, torch.utils.data.DataLoader)


def to_numpy(obj: Any) -> Any:
    """Recursively convert torch tensors to numpy (CPU) in a sample pytree."""
    torch = _torch()
    if torch is not None and isinstance(obj, torch.Tensor):
        t = obj.detach().cpu()
        # numpy has no bf16 (or fp8) dtype — upcast rather than crash a
        # migrating pipeline at the prepare boundary; the loader's device put
        # re-casts per the precision policy anyway.
        if t.dtype == torch.bfloat16 or (
            hasattr(torch, "float8_e4m3fn") and "float8" in str(t.dtype)
        ):
            t = t.float()
        return t.numpy()
    if isinstance(obj, dict):
        return {k: to_numpy(v) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        return type(obj)(*[to_numpy(v) for v in obj])
    if isinstance(obj, (list, tuple)):
        return type(obj)(to_numpy(v) for v in obj)
    return obj


class TorchDatasetAdapter:
    """Sized view over a torch map-style dataset.

    ``convert=True`` hands out numpy samples (for the framework's default
    collate); ``convert=False`` hands out the raw torch samples (a kept
    user collate expects tensors — only its OUTPUT is converted)."""

    def __init__(self, dataset: Any, convert: bool = True) -> None:
        self.dataset = dataset
        self.convert = convert

    def __len__(self) -> int:
        return len(self.dataset)

    def __getitem__(self, i: int) -> Any:
        sample = self.dataset[int(i)]
        return to_numpy(sample) if self.convert else sample


class TorchIterableAdapter:
    """Iterable view over a torch IterableDataset with numpy samples (the
    framework loader's iterable path batches it).

    Stateful streams (the torchdata `Stateful` protocol — `state_dict` /
    `load_state_dict` on the dataset, reference `data_loader.py:413-497`)
    are proxied through, so the framework loader checkpoints the stream
    position natively instead of replay-skipping."""

    def __init__(self, dataset: Any) -> None:
        self.dataset = dataset

    def __iter__(self):
        for sample in self.dataset:
            yield to_numpy(sample)

    def __getattr__(self, name: str):
        if name in ("state_dict", "load_state_dict") and hasattr(
            self.dataset, name
        ):
            return getattr(self.dataset, name)
        raise AttributeError(name)


def unwrap_torch_dataloader(loader: Any, *, has_user_collate: bool = False) -> dict[str, Any]:
    """Extract (dataset, batch_size, drop_last, shuffle, collate_fn) from a
    torch DataLoader so the framework loader can replace it wholesale.

    Shuffle intent is inferred from the sampler type (SequentialSampler ->
    False, RandomSampler -> True; anything else warns and asks for an
    explicit ``shuffle=``); the torch sampler itself is NOT carried over —
    cross-process sharding needs the framework's deterministic seeded
    sampler, the same substitution the reference performs.

    ``has_user_collate``: the caller supplies their own collate to the
    framework loader — samples are then handed out RAW (torch tensors),
    and the caller's collate output is converted by the accelerator.
    """
    import warnings

    torch = _torch()
    is_iterable = torch is not None and isinstance(
        loader.dataset, torch.utils.data.IterableDataset
    )
    sampler = getattr(loader, "sampler", None)
    shuffle = None
    # Iterable datasets have no sampler intent to infer (torch installs an
    # internal infinite sampler); ordering is the stream's own.
    if torch is not None and sampler is not None and not is_iterable:
        if isinstance(sampler, torch.utils.data.RandomSampler):
            shuffle = True
        elif isinstance(sampler, torch.utils.data.SequentialSampler):
            shuffle = False
        else:
            warnings.warn(
                f"Cannot infer shuffle intent from torch sampler "
                f"{type(sampler).__name__}; the sampler is replaced by the "
                "framework's sharded seeded sampler — pass shuffle= "
                "explicitly to prepare_data_loader.",
                stacklevel=3,
            )
    if loader.batch_size is None:
        raise ValueError(
            "This torch DataLoader has no batch_size (batch_sampler= or "
            "batch_size=None): its batching logic cannot carry over — pass "
            "the dataset and an explicit batch_size to prepare_data_loader."
        )

    collate = getattr(loader, "collate_fn", None)
    # torch's default_collate stacks into torch tensors; the framework's
    # numpy collate replaces it. A torch-side USER collate is kept, wrapped
    # with tensor->numpy conversion on its output.
    is_default = torch is not None and collate is torch.utils.data.default_collate

    wrapped_collate = None
    if collate is not None and not is_default and not has_user_collate:
        def wrapped_collate(samples, _c=collate):
            return to_numpy(_c(samples))

    # Carry the torch generator seed into the framework sampler so a
    # migrated run stays deterministic in the seed the user chose (the
    # *order* still differs — numpy PCG64 vs torch's Philox — which is the
    # same substitution the reference performs with its seeded sampler).
    seed = None
    gen = getattr(loader, "generator", None) or getattr(sampler, "generator", None)
    if gen is not None:
        try:
            seed = int(gen.initial_seed()) & 0x7FFFFFFF
        except Exception:
            seed = None

    raw_samples = wrapped_collate is not None or has_user_collate
    if is_iterable:
        dataset: Any = (
            loader.dataset if raw_samples else TorchIterableAdapter(loader.dataset)
        )
    else:
        dataset = TorchDatasetAdapter(loader.dataset, convert=not raw_samples)
    return {
        "dataset": dataset,
        "batch_size": loader.batch_size,
        "drop_last": bool(getattr(loader, "drop_last", False)),
        "shuffle": shuffle,
        "collate_fn": wrapped_collate,
        "seed": seed,
    }

"""Host-sharded, prefetching device data loader.

TPU-native redesign of the reference's wrapped loaders (`data_loader.py` —
`DataLoaderShard` :499, `DataLoaderDispatcher` :696, `MpDeviceLoaderWrapper`
:646, `prepare_data_loader` :988, `skip_first_batches` :1349). Key shift: the
reference hands each process a *local* batch and lets collectives stitch
results; here every step consumes one **global sharded `jax.Array`** formed
with `jax.make_array_from_callback`, so each process only materializes the
rows its local devices own, and the jitted SPMD step sees the whole batch.

Features carried over:
- deterministic seeded shuffling, re-seeded per epoch (`SeedableSampler`);
- shard vs dispatch semantics (`dispatch_batches`), `split_batches`,
  `even_batches` wraparound, `drop_last`;
- one-batch-ahead iteration so `end_of_dataloader`/`remainder` are visible to
  `gather_for_metrics` (reference `DataLoaderStateMixin`, :364-405);
- `skip_first_batches` + `state_dict()`/`load_state_dict` for mid-epoch
  resume (reference :1349-1425 and stateful-dataloader support :413-497);
- background device prefetch (the `MpDeviceLoader` analog, :646-693).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel.mesh import BATCH_AXES, batch_sharding, data_parallel_size
from ..state import GradientState, ProcessState
from ..utils.dataclasses import DataLoaderConfiguration
from .sampler import SeedableSampler, batch_indices, sharded_length

_SENTINEL = object()


def default_collate(samples: Sequence[Any]) -> Any:
    """Stack a list of samples into a batch pytree of numpy arrays."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples]) for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


def _leaf_sharding(mesh: Mesh, spec: PartitionSpec | None) -> NamedSharding:
    return NamedSharding(mesh, spec if spec is not None else PartitionSpec(BATCH_AXES))


def _form_global_batch(batch: Any, mesh: Mesh, spec: PartitionSpec | None = None) -> Any:
    """Turn a host batch pytree (full global content on this process) into
    global sharded arrays. Every process must pass identically-shaped data;
    only locally-owned blocks are transferred."""
    sharding_cache: dict[tuple, NamedSharding] = {}

    def to_global(x: np.ndarray) -> jax.Array:
        x = np.asarray(x)
        sh = sharding_cache.setdefault((), _leaf_sharding(mesh, spec))
        return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

    return jax.tree.map(to_global, batch)


class DataLoader:
    """Iterates global sharded batches over the mesh.

    ``batch_size`` follows the reference contract (`prepare_data_loader`,
    `data_loader.py:988`): it is the *per-process* batch size when
    ``split_batches=False`` (observed global batch = batch_size × world) and
    the *global* batch size when ``split_batches=True``.

    ``dataset`` may be: a sized indexable (``__len__``/``__getitem__``), or
    any iterable of samples (the `IterableDataset` path). Samples are
    collated with ``collate_fn`` (default: numpy stacking of dict/tuple
    leaves).

    With ``even_batches=False`` batches stay host-local numpy (ragged tails
    cannot form a uniform global array); use for eval loops that gather
    objects.
    """

    def __init__(
        self,
        dataset: Any,
        batch_size: int = 1,
        *,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = False,
        collate_fn: Callable[[Sequence[Any]], Any] | None = None,
        mesh: Mesh | None = None,
        spec: PartitionSpec | None = None,
        config: DataLoaderConfiguration | None = None,
        skip_batches: int = 0,
    ) -> None:
        if mesh is None:
            from ..state import AcceleratorState

            mesh = AcceleratorState().mesh
        self.dataset = dataset
        self.mesh = mesh
        self.spec = spec
        self.config = config or DataLoaderConfiguration()
        self.collate_fn = collate_fn or default_collate
        self.drop_last = drop_last
        self.skip_batches = skip_batches
        self.gradient_state = GradientState()
        self.state = ProcessState()

        self.batch_size = batch_size
        self._sized = hasattr(dataset, "__len__") and hasattr(dataset, "__getitem__")
        self.sampler = (
            SeedableSampler(len(dataset), shuffle=shuffle, seed=seed) if self._sized else None
        )
        self._epoch = 0
        self._batches_yielded = 0
        # batch index -> stream state for stateful iterable datasets (kept
        # only for the window the prefetch thread can run ahead).
        self._dataset_states: dict[int, Any] = {}
        self._stateful_resume_offset = 0
        self.end_of_dataloader = False
        self._rebind(mesh, self.config)

    def _rebind(self, mesh: Mesh, config: DataLoaderConfiguration) -> None:
        """(Re)derive mesh/config-dependent sizing. Called from __init__ and
        again by `Accelerator.prepare` when it swaps in its own mesh/config —
        total_batch_size and remainder must track the *final* topology."""
        self.mesh = mesh
        self.config = config
        dp = data_parallel_size(mesh)
        if config.split_batches:
            if self.batch_size % dp != 0:
                raise ValueError(
                    f"split_batches=True requires batch_size ({self.batch_size}) divisible "
                    f"by the data-parallel world size ({dp})"
                )
            self.total_batch_size = self.batch_size
        else:
            self.total_batch_size = self.batch_size * dp
        # Reference `DataLoaderStateMixin` fields (data_loader.py:364-405).
        # remainder only exists when the wraparound duplicates samples — with
        # drop_last the tail is dropped, nothing is duplicated, and
        # gather_for_metrics must not trim (reference data_loader.py:396-399).
        self.remainder = -1
        if self._sized and not self.drop_last:
            self.remainder = len(self.dataset) % self.total_batch_size

    # ----------------------------------------------------------------- sizing
    def __len__(self) -> int:
        if not self._sized:
            raise TypeError("Length of an iterable-dataset loader is unknown")
        n = len(self.dataset)
        total = n // self.total_batch_size if self.drop_last else -(-n // self.total_batch_size)
        return max(total - self.skip_batches, 0)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        if self.sampler is not None:
            self.sampler.set_epoch(epoch)
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    # ------------------------------------------------------------- iteration
    def _global_index_batches(self) -> Iterator[list[int]]:
        """Global batch index lists with even_batches wraparound.

        Equivalent to the union over processes of the reference's
        `BatchSamplerShard` outputs (`data/sampler.py` holds the per-process
        math and its spec tests); forming the *global* batch directly gives
        the same sample->step mapping.
        """
        raw = batch_indices(iter(self.sampler), self.total_batch_size, self.drop_last)
        first: list[int] | None = None
        for batch in raw:
            if first is None:
                first = list(batch)
            if len(batch) == self.total_batch_size:
                yield batch
            elif not self.drop_last:
                if self.config.even_batches:
                    fill = list(first)
                    while len(fill) < self.total_batch_size:
                        fill += fill
                    yield (batch + fill)[: self.total_batch_size]
                else:
                    yield batch  # ragged tail, host-local mode

    def _host_batches(self) -> Iterator[Any]:
        """Collated host batches containing the full global content."""
        if self._sized:
            dispatch = bool(self.config.dispatch_batches)
            # Array-backed datasets collate as one native row-gather per leaf
            # (accelerate_tpu.native) instead of a Python sample loop — only
            # when the default collate would do the equivalent stacking.
            fast_gather = (
                hasattr(self.dataset, "gather_batch")
                and self.collate_fn is default_collate
            )
            for idx_batch in self._global_index_batches():
                if dispatch and not self.state.is_main_process:
                    collated = None
                elif fast_gather:
                    collated = self.dataset.gather_batch(idx_batch)
                else:
                    samples = [self.dataset[i] for i in idx_batch]
                    collated = self.collate_fn(samples)
                if dispatch and self.state.num_processes > 1:
                    from ..ops.collectives import broadcast_object_list

                    collated = broadcast_object_list([collated])[0]
                yield collated
        else:
            yield from self._iterable_host_batches()

    def _iterable_collated(self) -> Iterator[Any]:
        """Collated batches straight off the iterable dataset's stream.

        Stateful streams (``dataset.state_dict`` — the torchdata protocol,
        reference `data_loader.py:413-497`): the state is snapshotted at
        every batch boundary, keyed by the batch index it resumes AT, so a
        checkpoint taken while the prefetch thread runs ahead still pairs
        the consumer-visible position with the right stream state."""
        stateful = hasattr(self.dataset, "state_dict")
        # A stateful resume continues mid-stream: batch indices (and the
        # states recorded under them) continue from the restored offset so
        # they stay aligned with `_batches_yielded`.
        produced = self._stateful_resume_offset
        buf: list[Any] = []
        first: list[Any] | None = None
        if stateful:
            self._record_dataset_state(produced)
        it = iter(self.dataset)
        for element in it:
            buf.append(element)
            if len(buf) == self.total_batch_size:
                if first is None:
                    first = list(buf)
                yield self.collate_fn(buf)
                buf = []
                produced += 1
                if stateful:
                    self._record_dataset_state(produced)
        if buf and not self.drop_last:
            if first is None:
                first = list(buf)
            if self.config.even_batches:
                while len(buf) < self.total_batch_size:
                    buf += first
                yield self.collate_fn(buf[: self.total_batch_size])
            else:
                yield self.collate_fn(buf)

    def _iterable_host_batches(self) -> Iterator[Any]:
        """Iterable-dataset path with the reference's dispatch default.

        ``dispatch_batches=None`` resolves to **True** here (reference
        `data_loader.py:1085-1089`): per-process iterable streams can
        diverge (network readers, unseeded generators), and divergent
        streams silently produce inconsistent global arrays in shard mode.
        Under dispatch, only the main process consumes the stream and
        broadcasts each batch (with an end-of-stream signal, since workers
        cannot know the length).

        Explicit ``dispatch_batches=False`` keeps shard mode — every process
        must then iterate an IDENTICAL stream; with ``ATX_DEBUG_MODE=1`` the
        first batch's content digest is compared across processes to catch
        divergence loudly.
        """
        dispatch = self.config.dispatch_batches
        if dispatch is None:
            dispatch = True
        it = self._iterable_collated()
        if dispatch and self.state.num_processes > 1:
            from ..ops.collectives import broadcast_object_list

            # Message protocol: ("batch", b) per batch, then exactly one
            # terminal ("end", None) on clean exhaustion or ("error", repr)
            # when the main rank's stream raises mid-epoch (workers re-raise,
            # keeping all ranks convergent instead of silently finishing a
            # failed epoch). An early consumer `break` is SPMD-symmetric —
            # every rank stops consuming at the same step, so no terminal
            # message is sent (a sentinel then would itself be the unmatched
            # collective).
            if self.state.is_main_process:
                try:
                    for collated in it:
                        broadcast_object_list([("batch", collated)])
                        yield collated
                except GeneratorExit:
                    raise
                except BaseException as e:
                    broadcast_object_list([("error", repr(e))])
                    raise
                else:
                    broadcast_object_list([("end", None)])
            else:
                while True:
                    kind, payload = broadcast_object_list([None])[0]
                    if kind == "end":
                        return
                    if kind == "error":
                        raise RuntimeError(
                            f"Main process's iterable dataset stream failed "
                            f"mid-epoch: {payload}"
                        )
                    yield payload
            return
        if dispatch or self.state.num_processes == 1 or not self.state.debug:
            yield from it
            return
        # Debug shard mode: digest-compare the first batch on EVERY rank —
        # including ranks whose divergent stream yields nothing (an empty
        # digest is itself a divergence the collective check must see, not a
        # silent skip that would deadlock the other ranks' gather).
        first = next(it, _SENTINEL)
        self._verify_shard_stream(None if first is _SENTINEL else first)
        if first is _SENTINEL:
            return
        yield first
        yield from it

    def _verify_shard_stream(self, collated: Any) -> None:
        """Debug-mode digest check: shard-mode iterable streams must agree.
        ``collated=None`` means this rank's stream was empty — still a digest
        (streams of different lengths diverge too)."""
        import hashlib

        from ..ops.collectives import DistributedOperationException, gather_object

        if collated is None:
            digest = "<empty stream>"
        else:
            md5 = hashlib.md5()
            for leaf in jax.tree.leaves(collated):
                md5.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
            digest = md5.hexdigest()
        digests = gather_object([digest])
        if len(set(digests)) > 1:
            raise DistributedOperationException(
                "Iterable dataset streams DIVERGE across processes in shard "
                f"mode (first-batch digests: {digests}). Every process must "
                "iterate an identical stream when dispatch_batches=False; "
                "seed the stream identically, or drop the flag to use the "
                "default dispatch mode (main process reads, others receive)."
            )

    def _device_batches(self) -> Iterator[Any]:
        for i, host_batch in enumerate(self._host_batches()):
            if i < self.skip_batches:
                continue
            from ..ops.collectives import find_batch_size

            if self.config.even_batches or find_batch_size(host_batch) == self.total_batch_size:
                yield _form_global_batch(host_batch, self.mesh, self.spec)
            else:
                yield host_batch  # ragged tail stays on host

    def _prefetched(self, it: Iterator[Any], stop: threading.Event) -> Iterator[Any]:
        q: queue.Queue = queue.Queue(maxsize=max(1, self.config.prefetch_size))
        err: list[BaseException] = []

        def put(item: Any) -> bool:
            # Bounded put that gives up when the consumer abandoned iteration,
            # so an early `break` can't strand the worker blocked on a full
            # queue (pinning the dataset iterator forever).
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker() -> None:
            try:
                for item in it:
                    if not put(item):
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                put(_SENTINEL)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()
            t.join(timeout=5.0)

    def __iter__(self) -> Iterator[Any]:
        self.begin()
        # Position within the epoch includes batches skipped on resume, so a
        # checkpoint taken later in the resumed epoch records the true offset.
        self._batches_yielded = self.skip_batches + self._stateful_resume_offset
        stop = threading.Event()
        it = self._device_batches()
        if self.config.prefetch_size > 0:
            it = self._prefetched(it, stop)
        try:
            # One-batch-ahead so the consumer can observe end_of_dataloader
            # while handling the final batch (reference :557).
            try:
                current = next(it)
            except StopIteration:
                self.end_of_dataloader = True
                if self.skip_batches or self._stateful_resume_offset:
                    # A resume that landed exactly on the epoch boundary
                    # (batches_yielded == total at save time) consumes the
                    # whole offset here — replay-skip AND native stateful
                    # resumes alike. Advance to the next epoch start;
                    # without this, the stale offset would suppress every
                    # subsequent epoch's batches too.
                    self._advance_epoch()
                return
            for upcoming in it:
                self.end_of_dataloader = False
                # Count before handing out: a checkpoint taken while the
                # consumer holds this batch must skip it on resume.
                self._batches_yielded += 1
                yield current
                current = upcoming
            self.end_of_dataloader = True
            self._batches_yielded += 1
            yield current
            self._advance_epoch()
        finally:
            # Runs on normal exhaustion AND on early break/GC (GeneratorExit):
            # unregister from GradientState and release the prefetch worker.
            stop.set()
            if hasattr(it, "close"):
                it.close()
            self.end()

    def _advance_epoch(self) -> None:
        """Move the position to "start of the next epoch". The consumed count
        zeroes WITH the epoch bump (a checkpoint taken after a completed epoch
        must not pair the new epoch with the old epoch's batch count, or
        resume would skip a full epoch of data); any mid-epoch resume offset
        applied only to the epoch that just ended."""
        self._epoch += 1
        self._batches_yielded = 0
        self.skip_batches = 0
        self._stateful_resume_offset = 0
        self._dataset_states.clear()
        if self.sampler is not None:
            self.sampler.set_epoch(self._epoch)

    # ------------------------------------------------------ GradientState glue
    def begin(self) -> None:
        self.end_of_dataloader = False
        self.gradient_state._add_dataloader(self)

    def end(self) -> None:
        self.gradient_state._remove_dataloader(self)

    # ---------------------------------------------------------------- resume
    def _record_dataset_state(self, batch_idx: int) -> None:
        self._dataset_states[batch_idx] = self.dataset.state_dict()
        # Keep only the lookahead window the prefetch thread can create.
        horizon = batch_idx - (self.config.prefetch_size + 2)
        for k in [k for k in self._dataset_states if k < horizon]:
            del self._dataset_states[k]

    def state_dict(self) -> dict[str, Any]:
        state: dict[str, Any] = {
            "epoch": self._epoch,
            "batches_yielded": self._batches_yielded,
            "seed": getattr(self.sampler, "seed", None),
        }
        ds_state = self._dataset_states.get(self._batches_yielded)
        if ds_state is not None:
            # The stream's own position (torchdata Stateful protocol,
            # reference `data_loader.py:413-497`). JSON when the state allows
            # it (typically a small position dict) — restoring JSON can never
            # execute code; pickle only for states JSON can't express, and
            # restoring THOSE requires an explicit opt-in (below).
            import json as _json

            try:
                encoded = _json.loads(_json.dumps(ds_state))
                # JSON must round-trip LOSSLESSLY or the dataset gets back a
                # different state than it saved (tuples->lists, int dict
                # keys->strings — json coerces those without erroring).
                if encoded != ds_state:
                    raise TypeError("dataset state not JSON-lossless")
                state["dataset"] = {"encoding": "json", "value": encoded}
            except (TypeError, ValueError):
                import base64
                import pickle

                state["dataset"] = {
                    "encoding": "pickle",
                    "value": base64.b64encode(pickle.dumps(ds_state)).decode(),
                }
        return state

    def load_state_dict(self, state: dict[str, Any]) -> None:
        self._epoch = int(state.get("epoch", 0))
        ds_state = state.get("dataset")
        if ds_state is not None and hasattr(self.dataset, "load_state_dict"):
            if isinstance(ds_state, dict) and ds_state.get("encoding") == "json":
                restored = ds_state["value"]
            else:
                # Legacy raw base64 string, or the explicit pickle encoding:
                # unpickling executes arbitrary code, so an untrusted
                # checkpoint must not reach it by default (torch.load's
                # historical threat model, avoided here for JSON states).
                import os as _os

                if not _os.environ.get("ATX_ALLOW_PICKLED_DATASET_STATE"):
                    raise ValueError(
                        "This checkpoint stores the dataset stream state as "
                        "a pickle, which executes code on load. If you trust "
                        "the checkpoint's origin, set "
                        "ATX_ALLOW_PICKLED_DATASET_STATE=1 to restore it."
                    )
                import base64
                import pickle

                payload = (
                    ds_state["value"] if isinstance(ds_state, dict) else ds_state
                )
                restored = pickle.loads(base64.b64decode(payload))
            self.dataset.load_state_dict(restored)
            # Position restored NATIVELY in the stream — replay-skipping on
            # top of it would drop batches twice.
            self.skip_batches = 0
            self._stateful_resume_offset = int(state.get("batches_yielded", 0))
            # A checkpoint taken right after restore (before any iteration)
            # must reproduce THIS position, not report batch 0 of a fresh
            # epoch — seed the bookkeeping as if we had just yielded here.
            self._batches_yielded = self._stateful_resume_offset
            self._dataset_states = {self._stateful_resume_offset: restored}
        else:
            self.skip_batches = int(state.get("batches_yielded", 0))
            # A stale offset from a PRIOR stateful resume would double-count
            # positions under this replay-skip restore.
            self._stateful_resume_offset = 0
            self._dataset_states.clear()
        if self.sampler is not None:
            self.sampler.set_epoch(self._epoch)


def prepare_data_loader(
    dataset: Any,
    batch_size: int = 1,
    *,
    mesh: Mesh | None = None,
    shuffle: bool = False,
    seed: int = 0,
    drop_last: bool = False,
    collate_fn: Callable | None = None,
    config: DataLoaderConfiguration | None = None,
    spec: PartitionSpec | None = None,
) -> DataLoader:
    """Functional entry (reference `prepare_data_loader`, `data_loader.py:988`)."""
    return DataLoader(
        dataset,
        batch_size,
        shuffle=shuffle,
        seed=seed,
        drop_last=drop_last,
        collate_fn=collate_fn,
        mesh=mesh,
        spec=spec,
        config=config,
    )


def skip_first_batches(dataloader: DataLoader, num_batches: int = 0) -> DataLoader:
    """Mid-epoch resume helper (reference `skip_first_batches`,
    `data_loader.py:1349`): returns a NEW loader over the same dataset that
    skips ``num_batches``. The argument is left untouched (the reference also
    constructs a fresh dataloader — callers may keep iterating the original
    without silently losing batches)."""
    import copy

    new = copy.copy(dataloader)
    if dataloader.sampler is not None:
        new.sampler = copy.copy(dataloader.sampler)
    new.skip_batches = num_batches
    new._batches_yielded = 0
    new._dataset_states = dict(dataloader._dataset_states)
    new.end_of_dataloader = False
    return new

"""Deterministic sampling & cross-process batch-sharding index math.

Behavioral spec from the reference (`data_loader.py` — `SeedableRandomSampler`
:72, `BatchSamplerShard` :109-262, `IterableDatasetShard` :265-364), re-built
as pure generators over index lists (no torch sampler classes):

- every process always sees the same number of batches, all of equal size,
  unless ``even_batches=False``;
- with ``even_batches=True`` the tail is completed by cycling samples from the
  *beginning* of the epoch (the reference's wraparound contract);
- ``split_batches=True`` slices each global batch into per-process pieces
  instead of handing out alternating full batches.

These generators are the single source of truth for which sample lands on
which process at which step — the device loader (`data/loader.py`) only
materializes them.
"""

from __future__ import annotations

import math
from typing import Any, Iterable, Iterator, Sequence

import numpy as np


class SeedableSampler:
    """Deterministic (optionally shuffled) index stream, re-seeded per epoch.

    Reference `SeedableRandomSampler` (`data_loader.py:72`): identical
    permutations on every process for a given (seed, epoch) pair, so shards
    are disjoint by construction.
    """

    def __init__(
        self,
        num_samples: int,
        shuffle: bool = True,
        seed: int = 0,
        backend: str = "numpy",
    ) -> None:
        """``backend="native"`` shuffles with the C++ Fisher-Yates kernel
        (`accelerate_tpu.native.permutation`) — same determinism contract
        (identical order for a (seed, epoch) pair on every process/machine
        running the native path) but a DIFFERENT order than numpy's PCG64,
        so switching backends mid-training reshuffles the epoch."""
        if backend not in ("numpy", "native"):
            raise ValueError(f"backend must be 'numpy' or 'native', got {backend!r}")
        self.num_samples = num_samples
        self.shuffle = shuffle
        self.seed = seed
        self.backend = backend
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.num_samples

    def __iter__(self) -> Iterator[int]:
        if not self.shuffle:
            yield from range(self.num_samples)
        elif self.backend == "native":
            from ..native import permutation

            yield from permutation(self.num_samples, seed=self.seed + self.epoch).tolist()
        else:
            rng = np.random.RandomState(seed=(self.seed + self.epoch) % (2**32))
            yield from rng.permutation(self.num_samples).tolist()


def batch_indices(
    sampler: Iterable[int], batch_size: int, drop_last: bool = False
) -> Iterator[list[int]]:
    """Group an index stream into batches (torch `BatchSampler` analog)."""
    batch: list[int] = []
    for idx in sampler:
        batch.append(idx)
        if len(batch) == batch_size:
            yield batch
            batch = []
    if batch and not drop_last:
        yield batch


def shard_batches(
    batches: Iterable[Sequence[int]],
    num_processes: int,
    process_index: int,
    *,
    batch_size: int,
    split_batches: bool = False,
    even_batches: bool = True,
    drop_last: bool = False,
) -> Iterator[list[int]]:
    """Yield this process's batches from a global batch stream.

    Contract of reference `BatchSamplerShard` (`data_loader.py:109-262`):

    - ``split_batches=False``: batch *k* of the underlying stream goes to
      process ``k % num_processes``; a full round of ``num_processes``
      batches is required before any is released. Tail handling: drop_last
      drops the incomplete round; ``even_batches`` completes it by cycling
      samples collected from the first round; otherwise processes holding a
      leftover batch yield it unevenly.
    - ``split_batches=True``: each global batch (size must divide by
      ``num_processes``) is sliced; the tail is completed from the first
      batch's samples when ``even_batches``.
    """
    if split_batches:
        yield from _shard_split(
            batches, num_processes, process_index, batch_size, even_batches, drop_last
        )
    else:
        yield from _shard_no_split(
            batches, num_processes, process_index, batch_size, even_batches, drop_last
        )


def _shard_split(
    batches: Iterable[Sequence[int]],
    num_processes: int,
    process_index: int,
    batch_size: int,
    even_batches: bool,
    drop_last: bool,
) -> Iterator[list[int]]:
    if batch_size % num_processes != 0:
        raise ValueError(
            f"split_batches requires the global batch size ({batch_size}) to be a "
            f"round multiple of the number of processes ({num_processes})."
        )
    piece = batch_size // num_processes
    lo, hi = piece * process_index, piece * (process_index + 1)
    first: list[int] = []
    last: list[int] = []
    for i, batch in enumerate(batches):
        batch = list(batch)
        if i == 0:
            first = batch
        last = batch
        if len(batch) == batch_size:
            yield batch[lo:hi]
    if drop_last or not first or len(last) == batch_size:
        return
    if not even_batches:
        if len(last) > lo:
            yield last[lo:hi]
        return
    fill = list(first)
    while len(fill) < batch_size:
        fill += fill
    completed = last + fill
    yield completed[lo:hi]


def _shard_no_split(
    batches: Iterable[Sequence[int]],
    num_processes: int,
    process_index: int,
    batch_size: int,
    even_batches: bool,
    drop_last: bool,
) -> Iterator[list[int]]:
    first_round: list[int] = []
    mine: list[int] = []
    last: list[int] = []
    count = 0
    for count, batch in enumerate(batches, start=1):
        batch = list(batch)
        if not drop_last and count <= num_processes:
            first_round += batch
        if (count - 1) % num_processes == process_index:
            mine = batch
        last = batch
        if count % num_processes == 0 and len(batch) == batch_size:
            yield mine
            mine = []
    if drop_last or not first_round:
        return
    if not even_batches:
        if mine:
            yield mine
        return
    # A full round whose last batch was full has already been yielded above;
    # anything else must be completed by cycling first-round samples so every
    # process ends the epoch with the same batch count and size.
    if count % num_processes == 0 and len(last) == batch_size:
        return
    # A full-size batch held from the unfinished round is released as-is; a
    # short one is completed inside the recycle loop below.
    if len(mine) == batch_size:
        yield mine
    fill = list(first_round)
    while len(fill) < num_processes * batch_size:
        fill += fill
    if len(last) == batch_size:
        # The trailing partial round consists of full batches only; processes
        # beyond it get recycled batches.
        carry: list[int] = []
        idx = count
    else:
        carry = last
        idx = count - 1  # the partial batch is re-issued, completed
    cursor = 0
    while idx % num_processes != 0 or carry:
        take = batch_size - len(carry)
        carry = carry + fill[cursor : cursor + take]
        cursor += take
        if idx % num_processes == process_index:
            yield carry
        carry = []
        idx += 1


def shard_iterable(
    iterable: Iterable[Any],
    *,
    batch_size: int,
    num_processes: int,
    process_index: int,
    split_batches: bool = False,
    drop_last: bool = False,
) -> Iterator[Any]:
    """Per-process element stream over a shared iterable dataset.

    Contract of reference `IterableDatasetShard` (`data_loader.py:265-364`):
    buffer ``real_batch_size`` elements (``batch_size`` if split_batches else
    ``batch_size * num_processes``), hand this process its contiguous slice;
    complete the tail by cycling the first buffered batch unless drop_last.
    """
    real = batch_size if split_batches else batch_size * num_processes
    per_process = batch_size // num_processes if split_batches else batch_size
    lo = process_index * per_process
    hi = lo + per_process

    first: list[Any] | None = None
    buf: list[Any] = []
    for element in iterable:
        buf.append(element)
        if len(buf) == real:
            yield from buf[lo:hi]
            if first is None:
                first = list(buf)
            buf = []
    if drop_last or not buf:
        return
    if first is None:
        first = list(buf)
    while len(buf) < real:
        buf += first
    yield from buf[lo:hi]


def sharded_length(
    total: int, batch_size: int, num_processes: int, drop_last: bool, even_batches: bool = True
) -> int:
    """Number of batches each process will see (reference
    `BatchSamplerShard.__len__`, `data_loader.py:175-191`)."""
    n_batches = total // batch_size if drop_last else math.ceil(total / batch_size)
    if n_batches % num_processes == 0:
        return n_batches // num_processes
    if drop_last:
        return n_batches // num_processes
    if even_batches:
        return n_batches // num_processes + 1
    return n_batches // num_processes  # + 1 only for low process indices

"""Array-backed dataset with native batch assembly.

For the dominant TPU training case — pre-tokenized arrays (or np.memmap
token files) on the host — per-sample `__getitem__` + `np.stack` collation
is pure Python overhead. `ArrayDataset` keeps the whole dataset as a pytree
of equal-length arrays and assembles a batch as one row-gather per leaf,
which `DataLoader._host_batches` routes through the native threaded gather
(`accelerate_tpu.native.gather_rows`) instead of the sample loop.

Works as a plain sized dataset too (`__len__`/`__getitem__`), so every
other loader feature (shard/dispatch, even_batches, resume) is unchanged.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ..native import gather_rows


class ArrayDataset:
    """A pytree of arrays sharing their leading (sample) dimension.

    ``ArrayDataset({"input_ids": tokens, "labels": labels})`` — leaves may be
    numpy arrays or np.memmap (kept unmaterialized until gathered).
    """

    def __init__(self, arrays: Any) -> None:
        leaves = jax.tree.leaves(arrays)
        if not leaves:
            raise ValueError("ArrayDataset needs at least one array")
        n = leaves[0].shape[0]
        for leaf in leaves:
            if leaf.shape[0] != n:
                raise ValueError(
                    f"all leaves must share the leading dimension: {leaf.shape[0]} != {n}"
                )
        self.arrays = arrays
        self._length = int(n)

    def __len__(self) -> int:
        return self._length

    def __getitem__(self, i: int) -> Any:
        return jax.tree.map(lambda a: a[i], self.arrays)

    def gather_batch(self, indices: Any) -> Any:
        """Assemble the batch pytree for ``indices`` — one contiguous
        row-gather per leaf (native threaded path when available)."""
        idx = np.asarray(indices, dtype=np.int64)
        return jax.tree.map(lambda a: gather_rows(np.asarray(a), idx), self.arrays)

from .array_dataset import ArrayDataset
from .loader import DataLoader, default_collate, prepare_data_loader, skip_first_batches
from .sampler import (
    SeedableSampler,
    batch_indices,
    shard_batches,
    shard_iterable,
    sharded_length,
)

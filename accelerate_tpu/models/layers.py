"""Shared neural-net building blocks (pure functions over param pytrees).

The reference framework owns no model code — models come from `transformers`
and are rewritten by `Accelerator.prepare` (reference `accelerator.py:1421`).
A TPU-native framework must own its model family instead, because the sharding
plan, the scan-over-layers structure, and the attention kernels ARE the
performance story (SURVEY.md §7: MFU target requires fused attention + 2-D
sharding). These blocks follow the standard TPU recipe:

- params in fp32, compute in bf16 (cast at call boundaries);
- einsum-everything so XLA tiles straight onto the MXU;
- no python control flow on data — shapes static under jit.

Conventions: ``B`` batch, ``S`` sequence, ``D`` model dim, ``H`` heads,
``K`` kv-heads, ``h`` head dim, ``F`` ff dim, ``L`` layers.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.fp8 import matmul_einsum  # noqa: F401  (re-export: every projection routes through it)

Params = Any


def truncated_normal_init(rng: jax.Array, shape: tuple[int, ...], stddev: float, dtype=jnp.float32) -> jax.Array:
    return jax.random.truncated_normal(rng, -2.0, 2.0, shape, jnp.float32).astype(dtype) * stddev


def remat_policy(name: str):
    """Resolve a remat-policy name to a `jax.checkpoint` policy (shared by
    every model family's ``remat_policy`` config knob)."""
    if name == "nothing":
        return None  # jax.checkpoint default: save nothing, recompute all
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if name == "block_outputs":
        return jax.checkpoint_policies.save_only_these_names("attn_out", "ffn_out")
    if name == "attn_and_outputs":
        # Additionally keep the rotated q/k/v so the backward skips the qkv
        # projections + rope recompute. The flash forward kernel itself still
        # re-runs (its lse residual is internal to the custom_vjp and can't be
        # kept by a name policy), so this trades ~64MB/layer for only the qkv
        # recompute — measured neutral at bench scale; useful when qkv is a
        # larger fraction (big d_model, short S).
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", "ffn_out", "q_rope", "k_rope", "v_proj"
        )
    raise ValueError(
        f"Unknown remat_policy {name!r}; expected 'nothing', 'dots', "
        "'block_outputs', or 'attn_and_outputs'"
    )


# --------------------------------------------------------------------- norms
def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm in fp32 regardless of input dtype (normalization is
    numerically fragile in bf16; standard TPU practice)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * (1.0 + scale.astype(jnp.float32))).astype(dtype)


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-12) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    return (x * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


# Crossover where forward_with_cache switches the KV cache from scan xs/ys
# (restacked every step — cheap while the cache is small) to an in-place scan
# carry (no per-step restack; measured 1.3x decode at 16k ctx on one v5e).
# Shared by every family's cache path so the layouts can't silently diverge.
CARRY_CACHE_MIN_LEN = 4096


# ------------------------------------------------------------------ kv cache
def cache_positions(start: jax.Array, t_new: int, batch: int) -> jax.Array:
    """(B, T_new) logical positions for tokens appended at ``start``.

    ``start`` is the cache length cursor: a scalar (every row appends at the
    same offset — the plain decode contract) or shape (B,) (per-row offsets —
    speculative decoding commits a different number of tokens per row, so
    rows advance independently). Plain Python ints are accepted (caches
    built with host-side int lengths) and normalized here."""
    start = jnp.asarray(start, jnp.int32)
    offs = jnp.arange(t_new, dtype=jnp.int32)[None, :]
    pos = (start[:, None] if start.ndim == 1 else start) + offs
    return jnp.broadcast_to(pos, (batch, t_new))


def cache_write(buf: jax.Array, new: jax.Array, start: jax.Array) -> jax.Array:
    """Write ``new`` (B, T, ...) into ``buf`` (B, S, ...) at offset ``start``
    along the sequence dim.

    Scalar ``start`` keeps the one-``dynamic_update_slice`` decode fast path;
    a (B,) ``start`` vmaps the update over rows (per-row write offsets lower
    to one scatter — the enabling primitive for per-row speculative commit
    lengths). Plain Python int ``start`` is normalized to a jnp scalar."""
    start = jnp.asarray(start, jnp.int32)
    new = new.astype(buf.dtype)
    zeros = (0,) * (buf.ndim - 2)
    if start.ndim == 0:
        return jax.lax.dynamic_update_slice(buf, new, (0, start) + zeros)
    return jax.vmap(
        lambda b, n, s: jax.lax.dynamic_update_slice(b, n, (s,) + zeros)
    )(buf, new, start)


def cache_write_stacked(
    all_buf: jax.Array, i: jax.Array, rows: jax.Array, start: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Write ``rows`` (B, T, ...) into layer ``i`` of a layer-stacked cache
    buffer (L, B, S, ...) at offset ``start`` (scalar or (B,) — see
    `cache_write`). Returns (updated stacked buffer, updated (B, S, ...)
    layer) so carry-layout scan bodies can attend against the fresh layer
    without re-slicing. Shared by every family's carry cache path."""
    start = jnp.asarray(start, jnp.int32)
    lead = (0,) * (all_buf.ndim - 1)
    full = (1,) + all_buf.shape[1:]
    if start.ndim == 1:
        layer = jax.lax.dynamic_slice(all_buf, (i,) + lead, full)[0]
        layer = cache_write(layer, rows, start)
        all_buf = jax.lax.dynamic_update_slice(all_buf, layer[None], (i,) + lead)
        return all_buf, layer
    idx = (i, 0, start) + (0,) * (all_buf.ndim - 3)
    all_buf = jax.lax.dynamic_update_slice(
        all_buf, rows.astype(all_buf.dtype)[None], idx
    )
    layer = jax.lax.dynamic_slice(all_buf, (i,) + lead, full)[0]
    return all_buf, layer


def cache_slot_view(kv: Any, slot: jax.Array) -> Any:
    """Slice one slot row (batch axis 1) out of every layer-stacked KV leaf.

    ``kv`` is a family cache dict WITHOUT its ``length`` cursor (leaves are
    (L, B, T, ...) layer-stacked buffers — k/v and, for int8 caches, their
    scales). ``slot`` is a traced int32 index, so one jitted caller serves
    every slot without recompiling. The result is a batch-1 cache view the
    family ``forward_with_cache`` runs on directly; pair with
    `cache_slot_write` to fold the updated row back. This is the primitive
    the serving engine's bucketed prefill rides: prefill computes on a
    single slot's row while the other slots' entries stay untouched."""
    return jax.tree.map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, slot, 1, axis=1), kv
    )


def cache_slot_write(kv: Any, row: Any, slot: jax.Array) -> Any:
    """Write a batch-1 cache view (from `cache_slot_view`, after a forward
    updated it) back into slot ``slot`` of the full slot-batched cache."""
    return jax.tree.map(
        lambda a, r: jax.lax.dynamic_update_slice_in_dim(
            a, r.astype(a.dtype), slot, axis=1
        ),
        kv,
        row,
    )


def cache_slot_copy(
    dst: Any,
    src: Any,
    dst_slot: jax.Array,
    src_slot: jax.Array,
    start: jax.Array,
    length: int,
) -> Any:
    """Copy ``length`` committed KV positions from row ``src_slot`` of
    ``src`` into row ``dst_slot`` of ``dst`` at the same sequence offset
    ``start``, for every layer-stacked (L, B, T, ...) leaf of two family
    caches (``length`` cursors excluded, like `cache_slot_view`).

    The positions are preserved (source offset == destination offset)
    because committed KV has its rotary/positional encoding baked in — KV
    for token t at position p is only reusable AT position p. ``length`` is
    a static chunk size drawn from the serving engine's prefill bucket set
    while ``dst_slot``/``src_slot``/``start`` are traced int32, so one
    jitted caller compiles at most once per bucket whatever slots and
    cursors traffic produces — the primitive behind the prefix cache's
    device-to-device hit copies and promotions (serving/prefix_cache.py).
    ``dst`` and ``src`` may have different batch (row-pool) sizes."""
    dst_slot = jnp.asarray(dst_slot, jnp.int32)
    src_slot = jnp.asarray(src_slot, jnp.int32)
    start = jnp.asarray(start, jnp.int32)

    def one(d: jax.Array, s: jax.Array) -> jax.Array:
        tail = (0,) * (s.ndim - 3)
        seg = jax.lax.dynamic_slice(
            s, (0, src_slot, start) + tail, (s.shape[0], 1, length) + s.shape[3:]
        )
        return jax.lax.dynamic_update_slice(
            d, seg.astype(d.dtype), (0, dst_slot, start) + tail
        )

    return jax.tree.map(one, dst, src)


# ---------------------------------------------------------------------- rope
@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Rotary-frequency rescaling (HF ``rope_scaling``), hashable so configs
    carrying it stay valid jit static args / lru_cache keys.

    ``rope_type``:
      - ``"llama3"`` — Llama-3.1+ wavelength-banded rescale: low-frequency
        (long-wavelength) components are slowed by ``factor``, high-frequency
        ones kept, with a smooth ramp between the two bands (reference
        semantics: transformers ``modeling_rope_utils._compute_llama3_parameters``).
      - ``"linear"`` — position interpolation: every frequency divided by
        ``factor``.
    """

    rope_type: str
    factor: float
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192


def rope_frequencies(
    head_dim: int,
    max_len: int,
    theta: float = 10000.0,
    scaling: RopeScaling | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Precomputed cos/sin tables, shape (max_len, head_dim/2), fp32.

    Tables are built host-side in fp64 (they're tiny and computed once per
    trace), so the scaled frequencies match transformers' fp32 tables to
    rounding."""
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    if scaling is not None:
        if scaling.rope_type == "linear":
            inv_freq = inv_freq / scaling.factor
        elif scaling.rope_type == "llama3":
            old_len = scaling.original_max_position_embeddings
            low_wavelen = old_len / scaling.low_freq_factor
            high_wavelen = old_len / scaling.high_freq_factor
            wavelen = 2.0 * np.pi / inv_freq
            smooth = (old_len / wavelen - scaling.low_freq_factor) / (
                scaling.high_freq_factor - scaling.low_freq_factor
            )
            smoothed = ((1.0 - smooth) / scaling.factor + smooth) * inv_freq
            inv_freq = np.where(
                wavelen > low_wavelen,
                inv_freq / scaling.factor,
                np.where(wavelen < high_wavelen, inv_freq, smoothed),
            )
        else:
            raise ValueError(
                f"Unimplemented rope_type {scaling.rope_type!r}; supported: "
                "'llama3', 'linear'."
            )
    t = np.arange(max_len, dtype=np.float64)
    freqs = np.outer(t, inv_freq)
    return np.cos(freqs).astype(np.float32), np.sin(freqs).astype(np.float32)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array) -> jax.Array:
    """Rotary position embedding, rotate-half pairing (llama/GPT-NeoX:
    dimension i pairs with i + h/2). x: (B, S, H, h); positions: (B, S)."""
    dtype = x.dtype
    cos = cos[positions][:, :, None, :]  # (B, S, 1, h/2)
    sin = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(dtype)


def apply_rope_interleaved(
    x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array
) -> jax.Array:
    """Rotary position embedding, interleaved pairing (GPT-J
    ``rotate_every_two``: dimension 2i pairs with 2i+1). Same cos/sin tables
    as `apply_rope` — only the pairing differs, so checkpoints trained with
    one convention silently produce wrong logits under the other."""
    dtype = x.dtype
    cos = cos[positions][:, :, None, :]  # (B, S, 1, h/2)
    sin = sin[positions][:, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., 0::2], xf[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.reshape(xf.shape).astype(dtype)


# ----------------------------------------------------------------- attention
def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mask: jax.Array | None = None,
    bias: jax.Array | None = None,
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """Reference (non-fused) attention. q: (B, S, H, h), k/v: (B, T, K, h)
    with grouped-query broadcast when K < H. fp32 softmax. ``bias`` is an
    additive (H, S, T) logit bias (T5-style relative position bias).

    The fused path lives in `ops/flash_attention.py` (Pallas) and the
    sequence-parallel path in `ops/ring_attention.py`; this function is the
    numerical oracle both are tested against.
    """
    B, S, H, h = q.shape
    T, K = k.shape[1], k.shape[2]
    if K != H:
        if H % K != 0:
            raise ValueError(f"num_heads {H} not divisible by num_kv_heads {K}")
        group = H // K
        q = q.reshape(B, S, K, group, h)
        logits = jnp.einsum("bskgh,btkh->bkgst", q, k).astype(jnp.float32)
    else:
        logits = jnp.einsum("bskh,btkh->bkst", q, k).astype(jnp.float32)
        logits = logits[:, :, None]  # group dim of 1
        group = 1
        q = q.reshape(B, S, K, group, h)
    scale = scale if scale is not None else 1.0 / np.sqrt(h)
    logits = logits * scale

    if bias is not None:
        # (H, S, T) -> (1, K, group, S, T) matching the logits layout
        logits = logits + bias.astype(jnp.float32).reshape(1, K, group, S, T)

    if causal:
        causal_mask = jnp.tril(jnp.ones((S, T), bool), k=T - S)
        logits = jnp.where(causal_mask[None, None, None], logits, -1e30)
    if mask is not None:
        # mask: (B, T) padding mask or (B, S, T) full mask
        if mask.ndim == 2:
            mask = mask[:, None, :]
        logits = jnp.where(mask[:, None, None].astype(bool), logits, -1e30)

    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgst,btkh->bskgh", probs, v)
    return out.reshape(B, S, H, h)


def cached_decode_attention(
    q: jax.Array,
    k_full: jax.Array,
    v_full: jax.Array,
    *,
    mask: jax.Array | None = None,
    lengths: jax.Array | None = None,
    kv_raw=None,
    window: int | None = None,
) -> jax.Array:
    """Decode-step attention over a slot KV cache.

    Routes through the `native/pallas` flash-decode kernel when the
    `decode_attn` kernel is enabled and the shapes are supported (single
    query token, no sliding window, cursor-masked by ``lengths``); otherwise
    the reference `dot_product_attention` with the full cache ``mask`` — the
    exact current lowering, so with kernels off this function is
    byte-identical to calling the reference directly. ``kv_raw`` optionally
    carries the raw int8 cache + scales so the kernel fuses the dequant.
    """
    if lengths is not None and window is None and q.shape[1] == 1:
        try:
            from ..native.pallas.decode_attention import maybe_flash_decode
        except Exception:  # pragma: no cover - environment dependent
            maybe_flash_decode = None
        if maybe_flash_decode is not None:
            out = maybe_flash_decode(q, k_full, v_full, lengths, kv_raw=kv_raw)
            if out is not None:
                return out
    return dot_product_attention(q, k_full, v_full, mask=mask)


# ------------------------------------------------------------------ attention block
@dataclasses.dataclass(frozen=True)
class AttentionSpec:
    d_model: int
    num_heads: int
    num_kv_heads: int
    head_dim: int

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim


def init_attention(
    rng: jax.Array, spec: AttentionSpec, dtype=jnp.float32, *, bias: bool = False
) -> Params:
    """``bias=True`` adds per-head q/k/v biases and an output bias (BERT /
    GPT-2 / ViT convention; llama-family attention is bias-free)."""
    kq, kk, kv, ko = jax.random.split(rng, 4)
    std = 1.0 / np.sqrt(spec.d_model)
    params = {
        "wq": truncated_normal_init(kq, (spec.d_model, spec.num_heads, spec.head_dim), std, dtype),
        "wk": truncated_normal_init(kk, (spec.d_model, spec.num_kv_heads, spec.head_dim), std, dtype),
        "wv": truncated_normal_init(kv, (spec.d_model, spec.num_kv_heads, spec.head_dim), std, dtype),
        "wo": truncated_normal_init(ko, (spec.num_heads, spec.head_dim, spec.d_model), std, dtype),
    }
    if bias:
        params["bq"] = jnp.zeros((spec.num_heads, spec.head_dim), dtype)
        params["bk"] = jnp.zeros((spec.num_kv_heads, spec.head_dim), dtype)
        params["bv"] = jnp.zeros((spec.num_kv_heads, spec.head_dim), dtype)
        params["bo"] = jnp.zeros((spec.d_model,), dtype)
    return params


def attention_qkv(params: Params, x: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    q = matmul_einsum("bsd,dhk->bshk", x, params["wq"])
    k = matmul_einsum("bsd,dhk->bshk", x, params["wk"])
    v = matmul_einsum("bsd,dhk->bshk", x, params["wv"])
    if "bq" in params:
        q = q + params["bq"].astype(q.dtype)
        k = k + params["bk"].astype(k.dtype)
        v = v + params["bv"].astype(v.dtype)
    return q, k, v


def attention_out(params: Params, attn: jax.Array) -> jax.Array:
    out = matmul_einsum("bshk,hkd->bsd", attn, params["wo"])
    if "bo" in params:
        out = out + params["bo"].astype(out.dtype)
    return out


# ------------------------------------------------------------------------ mlp
def init_swiglu(rng: jax.Array, d_model: int, d_ff: int, dtype=jnp.float32) -> Params:
    kg, ku, kd = jax.random.split(rng, 3)
    std_in = 1.0 / np.sqrt(d_model)
    std_out = 1.0 / np.sqrt(d_ff)
    return {
        "w_gate": truncated_normal_init(kg, (d_model, d_ff), std_in, dtype),
        "w_up": truncated_normal_init(ku, (d_model, d_ff), std_in, dtype),
        "w_down": truncated_normal_init(kd, (d_ff, d_model), std_out, dtype),
    }


def gated_mlp(params: Params, x: jax.Array, activation=jax.nn.silu) -> jax.Array:
    """Gated MLP over {w_gate, w_up, w_down}: swiglu with silu (llama),
    gated-gelu with gelu (T5 v1.1)."""
    gate = matmul_einsum("bsd,df->bsf", x, params["w_gate"])
    up = matmul_einsum("bsd,df->bsf", x, params["w_up"])
    hidden = activation(gate) * up
    return matmul_einsum("bsf,fd->bsd", hidden, params["w_down"])


def swiglu(params: Params, x: jax.Array) -> jax.Array:
    return gated_mlp(params, x, jax.nn.silu)


def init_mlp_gelu(rng: jax.Array, d_model: int, d_ff: int, dtype=jnp.float32) -> Params:
    ki, ko = jax.random.split(rng)
    return {
        "w_in": truncated_normal_init(ki, (d_model, d_ff), 1.0 / np.sqrt(d_model), dtype),
        "b_in": jnp.zeros((d_ff,), dtype),
        "w_out": truncated_normal_init(ko, (d_ff, d_model), 1.0 / np.sqrt(d_ff), dtype),
        "b_out": jnp.zeros((d_model,), dtype),
    }


def activation_fn(name: str):
    """HF ``ACT2FN`` names -> jax callables for the variants the zoo's
    checkpoints actually ship. ``gelu_fast`` is ``gelu_new`` with the tanh
    argument factored differently — algebraically identical."""
    try:
        return {
            "gelu_new": partial(jax.nn.gelu, approximate=True),
            "gelu_fast": partial(jax.nn.gelu, approximate=True),
            "gelu": partial(jax.nn.gelu, approximate=False),
            "relu": jax.nn.relu,
            "silu": jax.nn.silu,
        }[name]
    except KeyError:
        raise ValueError(
            f"Unimplemented activation {name!r}; implemented: gelu_new, "
            "gelu_fast, gelu, relu, silu."
        ) from None


def mlp_gelu(
    params: Params, x: jax.Array, *, approximate: bool = True, act=None
) -> jax.Array:
    """``approximate=True`` is GPT-2's tanh "gelu_new"; BERT/ViT use the
    exact erf gelu (transformers ``ACT2FN["gelu"]``) — the two differ by up
    to ~3e-3 at real activation scales, so the variant must match the
    checkpoint's or logit parity quietly breaks. ``act`` (a callable)
    overrides entirely (OPT's relu MLP rides the same param layout)."""
    h = matmul_einsum("bsd,df->bsf", x, params["w_in"]) + params["b_in"].astype(x.dtype)
    h = act(h) if act is not None else jax.nn.gelu(h, approximate=approximate)
    return matmul_einsum("bsf,fd->bsd", h, params["w_out"]) + params["b_out"].astype(x.dtype)


# ----------------------------------------------------------------------- loss
def chunked_lm_loss(
    x: jax.Array,
    head: jax.Array,
    labels: jax.Array,
    *,
    mask: jax.Array | None = None,
    z_loss: float = 0.0,
    chunk_size: int = 512,
) -> jax.Array:
    """Next-token cross entropy WITHOUT materializing the full (B, S, V)
    logits: the sequence is scanned in chunks, each chunk's
    projection+softmax is `jax.checkpoint`ed so the backward recomputes it
    chunk-by-chunk. At (8, 2048, 32k) the fp32 logit tail is ~2 GB of
    residuals; chunking caps it at chunk_size/S of that. Numerically
    identical (fp32 reductions, same masking/z-loss) to
    ``cross_entropy_loss(einsum(x, head), labels, ...)``.

    x: (B, S, D) trunk output aligned with labels (B, S); S must be a
    multiple of ``chunk_size`` (pick a divisor — S is static under jit).
    """
    B, S, D = x.shape
    if S % chunk_size != 0:
        raise ValueError(f"chunk_size {chunk_size} must divide sequence length {S}")
    n_chunks = S // chunk_size
    xc = x.reshape(B, n_chunks, chunk_size, D).swapaxes(0, 1)
    lc = labels.reshape(B, n_chunks, chunk_size).swapaxes(0, 1)
    if mask is None:
        mc = jnp.ones((n_chunks, B, chunk_size), jnp.float32)
    else:
        mc = mask.reshape(B, n_chunks, chunk_size).swapaxes(0, 1).astype(jnp.float32)

    @jax.checkpoint
    def chunk_sums(x_chunk, label_chunk, mask_chunk):
        logits = jnp.einsum("bsd,dv->bsv", x_chunk, head.astype(x_chunk.dtype))
        logits = logits.astype(jnp.float32)
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        label_logits = jnp.take_along_axis(logits, label_chunk[..., None], axis=-1)[..., 0]
        losses = logz - label_logits
        if z_loss > 0.0:
            losses = losses + z_loss * jnp.square(logz)
        return jnp.sum(losses * mask_chunk), jnp.sum(mask_chunk)

    def scan_body(carry, inputs):
        loss_sum, count = carry
        s, c = chunk_sums(*inputs)
        return (loss_sum + s, count + c), None

    (loss_sum, count), _ = jax.lax.scan(
        scan_body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), (xc, lc, mc)
    )
    return loss_sum / jnp.maximum(count, 1.0)


def shifted_labels_and_mask(
    tokens: jax.Array, attn_mask: jax.Array | None
) -> tuple[jax.Array, jax.Array]:
    """Next-token labels/mask at FULL sequence length for the chunked loss:
    position i predicts token i+1; the final position is masked out instead
    of sliced off (chunking needs chunk_size | S)."""
    B, S = tokens.shape
    labels = jnp.concatenate([tokens[:, 1:], tokens[:, :1]], axis=1)
    loss_mask = jnp.ones((B, S), jnp.float32).at[:, -1].set(0.0)
    if attn_mask is not None:
        shifted = jnp.concatenate(
            [attn_mask[:, 1:], jnp.zeros((B, 1), attn_mask.dtype)], axis=1
        )
        loss_mask = loss_mask * shifted.astype(jnp.float32)
    return labels, loss_mask


def chunked_lm_loss_from_batch(
    x: jax.Array,
    head: jax.Array,
    tokens: jax.Array,
    labels: jax.Array | None,
    attn_mask: jax.Array | None,
    *,
    z_loss: float,
    chunk_size: int,
) -> jax.Array:
    """The shared chunked-loss entry for decoder families: resolves the
    shifted-labels default, then runs `chunked_lm_loss`."""
    if labels is None:
        labels, loss_mask = shifted_labels_and_mask(tokens, attn_mask)
    else:
        loss_mask = attn_mask
    return chunked_lm_loss(
        x, head, labels, mask=loss_mask, z_loss=z_loss, chunk_size=chunk_size
    )


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    *,
    mask: jax.Array | None = None,
    z_loss: float = 0.0,
) -> jax.Array:
    """Token-level cross entropy in fp32 with optional z-loss regularizer
    (keeps the softmax normalizer bounded — stabilizes long bf16 runs)."""
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    losses = logz - label_logits
    if z_loss > 0.0:
        losses = losses + z_loss * jnp.square(logz)
    if mask is not None:
        mask = mask.astype(jnp.float32)
        return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(losses)

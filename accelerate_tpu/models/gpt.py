"""GPT-2-style decoder family.

Widens the model zoo to the reference's breadth: the reference trains
GPT-class models through Megatron's `GPTTrainStep` (reference
`utils/megatron_lm.py:588`) and serves GPT-J/GPT-NeoX through big-model
inference (reference `benchmarks/big_model_inference/README.md`). Same
TPU-native skeleton as `models/llama.py` (scan-over-layers, optional remat,
pluggable attention) with the GPT architectural choices:

- learned absolute position embeddings (``wpe``) instead of RoPE;
- pre-LN `layer_norm` (scale+bias) instead of RMSNorm;
- full multi-head attention (no GQA) + gelu MLP with biases;
- LM head tied to the token embedding (GPT-2 ties by default).

Attention projections are bias-free: the q/k/v/o biases in the original
GPT-2 contribute nothing measurable and dropping them keeps the projections
on the shared `layers.matmul_einsum` path (bf16/fp8 policy for free).

The TP/FSDP plan is registered in `parallel/tp.py` as ``"gpt"``.
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .layers import (
    AttentionSpec,
    attention_out,
    attention_qkv,
    cross_entropy_loss,
    dot_product_attention,
    init_attention,
    init_mlp_gelu,
    layer_norm,
    mlp_gelu,
    remat_policy,
    truncated_normal_init,
)

Params = Any


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    d_model: int = 768
    n_layers: int = 12
    num_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 1024
    norm_eps: float = 1e-5
    tie_embeddings: bool = True
    remat: bool = False
    remat_policy: str = "block_outputs"
    attention_impl: str = "dot"  # "dot" | "flash"
    z_loss: float = 0.0
    # Chunked LM loss (layers.chunked_lm_loss): compute the loss in sequence
    # chunks without materializing the (B, S, V) fp32 logits. None = off.
    loss_chunk_size: int | None = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def attention_spec(self) -> AttentionSpec:
        return AttentionSpec(self.d_model, self.num_heads, self.num_heads, self.head_dim)

    @classmethod
    def tiny(cls, **overrides: Any) -> "GPTConfig":
        defaults = dict(
            vocab_size=256, d_model=64, n_layers=2, num_heads=4, d_ff=128, max_seq_len=128
        )
        defaults.update(overrides)
        return cls(**defaults)

    @classmethod
    def gpt2(cls, **overrides: Any) -> "GPTConfig":
        return cls(**overrides)

    @classmethod
    def gpt2_xl(cls, **overrides: Any) -> "GPTConfig":
        return cls(**{**dict(d_model=1600, n_layers=48, num_heads=25, d_ff=6400), **overrides})

    def param_count(self) -> int:
        attn = 4 * self.d_model * self.d_model + 4 * self.d_model  # + q/k/v/o biases
        ffn = 2 * self.d_model * self.d_ff + self.d_ff + self.d_model
        norms = 2 * 2 * self.d_model
        block = attn + ffn + norms
        embed = self.vocab_size * self.d_model + self.max_seq_len * self.d_model
        head = 0 if self.tie_embeddings else self.d_model * self.vocab_size
        return self.n_layers * block + embed + 2 * self.d_model + head

    def flops_per_token(self) -> float:
        return 6.0 * self.param_count() + 12.0 * self.n_layers * self.d_model * self.max_seq_len


def init_block(rng: jax.Array, config: GPTConfig, dtype=jnp.float32) -> Params:
    ka, km = jax.random.split(rng)
    return {
        "ln1_scale": jnp.ones((config.d_model,), dtype),
        "ln1_bias": jnp.zeros((config.d_model,), dtype),
        "attn": init_attention(ka, config.attention_spec, dtype, bias=True),
        "ln2_scale": jnp.ones((config.d_model,), dtype),
        "ln2_bias": jnp.zeros((config.d_model,), dtype),
        "mlp": init_mlp_gelu(km, config.d_model, config.d_ff, dtype),
    }


def init(rng: jax.Array, config: GPTConfig, dtype=jnp.float32) -> Params:
    """Layer params stacked along a leading ``n_layers`` axis (scan layout)."""
    k_tok, k_pos, k_blocks, k_head = jax.random.split(rng, 4)
    block_keys = jax.random.split(k_blocks, config.n_layers)
    blocks = jax.vmap(lambda k: init_block(k, config, dtype))(block_keys)
    params = {
        "wte": truncated_normal_init(k_tok, (config.vocab_size, config.d_model), 0.02, dtype),
        "wpe": truncated_normal_init(k_pos, (config.max_seq_len, config.d_model), 0.01, dtype),
        "blocks": blocks,
        "lnf_scale": jnp.ones((config.d_model,), dtype),
        "lnf_bias": jnp.zeros((config.d_model,), dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = truncated_normal_init(
            k_head, (config.d_model, config.vocab_size), 1.0 / np.sqrt(config.d_model), dtype
        )
    return params


def _attention(config: GPTConfig, q, k, v, mask):
    if config.attention_impl == "flash":
        from ..ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=True, segment_mask=mask)
    if config.attention_impl != "dot":
        raise ValueError(
            f"Unknown attention_impl {config.attention_impl!r}; expected 'dot' or 'flash'"
        )
    return dot_product_attention(q, k, v, mask=mask, causal=True)


def block_forward(
    block: Params,
    x: jax.Array,
    *,
    config: GPTConfig,
    mask: jax.Array | None,
) -> jax.Array:
    from jax.ad_checkpoint import checkpoint_name

    h = layer_norm(x, block["ln1_scale"], block["ln1_bias"], config.norm_eps)
    q, k, v = attention_qkv(block["attn"], h)
    attn = _attention(config, q, k, v, mask)
    x = x + checkpoint_name(attention_out(block["attn"], attn), "attn_out")
    h = layer_norm(x, block["ln2_scale"], block["ln2_bias"], config.norm_eps)
    x = x + checkpoint_name(mlp_gelu(block["mlp"], h), "ffn_out")
    return x


def _lm_head(params: Params, config: GPTConfig) -> jax.Array:
    return params["wte"].T if config.tie_embeddings else params["lm_head"]


def _logits(params: Params, x: jax.Array, config: GPTConfig) -> jax.Array:
    head = _lm_head(params, config)
    return jnp.einsum("bsd,dv->bsv", x, head.astype(x.dtype))


def forward(
    params: Params,
    tokens: jax.Array,
    config: GPTConfig,
    *,
    positions: jax.Array | None = None,
    mask: jax.Array | None = None,
    return_hidden: bool = False,
) -> jax.Array:
    """tokens (B, S) int32 -> logits (B, S, vocab). ``return_hidden`` skips
    the logits head (the chunked-loss path projects chunk-by-chunk)."""
    B, S = tokens.shape
    if S > config.max_seq_len:
        # XLA gathers clamp out-of-range rows, which would silently hand
        # every position past the table its last row.
        raise ValueError(f"sequence length {S} exceeds max_seq_len={config.max_seq_len}")
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    x = params["wte"][tokens] + params["wpe"][positions]

    body = partial(block_forward, config=config, mask=mask)
    if config.remat:
        body = jax.checkpoint(body, policy=remat_policy(config.remat_policy))

    def scan_body(carry, block):
        return body(block, carry), None

    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    x = layer_norm(x, params["lnf_scale"], params["lnf_bias"], config.norm_eps)
    if return_hidden:
        return x
    return _logits(params, x, config)


# ---------------------------------------------------------------- KV cache
def init_cache(
    config: GPTConfig, batch_size: int, max_len: int, dtype=jnp.bfloat16
) -> dict[str, jax.Array]:
    shape = (config.n_layers, batch_size, max_len, config.num_heads, config.head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def forward_with_cache(
    params: Params,
    tokens: jax.Array,
    cache: dict[str, jax.Array],
    config: GPTConfig,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Incremental forward (prefill or decode) against the KV cache."""
    B, T_new = tokens.shape
    max_len = cache["k"].shape[2]
    start = cache["length"]
    positions = start + jnp.arange(T_new, dtype=jnp.int32)[None, :]
    positions = jnp.broadcast_to(positions, (B, T_new))
    cache_pos = jnp.arange(max_len, dtype=jnp.int32)
    mask = cache_pos[None, None, :] <= positions[:, :, None]

    x = params["wte"][tokens] + params["wpe"][positions]

    def scan_body(carry, xs):
        x = carry
        block, k_cache, v_cache = xs
        h = layer_norm(x, block["ln1_scale"], block["ln1_bias"], config.norm_eps)
        q, k, v = attention_qkv(block["attn"], h)
        k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype), (0, start, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype), (0, start, 0, 0))
        attn = dot_product_attention(
            q, k_cache.astype(q.dtype), v_cache.astype(q.dtype), mask=mask
        )
        x = x + attention_out(block["attn"], attn)
        h = layer_norm(x, block["ln2_scale"], block["ln2_bias"], config.norm_eps)
        x = x + mlp_gelu(block["mlp"], h)
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(
        scan_body, x, (params["blocks"], cache["k"], cache["v"])
    )
    x = layer_norm(x, params["lnf_scale"], params["lnf_bias"], config.norm_eps)
    logits = _logits(params, x, config)
    return logits, {"k": new_k, "v": new_v, "length": start + T_new}


@functools.lru_cache(maxsize=16)
def _generator(config: GPTConfig, generation_config: Any, jit_loop: bool):
    from ..generation import Generator

    return Generator(
        lambda p, t, c: forward_with_cache(p, t, c, config),
        lambda b, m: init_cache(config, b, m),
        generation_config,
        jit_loop=jit_loop,
    )


def generate(
    params: Params,
    prompt: jax.Array,
    config: GPTConfig,
    *,
    generation_config: Any = None,
    rng: jax.Array | None = None,
    jit_loop: bool = True,
) -> jax.Array:
    gen = _generator(config, generation_config, jit_loop)
    total = prompt.shape[1] + gen.config.max_new_tokens
    if total > config.max_seq_len:
        raise ValueError(
            f"prompt ({prompt.shape[1]}) + max_new_tokens "
            f"({gen.config.max_new_tokens}) = {total} exceeds "
            f"max_seq_len={config.max_seq_len}"
        )
    return gen(params, prompt, rng=rng)


def loss_fn(
    params: Params,
    batch: dict[str, jax.Array],
    config: GPTConfig,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Next-token prediction. batch: {"input_ids": (B, S)} with optional
    "labels" and "attention_mask" (same contract as `llama.loss_fn`)."""
    tokens = batch["input_ids"]
    labels = batch.get("labels")
    attn_mask = batch.get("attention_mask")
    if config.loss_chunk_size:
        from .layers import chunked_lm_loss_from_batch

        x = forward(params, tokens, config, mask=attn_mask, return_hidden=True)
        return chunked_lm_loss_from_batch(
            x, _lm_head(params, config), tokens, labels, attn_mask,
            z_loss=config.z_loss, chunk_size=config.loss_chunk_size,
        )
    logits = forward(params, tokens, config, mask=attn_mask)
    if labels is None:
        labels = tokens[:, 1:]
        loss_mask = attn_mask[:, 1:] if attn_mask is not None else None
        logits = logits[:, :-1]
    else:
        loss_mask = attn_mask
    return cross_entropy_loss(logits, labels, mask=loss_mask, z_loss=config.z_loss)

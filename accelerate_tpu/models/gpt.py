"""GPT-style decoder family: GPT-2, GPT-NeoX, GPT-J, and OPT.

Widens the model zoo to the reference's breadth: the reference trains
GPT-class models through Megatron's `GPTTrainStep` (reference
`utils/megatron_lm.py:588`) and its published big-model-inference table is
GPT-J-6B / GPT-NeoX-20B / OPT-30B (reference
`benchmarks/big_model_inference/README.md:27-37`). Same TPU-native skeleton
as `models/llama.py` (scan-over-layers, optional remat, pluggable
attention), with the architecture selected by config knobs instead of four
near-identical modules — every variant therefore inherits the family's TP
plan (`parallel/tp.py` ``"gpt"``), quantize-on-load, offload, and
generation paths for free:

- ``positional``: learned absolute embeddings (``wpe``; GPT-2/OPT) or
  rotary (``rotary_dim`` for partial application, ``rotary_interleaved``
  for GPT-J's rotate-every-two pairing vs NeoX's rotate-half);
- ``parallel_residual``: NeoX computes attn and MLP from the SAME block
  input (two norms); ``shared_parallel_norm`` is GPT-J's single-norm
  version;
- ``activation``: gelu_new (GPT-2/GPT-J), gelu (NeoX), relu (OPT);
- bias layout: ``attn_bias`` (GPT-J is bias-free in attention),
  ``head_bias`` (GPT-J's untied lm_head carries one).

Pre-LN `layer_norm` (scale+bias), full multi-head attention (no GQA), and
biased MLPs are common to all four.
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .layers import (
    CARRY_CACHE_MIN_LEN,
    AttentionSpec,
    activation_fn,
    apply_rope,
    apply_rope_interleaved,
    attention_out,
    attention_qkv,
    cache_positions,
    cache_write,
    cache_write_stacked,
    cross_entropy_loss,
    dot_product_attention,
    init_attention,
    init_mlp_gelu,
    layer_norm,
    mlp_gelu,
    remat_policy,
    rope_frequencies,
    truncated_normal_init,
)

Params = Any


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    d_model: int = 768
    n_layers: int = 12
    num_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 1024
    norm_eps: float = 1e-5
    tie_embeddings: bool = True
    remat: bool = False
    remat_policy: str = "block_outputs"
    attention_impl: str = "dot"  # "dot" | "flash"
    z_loss: float = 0.0
    # Chunked LM loss (layers.chunked_lm_loss): compute the loss in sequence
    # chunks without materializing the (B, S, V) fp32 logits. None = off.
    loss_chunk_size: int | None = None
    # ------------------------------------------- variant knobs (GPT-2 dflt)
    # Which HF tensor layout this config ingests/exports as
    # (models/hf.py): "gpt2" | "gpt_neox" | "gptj" | "opt".
    hf_layout: str = "gpt2"
    positional: str = "learned"  # "learned" (wpe) | "rotary"
    # Partial rotary: rope applied to the first `rotary_dim` dims of each
    # head (GPT-NeoX rotary_pct, GPT-J rotary_dim); None = full head_dim.
    rotary_dim: int | None = None
    rotary_interleaved: bool = False  # GPT-J pairing; False = rotate-half
    rope_theta: float = 10000.0
    # NeoX: x + attn(ln1(x)) + mlp(ln2(x)) in one residual hop; GPT-J is the
    # same with the MLP reusing ln1's output (shared_parallel_norm — the
    # block then has no ln2 params at all).
    parallel_residual: bool = False
    shared_parallel_norm: bool = False
    activation: str = "gelu_new"  # "gelu_new" | "gelu" | "relu"
    attn_bias: bool = True  # GPT-J attention projections are bias-free
    head_bias: bool = False  # GPT-J's untied lm_head has a bias

    def __post_init__(self) -> None:
        if self.shared_parallel_norm and not self.parallel_residual:
            # init_block omits ln2 under shared_parallel_norm; the
            # sequential path reads it — fail at config time, not mid-trace.
            raise ValueError(
                "shared_parallel_norm=True requires parallel_residual=True "
                "(the shared norm IS the parallel layout's single norm)."
            )
        if self.positional not in ("learned", "rotary"):
            raise ValueError(
                f"positional={self.positional!r}; expected 'learned' or 'rotary'."
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def resolved_rotary_dim(self) -> int:
        return self.rotary_dim if self.rotary_dim is not None else self.head_dim

    @property
    def attention_spec(self) -> AttentionSpec:
        return AttentionSpec(self.d_model, self.num_heads, self.num_heads, self.head_dim)

    @classmethod
    def tiny(cls, **overrides: Any) -> "GPTConfig":
        defaults = dict(
            vocab_size=256, d_model=64, n_layers=2, num_heads=4, d_ff=128, max_seq_len=128
        )
        defaults.update(overrides)
        return cls(**defaults)

    @classmethod
    def gpt2(cls, **overrides: Any) -> "GPTConfig":
        return cls(**overrides)

    @classmethod
    def gpt2_xl(cls, **overrides: Any) -> "GPTConfig":
        return cls(**{**dict(d_model=1600, n_layers=48, num_heads=25, d_ff=6400), **overrides})

    @classmethod
    def gptj_6b(cls, **overrides: Any) -> "GPTConfig":
        defaults = dict(
            vocab_size=50400, d_model=4096, n_layers=28, num_heads=16,
            d_ff=16384, max_seq_len=2048, hf_layout="gptj",
            positional="rotary", rotary_dim=64, rotary_interleaved=True,
            parallel_residual=True, shared_parallel_norm=True,
            attn_bias=False, tie_embeddings=False, head_bias=True,
        )
        defaults.update(overrides)
        return cls(**defaults)

    @classmethod
    def gpt_neox_20b(cls, **overrides: Any) -> "GPTConfig":
        defaults = dict(
            vocab_size=50432, d_model=6144, n_layers=44, num_heads=64,
            d_ff=24576, max_seq_len=2048, hf_layout="gpt_neox",
            positional="rotary", rotary_dim=24, parallel_residual=True,
            activation="gelu", tie_embeddings=False,
        )
        defaults.update(overrides)
        return cls(**defaults)

    @classmethod
    def opt_30b(cls, **overrides: Any) -> "GPTConfig":
        defaults = dict(
            vocab_size=50272, d_model=7168, n_layers=48, num_heads=56,
            d_ff=28672, max_seq_len=2048, hf_layout="opt",
            activation="relu", tie_embeddings=True,
        )
        defaults.update(overrides)
        return cls(**defaults)

    def param_count(self) -> int:
        attn = 4 * self.d_model * self.d_model
        if self.attn_bias:
            attn += 4 * self.d_model  # q/k/v/o biases
        ffn = 2 * self.d_model * self.d_ff + self.d_ff + self.d_model
        n_norms = 1 if self.shared_parallel_norm else 2
        block = attn + ffn + n_norms * 2 * self.d_model
        embed = self.vocab_size * self.d_model
        if self.positional == "learned":
            embed += self.max_seq_len * self.d_model
        head = 0 if self.tie_embeddings else self.d_model * self.vocab_size
        if self.head_bias and not self.tie_embeddings:
            head += self.vocab_size
        return self.n_layers * block + embed + 2 * self.d_model + head

    def flops_per_token(self) -> float:
        return 6.0 * self.param_count() + 12.0 * self.n_layers * self.d_model * self.max_seq_len


def init_block(rng: jax.Array, config: GPTConfig, dtype=jnp.float32) -> Params:
    ka, km = jax.random.split(rng)
    block = {
        "ln1_scale": jnp.ones((config.d_model,), dtype),
        "ln1_bias": jnp.zeros((config.d_model,), dtype),
        "attn": init_attention(ka, config.attention_spec, dtype, bias=config.attn_bias),
        "mlp": init_mlp_gelu(km, config.d_model, config.d_ff, dtype),
    }
    if not config.shared_parallel_norm:
        block["ln2_scale"] = jnp.ones((config.d_model,), dtype)
        block["ln2_bias"] = jnp.zeros((config.d_model,), dtype)
    return block


def init(rng: jax.Array, config: GPTConfig, dtype=jnp.float32) -> Params:
    """Layer params stacked along a leading ``n_layers`` axis (scan layout)."""
    k_tok, k_pos, k_blocks, k_head = jax.random.split(rng, 4)
    block_keys = jax.random.split(k_blocks, config.n_layers)
    blocks = jax.vmap(lambda k: init_block(k, config, dtype))(block_keys)
    params = {
        "wte": truncated_normal_init(k_tok, (config.vocab_size, config.d_model), 0.02, dtype),
        "blocks": blocks,
        "lnf_scale": jnp.ones((config.d_model,), dtype),
        "lnf_bias": jnp.zeros((config.d_model,), dtype),
    }
    if config.positional == "learned":
        params["wpe"] = truncated_normal_init(
            k_pos, (config.max_seq_len, config.d_model), 0.01, dtype
        )
    if not config.tie_embeddings:
        params["lm_head"] = truncated_normal_init(
            k_head, (config.d_model, config.vocab_size), 1.0 / np.sqrt(config.d_model), dtype
        )
        if config.head_bias:
            params["lm_head_bias"] = jnp.zeros((config.vocab_size,), dtype)
    return params


def _rope_tables(config: GPTConfig):
    """cos/sin tables over the ROTARY dims only (partial rotary leaves the
    tail of each head untouched). Rebuilt per call, NOT cached: under jit
    the `jnp.asarray` result is a trace-local constant, and caching it
    would leak the tracer into later traces (llama._rope_tables ditto)."""
    cos, sin = rope_frequencies(
        config.resolved_rotary_dim, config.max_seq_len, config.rope_theta
    )
    return jnp.asarray(cos), jnp.asarray(sin)


def _apply_rotary(x, cos, sin, positions, config: GPTConfig):
    rd = config.resolved_rotary_dim
    rope = apply_rope_interleaved if config.rotary_interleaved else apply_rope
    if rd == config.head_dim:
        return rope(x, cos, sin, positions)
    rot = rope(x[..., :rd], cos, sin, positions)
    return jnp.concatenate([rot, x[..., rd:]], axis=-1)


def _attention(config: GPTConfig, q, k, v, mask):
    if config.attention_impl == "flash":
        from ..ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=True, segment_mask=mask)
    if config.attention_impl != "dot":
        raise ValueError(
            f"Unknown attention_impl {config.attention_impl!r}; expected 'dot' or 'flash'"
        )
    return dot_product_attention(q, k, v, mask=mask, causal=True)


def _mlp(config: GPTConfig, mlp_params: Params, h: jax.Array) -> jax.Array:
    return mlp_gelu(mlp_params, h, act=activation_fn(config.activation))


def block_forward(
    block: Params,
    x: jax.Array,
    *,
    config: GPTConfig,
    mask: jax.Array | None,
    cos: jax.Array | None = None,
    sin: jax.Array | None = None,
    positions: jax.Array | None = None,
) -> jax.Array:
    from jax.ad_checkpoint import checkpoint_name

    h1 = layer_norm(x, block["ln1_scale"], block["ln1_bias"], config.norm_eps)
    q, k, v = attention_qkv(block["attn"], h1)
    if config.positional == "rotary":
        q = checkpoint_name(_apply_rotary(q, cos, sin, positions, config), "q_rope")
        k = checkpoint_name(_apply_rotary(k, cos, sin, positions, config), "k_rope")
    attn = _attention(config, q, k, v, mask)
    attn_out = checkpoint_name(attention_out(block["attn"], attn), "attn_out")
    if config.parallel_residual:
        h2 = (
            h1
            if config.shared_parallel_norm
            else layer_norm(x, block["ln2_scale"], block["ln2_bias"], config.norm_eps)
        )
        return x + attn_out + checkpoint_name(_mlp(config, block["mlp"], h2), "ffn_out")
    x = x + attn_out
    h2 = layer_norm(x, block["ln2_scale"], block["ln2_bias"], config.norm_eps)
    return x + checkpoint_name(_mlp(config, block["mlp"], h2), "ffn_out")


def _lm_head(params: Params, config: GPTConfig) -> jax.Array:
    return params["wte"].T if config.tie_embeddings else params["lm_head"]


def _logits(params: Params, x: jax.Array, config: GPTConfig) -> jax.Array:
    head = _lm_head(params, config)
    logits = jnp.einsum("bsd,dv->bsv", x, head.astype(x.dtype))
    if "lm_head_bias" in params:
        logits = logits + params["lm_head_bias"].astype(logits.dtype)
    return logits


def forward(
    params: Params,
    tokens: jax.Array,
    config: GPTConfig,
    *,
    positions: jax.Array | None = None,
    mask: jax.Array | None = None,
    return_hidden: bool = False,
) -> jax.Array:
    """tokens (B, S) int32 -> logits (B, S, vocab). ``return_hidden`` skips
    the logits head (the chunked-loss path projects chunk-by-chunk)."""
    B, S = tokens.shape
    if S > config.max_seq_len:
        # XLA gathers clamp out-of-range rows, which would silently hand
        # every position past the table its last row.
        raise ValueError(f"sequence length {S} exceeds max_seq_len={config.max_seq_len}")
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    x = params["wte"][tokens]
    if config.positional == "learned":
        x = x + params["wpe"][positions]
        cos = sin = None
    else:
        cos, sin = _rope_tables(config)

    body = partial(
        block_forward, config=config, mask=mask, cos=cos, sin=sin, positions=positions
    )
    if config.remat:
        body = jax.checkpoint(body, policy=remat_policy(config.remat_policy))

    def scan_body(carry, block):
        return body(block, carry), None

    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    x = layer_norm(x, params["lnf_scale"], params["lnf_bias"], config.norm_eps)
    if return_hidden:
        return x
    return _logits(params, x, config)


# ---------------------------------------------------------------- KV cache
def init_cache(
    config: GPTConfig, batch_size: int, max_len: int, dtype=jnp.bfloat16
) -> dict[str, jax.Array]:
    if dtype == jnp.int8:
        raise NotImplementedError(
            "int8 KV caches are implemented for the llama family "
            "(models/llama.py init_cache); the gpt cache path would "
            "silently misread scale-free int8 values."
        )
    shape = (config.n_layers, batch_size, max_len, config.num_heads, config.head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def forward_with_cache(
    params: Params,
    tokens: jax.Array,
    cache: dict[str, jax.Array],
    config: GPTConfig,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Incremental forward (prefill or decode) against the KV cache.

    ``cache['length']`` is a scalar or per-row (B,) cursor — same contract
    as `llama.forward_with_cache` (per-row = speculative decoding)."""
    B, T_new = tokens.shape
    max_len = cache["k"].shape[2]
    start = cache["length"]
    positions = cache_positions(start, T_new, B)
    cache_pos = jnp.arange(max_len, dtype=jnp.int32)
    mask = cache_pos[None, None, :] <= positions[:, :, None]

    x = params["wte"][tokens]
    if config.positional == "learned":
        x = x + params["wpe"][positions]
        cos = sin = None
    else:
        cos, sin = _rope_tables(config)

    # Same dual cache layout as llama.forward_with_cache: long contexts
    # carry the stacked cache through the scan (in-place, no per-step
    # restack — measured 1.3x decode at 16k there); short ones keep xs/ys.
    carry_cache = max_len >= CARRY_CACHE_MIN_LEN

    def block_compute(block, x, k_full, v_full, q, h1, mask):
        # h1 is project()'s pre-attention norm of the SAME x (the parallel-
        # residual MLP branches off the block input, not the post-attn sum).
        attn = dot_product_attention(q, k_full, v_full, mask=mask)
        attn_out = attention_out(block["attn"], attn)
        if config.parallel_residual:
            h2 = (
                h1
                if config.shared_parallel_norm
                else layer_norm(x, block["ln2_scale"], block["ln2_bias"], config.norm_eps)
            )
            return x + attn_out + _mlp(config, block["mlp"], h2)
        x = x + attn_out
        h2 = layer_norm(x, block["ln2_scale"], block["ln2_bias"], config.norm_eps)
        return x + _mlp(config, block["mlp"], h2)

    def project(block, x):
        h1 = layer_norm(x, block["ln1_scale"], block["ln1_bias"], config.norm_eps)
        q, k, v = attention_qkv(block["attn"], h1)
        if config.positional == "rotary":
            q = _apply_rotary(q, cos, sin, positions, config)
            k = _apply_rotary(k, cos, sin, positions, config)
        return q, k, v, h1

    if carry_cache:
        def scan_body(carry, block):
            x, k_all, v_all, i = carry
            q, k, v, h1 = project(block, x)
            k_all, k_layer = cache_write_stacked(k_all, i, k, start)
            v_all, v_layer = cache_write_stacked(v_all, i, v, start)
            x = block_compute(
                block, x, k_layer.astype(x.dtype), v_layer.astype(x.dtype), q, h1, mask
            )
            return (x, k_all, v_all, i + 1), None

        (x, new_k, new_v, _), _ = jax.lax.scan(
            scan_body,
            (x, cache["k"], cache["v"], jnp.zeros((), jnp.int32)),
            params["blocks"],
        )
    else:
        def scan_body(carry, xs):
            x = carry
            block, k_cache, v_cache = xs
            q, k, v, h1 = project(block, x)
            k_cache = cache_write(k_cache, k, start)
            v_cache = cache_write(v_cache, v, start)
            x = block_compute(
                block, x, k_cache.astype(q.dtype), v_cache.astype(q.dtype), q, h1, mask
            )
            return x, (k_cache, v_cache)

        x, (new_k, new_v) = jax.lax.scan(
            scan_body, x, (params["blocks"], cache["k"], cache["v"])
        )
    x = layer_norm(x, params["lnf_scale"], params["lnf_bias"], config.norm_eps)
    logits = _logits(params, x, config)
    return logits, {"k": new_k, "v": new_v, "length": start + T_new}


@functools.lru_cache(maxsize=16)
def _generator(config: GPTConfig, generation_config: Any, jit_loop: bool):
    from ..generation import GenerationConfig, Generator, cache_dtype

    gcfg = generation_config or GenerationConfig()
    kv_dtype = cache_dtype(gcfg)  # int8 request fails loudly in init_cache
    return Generator(
        lambda p, t, c: forward_with_cache(p, t, c, config),
        lambda b, m: init_cache(config, b, m, dtype=kv_dtype),
        gcfg,
        jit_loop=jit_loop,
    )


def generate(
    params: Params,
    prompt: jax.Array,
    config: GPTConfig,
    *,
    generation_config: Any = None,
    rng: jax.Array | None = None,
    jit_loop: bool = True,
) -> jax.Array:
    gen = _generator(config, generation_config, jit_loop)
    total = prompt.shape[1] + gen.config.max_new_tokens
    if total > config.max_seq_len:
        raise ValueError(
            f"prompt ({prompt.shape[1]}) + max_new_tokens "
            f"({gen.config.max_new_tokens}) = {total} exceeds "
            f"max_seq_len={config.max_seq_len}"
        )
    return gen(params, prompt, rng=rng)


def loss_fn(
    params: Params,
    batch: dict[str, jax.Array],
    config: GPTConfig,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Next-token prediction. batch: {"input_ids": (B, S)} with optional
    "labels" and "attention_mask" (same contract as `llama.loss_fn`)."""
    tokens = batch["input_ids"]
    labels = batch.get("labels")
    attn_mask = batch.get("attention_mask")
    if config.loss_chunk_size:
        from .layers import chunked_lm_loss_from_batch

        x = forward(params, tokens, config, mask=attn_mask, return_hidden=True)
        return chunked_lm_loss_from_batch(
            x, _lm_head(params, config), tokens, labels, attn_mask,
            z_loss=config.z_loss, chunk_size=config.loss_chunk_size,
        )
    logits = forward(params, tokens, config, mask=attn_mask)
    if labels is None:
        labels = tokens[:, 1:]
        loss_mask = attn_mask[:, 1:] if attn_mask is not None else None
        logits = logits[:, :-1]
    else:
        loss_mask = attn_mask
    return cross_entropy_loss(logits, labels, mask=loss_mask, z_loss=config.z_loss)

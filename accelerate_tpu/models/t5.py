"""T5-style encoder-decoder family.

Completes the Megatron model-type trio the reference drives (BERT / GPT / T5
train steps, reference `utils/megatron_lm.py:446/:588/:720`). Same TPU-native
skeleton as the other families (scan-over-layers, stacked block params,
einsum projections on the shared `matmul_einsum` path) with the T5
architectural choices:

- relative position bias instead of absolute positions: one learned
  ``(num_buckets, num_heads)`` table per stack, shared by all layers of that
  stack (exactly T5's sharing scheme), added to the attention logits;
- RMSNorm pre-norm, bias-free projections, unscaled attention (T5 folds the
  1/sqrt(h) into init);
- gated-gelu MLP (T5 v1.1) built on the shared matmul path;
- decoder = causal self-attention + cross-attention over the encoder output;
- logits tied to the input embedding with the T5 ``d_model**-0.5`` rescale.

`generate` is a greedy/temperature loop that re-runs the decoder on the
growing target (no KV cache: T5-class seq2seq targets are short; the
decoder-only families own the cached decode path).

TP/FSDP plan registered in `parallel/tp.py` as ``"t5"``.
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .layers import (
    AttentionSpec,
    cross_entropy_loss,
    dot_product_attention,
    gated_mlp,
    init_attention,
    init_swiglu,
    matmul_einsum,
    rms_norm,
    truncated_normal_init,
)

Params = Any


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    n_encoder_layers: int = 6
    n_decoder_layers: int = 6
    num_heads: int = 8
    head_dim: int = 64
    d_ff: int = 1024
    rel_buckets: int = 32
    rel_max_distance: int = 128
    norm_eps: float = 1e-6
    tie_embeddings: bool = True
    remat: bool = False
    z_loss: float = 0.0

    @property
    def attention_spec(self) -> AttentionSpec:
        return AttentionSpec(self.d_model, self.num_heads, self.num_heads, self.head_dim)

    @classmethod
    def tiny(cls, **overrides: Any) -> "T5Config":
        defaults = dict(
            vocab_size=256, d_model=64, n_encoder_layers=2, n_decoder_layers=2,
            num_heads=4, head_dim=16, d_ff=128, rel_buckets=8, rel_max_distance=20,
        )
        defaults.update(overrides)
        return cls(**defaults)

    @classmethod
    def t5_small(cls, **overrides: Any) -> "T5Config":
        return cls(**overrides)

    @classmethod
    def t5_base(cls, **overrides: Any) -> "T5Config":
        return cls(**{**dict(
            d_model=768, n_encoder_layers=12, n_decoder_layers=12,
            num_heads=12, d_ff=2048,
        ), **overrides})

    def param_count(self) -> int:
        d, f, H, h = self.d_model, self.d_ff, self.num_heads, self.head_dim
        attn = d * H * h * 4
        mlp = 3 * d * f
        enc_block = attn + mlp + 2 * d
        dec_block = 2 * attn + mlp + 3 * d
        rel = 2 * self.rel_buckets * H
        embed = self.vocab_size * d * (1 if self.tie_embeddings else 2)
        return (
            self.n_encoder_layers * enc_block
            + self.n_decoder_layers * dec_block
            + rel + embed + 2 * d
        )


# ------------------------------------------------------- relative positions
def relative_position_bucket(
    relative_position: jax.Array,
    *,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> jax.Array:
    """T5's log-bucketed relative positions: half the buckets cover exact
    small offsets, the other half log-spaced offsets up to ``max_distance``."""
    ret = jnp.zeros_like(relative_position)
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


def _rel_bias(table: jax.Array, S: int, T: int, config: T5Config, *, bidirectional: bool) -> jax.Array:
    """(num_buckets, H) table -> (H, S, T) additive logit bias."""
    ctx = jnp.arange(S, dtype=jnp.int32)[:, None]
    mem = jnp.arange(T, dtype=jnp.int32)[None, :]
    buckets = relative_position_bucket(
        mem - ctx,
        bidirectional=bidirectional,
        num_buckets=config.rel_buckets,
        max_distance=config.rel_max_distance,
    )
    return jnp.transpose(table[buckets], (2, 0, 1))  # (S, T, H) -> (H, S, T)


# ------------------------------------------------------------------- blocks
def _gated_gelu(params: Params, x: jax.Array) -> jax.Array:
    """T5 v1.1 gated-gelu on the shared gated-MLP block (layers.gated_mlp)."""
    return gated_mlp(params, x, partial(jax.nn.gelu, approximate=True))


def _attn(params: Params, x: jax.Array, kv: jax.Array, *, mask, bias, causal) -> jax.Array:
    q = matmul_einsum("bsd,dhk->bshk", x, params["wq"])
    k = matmul_einsum("bsd,dhk->bshk", kv, params["wk"])
    v = matmul_einsum("bsd,dhk->bshk", kv, params["wv"])
    # T5 folds 1/sqrt(h) into initialization: unscaled attention.
    attn = dot_product_attention(q, k, v, mask=mask, bias=bias, causal=causal, scale=1.0)
    return matmul_einsum("bshk,hkd->bsd", attn, params["wo"])


def _init_t5_attention(rng: jax.Array, config: T5Config, dtype) -> Params:
    """T5 runs UNSCALED attention and compensates in init: wq gets an extra
    head_dim**-0.5 so q.k logits at init have the same scale a 1/sqrt(h)
    -scaled attention would (without this, logits are ~sqrt(h) too large and
    the softmax saturates from step 0 at real head dims)."""
    attn = init_attention(rng, config.attention_spec, dtype)
    attn["wq"] = attn["wq"] * (config.head_dim**-0.5)
    return attn


def _init_encoder_block(rng: jax.Array, config: T5Config, dtype) -> Params:
    ka, km = jax.random.split(rng)
    return {
        "attn_norm": jnp.zeros((config.d_model,), dtype),
        "attn": _init_t5_attention(ka, config, dtype),
        "mlp_norm": jnp.zeros((config.d_model,), dtype),
        "mlp": init_swiglu(km, config.d_model, config.d_ff, dtype),
    }


def _init_decoder_block(rng: jax.Array, config: T5Config, dtype) -> Params:
    ka, kc, km = jax.random.split(rng, 3)
    return {
        "self_norm": jnp.zeros((config.d_model,), dtype),
        "self_attn": _init_t5_attention(ka, config, dtype),
        "cross_norm": jnp.zeros((config.d_model,), dtype),
        "cross_attn": _init_t5_attention(kc, config, dtype),
        "mlp_norm": jnp.zeros((config.d_model,), dtype),
        "mlp": init_swiglu(km, config.d_model, config.d_ff, dtype),
    }


def init(rng: jax.Array, config: T5Config, dtype=jnp.float32) -> Params:
    k_embed, k_enc, k_dec, k_re, k_rd, k_head = jax.random.split(rng, 6)
    enc_keys = jax.random.split(k_enc, config.n_encoder_layers)
    dec_keys = jax.random.split(k_dec, config.n_decoder_layers)
    params = {
        "embed": truncated_normal_init(k_embed, (config.vocab_size, config.d_model), 1.0, dtype),
        "enc_rel_bias": truncated_normal_init(
            k_re, (config.rel_buckets, config.num_heads), 0.02, dtype
        ),
        "dec_rel_bias": truncated_normal_init(
            k_rd, (config.rel_buckets, config.num_heads), 0.02, dtype
        ),
        "encoder": jax.vmap(lambda k: _init_encoder_block(k, config, dtype))(enc_keys),
        "enc_final_norm": jnp.zeros((config.d_model,), dtype),
        "decoder": jax.vmap(lambda k: _init_decoder_block(k, config, dtype))(dec_keys),
        "dec_final_norm": jnp.zeros((config.d_model,), dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = truncated_normal_init(
            k_head, (config.d_model, config.vocab_size), 1.0 / np.sqrt(config.d_model), dtype
        )
    return params


# ------------------------------------------------------------------ forward
def encode(
    params: Params,
    input_ids: jax.Array,
    config: T5Config,
    *,
    attention_mask: jax.Array | None = None,
) -> jax.Array:
    """input_ids (B, S) -> encoder states (B, S, D)."""
    B, S = input_ids.shape
    x = params["embed"][input_ids]
    bias = _rel_bias(params["enc_rel_bias"], S, S, config, bidirectional=True)

    def body(block, carry):
        h = rms_norm(carry, block["attn_norm"], config.norm_eps)
        carry = carry + _attn(block["attn"], h, h, mask=attention_mask, bias=bias, causal=False)
        h = rms_norm(carry, block["mlp_norm"], config.norm_eps)
        return carry + _gated_gelu(block["mlp"], h)

    if config.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(lambda c, b: (body(b, c), None), x, params["encoder"])
    return rms_norm(x, params["enc_final_norm"], config.norm_eps)


def decode(
    params: Params,
    decoder_input_ids: jax.Array,
    encoder_states: jax.Array,
    config: T5Config,
    *,
    encoder_mask: jax.Array | None = None,
) -> jax.Array:
    """decoder_input_ids (B, T) + encoder states -> logits (B, T, vocab)."""
    B, T = decoder_input_ids.shape
    x = params["embed"][decoder_input_ids]
    bias = _rel_bias(params["dec_rel_bias"], T, T, config, bidirectional=False)

    def body(block, carry):
        h = rms_norm(carry, block["self_norm"], config.norm_eps)
        carry = carry + _attn(block["self_attn"], h, h, mask=None, bias=bias, causal=True)
        h = rms_norm(carry, block["cross_norm"], config.norm_eps)
        carry = carry + _attn(
            block["cross_attn"], h, encoder_states.astype(h.dtype),
            mask=encoder_mask, bias=None, causal=False,
        )
        h = rms_norm(carry, block["mlp_norm"], config.norm_eps)
        return carry + _gated_gelu(block["mlp"], h)

    if config.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(lambda c, b: (body(b, c), None), x, params["decoder"])
    x = rms_norm(x, params["dec_final_norm"], config.norm_eps)
    if config.tie_embeddings:
        # T5 rescales tied logits by d_model**-0.5.
        head = params["embed"].T
        return jnp.einsum("btd,dv->btv", x * (config.d_model**-0.5), head.astype(x.dtype))
    return jnp.einsum("btd,dv->btv", x, params["lm_head"].astype(x.dtype))


def forward(
    params: Params,
    input_ids: jax.Array,
    decoder_input_ids: jax.Array,
    config: T5Config,
    *,
    attention_mask: jax.Array | None = None,
) -> jax.Array:
    enc = encode(params, input_ids, config, attention_mask=attention_mask)
    return decode(params, decoder_input_ids, enc, config, encoder_mask=attention_mask)


def loss_fn(
    params: Params,
    batch: dict[str, jax.Array],
    config: T5Config,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Seq2seq LM loss. batch: {"input_ids", "decoder_input_ids"} plus
    optional "labels" (defaults to next-token on the decoder side),
    "attention_mask" (encoder padding), "decoder_attention_mask" (loss mask)."""
    dec_in = batch["decoder_input_ids"]
    labels = batch.get("labels")
    dec_mask = batch.get("decoder_attention_mask")
    logits = forward(
        params, batch["input_ids"], dec_in, config,
        attention_mask=batch.get("attention_mask"),
    )
    if labels is None:
        labels = dec_in[:, 1:]
        loss_mask = dec_mask[:, 1:] if dec_mask is not None else None
        logits = logits[:, :-1]
    else:
        loss_mask = dec_mask
    return cross_entropy_loss(logits, labels, mask=loss_mask, z_loss=config.z_loss)


@functools.lru_cache(maxsize=16)
def _jitted_encode(config: T5Config):
    return jax.jit(lambda p, i, m: encode(p, i, config, attention_mask=m))


@functools.lru_cache(maxsize=16)
def _jitted_decode(config: T5Config):
    return jax.jit(lambda p, d, e, m: decode(p, d, e, config, encoder_mask=m))


def generate(
    params: Params,
    input_ids: jax.Array,
    config: T5Config,
    *,
    max_new_tokens: int = 32,
    bos_token_id: int = 0,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    attention_mask: jax.Array | None = None,
) -> jax.Array:
    """Greedy (or sampled) seq2seq generation: encode once, re-run the
    decoder on the growing target. Returns (B, max_new_tokens) tokens
    (including no BOS). O(T^2) decoder work — fine for seq2seq-length
    targets; cached decode belongs to the decoder-only families."""
    B = input_ids.shape[0]
    enc = _jitted_encode(config)(params, input_ids, attention_mask)
    dec_step = _jitted_decode(config)
    # Fixed-shape target buffer: the decoder always sees (B, max_new_tokens+1),
    # so the whole loop costs ONE compilation. Causal self-attention makes the
    # not-yet-written suffix (zeros) invisible to the position being read.
    tokens = jnp.zeros((B, max_new_tokens + 1), jnp.int32).at[:, 0].set(bos_token_id)
    for i in range(max_new_tokens):
        logits = dec_step(params, tokens, enc, attention_mask)[:, i]
        if temperature > 0.0:
            rng, step_rng = jax.random.split(rng if rng is not None else jax.random.PRNGKey(0))
            nxt = jax.random.categorical(step_rng, logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        tokens = tokens.at[:, i + 1].set(nxt.astype(jnp.int32))
    return tokens[:, 1:]

"""Hugging Face checkpoint interop: zero-key-map ingestion of HF repos.

Reference parity: `load_checkpoint_in_model` (`utils/modeling.py:1787`) and
`load_checkpoint_and_dispatch` (`big_modeling.py:511`) let a user point at an
HF repo directory and get a dispatched model with no manual tensor-name
mapping — the reference's core migration value prop. This module gives the
model zoo the same ergonomics, TPU-style:

    family, config, params, plan = hf.load_pretrained("/path/to/Llama-3-8B",
                                                      mesh=mesh)

`load_pretrained` reads ``config.json``, builds the matching family config
(`from_hf_config`), plans shardings against an optional HBM budget
(`infer_sharding_plan`), and streams the HF-named safetensors tensors into
the family's scan-over-layers pytree. Because this framework stacks all L
transformer blocks along a leading layer axis (one leaf per weight *kind*,
not per layer), the translation is not a plain rename: each stacked leaf
gathers L per-layer HF tensors, transposed from torch Linear's ``(out, in)``
to the einsum-native ``(in, out)`` and reshaped to split fused head dims.
Every transform is *slice-mapped* — a device asking for its planned shard of
a leaf reads only the matching byte ranges of the source tensors, so a 70B
repo never materializes a full tensor on any host (the streaming contract of
`load_checkpoint_and_dispatch`).

Supported ``model_type``s: llama, mistral, mixtral, qwen2 (the llama
family — mixtral routes through the MoE blocks, qwen2 adds q/k/v biases),
gpt2, gpt_neox, gptj, opt (the gpt family — variant knobs select rotary
style, parallel residual, activation, and bias layout; these are the
reference's published big-model-inference models,
`benchmarks/big_model_inference/README.md:27-37`), bert, vit, t5 (v1.1
gated layout). Norm weights are rebased for this framework's
``(1 + scale)`` RMSNorm parameterization where applicable.
`save_pretrained` writes the repo back out in HF layout (every family and
layout above) so `transformers` loads the export unchanged — round-trip
logit parity is tested for every family.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Callable, NamedTuple

import jax
import numpy as np
Params = Any

# A fetcher maps (read, out_idx, out_shape) -> np array for ONE layer (or the
# whole leaf when not per-layer). `read(idx)` returns the source tensor's
# slice `idx`; `out_idx` is the requested slice of the TARGET leaf (without
# the stacked layer axis); `out_shape` the target leaf shape (ditto).
Fetcher = Callable[[Callable, tuple, tuple], np.ndarray]


def _norm_idx(idx: tuple, shape: tuple) -> tuple[slice, ...]:
    return tuple(slice(*s.indices(d)) for s, d in zip(idx, shape))


def _ident(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
    return read(idx)


def _minus1(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
    # HF norm weight w -> this framework's rms_norm computes x * (1 + scale),
    # so scale = w - 1 (layers.py:69).
    arr = read(idx)
    return arr - np.asarray(1, dtype=arr.dtype)


def _t2(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
    # torch Linear (out, in) -> (in, out).
    i0, i1 = idx
    return read((i1, i0)).T


def _full(s: slice, dim: int) -> bool:
    return s.start == 0 and s.stop == dim


def _qkv(head_dim: int) -> Fetcher:
    """HF ``{q,k,v}_proj.weight`` (n_heads*h, d) -> (d, n_heads, h)."""

    def fetch(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
        ds, hs, hd = idx
        if not _full(hd, shape[2]):
            raise NotImplementedError(
                "HF streaming does not support sharding the head_dim axis "
                f"(requested {hd} of {shape[2]}); shard heads instead."
            )
        h = head_dim
        rows = slice(hs.start * h, hs.stop * h)
        arr = read((rows, ds))  # ((hs)*h, d_sub)
        return arr.T.reshape(ds.stop - ds.start, hs.stop - hs.start, h)

    return fetch


def _oproj(head_dim: int) -> Fetcher:
    """HF ``o_proj.weight`` (d, n_heads*h) -> (n_heads, h, d)."""

    def fetch(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
        hs, hd, ds = idx
        if not _full(hd, shape[1]):
            raise NotImplementedError(
                "HF streaming does not support sharding the head_dim axis "
                f"(requested {hd} of {shape[1]}); shard heads instead."
            )
        h = head_dim
        cols = slice(hs.start * h, hs.stop * h)
        arr = read((ds, cols))  # (d_sub, (hs)*h)
        return arr.T.reshape(hs.stop - hs.start, h, ds.stop - ds.start)

    return fetch


def _conv1d_qkv(d_model: int, head_dim: int, part: int) -> Fetcher:
    """GPT-2 fused ``c_attn.weight`` (d, 3d), already (in, out): block
    ``part`` (0=q, 1=k, 2=v) -> (d, n_heads, h)."""

    def fetch(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
        ds, hs, hd = idx
        if not _full(hd, shape[2]):
            raise NotImplementedError("head_dim axis must not be sharded")
        h = head_dim
        cols = slice(part * d_model + hs.start * h, part * d_model + hs.stop * h)
        arr = read((ds, cols))
        return arr.reshape(ds.stop - ds.start, hs.stop - hs.start, h)

    return fetch


def _conv1d_qkv_bias(d_model: int, head_dim: int, part: int) -> Fetcher:
    """GPT-2 fused ``c_attn.bias`` (3d,): block ``part`` -> (n_heads, h)."""

    def fetch(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
        hs, hd = idx
        if not _full(hd, shape[1]):
            raise NotImplementedError("head_dim axis must not be sharded")
        h = head_dim
        rows = slice(part * d_model + hs.start * h, part * d_model + hs.stop * h)
        return read((rows,)).reshape(hs.stop - hs.start, h)

    return fetch


def _neox_qkv(head_dim: int, part: int) -> Fetcher:
    """GPT-NeoX fused ``query_key_value.weight`` (3d, d): rows for head i
    are ``[i*3h, (i+1)*3h)`` laid out ``[q|k|v]`` PER HEAD (transformers
    views to ``(..., num_heads, 3*head_size)`` then chunks) — unlike
    GPT-2's ``[all-q|all-k|all-v]`` Conv1D blocks. -> (d, n_heads, h)."""

    def fetch(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
        ds, hs, hd = idx
        if not _full(hd, shape[2]):
            raise NotImplementedError("head_dim axis must not be sharded")
        h = head_dim
        rows = slice(hs.start * 3 * h, hs.stop * 3 * h)
        arr = read((rows, ds))  # (3h * heads, d_sub)
        arr = arr.reshape(hs.stop - hs.start, 3, h, ds.stop - ds.start)
        return np.ascontiguousarray(arr[:, part].transpose(2, 0, 1))

    return fetch


def _neox_qkv_bias(head_dim: int, part: int) -> Fetcher:
    """GPT-NeoX fused ``query_key_value.bias`` (3d,) -> (n_heads, h)."""

    def fetch(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
        hs, hd = idx
        if not _full(hd, shape[1]):
            raise NotImplementedError("head_dim axis must not be sharded")
        h = head_dim
        arr = read((slice(hs.start * 3 * h, hs.stop * 3 * h),))
        return np.ascontiguousarray(arr.reshape(-1, 3, h)[:, part])

    return fetch


def _vec_heads(head_dim: int) -> Fetcher:
    """HF flat per-head bias (n_heads*h,) -> (n_heads, h)."""

    def fetch(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
        hs, hd = idx
        if not _full(hd, shape[1]):
            raise NotImplementedError("head_dim axis must not be sharded")
        h = head_dim
        return read((slice(hs.start * h, hs.stop * h),)).reshape(
            hs.stop - hs.start, h
        )

    return fetch


@dataclass(frozen=True)
class _Src:
    """Where one target leaf comes from in the HF checkpoint.

    ``invert`` (when set) maps ONE per-layer slice of this framework's leaf
    back to the HF tensor layout — the export direction
    (`save_pretrained`)."""

    key: str  # tensor name; ``{i}`` substituted per layer when per_layer
    fetch: Fetcher = _ident
    per_layer: bool = False
    invert: Callable[[np.ndarray], np.ndarray] | None = None
    # Leaf carries a second stacked axis of per-expert HF tensors (``{e}``
    # in the template) — the Mixtral block_sparse_moe layout.
    per_expert: bool = False


# Inverse layouts for the export direction.
def _inv_ident(arr: np.ndarray) -> np.ndarray:
    return arr


def _inv_vec_heads(arr: np.ndarray) -> np.ndarray:
    # (n_heads, h) -> (n_heads*h,)
    return np.ascontiguousarray(arr.reshape(-1))


def _inv_plus1(arr: np.ndarray) -> np.ndarray:
    return arr + np.asarray(1, dtype=arr.dtype)


def _inv_t2(arr: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(arr.T)


def _inv_qkv(arr: np.ndarray) -> np.ndarray:
    # (d, n_heads, h) -> (n_heads*h, d)
    d = arr.shape[0]
    return np.ascontiguousarray(arr.reshape(d, -1).T)


def _inv_oproj(arr: np.ndarray) -> np.ndarray:
    # (n_heads, h, d) -> (d, n_heads*h)
    d = arr.shape[-1]
    return np.ascontiguousarray(arr.reshape(-1, d).T)


# --------------------------------------------------------------- family maps
def _llama_specs(config) -> dict[str, _Src]:
    h = config.resolved_head_dim
    L = "model.layers.{i}."
    m = {
        "embed": _Src("model.embed_tokens.weight", invert=_inv_ident),
        "final_norm": _Src("model.norm.weight", _minus1, invert=_inv_plus1),
        "blocks.attn_norm": _Src(
            L + "input_layernorm.weight", _minus1, True, invert=_inv_plus1
        ),
        "blocks.mlp_norm": _Src(
            L + "post_attention_layernorm.weight", _minus1, True, invert=_inv_plus1
        ),
        "blocks.attn.wq": _Src(
            L + "self_attn.q_proj.weight", _qkv(h), True, invert=_inv_qkv
        ),
        "blocks.attn.wk": _Src(
            L + "self_attn.k_proj.weight", _qkv(h), True, invert=_inv_qkv
        ),
        "blocks.attn.wv": _Src(
            L + "self_attn.v_proj.weight", _qkv(h), True, invert=_inv_qkv
        ),
        "blocks.attn.wo": _Src(
            L + "self_attn.o_proj.weight", _oproj(h), True, invert=_inv_oproj
        ),
        "blocks.mlp.w_gate": _Src(
            L + "mlp.gate_proj.weight", _t2, True, invert=_inv_t2
        ),
        "blocks.mlp.w_up": _Src(L + "mlp.up_proj.weight", _t2, True, invert=_inv_t2),
        "blocks.mlp.w_down": _Src(
            L + "mlp.down_proj.weight", _t2, True, invert=_inv_t2
        ),
    }
    if config.attn_bias:
        # Qwen2 layout: q/k/v projections carry biases (o_proj does not).
        m["blocks.attn.bq"] = _Src(
            L + "self_attn.q_proj.bias", _vec_heads(h), True, invert=_inv_vec_heads
        )
        m["blocks.attn.bk"] = _Src(
            L + "self_attn.k_proj.bias", _vec_heads(h), True, invert=_inv_vec_heads
        )
        m["blocks.attn.bv"] = _Src(
            L + "self_attn.v_proj.bias", _vec_heads(h), True, invert=_inv_vec_heads
        )
    if config.n_experts:
        # Mixtral block_sparse_moe layout: w1=gate, w3=up, w2=down, all
        # torch (out, in); router `gate.weight` is (E, d).
        E = L + "block_sparse_moe.experts.{e}."
        for leaf in ("blocks.mlp.w_gate", "blocks.mlp.w_up", "blocks.mlp.w_down"):
            del m[leaf]
        m["blocks.moe.router"] = _Src(
            L + "block_sparse_moe.gate.weight", _t2, True, invert=_inv_t2
        )
        m["blocks.moe.w_gate"] = _Src(
            E + "w1.weight", _t2, True, invert=_inv_t2, per_expert=True
        )
        m["blocks.moe.w_up"] = _Src(
            E + "w3.weight", _t2, True, invert=_inv_t2, per_expert=True
        )
        m["blocks.moe.w_down"] = _Src(
            E + "w2.weight", _t2, True, invert=_inv_t2, per_expert=True
        )
    if not config.tie_embeddings:
        m["lm_head"] = _Src("lm_head.weight", _t2, invert=_inv_t2)
    return m


def _gpt2_specs(config) -> dict[str, _Src]:
    h = config.attention_spec.head_dim
    d = config.d_model
    L = "h.{i}."
    m = {
        "wte": _Src("wte.weight"),
        "wpe": _Src("wpe.weight"),
        "lnf_scale": _Src("ln_f.weight"),
        "lnf_bias": _Src("ln_f.bias"),
        "blocks.ln1_scale": _Src(L + "ln_1.weight", _ident, True),
        "blocks.ln1_bias": _Src(L + "ln_1.bias", _ident, True),
        "blocks.ln2_scale": _Src(L + "ln_2.weight", _ident, True),
        "blocks.ln2_bias": _Src(L + "ln_2.bias", _ident, True),
        "blocks.attn.wq": _Src(L + "attn.c_attn.weight", _conv1d_qkv(d, h, 0), True),
        "blocks.attn.wk": _Src(L + "attn.c_attn.weight", _conv1d_qkv(d, h, 1), True),
        "blocks.attn.wv": _Src(L + "attn.c_attn.weight", _conv1d_qkv(d, h, 2), True),
        "blocks.attn.bq": _Src(L + "attn.c_attn.bias", _conv1d_qkv_bias(d, h, 0), True),
        "blocks.attn.bk": _Src(L + "attn.c_attn.bias", _conv1d_qkv_bias(d, h, 1), True),
        "blocks.attn.bv": _Src(L + "attn.c_attn.bias", _conv1d_qkv_bias(d, h, 2), True),
        # c_proj is Conv1D too: (in = H*h, out = d) — no transpose, reshape only.
        "blocks.attn.wo": _Src(L + "attn.c_proj.weight", _gpt2_oproj(h), True),
        "blocks.attn.bo": _Src(L + "attn.c_proj.bias", _ident, True),
        "blocks.mlp.w_in": _Src(L + "mlp.c_fc.weight", _ident, True),
        "blocks.mlp.b_in": _Src(L + "mlp.c_fc.bias", _ident, True),
        "blocks.mlp.w_out": _Src(L + "mlp.c_proj.weight", _ident, True),
        "blocks.mlp.b_out": _Src(L + "mlp.c_proj.bias", _ident, True),
    }
    if not config.tie_embeddings:
        # Untied head (this framework's own exports write one): HF (V, d)
        # -> (d, V).
        m["lm_head"] = _Src("lm_head.weight", _t2)
    return m


def _gpt2_oproj(head_dim: int) -> Fetcher:
    """GPT-2 ``c_proj.weight`` (n_heads*h, d) already (in, out) ->
    (n_heads, h, d): reshape only."""

    def fetch(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
        hs, hd, ds = idx
        if not _full(hd, shape[1]):
            raise NotImplementedError("head_dim axis must not be sharded")
        h = head_dim
        rows = slice(hs.start * h, hs.stop * h)
        arr = read((rows, ds))
        return arr.reshape(hs.stop - hs.start, h, ds.stop - ds.start)

    return fetch


def _neox_specs(config) -> dict[str, _Src]:
    """GPT-NeoX layout (``gpt_neox.layers.{i}.*`` + ``embed_in``/
    ``embed_out``); canonical names are unprefixed, the loader's suffix
    match absorbs the ``gpt_neox.`` root."""
    h = config.head_dim
    L = "layers.{i}."
    m = {
        "wte": _Src("embed_in.weight", invert=_inv_ident),
        "lnf_scale": _Src("final_layer_norm.weight", invert=_inv_ident),
        "lnf_bias": _Src("final_layer_norm.bias", invert=_inv_ident),
        "blocks.ln1_scale": _Src(L + "input_layernorm.weight", _ident, True, _inv_ident),
        "blocks.ln1_bias": _Src(L + "input_layernorm.bias", _ident, True, _inv_ident),
        "blocks.ln2_scale": _Src(L + "post_attention_layernorm.weight", _ident, True, _inv_ident),
        "blocks.ln2_bias": _Src(L + "post_attention_layernorm.bias", _ident, True, _inv_ident),
        "blocks.attn.wq": _Src(L + "attention.query_key_value.weight", _neox_qkv(h, 0), True),
        "blocks.attn.wk": _Src(L + "attention.query_key_value.weight", _neox_qkv(h, 1), True),
        "blocks.attn.wv": _Src(L + "attention.query_key_value.weight", _neox_qkv(h, 2), True),
        "blocks.attn.wo": _Src(L + "attention.dense.weight", _oproj(h), True, _inv_oproj),
        "blocks.mlp.w_in": _Src(L + "mlp.dense_h_to_4h.weight", _t2, True, _inv_t2),
        "blocks.mlp.b_in": _Src(L + "mlp.dense_h_to_4h.bias", _ident, True, _inv_ident),
        "blocks.mlp.w_out": _Src(L + "mlp.dense_4h_to_h.weight", _t2, True, _inv_t2),
        "blocks.mlp.b_out": _Src(L + "mlp.dense_4h_to_h.bias", _ident, True, _inv_ident),
    }
    if config.attn_bias:
        m["blocks.attn.bq"] = _Src(L + "attention.query_key_value.bias", _neox_qkv_bias(h, 0), True)
        m["blocks.attn.bk"] = _Src(L + "attention.query_key_value.bias", _neox_qkv_bias(h, 1), True)
        m["blocks.attn.bv"] = _Src(L + "attention.query_key_value.bias", _neox_qkv_bias(h, 2), True)
        m["blocks.attn.bo"] = _Src(L + "attention.dense.bias", _ident, True, _inv_ident)
    if not config.tie_embeddings:
        m["lm_head"] = _Src("embed_out.weight", _t2, invert=_inv_t2)
    return m


def _gptj_specs(config) -> dict[str, _Src]:
    """GPT-J layout (``transformer.h.{i}.*``): separate bias-free q/k/v/out
    projections, biased MLP, single shared ``ln_1``, untied biased head."""
    h = config.head_dim
    L = "h.{i}."
    m = {
        "wte": _Src("wte.weight", invert=_inv_ident),
        "lnf_scale": _Src("ln_f.weight", invert=_inv_ident),
        "lnf_bias": _Src("ln_f.bias", invert=_inv_ident),
        "blocks.ln1_scale": _Src(L + "ln_1.weight", _ident, True, _inv_ident),
        "blocks.ln1_bias": _Src(L + "ln_1.bias", _ident, True, _inv_ident),
        "blocks.attn.wq": _Src(L + "attn.q_proj.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.wk": _Src(L + "attn.k_proj.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.wv": _Src(L + "attn.v_proj.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.wo": _Src(L + "attn.out_proj.weight", _oproj(h), True, _inv_oproj),
        "blocks.mlp.w_in": _Src(L + "mlp.fc_in.weight", _t2, True, _inv_t2),
        "blocks.mlp.b_in": _Src(L + "mlp.fc_in.bias", _ident, True, _inv_ident),
        "blocks.mlp.w_out": _Src(L + "mlp.fc_out.weight", _t2, True, _inv_t2),
        "blocks.mlp.b_out": _Src(L + "mlp.fc_out.bias", _ident, True, _inv_ident),
    }
    if not config.tie_embeddings:
        m["lm_head"] = _Src("lm_head.weight", _t2, invert=_inv_t2)
        if config.head_bias:
            m["lm_head_bias"] = _Src("lm_head.bias", invert=_inv_ident)
    return m


def _inv_opt_pos(arr: np.ndarray) -> np.ndarray:
    # Re-prepend OPTLearnedPositionalEmbedding's 2 offset rows (never read
    # at inference — position lookups add offset 2).
    return np.concatenate([np.zeros((2, arr.shape[1]), arr.dtype), arr])


def _opt_specs(config) -> dict[str, _Src]:
    """OPT layout (``model.decoder.layers.{i}.*``). ``embed_positions`` has
    a 2-row lookup offset (transformers ``OPTLearnedPositionalEmbedding``);
    the fetch slices it off so forward uses plain 0-based positions. The
    per-layer ``final_layer_norm`` is the MLP's pre-norm (ln2) — only the
    top-level ``decoder.final_layer_norm`` is the real final norm, and the
    canonical names keep the ``decoder.`` segment so the suffix match can't
    confuse the two."""
    h = config.head_dim

    def pos_fetch(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
        i0, i1 = _norm_idx(idx, shape)
        return read((slice(i0.start + 2, i0.stop + 2), i1))

    L = "decoder.layers.{i}."
    m = {
        "wte": _Src("decoder.embed_tokens.weight", invert=_inv_ident),
        "wpe": _Src("decoder.embed_positions.weight", pos_fetch, invert=_inv_opt_pos),
        "lnf_scale": _Src("decoder.final_layer_norm.weight", invert=_inv_ident),
        "lnf_bias": _Src("decoder.final_layer_norm.bias", invert=_inv_ident),
        "blocks.ln1_scale": _Src(L + "self_attn_layer_norm.weight", _ident, True, _inv_ident),
        "blocks.ln1_bias": _Src(L + "self_attn_layer_norm.bias", _ident, True, _inv_ident),
        "blocks.ln2_scale": _Src(L + "final_layer_norm.weight", _ident, True, _inv_ident),
        "blocks.ln2_bias": _Src(L + "final_layer_norm.bias", _ident, True, _inv_ident),
        "blocks.attn.wq": _Src(L + "self_attn.q_proj.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.wk": _Src(L + "self_attn.k_proj.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.wv": _Src(L + "self_attn.v_proj.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.bq": _Src(L + "self_attn.q_proj.bias", _vec_heads(h), True, _inv_vec_heads),
        "blocks.attn.bk": _Src(L + "self_attn.k_proj.bias", _vec_heads(h), True, _inv_vec_heads),
        "blocks.attn.bv": _Src(L + "self_attn.v_proj.bias", _vec_heads(h), True, _inv_vec_heads),
        "blocks.attn.wo": _Src(L + "self_attn.out_proj.weight", _oproj(h), True, _inv_oproj),
        "blocks.attn.bo": _Src(L + "self_attn.out_proj.bias", _ident, True, _inv_ident),
        "blocks.mlp.w_in": _Src(L + "fc1.weight", _t2, True, _inv_t2),
        "blocks.mlp.b_in": _Src(L + "fc1.bias", _ident, True, _inv_ident),
        "blocks.mlp.w_out": _Src(L + "fc2.weight", _t2, True, _inv_t2),
        "blocks.mlp.b_out": _Src(L + "fc2.bias", _ident, True, _inv_ident),
    }
    if not config.tie_embeddings:
        # Every released OPT ties, but an untied config must still map its
        # head — otherwise export silently drops the trained weight.
        m["lm_head"] = _Src("lm_head.weight", _t2, invert=_inv_t2)
    return m


def _gpt_specs(config) -> dict[str, _Src]:
    layout = getattr(config, "hf_layout", "gpt2")
    builder = {
        "gpt2": _gpt2_specs,
        "gpt_neox": _neox_specs,
        "gptj": _gptj_specs,
        "opt": _opt_specs,
    }.get(layout)
    if builder is None:
        raise ValueError(
            f"GPTConfig.hf_layout={layout!r} has no HF map; known: gpt2, "
            "gpt_neox, gptj, opt."
        )
    return builder(config)


def _bert_specs(config) -> dict[str, _Src]:
    h = config.attention_spec.head_dim
    E = "embeddings."
    L = "encoder.layer.{i}."
    return {
        "tok_embed": _Src(E + "word_embeddings.weight", invert=_inv_ident),
        "pos_embed": _Src(E + "position_embeddings.weight", invert=_inv_ident),
        "type_embed": _Src(E + "token_type_embeddings.weight", invert=_inv_ident),
        "embed_norm_scale": _Src(E + "LayerNorm.weight", invert=_inv_ident),
        "embed_norm_bias": _Src(E + "LayerNorm.bias", invert=_inv_ident),
        "blocks.attn.wq": _Src(L + "attention.self.query.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.wk": _Src(L + "attention.self.key.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.wv": _Src(L + "attention.self.value.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.bq": _Src(L + "attention.self.query.bias", _vec_heads(h), True, _inv_vec_heads),
        "blocks.attn.bk": _Src(L + "attention.self.key.bias", _vec_heads(h), True, _inv_vec_heads),
        "blocks.attn.bv": _Src(L + "attention.self.value.bias", _vec_heads(h), True, _inv_vec_heads),
        "blocks.attn.wo": _Src(L + "attention.output.dense.weight", _oproj(h), True, _inv_oproj),
        "blocks.attn.bo": _Src(L + "attention.output.dense.bias", _ident, True, _inv_ident),
        "blocks.attn_norm_scale": _Src(L + "attention.output.LayerNorm.weight", _ident, True, _inv_ident),
        "blocks.attn_norm_bias": _Src(L + "attention.output.LayerNorm.bias", _ident, True, _inv_ident),
        "blocks.mlp.w_in": _Src(L + "intermediate.dense.weight", _t2, True, _inv_t2),
        "blocks.mlp.b_in": _Src(L + "intermediate.dense.bias", _ident, True, _inv_ident),
        "blocks.mlp.w_out": _Src(L + "output.dense.weight", _t2, True, _inv_t2),
        "blocks.mlp.b_out": _Src(L + "output.dense.bias", _ident, True, _inv_ident),
        "blocks.mlp_norm_scale": _Src(L + "output.LayerNorm.weight", _ident, True, _inv_ident),
        "blocks.mlp_norm_bias": _Src(L + "output.LayerNorm.bias", _ident, True, _inv_ident),
        "pooler.w": _Src("pooler.dense.weight", _t2, invert=_inv_t2),
        "pooler.b": _Src("pooler.dense.bias", invert=_inv_ident),
        "classifier.w": _Src("classifier.weight", _t2, invert=_inv_t2),
        "classifier.b": _Src("classifier.bias", invert=_inv_ident),
    }


def _vit_specs(config) -> dict[str, _Src]:
    h = config.attention_spec.head_dim
    E = "embeddings."
    L = "encoder.layer.{i}."

    def patch_fetch(read: Callable, idx: tuple, shape: tuple) -> np.ndarray:
        # HF conv kernel (d, C, p, p) -> patchify matmul weight (p*p*C, d).
        # Patch rows are ordered (p, p, C) here (image unfolded HWC); torch
        # conv weight is (d, C, p, p) -> permute to (p, p, C, d) then flatten.
        i0, i1 = idx
        arr = read((i1, slice(None), slice(None), slice(None)))
        arr = np.transpose(arr, (2, 3, 1, 0)).reshape(-1, i1.stop - i1.start)
        return arr[i0]

    def patch_invert(arr: np.ndarray) -> np.ndarray:
        # (p*p*C, d) -> conv kernel (d, C, p, p)
        p_sz, C = config.patch_size, config.channels
        d = arr.shape[-1]
        return np.ascontiguousarray(
            arr.reshape(p_sz, p_sz, C, d).transpose(3, 2, 0, 1)
        )

    return {
        "patch_proj.w": _Src(E + "patch_embeddings.projection.weight", patch_fetch, invert=patch_invert),
        "patch_proj.b": _Src(E + "patch_embeddings.projection.bias", invert=_inv_ident),
        "cls_token": _Src(E + "cls_token", lambda r, i, s: r((slice(0, 1), slice(0, 1), i[0]))[0, 0],
                          invert=lambda a: a[None, None, :]),
        "pos_embed": _Src(E + "position_embeddings", lambda r, i, s: r((slice(0, 1), i[0], i[1]))[0],
                          invert=lambda a: a[None]),
        "lnf_scale": _Src("layernorm.weight", invert=_inv_ident),
        "lnf_bias": _Src("layernorm.bias", invert=_inv_ident),
        "blocks.ln1_scale": _Src(L + "layernorm_before.weight", _ident, True, _inv_ident),
        "blocks.ln1_bias": _Src(L + "layernorm_before.bias", _ident, True, _inv_ident),
        "blocks.ln2_scale": _Src(L + "layernorm_after.weight", _ident, True, _inv_ident),
        "blocks.ln2_bias": _Src(L + "layernorm_after.bias", _ident, True, _inv_ident),
        "blocks.attn.wq": _Src(L + "attention.attention.query.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.wk": _Src(L + "attention.attention.key.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.wv": _Src(L + "attention.attention.value.weight", _qkv(h), True, _inv_qkv),
        "blocks.attn.bq": _Src(L + "attention.attention.query.bias", _vec_heads(h), True, _inv_vec_heads),
        "blocks.attn.bk": _Src(L + "attention.attention.key.bias", _vec_heads(h), True, _inv_vec_heads),
        "blocks.attn.bv": _Src(L + "attention.attention.value.bias", _vec_heads(h), True, _inv_vec_heads),
        "blocks.attn.wo": _Src(L + "attention.output.dense.weight", _oproj(h), True, _inv_oproj),
        "blocks.attn.bo": _Src(L + "attention.output.dense.bias", _ident, True, _inv_ident),
        "blocks.mlp.w_in": _Src(L + "intermediate.dense.weight", _t2, True, _inv_t2),
        "blocks.mlp.b_in": _Src(L + "intermediate.dense.bias", _ident, True, _inv_ident),
        "blocks.mlp.w_out": _Src(L + "output.dense.weight", _t2, True, _inv_t2),
        "blocks.mlp.b_out": _Src(L + "output.dense.bias", _ident, True, _inv_ident),
        "head.w": _Src("classifier.weight", _t2, invert=_inv_t2),
        "head.b": _Src("classifier.bias", invert=_inv_ident),
    }


def _t5_specs(config) -> dict[str, _Src]:
    """T5 **v1.1** layout (gated-gelu `DenseGatedActDense`, untied head).
    The rel-bias tables live only on block 0 in HF; this framework keeps one
    shared table per stack, which is the same tensor."""
    h = config.head_dim
    E = "encoder.block.{i}.layer."
    D = "decoder.block.{i}.layer."
    m = {
        "embed": _Src("shared.weight", invert=_inv_ident),
        "enc_rel_bias": _Src(
            "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight",
            invert=_inv_ident,
        ),
        "dec_rel_bias": _Src(
            "decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight",
            invert=_inv_ident,
        ),
        "enc_final_norm": _Src("encoder.final_layer_norm.weight", _minus1, invert=_inv_plus1),
        "dec_final_norm": _Src("decoder.final_layer_norm.weight", _minus1, invert=_inv_plus1),
        "encoder.attn_norm": _Src(E + "0.layer_norm.weight", _minus1, True, _inv_plus1),
        "encoder.attn.wq": _Src(E + "0.SelfAttention.q.weight", _qkv(h), True, _inv_qkv),
        "encoder.attn.wk": _Src(E + "0.SelfAttention.k.weight", _qkv(h), True, _inv_qkv),
        "encoder.attn.wv": _Src(E + "0.SelfAttention.v.weight", _qkv(h), True, _inv_qkv),
        "encoder.attn.wo": _Src(E + "0.SelfAttention.o.weight", _oproj(h), True, _inv_oproj),
        "encoder.mlp_norm": _Src(E + "1.layer_norm.weight", _minus1, True, _inv_plus1),
        "encoder.mlp.w_gate": _Src(E + "1.DenseReluDense.wi_0.weight", _t2, True, _inv_t2),
        "encoder.mlp.w_up": _Src(E + "1.DenseReluDense.wi_1.weight", _t2, True, _inv_t2),
        "encoder.mlp.w_down": _Src(E + "1.DenseReluDense.wo.weight", _t2, True, _inv_t2),
        "decoder.self_norm": _Src(D + "0.layer_norm.weight", _minus1, True, _inv_plus1),
        "decoder.self_attn.wq": _Src(D + "0.SelfAttention.q.weight", _qkv(h), True, _inv_qkv),
        "decoder.self_attn.wk": _Src(D + "0.SelfAttention.k.weight", _qkv(h), True, _inv_qkv),
        "decoder.self_attn.wv": _Src(D + "0.SelfAttention.v.weight", _qkv(h), True, _inv_qkv),
        "decoder.self_attn.wo": _Src(D + "0.SelfAttention.o.weight", _oproj(h), True, _inv_oproj),
        "decoder.cross_norm": _Src(D + "1.layer_norm.weight", _minus1, True, _inv_plus1),
        "decoder.cross_attn.wq": _Src(D + "1.EncDecAttention.q.weight", _qkv(h), True, _inv_qkv),
        "decoder.cross_attn.wk": _Src(D + "1.EncDecAttention.k.weight", _qkv(h), True, _inv_qkv),
        "decoder.cross_attn.wv": _Src(D + "1.EncDecAttention.v.weight", _qkv(h), True, _inv_qkv),
        "decoder.cross_attn.wo": _Src(D + "1.EncDecAttention.o.weight", _oproj(h), True, _inv_oproj),
        "decoder.mlp_norm": _Src(D + "2.layer_norm.weight", _minus1, True, _inv_plus1),
        "decoder.mlp.w_gate": _Src(D + "2.DenseReluDense.wi_0.weight", _t2, True, _inv_t2),
        "decoder.mlp.w_up": _Src(D + "2.DenseReluDense.wi_1.weight", _t2, True, _inv_t2),
        "decoder.mlp.w_down": _Src(D + "2.DenseReluDense.wo.weight", _t2, True, _inv_t2),
    }
    if not config.tie_embeddings:
        m["lm_head"] = _Src("lm_head.weight", _t2, invert=_inv_t2)
    return m


_SPEC_BUILDERS: dict[str, Callable[[Any], dict[str, _Src]]] = {
    "llama": _llama_specs,
    "gpt": _gpt_specs,
    "bert": _bert_specs,
    "vit": _vit_specs,
    "t5": _t5_specs,
}


def hf_key_specs(family: str, config: Any) -> dict[str, _Src]:
    """The built-in leaf-path -> HF-tensor map for a model family."""
    try:
        return _SPEC_BUILDERS[family](config)
    except KeyError:
        raise ValueError(
            f"No built-in HF map for family {family!r}; known: "
            f"{sorted(_SPEC_BUILDERS)}. Use load_checkpoint_and_dispatch "
            "with an explicit key_map instead."
        ) from None


# ------------------------------------------------------------ config parsing
def _num_labels(config: dict, default: int = 2) -> int:
    """transformers serializes num_labels as the id2label map."""
    if "num_labels" in config:
        return config["num_labels"]
    if config.get("id2label"):
        return len(config["id2label"])
    return default


def resolve_repo(path_or_id: str) -> str:
    """Local directory/file passthrough, or Hugging Face Hub id resolution
    (reference `create_empty_model` accepts Hub names, `commands/estimate.py:64`).

    Hub ids resolve cache-first (`snapshot_download(local_files_only=True)`
    — works fully offline against a pre-populated HF_HUB_CACHE), then via
    the network; both failing raises with the pre-download remedy."""
    path = os.fspath(path_or_id)
    if os.path.exists(path):
        return path
    # Hub ids look like "org/name" (or bare "name"): no absolute/relative
    # filesystem syntax.
    if path.startswith((".", "/", "~")) or path.count("/") > 1:
        raise ValueError(f"checkpoint path {path!r} does not exist")
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:
        raise ValueError(
            f"{path!r} is not a local directory and huggingface_hub is not "
            "installed to resolve it as a Hub id."
        ) from e
    patterns = ["*.safetensors", "*.safetensors.index.json", "config.json"]
    # huggingface_hub latches HF_HUB_CACHE at import; read the env at call
    # time so per-process/per-test cache dirs work.
    cache_dir = os.environ.get("HF_HUB_CACHE") or None
    try:
        return snapshot_download(
            path, allow_patterns=patterns, local_files_only=True,
            cache_dir=cache_dir,
        )
    except Exception:
        pass
    try:
        return snapshot_download(path, allow_patterns=patterns, cache_dir=cache_dir)
    except Exception as e:
        raise ValueError(
            f"{path!r} is not a local directory, is not in the local Hub "
            f"cache, and could not be downloaded ({type(e).__name__}: {e}). "
            "In an air-gapped environment, pre-download with "
            f"`huggingface-cli download {path}` on a connected machine and "
            "point HF_HUB_CACHE at the result, or pass a local repo path."
        ) from e


def _parse_rope_scaling(rs: dict | None, RopeScaling: Any) -> Any:
    """HF ``rope_scaling`` dict -> layers.RopeScaling (or None).

    Implements the two schemes real llama-family checkpoints ship:
    ``llama3`` (every Llama-3.1/3.2 repo) and ``linear`` position
    interpolation; anything else (yarn, dynamic-NTK, longrope) still fails
    loudly — those change the frequency tables per sequence length and are
    not implemented."""
    if rs is None:
        return None
    rtype = rs.get("rope_type") or rs.get("type") or "default"
    if rtype == "default":
        return None
    if rtype == "llama3":
        return RopeScaling(
            rope_type="llama3",
            factor=float(rs["factor"]),
            low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
            high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
            original_max_position_embeddings=int(
                rs.get("original_max_position_embeddings", 8192)
            ),
        )
    if rtype == "linear":
        return RopeScaling(rope_type="linear", factor=float(rs["factor"]))
    raise ValueError(
        f"This checkpoint uses rope_scaling rope_type={rtype!r}; implemented "
        "types: 'llama3' (Llama-3.1+), 'linear'. Loading with plain RoPE "
        "would silently diverge from the original model."
    )


def from_hf_config(config: Any) -> tuple[str, Any]:
    """Translate an HF ``config.json`` (dict, file path, or repo dir) into
    ``(family, FamilyConfig)`` for this framework's model zoo."""
    if isinstance(config, (str, os.PathLike)):
        path = os.fspath(config)
        if path.endswith(".json"):
            if not os.path.exists(path):
                raise ValueError(f"checkpoint config {path!r} does not exist")
        else:
            path = resolve_repo(path)
        if os.path.isdir(path):
            path = os.path.join(path, "config.json")
        with open(path) as f:
            config = json.load(f)
    mt = config.get("model_type")
    if mt in ("llama", "mistral", "mixtral", "qwen2"):
        from .layers import RopeScaling
        from .llama import LlamaConfig

        # Refuse architecture-affecting knobs this family doesn't implement:
        # loading would succeed but every forward pass would silently diverge
        # from transformers' output — the opposite of the parity contract.
        # (hidden_act is validated for the same reason: a llama variant with
        # hidden_act="gelu" would load cleanly and silently diverge.)
        act = config.get("hidden_act", "silu")
        if act != "silu":
            raise ValueError(
                f"This llama-family checkpoint uses hidden_act={act!r}; the "
                "block here hardwires the standard silu/swiglu MLP — logits "
                "would silently diverge if the activation were substituted."
            )
        rope_scaling = _parse_rope_scaling(config.get("rope_scaling"), RopeScaling)
        # Community llama variants can carry q/k/v/o and MLP biases
        # (LlamaConfig.attention_bias / mlp_bias); the block here models
        # q/k/v biases only in the qwen2 layout — anything else would load
        # with silently dropped tensors.
        if mt != "qwen2" and config.get("attention_bias"):
            raise ValueError(
                "This llama-family checkpoint sets attention_bias=true "
                "(biases on q/k/v/o projections); only the qwen2 bias "
                "layout (q/k/v, no o_proj bias) is implemented — logits "
                "would silently diverge if the biases were dropped."
            )
        if config.get("mlp_bias"):
            raise ValueError(
                "This checkpoint sets mlp_bias=true; the llama family here "
                "has bias-free MLPs — loading would silently drop tensors."
            )
        sliding = config.get("sliding_window")
        if mt == "qwen2":
            # HF qwen2 applies the window only to layers i >= max_window_layers
            # (layer_types in Qwen2Config; default 28). Uniform SWA therefore
            # means max_window_layers == 0; max_window_layers >= num layers
            # means NO layer uses it (full attention everywhere).
            mwl = config.get("max_window_layers", 28)
            if not config.get("use_sliding_window", False):
                sliding = None  # qwen2 ships the field but disables the feature
            elif mwl >= config["num_hidden_layers"]:
                sliding = None  # window enabled but banded past the last layer
            elif mwl != 0:
                # A mixed schedule (full attention below mwl, SWA above) would
                # silently diverge on one band or the other; this family
                # applies one attention pattern uniformly.
                raise ValueError(
                    "This qwen2 checkpoint enables sliding-window attention "
                    f"on a subset of layers (max_window_layers={mwl} of "
                    f"{config['num_hidden_layers']}); only uniform windows "
                    "(max_window_layers=0) are implemented."
                )
        if mt == "mixtral" and sliding:
            # Mixtral-8x7B-v0.1 ships sliding_window=4096 in some revisions
            # but the released model was trained (and is served by
            # transformers) with full attention when the context fits; the
            # window composes with MoE untested here, so refuse loudly.
            raise ValueError(
                "sliding_window on a mixtral checkpoint is not supported "
                "(the MoE block + window composition is untested); edit the "
                "config to sliding_window=null if the model was trained "
                "with full attention."
            )

        return "llama", LlamaConfig(
            vocab_size=config["vocab_size"],
            d_model=config["hidden_size"],
            n_layers=config["num_hidden_layers"],
            num_heads=config["num_attention_heads"],
            num_kv_heads=config.get(
                "num_key_value_heads", config["num_attention_heads"]
            ),
            d_ff=config["intermediate_size"],
            head_dim=config.get("head_dim"),
            max_seq_len=config.get("max_position_embeddings", 8192),
            rope_theta=config.get("rope_theta", 10000.0),
            rope_scaling=rope_scaling,
            sliding_window=sliding,
            norm_eps=config.get("rms_norm_eps", 1e-5),
            tie_embeddings=config.get("tie_word_embeddings", False),
            # Qwen2 = llama block + q/k/v biases.
            attn_bias=(mt == "qwen2"),
            # Mixtral: routed experts replace every block's FFN. A capacity
            # factor of E/k removes dropping entirely, matching HF's
            # capacity-free routing exactly (ops/moe.py renormalizes kept
            # gates the same way Mixtral softmaxes over the top-k).
            n_experts=config.get("num_local_experts", 0),
            moe_top_k=config.get("num_experts_per_tok", 2),
            moe_capacity_factor=(
                config["num_local_experts"] / config.get("num_experts_per_tok", 2)
                if config.get("num_local_experts")
                else 1.25
            ),
        )
    if mt == "gpt2":
        from .gpt import GPTConfig

        act = config.get("activation_function", "gelu_new")
        if act != "gelu_new":
            raise ValueError(
                f"This GPT-2 checkpoint uses activation_function={act!r}; "
                "the block here hardwires gelu_new (the tanh approximation) "
                "— logits would silently diverge otherwise."
            )
        d = config["n_embd"]
        return "gpt", GPTConfig(
            vocab_size=config["vocab_size"],
            d_model=d,
            n_layers=config["n_layer"],
            num_heads=config["n_head"],
            d_ff=config.get("n_inner") or 4 * d,
            max_seq_len=config.get("n_positions", 1024),
            norm_eps=config.get("layer_norm_epsilon", 1e-5),
            tie_embeddings=config.get("tie_word_embeddings", True),
        )
    if mt == "gpt_neox":
        from .gpt import GPTConfig

        act = {"gelu": "gelu", "gelu_new": "gelu_new", "gelu_fast": "gelu_new"}.get(
            config.get("hidden_act", "gelu")
        )
        if act is None:
            raise ValueError(
                f"This GPT-NeoX checkpoint uses hidden_act="
                f"{config.get('hidden_act')!r}; implemented: gelu, gelu_new, "
                "gelu_fast — logits would silently diverge otherwise."
            )
        rs = config.get("rope_scaling")
        if rs and (rs.get("rope_type") or rs.get("type") or "default") != "default":
            raise ValueError(
                "rope_scaling on a GPT-NeoX checkpoint is not implemented "
                "for this family (no released NeoX-lineage checkpoint ships "
                "one); loading with unscaled rotary would silently diverge."
            )
        d = config["hidden_size"]
        head_dim = d // config["num_attention_heads"]
        return "gpt", GPTConfig(
            vocab_size=config["vocab_size"],
            d_model=d,
            n_layers=config["num_hidden_layers"],
            num_heads=config["num_attention_heads"],
            d_ff=config["intermediate_size"],
            max_seq_len=config.get("max_position_embeddings", 2048),
            norm_eps=config.get("layer_norm_eps", 1e-5),
            tie_embeddings=config.get("tie_word_embeddings", False),
            hf_layout="gpt_neox",
            positional="rotary",
            # 0.25 is GPTNeoXConfig's default — an omitted rotary_pct means
            # quarter-head rotary, not full-head.
            rotary_dim=int(head_dim * config.get("rotary_pct", 0.25)),
            rope_theta=float(
                config.get("rotary_emb_base", config.get("rope_theta", 10000.0))
            ),
            parallel_residual=config.get("use_parallel_residual", True),
            activation=act,
            attn_bias=config.get("attention_bias", True),
        )
    if mt == "gptj":
        from .gpt import GPTConfig

        act = config.get("activation_function", "gelu_new")
        if act not in ("gelu_new", "gelu_fast"):
            raise ValueError(
                f"This GPT-J checkpoint uses activation_function={act!r}; "
                "the family hardwires gelu_new — logits would silently "
                "diverge otherwise."
            )
        d = config["n_embd"]
        tie = config.get("tie_word_embeddings", False)
        # 64 is GPTJConfig's default when the key is omitted; an EXPLICIT
        # null selects a transformers code path whose table sizing is tied
        # to embed_dim (broken for multi-head) — refuse rather than guess.
        rotary_dim = config.get("rotary_dim", 64)
        if rotary_dim is None:
            raise ValueError(
                "This GPT-J checkpoint sets rotary_dim=null; the "
                "full-embedding rotary path is not implemented — set the "
                "trained rotary_dim explicitly."
            )
        return "gpt", GPTConfig(
            vocab_size=config["vocab_size"],
            d_model=d,
            n_layers=config["n_layer"],
            num_heads=config["n_head"],
            d_ff=config.get("n_inner") or 4 * d,
            max_seq_len=config.get("n_positions", 2048),
            norm_eps=config.get("layer_norm_epsilon", 1e-5),
            tie_embeddings=tie,
            hf_layout="gptj",
            positional="rotary",
            rotary_dim=rotary_dim,
            rotary_interleaved=True,
            parallel_residual=True,
            shared_parallel_norm=True,
            attn_bias=False,
            head_bias=not tie,
        )
    if mt == "opt":
        from .gpt import GPTConfig

        # The 350m checkpoint (post-LN + a d_model!=word_embed_proj_dim
        # projection) and the bias-free research variants change the block
        # structure itself; loading them into this layout would silently
        # diverge, so they fail loudly.
        if not config.get("do_layer_norm_before", True):
            raise ValueError(
                "This OPT checkpoint uses post-layernorm blocks "
                "(do_layer_norm_before=false, the 350m layout); only the "
                "pre-LN layout is implemented."
            )
        if config.get("word_embed_proj_dim", config["hidden_size"]) != config["hidden_size"]:
            raise ValueError(
                "This OPT checkpoint projects embeddings "
                f"(word_embed_proj_dim={config['word_embed_proj_dim']} != "
                f"hidden_size={config['hidden_size']}); the projection "
                "layers are not implemented."
            )
        if not config.get("enable_bias", True) or not config.get(
            "layer_norm_elementwise_affine", True
        ):
            raise ValueError(
                "This OPT checkpoint disables projection biases or affine "
                "layernorms; only the standard released layout is implemented."
            )
        if config.get("_remove_final_layer_norm"):
            raise ValueError(
                "This OPT checkpoint sets _remove_final_layer_norm (a "
                "pre-release conversion quirk); re-convert with a current "
                "transformers before loading."
            )
        act = config.get("activation_function", "relu")
        if act not in ("relu", "gelu", "gelu_new"):
            raise ValueError(
                f"This OPT checkpoint uses activation_function={act!r}; "
                "implemented: relu, gelu, gelu_new."
            )
        return "gpt", GPTConfig(
            vocab_size=config["vocab_size"],
            d_model=config["hidden_size"],
            n_layers=config["num_hidden_layers"],
            num_heads=config["num_attention_heads"],
            d_ff=config["ffn_dim"],
            max_seq_len=config.get("max_position_embeddings", 2048),
            # torch nn.LayerNorm default — OPT has no eps config field.
            norm_eps=1e-5,
            tie_embeddings=config.get("tie_word_embeddings", True),
            hf_layout="opt",
            activation=act,
        )
    if mt == "bert":
        from .bert import BertConfig

        act = config.get("hidden_act", "gelu")
        if act != "gelu":
            raise ValueError(
                f"This BERT checkpoint uses hidden_act={act!r}; the block "
                "here hardwires the exact-erf gelu — logits would silently "
                "diverge otherwise."
            )
        return "bert", BertConfig(
            vocab_size=config["vocab_size"],
            d_model=config["hidden_size"],
            n_layers=config["num_hidden_layers"],
            num_heads=config["num_attention_heads"],
            d_ff=config["intermediate_size"],
            max_seq_len=config.get("max_position_embeddings", 512),
            type_vocab_size=config.get("type_vocab_size", 2),
            norm_eps=config.get("layer_norm_eps", 1e-12),
            num_labels=_num_labels(config),
        )
    if mt == "vit":
        from .vit import ViTConfig

        act = config.get("hidden_act", "gelu")
        if act != "gelu":
            raise ValueError(
                f"This ViT checkpoint uses hidden_act={act!r}; the block "
                "here hardwires the exact-erf gelu — logits would silently "
                "diverge otherwise."
            )
        return "vit", ViTConfig(
            image_size=config.get("image_size", 224),
            patch_size=config.get("patch_size", 16),
            d_model=config["hidden_size"],
            n_layers=config["num_hidden_layers"],
            num_heads=config["num_attention_heads"],
            d_ff=config["intermediate_size"],
            norm_eps=config.get("layer_norm_eps", 1e-12),
            num_classes=_num_labels(config),
        )
    if mt == "t5":
        from .t5 import T5Config

        ff_proj = config.get("feed_forward_proj", "relu")
        if ff_proj != "gated-gelu":
            raise ValueError(
                f"This T5 checkpoint uses feed_forward_proj={ff_proj!r}; the "
                "t5 family here implements the v1.1 gated-gelu layout only "
                "(ungated relu and gated-silu would silently diverge) — use "
                "a google/t5-v1_1-* style checkpoint."
            )
        return "t5", T5Config(
            vocab_size=config["vocab_size"],
            d_model=config["d_model"],
            n_encoder_layers=config["num_layers"],
            n_decoder_layers=config.get("num_decoder_layers", config["num_layers"]),
            num_heads=config["num_heads"],
            head_dim=config["d_kv"],
            d_ff=config["d_ff"],
            rel_buckets=config.get("relative_attention_num_buckets", 32),
            rel_max_distance=config.get("relative_attention_max_distance", 128),
            norm_eps=config.get("layer_norm_epsilon", 1e-6),
            tie_embeddings=config.get("tie_word_embeddings", True),
        )
    raise ValueError(
        f"Unsupported HF model_type {mt!r}; supported: llama, mistral, "
        "mixtral, qwen2, gpt2, gpt_neox, gptj, opt, bert, vit, t5 (v1.1 "
        "gated layout)."
    )


# --------------------------------------------------------------- entry point
class PretrainedModel(NamedTuple):
    family: str
    config: Any
    params: Params
    plan: Any


def load_pretrained(
    path: str,
    *,
    mesh=None,
    dtype: Any | None = None,
    hbm_budget: int | None = None,
    rules: Any = None,
    min_weight_size: int = 2**11,
    no_offload_patterns=(),
    quantize_bits: int | None = None,
    offload_dir: str | None = None,
) -> PretrainedModel:
    """One-call HF repo ingestion: ``config.json`` -> family config, plan
    shardings, stream weights (reference `load_checkpoint_and_dispatch`
    ergonomics, `big_modeling.py:511`, with the key map built in).

    ``path`` is a local HF repo directory (``config.json`` plus
    ``*.safetensors`` / ``*.safetensors.index.json``). ``dtype`` casts on
    the fly (e.g. ``jnp.bfloat16`` for inference deploys). ``rules``
    defaults to the family's registered TP plan (`parallel/tp.py`) so the
    params land sharded over whatever mesh axes exist — pass ``rules=()``
    explicitly to replicate instead. Leaves the plan offloads stay
    host-resident numpy, ready for `streamed_scan`.

    ``quantize_bits=8|4`` quantizes the big matmul weights ON THE WAY IN
    (the `load_and_quantize_model` analog, reference `utils/bnb.py`): each
    leaf is streamed to host, packed to int8/int4 with per-channel scales
    there, and only the packed values reach HBM — an 8B bf16 repo loads
    into ≈8/4 GiB of device memory without the full-precision weights ever
    being resident. Embeddings/norms/heads stay full precision
    (`utils/quantization.DEFAULT_SKIP_PATTERNS`); the model families
    dequantize per layer inside their scan.
    """
    from .. import models
    from ..big_modeling import infer_sharding_plan

    if mesh is None:
        from ..state import AcceleratorState

        mesh = AcceleratorState().mesh

    path = resolve_repo(path)
    family, config = from_hf_config(path)
    if rules is None:
        from ..parallel.tp import get_tp_plan

        rules = get_tp_plan(family)
    module = getattr(models, family)
    shapes = jax.eval_shape(lambda: module.init(jax.random.PRNGKey(0), config))
    plan = infer_sharding_plan(
        shapes,
        mesh,
        hbm_budget=hbm_budget,
        rules=rules,
        dtype=dtype,
        no_offload_patterns=no_offload_patterns,
        min_weight_size=min_weight_size,
    )
    params = load_hf_checkpoint(
        shapes, path, plan, family=family, config=config, dtype=dtype,
        quantize_bits=quantize_bits, offload_dir=offload_dir,
    )
    return PretrainedModel(family, config, params, plan)


def _make_quantize_override(plan, bits):
    """leaf_override for `dispatch_leaves`: pack eligible weights on the
    host, ship only int8/int4 + scales to device (specs sanitized to the
    packed shapes). Stacked leaves quantize ONE stack slice at a time —
    scales are per-slice, so the result is identical, but the transient
    host buffer is a single layer's worth instead of 3x the whole leaf.
    Leaves the plan offloads keep the normal host-resident bf16 path
    (`streamed_scan` owns their lifecycle)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.sharding import _path_str, _sanitize_spec
    from ..utils.quantization import leaf_quant_plan, quantize_array_host

    spec_by_key: dict[str, Any] = {}

    def spec_for(key):
        if not spec_by_key:
            leaves, _ = jax.tree_util.tree_flatten_with_path(
                plan.specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
            )
            for p, s in leaves:
                spec_by_key[_path_str(p)] = s
        return spec_by_key[key]

    def quantize_streaming(leaf, fetch, stack):
        shape = tuple(leaf.shape)
        if stack is None and leaf.ndim >= 3:
            stack = 1
        if not stack:
            full = fetch(tuple(slice(0, d) for d in shape))
            return quantize_array_host(np.asarray(full), stack_dims=0, bits=bits)
        out: dict[str, np.ndarray] = {}
        for i in range(shape[0]):
            idx = (slice(i, i + 1),) + tuple(slice(0, d) for d in shape[1:])
            part = quantize_array_host(
                np.asarray(fetch(idx)), stack_dims=stack, bits=bits
            )
            for name, arr in part.items():
                if name not in out:
                    out[name] = np.empty((shape[0],) + arr.shape[1:], arr.dtype)
                out[name][i] = arr[0]
        return out

    def override(plan_key, leaf, fetch):
        if plan_key in plan.offload:
            return None
        eligible, stack = leaf_quant_plan(plan_key, tuple(leaf.shape), leaf.dtype)
        if not eligible:
            return None

        # (host_fn, place_fn) pair: dispatch_leaves runs the read+pack on
        # its IO worker and the place stage on the transfer engine's
        # pool, overlapped with the previous leaf's device traffic.
        def host_fn():
            return quantize_streaming(leaf, fetch, stack)

        def place_fn(packed):
            spec = spec_for(plan_key)
            shardings = {
                name: NamedSharding(
                    plan.mesh, _sanitize_spec(spec, arr.shape, plan.mesh, path=plan_key)
                )
                for name, arr in packed.items()
            }
            # One pytree transfer per leaf: values + scales ride a single
            # device_put call instead of paying the link's per-call
            # overhead once per array (runs on a transfer-engine worker,
            # so packed leaves stream concurrently).
            return jax.device_put(packed, shardings)

        return host_fn, place_fn

    return override


def load_hf_checkpoint(
    shapes: Any,
    path: str,
    plan: Any,
    *,
    family: str,
    config: Any,
    dtype: Any | None = None,
    quantize_bits: int | None = None,
    offload_dir: str | None = None,
) -> Params:
    """Stream an HF-named checkpoint into sharded device buffers per
    ``plan`` using the built-in family map (the key-mapped sibling of
    `load_checkpoint_and_dispatch`; both ride
    `big_modeling.dispatch_leaves`)."""
    from ..big_modeling import _open_source, dispatch_leaves

    specs_map = hf_key_specs(family, config)
    source = _open_source(path)
    available = set(source.keys())
    _resolved: dict[str, str] = {}

    def resolve(name: str) -> str:
        """Map a canonical tensor name to the checkpoint's actual key. HF
        task wrappers prefix the backbone (``transformer.`` for
        GPT2LMHeadModel, ``bert.``/``vit.`` for classification heads); a
        unique suffix match absorbs the prefix without hardcoding it."""
        hit = _resolved.get(name)
        if hit is not None:
            return hit
        if name in available:
            _resolved[name] = name
            return name
        cands = [k for k in available if k.endswith("." + name)]
        if len(cands) == 1:
            _resolved[name] = cands[0]
            return cands[0]
        raise KeyError(
            f"Checkpoint at {path!r} has no tensor {name!r} "
            f"({'ambiguous: ' + str(cands) if cands else 'no suffix match'})."
        )

    def make_fetch(plan_key: str, leaf: Any):
        # Plan paths are '/'-joined; the maps here use '.' (HF style).
        key = plan_key.replace("/", ".")
        if key not in specs_map:
            raise KeyError(
                f"No HF mapping for model leaf {key!r} (family "
                f"{family!r}). Mapped leaves: {sorted(specs_map)}"
            )
        src = specs_map[key]
        # Resolve every needed tensor up front so a truncated repo (config
        # promising more layers than the weights hold) fails loudly before
        # any device allocation.
        if src.per_layer:
            for i in range(int(leaf.shape[0])):
                if src.per_expert:
                    for e in range(int(leaf.shape[1])):
                        resolve(src.key.format(i=i, e=e))
                else:
                    resolve(src.key.format(i=i))
        else:
            resolve(src.key)
        shape = tuple(leaf.shape)

        def read_for(name: str):
            return lambda s_idx, _k=resolve(name): np.asarray(
                source.read_slice(_k, tuple(s_idx))
            )

        def fetch_host(idx: tuple, _src=src, _shape=shape) -> np.ndarray:
            idx = _norm_idx(idx, _shape)
            if _src.per_layer:
                layers = idx[0]
                sub_idx, sub_shape = idx[1:], _shape[1:]
                planes = []
                for i in range(layers.start, layers.stop):
                    if _src.per_expert:
                        experts = sub_idx[0]
                        e_planes = [
                            _src.fetch(
                                read_for(_src.key.format(i=i, e=e)),
                                sub_idx[1:],
                                sub_shape[1:],
                            )
                            for e in range(experts.start, experts.stop)
                        ]
                        planes.append(np.stack(e_planes))
                    else:
                        planes.append(
                            _src.fetch(
                                read_for(_src.key.format(i=i)), sub_idx, sub_shape
                            )
                        )
                return np.stack(planes)
            return _src.fetch(read_for(_src.key), idx, _shape)

        return fetch_host

    try:
        return dispatch_leaves(
            shapes,
            plan,
            make_fetch,
            dtype=dtype,
            leaf_override=(
                _make_quantize_override(plan, quantize_bits)
                if quantize_bits
                else None
            ),
            offload_dir=offload_dir,
            source_id=(
                __import__("accelerate_tpu.big_modeling", fromlist=["source_fingerprint"]).source_fingerprint(path)
                if offload_dir
                else ""
            ),
        )
    finally:
        source.close()


# ----------------------------------------------------------------- export
def config_to_hf(family: str, config: Any, *, torch_dtype: str = "float32") -> dict:
    """Family config -> HF ``config.json`` payload (inverse of
    `from_hf_config`) for every exportable family."""
    if family == "llama":
        qwen = getattr(config, "attn_bias", False)
        sliding = getattr(config, "sliding_window", None)
        moe = getattr(config, "n_experts", 0)
        if moe:
            mt, arch = "mixtral", "MixtralForCausalLM"
        elif qwen:
            mt, arch = "qwen2", "Qwen2ForCausalLM"
        elif sliding is not None:
            # LlamaConfig (HF) has no sliding_window field; exporting a
            # windowed model as model_type=llama would silently drop the
            # window on reload. Mistral is the HF family with this layout.
            mt, arch = "mistral", "MistralForCausalLM"
        else:
            mt, arch = "llama", "LlamaForCausalLM"
        out = {
            "model_type": mt,
            "architectures": [arch],
            "vocab_size": config.vocab_size,
            "hidden_size": config.d_model,
            "intermediate_size": config.d_ff,
            "num_hidden_layers": config.n_layers,
            "num_attention_heads": config.num_heads,
            "num_key_value_heads": config.num_kv_heads,
            "head_dim": config.resolved_head_dim,
            "max_position_embeddings": config.max_seq_len,
            "rope_theta": config.rope_theta,
            "rms_norm_eps": config.norm_eps,
            "tie_word_embeddings": config.tie_embeddings,
            "hidden_act": "silu",
            "torch_dtype": torch_dtype,
        }
        if moe:
            out["num_local_experts"] = config.n_experts
            out["num_experts_per_tok"] = config.moe_top_k
        rs = getattr(config, "rope_scaling", None)
        if rs is not None:
            payload = {"rope_type": rs.rope_type, "factor": rs.factor}
            if rs.rope_type == "llama3":
                payload.update(
                    low_freq_factor=rs.low_freq_factor,
                    high_freq_factor=rs.high_freq_factor,
                    original_max_position_embeddings=rs.original_max_position_embeddings,
                )
            out["rope_scaling"] = payload
        if sliding is not None:
            out["sliding_window"] = sliding
            if qwen:
                out["use_sliding_window"] = True
                # 0 = every layer windowed (HF windows layers >= this index);
                # n_layers here would silently disable SWA on reload.
                out["max_window_layers"] = 0
        return out
    if family == "bert":
        return {
            "model_type": "bert",
            "architectures": ["BertForSequenceClassification"],
            "vocab_size": config.vocab_size,
            "hidden_size": config.d_model,
            "num_hidden_layers": config.n_layers,
            "num_attention_heads": config.num_heads,
            "intermediate_size": config.d_ff,
            "max_position_embeddings": config.max_seq_len,
            "type_vocab_size": config.type_vocab_size,
            "layer_norm_eps": config.norm_eps,
            "num_labels": config.num_labels,
            "id2label": {str(i): f"LABEL_{i}" for i in range(config.num_labels)},
            "torch_dtype": torch_dtype,
        }
    if family == "vit":
        return {
            "model_type": "vit",
            "architectures": ["ViTForImageClassification"],
            "image_size": config.image_size,
            "patch_size": config.patch_size,
            "hidden_size": config.d_model,
            "num_hidden_layers": config.n_layers,
            "num_attention_heads": config.num_heads,
            "intermediate_size": config.d_ff,
            "num_channels": config.channels,
            "layer_norm_eps": config.norm_eps,
            "num_labels": config.num_classes,
            "id2label": {str(i): f"LABEL_{i}" for i in range(config.num_classes)},
            "torch_dtype": torch_dtype,
        }
    if family == "t5":
        return {
            "model_type": "t5",
            "architectures": ["T5ForConditionalGeneration"],
            "vocab_size": config.vocab_size,
            "d_model": config.d_model,
            "d_kv": config.head_dim,
            "d_ff": config.d_ff,
            "num_layers": config.n_encoder_layers,
            "num_decoder_layers": config.n_decoder_layers,
            "num_heads": config.num_heads,
            "relative_attention_num_buckets": config.rel_buckets,
            "relative_attention_max_distance": config.rel_max_distance,
            "layer_norm_epsilon": config.norm_eps,
            "feed_forward_proj": "gated-gelu",
            "tie_word_embeddings": config.tie_embeddings,
            "is_encoder_decoder": True,
            "torch_dtype": torch_dtype,
        }
    if family == "gpt":
        layout = getattr(config, "hf_layout", "gpt2")
        if layout == "gpt2":
            return {
                "model_type": "gpt2",
                "architectures": ["GPT2LMHeadModel"],
                "vocab_size": config.vocab_size,
                "n_embd": config.d_model,
                "n_layer": config.n_layers,
                "n_head": config.num_heads,
                "n_inner": config.d_ff,
                "n_positions": config.max_seq_len,
                "n_ctx": config.max_seq_len,
                # The true trained activation, not a hardwired default — a
                # mislabeled config.json reloads with the wrong ACT2FN and
                # silently diverges.
                "activation_function": config.activation,
                "layer_norm_epsilon": config.norm_eps,
                "tie_word_embeddings": config.tie_embeddings,
                "torch_dtype": torch_dtype,
            }
        if layout == "gpt_neox":
            return {
                "model_type": "gpt_neox",
                "architectures": ["GPTNeoXForCausalLM"],
                "vocab_size": config.vocab_size,
                "hidden_size": config.d_model,
                "num_hidden_layers": config.n_layers,
                "num_attention_heads": config.num_heads,
                "intermediate_size": config.d_ff,
                "max_position_embeddings": config.max_seq_len,
                "rotary_pct": config.resolved_rotary_dim / config.head_dim,
                "rotary_emb_base": config.rope_theta,
                "hidden_act": config.activation,
                "use_parallel_residual": config.parallel_residual,
                "attention_bias": config.attn_bias,
                "layer_norm_eps": config.norm_eps,
                "tie_word_embeddings": config.tie_embeddings,
                "torch_dtype": torch_dtype,
            }
        if layout == "gptj":
            return {
                "model_type": "gptj",
                "architectures": ["GPTJForCausalLM"],
                "vocab_size": config.vocab_size,
                "n_embd": config.d_model,
                "n_layer": config.n_layers,
                "n_head": config.num_heads,
                "n_inner": config.d_ff,
                "n_positions": config.max_seq_len,
                "rotary_dim": config.resolved_rotary_dim,
                "activation_function": config.activation,
                "layer_norm_epsilon": config.norm_eps,
                "tie_word_embeddings": config.tie_embeddings,
                "torch_dtype": torch_dtype,
            }
        if layout == "opt":
            return {
                "model_type": "opt",
                "architectures": ["OPTForCausalLM"],
                "vocab_size": config.vocab_size,
                "hidden_size": config.d_model,
                "num_hidden_layers": config.n_layers,
                "num_attention_heads": config.num_heads,
                "ffn_dim": config.d_ff,
                "max_position_embeddings": config.max_seq_len,
                "word_embed_proj_dim": config.d_model,
                "do_layer_norm_before": True,
                "activation_function": config.activation,
                "tie_word_embeddings": config.tie_embeddings,
                "torch_dtype": torch_dtype,
            }
        raise ValueError(f"config_to_hf has no branch for gpt layout {layout!r}.")
    raise ValueError(f"config_to_hf has no branch for family {family!r}.")


def save_pretrained(
    path: str,
    family: str,
    config: Any,
    params: Params,
    *,
    max_shard_bytes: int = 4 << 30,
) -> str:
    """Export params to an HF-layout repo (``config.json`` + sharded
    safetensors with HF tensor names + ``model.safetensors.index.json``) —
    the return leg of the migration loop: a model trained here loads in
    `transformers.AutoModel.from_pretrained` unchanged. Inverse of
    `load_pretrained`; round-trip parity is tested against transformers.

    Quantized params must be dequantized first
    (`utils.quantization.dequantize_pytree`)."""
    from ..utils.quantization import has_quantized

    if has_quantized(params):
        raise ValueError(
            "save_pretrained needs full-precision params; run "
            "utils.quantization.dequantize_pytree first."
        )
    specs_map = hf_key_specs(family, config)
    # GPT-2 and GPT-NeoX re-FUSE q/k/v into one checkpoint tensor on the way
    # out — a dedicated generator, not per-leaf inverts.
    gpt_layout = getattr(config, "hf_layout", "gpt2") if family == "gpt" else None
    fused_qkv_export = gpt_layout in ("gpt2", "gpt_neox")
    if not fused_qkv_export:
        missing = [k for k, s in specs_map.items() if s.invert is None]
        if missing:
            raise NotImplementedError(
                f"Export has no inverse transform for leaves {missing[:4]} "
                f"(family {family!r})."
            )

    def leaf_for(dotted: str) -> Any:
        node: Any = params
        for part in dotted.split("."):
            node = node[part]
        return node

    # torch_dtype must reflect what lands on disk, or transformers
    # re-instantiates the export at the wrong precision.
    dtype_name = str(np.dtype(leaf_for(next(iter(specs_map))).dtype))
    if dtype_name not in ("bfloat16", "float16"):
        dtype_name = "float32"
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(config_to_hf(family, config, torch_dtype=dtype_name), f, indent=2)

    def tensors() -> Any:
        if fused_qkv_export:
            gen = _gpt2_export_tensors if gpt_layout == "gpt2" else _neox_export_tensors
            yield from gen(config, params, leaf_for)
            return
        for key, src in specs_map.items():
            leaf = leaf_for(key)
            if src.per_layer:
                # One layer slice at a time: a 70B stacked leaf is tens of
                # GiB — the full-leaf device_get would OOM the host, the
                # per-slice gather keeps the spike to one layer's worth.
                for i in range(leaf.shape[0]):
                    arr = np.asarray(jax.device_get(leaf[i]))
                    if src.per_expert:
                        # (E, ...) expert stack un-fuses back into Mixtral's
                        # block_sparse_moe.experts.{e} tensors.
                        for e in range(arr.shape[0]):
                            yield src.key.format(i=i, e=e), src.invert(arr[e])
                        continue
                    yield src.key.format(i=i), src.invert(arr)
            else:
                yield src.key, src.invert(np.asarray(jax.device_get(leaf)))

    from safetensors.numpy import save_file

    # Task-model checkpoints prefix the backbone ("bert.embeddings...",
    # "vit.encoder...") while head weights stay bare; transformers refuses
    # the load otherwise. The maps here are canonical/unprefixed, so the
    # prefix is applied on the way out.
    if family == "gpt":
        prefix, exempt = {
            "gpt2": ("transformer.", ("lm_head.",)),
            "gptj": ("transformer.", ("lm_head.",)),
            "gpt_neox": ("gpt_neox.", ("embed_out.",)),
            "opt": ("model.", ("lm_head.",)),
        }[gpt_layout]
    else:
        prefix, exempt = {
            "bert": ("bert.", ("classifier.",)),
            "vit": ("vit.", ("classifier.",)),
        }.get(family, ("", ()))

    def exported_name(name: str) -> str:
        if prefix and not name.startswith(exempt):
            return prefix + name
        return name

    weight_map: dict[str, str] = {}
    shard: dict[str, np.ndarray] = {}
    shard_bytes = 0
    shard_idx = 0

    def flush() -> None:
        nonlocal shard, shard_bytes, shard_idx
        if not shard:
            return
        fname = f"model-{shard_idx:05d}.safetensors"
        save_file(shard, os.path.join(path, fname))
        for k in shard:
            weight_map[k] = fname
        shard = {}
        shard_bytes = 0
        shard_idx += 1

    total = 0
    for name, arr in tensors():
        name = exported_name(name)
        if shard_bytes + arr.nbytes > max_shard_bytes and shard:
            flush()
        shard[name] = arr
        shard_bytes += arr.nbytes
        total += arr.nbytes
    flush()
    with open(os.path.join(path, "model.safetensors.index.json"), "w") as f:
        # transformers' hub loader requires the metadata block.
        json.dump(
            {"metadata": {"total_size": total}, "weight_map": weight_map}, f
        )
    return path


def _neox_export_tensors(config, params, leaf_for):
    """GPT-NeoX export: q/k/v re-fuse into ``query_key_value`` with the
    PER-HEAD ``[q|k|v]`` row layout (see `_neox_qkv`)."""

    def get(dotted):
        return np.asarray(jax.device_get(leaf_for(dotted)))

    yield "embed_in.weight", get("wte")
    yield "final_layer_norm.weight", get("lnf_scale")
    yield "final_layer_norm.bias", get("lnf_bias")
    if not config.tie_embeddings:
        yield "embed_out.weight", np.ascontiguousarray(get("lm_head").T)
    d = config.d_model
    for i in range(config.n_layers):
        L = f"layers.{i}."
        for ours, theirs in (
            ("ln1_scale", "input_layernorm.weight"),
            ("ln1_bias", "input_layernorm.bias"),
            ("ln2_scale", "post_attention_layernorm.weight"),
            ("ln2_bias", "post_attention_layernorm.bias"),
        ):
            yield L + theirs, np.asarray(jax.device_get(leaf_for(f"blocks.{ours}")[i]))
        attn = params["blocks"]["attn"]
        # (d, nh, h) x3 -> (nh, 3, h, d) -> (3d, d)
        qkv = np.stack(
            [np.asarray(jax.device_get(attn[k][i])).transpose(1, 2, 0) for k in ("wq", "wk", "wv")],
            axis=1,
        )
        yield L + "attention.query_key_value.weight", np.ascontiguousarray(
            qkv.reshape(-1, d)
        )
        if config.attn_bias:
            bias = np.stack(
                [np.asarray(jax.device_get(attn[k][i])) for k in ("bq", "bk", "bv")],
                axis=1,
            )  # (nh, 3, h)
            yield L + "attention.query_key_value.bias", np.ascontiguousarray(
                bias.reshape(-1)
            )
            yield L + "attention.dense.bias", np.asarray(jax.device_get(attn["bo"][i]))
        yield L + "attention.dense.weight", np.ascontiguousarray(
            np.asarray(jax.device_get(attn["wo"][i])).reshape(-1, d).T
        )
        mlp = params["blocks"]["mlp"]
        yield L + "mlp.dense_h_to_4h.weight", np.ascontiguousarray(
            np.asarray(jax.device_get(mlp["w_in"][i])).T
        )
        yield L + "mlp.dense_h_to_4h.bias", np.asarray(jax.device_get(mlp["b_in"][i]))
        yield L + "mlp.dense_4h_to_h.weight", np.ascontiguousarray(
            np.asarray(jax.device_get(mlp["w_out"][i])).T
        )
        yield L + "mlp.dense_4h_to_h.bias", np.asarray(jax.device_get(mlp["b_out"][i]))


def _gpt2_export_tensors(config, params, leaf_for):
    """GPT-2 export: unlike the 1:1 families, q/k/v re-FUSE into Conv1D
    ``c_attn`` (weights already (in, out) — concatenation, no transpose)."""

    def get(dotted):
        return np.asarray(jax.device_get(leaf_for(dotted)))

    yield "wte.weight", get("wte")
    yield "wpe.weight", get("wpe")
    yield "ln_f.weight", get("lnf_scale")
    yield "ln_f.bias", get("lnf_bias")
    if not config.tie_embeddings:
        # Untied head: params["lm_head"] is (d, V); HF stores (V, d).
        yield "lm_head.weight", np.ascontiguousarray(get("lm_head").T)
    d = config.d_model
    for i in range(config.n_layers):
        L = f"h.{i}."
        blk = {k: np.asarray(jax.device_get(leaf_for(f"blocks.{k}")[i]))
               for k in ("ln1_scale", "ln1_bias", "ln2_scale", "ln2_bias")}
        yield L + "ln_1.weight", blk["ln1_scale"]
        yield L + "ln_1.bias", blk["ln1_bias"]
        yield L + "ln_2.weight", blk["ln2_scale"]
        yield L + "ln_2.bias", blk["ln2_bias"]
        attn = params["blocks"]["attn"]
        wq, wk, wv = (np.asarray(jax.device_get(attn[k][i])).reshape(d, -1)
                      for k in ("wq", "wk", "wv"))
        yield L + "attn.c_attn.weight", np.ascontiguousarray(
            np.concatenate([wq, wk, wv], axis=1)
        )
        bq, bk, bv = (np.asarray(jax.device_get(attn[k][i])).reshape(-1)
                      for k in ("bq", "bk", "bv"))
        yield L + "attn.c_attn.bias", np.concatenate([bq, bk, bv])
        yield L + "attn.c_proj.weight", np.ascontiguousarray(
            np.asarray(jax.device_get(attn["wo"][i])).reshape(-1, d)
        )
        yield L + "attn.c_proj.bias", np.asarray(jax.device_get(attn["bo"][i]))
        mlp = params["blocks"]["mlp"]
        yield L + "mlp.c_fc.weight", np.asarray(jax.device_get(mlp["w_in"][i]))
        yield L + "mlp.c_fc.bias", np.asarray(jax.device_get(mlp["b_in"][i]))
        yield L + "mlp.c_proj.weight", np.asarray(jax.device_get(mlp["w_out"][i]))
        yield L + "mlp.c_proj.bias", np.asarray(jax.device_get(mlp["b_out"][i]))

"""TPU-native model families.

The reference owns no models (they come from `transformers` and are rewritten
post-hoc); a TPU-native framework owns them because scan-over-layers structure,
sharding plans, and attention kernels are the performance story. Each family
module exposes: a frozen ``*Config``, ``init(rng, config) -> params``,
``forward``/``loss_fn`` pure functions, and a registered TP plan
(`parallel/tp.py`).
"""

from . import bert, gpt, hf, llama, t5, vit
from .hf import from_hf_config, load_pretrained, save_pretrained
from .layers import cross_entropy_loss, dot_product_attention

__all__ = [
    "bert", "gpt", "hf", "llama", "t5", "vit",
    "cross_entropy_loss", "dot_product_attention",
    "from_hf_config", "load_pretrained", "save_pretrained",
]

"""BERT-style encoder + sequence classifier.

Parity target: the reference's canonical example trains BERT-base on GLUE/MRPC
(`examples/nlp_example.py`, perf gate `test_utils/scripts/external_deps/
test_performance.py:157-219`). This is the same architecture built TPU-native:
scan-over-layers, einsum projections, fp32 layernorm, learned positions.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .layers import (
    AttentionSpec,
    attention_out,
    attention_qkv,
    dot_product_attention,
    init_attention,
    init_mlp_gelu,
    layer_norm,
    mlp_gelu,
    truncated_normal_init,
)

Params = Any


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    num_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    num_labels: int = 2
    norm_eps: float = 1e-12
    dropout_rate: float = 0.1
    remat: bool = False

    @property
    def attention_spec(self) -> AttentionSpec:
        return AttentionSpec(self.d_model, self.num_heads, self.num_heads, self.d_model // self.num_heads)

    @classmethod
    def tiny(cls, **overrides: Any) -> "BertConfig":
        defaults = dict(vocab_size=128, d_model=32, n_layers=2, num_heads=2, d_ff=64, max_seq_len=64)
        defaults.update(overrides)
        return cls(**defaults)

    @classmethod
    def bert_base(cls, **overrides: Any) -> "BertConfig":
        return cls(**overrides)

    def param_count(self) -> int:
        d, f = self.d_model, self.d_ff
        block = 4 * d * d + 2 * d * f + 9 * d + f  # matmuls + 2 norms + mlp&attn biases
        embed = (self.vocab_size + self.max_seq_len + self.type_vocab_size) * d + 2 * d
        heads = d * d + d + d * self.num_labels + self.num_labels  # pooler + classifier
        return self.n_layers * block + embed + heads


def init_block(rng: jax.Array, config: BertConfig, dtype=jnp.float32) -> Params:
    ka, km = jax.random.split(rng)
    return {
        "attn": init_attention(ka, config.attention_spec, dtype, bias=True),
        "attn_norm_scale": jnp.ones((config.d_model,), dtype),
        "attn_norm_bias": jnp.zeros((config.d_model,), dtype),
        "mlp": init_mlp_gelu(km, config.d_model, config.d_ff, dtype),
        "mlp_norm_scale": jnp.ones((config.d_model,), dtype),
        "mlp_norm_bias": jnp.zeros((config.d_model,), dtype),
    }


def init(rng: jax.Array, config: BertConfig, dtype=jnp.float32) -> Params:
    k_tok, k_pos, k_typ, k_blocks, k_pool, k_cls = jax.random.split(rng, 6)
    block_keys = jax.random.split(k_blocks, config.n_layers)
    return {
        "tok_embed": truncated_normal_init(k_tok, (config.vocab_size, config.d_model), 0.02, dtype),
        "pos_embed": truncated_normal_init(k_pos, (config.max_seq_len, config.d_model), 0.02, dtype),
        "type_embed": truncated_normal_init(k_typ, (config.type_vocab_size, config.d_model), 0.02, dtype),
        "embed_norm_scale": jnp.ones((config.d_model,), dtype),
        "embed_norm_bias": jnp.zeros((config.d_model,), dtype),
        "blocks": jax.vmap(lambda k: init_block(k, config, dtype))(block_keys),
        "pooler": {
            "w": truncated_normal_init(k_pool, (config.d_model, config.d_model), 0.02, dtype),
            "b": jnp.zeros((config.d_model,), dtype),
        },
        "classifier": {
            "w": truncated_normal_init(k_cls, (config.d_model, config.num_labels), 0.02, dtype),
            "b": jnp.zeros((config.num_labels,), dtype),
        },
    }


def _dropout(x: jax.Array, rate: float, rng: jax.Array | None) -> jax.Array:
    """Inverted dropout; identity when rng is None (eval mode) or rate == 0."""
    if rng is None or rate <= 0.0:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def block_forward(
    block: Params,
    x: jax.Array,
    *,
    config: BertConfig,
    mask: jax.Array | None,
    rng: jax.Array | None,
) -> jax.Array:
    r1, r2 = (None, None) if rng is None else tuple(jax.random.split(rng))
    q, k, v = attention_qkv(block["attn"], x)
    attn = dot_product_attention(q, k, v, mask=mask)
    h = _dropout(attention_out(block["attn"], attn), config.dropout_rate, r1)
    x = layer_norm(x + h, block["attn_norm_scale"], block["attn_norm_bias"], config.norm_eps)
    # HF BERT's hidden_act="gelu" is the exact erf gelu, not the tanh approx.
    h = _dropout(mlp_gelu(block["mlp"], x, approximate=False), config.dropout_rate, r2)
    return layer_norm(x + h, block["mlp_norm_scale"], block["mlp_norm_bias"], config.norm_eps)


def encode(
    params: Params,
    input_ids: jax.Array,
    config: BertConfig,
    *,
    attention_mask: jax.Array | None = None,
    token_type_ids: jax.Array | None = None,
    rng: jax.Array | None = None,
) -> jax.Array:
    """``rng`` enables train-mode dropout; None = deterministic eval."""
    B, S = input_ids.shape
    positions = jnp.arange(S, dtype=jnp.int32)
    x = params["tok_embed"][input_ids] + params["pos_embed"][positions][None]
    if token_type_ids is not None:
        x = x + params["type_embed"][token_type_ids]
    else:
        x = x + params["type_embed"][jnp.zeros((B, S), jnp.int32)]
    x = layer_norm(x, params["embed_norm_scale"], params["embed_norm_bias"], config.norm_eps)
    if rng is not None:
        rng, embed_rng = jax.random.split(rng)
        x = _dropout(x, config.dropout_rate, embed_rng)

    layer_rngs = None if rng is None else jax.random.split(rng, config.n_layers)

    def body(block, carry, layer_rng):
        return block_forward(block, carry, config=config, mask=attention_mask, rng=layer_rng)

    if config.remat:
        body = jax.checkpoint(body)

    def scan_body(carry, xs):
        if layer_rngs is None:
            return body(xs, carry, None), None
        block, layer_rng = xs
        return body(block, carry, layer_rng), None

    xs = params["blocks"] if layer_rngs is None else (params["blocks"], layer_rngs)
    x, _ = jax.lax.scan(scan_body, x, xs)
    return x


def classify(
    params: Params,
    batch: dict[str, jax.Array],
    config: BertConfig,
    rng: jax.Array | None = None,
) -> jax.Array:
    """batch -> classification logits (B, num_labels) from the [CLS] token."""
    x = encode(
        params,
        batch["input_ids"],
        config,
        attention_mask=batch.get("attention_mask"),
        token_type_ids=batch.get("token_type_ids"),
        rng=rng,
    )
    cls = x[:, 0]
    pooled = jnp.tanh(cls @ params["pooler"]["w"].astype(cls.dtype) + params["pooler"]["b"].astype(cls.dtype))
    if rng is not None:
        pooled = _dropout(pooled, config.dropout_rate, jax.random.fold_in(rng, 1))
    return pooled @ params["classifier"]["w"].astype(cls.dtype) + params["classifier"]["b"].astype(cls.dtype)


def loss_fn(
    params: Params,
    batch: dict[str, jax.Array],
    config: BertConfig,
    rng: jax.Array | None = None,
) -> jax.Array:
    logits = classify(params, batch, config, rng=rng).astype(jnp.float32)
    labels = batch["labels"]
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logprobs, labels[:, None], axis=-1))

"""Vision Transformer (ViT) — the vision model family.

The reference's vision story is `examples/cv_example.py` (torchvision
resnet50 fine-tune); the tracked config in BASELINE.md is "cv_example
(data-parallel)". A TPU-native framework wants a transformer vision
backbone instead: patch-embedding is one big matmul (MXU-friendly, unlike
stride-heavy convs), and the encoder reuses the exact block structure,
sharding plans, and kernels the text families already exercise.

- patchify = reshape + one linear projection on the shared `matmul_einsum`
  path (equivalent to the non-overlapping conv, but lowered as a single
  (B*N, P*P*C) x (P*P*C, D) matmul);
- learned [CLS] token + learned position embeddings;
- pre-LN encoder blocks identical in shape to `models/gpt.py` blocks
  (bidirectional attention — no causal mask);
- classification head on the [CLS] representation.

TP/FSDP plan registered in `parallel/tp.py` as ``"vit"``.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .layers import (
    AttentionSpec,
    attention_out,
    attention_qkv,
    dot_product_attention,
    init_attention,
    init_mlp_gelu,
    layer_norm,
    matmul_einsum,
    mlp_gelu,
    truncated_normal_init,
)

Params = Any


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    d_model: int = 768
    n_layers: int = 12
    num_heads: int = 12
    d_ff: int = 3072
    num_classes: int = 1000
    norm_eps: float = 1e-6
    remat: bool = False

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels

    @property
    def attention_spec(self) -> AttentionSpec:
        return AttentionSpec(self.d_model, self.num_heads, self.num_heads, self.d_model // self.num_heads)

    @classmethod
    def tiny(cls, **overrides: Any) -> "ViTConfig":
        defaults = dict(
            image_size=32, patch_size=8, d_model=64, n_layers=2,
            num_heads=4, d_ff=128, num_classes=4,
        )
        defaults.update(overrides)
        return cls(**defaults)

    @classmethod
    def vit_base(cls, **overrides: Any) -> "ViTConfig":
        return cls(**overrides)

    @classmethod
    def vit_large(cls, **overrides: Any) -> "ViTConfig":
        return cls(**{**dict(d_model=1024, n_layers=24, num_heads=16, d_ff=4096), **overrides})

    def param_count(self) -> int:
        d, f = self.d_model, self.d_ff
        block = 4 * d * d + 2 * d * f + f + d + 8 * d  # incl. q/k/v/o biases
        patch = self.patch_dim * d + d
        pos = (self.n_patches + 1) * d
        head = d * self.num_classes + self.num_classes
        return self.n_layers * block + patch + pos + d + 2 * d + head


def init_block(rng: jax.Array, config: ViTConfig, dtype=jnp.float32) -> Params:
    ka, km = jax.random.split(rng)
    return {
        "ln1_scale": jnp.ones((config.d_model,), dtype),
        "ln1_bias": jnp.zeros((config.d_model,), dtype),
        "attn": init_attention(ka, config.attention_spec, dtype, bias=True),
        "ln2_scale": jnp.ones((config.d_model,), dtype),
        "ln2_bias": jnp.zeros((config.d_model,), dtype),
        "mlp": init_mlp_gelu(km, config.d_model, config.d_ff, dtype),
    }


def init(rng: jax.Array, config: ViTConfig, dtype=jnp.float32) -> Params:
    k_patch, k_cls, k_pos, k_blocks, k_head = jax.random.split(rng, 5)
    block_keys = jax.random.split(k_blocks, config.n_layers)
    return {
        "patch_proj": {
            "w": truncated_normal_init(
                k_patch, (config.patch_dim, config.d_model), 1.0 / np.sqrt(config.patch_dim), dtype
            ),
            "b": jnp.zeros((config.d_model,), dtype),
        },
        "cls_token": truncated_normal_init(k_cls, (config.d_model,), 0.02, dtype),
        "pos_embed": truncated_normal_init(
            k_pos, (config.n_patches + 1, config.d_model), 0.02, dtype
        ),
        "blocks": jax.vmap(lambda k: init_block(k, config, dtype))(block_keys),
        "lnf_scale": jnp.ones((config.d_model,), dtype),
        "lnf_bias": jnp.zeros((config.d_model,), dtype),
        "head": {
            "w": truncated_normal_init(k_head, (config.d_model, config.num_classes), 0.02, dtype),
            "b": jnp.zeros((config.num_classes,), dtype),
        },
    }


def patchify(images: jax.Array, config: ViTConfig) -> jax.Array:
    """(B, H, W, C) -> (B, N, P*P*C) non-overlapping patches."""
    B, H, W, C = images.shape
    p = config.patch_size
    if H != config.image_size or W != config.image_size or C != config.channels:
        raise ValueError(
            f"expected {(config.image_size, config.image_size, config.channels)} "
            f"images, got {(H, W, C)}"
        )
    x = images.reshape(B, H // p, p, W // p, p, C)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))  # (B, Hp, Wp, p, p, C)
    return x.reshape(B, config.n_patches, config.patch_dim)


def block_forward(block: Params, x: jax.Array, *, config: ViTConfig) -> jax.Array:
    h = layer_norm(x, block["ln1_scale"], block["ln1_bias"], config.norm_eps)
    q, k, v = attention_qkv(block["attn"], h)
    x = x + attention_out(block["attn"], dot_product_attention(q, k, v))
    h = layer_norm(x, block["ln2_scale"], block["ln2_bias"], config.norm_eps)
    # HF ViT's hidden_act="gelu" is the exact erf gelu, not the tanh approx.
    return x + mlp_gelu(block["mlp"], h, approximate=False)


def forward(params: Params, images: jax.Array, config: ViTConfig) -> jax.Array:
    """images (B, H, W, C) -> class logits (B, num_classes)."""
    patches = patchify(images, config)
    x = matmul_einsum("bsd,df->bsf", patches, params["patch_proj"]["w"])
    x = x + params["patch_proj"]["b"].astype(x.dtype)
    B = x.shape[0]
    cls = jnp.broadcast_to(params["cls_token"].astype(x.dtype), (B, 1, x.shape[-1]))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(x.dtype)[None]

    body = partial(block_forward, config=config)
    if config.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(lambda c, b: (body(b, c), None), x, params["blocks"])
    x = layer_norm(x, params["lnf_scale"], params["lnf_bias"], config.norm_eps)
    cls_repr = x[:, 0]
    head = params["head"]
    return cls_repr @ head["w"].astype(cls_repr.dtype) + head["b"].astype(cls_repr.dtype)


def loss_fn(
    params: Params,
    batch: dict[str, jax.Array],
    config: ViTConfig,
    rng: jax.Array | None = None,
) -> jax.Array:
    """batch: {"pixel_values": (B, H, W, C), "labels": (B,)}."""
    logits = forward(params, batch["pixel_values"], config).astype(jnp.float32)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logprobs, batch["labels"][:, None], axis=-1))


def accuracy(params: Params, batch: dict[str, jax.Array], config: ViTConfig) -> jax.Array:
    logits = forward(params, batch["pixel_values"], config)
    return jnp.mean((jnp.argmax(logits, axis=-1) == batch["labels"]).astype(jnp.float32))

"""Llama-3-style decoder — the framework's flagship model family.

The reference delegates model code to `transformers` and shards it after the
fact (TP via `model.tensor_parallel(mesh)`, reference `accelerator.py:1545`;
FSDP wrapping :1555). Here the model is TPU-native from the start:

- **scan-over-layers**: all L transformer blocks' params are stacked along a
  leading layer axis and the body is one `lax.scan` — O(1) compile time in
  depth and a uniform sharding story;
- **remat**: optional `jax.checkpoint` on the block so activations are
  recomputed in backward (the activation-checkpointing analog of the
  reference FSDP plugin flag, `utils/dataclasses.py:1449`);
- **GQA + RoPE + SwiGLU + RMSNorm** in bf16-friendly form;
- attention is pluggable: "dot" (oracle), "flash" (Pallas kernel), "ring"
  (sequence-parallel ppermute) — see `ops/`.

The TP/FSDP sharding plan for this family is registered in `parallel/tp.py`
under the name ``"llama"``.
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from .layers import (
    CARRY_CACHE_MIN_LEN,
    AttentionSpec,
    apply_rope,
    attention_out,
    attention_qkv,
    cache_positions,
    cache_write,
    cache_write_stacked,
    cached_decode_attention,
    cross_entropy_loss,
    dot_product_attention,
    init_attention,
    init_swiglu,
    rms_norm,
    remat_policy,
    RopeScaling,
    rope_frequencies,
    swiglu,
    truncated_normal_init,
)

Params = Any


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    d_ff: int = 14336
    head_dim: int | None = None
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    # Rotary rescaling — Llama-3.1+ "llama3" banded rescale or "linear"
    # position interpolation; None = plain RoPE.
    rope_scaling: RopeScaling | None = None
    # Mistral-style sliding-window attention: position i attends to keys in
    # (i - sliding_window, i], uniformly across layers. None = full causal.
    sliding_window: int | None = None
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    remat: bool = False
    # What the checkpointed block may keep instead of recomputing:
    # "nothing" = full recompute (lowest memory); "dots" = keep every matmul
    # output (backward at ~2x forward FLOPs but O(10GB) of residuals at
    # bench scale); "block_outputs" = keep only the two residual-branch
    # outputs per layer (attention out-proj + FFN down-proj) — the best
    # recompute-FLOPs-avoided per byte (those are the highest-arithmetic-
    # intensity matmuls) at ~64MB/layer for the bench shape.
    remat_policy: str = "block_outputs"
    attention_impl: str = "dot"  # "dot" | "flash" | "ring" | "ulysses"
    z_loss: float = 0.0
    # Compute the LM loss in sequence chunks of this size (must divide S)
    # without materializing the full (B, S, V) logits — the fp32 logit tail
    # is the single biggest activation at long S / large vocab
    # (layers.chunked_lm_loss). None = unchunked.
    loss_chunk_size: int | None = None
    # Qwen2-style q/k/v biases (the only block-level deviation Qwen2 makes
    # from llama); o_proj stays bias-free there, so only bq/bk/bv are added.
    attn_bias: bool = False
    # Mixture-of-Experts: n_experts > 0 replaces every block's FFN with a
    # top-k routed expert layer (ops/moe.py); expert weights shard over the
    # `expert` mesh axis via the "llama" plan.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    moe_z_weight: float = 1e-3

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim if self.head_dim is not None else self.d_model // self.num_heads

    @property
    def attention_spec(self) -> AttentionSpec:
        return AttentionSpec(self.d_model, self.num_heads, self.num_kv_heads, self.resolved_head_dim)

    @classmethod
    def tiny(cls, **overrides: Any) -> "LlamaConfig":
        """A toy config for tests/CI (fits the 8-device CPU mesh)."""
        defaults = dict(
            vocab_size=256, d_model=64, n_layers=2, num_heads=4, num_kv_heads=2,
            d_ff=128, max_seq_len=128, rope_theta=10000.0,
        )
        defaults.update(overrides)
        return cls(**defaults)

    @classmethod
    def llama3_8b(cls, **overrides: Any) -> "LlamaConfig":
        return cls(**{**dict(
            vocab_size=128256, d_model=4096, n_layers=32, num_heads=32,
            num_kv_heads=8, d_ff=14336, max_seq_len=8192,
        ), **overrides})

    @classmethod
    def llama3_70b(cls, **overrides: Any) -> "LlamaConfig":
        return cls(**{**dict(
            vocab_size=128256, d_model=8192, n_layers=80, num_heads=64,
            num_kv_heads=8, d_ff=28672, max_seq_len=8192,
        ), **overrides})

    def param_count(self) -> int:
        h = self.resolved_head_dim
        attn = self.d_model * h * (2 * self.num_heads + 2 * self.num_kv_heads)
        if self.attn_bias:
            attn += h * (self.num_heads + 2 * self.num_kv_heads)
        if self.n_experts:
            ffn = self.n_experts * 3 * self.d_model * self.d_ff + self.d_model * self.n_experts
        else:
            ffn = 3 * self.d_model * self.d_ff
        block = attn + ffn + 2 * self.d_model
        embed = self.vocab_size * self.d_model * (1 if self.tie_embeddings else 2)
        return self.n_layers * block + embed + self.d_model

    def flops_per_token(self) -> float:
        """Approximate training FLOPs/token (6N + attention term)."""
        return 6.0 * self.param_count() + 12.0 * self.n_layers * self.d_model * self.max_seq_len


def init_block(rng: jax.Array, config: LlamaConfig, dtype=jnp.float32) -> Params:
    ka, km = jax.random.split(rng)
    attn = init_attention(ka, config.attention_spec, dtype, bias=config.attn_bias)
    if config.attn_bias:
        del attn["bo"]  # Qwen2 convention: q/k/v biased, o_proj is not
    block = {
        "attn_norm": jnp.zeros((config.d_model,), dtype),
        "attn": attn,
        "mlp_norm": jnp.zeros((config.d_model,), dtype),
    }
    if config.n_experts:
        from ..ops.moe import init_moe

        block["moe"] = init_moe(km, config.d_model, config.d_ff, config.n_experts, dtype)
    else:
        block["mlp"] = init_swiglu(km, config.d_model, config.d_ff, dtype)
    return block


def init(rng: jax.Array, config: LlamaConfig, dtype=jnp.float32) -> Params:
    """Initialize params. Layer params are stacked: every leaf under
    ``blocks`` has a leading ``n_layers`` axis (scan-over-layers layout)."""
    k_embed, k_blocks, k_out = jax.random.split(rng, 3)
    block_keys = jax.random.split(k_blocks, config.n_layers)
    blocks = jax.vmap(lambda k: init_block(k, config, dtype))(block_keys)
    params = {
        "embed": truncated_normal_init(k_embed, (config.vocab_size, config.d_model), 1.0, dtype),
        "blocks": blocks,
        "final_norm": jnp.zeros((config.d_model,), dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = truncated_normal_init(
            k_out, (config.d_model, config.vocab_size), 1.0 / np.sqrt(config.d_model), dtype
        )
    return params


_remat_policy = remat_policy  # shared impl in layers.py


def _rope_tables(config: LlamaConfig) -> tuple[jax.Array, jax.Array]:
    cos_np, sin_np = rope_frequencies(
        config.resolved_head_dim,
        config.max_seq_len,
        config.rope_theta,
        scaling=config.rope_scaling,
    )
    return jnp.asarray(cos_np), jnp.asarray(sin_np)


def _window_mask(
    mask: jax.Array | None, positions: jax.Array, seq_len: int, window: int
) -> jax.Array:
    """Fold the sliding-window band into the (optional) user mask: key j is
    visible from query position p iff ``p - j < window`` (HF Mistral
    semantics — the window includes the current token; causality is applied
    separately by the attention op). Returns a (B, S, T) boolean mask."""
    j = jnp.arange(seq_len, dtype=jnp.int32)
    win = (positions[:, :, None] - j[None, None, :]) < window
    if mask is None:
        return win
    if mask.ndim == 2:
        mask = mask[:, None, :]
    return mask.astype(bool) & win


def _attention(config: LlamaConfig, q, k, v, mask):
    if config.attention_impl == "flash":
        from ..ops.flash_attention import flash_attention

        # window only when no mask arrived: a non-None mask means the band
        # (if any) is already folded in by the caller (see forward) — the
        # kernel's row-index band must not be applied on top.
        if config.sliding_window is not None and mask is not None:
            # Folded-band cases (explicit positions / user masks) run the
            # unfused oracle — at windowed long contexts that is exactly
            # the O(S^2) blowup the kernel exists to avoid; say so.
            import warnings

            warnings.warn(
                "sliding_window with an explicit mask or non-default "
                "positions runs the unfused O(S^2) attention path (the "
                "fused band kernel needs default contiguous positions and "
                "no extra mask).",
                stacklevel=3,
            )
        return flash_attention(
            q, k, v, causal=True, segment_mask=mask,
            window=config.sliding_window if mask is None else None,
        )
    if config.attention_impl in ("ring", "ulysses"):
        if mask is not None and mask.ndim != 2:
            hint = (
                " (with sliding_window, a folded 3-D band mask reaches here "
                "whenever positions are non-default — packed/shifted "
                "sequences band by position, which the ring/ulysses chunk "
                "plumbing cannot express)"
                if config.sliding_window is not None
                else ""
            )
            raise NotImplementedError(
                f"attention_impl={config.attention_impl!r} supports (B, S) "
                "key-padding masks only; full (B, S, T) masks need 'flash' "
                f"or 'dot'.{hint}"
            )
        if config.attention_impl == "ring":
            from ..ops.ring_attention import ring_attention

            # Window rides the per-step chunk masks (einsum path; band-dead
            # ring steps skip their FLOPs).
            return ring_attention(
                q, k, v, causal=True, kv_mask=mask,
                window=config.sliding_window,
            )
        if mask is not None:
            # Masked ulysses falls back to the O(S^2)-per-device oracle over
            # the gathered sequence — exactly what long context cannot
            # afford; ring handles masks chunked at O(S^2/n).
            raise NotImplementedError(
                "attention_impl='ulysses' with a padding mask would "
                "materialize full-sequence attention per device; use "
                "attention_impl='ring' for padded long-context batches."
            )
        from ..ops.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, causal=True, window=config.sliding_window)
    if config.attention_impl != "dot":
        raise ValueError(
            f"Unknown attention_impl {config.attention_impl!r}; expected "
            "'dot', 'flash', 'ring', or 'ulysses'"
        )
    return dot_product_attention(q, k, v, mask=mask, causal=True)


def block_forward(
    block: Params,
    x: jax.Array,
    *,
    config: LlamaConfig,
    cos: jax.Array,
    sin: jax.Array,
    positions: jax.Array,
    mask: jax.Array | None,
) -> jax.Array:
    from jax.ad_checkpoint import checkpoint_name

    from ..parallel.mesh import constrain_batch

    # Re-pin the residual stream's batch sharding every layer: inside the
    # scan the partitioner otherwise drifts (mesh.constrain_batch docstring).
    x = constrain_batch(x)
    h = rms_norm(x, block["attn_norm"], config.norm_eps)
    q, k, v = attention_qkv(block["attn"], h)
    q = checkpoint_name(apply_rope(q, cos, sin, positions), "q_rope")
    k = checkpoint_name(apply_rope(k, cos, sin, positions), "k_rope")
    v = checkpoint_name(v, "v_proj")
    attn = _attention(config, q, k, v, mask)
    x = x + checkpoint_name(attention_out(block["attn"], attn), "attn_out")
    h = rms_norm(x, block["mlp_norm"], config.norm_eps)
    ffn_out, aux = _ffn(block, h, config)
    x = x + checkpoint_name(ffn_out, "ffn_out")
    return x, aux


def _maybe_dequantize(block: Params, dtype: Any) -> Params:
    """Transparent weight-only int8 support (utils/quantization.py): when a
    block carries quantized leaves, dequantize them to the compute dtype here
    — per layer, inside the scan — so HBM holds int8 while matmuls see the
    compute dtype.

    Inside an `ops.int8.int8_compute()` context the quantized nodes pass
    through UNTOUCHED: every projection routes through `matmul_einsum`,
    which contracts them int8×int8→int32 on the int8 MXU (~2× the bf16
    rate — the compute-bound prefill/verify win; `ops/int8.py`)."""
    from ..ops.int8 import int8_compute_enabled
    from ..utils.quantization import dequantize_pytree, has_quantized

    if has_quantized(block):
        if int8_compute_enabled():
            return block
        return dequantize_pytree(block, dtype)
    return block


def _ffn(block: Params, h: jax.Array, config: LlamaConfig):
    """Dense swiglu or routed MoE; returns (out, aux-losses-or-None)."""
    if config.n_experts:
        from ..ops.moe import moe_forward

        return moe_forward(
            block["moe"],
            h,
            top_k=config.moe_top_k,
            capacity_factor=config.moe_capacity_factor,
        )
    return swiglu(block["mlp"], h), None


def forward(
    params: Params,
    tokens: jax.Array,
    config: LlamaConfig,
    *,
    positions: jax.Array | None = None,
    mask: jax.Array | None = None,
    return_aux: bool = False,
    return_hidden: bool = False,
) -> jax.Array:
    """tokens (B, S) int32 -> logits (B, S, vocab).

    With ``return_aux`` (MoE training) returns ``(logits, aux)`` where aux
    holds the per-layer-averaged router losses. ``return_hidden`` skips the
    logits head and returns the final-norm hidden states instead (the
    chunked-loss path projects them chunk-by-chunk)."""
    B, S = tokens.shape
    if S > config.max_seq_len:
        # RoPE table gathers clamp out-of-range positions under jit, which
        # would silently degrade instead of failing.
        raise ValueError(f"sequence length {S} exceeds max_seq_len={config.max_seq_len}")
    default_positions = positions is None
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    cos, sin = _rope_tables(config)
    _kernel_band = default_positions and (
        (config.attention_impl in ("flash", "ulysses") and mask is None)
        # ring combines its per-step band with (B, S) padding masks natively.
        or config.attention_impl == "ring"
    )
    if config.sliding_window is not None and not _kernel_band:
        # flash/ulysses apply the band in-kernel (tile skipping) — but only
        # for the unmasked default-positions case; explicit positions
        # (packed/shifted sequences) band by POSITION, which the kernel's
        # row-index band cannot express, and user masks force the oracle
        # anyway, so every other combination folds into ONE materialized
        # mask (_attention then passes no window — the band must not be
        # applied twice with different anchors).
        mask = _window_mask(mask, positions, S, config.sliding_window)

    x = params["embed"][tokens]

    body = partial(
        block_forward, config=config, cos=cos, sin=sin, positions=positions, mask=mask
    )
    if config.remat:
        body = jax.checkpoint(body, policy=_remat_policy(config.remat_policy))

    def scan_body(carry, block):
        new_x, aux = body(_maybe_dequantize(block, carry.dtype), carry)
        return new_x, aux

    x, aux_stacked = jax.lax.scan(scan_body, x, params["blocks"])
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    aux = (
        jax.tree.map(lambda a: jnp.mean(a, axis=0), aux_stacked)
        if aux_stacked is not None
        else {}
    )
    if return_hidden:
        return (x, aux) if return_aux else x
    logits = jnp.einsum("bsd,dv->bsv", x, _lm_head(params, config).astype(x.dtype))
    if not return_aux:
        return logits
    return logits, aux


# ---------------------------------------------------------------- KV cache
def init_cache(
    config: LlamaConfig, batch_size: int, max_len: int, dtype=jnp.bfloat16
) -> dict[str, jax.Array]:
    """Decode-time KV cache, layer-stacked to match the scan layout.

    ``dtype=jnp.int8`` stores K/V quantized with per-(token, head) scales —
    half the HBM bytes per decode step, which IS the decode roofline once
    the context is long (at 32k the cache outweighs a 443M model's weights
    ~2:1). Dequantization fuses into the attention matmuls; accuracy is the
    standard per-token-scale int8 KV trade (logit drift ~1e-2, tested)."""
    shape = (config.n_layers, batch_size, max_len, config.num_kv_heads, config.resolved_head_dim)
    if dtype == jnp.int8:
        scale_shape = shape[:-1]
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(scale_shape, jnp.bfloat16),
            "v_scale": jnp.zeros(scale_shape, jnp.bfloat16),
            "length": jnp.zeros((), jnp.int32),
        }
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def _quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(B, T, H, h) -> int8 values + per-(token, head) scales."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.bfloat16)


def _dequant_kv(vals: jax.Array, scales: jax.Array, dtype) -> jax.Array:
    """Inverse of `_quantize_kv` — the ONE place the dequant arithmetic
    lives, whatever the cache layout indexes look like."""
    return vals.astype(dtype) * scales[..., None].astype(dtype)


def forward_with_cache(
    params: Params,
    tokens: jax.Array,
    cache: dict[str, jax.Array],
    config: LlamaConfig,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Incremental forward: append ``tokens`` (B, T_new) at ``cache['length']``
    and attend against everything cached so far. Returns (logits, new_cache).

    Serves both prefill (T_new = prompt length) and decode (T_new = 1); the
    same jitted function handles either with static T_new.

    ``cache['length']`` may be a scalar (all rows share one cursor — the
    plain decode contract) or shape (B,) (per-row cursors: speculative
    decoding commits a different number of tokens per row, `speculative.py`).
    Positions, masks, and the KV writes are all per-row in the latter case.
    """
    B, T_new = tokens.shape
    max_len = cache["k"].shape[2]
    start = cache["length"]
    positions = cache_positions(start, T_new, B)
    cos, sin = _rope_tables(config)

    # (B, T_new, max_len) attention mask: cached positions < start+1+i.
    cache_pos = jnp.arange(max_len, dtype=jnp.int32)
    mask = cache_pos[None, None, :] <= positions[:, :, None]
    if config.sliding_window is not None:
        # The cache is still a full ring-free buffer; the window is applied
        # as a mask so positions older than (p - window) are invisible.
        mask = mask & (
            cache_pos[None, None, :] > positions[:, :, None] - config.sliding_window
        )

    x = params["embed"][tokens]
    int8_kv = cache["k"].dtype == jnp.int8
    # Long contexts keep the stacked cache in the scan CARRY: as xs/ys the
    # scan RESTACKS the whole cache every step (read+write), which becomes
    # the decode roofline once the per-row context is long — measured on
    # v5e at 16k ctx / 443M / B=1: 77.5 -> 100.7 tok/s bf16, 111.4 with
    # int8. Short contexts keep the xs/ys layout (the restack is cheap
    # there and the carry's dynamic-slice read measured ~7% slower at
    # 2k/B=8). The threshold is static — the choice costs nothing at trace
    # time and both paths are numerically identical (tested).
    carry_cache = max_len >= CARRY_CACHE_MIN_LEN

    # Decode steps (T_new == 1) may take the Pallas flash-decode kernel:
    # valid prefix per row after the write is positions[:, 0] + 1 (works for
    # the scalar cursor and the per-row speculative cursors alike). Prefill
    # and sliding-window configs always run the masked reference attention.
    decode_lengths = positions[:, 0] + 1 if T_new == 1 else None

    def attend(block, x, q, k_full, v_full, kv_raw=None):
        attn = cached_decode_attention(
            q, k_full, v_full, mask=mask, lengths=decode_lengths,
            kv_raw=kv_raw, window=config.sliding_window,
        )
        x = x + attention_out(block["attn"], attn)
        h = rms_norm(x, block["mlp_norm"], config.norm_eps)
        ffn_out, _ = _ffn(block, h, config)  # aux unused at inference
        return x + ffn_out

    def project(block, x):
        h = rms_norm(x, block["attn_norm"], config.norm_eps)
        q, k, v = attention_qkv(block["attn"], h)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        return q, k, v

    if carry_cache:
        def _update_layer(all_buf, i, rows):
            return cache_write_stacked(all_buf, i, rows, start)

        def scan_body(carry, block):
            if int8_kv:
                x, k_all, v_all, ks_all, vs_all, i = carry
            else:
                x, k_all, v_all, i = carry
            block = _maybe_dequantize(block, x.dtype)
            q, k, v = project(block, x)
            q_dtype = x.dtype
            if int8_kv:
                kq, ks = _quantize_kv(k)
                vq, vs = _quantize_kv(v)
                k_all, k_layer = _update_layer(k_all, i, kq)
                v_all, v_layer = _update_layer(v_all, i, vq)
                ks_all, ks_layer = _update_layer(ks_all, i, ks)
                vs_all, vs_layer = _update_layer(vs_all, i, vs)
                # Dequant stays elementwise on the sliced layer: HBM reads int8.
                k_full = _dequant_kv(k_layer, ks_layer, q_dtype)
                v_full = _dequant_kv(v_layer, vs_layer, q_dtype)
                # Raw cache for the flash-decode kernel: when it runs, the
                # dequantized copies above are dead and XLA drops them.
                kv_raw = (k_layer, ks_layer, v_layer, vs_layer)
            else:
                k_all, k_layer = _update_layer(k_all, i, k)
                v_all, v_layer = _update_layer(v_all, i, v)
                k_full = k_layer.astype(q_dtype)
                v_full = v_layer.astype(q_dtype)
                kv_raw = None
            x = attend(block, x, q, k_full, v_full, kv_raw)
            if int8_kv:
                return (x, k_all, v_all, ks_all, vs_all, i + 1), None
            return (x, k_all, v_all, i + 1), None

        layer0 = jnp.zeros((), jnp.int32)
        if int8_kv:
            carry = (x, cache["k"], cache["v"], cache["k_scale"], cache["v_scale"], layer0)
            (x, new_k, new_v, new_ks, new_vs, _), _ = jax.lax.scan(
                scan_body, carry, params["blocks"]
            )
            new_cache = {
                "k": new_k, "v": new_v, "k_scale": new_ks, "v_scale": new_vs,
                "length": start + T_new,
            }
        else:
            (x, new_k, new_v, _), _ = jax.lax.scan(
                scan_body, (x, cache["k"], cache["v"], layer0), params["blocks"]
            )
            new_cache = {"k": new_k, "v": new_v, "length": start + T_new}
    else:
        def scan_body(carry, xs):
            x = carry
            if int8_kv:
                block, k_cache, v_cache, k_sc, v_sc = xs
            else:
                block, k_cache, v_cache = xs
            block = _maybe_dequantize(block, x.dtype)
            q, k, v = project(block, x)
            q_dtype = x.dtype
            if int8_kv:
                kq, ks = _quantize_kv(k)
                vq, vs = _quantize_kv(v)
                k_cache = cache_write(k_cache, kq, start)
                v_cache = cache_write(v_cache, vq, start)
                k_sc = cache_write(k_sc, ks, start)
                v_sc = cache_write(v_sc, vs, start)
                k_full = _dequant_kv(k_cache, k_sc, q_dtype)
                v_full = _dequant_kv(v_cache, v_sc, q_dtype)
                kv_raw = (k_cache, k_sc, v_cache, v_sc)
            else:
                k_cache = cache_write(k_cache, k, start)
                v_cache = cache_write(v_cache, v, start)
                k_full = k_cache.astype(q_dtype)
                v_full = v_cache.astype(q_dtype)
                kv_raw = None
            x = attend(block, x, q, k_full, v_full, kv_raw)
            if int8_kv:
                return x, (k_cache, v_cache, k_sc, v_sc)
            return x, (k_cache, v_cache)

        if int8_kv:
            xs = (params["blocks"], cache["k"], cache["v"], cache["k_scale"], cache["v_scale"])
            x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(scan_body, x, xs)
            new_cache = {
                "k": new_k, "v": new_v, "k_scale": new_ks, "v_scale": new_vs,
                "length": start + T_new,
            }
        else:
            x, (new_k, new_v) = jax.lax.scan(
                scan_body, x, (params["blocks"], cache["k"], cache["v"])
            )
            new_cache = {"k": new_k, "v": new_v, "length": start + T_new}
    x = rms_norm(x, params["final_norm"], config.norm_eps)
    logits = jnp.einsum("bsd,dv->bsv", x, _lm_head(params, config).astype(x.dtype))
    return logits, new_cache


@functools.lru_cache(maxsize=16)
def _generator(config: LlamaConfig, generation_config: Any, jit_loop: bool):
    from ..generation import GenerationConfig, Generator, cache_dtype

    gcfg = generation_config or GenerationConfig()
    kv_dtype = cache_dtype(gcfg)
    return Generator(
        lambda p, t, c: forward_with_cache(p, t, c, config),
        lambda b, m: init_cache(config, b, m, dtype=kv_dtype),
        gcfg,
        jit_loop=jit_loop,
    )


def _lm_head(params: Params, config: LlamaConfig) -> jax.Array:
    return params["embed"].T if config.tie_embeddings else params["lm_head"]


def _add_moe_aux(loss: jax.Array, aux: dict, config: LlamaConfig) -> jax.Array:
    return (
        loss
        + config.moe_aux_weight * aux["moe_load_balance"]
        + config.moe_z_weight * aux["moe_z_loss"]
    )


def _chunked_loss_fn(
    params: Params, batch: dict[str, jax.Array], config: LlamaConfig
) -> jax.Array:
    """`loss_fn` with `layers.chunked_lm_loss`: the trunk runs at full S and
    only the logits projection + softmax are chunked. The shifted-labels
    default keeps S intact by masking out the final position instead of
    slicing (chunking needs chunk_size | S)."""
    from .layers import chunked_lm_loss_from_batch

    tokens = batch["input_ids"]
    attn_mask = batch.get("attention_mask")
    moe = config.n_experts > 0
    out = forward(
        params, tokens, config, mask=attn_mask, return_aux=moe, return_hidden=True
    )
    x, aux = out if moe else (out, {})
    loss = chunked_lm_loss_from_batch(
        x, _lm_head(params, config), tokens, batch.get("labels"), attn_mask,
        z_loss=config.z_loss, chunk_size=config.loss_chunk_size,
    )
    return _add_moe_aux(loss, aux, config) if moe else loss


def generate(
    params: Params,
    prompt: jax.Array,
    config: LlamaConfig,
    *,
    generation_config: Any = None,
    rng: jax.Array | None = None,
    jit_loop: bool = True,
) -> jax.Array:
    """Autoregressive generation for this family. Jitted prefill/decode steps
    are cached per (model config, generation config), so repeated calls skip
    tracing (both configs are frozen dataclasses, hence hashable)."""
    gen = _generator(config, generation_config, jit_loop)
    total = prompt.shape[1] + gen.config.max_new_tokens
    if total > config.max_seq_len:
        # RoPE table gathers clamp out-of-range positions under jit, which
        # would silently degrade instead of failing.
        raise ValueError(
            f"prompt ({prompt.shape[1]}) + max_new_tokens "
            f"({gen.config.max_new_tokens}) = {total} exceeds "
            f"max_seq_len={config.max_seq_len}"
        )
    return gen(params, prompt, rng=rng)


@functools.lru_cache(maxsize=16)
def _offloaded_block_step(config: LlamaConfig):
    """Jitted per-layer step for the offloaded path, cached per config so
    repeated streamed forwards reuse the compilation."""

    def step(block, x, cos, sin, positions, mask):
        x, _aux = block_forward(
            block, x, config=config, cos=cos, sin=sin, positions=positions, mask=mask
        )
        return x

    return jax.jit(step)


def forward_offloaded(
    params: Params,
    tokens: jax.Array,
    config: LlamaConfig,
    *,
    compute_dtype: Any = jnp.bfloat16,
) -> jax.Array:
    """Forward for over-HBM models: ``params['blocks']`` leaves may be
    host-resident numpy (see `big_modeling.offload_blocks`); each layer
    streams to the device one step ahead of compute
    (`big_modeling.streamed_scan`). Non-block params must fit on device.
    """
    from ..big_modeling import streamed_scan

    B, S = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    cos, sin = _rope_tables(config)
    mask = (
        _window_mask(None, positions, S, config.sliding_window)
        if config.sliding_window is not None
        else None
    )
    embed = jnp.asarray(params["embed"]).astype(compute_dtype)
    x = embed[tokens]

    block_step = _offloaded_block_step(config)
    x = streamed_scan(
        lambda carry, block: block_step(block, carry, cos, sin, positions, mask),
        x, params["blocks"],
        dtype=compute_dtype,
    )
    x = rms_norm(x, jnp.asarray(params["final_norm"]), config.norm_eps)
    head = embed.T if config.tie_embeddings else jnp.asarray(params["lm_head"]).astype(compute_dtype)
    return jnp.einsum("bsd,dv->bsv", x, head)


@functools.lru_cache(maxsize=16)
def _offloaded_cache_step(config: LlamaConfig):
    """Jitted per-layer cache step for offloaded decode: one block's weights
    (staged from host/disk), that layer's KV cache slices, and the running
    hidden state."""

    def step(block, k_cache, v_cache, x, cos, sin, positions, mask, start):
        block = _maybe_dequantize(block, x.dtype)
        h = rms_norm(x, block["attn_norm"], config.norm_eps)
        q, k, v = attention_qkv(block["attn"], h)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        k_cache = cache_write(k_cache, k, start)
        v_cache = cache_write(v_cache, v, start)
        attn = dot_product_attention(
            q, k_cache.astype(q.dtype), v_cache.astype(q.dtype), mask=mask
        )
        x = x + attention_out(block["attn"], attn)
        h = rms_norm(x, block["mlp_norm"], config.norm_eps)
        ffn_out, _ = _ffn(block, h, config)
        return x + ffn_out, k_cache, v_cache

    return jax.jit(step, donate_argnums=(1, 2))


def forward_with_cache_offloaded(
    params: Params,
    tokens: jax.Array,
    cache: dict[str, jax.Array],
    config: LlamaConfig,
    *,
    compute_dtype: Any = jnp.bfloat16,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """`forward_with_cache` for over-HBM (and over-host-RAM) models:
    ``params['blocks']`` leaves are host numpy arrays or disk memmaps
    (`big_modeling.offload_blocks` / disk offload via
    ``load_pretrained(offload_dir=...)``); each layer's weights stream to
    the device one step ahead of compute while the KV cache stays resident.
    The per-layer reads are what make a model larger than host RAM + HBM
    decodable — only one layer's weights are ever in flight (reference
    `disk_offload` + `OffloadedWeightsLoader`, `big_modeling.py:260`,
    `utils/offload.py:127`)."""
    if cache["k"].dtype == jnp.int8:
        raise NotImplementedError(
            "int8 KV caches are not implemented for the offloaded decode "
            "path (the streamed step would truncate float K/V into "
            "scale-free int8 and read them back as garbage); use "
            "forward_with_cache, or a bf16 cache here."
        )
    from ..big_modeling import streamed_scan

    B, T_new = tokens.shape
    start = cache["length"]
    positions = cache_positions(start, T_new, B)
    cos, sin = _rope_tables(config)
    max_len = cache["k"].shape[2]
    cache_pos = jnp.arange(max_len, dtype=jnp.int32)
    mask = cache_pos[None, None, :] <= positions[:, :, None]
    if config.sliding_window is not None:
        mask = mask & (
            cache_pos[None, None, :] > positions[:, :, None] - config.sliding_window
        )

    embed = jnp.asarray(params["embed"]).astype(compute_dtype)
    x = embed[tokens]
    step = _offloaded_cache_step(config)

    # Stream blocks while carrying per-layer cache slices alongside.
    n_layers = config.n_layers
    k_layers, v_layers = [], []

    def body(carry, block, _i=[0]):
        x = carry
        i = _i[0]
        _i[0] += 1
        x, k_i, v_i = step(
            block, cache["k"][i], cache["v"][i], x, cos, sin, positions, mask, start
        )
        k_layers.append(k_i)
        v_layers.append(v_i)
        return x

    x = streamed_scan(body, x, params["blocks"], dtype=compute_dtype)
    x = rms_norm(x, jnp.asarray(params["final_norm"]), config.norm_eps)
    head = (
        embed.T
        if config.tie_embeddings
        else jnp.asarray(params["lm_head"]).astype(compute_dtype)
    )
    logits = jnp.einsum("bsd,dv->bsv", x, head)
    new_cache = {
        "k": jnp.stack(k_layers),
        "v": jnp.stack(v_layers),
        "length": start + T_new,
    }
    return logits, new_cache


def generate_offloaded(
    params: Params,
    prompt: jax.Array,
    config: LlamaConfig,
    *,
    max_new_tokens: int = 16,
    compute_dtype: Any = jnp.bfloat16,
) -> jax.Array:
    """Greedy decoding over host/disk-offloaded blocks. Every generated
    token streams the full stack once — throughput is storage-bandwidth /
    model-size, the same roofline as the reference's disk-offloaded
    OPT-30B `generate` (BASELINE's over-RAM configuration)."""
    B, S = prompt.shape
    total = S + max_new_tokens
    if total > config.max_seq_len:
        raise ValueError(
            f"prompt ({S}) + max_new_tokens ({max_new_tokens}) = {total} "
            f"exceeds max_seq_len={config.max_seq_len}"
        )
    cache = init_cache(config, B, total, dtype=compute_dtype)
    logits, cache = forward_with_cache_offloaded(
        params, prompt, cache, config, compute_dtype=compute_dtype
    )
    out = [prompt]
    last = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
    for _ in range(max_new_tokens - 1):
        out.append(last)
        logits, cache = forward_with_cache_offloaded(
            params, last, cache, config, compute_dtype=compute_dtype
        )
        last = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
    out.append(last)
    return jnp.concatenate(out, axis=1)


def loss_fn(
    params: Params,
    batch: dict[str, jax.Array],
    config: LlamaConfig,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Next-token prediction loss. batch: {"input_ids": (B, S)} with optional
    "labels" (shifted) and "attention_mask"."""
    if config.loss_chunk_size:
        return _chunked_loss_fn(params, batch, config)
    tokens = batch["input_ids"]
    labels = batch.get("labels")
    attn_mask = batch.get("attention_mask")
    moe = config.n_experts > 0
    out = forward(params, tokens, config, mask=attn_mask, return_aux=moe)
    logits, aux = out if moe else (out, {})
    if labels is None:
        # Run the forward at full S and drop the last logit instead of
        # slicing the tokens: keeps the sequence length at its (power-of-two,
        # block-aligned) value so matmul tiling and the flash kernel's block
        # path are preserved; one wasted position is noise.
        labels = tokens[:, 1:]
        loss_mask = attn_mask[:, 1:] if attn_mask is not None else None
        logits = logits[:, :-1]
    else:
        loss_mask = attn_mask
    loss = cross_entropy_loss(logits, labels, mask=loss_mask, z_loss=config.z_loss)
    return _add_moe_aux(loss, aux, config) if moe else loss

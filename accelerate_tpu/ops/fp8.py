"""fp8 matmuls with dynamic per-tensor scaling.

The reference ships three fp8 backends (TransformerEngine
`utils/transformer_engine.py:26-88`, torchao `utils/ao.py:103`
`convert_model_to_fp8_ao`, MS-AMP `accelerator.py:2164-2211`) that swap
`nn.Linear` for fp8-scaled variants. The TPU-native analog is a *function*, not
a module swap: every matmul-shaped einsum in `models/layers.py` routes through
:func:`matmul_einsum`, which under the fp8 mode quantizes both operands and
runs the contraction on fp8 values.

Recipe (the torchao "dynamic scaling" recipe — no amax history to carry in the
train state, unlike TE's delayed scaling):

- forward: x and w quantized to **e4m3** (max 448) with per-tensor scales
  ``amax/448``; the dot accumulates in fp32 and the result is rescaled by
  ``scale_x * scale_w``.
- backward: the cotangent is quantized to **e5m2** (max 57344 — gradients
  need exponent range, not mantissa) and both transposed dots run on fp8
  values the same way.
- first/last layers (embedding lookup, logits head) are *not* routed through
  fp8 — the reference's torchao path filters them too (`utils/ao.py:31-92`)
  because they dominate quantization error.

On hardware with fp8 MXU support XLA lowers these dots natively; elsewhere
(CPU simulation, older TPUs) XLA upcasts the fp8 *values* — numerics are
identical (the quantization happened on the way in), only the speed benefit
is hardware-dependent.
"""

from __future__ import annotations

import contextlib
import threading
from functools import partial

import jax
import jax.numpy as jnp

E4M3 = jnp.float8_e4m3fn
E5M2 = jnp.float8_e5m2

_MODE = threading.local()


def fp8_enabled() -> bool:
    return getattr(_MODE, "fp8", False)


def fp8_hits() -> int:
    """How many matmuls were routed to fp8 inside the current (innermost)
    `fp8_matmuls` context — lets callers detect a model that never touches
    `matmul_einsum` (for which fp8 mode would be a silent no-op)."""
    return getattr(_MODE, "hits", 0)


@contextlib.contextmanager
def fp8_matmuls(enabled: bool = True):
    """While active (including during jit tracing), `matmul_einsum` lowers to
    fp8-quantized contractions."""
    prev = getattr(_MODE, "fp8", False)
    prev_hits = getattr(_MODE, "hits", 0)
    _MODE.fp8 = enabled
    _MODE.hits = 0
    try:
        yield
    finally:
        _MODE.fp8 = prev
        _MODE.hits = prev_hits


def matmul_einsum(eq: str, x: jax.Array, w) -> jax.Array:
    """The one matmul entry point for every projection in the model zoo
    (`models/layers.py`, `ops/moe.py`).

    Normally a plain einsum with the weight cast to the activation dtype
    (the bf16-compute / fp32-master policy). Inside an `fp8_matmuls()`
    context — which `Accelerator` enters when ``mixed_precision='fp8'`` —
    it lowers to a dynamically-scaled fp8 contraction instead (reference fp8
    backends: `utils/ao.py:103`, `utils/transformer_engine.py:26-88`).

    ``w`` may also be a quantized-weight node from `utils/quantization.py`:
    inside an `ops.int8.int8_compute()` context the contraction runs
    int8×int8→int32 on the int8 MXU (`ops/int8.py`); otherwise the node
    dequantizes to the activation dtype and takes the normal path."""
    if isinstance(w, dict):
        from ..utils.quantization import dequantize_array
        from .int8 import int8_compute_enabled, int8_einsum_quantized

        if int8_compute_enabled() and not fp8_enabled():
            return int8_einsum_quantized(eq, x, w)
        w = dequantize_array(w, x.dtype)
    if fp8_enabled():
        _MODE.hits = getattr(_MODE, "hits", 0) + 1
        return fp8_einsum(eq, x, w.astype(x.dtype))
    return jnp.einsum(eq, x, w.astype(x.dtype))


def quantize(x: jax.Array, dtype=E4M3) -> tuple[jax.Array, jax.Array]:
    """Per-tensor dynamic scaling: returns ``(q, scale)`` with
    ``q ≈ x / scale`` in ``dtype`` and ``scale = amax / finfo(dtype).max``
    (fp32 scalar), so ``q`` spans the full fp8 range."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf))
    fmax = float(jnp.finfo(dtype).max)
    scale = jnp.maximum(amax, 1e-12) / fmax
    q = (xf / scale).astype(dtype)
    return q, scale


def _grad_equations(eq: str) -> tuple[str, str]:
    """Transpose equations for ``einsum(eq, x, w)``: returns
    ``(dx_eq, dw_eq)`` with ``dx = einsum(dx_eq, g, w)`` and
    ``dw = einsum(dw_eq, x, g)``. Valid for matmul-shaped equations where
    every label of each operand appears in the output or the other operand
    (true for all projections in `models/layers.py`)."""
    ins, out = eq.split("->")
    a, b = ins.split(",")
    return f"{out},{b}->{a}", f"{a},{out}->{b}"


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def fp8_einsum(eq: str, x: jax.Array, w: jax.Array) -> jax.Array:
    """``einsum(eq, x, w)`` computed on dynamically-scaled fp8 operands
    (e4m3 forward / e5m2 cotangent), fp32 accumulation."""
    return _fp8_einsum_fwd(eq, x, w)[0]


def _contract(eq: str, a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.einsum(eq, a, b, preferred_element_type=jnp.float32)


def _scaled_contract(eq, qa, qb, scale, out_dtype):
    """``(dot(qa, qb) * scale).astype(out_dtype)`` — through the `fp8_matmul`
    Pallas kernel when enabled (fp8 operands straight to the MXU, no
    materialized upcast), else the exact reference expression."""
    try:
        from ..native.pallas.quant_matmul import maybe_scaled_matmul
    except Exception:  # pragma: no cover - environment dependent
        maybe_scaled_matmul = None
    if maybe_scaled_matmul is not None:
        out = maybe_scaled_matmul(eq, qa, qb, scale, out_dtype)
        if out is not None:
            return out
    return (_contract(eq, qa, qb) * scale).astype(out_dtype)


def _fp8_einsum_fwd(eq, x, w):
    qx, sx = quantize(x, E4M3)
    qw, sw = quantize(w, E4M3)
    out = _scaled_contract(eq, qx, qw, sx * sw, x.dtype)
    # Zero-size sentinels carry the primal dtypes (x and w may differ) so the
    # cotangents come back dtype-exact, as custom_vjp requires.
    return out, (qx, sx, qw, sw, jnp.zeros((), x.dtype), jnp.zeros((), w.dtype))


def _fp8_einsum_bwd(eq, res, g):
    qx, sx, qw, sw, x_proto, w_proto = res
    dx_eq, dw_eq = _grad_equations(eq)
    qg, sg = quantize(g, E5M2)
    dx = _scaled_contract(dx_eq, qg, qw, sg * sw, x_proto.dtype)
    dw = _scaled_contract(dw_eq, qx, qg, sx * sg, w_proto.dtype)
    return dx, dw


fp8_einsum.defvjp(_fp8_einsum_fwd, _fp8_einsum_bwd)

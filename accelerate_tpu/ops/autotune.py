"""Persisted block-size autotune cache for the Pallas kernel tier.

`ops/flash_attention.py::pick_block` is a static heuristic ("largest tile
that divides"). This module promotes it to a small persisted cache so a
measured-best block survives process restarts and is shared across kernels:

- entries are keyed ``op|shape|dtype`` inside a per-chip-generation JSON
  file (``$ATX_AUTOTUNE_DIR/<chip>.json``) — a v5e tuning never leaks onto
  a v4;
- an environment override always wins: ``ATX_BLOCK_<OP>`` (e.g.
  ``ATX_BLOCK_FLASH_ATTENTION=1024``) forces the block for every shape of
  that op, the knob used when bisecting a tuning regression;
- without ``ATX_AUTOTUNE_DIR`` the cache is purely in-memory (tests, and
  one-shot jobs that shouldn't write dotfiles);
- a cached block that no longer divides the requested dim (shape drifted)
  is ignored, never returned stale.

ATX603 uses the same table as ground truth: a dot whose dims defeat every
cached/heuristic block is exactly the tiling-waste case it flags.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Any

from .flash_attention import pick_block, tuned_call_kwargs  # noqa: F401  (re-export)

_ENV_DIR = "ATX_AUTOTUNE_DIR"
_DEFAULT_CANDIDATES = (512, 256, 128, 64, 32, 16, 8)


def _chip_name() -> str:
    from ..analysis.roofline import chip_spec_for

    try:
        return chip_spec_for().name
    except Exception:
        return "cpu"


def _env_override(op: str) -> int | None:
    raw = os.environ.get("ATX_BLOCK_" + re.sub(r"\W", "_", op).upper())
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


class AutotuneCache:
    """Per-chip block table: in-memory always, JSON-persisted when a
    directory is configured. Thread-safe; writes are atomic (tmp+rename)
    so a killed process never leaves a torn table."""

    def __init__(self, chip: str | None = None, directory: str | None = None):
        self.chip = chip or _chip_name()
        self.directory = directory if directory is not None else os.environ.get(_ENV_DIR)
        self._lock = threading.Lock()
        self._table: dict[str, int] = {}
        self._loaded = False

    # ---------------------------------------------------------- internals
    @property
    def path(self) -> str | None:
        if not self.directory:
            return None
        return os.path.join(self.directory, f"{self.chip}.json")

    @staticmethod
    def key(op: str, shape: tuple[int, ...], dtype: Any) -> str:
        dt = getattr(dtype, "name", None) or str(dtype)
        return f"{op}|{'x'.join(str(int(d)) for d in shape)}|{dt}"

    def _load(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        path = self.path
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as fh:
                disk = json.load(fh)
            blocks = disk.get("blocks", disk)
            # Disk entries fill gaps; in-memory puts from this process win.
            merged = {k: int(v) for k, v in blocks.items()}
            merged.update(self._table)
            self._table = merged
        except (OSError, ValueError):
            pass  # unreadable cache == empty cache

    def _persist(self) -> None:
        path = self.path
        if path is None:
            return
        try:
            os.makedirs(self.directory, exist_ok=True)
            tmp = path + f".tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(
                    {"chip": self.chip, "blocks": dict(sorted(self._table.items()))},
                    fh,
                    indent=2,
                )
                fh.write("\n")
            os.replace(tmp, path)
        except OSError:
            pass  # read-only FS: stay in-memory

    # ------------------------------------------------------------- access
    def get(self, op: str, shape: tuple[int, ...], dtype: Any) -> int | None:
        override = _env_override(op)
        if override is not None:
            return override
        with self._lock:
            self._load()
            return self._table.get(self.key(op, shape, dtype))

    def put(self, op: str, shape: tuple[int, ...], dtype: Any, block: int) -> None:
        key = self.key(op, shape, dtype)
        with self._lock:
            self._load()
            if self._table.get(key) == int(block):
                return
            self._table[key] = int(block)
            self._persist()


_default_cache: AutotuneCache | None = None
_default_lock = threading.Lock()


def default_cache() -> AutotuneCache:
    """Process-wide cache; rebuilt if ATX_AUTOTUNE_DIR changed (tests)."""
    global _default_cache
    with _default_lock:
        current_dir = os.environ.get(_ENV_DIR)
        if _default_cache is None or _default_cache.directory != current_dir:
            _default_cache = AutotuneCache()
        return _default_cache


def cached_pick_block(
    op: str,
    dim: int,
    candidates: tuple[int, ...] = _DEFAULT_CANDIDATES,
    dtype: Any = "any",
    cache: AutotuneCache | None = None,
):
    """`pick_block` with the persisted table consulted first. Precedence:
    ``ATX_BLOCK_<OP>`` env override > cached entry > heuristic. A cached or
    overridden block that doesn't divide ``dim`` is ignored (the kernels
    never pad). Heuristic picks are written back so the table documents
    what actually ran."""
    cache = cache or default_cache()
    hit = cache.get(op, (dim,), dtype)
    if hit is not None and hit > 0 and dim % hit == 0:
        return hit
    block = pick_block(dim, candidates)
    if block is not None:
        cache.put(op, (dim,), dtype, block)
    return block

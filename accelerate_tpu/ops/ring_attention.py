"""Ring attention — sequence/context parallelism over the ``sequence`` mesh axis.

The reference has NO native long-context support (SURVEY.md §2.2: sequence
parallelism exists only as a Megatron flag; "no ring attention, no Ulysses,
no blockwise attention anywhere in the repo" — this module is a
capability-exceeding component, not parity).

Design: Q, K, V are sharded along the sequence dimension across the
``sequence`` mesh axis. Each device holds one sequence chunk; K/V chunks
rotate around the ring with `ppermute` while every device accumulates
attention against each visiting chunk using online-softmax merging — peak
memory per device is O(S/n) and the KV transfers ride the ICI ring
(jax-ml.github.io/scaling-book recipe; reference has no equivalent).

Causality is handled at chunk granularity: a device skips score computation
for chunks entirely in its future (mask to -inf), uses a triangular mask for
its own chunk, and attends fully to past chunks.
"""

from __future__ import annotations

import functools
import math
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import BATCH_AXES, SEQUENCE_AXIS

_NEG_INF = -1e30


def _chunk_attention(q, k, v, *, scale, mask):
    """Unnormalized attention stats for one KV chunk.

    q: (B, S, H, h); k/v: (B, C, K, h) with GQA broadcast.
    Returns (o_unnorm (B,S,H,h), m (B,S,H), l (B,S,H)).
    """
    B, S, H, h = q.shape
    C, K = k.shape[1], k.shape[2]
    group = H // K
    qg = q.reshape(B, S, K, group, h)
    s = jnp.einsum("bskgh,btkh->bkgst", qg.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    if mask is not None:
        # mask: (S, C) True = attend, or (B, S, C) when it carries padding
        if mask.ndim == 2:
            mask = mask[None]
        s = jnp.where(mask[:, None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # (B,K,g,S)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)  # (B,K,g,S)
    o = jnp.einsum("bkgst,btkh->bskgh", p, v.astype(jnp.float32))
    # reshape head axes back to H
    m = m.transpose(0, 3, 1, 2).reshape(B, S, H)
    l = l.transpose(0, 3, 1, 2).reshape(B, S, H)
    o = o.reshape(B, S, H, h)
    return o, m, l


def _merge(acc, chunk):
    o1, m1, l1 = acc
    o2, m2, l2 = chunk
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    o = o1 * a1[..., None] + o2 * a2[..., None]
    l = l1 * a1 + l2 * a2
    return o, m, l


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None,
    *,
    axis_name: str,
    causal: bool,
    scale: float,
) -> jax.Array:
    """Body run per-device under shard_map: local q against the rotating kv.

    ``kv_mask`` is this device's (B, S_local) key-padding chunk (True =
    attend); it rotates around the ring with its k/v chunk.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, S, H, h = q.shape
    rows = jnp.arange(S)[:, None]
    cols = jnp.arange(S)[None, :]
    perm = [(i, (i + 1) % n) for i in range(n)]

    m0 = jnp.full((B, S, H), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, S, H), jnp.float32)
    o0 = jnp.zeros((B, S, H, h), jnp.float32)

    def step(t, carry):
        acc, kk, vv, mm = carry
        src = (my - t) % n  # which chunk is visiting this step
        if causal:
            # chunk-level causality: future chunk -> all masked; own chunk ->
            # triangular; past chunk -> full. Build the (S, S) mask by cases.
            offset = (my - src) * S  # global row - col offset between chunks
            mask = (rows + offset) >= cols
        else:
            mask = None
        if mm is not None:
            pad = mm[:, None, :]  # (B, 1, C) keys of the visiting chunk
            mask = pad if mask is None else jnp.logical_and(mask[None], pad)

        def attend(acc):
            return _merge(acc, _chunk_attention(q, kk, vv, scale=scale, mask=mask))

        if causal:
            # Entirely-future chunks (src > my) contribute nothing; skip the
            # FLOPs, not just the values.
            acc = jax.lax.cond(src <= my, attend, lambda a: a, acc)
        else:
            acc = attend(acc)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        if mm is not None:
            mm = jax.lax.ppermute(mm, axis_name, perm)
        return acc, kk, vv, mm

    (o, m, l), _, _, _ = jax.lax.fori_loop(0, n, step, ((o0, m0, l0), k, v, kv_mask))
    l = jnp.maximum(l, 1e-30)
    return (o / l[..., None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    kv_mask: jax.Array | None = None,
    scale: float | None = None,
    mesh: Mesh | None = None,
    axis_name: str = SEQUENCE_AXIS,
    batch_axes: Sequence[str] = BATCH_AXES,
) -> jax.Array:
    """Sequence-parallel attention over (B, S, H, h) global arrays.

    Shards S over ``axis_name`` and B over ``batch_axes`` with shard_map;
    call inside or outside jit. With an unsharded/absent sequence axis this
    degrades to one local chunk (exact attention). ``kv_mask`` is a (B, S)
    key-padding mask (True/1 = attend), sequence-sharded like k/v — each
    chunk's mask rotates around the ring with it."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mesh is None:
        from ..state import AcceleratorState

        mesh = AcceleratorState().mesh
    batch_group = 1
    for a in batch_axes:
        batch_group *= mesh.shape[a]
    # Replicate the batch when it can't divide over the batch axes (e.g. eval
    # with a small batch on a large mesh) — sequence sharding still applies.
    use_batch = tuple(batch_axes) if batch_group > 1 and q.shape[0] % batch_group == 0 else None
    spec = P(use_batch, axis_name, None, None)
    mask_spec = P(use_batch, axis_name)
    fn = functools.partial(
        _ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
    )
    if kv_mask is not None:
        kv_mask = kv_mask.astype(bool)
    shard_fn = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec, mask_spec if kv_mask is not None else P()),
        out_specs=spec,
        check_vma=False,
    )
    return shard_fn(q, k, v, kv_mask)

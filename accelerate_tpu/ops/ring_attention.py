"""Ring attention — sequence/context parallelism over the ``sequence`` mesh axis.

The reference has NO native long-context support (SURVEY.md §2.2: sequence
parallelism exists only as a Megatron flag; "no ring attention, no Ulysses,
no blockwise attention anywhere in the repo" — this module is a
capability-exceeding component, not parity).

Design: Q, K, V are sharded along the sequence dimension across the
``sequence`` mesh axis. Each device holds one sequence chunk; K/V chunks
rotate around the ring with `ppermute` while every device accumulates
attention against each visiting chunk using online-softmax merging — peak
memory per device is O(S/n) and the KV transfers ride the ICI ring
(jax-ml.github.io/scaling-book recipe; reference has no equivalent).

Causality is handled at chunk granularity: a device skips score computation
for chunks entirely in its future (mask to -inf), uses a triangular mask for
its own chunk, and attends fully to past chunks.
"""

from __future__ import annotations

import functools
import math
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import BATCH_AXES, SEQUENCE_AXIS
from .in_jit import ring_neighbors, shard_map_over

_NEG_INF = -1e30


def _chunk_attention(q, k, v, *, scale, mask):
    """Unnormalized attention stats for one KV chunk.

    q: (B, S, H, h); k/v: (B, C, K, h) with GQA broadcast.
    Returns (o_unnorm (B,S,H,h), m (B,S,H), l (B,S,H)).
    """
    B, S, H, h = q.shape
    C, K = k.shape[1], k.shape[2]
    group = H // K
    qg = q.reshape(B, S, K, group, h)
    s = jnp.einsum("bskgh,btkh->bkgst", qg.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    if mask is not None:
        # mask: (S, C) True = attend, or (B, S, C) when it carries padding
        if mask.ndim == 2:
            mask = mask[None]
        s = jnp.where(mask[:, None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # (B,K,g,S)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)  # (B,K,g,S)
    o = jnp.einsum("bkgst,btkh->bskgh", p, v.astype(jnp.float32))
    # reshape head axes back to H
    m = m.transpose(0, 3, 1, 2).reshape(B, S, H)
    l = l.transpose(0, 3, 1, 2).reshape(B, S, H)
    o = o.reshape(B, S, H, h)
    return o, m, l


def _merge(acc, chunk):
    o1, m1, l1 = acc
    o2, m2, l2 = chunk
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    o = o1 * a1[..., None] + o2 * a2[..., None]
    l = l1 * a1 + l2 * a2
    return o, m, l


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None,
    *,
    axis_name: str,
    causal: bool,
    scale: float,
    window: int | None = None,
) -> jax.Array:
    """Body run per-device under shard_map: local q against the rotating kv.

    ``kv_mask`` is this device's (B, S_local) key-padding chunk (True =
    attend); it rotates around the ring with its k/v chunk.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, S, H, h = q.shape
    rows = jnp.arange(S)[:, None]
    cols = jnp.arange(S)[None, :]
    perm = ring_neighbors(axis_name, n)

    m0 = jnp.full((B, S, H), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, S, H), jnp.float32)
    o0 = jnp.zeros((B, S, H, h), jnp.float32)

    def step(t, carry):
        acc, kk, vv, mm = carry
        src = (my - t) % n  # which chunk is visiting this step
        offset = (my - src) * S  # global row - col offset between chunks
        if causal:
            # chunk-level causality: future chunk -> all masked; own chunk ->
            # triangular; past chunk -> full. Build the (S, S) mask by cases.
            mask = (rows + offset) >= cols
        else:
            mask = None
        if window is not None:
            # Sliding band in GLOBAL coordinates: (row + offset) - col < window.
            band = (rows + offset) - cols < window
            mask = band if mask is None else jnp.logical_and(mask, band)
        if mm is not None:
            pad = mm[:, None, :]  # (B, 1, C) keys of the visiting chunk
            mask = pad if mask is None else jnp.logical_and(mask[None], pad)

        def attend(acc):
            return _merge(acc, _chunk_attention(q, kk, vv, scale=scale, mask=mask))

        if causal or window is not None:
            live = jnp.asarray(True)
            if causal:
                # Entirely-future chunks (src > my) contribute nothing.
                live = src <= my
            if window is not None:
                # Chunks entirely below the band contribute nothing either:
                # min(row - col) + offset = offset - (S - 1) must be < window.
                live = jnp.logical_and(live, offset - (S - 1) < window)
            acc = jax.lax.cond(live, attend, lambda a: a, acc)
        else:
            acc = attend(acc)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        if mm is not None:
            mm = jax.lax.ppermute(mm, axis_name, perm)
        return acc, kk, vv, mm

    (o, m, l), _, _, _ = jax.lax.fori_loop(0, n, step, ((o0, m0, l0), k, v, kv_mask))
    l = jnp.maximum(l, 1e-30)
    return (o / l[..., None]).astype(q.dtype)


# ------------------------------------------------------- fused (flash) path
def _merge_lse(o, lse, oc, lsec):
    """Merge two normalized partial attentions via their logsumexps."""
    new_lse = jnp.logaddexp(lse, lsec)
    w = jnp.exp(lse - new_lse)
    wc = jnp.exp(lsec - new_lse)
    return o * w + oc.astype(o.dtype) * wc, new_lse


def _ring_fused_fwd_impl(q, k, v, axis_name, causal, scale, block, interpret):
    from .flash_attention import _fwd

    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    qt = q.transpose(0, 2, 1, 3)  # (B, H, S, h)
    S = qt.shape[2]
    perm = ring_neighbors(axis_name, n)

    def chunk(kk, vv, chunk_causal):
        oc, lsec = _fwd(
            qt,
            kk.transpose(0, 2, 1, 3),
            vv.transpose(0, 2, 1, 3),
            scale=scale,
            block=block,
            causal=chunk_causal,
            interpret=interpret,
            valid=S,
        )
        return oc, lsec

    # t = 0: the device's own chunk (diagonal) — triangular under causal.
    oc, lsec = chunk(k, v, causal)
    o = oc.astype(jnp.float32)
    lse = lsec
    kk = jax.lax.ppermute(k, axis_name, perm)
    vv = jax.lax.ppermute(v, axis_name, perm)

    def step(t, carry):
        (o, lse), kk, vv = carry

        def attend(ol):
            oc, lsec = chunk(kk, vv, False)
            return _merge_lse(ol[0], ol[1], oc, lsec)

        if causal:
            # Entirely-future chunks contribute nothing: skip their FLOPs.
            src = (my - t) % n
            o, lse = jax.lax.cond(src < my, attend, lambda ol: ol, (o, lse))
        else:
            o, lse = attend((o, lse))
        kk2 = jax.lax.ppermute(kk, axis_name, perm)
        vv2 = jax.lax.ppermute(vv, axis_name, perm)
        return (o, lse), kk2, vv2

    (o, lse), _, _ = jax.lax.fori_loop(1, n, step, ((o, lse), kk, vv))
    return o.astype(q.dtype).transpose(0, 2, 1, 3), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_fused(q, k, v, axis_name, causal, scale, block, interpret):
    o, _ = _ring_fused_fwd_impl(q, k, v, axis_name, causal, scale, block, interpret)
    return o


def _ring_fused_fwd(q, k, v, axis_name, causal, scale, block, interpret):
    o, lse = _ring_fused_fwd_impl(q, k, v, axis_name, causal, scale, block, interpret)
    return o, (q, k, v, o, lse)


def _ring_fused_bwd(axis_name, causal, scale, block, interpret, residuals, g):
    from .flash_attention import dkv_call, dq_call, fold_gqa_groups

    q, k, v, o, lse = residuals
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = ring_neighbors(axis_name, n)
    qt = q.transpose(0, 2, 1, 3)
    dot_ = g.transpose(0, 2, 1, 3)
    ot = o.transpose(0, 2, 1, 3)
    S = qt.shape[2]
    delta = jnp.sum(
        dot_.astype(jnp.float32) * ot.astype(jnp.float32), axis=-1, keepdims=True
    )
    kwargs = dict(scale=scale, block=block, interpret=interpret, valid=S)

    def chunk_grads(kk, vv, chunk_causal):
        kt = kk.transpose(0, 2, 1, 3)
        vt = vv.transpose(0, 2, 1, 3)
        dq_c = dq_call(qt, kt, vt, dot_, lse, delta, causal=chunk_causal, **kwargs)
        dkh, dvh = dkv_call(qt, kt, vt, dot_, lse, delta, causal=chunk_causal, **kwargs)
        return dq_c.astype(jnp.float32), dkh.astype(jnp.float32), dvh.astype(jnp.float32)

    # t = 0: own chunk.
    dq_t, dkh, dvh = chunk_grads(k, v, causal)
    kk = jax.lax.ppermute(k, axis_name, perm)
    vv = jax.lax.ppermute(v, axis_name, perm)
    # Accumulators travel WITH their chunk: after n total rotations each
    # device's own chunk gradients are back home.
    dkh = jax.lax.ppermute(dkh, axis_name, perm)
    dvh = jax.lax.ppermute(dvh, axis_name, perm)

    def step(t, carry):
        dq_t, dkh, dvh, kk, vv = carry

        def attend(args):
            dq_t, dkh, dvh = args
            dq_c, dkh_c, dvh_c = chunk_grads(kk, vv, False)
            return dq_t + dq_c, dkh + dkh_c, dvh + dvh_c

        if causal:
            src = (my - t) % n
            dq_t, dkh, dvh = jax.lax.cond(src < my, attend, lambda a: a, (dq_t, dkh, dvh))
        else:
            dq_t, dkh, dvh = attend((dq_t, dkh, dvh))
        return (
            dq_t,
            jax.lax.ppermute(dkh, axis_name, perm),
            jax.lax.ppermute(dvh, axis_name, perm),
            jax.lax.ppermute(kk, axis_name, perm),
            jax.lax.ppermute(vv, axis_name, perm),
        )

    dq_t, dkh, dvh, _, _ = jax.lax.fori_loop(1, n, step, (dq_t, dkh, dvh, kk, vv))
    K = k.shape[2]
    dk_t, dv_t = fold_gqa_groups(
        dkh.astype(q.dtype), dvh.astype(q.dtype), K, k.dtype, v.dtype
    )
    dq = dq_t.astype(q.dtype).transpose(0, 2, 1, 3)
    dk = dk_t.transpose(0, 2, 1, 3)
    dv = dv_t.transpose(0, 2, 1, 3)
    return dq, dk, dv


_ring_fused.defvjp(_ring_fused_fwd, _ring_fused_bwd)


def _fused_block(s_local: int, h: int, dtype) -> int | None:
    """Kernel block size for the fused path; None = chunk too small/ragged,
    use the einsum path. Long chunks prefer 1024 — same measurement as
    `flash_attention`'s adaptive default (1.5x over 512 from 4k up on v5e;
    2048 exceeds VMEM; below 4k the resident kernels win and they take 512)."""
    del h, dtype  # crossover is purely in s_local since the resident cutover
    if s_local >= 4096 and s_local % 1024 == 0:
        return 1024
    for b in (512, 256, 128):
        if s_local % b == 0:
            return b
    return None


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    kv_mask: jax.Array | None = None,
    scale: float | None = None,
    mesh: Mesh | None = None,
    axis_name: str = SEQUENCE_AXIS,
    batch_axes: Sequence[str] = BATCH_AXES,
    impl: str = "auto",
    window: int | None = None,
) -> jax.Array:
    """Sequence-parallel attention over (B, S, H, h) global arrays.

    ``window`` = Mistral-style sliding window in global coordinates; ring
    steps whose visiting chunk is entirely outside the band skip their
    FLOPs (einsum path only — the fused kernels need static per-chunk
    bands, which per-device ring offsets cannot provide).

    Shards S over ``axis_name`` and B over ``batch_axes`` with shard_map;
    call inside or outside jit. With an unsharded/absent sequence axis this
    degrades to one local chunk (exact attention). ``kv_mask`` is a (B, S)
    key-padding mask (True/1 = attend), sequence-sharded like k/v — each
    chunk's mask rotates around the ring with it.

    ``impl``: "fused" runs the Pallas flash kernels inside every ring chunk
    (forward AND backward — a custom VJP rings the kv gradients home with
    their chunks); "einsum" is the unfused oracle path; "auto" picks fused
    whenever the local chunk is block-aligned and no kv_mask is given.
    """
    if impl not in ("auto", "fused", "einsum"):
        raise ValueError(f"impl must be auto|fused|einsum, got {impl!r}")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mesh is None:
        from ..state import AcceleratorState

        mesh = AcceleratorState().mesh
    from .in_jit import sequence_parallel_specs

    spec, mask_spec = sequence_parallel_specs(mesh, q.shape[0], batch_axes, axis_name)

    n_shards = mesh.shape[axis_name]
    s_local = q.shape[1] // n_shards if q.shape[1] % n_shards == 0 else 0
    block = _fused_block(s_local, q.shape[-1], k.dtype) if s_local else None
    use_fused = impl == "fused" or (
        impl == "auto" and kv_mask is None and window is None and block is not None
    )
    if use_fused:
        if kv_mask is not None:
            raise NotImplementedError("impl='fused' does not take kv_mask; use 'einsum'")
        if window is not None:
            raise NotImplementedError(
                "impl='fused' cannot apply a sliding window (per-chunk band "
                "offsets are device-dependent but the kernel band is "
                "static); use impl='einsum' (the 'auto' default does)."
            )
        if not s_local:
            raise ValueError(
                f"impl='fused' needs sequence length {q.shape[1]} divisible "
                f"by the {n_shards}-way '{axis_name}' mesh axis"
            )
        if block is None:
            raise ValueError(
                f"impl='fused' needs the local chunk ({s_local}) to be a "
                "multiple of 128"
            )
        from .flash_attention import _interpret_default

        interp = _interpret_default()

        def fused(q, k, v):
            # custom_vjp nondiff args must be positional
            return _ring_fused(q, k, v, axis_name, causal, scale, block, interp)
        shard_fused = shard_map_over(
            fused, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return shard_fused(q, k, v)

    fn = functools.partial(
        _ring_attention_local, axis_name=axis_name, causal=causal, scale=scale,
        window=window,
    )
    if kv_mask is not None:
        kv_mask = kv_mask.astype(bool)
    shard_fn = shard_map_over(
        fn,
        mesh=mesh,
        in_specs=(spec, spec, spec, mask_spec if kv_mask is not None else P()),
        out_specs=spec,
        check_vma=False,
    )
    return shard_fn(q, k, v, kv_mask)

"""Mixture-of-Experts layer with expert parallelism over the ``expert`` axis.

The reference has no MoE support (Megatron-LM integration exposes none of it
through accelerate); this fills the framework's ``expert`` mesh axis —
declared in `parallel/mesh.py:MESH_AXES` — with a real consumer. The design
is the GShard/Switch capacity-based dispatch, which is THE TPU-native MoE
construction (static shapes, einsum dispatch, XLA inserts the all-to-alls):

- router: tokens -> softmax logits over E experts, top-k choice;
- capacity: each expert processes at most C = ceil(k*N/E * capacity_factor)
  tokens; overflow tokens are dropped (their combine weight is zero and the
  residual connection carries them through unchanged — standard Switch
  behavior);
- dispatch/combine are one-hot einsum contractions, so the whole layer is
  three matmuls + the expert FFN — no sorting, no dynamic shapes;
- expert weights carry a leading [E] axis; sharding it over the ``expert``
  mesh axis (see `llama.tp_plan`) makes XLA lower the dispatch einsum to an
  all-to-all over ICI — expert parallelism without any explicit collective
  in this file;
- aux losses: load-balance (Switch eq. 4) + router z-loss, returned for the
  model's loss function to weight in.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..models.layers import truncated_normal_init
from .fp8 import matmul_einsum

Params = Any


def init_moe(
    rng: jax.Array,
    d_model: int,
    d_ff: int,
    n_experts: int,
    dtype=jnp.float32,
) -> Params:
    """Router + E parallel swiglu experts (leading [E] axis on every weight)."""
    kr, kg, ku, kd = jax.random.split(rng, 4)
    std_in = 1.0 / np.sqrt(d_model)
    std_out = 1.0 / np.sqrt(d_ff)
    return {
        "router": truncated_normal_init(kr, (d_model, n_experts), std_in, dtype),
        "w_gate": truncated_normal_init(kg, (n_experts, d_model, d_ff), std_in, dtype),
        "w_up": truncated_normal_init(ku, (n_experts, d_model, d_ff), std_in, dtype),
        "w_down": truncated_normal_init(kd, (n_experts, d_ff, d_model), std_out, dtype),
    }


def _n_groups(n_tokens: int, tokens_per_group: int) -> int:
    """Smallest divisor of ``n_tokens`` keeping groups <= tokens_per_group."""
    for g in range(1, n_tokens + 1):
        if n_tokens % g == 0 and n_tokens // g <= tokens_per_group:
            return g
    return n_tokens


def _group_moe(params: Params, xt: jax.Array, *, top_k: int, capacity: int):
    """Dispatch/FFN/combine for ONE token group. xt: (n, d)."""
    n, d = xt.shape
    E = params["router"].shape[-1]
    # Router in fp32: tiny FLOPs, and logit precision decides expert choice.
    logits = xt.astype(jnp.float32) @ params["router"].astype(jnp.float32)  # (n, E)
    probs = jax.nn.softmax(logits, axis=-1)

    # Top-k selection (static k) with per-round masking.
    remaining = probs
    dispatch = jnp.zeros((n, E, capacity), xt.dtype)
    combine = jnp.zeros((n, E, capacity), jnp.float32)
    # Track per-expert fill across rounds so round 2 continues where 1 ended.
    fill = jnp.zeros((E,), jnp.int32)
    importance = jnp.zeros((E,), jnp.float32)  # fraction routed per expert
    for _ in range(top_k):
        choice = jnp.argmax(remaining, axis=-1)  # (n,)
        gate = jnp.take_along_axis(remaining, choice[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.int32)  # (n, E)
        # Position of each token within its chosen expert's buffer.
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - onehot) + fill[None, :]
        pos = jnp.sum(pos_in_expert * onehot, axis=-1)  # (n,)
        keep = pos < capacity
        pos_oh = jax.nn.one_hot(jnp.where(keep, pos, 0), capacity, dtype=jnp.float32)
        contrib = (
            onehot.astype(jnp.float32)[:, :, None]
            * pos_oh[:, None, :]
            * keep.astype(jnp.float32)[:, None, None]
        )
        dispatch = dispatch + contrib.astype(xt.dtype)
        combine = combine + contrib * gate[:, None, None]
        fill = fill + jnp.sum(onehot * keep[:, None].astype(jnp.int32), axis=0)
        importance = importance + jnp.mean(onehot.astype(jnp.float32), axis=0)
        remaining = remaining * (1.0 - onehot.astype(probs.dtype))

    # Dispatch -> expert FFN -> combine. The expert projections (the FLOPs)
    # route through `matmul_einsum` so fp8 mode covers them; the one-hot
    # dispatch/combine contractions are data movement, not matmuls, and stay
    # in the compute dtype.
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, xt)  # (E, C, d)
    gate_h = matmul_einsum("ecd,edf->ecf", expert_in, params["w_gate"])
    up_h = matmul_einsum("ecd,edf->ecf", expert_in, params["w_up"])
    hidden = jax.nn.silu(gate_h) * up_h
    expert_out = matmul_einsum("ecf,efd->ecd", hidden, params["w_down"])
    out = jnp.einsum("nec,ecd->nd", combine.astype(xt.dtype), expert_out)

    # Renormalize: dropped tokens keep whatever gate mass survived; the usual
    # top-k renorm divides by the sum of kept gates (guarded for full drops).
    gate_sum = jnp.sum(combine, axis=(1, 2))  # (n,)
    out = out / jnp.maximum(gate_sum, 1e-9)[:, None].astype(out.dtype)

    # Aux stats. Load balance (Switch eq. 4): E * sum_e f_e * P_e where f_e
    # is the routed fraction and P_e the mean router prob. z-loss keeps
    # logits from drifting to fp32-hostile magnitudes.
    mean_prob = jnp.mean(probs, axis=0)  # (E,)
    load_balance = E * jnp.sum((importance / top_k) * mean_prob)
    z_loss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    kept = jnp.sum(dispatch.astype(jnp.float32))
    return out, load_balance, z_loss, kept


def moe_forward(
    params: Params,
    x: jax.Array,
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    tokens_per_group: int = 2048,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """(B, S, d) -> (B, S, d) plus aux losses.

    Tokens are split into groups of at most ``tokens_per_group`` with
    per-group expert capacity (the GShard group axis): the dispatch/combine
    one-hots are then O(N * top_k * capacity_factor * tokens_per_group / E)
    — linear in total tokens — instead of the O(N^2) a single global
    capacity would cost at training sequence lengths.
    """
    B, S, d = x.shape
    E = params["router"].shape[-1]
    N = B * S
    G = _n_groups(N, tokens_per_group)
    n = N // G
    capacity = max(int(math.ceil(top_k * n / E * capacity_factor)), 1)

    xg = x.reshape(G, n, d)
    out, load_balance, z_loss, kept = jax.vmap(
        lambda xt: _group_moe(params, xt, top_k=top_k, capacity=capacity)
    )(xg)
    aux = {
        "moe_load_balance": jnp.mean(load_balance).astype(jnp.float32),
        "moe_z_loss": jnp.mean(z_loss).astype(jnp.float32),
        # Fraction of token-slots dropped by capacity limits (diagnostic).
        "moe_drop_fraction": 1.0 - jnp.sum(kept) / (top_k * N),
    }
    return out.reshape(B, S, d), aux


def moe_reference(params: Params, x: jax.Array, *, top_k: int = 2) -> jax.Array:
    """Oracle: per-token dense computation of the same top-k mixture with
    unlimited capacity (for tests)."""
    B, S, d = x.shape
    xt = x.reshape(-1, d)
    logits = xt.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    E = probs.shape[-1]
    _, topk_idx = jax.lax.top_k(probs, top_k)

    def one_expert(e):
        gate = xt @ params["w_gate"][e].astype(xt.dtype)
        up = xt @ params["w_up"][e].astype(xt.dtype)
        return (jax.nn.silu(gate) * up) @ params["w_down"][e].astype(xt.dtype)

    all_out = jnp.stack([one_expert(e) for e in range(E)], axis=1)  # (N, E, d)
    mask = jax.nn.one_hot(topk_idx, E).sum(axis=1)  # (N, E)
    weights = probs * mask
    weights = weights / jnp.maximum(weights.sum(-1, keepdims=True), 1e-9)
    out = jnp.einsum("ne,ned->nd", weights.astype(xt.dtype), all_out)
    return out.reshape(B, S, d)
